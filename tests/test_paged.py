"""mxnet_tpu.serve.paged: paged-KV LLM serving (tier-1, CPU).

ISSUE 16 acceptance: the paged engine emits BITWISE-identical token
streams to the dense-stripe baseline under a mixed-length flood; the
speculative path is token-identical to pure target decode (good draft
and bad draft); pool exhaustion queues instead of dropping
(dropped_streams stays 0 by design); chunked prefill co-batches with
in-flight decode; the steady loop never enters the XLA compiler; and
engine.device_bytes() counts the full KV pool + draft model, which is
what keeps ModelMultiplexer admission honest for pool-resident engines.
"""
import os
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu.serve import (KVBlockPool, LMConfig, PagedDecodeEngine,
                             ServeClosedError, ServeError,
                             ServeOverloadError, ServeRequestError,
                             init_lm_params)
from mxnet_tpu.serve.paged.model import param_bytes

CFG = LMConfig(vocab=64, dim=32, heads=4, layers=2, max_context=96)


def _prompts(n, seed=7, lens=(3, 17, 33, 5, 26, 48, 1, 12)):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, CFG.vocab, size=lens[i % len(lens)])
            .astype(np.int64) for i in range(n)]


def _engine(params, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("block_tokens", 8)
    kw.setdefault("chunk_tokens", 16)
    kw.setdefault("name", "test-paged")
    return PagedDecodeEngine(params, CFG, **kw)


def _run_all(eng, prompts, max_new=24):
    futs = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    return [f.result(timeout=120) for f in futs]


@pytest.fixture(scope="module")
def params():
    return init_lm_params(CFG, seed=0)


@pytest.fixture(scope="module")
def dense_streams(params):
    """The dense-stripe baseline: every slot statically owns a full
    max-context stripe, same step program — the parity ground truth."""
    eng = _engine(params, paged=False, name="dense-base")
    try:
        return _run_all(eng, _prompts(8))
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# pool allocator

def test_pool_reserve_ensure_release_invariants():
    pool = KVBlockPool(num_slots=2, max_blocks_per_slot=4, num_blocks=6,
                       block_tokens=8)
    assert pool.blocks_for(1) == 1 and pool.blocks_for(8) == 1
    assert pool.blocks_for(9) == 2 and pool.blocks_for(32) == 4
    assert pool.available_blocks() == 6 and pool.sentinel == 6
    assert np.all(pool.page_table() == pool.sentinel)
    # exact reservation: blocks leave the admission budget immediately
    assert pool.reserve(0, 4)
    assert pool.available_blocks() == 2
    assert not pool.reserve(1, 3)       # would oversubscribe: refused
    assert pool.available_blocks() == 2
    assert pool.reserve(1, 2)
    # lazy assignment: physical pages appear as tokens land
    assert pool.used_blocks() == 0
    pool.ensure(0, 9)                   # 2 blocks
    assert pool.used_blocks() == 2
    assert sorted(set(int(b) for b in pool.page_table()[0, :2])) \
        == sorted(set(int(b) for b in pool.page_table()[0, :2]))
    assert all(0 <= int(b) < 6 for b in pool.page_table()[0, :2])
    pool.ensure(0, 9)                   # idempotent
    assert pool.used_blocks() == 2
    with pytest.raises(ServeError):     # beyond the reservation: a bug
        pool.ensure(1, 32)
    # release returns pages AND the unused reservation tail
    pool.release(0)
    assert pool.used_blocks() == 0 and pool.available_blocks() == 4
    assert np.all(pool.page_table()[0] == pool.sentinel)
    pool.release(1)
    assert pool.available_blocks() == 6


def test_pool_geometry_validation_and_dense_mode():
    with pytest.raises(ServeError):
        KVBlockPool(2, 4, num_blocks=3, block_tokens=8)   # < one stream
    with pytest.raises(ServeError):
        KVBlockPool(2, 4, num_blocks=6, block_tokens=8, dense=True)
    with pytest.raises(ServeError):
        KVBlockPool(2, 4, block_tokens=0)
    dense = KVBlockPool(2, 4, block_tokens=8, dense=True)
    # static stripes, reservations always fit, release keeps the stripe
    assert dense.num_blocks == 8
    assert np.array_equal(dense.page_table()[1], np.arange(4, 8))
    assert dense.available_blocks() == 8
    assert dense.reserve(0, 4) and dense.reserve(0, 4)
    dense.release(0)
    assert np.array_equal(dense.page_table()[0], np.arange(0, 4))


def test_pool_views_and_device_bytes():
    pool = KVBlockPool(2, 4, num_blocks=6, block_tokens=8)
    pool.add_view("target", layers=2, heads=4, head_dim=8)
    with pytest.raises(ServeError):
        pool.add_view("target", 2, 4, 8)
    k, v = pool.view("target")
    # +1 sentinel scratch row, 4 bytes/float, K and V
    want = 2 * (2 * 7 * 8 * 4 * 8 * 4)
    assert pool.device_bytes() == want
    assert k.shape == (2, 7, 8, 4, 8)


def test_env_pool_geometry(monkeypatch):
    monkeypatch.setenv("MXNET_KVPOOL_BLOCK_TOKENS", "4")
    monkeypatch.setenv("MXNET_KVPOOL_BLOCKS", "13")
    pool = KVBlockPool(2, 4)
    assert pool.block_tokens == 4 and pool.num_blocks == 13


# ---------------------------------------------------------------------------
# engine parity

def test_paged_matches_dense_mixed_length_flood(params, dense_streams):
    """8 mixed-length streams through 4 slots with a pool SMALLER than
    dense-equivalent (admission must queue on blocks): every token
    stream is bitwise identical to the dense-stripe baseline."""
    eng = _engine(params, num_blocks=30, name="paged-parity")
    try:
        got = _run_all(eng, _prompts(8))
        for i, (a, b) in enumerate(zip(dense_streams, got)):
            assert a.dtype == b.dtype == np.int32
            assert np.array_equal(a, b), (i, a, b)
        rep = eng.stats.report()
        assert rep["kind"] == "paged"
        assert rep["completed"] == 8 and rep["dropped_streams"] == 0
        assert rep["prefill_tokens"] == sum(
            len(p) for p in _prompts(8))
        # mixed-length flood through half-size pool must have paged
        assert rep["kv_blocks"] == 30
    finally:
        eng.close()


@pytest.mark.parametrize("draft_seed", [0, 99])
def test_spec_decode_token_identical(params, dense_streams, draft_seed):
    """Speculative decode emits the SAME stream as plain decode whether
    the draft is perfect (seed 0 = the target itself: near-1.0
    acceptance) or unrelated (seed 99: low acceptance) — acceptance
    moves throughput, never tokens."""
    draft = params if draft_seed == 0 else init_lm_params(CFG, seed=99)
    eng = _engine(params, num_blocks=40, draft_params=draft,
                  draft_cfg=CFG, spec_k=4,
                  name="spec-%d" % draft_seed)
    try:
        got = _run_all(eng, _prompts(8))
        for a, b in zip(dense_streams, got):
            assert np.array_equal(a, b)
        rep = eng.stats.report()
        assert rep["spec_rounds"] > 0
        assert rep["spec_proposed"] >= rep["spec_accepted"] >= 0
        if draft_seed == 0:
            assert rep["spec_accept_rate"] > 0.9, rep
    finally:
        eng.close()


def test_chunked_prefill_counters_and_long_prompt(params):
    """A near-max-context prompt prefills in chunk_tokens pieces while a
    short stream keeps decoding — both finish, prefill accounting adds
    up, and the long stream's answer matches the dense baseline."""
    long_p = _prompts(1, seed=11, lens=(72,))[0]
    short_p = _prompts(1, seed=12, lens=(2,))[0]
    base = _engine(params, paged=False, name="chunk-base")
    try:
        want_long, want_short = _run_all(base, [long_p, short_p],
                                         max_new=12)
    finally:
        base.close()
    eng = _engine(params, num_blocks=24, chunk_tokens=16,
                  name="chunk-paged")
    try:
        got_long, got_short = _run_all(eng, [long_p, short_p],
                                       max_new=12)
        assert np.array_equal(got_long, want_long)
        assert np.array_equal(got_short, want_short)
        rep = eng.stats.report()
        assert rep["prefill_tokens"] == len(long_p) + len(short_p)
        assert rep["inter_token_p99_ms"] > 0
    finally:
        eng.close()


def test_pool_exhaustion_queues_never_drops(params):
    """A pool that fits ~2 worst-case streams against 4 slots and 12
    queued streams: admission waits on blocks (FIFO, no head-of-line
    skipping), every stream completes, dropped_streams is 0 BY DESIGN."""
    prompts = _prompts(12)
    dense = _engine(params, paged=False, queue_depth=16,
                    name="exhaust-base")
    try:
        want = _run_all(dense, prompts, max_new=16)
    finally:
        dense.close()
    eng = _engine(params, num_blocks=14, queue_depth=16,
                  name="exhaust-paged")
    try:
        got = _run_all(eng, prompts, max_new=16)
        for a, b in zip(want, got):
            assert np.array_equal(a, b)
        rep = eng.stats.report()
        assert rep["completed"] == 12
        assert rep["dropped_streams"] == 0 and rep["failed"] == 0
        assert rep["kv_utilization"] <= 1.0
    finally:
        eng.close()


def test_eos_at_max_new_and_submit_validation(params):
    eng = _engine(params, num_blocks=30)
    try:
        with pytest.raises(ServeRequestError):
            eng.submit([])
        with pytest.raises(ServeRequestError):
            eng.submit([[1, 2]])
        with pytest.raises(ServeRequestError):
            eng.submit([0.5])
        with pytest.raises(ServeRequestError):
            eng.submit([CFG.vocab])             # out of vocab
        with pytest.raises(ServeRequestError):
            eng.submit([1], max_new_tokens=0)
        with pytest.raises(ServeRequestError):  # can't fit max_context
            eng.submit(np.ones(60, np.int64), max_new_tokens=60)
        p = _prompts(1)[0]
        full = [int(t) for t in eng.generate(p, timeout=120,
                                             max_new_tokens=8)]
        k = max(i for i, t in enumerate(full) if t not in full[:i])
        got = eng.generate(p, timeout=120, max_new_tokens=k + 1,
                           eos_id=full[k])
        assert np.array_equal(got, np.asarray(full[:k + 1], np.int32))
        rep = eng.stats.report()
        assert rep["outstanding"] == 0 and rep["failed"] == 0
    finally:
        eng.close()


def test_overload_and_closed_fast_fail(params):
    eng = _engine(params, num_slots=1, num_blocks=13, queue_depth=2,
                  name="overload-paged")
    hog = eng.submit([1], max_new_tokens=64)
    t0 = time.perf_counter()
    while eng.pending_requests() > 0:
        assert time.perf_counter() - t0 < 10, "hog never admitted"
        time.sleep(0.005)
    queued = [eng.submit([2], max_new_tokens=64) for _ in range(2)]
    with pytest.raises(ServeOverloadError):
        eng.submit([3], max_new_tokens=4)
    assert eng.stats.report()["overloaded"] == 1
    for f in [hog] + queued:
        f.result(timeout=120)
    eng.close()
    with pytest.raises(ServeClosedError):       # closed beats full
        eng.submit([1], max_new_tokens=4)
    eng.close()                                 # idempotent


def test_close_no_drain_fails_streams_and_releases_pool(params):
    eng = _engine(params, num_slots=2, num_blocks=26,
                  name="nodrain-paged")
    futs = [eng.submit(p, max_new_tokens=32) for p in _prompts(4)]
    eng.close(drain=False)
    failed = 0
    for f in futs:
        try:
            f.result(timeout=60)
        except ServeClosedError:
            failed += 1
    assert failed >= 1
    assert eng.pool.used_blocks() == 0
    assert eng.pool.available_blocks() == 26


def test_no_compiles_in_steady_paged_loop(params):
    """Warmup builds both widths (C=1 and C=chunk) for target AND
    draft; the serving loop — admission, prefill chunks, spec rounds,
    finishes — must never enter the XLA compiler."""
    from compile_guard import assert_no_compiles
    prompts = _prompts(8)
    eng = _engine(params, num_blocks=40, draft_params=params,
                  draft_cfg=CFG, spec_k=3, name="warm-paged")
    try:
        eng.generate(prompts[0], timeout=120, max_new_tokens=4)
        with assert_no_compiles("paged decode loop"):
            _run_all(eng, prompts, max_new=12)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# accounting + integration

def test_device_bytes_counts_pool_and_draft(params):
    """The mux admission currency must include the KV pool (dominant
    for long contexts) and the draft model — a params-only number would
    let pool-resident engines silently blow MXNET_SERVE_MUX_BYTES."""
    draft = init_lm_params(CFG, seed=1)
    eng = _engine(params, num_blocks=30, draft_params=draft,
                  draft_cfg=CFG, spec_k=2, name="bytes-paged")
    try:
        pb = param_bytes(eng._params)
        assert eng.device_bytes() == (pb + eng.pool.device_bytes()
                                      + param_bytes(eng._spec.params))
        assert eng.pool.device_bytes() > 0
        # two views (target + draft) over 30+1 blocks
        assert eng.pool.device_bytes() == \
            2 * 2 * (CFG.layers * 31 * 8 * CFG.heads * CFG.head_dim * 4)
    finally:
        eng.close()


def test_paged_memory_per_stream_below_dense(params):
    """The headline: serving the same stream load, the paged pool holds
    fewer device bytes than dense-equivalent stripes."""
    dense = _engine(params, paged=False, name="mem-dense")
    paged = _engine(params, num_blocks=30, name="mem-paged")
    try:
        assert paged.pool.device_bytes() < dense.pool.device_bytes()
        d = _run_all(dense, _prompts(8))
        p = _run_all(paged, _prompts(8))
        for a, b in zip(d, p):
            assert np.array_equal(a, b)
    finally:
        dense.close()
        paged.close()


def test_mux_evicts_pool_resident_paged_engine(params):
    """ModelMultiplexer admission over paged engines: measured bytes
    (device_bytes = params + FULL pool + draft) drive eviction; an idle
    paged engine is evicted to admit the next one, and comes back warm
    on demand."""
    from mxnet_tpu.serve import ModelMultiplexer

    def mk(name):
        return lambda: _engine(params, num_blocks=16, num_slots=2,
                               name=name)

    one = _engine(params, num_blocks=16, num_slots=2, name="probe")
    cost = one.device_bytes()
    one.close()
    mux = ModelMultiplexer(budget_bytes=int(cost * 1.5), max_live=0,
                           name="paged-mux")
    try:
        mux.add_model("a", mk("mux-a"), bytes_hint=cost)
        mux.add_model("b", mk("mux-b"), bytes_hint=cost)
        pa = _prompts(1)[0]
        got_a = mux.submit("a", pa, max_new_tokens=6).result(timeout=120)
        assert mux.live_models() == ["a"]
        # b does not fit beside a: a (idle) must be evicted, not b refused
        got_b = mux.submit("b", pa, max_new_tokens=6).result(timeout=120)
        assert mux.live_models() == ["b"]
        assert np.array_equal(got_a, got_b)     # same params, same stream
        rep = mux.stats.report()
        assert rep["evictions"] == 1 and rep["rejected"] == 0
        # measured footprint replaced the hint and includes the pool
        with mux._lock:
            e = mux._entries["b"]
            assert e.measured_bytes == cost
        # a comes back via rebuild and still serves correctly
        got_a2 = mux.submit("a", pa, max_new_tokens=6).result(timeout=120)
        assert np.array_equal(got_a2, got_a)
        assert mux.stats.report()["evictions"] == 2
    finally:
        mux.close()


def test_profiler_serve_report_paged_row(params):
    eng = _engine(params, num_blocks=30, draft_params=params,
                  draft_cfg=CFG, spec_k=2, name="report-paged")
    try:
        _run_all(eng, _prompts(4), max_new=8)
        rep = mx.profiler.serve_report()
        keys = [k for k in rep if k.startswith("report-paged#")]
        assert keys, "paged engine not registered with mx.profiler"
        r = rep[keys[-1]]
        assert r["kind"] == "paged" and r["completed"] == 4
        assert r["spec_rounds"] > 0 and r["prefill_tokens"] > 0
        assert 0 <= r["kv_utilization"] <= 1
        assert r["inter_token_p99_ms"] >= r["inter_token_p50_ms"] >= 0
        s = mx.profiler.serve_report_str()
        assert "report-paged" in s and "kv" in s
    finally:
        eng.close()


def test_env_knobs(params, monkeypatch):
    monkeypatch.setenv("MXNET_SERVE_SLOTS", "2")
    monkeypatch.setenv("MXNET_SERVE_MAX_TOKENS", "3")
    monkeypatch.setenv("MXNET_PAGED_CHUNK", "8")
    monkeypatch.setenv("MXNET_KVPOOL_BLOCK_TOKENS", "4")
    monkeypatch.setenv("MXNET_SPEC_DECODE_K", "2")
    eng = PagedDecodeEngine(params, CFG, draft_params=params,
                            draft_cfg=CFG, name="env-paged")
    try:
        assert eng.num_slots == 2 and eng.max_new_tokens == 3
        assert eng.chunk == 8 and eng.spec_k == 2
        assert eng.pool.block_tokens == 4
        got = eng.generate([1], timeout=120)
        assert len(got) == 3
    finally:
        eng.close()


def test_injected_step_fault_closes_engine(params):
    """The decode.step fault seam exists on the paged loop too: an
    injected paged.step error kills the loop, the engine flips closed,
    and later submits fast-fail instead of hanging."""
    from mxnet_tpu import faults
    eng = _engine(params, num_blocks=30, name="fault-paged")
    try:
        eng.generate([1], timeout=120, max_new_tokens=2)
        faults.install(faults.Rule(points="paged.step", kinds="error",
                                   max_faults=1))
        doomed = eng.submit([2], max_new_tokens=4)
        with pytest.raises(ServeError):
            doomed.result(timeout=60)
        faults.clear()
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline:
            try:
                eng.submit([3], max_new_tokens=2)
            except ServeClosedError:
                break
            time.sleep(0.02)
        else:
            pytest.fail("dead paged engine still accepting submits")
    finally:
        faults.clear()
        eng.close(drain=False)
