"""PagedDecodeEngine: LLM-class continuous batching over a paged KV cache.

The dense :class:`~mxnet_tpu.serve.decode.DecodeEngine` carries
fixed-shape per-slot state rows — right for RNN cells, wrong for
transformer decode, where per-slot state is a KV cache that grows with
context and padding every slot to max context makes long and short
streams uneconomical to co-host.  This engine keeps the slot/queue/
drain discipline of decode.py and swaps the state story:

* **paged KV cache** (pool.py) — K/V live in a shared device pool of
  fixed-size blocks; each slot maps logical context onto physical
  blocks through a page table.  Admission reserves a stream's exact
  worst-case block count (prompt + max_new are known at submit), so an
  admitted stream can never be dropped or deadlocked mid-generation:
  ``dropped_streams`` is 0 **by design**, and the bench gate holds it
  there;
* **one step program, two widths** — the compiled step consumes a
  ``(num_slots, C)`` token window with a per-slot valid count; C = 1 is
  the pure-decode program, C = ``chunk_tokens`` serves prefill chunks
  and speculative verification.  Both are warmed at construction, so
  the steady loop never compiles;
* **chunked prefill** — a long prompt enters the batch ``chunk_tokens``
  tokens at a time *alongside* in-flight decode slots (which keep
  emitting one token per step), bounding p99 inter-token latency under
  mixed prompt lengths instead of stalling the world on admission;
* **speculative decode** (spec.py) — a draft model sharing the pool's
  page table proposes K tokens per round; the target verifies K+1
  positions in ONE chunk-width step.  Greedy argmax acceptance makes
  the emitted stream token-identical to pure target decode — rejected
  tokens roll back by moving length counters, their stale KV rows are
  simply overwritten later;
* **attention** — the Pallas page-walk kernel
  (:func:`mxnet_tpu.ops.pallas_kernels.paged_attention`) on TPU, the
  dense gather reference off-TPU.  The reference reorders pool rows
  into logical order before one fixed-shape reduction, so dense-stripe
  (``paged=False``) and scattered page tables produce bitwise-identical
  logits — the parity baseline the tests pin.

Knobs: ``MXNET_KVPOOL_BLOCKS``, ``MXNET_KVPOOL_BLOCK_TOKENS``,
``MXNET_PAGED_CHUNK``, ``MXNET_SPEC_DECODE_K``, ``MXNET_PAGED_PALLAS``
(plus the decode-engine family: ``MXNET_SERVE_SLOTS``,
``MXNET_SERVE_DECODE_QUEUE``, ``MXNET_SERVE_MAX_TOKENS``) — see
docs/env_var.md and docs/llm_serve.md.
"""
from __future__ import annotations

import collections
import functools
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

import numpy as np

from ... import trace as _trace
from ...base import get_env, make_condition
from ...faults import point as _fault_point
from ..batcher import _IDLE_POLL_S, _set_exception, _set_result
from ..decode import _DecodeRequest, _trace_end
from ..errors import (ServeClosedError, ServeDeadlineError, ServeError,
                      ServeOverloadError, ServeRequestError)
from ..stats import PagedStats
from .model import LMConfig, lm_forward, param_bytes
from .pool import KVBlockPool

__all__ = ["PagedDecodeEngine"]


def _paged_step(params, kv_k, kv_v, tokens, pages, positions, n_valid,
                lengths, *, cfg, use_kernel):
    """One compiled decode step over a (S, C) token window.

    tokens/positions (S, C) int32; pages (S, B) int32; n_valid (S,)
    int32 tokens valid per slot; lengths (S,) int32 context size AFTER
    this step's appends.  Appends each valid token's K/V through the
    page table, then attends causally over the paged context.  Returns
    (argmax tokens (S, C) int32, kv_k, kv_v).

    Invalid window positions scatter into the pool's sentinel scratch
    row — a *positive* index with ``mode='drop'`` as the backstop, so
    nothing can wrap to block -1 (negative indices wrap in ``.at[]``;
    the PR 12 embedding-engine bug class).
    """
    import jax.numpy as jnp

    from ...ops.pallas_kernels import (_paged_attention_dense,
                                       paged_attention)
    s, c = tokens.shape
    bt = kv_k.shape[2]
    sentinel_row = kv_k.shape[1] - 1
    valid = jnp.arange(c, dtype=jnp.int32)[None, :] < n_valid[:, None]
    logical = jnp.clip(positions // bt, 0, pages.shape[1] - 1)
    phys = jnp.take_along_axis(pages, logical, axis=1)
    dest_blk = jnp.where(valid, phys, sentinel_row)
    off = positions % bt
    state = {"k": kv_k, "v": kv_v}

    def attend(layer, q, k_new, v_new):
        state["k"] = state["k"].at[layer, dest_blk, off].set(
            k_new, mode="drop")
        state["v"] = state["v"].at[layer, dest_blk, off].set(
            v_new, mode="drop")
        kp, vp = state["k"][layer], state["v"][layer]
        if use_kernel:
            return paged_attention(q, kp, vp, pages, lengths,
                                   q_pos=positions, causal=True)
        return _paged_attention_dense(q, kp, vp, pages, lengths,
                                      positions, causal=True)

    logits = lm_forward(params, tokens, positions, attend, cfg)
    toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return toks, state["k"], state["v"]


class _PagedSlot:
    __slots__ = ("req", "pos", "cache_len", "emitted", "next_tok",
                 "draft_len", "last_emit_t")

    def __init__(self, req: _DecodeRequest):
        self.req = req
        self.pos = 0                    # prompt tokens consumed
        self.cache_len = 0              # target KV length (tokens)
        self.emitted: List[int] = []
        self.next_tok: Optional[int] = None
        self.draft_len = 0              # draft KV length (tokens)
        self.last_emit_t = time.perf_counter()

    def prefilling(self) -> bool:
        return self.pos < self.req.prompt.size

    def committed(self, idx: int) -> int:
        """Token at committed-sequence index (prompt then emitted)."""
        p = self.req.prompt.size
        return int(self.req.prompt[idx]) if idx < p \
            else int(self.emitted[idx - p])


class PagedDecodeEngine:
    """Continuous batching for a paged-KV transformer LM (see module
    docstring).

    Parameters
    ----------
    params : dict name -> array
        :func:`~mxnet_tpu.serve.paged.model.init_lm_params` blob for
        ``cfg``.
    cfg : LMConfig
        Model geometry; ``cfg.max_context`` bounds
        ``prompt + max_new_tokens`` per stream.
    num_slots / max_new_tokens / queue_depth / deadline_ms / eos_id :
        As in DecodeEngine (same env defaults).
    num_blocks / block_tokens : int, optional
        KV pool geometry (``MXNET_KVPOOL_BLOCKS`` — default
        dense-equivalent — / ``MXNET_KVPOOL_BLOCK_TOKENS``).
    paged : bool
        False = dense baseline: every slot statically owns a full
        max-context block stripe (the DecodeEngine memory discipline),
        same step program — the bitwise token-parity reference.
    chunk_tokens : int, optional
        Prefill chunk / verify width (``MXNET_PAGED_CHUNK``, 32).
        Raised to ``spec_k + 1`` when speculative decode is on.
    draft_params / draft_cfg / spec_k :
        Speculative decode: draft model blob + geometry and the
        proposal depth K (``MXNET_SPEC_DECODE_K``, 0 = off).  The draft
        shares the pool's allocator and page table with its own K/V
        view.
    use_pallas : bool, optional
        Force the Pallas paged-attention kernel on/off; default
        ``MXNET_PAGED_PALLAS`` (auto: kernel on TPU, dense reference
        elsewhere).
    """

    def __init__(self, params: Dict, cfg: LMConfig, *,
                 num_slots: Optional[int] = None,
                 num_blocks: Optional[int] = None,
                 block_tokens: Optional[int] = None,
                 paged: bool = True,
                 chunk_tokens: Optional[int] = None,
                 draft_params: Optional[Dict] = None,
                 draft_cfg: Optional[LMConfig] = None,
                 spec_k: Optional[int] = None,
                 max_new_tokens: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 eos_id: Optional[int] = None,
                 use_pallas: Optional[bool] = None,
                 name: str = "paged", warmup: bool = True):
        import jax
        import jax.numpy as jnp

        from ...compile_cache import cached_jit

        if num_slots is None:
            num_slots = get_env("MXNET_SERVE_SLOTS", 8, int)
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ServeError("num_slots must be >= 1, got %d"
                             % self.num_slots)
        if max_new_tokens is None:
            max_new_tokens = get_env("MXNET_SERVE_MAX_TOKENS", 128, int)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ServeError("max_new_tokens must be >= 1, got %d"
                             % self.max_new_tokens)
        if queue_depth is None:
            queue_depth = get_env("MXNET_SERVE_DECODE_QUEUE",
                                  4 * self.num_slots, int)
        self.queue_depth = int(queue_depth)
        if self.queue_depth < 1:
            raise ServeError("queue_depth must be >= 1, got %d"
                             % self.queue_depth)
        self.deadline_ms = float(deadline_ms) if deadline_ms else None
        self.eos_id = eos_id
        self.name = name
        self.cfg = cfg
        self.max_context = int(cfg.max_context)
        self.paged = bool(paged)

        if spec_k is None:
            spec_k = get_env("MXNET_SPEC_DECODE_K", 0, int)
        self.spec_k = int(spec_k) if draft_params is not None else 0
        if self.spec_k and draft_cfg is None:
            raise ServeError("spec_k > 0 needs draft_cfg with "
                             "draft_params")
        if chunk_tokens is None:
            chunk_tokens = get_env("MXNET_PAGED_CHUNK", 32, int)
        self.chunk = max(2, min(int(chunk_tokens), self.max_context))
        if self.spec_k:
            if self.spec_k + 1 > self.chunk:
                # the verify window must fit the chunk program
                self.chunk = self.spec_k + 1
            if draft_cfg.max_context < cfg.max_context:
                raise ServeError(
                    "draft max_context %d < target max_context %d"
                    % (draft_cfg.max_context, cfg.max_context))

        if block_tokens is None:
            block_tokens = get_env("MXNET_KVPOOL_BLOCK_TOKENS", 16, int)
        bt = int(block_tokens)
        max_blocks = -(-self.max_context // bt)
        if not self.paged:
            num_blocks = self.num_slots * max_blocks
        self._pool = KVBlockPool(self.num_slots, max_blocks,
                                 num_blocks=num_blocks, block_tokens=bt,
                                 dense=not self.paged)
        self._pool.add_view("target", cfg.layers, cfg.heads, cfg.head_dim)
        self._params = {k: jnp.asarray(v) for k, v in params.items()}

        on_tpu = jax.default_backend() == "tpu"
        if use_pallas is None:
            use_pallas = on_tpu and bool(
                get_env("MXNET_PAGED_PALLAS", 1, int))
        self._use_kernel = bool(use_pallas)
        self._step_jit = cached_jit(
            functools.partial(_paged_step, cfg=cfg,
                              use_kernel=self._use_kernel),
            name="serve:paged_step", fast_key="serve|paged_step")

        self.stats = PagedStats(name, self.num_slots,
                                self._pool.num_blocks)
        from ... import profiler
        profiler.register_serve_stats(self.stats)

        self._spec = None
        if self.spec_k:
            from .spec import SpecDecoder
            self._spec = SpecDecoder(self, draft_params, draft_cfg,
                                     use_kernel=self._use_kernel)

        self._cv = make_condition("serve.paged")
        self._q: collections.deque = collections.deque()
        self._slots: List[Optional[_PagedSlot]] = [None] * self.num_slots
        self._active = 0
        self._closed = False
        self._drain = True

        if warmup:
            self._warmup()
        self._thread = threading.Thread(
            target=self._loop, name="%s-paged" % name, daemon=True)
        self._thread.start()

    # -- compiled-step plumbing --------------------------------------------
    def _run_target(self, tokens, positions, n_valid, lengths) -> np.ndarray:
        kv_k, kv_v = self._pool.view("target")
        toks, kk, vv = self._step_jit(
            self._params, kv_k, kv_v, tokens, self._pool.page_table(),
            positions, n_valid, lengths)
        self._pool.set_view("target", kk, vv)
        return np.asarray(toks)         # the step's ONE host sync

    def _staging(self, c: int):
        s = self.num_slots
        return (np.zeros((s, c), np.int32), np.zeros((s, c), np.int32),
                np.zeros((s,), np.int32), np.zeros((s,), np.int32))

    def _warmup(self) -> None:
        """Trace + compile every steady-loop program (C = 1 and
        C = chunk, target and draft) through the persistent compile
        cache: the decode loop itself never sees the XLA compiler.
        Zero-valid windows scatter only into the sentinel scratch row,
        so warmup leaves the logical cache untouched."""
        try:
            for c in (1, self.chunk):
                self._run_target(*self._staging(c))
            if self._spec is not None:
                for c in (1, self.chunk):
                    self._spec.run(*self._staging(c))
        except Exception as e:
            raise ServeError(
                "paged step compilation failed (slots=%d, chunk=%d, "
                "cfg=%s): %s: %s" % (self.num_slots, self.chunk,
                                     (self.cfg,), type(e).__name__, e)) \
                from e

    # -- client API --------------------------------------------------------
    def submit(self, prompt, max_new_tokens: Optional[int] = None,
               eos_id: Optional[int] = None,
               deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one decode stream; Future resolves to the np.int32
        array of newly generated tokens (prompt not echoed).  Raises
        ServeRequestError / ServeOverloadError / ServeClosedError
        immediately, in this thread."""
        arr = np.asarray(prompt)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        if arr.ndim != 1 or arr.size < 1:
            raise ServeRequestError(
                "prompt must be a non-empty 1-D token-id sequence, got "
                "shape %s" % (tuple(arr.shape),))
        if arr.dtype.kind not in "iu":
            if arr.dtype.kind == "f" and np.all(arr == np.floor(arr)):
                arr = arr.astype(np.int64)
            else:
                raise ServeRequestError(
                    "prompt dtype %s is not integral token ids"
                    % arr.dtype)
        if int(arr.min()) < 0 or int(arr.max()) >= self.cfg.vocab:
            raise ServeRequestError(
                "prompt token ids must be in [0, %d)" % self.cfg.vocab)
        mn = self.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if mn < 1:
            raise ServeRequestError(
                "max_new_tokens must be >= 1, got %d" % mn)
        if arr.size + mn > self.max_context:
            raise ServeRequestError(
                "prompt (%d) + max_new_tokens (%d) exceeds max_context "
                "%d" % (arr.size, mn, self.max_context))
        eos = self.eos_id if eos_id is None else eos_id
        dl = self.deadline_ms if deadline_ms is None else \
            (float(deadline_ms) or None)
        now = time.perf_counter()
        traced = _trace.enabled()
        req = _DecodeRequest(
            arr.astype(np.int64), mn, eos, Future(), now,
            now + dl / 1000.0 if dl else None,
            trace_id=_trace.next_async_id() if traced else None)
        if traced:
            _trace.async_begin("serve:decode_request", req.trace_id,
                               cat="serve", prompt_len=int(arr.size))
        with self._cv:
            if self._closed:
                _trace_end(req, "closed")
                raise ServeClosedError(
                    "paged engine %r is closed" % self.name)
            if len(self._q) >= self.queue_depth:
                self.stats.on_overload()
                _trace_end(req, "overloaded")
                raise ServeOverloadError(
                    "paged decode queue full (%d queued, depth %d): "
                    "shed load or retry with backoff"
                    % (len(self._q), self.queue_depth))
            self._q.append(req)
            self.stats.on_submit(len(self._q))
            self._cv.notify_all()
        return req.future

    def generate(self, prompt, timeout: Optional[float] = None,
                 **kwargs) -> np.ndarray:
        """Blocking one-shot: submit + result."""
        return self.submit(prompt, **kwargs).result(timeout=timeout)

    # -- decode loop (one owner thread) ------------------------------------
    def _blocks_for(self, req: _DecodeRequest) -> int:
        return self._pool.blocks_for(req.prompt.size + req.max_new)

    def _claim_locked(self) -> Optional[List[_DecodeRequest]]:
        """Pop admissible requests for the free slots (cv held).
        Admission is FIFO with **exact block reservation**: when the
        head stream's worst-case blocks do not fit the pool, nothing
        behind it is admitted either (no head-of-line skipping — large
        streams cannot be starved by a trickle of small ones)."""
        free = self.num_slots - self._active
        if free <= 0 or not self._q:
            return None
        out: List[_DecodeRequest] = []
        budget = self._pool.available_blocks()
        now = time.perf_counter()
        while self._q and len(out) < free:
            head = self._q[0]
            need = self._blocks_for(head)
            if need > budget and not head.future.cancelled() and not (
                    head.deadline_t is not None and now > head.deadline_t):
                break                   # pool full: head waits, FIFO
            req = self._q.popleft()
            if not req.future.set_running_or_notify_cancel():
                self.stats.on_cancelled(1)
                _trace_end(req, "cancelled")
            elif req.deadline_t is not None and now > req.deadline_t:
                self.stats.on_expired(1)
                _trace_end(req, "expired")
                _set_exception(req.future, ServeDeadlineError(
                    "admission deadline exceeded: %.1f ms queued against "
                    "a %.1f ms deadline"
                    % ((now - req.enqueue_t) * 1e3,
                       (req.deadline_t - req.enqueue_t) * 1e3)))
            else:
                out.append(req)
                budget -= need
        self.stats.set_queue_depth(len(self._q))
        return out or None

    def _join(self, reqs: List[_DecodeRequest]) -> None:
        for req in reqs:
            slot_idx = self._slots.index(None)
            if not self._pool.reserve(slot_idx, self._blocks_for(req)):
                # _claim_locked checked the budget and only this thread
                # touches the pool — reaching here is an accounting bug
                raise ServeError(
                    "pool reservation failed after admission check "
                    "(slot %d)" % slot_idx)
            self._slots[slot_idx] = _PagedSlot(req)
            self._active += 1
            if req.trace_id is not None and _trace.enabled():
                _trace.async_instant("serve:decode_request", req.trace_id,
                                     cat="serve", at="admit",
                                     slot=slot_idx)
        self.stats.on_admitted(len(reqs))

    def _k_eff(self, sl: _PagedSlot) -> int:
        """Speculation depth for this slot this round: never propose
        past max_new (the bonus token always lands) or the verify
        window."""
        return max(0, min(self.spec_k,
                          sl.req.max_new - len(sl.emitted) - 1,
                          self.chunk - 1))

    def _emit(self, i: int, sl: _PagedSlot, toks: List[int]) -> int:
        """Append generated tokens to slot ``i``'s stream, stopping at
        eos / max_new; resolves + frees the slot when the stream
        finishes.  Returns the number of tokens emitted."""
        req = sl.req
        now = time.perf_counter()
        gaps: List[float] = []
        count = 0
        finished = False
        for t in toks:
            sl.emitted.append(t)
            sl.next_tok = t
            count += 1
            gaps.append((now - sl.last_emit_t) * 1e3 if count == 1
                        else 0.0)
            if len(sl.emitted) >= req.max_new or \
                    (req.eos_id is not None and t == req.eos_id):
                finished = True
                break
        sl.last_emit_t = now
        self.stats.on_inter_token(gaps)
        if finished:
            if _set_result(req.future, np.asarray(sl.emitted, np.int32)):
                self.stats.on_complete([(now - req.enqueue_t) * 1e3])
            _trace_end(req, "resolved")
            self._pool.release(i)
            self._slots[i] = None
            self._active -= 1
        return count

    def _mixed_step(self, active) -> int:
        """One chunk-width step: prefilling slots consume up to
        ``chunk`` prompt tokens, decoding slots one token — a long
        prompt shares the batch with in-flight decode instead of
        stalling it."""
        tokens, positions, n_valid, lengths = self._staging(self.chunk)
        plan: Dict[int, int] = {}
        for i, sl in active:
            if sl.prefilling():
                c = min(self.chunk, sl.req.prompt.size - sl.pos)
                tokens[i, :c] = sl.req.prompt[sl.pos:sl.pos + c]
                plan[i] = c
            else:
                c = 1
                tokens[i, 0] = sl.next_tok
                plan[i] = 0
            n_valid[i] = c
            positions[i, :c] = sl.cache_len + np.arange(c)
            lengths[i] = sl.cache_len + c
            self._pool.ensure(i, sl.cache_len + c)
        toks = self._run_target(tokens, positions, n_valid, lengths)
        emitted = 0
        prefill_tokens = 0
        for i, sl in active:
            c = plan[i]
            if c:                       # prefill slot
                sl.pos += c
                sl.cache_len += c
                prefill_tokens += c
                if not sl.prefilling():
                    # final chunk: its last logit is the first token
                    emitted += self._emit(i, sl, [int(toks[i, c - 1])])
            else:
                sl.cache_len += 1
                emitted += self._emit(i, sl, [int(toks[i, 0])])
        if prefill_tokens:
            self.stats.on_prefill(prefill_tokens)
        return emitted

    def _plain_step(self, active) -> int:
        """One pure-decode step: every slot consumes its last token."""
        tokens, positions, n_valid, lengths = self._staging(1)
        for i, sl in active:
            tokens[i, 0] = sl.next_tok
            n_valid[i] = 1
            positions[i, 0] = sl.cache_len
            lengths[i] = sl.cache_len + 1
            self._pool.ensure(i, sl.cache_len + 1)
        toks = self._run_target(tokens, positions, n_valid, lengths)
        emitted = 0
        for i, sl in active:
            sl.cache_len += 1
            emitted += self._emit(i, sl, [int(toks[i, 0])])
        return emitted

    def _spec_round(self, active) -> int:
        """One speculative round: the draft proposes up to K tokens per
        slot, the target verifies every slot's window in ONE chunk-width
        step, greedy acceptance commits the longest agreeing prefix
        plus the target's own next token.  Rejected positions roll back
        by *not advancing* the length counters — their stale KV rows
        are overwritten when those positions refill."""
        k_eff = {i: self._k_eff(sl) for i, sl in active}
        props = self._spec.propose(active, k_eff)
        tokens, positions, n_valid, lengths = self._staging(self.chunk)
        for i, sl in active:
            window = [sl.next_tok] + props.get(i, [])
            nv = len(window)
            tokens[i, :nv] = window
            n_valid[i] = nv
            positions[i, :nv] = sl.cache_len + np.arange(nv)
            lengths[i] = sl.cache_len + nv
            self._pool.ensure(i, sl.cache_len + nv)
        toks = self._run_target(tokens, positions, n_valid, lengths)
        emitted = 0
        for i, sl in active:
            prop = props.get(i, [])
            a = [int(x) for x in toks[i, :len(prop) + 1]]
            j = 0
            while j < len(prop) and prop[j] == a[j]:
                j += 1
            base = sl.cache_len
            sl.cache_len = base + j + 1
            sl.draft_len = base + min(j + 1, len(prop))
            self.stats.on_spec_round(len(prop), j)
            emitted += self._emit(i, sl, a[:j + 1])
        return emitted

    def _step(self) -> None:
        active = [(i, sl) for i, sl in enumerate(self._slots)
                  if sl is not None]
        n_active = len(active)
        # same seam as decode.step: `delay` stretches a step, `error`
        # kills the loop (replica-crash shape)
        _fault_point("paged.step", active=n_active)
        with _trace.span("serve:paged_step", cat="serve",
                         active=n_active, slots=self.num_slots):
            if any(sl.prefilling() for _, sl in active):
                emitted = self._mixed_step(active)
            elif self._spec is not None and \
                    any(self._k_eff(sl) > 0 for _, sl in active):
                emitted = self._spec_round(active)
            else:
                emitted = self._plain_step(active)
        self.stats.on_step(n_active, emitted)
        self.stats.set_pool(self._pool.used_blocks(),
                            self._pool.reserved_blocks())
        _trace.counter("serve:paged_kv_blocks", cat="serve",
                       used=self._pool.used_blocks(),
                       reserved=self._pool.reserved_blocks())

    def _loop(self) -> None:
        try:
            while True:
                admitted = None
                with self._cv:
                    while (not self._closed and self._active == 0
                           and not self._q):
                        self._cv.wait(_IDLE_POLL_S)
                    if self._closed and not self._drain:
                        break
                    admitted = self._claim_locked()
                    if (self._closed and self._active == 0
                            and admitted is None and not self._q):
                        break
                if admitted:
                    self._join(admitted)
                if self._active:
                    self._step()
        finally:
            self._shutdown_tail()

    def _shutdown_tail(self) -> None:
        """Loop epilogue: fail whatever remains (drain=False, or a step
        error) and flip _closed so no new submit can enqueue onto a
        dead loop."""
        with self._cv:
            self._closed = True
            leftovers = list(self._q)
            self._q.clear()
            self.stats.set_queue_depth(0)
        exc = ServeClosedError(
            "paged engine %r closed before this stream finished"
            % self.name)
        failed = cancelled = 0
        for i, sl in enumerate(self._slots):
            if sl is None:
                continue
            self._slots[i] = None
            self._active -= 1
            self._pool.release(i)
            _trace_end(sl.req, "closed")
            if _set_exception(sl.req.future, exc):
                failed += 1
        for req in leftovers:
            _trace_end(req, "closed")
            if _set_exception(req.future, exc):
                failed += 1
            else:
                cancelled += 1
        if failed:
            self.stats.on_failed(failed)
        if cancelled:
            self.stats.on_cancelled(cancelled)

    # -- introspection / lifecycle -----------------------------------------
    def pending_requests(self) -> int:
        with self._cv:
            return len(self._q)

    def outstanding(self) -> int:
        return self.stats.outstanding()

    @property
    def pool(self) -> KVBlockPool:
        return self._pool

    def device_bytes(self) -> int:
        """Device footprint: target params + draft params + the FULL
        KV block pool (every view) — the multiplexer admission
        currency.  The pool is the dominant term for long contexts;
        counting it here is what keeps co-hosting a draft model from
        silently blowing MXNET_SERVE_MUX_BYTES."""
        total = param_bytes(self._params) + self._pool.device_bytes()
        if self._spec is not None:
            total += param_bytes(self._spec.params)
        return total

    def close(self, drain: bool = True) -> None:
        """Stop admissions; drain=True finishes queued + in-flight
        streams first, drain=False fails them with ServeClosedError.
        Thread-safe, idempotent; from the decode thread itself this
        degrades to a non-joining shutdown request."""
        with self._cv:
            self._closed = True
            if not drain:
                self._drain = False
            self._cv.notify_all()
        if threading.current_thread() is self._thread:
            return
        self._thread.join()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
