package ml.dmlc.mxnet_tpu

import java.io.PrintWriter

import org.scalatest.FunSuite

import ml.dmlc.mxnet_tpu.io.{FullNDArrayIter, IO, PrefetchingIter, ResizeIter}

/** Reference IOSuite.scala analogue: the ABI-backed iterator registry
 * plus the Scala-side iterator adapters. */
class IOSuite extends FunSuite {

  private def writeCsv(rows: Int, cols: Int): String = {
    val f = java.io.File.createTempFile("iodata", ".csv")
    f.deleteOnExit()
    val w = new PrintWriter(f)
    try {
      for (i <- 0 until rows) {
        w.println((0 until cols).map(c => i * cols + c).mkString(","))
      }
    } finally w.close()
    f.getPath
  }

  test("registry lists the native iterators") {
    val names = IO.iterNames
    assert(names.contains("CSVIter"))
    assert(names.contains("MNISTIter"))
    assert(names.contains("ImageRecordIter"))
  }

  test("CSVIter end to end with rewind") {
    val csv = writeCsv(8, 3)
    val it = IO.createIterator("CSVIter",
      Map("data_csv" -> csv, "data_shape" -> "(3)", "batch_size" -> "4"))
    assert(it.batchSize == 4)
    assert(it.provideData("data") == Shape(4, 3))
    var batches = 0
    var first = -1f
    while (it.hasNext) {
      val b = it.next()
      if (batches == 0) first = b.data.head.toArray.head
      batches += 1
    }
    assert(batches == 2)
    assert(first == 0f)
    it.reset()
    assert(it.hasNext)   // rewound
    it.dispose()
  }

  test("FullNDArrayIter pads the wrapped final batch") {
    val data = (0 until 10 * 4).map(_.toFloat).toArray
    val label = (0 until 10).map(_.toFloat).toArray
    val it = new FullNDArrayIter(data, Shape(4), label, 1, batchSize = 4)
    val batches = it.toIndexedSeq
    assert(batches.length == 3)
    assert(batches.last.pad == 2)
    it.reset()
    assert(it.next().label.head.toArray.head == 0f)
  }

  test("FullNDArrayIter discard drops the ragged tail") {
    val data = (0 until 10 * 2).map(_.toFloat).toArray
    val label = (0 until 10).map(_.toFloat).toArray
    val it = new FullNDArrayIter(data, Shape(2), label, 1, batchSize = 4,
                                 lastBatchHandle = "discard")
    assert(it.toIndexedSeq.length == 2)
  }

  test("PrefetchingIter delivers every batch, supports mid-epoch reset") {
    val data = (0 until 12 * 2).map(_.toFloat).toArray
    val label = (0 until 12).map(_.toFloat).toArray
    val inner = new FullNDArrayIter(data, Shape(2), label, 1, batchSize = 4)
    val p = new PrefetchingIter(IndexedSeq(inner))
    assert(p.next() != null)       // consume one batch
    p.reset()                      // then abandon the epoch
    var n = 0
    while (p.hasNext) { p.next(); n += 1 }
    assert(n == 3)                 // fresh epoch delivers all batches
  }

  test("ResizeIter wraps short epochs to the requested length") {
    val data = (0 until 8 * 2).map(_.toFloat).toArray
    val label = (0 until 8).map(_.toFloat).toArray
    val inner = new FullNDArrayIter(data, Shape(2), label, 1, batchSize = 4)
    val r = new ResizeIter(inner, 5)
    var n = 0
    while (r.hasNext) { r.next(); n += 1 }
    assert(n == 5)
  }
}
