"""Profiler: step traces and scoped annotations.

Reference era had no timeline profiler (SURVEY §5.1: Monitor + debug_str +
MXNET_ENGINE_INFO were the tools; later MXNet grew mx.profiler).  The
TPU-native build completes the observability story by exposing XLA's real
profiler through the mx surface:

    mx.profiler.profiler_set_config(filename="/tmp/trace")
    mx.profiler.profiler_set_state("run")
    ... training steps ...
    mx.profiler.profiler_set_state("stop")   # trace dir for xprof/tensorboard

    with mx.profiler.scope("data-loading"):  # named regions in the trace
        batch = next(it)

Function names mirror the later-mxnet C API (MXSetProfilerConfig /
MXSetProfilerState) so ported scripts work unchanged.
"""
from __future__ import annotations

import contextlib
import os
import threading
import weakref

from .base import make_lock

__all__ = ["profiler_set_config", "profiler_set_state", "scope",
           "dump_profile", "dump_trace", "state", "register_feed_stats",
           "feed_report", "feed_report_str", "register_checkpoint_stats",
           "checkpoint_report", "checkpoint_report_str", "SuperstepStats",
           "register_superstep_stats", "superstep_report",
           "superstep_report_str", "register_serve_stats", "serve_report",
           "serve_report_str", "register_embed_stats", "embed_report",
           "embed_report_str", "register_moe_stats", "moe_report",
           "moe_report_str", "compile_report", "compile_report_str",
           "register_passes_stats", "passes_report", "passes_report_str",
           "register_autotune_stats", "autotune_report",
           "autotune_report_str", "costmodel_report",
           "costmodel_report_str", "register_faults_stats",
           "faults_report", "faults_report_str",
           "register_online_stats", "online_report", "online_report_str",
           "MultichipStats", "register_multichip_stats",
           "parse_hlo_collectives", "multichip_report",
           "multichip_report_str", "unified_report", "unified_report_str"]

_config = {"filename": "profile_output", "mode": "symbolic"}
_state = "stop"


def profiler_set_config(mode: str = "symbolic",
                        filename: str = "profile_output") -> None:
    """Configure the trace output directory (reference
    MXSetProfilerConfig(mode, filename))."""
    _config["mode"] = mode
    _config["filename"] = filename


def profiler_set_state(state_name: str = "stop") -> None:
    """'run' starts a jax.profiler trace into the configured directory,
    'stop' ends it (reference MXSetProfilerState(1/0))."""
    global _state
    import jax
    if state_name not in ("run", "stop"):
        raise ValueError("state must be 'run' or 'stop'")
    if state_name == "run" and _state != "run":
        out = _config["filename"]
        os.makedirs(out, exist_ok=True)
        jax.profiler.start_trace(out)
        _state = "run"
    elif state_name == "stop" and _state == "run":
        jax.profiler.stop_trace()
        _state = "stop"


def state() -> str:
    return _state


def dump_profile() -> str:
    """Write the Chrome-format span trace for the configured filename and
    return its path (reference MXDumpProfile wrote the json to the
    configured file; the span runtime now honors that contract — the
    returned file loads in chrome://tracing / Perfetto).  XLA's own
    xprof trace, when profiler_set_state("run") was used, streams into
    the configured directory separately."""
    from . import trace as _trace
    out = _config["filename"]
    path = out if out.endswith(".json") else out + ".trace.json"
    return _trace.dump_trace(path)


def dump_trace(path: str) -> str:
    """Write the merged span timeline (this process + registered worker
    spill dirs) as Chrome/Perfetto trace-event JSON; returns ``path``.
    See mxnet_tpu.trace and docs/observability.md."""
    from . import trace as _trace
    return _trace.dump_trace(path)


# -- the shared stats registry ----------------------------------------------
# Every subsystem's live stats objects register here (weakly: a dropped
# pipeline/engine/manager disappears from reports without an unregister
# call).  ONE lock guards every registry's mutation and iteration:
# register_* is called from writer threads (serve engines from request
# threads, checkpoint managers from fit, feed pipelines from pipeline
# construction) while report readers iterate — a WeakValueDictionary
# mutating under iteration is a RuntimeError, so every reader
# snapshot-copies under the lock first.  The per-object counter locks
# (StageStats, ServeStats, ...) stay where they are; this lock only
# covers registry membership.
_registry_lock = make_lock("profiler.registry")


class _Registry:
    """name -> live stats objects, weakly held, creation-ordered."""

    def __init__(self, label: str, empty_str: str):
        self.label = label
        self.empty_str = empty_str
        self._items = weakref.WeakValueDictionary()
        self._seq = 0

    def register(self, obj) -> None:
        with _registry_lock:
            self._seq += 1
            # zero-padded seq so lexicographic order == creation order
            self._items["%s#%06d" % (obj.name, self._seq)] = obj

    def snapshot(self):
        """Strong-referenced (key, obj) list — safe to iterate while
        other threads register/drop."""
        with _registry_lock:
            return sorted(self._items.items())

    def __len__(self) -> int:
        with _registry_lock:
            return len(self._items)

    def report(self, **kw) -> dict:
        return {key: obj.report(**kw) for key, obj in self.snapshot()}

    def report_str(self, **kw) -> str:
        parts = [obj.report_str(**kw) for _, obj in self.snapshot()]
        return "\n\n".join(parts) if parts else self.empty_str


# -- feed-pipeline instrumentation (mxnet_tpu.feed) -------------------------
# Live pipelines register their PipelineStats here (weakly: a dropped
# pipeline disappears from reports without an unregister call), so one
# feed_report() shows every stage of every running input pipeline —
# items/sec, busy time, producer/consumer stall time, queue depth — and
# therefore exactly which stage starves the chip.  Multi-process stages
# (feed.ParallelReader) publish per-worker counters through shared
# memory; their StageStats merges them into every snapshot (a "workers"
# sub-dict with per-process items/s, busy time, restart count and
# liveness, plus aggregated worker_items/worker_busy_s/restarts), so the
# report covers the whole reader process tree, not just the parent.
_feed_registry = _Registry("feed", "(no live feed pipelines)")


def register_feed_stats(pipeline_stats) -> None:
    """Called by feed.Pipeline / feed.DevicePrefetchIter on construction."""
    _feed_registry.register(pipeline_stats)


def feed_report() -> dict:
    """{pipeline key: {stage name: counters}} for every live pipeline,
    including per-worker-process counters for multi-process reader
    stages (see the registry note above)."""
    return _feed_registry.report()


def feed_report_str() -> str:
    """Human-readable per-stage table for every live feed pipeline."""
    out = _feed_registry.report_str()
    if len(_superstep_registry):
        # the chip-side half of the same story: whether the loop is
        # dispatch-bound or compute-bound lives in superstep_report()
        out += ("\n\n(superstep dispatch/wait/stage split: see "
                "mx.profiler.superstep_report_str())")
    return out


# -- superstep instrumentation (module/fused.py build_superstep) -------------
# One SuperstepStats per training Module running fit(superstep=K),
# registered weakly like the feed pipelines.  The counters split the host
# side of every superstep into the three places time can go, so
# "dispatch-bound vs compute-bound" is measured rather than inferred:
#
#   h2d_stage_s     megabatch assembly + the device_put issue time
#   step_dispatch_s enqueueing the K-step program (host->XLA dispatch;
#                   on an async backend this returns before compute ends)
#   device_wait_s   blocking on the drained metric accumulators — i.e.
#                   actual device compute the host had to wait out
_superstep_registry = _Registry("superstep", "(no live superstep loops)")


class SuperstepStats:
    """Counters for the K-steps-per-dispatch training loop.  Cumulative
    totals plus ``window()`` deltas (per-window counters for bench
    loops: call once per measurement window and diff)."""

    def __init__(self, name: str = "superstep"):
        self.name = name
        self.supersteps = 0
        self.steps = 0
        self.h2d_stage_s = 0.0
        self.step_dispatch_s = 0.0
        self.device_wait_s = 0.0
        self._window_base = self._totals()

    def _totals(self) -> dict:
        return {"supersteps": self.supersteps, "steps": self.steps,
                "h2d_stage_s": self.h2d_stage_s,
                "step_dispatch_s": self.step_dispatch_s,
                "device_wait_s": self.device_wait_s}

    def add(self, steps: int, h2d_s: float, dispatch_s: float,
            wait_s: float) -> None:
        self.supersteps += 1
        self.steps += int(steps)
        self.h2d_stage_s += h2d_s
        self.step_dispatch_s += dispatch_s
        self.device_wait_s += wait_s

    def window(self) -> dict:
        """Counters accumulated since the previous window() call."""
        now = self._totals()
        delta = {k: now[k] - self._window_base[k] for k in now}
        self._window_base = now
        return delta

    def report(self) -> dict:
        out = self._totals()
        if self.steps:
            out["host_s_per_step"] = (
                self.h2d_stage_s + self.step_dispatch_s
                + self.device_wait_s) / self.steps
        return out

    def report_str(self) -> str:
        r = self.report()
        lines = ["%s: %d supersteps / %d steps" % (self.name,
                                                   r["supersteps"],
                                                   r["steps"])]
        for key in ("h2d_stage_s", "step_dispatch_s", "device_wait_s"):
            lines.append("  %-16s %10.4f" % (key, r[key]))
        if "host_s_per_step" in r:
            lines.append("  %-16s %10.6f" % ("host_s/step",
                                             r["host_s_per_step"]))
        return "\n".join(lines)


def register_superstep_stats(superstep_stats) -> None:
    """Called by Module.superstep_train on first dispatch."""
    _superstep_registry.register(superstep_stats)


def superstep_report() -> dict:
    """{key: counters} for every live superstep-training module; the
    feed-side view of the same loop is feed_report()."""
    return _superstep_registry.report()


def superstep_report_str() -> str:
    """Human-readable dispatch/wait/stage split per training loop."""
    out = _superstep_registry.report_str()
    if len(_multichip_registry):
        # the mesh-side view of the same loop: collective vs compute
        # split and per-axis usage live in multichip_report()
        out += ("\n\n(per-axis collective/compute split: see "
                "mx.profiler.multichip_report_str())")
    return out


# -- multichip instrumentation (module/fused.py over a device mesh) ----------
# One MultichipStats per FusedTrainStep spanning >1 device, registered
# weakly like the feed pipelines.  The counters answer "where does a mesh
# step's time go, and how much of it is collectives":
#
#   dispatch_s          host time enqueueing the step program (async
#                       backends return before compute ends)
#   sampled_device_s    full step wall measured by block_until_ready on
#                       a sampled subset (1 in sample_every steps — the
#                       async pipeline stays intact between samples)
#   flops/bytes         XLA cost analysis of the AOT-compiled step —
#                       PER DEVICE (SPMD cost analysis reports one
#                       partition's work)
#   collectives         op counts + per-device payload bytes parsed
#                       from the optimized (post-SPMD-partitioner) HLO
#                       — the REAL all-reduce/all-gather/reduce-scatter
#                       the partitioner inserted for the mesh
#
# ``report(peak_tflops=, ici_gbps=)`` turns the static numbers into a
# collective-vs-compute time split estimate; without them the raw
# counts/bytes and the measured wall splits are reported as-is.
_multichip_registry = _Registry("multichip", "(no live multichip steps)")

_HLO_COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
                    "collective-permute", "all-to-all")
_HLO_ITEMSIZE = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
                 "s64": 8, "s32": 4, "s16": 2, "s8": 1,
                 "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1}


def parse_hlo_collectives(hlo_text: str) -> dict:
    """Collective-op census of one post-partitioner HLO module text:
    per-op instruction counts plus the payload bytes of every typed
    collective result.  The partitioned HLO is per-device, so counts
    and bytes are PER DEVICE per program execution.  Async ``-start``
    ops (TPU backends) return a tuple mixing the aliased operand, the
    result and possibly context scalars — the largest element counts
    as the payload, and the ``-done`` halves of the pairs are skipped
    entirely (the -start carries the shape)."""
    import re
    out = {op: {"count": 0, "bytes": 0} for op in _HLO_COLLECTIVES}
    line_pat = re.compile(
        r"=\s*(\([^)]*\)|\S+)\s+(%s)(-start)?\("
        % "|".join(_HLO_COLLECTIVES))
    shape_pat = re.compile(r"([a-z]+\d*)\[([0-9,]*)\]")
    for m in line_pat.finditer(hlo_text or ""):
        shapes, op, started = m.group(1), m.group(2), m.group(3)
        out[op]["count"] += 1
        found = shape_pat.findall(shapes)

        def nbytes(dt, dims):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            return n * _HLO_ITEMSIZE.get(dt, 4)
        sizes = [nbytes(dt, dims) for dt, dims in found]
        if started and len(sizes) > 1:
            # -start tuples mix the aliased operand, the result, and
            # (collective-permute) u32 context scalars — the largest
            # element is the payload; summing would double it and
            # halving would keep the context scalars
            out[op]["bytes"] += max(sizes)
        else:
            out[op]["bytes"] += sum(sizes)
    out["total_count"] = sum(v["count"] for k, v in out.items()
                             if isinstance(v, dict))
    out["total_bytes"] = sum(v["bytes"] for k, v in out.items()
                             if isinstance(v, dict))
    return out


class MultichipStats:
    """Counters for one mesh-spanning fused train step (see the section
    note above).  ``axes`` is the mesh's ((name, size), ...) tuple;
    ``spec_axes`` the axes any per-param sharding spec references."""

    def __init__(self, name: str, axes, spec_axes=(), sample_every: int = 16):
        self.name = name
        self.axes = tuple((str(a), int(s)) for a, s in axes)
        self.spec_axes = tuple(spec_axes)
        self.devices = 1
        for _, s in self.axes:
            self.devices *= s
        self.sample_every = max(1, int(sample_every))
        self.steps = 0
        self.dispatch_s = 0.0
        self.first_step_s = 0.0
        self.sampled_steps = 0
        self.sampled_device_s = 0.0
        self.flops_per_step = 0.0
        self.bytes_per_step = 0.0
        self.collectives = None

    def add_step(self, dispatch_s: float) -> None:
        self.steps += 1
        self.dispatch_s += dispatch_s

    def note_first(self, dispatch_s: float) -> None:
        """The first dispatch blocks through trace+XLA compile (seconds
        on a cold cache) — recording it into dispatch_s would dominate
        dispatch_s_per_step forever, so it gets its own counter."""
        self.steps += 1
        self.first_step_s = dispatch_s

    def should_sample(self) -> bool:
        """Checked BEFORE add_step: true on the 2nd, (N+2)th, ... call
        — never the first, whose wall is compile time (the caller
        skips it; sample_every=1 samples every step after it)."""
        return self.sample_every == 1 \
            or self.steps % self.sample_every == 1

    def add_wait(self, device_s: float) -> None:
        self.sampled_steps += 1
        self.sampled_device_s += device_s

    def add_superstep(self, k: int, dispatch_s: float,
                      wait_s: float = 0.0) -> None:
        """K steps dispatched as ONE scan program (Module.superstep_
        train): the metric drain's wait already measures the device
        wall, so it feeds the sampled column without extra syncs."""
        self.steps += int(k)
        self.dispatch_s += dispatch_s
        if wait_s:
            self.sampled_steps += int(k)
            self.sampled_device_s += wait_s

    def set_cost(self, flops: float = 0.0, bytes_accessed: float = 0.0,
                 collectives=None) -> None:
        self.flops_per_step = float(flops)
        self.bytes_per_step = float(bytes_accessed)
        if collectives is not None:
            self.collectives = dict(collectives)

    def report(self, peak_tflops=None, ici_gbps=None) -> dict:
        out = {
            "mesh": dict(self.axes),
            "devices": self.devices,
            "steps": self.steps,
            "dispatch_s": round(self.dispatch_s, 4),
            "sampled_steps": self.sampled_steps,
            "sampled_device_s": round(self.sampled_device_s, 4),
        }
        # per-axis view: degree + what uses the axis (the batch rides
        # "dp"; tensor-parallel specs ride the axes they reference)
        out["per_axis"] = {
            a: {"size": s,
                "batch_sharded": a == "dp",
                "param_sharded": a in self.spec_axes}
            for a, s in self.axes}
        if self.first_step_s:
            out["first_step_s"] = round(self.first_step_s, 4)
        if self.sampled_steps:
            out["device_s_per_step"] = round(
                self.sampled_device_s / self.sampled_steps, 6)
        steady = self.steps - (1 if self.first_step_s else 0)
        if steady > 0:
            out["dispatch_s_per_step"] = round(
                self.dispatch_s / steady, 6)
        if self.flops_per_step:
            out["flops_per_step"] = self.flops_per_step
            out["bytes_per_step"] = self.bytes_per_step
        if self.collectives is not None:
            out["collectives"] = self.collectives
        # estimated collective-vs-compute split, only when the caller
        # supplies the hardware numbers the estimate needs.  cost
        # analysis of an SPMD executable and the partitioned HLO are
        # both PER DEVICE already (verified: a dp=8 matmul reports 1/8
        # the single-device flops), so neither divides by devices —
        # per-device work over per-device peak / link bandwidth IS the
        # per-device time estimate.
        if peak_tflops and self.flops_per_step:
            out["compute_s_est"] = self.flops_per_step \
                / (peak_tflops * 1e12)
        if ici_gbps and self.collectives and \
                self.collectives.get("total_bytes"):
            out["collective_s_est"] = (self.collectives["total_bytes"]
                                       / (ici_gbps * 1e9))
            if out.get("compute_s_est"):
                tot = out["compute_s_est"] + out["collective_s_est"]
                out["collective_frac_est"] = out["collective_s_est"] / tot
        # measured fallback for the same split: device wall minus the
        # compute estimate when both exist
        if out.get("device_s_per_step") and out.get("compute_s_est"):
            out["collective_s_measured_est"] = max(
                0.0, out["device_s_per_step"] - out["compute_s_est"])
        return out

    def report_str(self, peak_tflops=None, ici_gbps=None) -> str:
        r = self.report(peak_tflops=peak_tflops, ici_gbps=ici_gbps)
        mesh = " x ".join("%s=%d" % (a, s) for a, s in self.axes)
        lines = ["%s: mesh %s (%d devices), %d steps"
                 % (self.name, mesh or "1", r["devices"], r["steps"])]
        if "dispatch_s_per_step" in r:
            lines.append("  dispatch_s/step   %10.6f"
                         % r["dispatch_s_per_step"])
        if "first_step_s" in r:
            lines.append("  first step        %10.4f (trace+compile)"
                         % r["first_step_s"])
        if "device_s_per_step" in r:
            lines.append("  device_s/step     %10.6f (sampled %d)"
                         % (r["device_s_per_step"], r["sampled_steps"]))
        if "flops_per_step" in r:
            lines.append("  flops/step        %10.3e" % r["flops_per_step"])
        c = r.get("collectives")
        if c:
            lines.append("  collectives/step  %d ops, %.3f MB"
                         % (c["total_count"], c["total_bytes"] / 1e6))
            for op in _HLO_COLLECTIVES:
                if c.get(op, {}).get("count"):
                    lines.append("    %-19s %3d ops %10.3f MB"
                                 % (op, c[op]["count"],
                                    c[op]["bytes"] / 1e6))
        if "collective_frac_est" in r:
            lines.append("  collective frac   %10.3f (est @ %s TF/s, %s "
                         "GB/s ICI)" % (r["collective_frac_est"],
                                        peak_tflops, ici_gbps))
        for a, info in r["per_axis"].items():
            use = [u for u, on in (("batch", info["batch_sharded"]),
                                   ("params", info["param_sharded"])) if on]
            lines.append("  axis %-6s size %2d  shards: %s"
                         % (a, info["size"], ", ".join(use) or "(unused)"))
        return "\n".join(lines)


def register_multichip_stats(multichip_stats) -> None:
    """Called by FusedTrainStep when its mesh spans >1 device."""
    _multichip_registry.register(multichip_stats)


def multichip_report(peak_tflops=None, ici_gbps=None) -> dict:
    """{key: counters} for every live mesh-spanning train step; pass
    PER-DEVICE ``peak_tflops`` (e.g. bench.py's probe result) and
    ``ici_gbps`` link bandwidth for the collective-vs-compute time
    estimate."""
    return _multichip_registry.report(peak_tflops=peak_tflops,
                                      ici_gbps=ici_gbps)


def multichip_report_str(peak_tflops=None, ici_gbps=None) -> str:
    """Human-readable per-mesh dispatch/device/collective table."""
    return _multichip_registry.report_str(peak_tflops=peak_tflops,
                                          ici_gbps=ici_gbps)


# -- checkpoint instrumentation (mxnet_tpu.checkpoint) ----------------------
# Live CheckpointManagers register their CheckpointStats here, weakly like
# the feed pipelines above, so one checkpoint_report() shows every
# manager's save/restore wall time, bytes/s, and the train-thread overhead
# each save cost — the numbers BENCH's ckpt leg tracks over rounds.
_ckpt_registry = _Registry("checkpoint", "(no live checkpoint managers)")


def register_checkpoint_stats(ckpt_stats) -> None:
    """Called by checkpoint.CheckpointManager on construction."""
    _ckpt_registry.register(ckpt_stats)


def checkpoint_report() -> dict:
    """{manager key: counters} for every live CheckpointManager."""
    return _ckpt_registry.report()


def checkpoint_report_str() -> str:
    """Human-readable save/restore counters for every live manager."""
    return _ckpt_registry.report_str()


# -- serving instrumentation (mxnet_tpu.serve) ------------------------------
# Every serving component registers its stats object here, weakly like
# the feed pipelines, so one serve_report() is MULTIPLEX-AWARE: a
# process serving N models shows one row per component, each tagged by
# "kind" and carrying its OWN capacity shape — ServeStats rows (kind
# "engine": latency percentiles, queue depth, batch occupancy against
# that engine's max_batch_size, pad waste, per-bucket hits), DecodeStats
# rows (kind "decode": slot occupancy, steps, tokens out), the
# multiplexer's MuxStats (kind "mux": swap-in/eviction counters, live
# bytes vs budget) and the router's RouterStats (kind "router":
# per-replica dispatch/health plus a rollup of the replicas' counters).
_serve_registry = _Registry("serve", "(no live serve engines)")


def register_serve_stats(serve_stats) -> None:
    """Called by serve.ServeEngine / DecodeEngine / ModelMultiplexer /
    ServeRouter on construction (any object with name/report/report_str
    rides along)."""
    _serve_registry.register(serve_stats)


def serve_report() -> dict:
    """{component key: counters} for every live serving component
    (engines, decode engines, multiplexers, routers — see the "kind"
    field per row)."""
    return _serve_registry.report()


def serve_report_str() -> str:
    """Human-readable per-component serving table (latency/occupancy/
    queue per engine, slot occupancy per decode engine, swap-in and
    eviction counters per multiplexer, per-replica rollups per
    router)."""
    return _serve_registry.report_str()


# -- embedding instrumentation (mxnet_tpu.embed) ----------------------------
# Every embedding consumer (a FusedTrainStep with sparse tables, an
# EmbeddingTable, a device_embed kvstore) registers its EmbedStats at
# construction, weakly like the rest; embed_report() shows per-table
# lookup/update counts and the measured dedup ratio on the live id
# distribution — the number bench_embed's embed_dedup_ratio leg holds.
_embed_registry = _Registry("embed", "(no live embedding tables)")


def register_embed_stats(embed_stats) -> None:
    """Called by embed.EmbeddingTable / FusedTrainStep on construction."""
    _embed_registry.register(embed_stats)


def embed_report() -> dict:
    """{consumer key: per-table counters} for every live embedding
    consumer."""
    return _embed_registry.report()


def embed_report_str() -> str:
    """Human-readable per-table lookup/dedup/update table."""
    return _embed_registry.report_str()


# -- MoE instrumentation (mxnet_tpu.moe) ------------------------------------
# Every MoE consumer (a FusedTrainStep whose graph routes through
# _moe_dispatch, a DecodeEngine sampling its per-slot routing state)
# registers its MoeStats at construction, weakly like the rest;
# moe_report() shows per-block expert hit histograms, the max/mean
# imbalance bench gates as moe_expert_imbalance, and the dropped
# fraction the capacity factor buys.
_moe_registry = _Registry("moe", "(no live MoE blocks)")


def register_moe_stats(moe_stats) -> None:
    """Called by FusedTrainStep / DecodeEngine on construction."""
    _moe_registry.register(moe_stats)


def moe_report() -> dict:
    """{consumer key: per-block routing counters} for every live MoE
    consumer."""
    return _moe_registry.report()


def moe_report_str() -> str:
    """Human-readable per-block expert-traffic table."""
    return _moe_registry.report_str()


# -- pass-pipeline instrumentation (mxnet_tpu.passes) ------------------------
# Every PassPipeline registers its PassStats at construction; one
# passes_report() shows, per live pipeline, the per-pass wall time, node
# counts and rewrite counts of its runs plus the fingerprint the
# compile-cache fast key carries.
_passes_registry = _Registry("passes", "(no pass pipelines)")


def register_passes_stats(passes_stats) -> None:
    """Called by passes.PassPipeline on construction."""
    _passes_registry.register(passes_stats)


def passes_report() -> dict:
    """Per-pipeline, per-pass wall seconds, node counts in/out, rewrite
    counts and the pipeline fingerprint (see mxnet_tpu.passes)."""
    return _passes_registry.report()


def passes_report_str() -> str:
    """Human-readable pass-pipeline table (see passes_report)."""
    return _passes_registry.report_str()


# -- autotune instrumentation (mxnet_tpu.autotune) ---------------------------
# One AutotuneStats per tuning run (fit's superstep search, a serve
# engine's pipeline-variant search).  Registered weakly like every other
# registry; the autotune package ALSO keeps the last N strongly, so a
# report after the tuning call returns still shows what was decided.
_autotune_registry = _Registry("autotune", "(no autotune runs)")


def register_autotune_stats(autotune_stats) -> None:
    """Called by autotune.Autotuner on construction."""
    _autotune_registry.register(autotune_stats)


def autotune_report() -> dict:
    """{run key: record} per tuning run: the store key, whether the
    config was measured or loaded, every candidate's measured cost, and
    the winner (see mxnet_tpu.autotune)."""
    return _autotune_registry.report()


def autotune_report_str() -> str:
    """Human-readable candidate/cost table per tuning run."""
    return _autotune_registry.report_str()


def costmodel_report() -> dict:
    """The shared learned cost model's lifecycle snapshot for this
    backend: version, trained or prior-only, training-sample count, and
    the pickle path (see autotune.costmodel)."""
    from .autotune import costmodel
    return costmodel.report()


def costmodel_report_str() -> str:
    """Human-readable cost-model lifecycle line (see costmodel_report)."""
    r = costmodel_report()
    return ("costmodel v%d backend=%s %s samples=%d path=%s"
            % (r["version"], r["backend"],
               "trained" if r["trained"]
               else ("loaded(prior)" if r["loaded"] else "(not loaded)"),
               r["samples"], r["path"] or "-"))


# -- fault-injection / recovery instrumentation (mxnet_tpu.faults) -----------
# The fault plane's process-global FaultStats (kind "plane": injected
# faults by kind and point) and every live Supervisor's SupervisorStats
# (kind "supervisor": restarts, recovery seconds, backoff waits) share
# one registry, so faults_report() is the single "what broke and how we
# recovered" view of a chaos run.
_faults_registry = _Registry("faults", "(no fault plane or supervisor)")


def register_faults_stats(faults_stats) -> None:
    """Called by faults.install (the plane singleton) and
    faults.Supervisor on construction."""
    _faults_registry.register(faults_stats)


def faults_report() -> dict:
    """Per-component fault counters: the plane row (injected faults by
    kind/point, current attempt) and one row per supervisor (attempts,
    restarts, recovery_s, backoff waits).  See mxnet_tpu.faults."""
    return _faults_registry.report()


def faults_report_str() -> str:
    """Human-readable fault-injection + recovery table."""
    return _faults_registry.report_str()


# -- online-loop instrumentation (mxnet_tpu.online) --------------------------
# The continuous-training loop's three legs share one registry: every
# CaptureWriter (kind "capture": offered/kept/shards sealed — the
# counters that make the sampled capture rate verifiable), OnlineTrainer
# (kind "trainer": fine-tune rounds, last candidate step) and
# PromotionGate (kind "gate": decisions, promoted vs quarantined), so
# online_report() is the loop's single health view.
_online_registry = _Registry("online", "(no online loop)")


def register_online_stats(online_stats) -> None:
    """Called by online.CaptureWriter / OnlineTrainer / PromotionGate
    on construction."""
    _online_registry.register(online_stats)


def online_report() -> dict:
    """Per-component online-loop counters: capture sampling, fine-tune
    rounds, gate decisions.  See mxnet_tpu.online."""
    return _online_registry.report()


def online_report_str() -> str:
    """Human-readable online-loop table."""
    return _online_registry.report_str()


# -- compilation instrumentation (mxnet_tpu.compile_cache) -------------------
# Compilation is process-global (one XLA compiler, one jit cache, one disk
# cache), so unlike the per-instance registries above there is exactly one
# CompileStats, owned by the compile_cache subsystem; these are thin views.

def compile_report() -> dict:
    """Per-program trace/lower/compile seconds, cache hits / misses /
    bypasses, steady-state retrace count, plus the disk cache's mode,
    entry count and bytes (totals + per_program + cache keys)."""
    from .compile_cache import get_cache, get_stats
    return get_stats().report(cache=get_cache())


def compile_report_str() -> str:
    """Human-readable compile/cold-start table (see compile_report)."""
    from .compile_cache import get_cache, get_stats
    return get_stats().report_str(cache=get_cache())


# -- the unified view --------------------------------------------------------
def unified_report() -> dict:
    """Every subsystem's report under one roof: ``{"feed": ...,
    "superstep": ..., "multichip": ..., "checkpoint": ..., "serve": ...,
    "compile": ..., "trace": ...}`` — the snapshot the run-metrics
    journal (``MXNET_TRACE_JOURNAL``) writes every N steps."""
    out = {
        "feed": feed_report(),
        "superstep": superstep_report(),
        "multichip": multichip_report(),
        "checkpoint": checkpoint_report(),
        "serve": serve_report(),
        "embed": embed_report(),
        "moe": moe_report(),
        "passes": passes_report(),
        "autotune": autotune_report(),
        "costmodel": costmodel_report(),
        "faults": faults_report(),
        "online": online_report(),
    }
    try:
        out["compile"] = compile_report()
    except Exception:   # no backend yet / cache import failure
        out["compile"] = {}
    from . import trace as _trace
    out["trace"] = _trace.trace_report()
    return out


def unified_report_str() -> str:
    """Every subsystem's human-readable table, sectioned."""
    sections = [
        ("feed", feed_report_str),
        ("superstep", superstep_report_str),
        ("multichip", multichip_report_str),
        ("checkpoint", checkpoint_report_str),
        ("serve", serve_report_str),
        ("embed", embed_report_str),
        ("moe", moe_report_str),
        ("passes", passes_report_str),
        ("autotune", autotune_report_str),
        ("costmodel", costmodel_report_str),
        ("faults", faults_report_str),
        ("online", online_report_str),
        ("compile", compile_report_str),
    ]
    parts = []
    for label, fn in sections:
        try:
            body = fn()
        except Exception as e:
            body = "(unavailable: %s)" % e
        parts.append("== %s %s\n%s" % (label, "=" * max(1, 68 - len(label)),
                                       body))
    from . import trace as _trace
    tr = _trace.trace_report()
    parts.append("== trace %s\nenabled=%s events=%d dropped=%d "
                 "spill_dirs=%d journal=%s"
                 % ("=" * 62, tr["enabled"], tr["events"], tr["dropped"],
                    len(tr["spill_dirs"]), tr["journal"] or "-"))
    return "\n\n".join(parts)


@contextlib.contextmanager
def scope(name: str):
    """Named region visible in BOTH trace timelines: the span runtime's
    Chrome/Perfetto dump (mxnet_tpu.trace) and, while
    profiler_set_state("run") holds an xprof trace open, jax's
    TraceAnnotation.  Also usable around host-side work like data
    loading.  API unchanged from the seed."""
    import jax
    from . import trace as _trace
    with jax.profiler.TraceAnnotation(name):
        with _trace.span(name, cat="scope"):
            yield
