"""ServeRouter: a front door spreading load across replica engines.

One engine is one dispatcher on one device; the front door for real
traffic is N **replicas** of the same model behind a router that

* **dispatches by queue depth**: each request goes to the live replica
  with the least work in flight (outstanding + queued) — the cheap
  approximation of join-the-shortest-queue that keeps p99 flat when one
  replica hiccups;
* **routes around overload**: a replica whose bounded queue rejects is
  skipped and the next-least-loaded one tried; only when EVERY live
  replica rejects does the caller see ``ServeOverloadError``;
* **tracks health**: replica failures (engine errors, not client-side
  deadline/validation errors) count per replica; at
  ``MXNET_SERVE_ROUTER_UNHEALTHY`` consecutive failures the replica is
  taken out of rotation (state ``down``) until an operator restarts it.
  A failed request is retried once on another replica before the
  client sees the error;
* **restarts without dropping**: ``restart(i)`` marks the replica
  *draining* — the router stops dispatching to it, waits out its
  in-flight requests, then hot-swaps weights (``reload=``) or rebuilds
  the engine through its factory (warm via the compile cache) and puts
  it back in rotation.  Traffic rides the other replicas the whole
  time: zero dropped requests.  ``rolling_restart()`` does this to
  every replica in turn — the zero-downtime deploy primitive.

::

    router = mx.serve.ServeRouter(
        lambda i: ServeEngine.from_checkpoint_dir(store, net, shapes,
                                                  name="rep%d" % i),
        replicas=3)
    fut = router.submit(x)
    router.rolling_restart()            # picks up the newest checkpoint
    print(mx.profiler.serve_report_str())
    router.close()

The router is in-process (replica engines own their device context and
threads); across hosts the same dispatch/drain logic fronts RPC stubs —
the replica surface is just ``submit / pending_requests / outstanding /
close``.
"""
from __future__ import annotations

import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional

from .. import trace as _trace
from ..base import get_env, make_condition
from .batcher import _set_exception, _set_result
from .errors import (ServeClosedError, ServeDeadlineError, ServeError,
                     ServeOverloadError, ServeRequestError,
                     ServeUnavailableError)

__all__ = ["ServeRouter", "RouterStats"]

LIVE, DRAINING, DOWN = "live", "draining", "down"

# drain poll bound: wakes also arrive via the cv notify in _on_done, so
# this only bounds shutdown/timeout latency
_IDLE_WAIT_S = 0.05


class RouterStats:
    """Router counters + per-replica rollup: one row in
    ``mx.profiler.serve_report()`` (kind "router")."""

    def __init__(self, name: str, router: "ServeRouter"):
        self.name = name
        import weakref
        self._router = weakref.ref(router)

    def report(self) -> Dict:
        r = self._router()
        if r is None:
            return {"kind": "router", "closed": True}
        return r._report()

    def report_str(self) -> str:
        r = self._router()
        if r is None:
            return "serve router (closed)"
        return r._report_str()


class _Replica:
    __slots__ = ("index", "engine", "state", "outstanding", "dispatched",
                 "failures", "restarts")

    def __init__(self, index: int, engine):
        self.index = index
        self.engine = engine
        self.state = LIVE
        self.outstanding = 0        # dispatched via the router, unresolved
        self.dispatched = 0
        self.failures = 0           # consecutive engine-side failures
        self.restarts = 0


class ServeRouter:
    """Queue-depth/health-aware dispatch over replica engines (see
    module docstring).

    Parameters
    ----------
    factory : callable(index) -> engine
        Builds replica ``i``; also used by ``restart`` to rebuild.  Any
        engine with ``submit / pending_requests / outstanding / close``
        qualifies (ServeEngine, DecodeEngine).
    replicas : int
        How many replicas to build at construction.
    unhealthy_after : int
        Consecutive engine-side failures that take a replica out of
        rotation (``MXNET_SERVE_ROUTER_UNHEALTHY``, default 3; 0
        disables).
    retries : int
        How many times a failed request is re-dispatched to another
        replica before the client sees the failure (default 1).
    """

    def __init__(self, factory: Callable[[int], object], replicas: int = 2,
                 *, unhealthy_after: Optional[int] = None,
                 retries: int = 1, name: str = "router"):
        if replicas < 1:
            raise ServeError("replicas must be >= 1, got %d" % replicas)
        if unhealthy_after is None:
            unhealthy_after = get_env("MXNET_SERVE_ROUTER_UNHEALTHY", 3, int)
        self.unhealthy_after = max(0, int(unhealthy_after))
        self.retries = max(0, int(retries))
        self.name = name
        self._factory = factory
        self._cv = make_condition("serve.router")
        self._closed = False
        self._rejected = 0
        self._retried = 0
        self._drains = 0
        self._downs = 0
        self._replicas: List[_Replica] = []
        try:
            for i in range(int(replicas)):
                self._replicas.append(_Replica(i, factory(i)))
        except BaseException:
            for rep in self._replicas:
                try:
                    rep.engine.close(drain=False)
                except Exception:
                    pass
            raise
        self.stats = RouterStats(name, self)
        from .. import profiler
        profiler.register_serve_stats(self.stats)

    # -- dispatch ----------------------------------------------------------
    def _load(self, rep: _Replica) -> int:
        try:
            return rep.outstanding + rep.engine.pending_requests()
        except Exception:
            return 1 << 30

    def _pick_locked(self, exclude) -> Optional[_Replica]:
        """Least-loaded live replica not in ``exclude``."""
        live = [r for r in self._replicas
                if r.state == LIVE and r.index not in exclude]
        if not live:
            return None
        return min(live, key=self._load)

    def submit(self, data, deadline_ms: Optional[float] = None,
               **kwargs) -> Future:
        """Dispatch one request; returns a router-owned Future.  Raises
        ServeUnavailableError when no replica is live,
        ServeOverloadError when every live replica's queue rejects;
        replica-side failures are retried on another replica before
        they reach this future."""
        rfut: Future = Future()
        self._dispatch(rfut, data, deadline_ms, kwargs, tried=set(),
                       retries_left=self.retries)
        return rfut

    def predict(self, data, timeout: Optional[float] = None, **kwargs):
        """Blocking one-shot: submit + result."""
        return self.submit(data, **kwargs).result(timeout=timeout)

    def _dispatch(self, rfut: Future, data, deadline_ms, kwargs,
                  tried, retries_left: int) -> None:
        """Place the request on the best available replica; on overload
        walk the remaining live replicas.  Raises into the CALLER when
        nothing accepted and ``rfut`` was never dispatched; replica
        failures after acceptance retry via the done callback."""
        overloads = 0
        last_exc = None
        while True:
            with self._cv:
                if self._closed:
                    raise ServeClosedError(
                        "serve router %r is closed" % self.name)
                rep = self._pick_locked(tried)
                if rep is None:
                    self._rejected += 1
                    if overloads:
                        raise ServeOverloadError(
                            "every live replica's queue is full "
                            "(%d rejected this dispatch): shed load or "
                            "add replicas" % overloads)
                    if last_exc is not None:
                        raise last_exc
                    raise ServeUnavailableError(
                        "no live replica (states: %s) — all draining/"
                        "down; restart or add replicas"
                        % [r.state for r in self._replicas])
                rep.outstanding += 1    # reserve before releasing the lock
            try:
                efut = rep.engine.submit(data, deadline_ms=deadline_ms,
                                         **kwargs)
            except ServeOverloadError:
                with self._cv:
                    rep.outstanding -= 1
                    self._cv.notify_all()
                tried.add(rep.index)
                overloads += 1
                continue
            except ServeRequestError:
                # the request itself is malformed: no replica will take
                # it — the caller's problem, not the replica's
                with self._cv:
                    rep.outstanding -= 1
                    self._cv.notify_all()
                raise
            except ServeError as e:
                # replica broken at submit time (closed underneath,
                # wedged): health-count it and walk on
                self._note_failure(rep)
                with self._cv:
                    rep.outstanding -= 1
                    self._cv.notify_all()
                tried.add(rep.index)
                last_exc = e
                continue
            except BaseException:
                with self._cv:
                    rep.outstanding -= 1
                    self._cv.notify_all()
                raise
            with self._cv:
                rep.dispatched += 1
            efut.add_done_callback(
                lambda f, rep=rep: self._on_done(
                    f, rep, rfut, data, deadline_ms, kwargs, tried,
                    retries_left))
            return

    def _note_failure_locked(self, rep: _Replica) -> None:
        """Health policy, ONE implementation (cv held): submit-time and
        future-time failures must agree on when a replica goes down."""
        rep.failures += 1
        if (self.unhealthy_after and rep.state == LIVE
                and rep.failures >= self.unhealthy_after):
            rep.state = DOWN
            self._downs += 1
            _trace.instant("serve:router_down", cat="serve",
                           replica=rep.index)

    def _note_failure(self, rep: _Replica) -> None:
        with self._cv:
            self._note_failure_locked(rep)

    def _retryable(self, exc: BaseException) -> bool:
        """Engine-side failures worth another replica: a closed or
        broken replica.  Client-side outcomes (deadline, malformed
        request) and overload (handled at dispatch) are final."""
        if isinstance(exc, (ServeDeadlineError, ServeRequestError,
                            ServeOverloadError)):
            return False
        return isinstance(exc, (ServeClosedError, ServeError))

    def _on_done(self, efut: Future, rep: _Replica, rfut: Future, data,
                 deadline_ms, kwargs, tried, retries_left: int) -> None:
        exc = efut.exception() if not efut.cancelled() else None
        engine_fail = exc is not None and self._retryable(exc)
        with self._cv:
            rep.outstanding -= 1
            if engine_fail:
                self._note_failure_locked(rep)
            elif exc is None and not efut.cancelled():
                rep.failures = 0
            self._cv.notify_all()       # drain waiters watch outstanding
        if efut.cancelled():
            rfut.cancel()
            return
        if exc is None:
            _set_result(rfut, efut.result())
            return
        if engine_fail and retries_left > 0 and not self._closed:
            with self._cv:
                self._retried += 1
            try:
                # fresh exclusion set: only the replica that just failed
                # is off-limits — an earlier transient overload on
                # another replica must not shrink the retry's options
                self._dispatch(rfut, data, deadline_ms, kwargs,
                               {rep.index}, retries_left - 1)
                return
            except Exception as redispatch_exc:
                exc = redispatch_exc
        _set_exception(rfut, exc)

    # -- draining restart --------------------------------------------------
    def drain(self, index: int, timeout: Optional[float] = None) -> None:
        """Take replica ``index`` out of rotation and wait until its
        in-flight work resolves (new traffic rides the other
        replicas).  On timeout the replica STAYS out of rotation
        (state ``draining``) — a drain that cannot finish means the
        replica is wedged, and handing it fresh traffic would hang
        clients; retry the restart or rebuild it."""
        rep = self._rep(index)
        with self._cv:
            if rep.state != DRAINING:   # idempotent: restart() after a
                rep.state = DRAINING    # manual drain() just waits
                self._drains += 1
                _trace.instant("serve:router_drain", cat="serve",
                               replica=index)
        deadline = (time.perf_counter() + timeout) if timeout else None
        with self._cv:
            while rep.outstanding > 0 or rep.engine.pending_requests() > 0:
                remaining = _IDLE_WAIT_S if deadline is None \
                    else min(_IDLE_WAIT_S, deadline - time.perf_counter())
                if remaining <= 0:
                    raise ServeError(
                        "replica %d did not drain within %.1fs "
                        "(%d outstanding); it stays out of rotation — "
                        "retry restart() or rebuild it"
                        % (index, timeout, rep.outstanding))
                self._cv.wait(remaining)

    def restart(self, index: int, reload: Optional[Dict] = None,
                factory: Optional[Callable] = None,
                timeout: Optional[float] = None) -> None:
        """Draining restart of one replica, zero dropped requests: drain
        it (see :meth:`drain`), then either hot-swap weights into the
        existing engine (``reload=`` params dict) or close it and
        rebuild via ``factory`` (default: the constructor's, so a
        checkpoint-dir factory redeploys the newest step), then return
        it to rotation with a clean health record."""
        rep = self._rep(index)
        self.drain(index, timeout=timeout)
        try:
            with _trace.span("serve:router_restart", cat="serve",
                             replica=index):
                if reload is not None:
                    rep.engine.reload(reload)
                else:
                    old = rep.engine
                    build = factory if factory is not None else self._factory
                    # build BEFORE closing the old engine: a failed
                    # build must leave the old replica restorable
                    fresh = build(index)
                    rep.engine = fresh
                    old.close(drain=True)
        finally:
            with self._cv:
                rep.failures = 0
                rep.restarts += 1
                rep.state = LIVE
                self._cv.notify_all()

    def rolling_restart(self, reload: Optional[Dict] = None,
                        factory: Optional[Callable] = None,
                        timeout: Optional[float] = None) -> None:
        """Restart every replica in turn — the zero-downtime deploy."""
        for rep in list(self._replicas):
            self.restart(rep.index, reload=reload, factory=factory,
                         timeout=timeout)

    # -- introspection -----------------------------------------------------
    def _rep(self, index: int) -> _Replica:
        if not 0 <= index < len(self._replicas):
            raise ServeError(
                "replica index %d out of range [0, %d)"
                % (index, len(self._replicas)))
        return self._replicas[index]

    @property
    def num_replicas(self) -> int:
        return len(self._replicas)

    def replica_states(self) -> List[str]:
        with self._cv:
            return [r.state for r in self._replicas]

    def replica(self, index: int):
        """The replica's engine (maintenance access; dispatch decisions
        belong to the router)."""
        return self._rep(index).engine

    def _report(self) -> Dict:
        with self._cv:
            reps = list(self._replicas)
            out = {
                "kind": "router",
                "replicas": len(reps),
                "rejected": self._rejected,
                "retried": self._retried,
                "drains": self._drains,
                "downs": self._downs,
            }
        per = {}
        agg_submitted = agg_completed = agg_failed = 0
        for r in reps:
            row = {"state": r.state, "dispatched": r.dispatched,
                   "outstanding": r.outstanding, "failures": r.failures,
                   "restarts": r.restarts}
            st = getattr(r.engine, "stats", None)
            if st is not None:
                erep = st.report()
                row["engine"] = erep
                agg_submitted += erep.get("submitted", 0)
                agg_completed += erep.get("completed", 0)
                agg_failed += erep.get("failed", 0)
            per[r.index] = row
        out["per_replica"] = per
        out["submitted"] = agg_submitted
        out["completed"] = agg_completed
        out["failed"] = agg_failed
        return out

    def _report_str(self) -> str:
        r = self._report()
        lines = ["serve router %r" % self.name,
                 "  replicas: %d, %d rejected, %d retried, %d drains, "
                 "%d downs" % (r["replicas"], r["rejected"], r["retried"],
                               r["drains"], r["downs"]),
                 "  rollup: %d submitted / %d completed / %d failed"
                 % (r["submitted"], r["completed"], r["failed"])]
        for i, row in sorted(r["per_replica"].items()):
            erep = row.get("engine") or {}
            lines.append(
                "  replica %d [%s]: %d dispatched, %d outstanding, "
                "p99 %.2f ms, %d restarts"
                % (i, row["state"], row["dispatched"], row["outstanding"],
                   erep.get("latency_p99_ms", 0.0), row["restarts"]))
        return "\n".join(lines)

    # -- lifecycle ---------------------------------------------------------
    def close(self, drain: bool = True) -> None:
        """Close every replica.  Idempotent; concurrent closers
        serialize on the replicas' own close locks."""
        with self._cv:
            if self._closed:
                reps = []
            else:
                self._closed = True
                reps = list(self._replicas)
            self._cv.notify_all()
        for rep in reps:
            rep.engine.close(drain=drain)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
