"""Dead-peer detection test (launched by tools/launch.py -n 2 -s 1).

Worker rank 1 "dies" (exits without the stop handshake) after a few
pushes.  The scheduler must detect the dropped connection and broadcast an
abort so worker rank 0 — blocked in a barrier that can now never complete —
fails fast with a clean message instead of hanging forever (the reference
job hung on node death and needed tools/kill-mxnet.py by hand; SURVEY
§5.3).  Rank 0 prints ABORT-DETECTED on the expected RuntimeError.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# deliberately leave MXNET_PS_RECV_TIMEOUT at its 600s default: only the
# abort broadcast can make this test finish inside its runner timeout, so
# a regression in abort delivery fails the test instead of hiding behind
# the RPC-timeout fallback

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]

import numpy as np
import mxnet_tpu as mx


def main():
    kv = mx.create_kvstore("dist_async")
    rank = kv.rank
    shape = (4, 5)
    kv.init(7, mx.nd.ones(shape))
    kv.push(7, mx.nd.ones(shape))
    out = mx.nd.zeros(shape)
    kv.pull(7, out=out)

    mode = sys.argv[1] if len(sys.argv) > 1 else "exit"
    if rank == 1:
        # simulate a crash: no kv close, no scheduler stop handshake.
        # The delay parks rank 0 in the barrier first, so the abort
        # broadcast (not a socket race) is what surfaces there.
        import time
        time.sleep(2.0)
        sys.stdout.flush()
        if mode == "raise":
            # unhandled exception: atexit still runs, but the excepthook
            # marks the client fatal so the stop handshake is skipped and
            # the scheduler sees a death, not a clean exit
            raise ValueError("simulated worker crash")
        os._exit(0)

    try:
        kv.barrier()          # can never complete: the peer dies mid-job
    except RuntimeError as e:
        msg = str(e)
        assert "abort" in msg.lower() or "connection lost" in msg, msg
        print("ABORT-DETECTED rank %d: %s" % (rank, msg))
        sys.stdout.flush()
        sys.exit(3)           # job must fail, but with this clean message
    print("UNEXPECTED: barrier completed with a dead peer")
    sys.exit(4)


if __name__ == "__main__":
    main()
