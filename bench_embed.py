"""Sharded-embedding bench legs (ISSUE 12): the sparse, memory-bound,
traffic-shaped workload the CNN/LSTM legs never exercise.

Four questions, measured at a realistic duplication rate (4096 ids per
batch drawn from a ~410-id hot set of a 200k-row table — ~10% unique,
the rec-traffic shape):

1. **What does the deduped sparse update buy over the naive path?**
   The naive baseline is what dense training actually does with an
   embedding table (MXNET_EMBED_SPARSE=0, the pre-ISSUE-12 fused step):
   the take-VJP scatter-adds every id occurrence into a full
   ``(vocab, dim)`` dense gradient and the optimizer sweeps the WHOLE
   table.  The sparse path dedups ids, segment-sums grads onto the
   unique rows and updates only those (lazy rows).  Both tables donated
   — the real training layout.

     embed_naive_update_ms    per-occurrence scatter-add + full-table
                              momentum update (lower is better)
     embed_sparse_update_ms   deduped update (lower is better)
     embed_update_speedup     naive / sparse (acceptance >= 2x)
     embed_lookups_per_sec    deduped lookup throughput (ids/s)

2. **Does the win survive the full fused train step?**  A rec model
   (ids -> Embedding -> dense tower) stepped through Module's fused
   path, sparse vs dense, interleaved windows:

     embed_sparse_step_ms / embed_dense_step_ms / embed_step_speedup

3. **How much duplication does the live id stream actually have?**

     embed_dedup_ratio        ids / unique ids per batch, read back
                              from mx.profiler.embed_report()

4. **What does the rec-serve path sustain end to end?**  ids ->
   embedding -> dense tower through a ServeEngine(embed_dedup=True)
   under closed-loop multithreaded load, outputs parity-checked
   against serial batch-1 predict:

     rec_serve_qps
"""
import os
import time

import numpy as np

VOCAB = 200_000
DIM = 64
BATCH_IDS = 4096          # ids per update batch (the acceptance point)
HOT_IDS = 410             # ~10% unique at 4096 draws
UNIQUE_CAP = 512
UPDATE_ITERS = 30

STEP_VOCAB = 200_000     # full-step leg: giant table, same id shape
STEP_DIM = 32
STEP_B, STEP_L = 512, 8   # 4096 ids per step
STEP_WINDOWS = 3
STEP_ITERS = 8

SERVE_VOCAB = 10_000
SERVE_DIM = 32
SERVE_L = 16
SERVE_THREADS = 8
SERVE_REQS = 25


def _hot_ids(rng, n, hot, vocab):
    pool = rng.choice(vocab, hot, replace=False)
    return pool[rng.randint(0, hot, n)].astype(np.int32)


def update_leg(feed=lambda *_: None):
    """Micro leg: deduped sparse update vs the naive per-occurrence
    scatter-add (dense take-VJP) update, donated tables, min-of-trials."""
    from functools import partial

    import jax
    import jax.numpy as jnp

    from mxnet_tpu.embed.sparse import dedup_ids, sparse_apply_rows

    lr, mu = 0.1, 0.9
    rng = np.random.RandomState(0)
    ids = jnp.asarray(_hot_ids(rng, BATCH_IDS, HOT_IDS, VOCAB))
    g = jnp.asarray(rng.randn(BATCH_IDS, DIM).astype(np.float32))

    def opt_update(w, grad, mom, _lr, wd, t):
        m = mu * mom - _lr * grad
        return w + m, m

    @partial(jax.jit, donate_argnums=(0, 1))
    def naive(table, mom, ids, g):
        gd = jnp.zeros_like(table).at[ids].add(g, mode="drop")
        m = mu * mom - lr * gd
        return table + m, m

    @partial(jax.jit, donate_argnums=(0, 1))
    def sparse(table, mom, ids, g):
        uniq, inv = dedup_ids(ids, UNIQUE_CAP, sentinel=VOCAB)
        grows = jax.ops.segment_sum(g, inv, num_segments=UNIQUE_CAP)
        return sparse_apply_rows(table, mom, uniq, grows, opt_update,
                                 lr, 0.0, 1)

    @jax.jit
    def lookup(table, ids):
        uniq, inv = dedup_ids(ids, UNIQUE_CAP, sentinel=VOCAB)
        rows = jnp.take(table, uniq, axis=0, mode="clip")
        return jnp.take(rows, inv, axis=0)

    # parity first: one step of each from identical state must land on
    # the same touched rows (plain scatter-add is associative; momentum
    # semantics differ only on UNTOUCHED rows, zero here at t=1)
    t0 = jnp.zeros((VOCAB, DIM), jnp.float32)
    m0 = jnp.zeros((VOCAB, DIM), jnp.float32)
    na, _ = naive(jnp.copy(t0), jnp.copy(m0), ids, g)
    sp, _ = sparse(jnp.copy(t0), jnp.copy(m0), ids, g)
    touched = np.unique(np.asarray(ids))
    np.testing.assert_allclose(np.asarray(na)[touched],
                               np.asarray(sp)[touched],
                               rtol=1e-4, atol=1e-5)

    def bench(f):
        table = jnp.zeros((VOCAB, DIM), jnp.float32)
        mom = jnp.zeros((VOCAB, DIM), jnp.float32)
        table, mom = f(table, mom, ids, g)      # warm (compile)
        table.block_until_ready()
        ts = []
        for _ in range(UPDATE_ITERS):
            t0 = time.perf_counter()
            table, mom = f(table, mom, ids, g)
            table.block_until_ready()
            ts.append(time.perf_counter() - t0)
        return min(ts) * 1e3

    feed("embed-naive")
    t_naive = bench(naive)
    feed("embed-sparse")
    t_sparse = bench(sparse)

    table = jnp.zeros((VOCAB, DIM), jnp.float32)
    lookup(table, ids).block_until_ready()
    ts = []
    for _ in range(UPDATE_ITERS):
        t0 = time.perf_counter()
        lookup(table, ids).block_until_ready()
        ts.append(time.perf_counter() - t0)
    lk = min(ts)

    return {
        "embed_naive_update_ms": round(t_naive, 3),
        "embed_sparse_update_ms": round(t_sparse, 3),
        "embed_update_speedup": round(t_naive / t_sparse, 2),
        "embed_lookups_per_sec": round(BATCH_IDS / lk),
    }


def _rec_symbol(vocab, dim, hidden, classes, name="embed",
                unique_cap=None):
    import mxnet_tpu as mx
    if unique_cap:
        # the traced dedup buffer size: the sparse step unique-sorts
        # into this many rows instead of the worst-case batch size
        weight = mx.sym.Variable(
            "%s_weight" % name,
            attr={"__embed_unique__": str(unique_cap)})
    else:
        weight = mx.sym.Variable("%s_weight" % name)
    net = mx.sym.Embedding(mx.sym.Variable("ids"), weight=weight,
                           input_dim=vocab, output_dim=dim, name=name)
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=hidden, name="rfc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="rfc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def step_leg(feed=lambda *_: None):
    """Full fused train step, sparse vs dense embedding update,
    interleaved windows (host drift must not fake a speedup)."""
    import mxnet_tpu as mx

    rng = np.random.RandomState(1)
    X = _hot_ids(rng, 4 * STEP_B * STEP_L, HOT_IDS,
                 STEP_VOCAB).reshape(4 * STEP_B, STEP_L).astype(np.float32)
    y = (X.sum(axis=1) % 2).astype(np.float32)

    def make_mod(sparse):
        os.environ["MXNET_EMBED_SPARSE"] = "1" if sparse else "0"
        try:
            mx.random.seed(7)
            it = mx.io.NDArrayIter(X, y, batch_size=STEP_B,
                                   data_name="ids")
            mod = mx.mod.Module(
                _rec_symbol(STEP_VOCAB, STEP_DIM, 64, 2,
                            unique_cap=UNIQUE_CAP),
                data_names=("ids",), context=mx.cpu(0))
            mod.bind(it.provide_data, it.provide_label)
            mod.init_params(mx.init.Xavier())
            mod.init_optimizer(optimizer_params={"learning_rate": 0.1,
                                                 "momentum": 0.9})
            assert mod._fused is not None
            assert bool(mod._fused.sparse_embeds) == sparse
            return mod, it
        finally:
            os.environ.pop("MXNET_EMBED_SPARSE", None)

    mods = {s: make_mod(s) for s in (False, True)}
    batches = {}
    for s, (mod, it) in mods.items():
        it.reset()
        batches[s] = next(iter(it))

    def window(mod, batch):
        # steady-state fused steps; block on the live state each window
        import jax
        for _ in range(2):                       # warm the queue
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        leaf = next(iter(mod._fused_state["params"].values()))
        jax.block_until_ready(leaf)
        t0 = time.perf_counter()
        for _ in range(STEP_ITERS):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        leaf = next(iter(mod._fused_state["params"].values()))
        jax.block_until_ready(leaf)
        return (time.perf_counter() - t0) / STEP_ITERS * 1e3

    dense_ms, sparse_ms = [], []
    for w in range(STEP_WINDOWS):
        feed("embed-step-dense")
        dense_ms.append(window(mods[False][0], batches[False]))
        feed("embed-step-sparse")
        sparse_ms.append(window(mods[True][0], batches[True]))
    td, ts = min(dense_ms), min(sparse_ms)
    ratio = mods[True][0]._fused.embed_stats.dedup_ratio()
    return {
        "embed_dense_step_ms": round(td, 2),
        "embed_sparse_step_ms": round(ts, 2),
        "embed_step_speedup": round(td / ts, 2),
        "embed_dedup_ratio": round(ratio, 2),
    }


def rec_serve_leg(feed=lambda *_: None):
    """ids -> embedding -> dense tower through ServeEngine under
    closed-loop multithreaded load; rec_serve_qps counts only if every
    answer matches serial batch-1 predict."""
    import threading

    from mxnet_tpu.predictor import Predictor
    from mxnet_tpu.serve import ServeEngine

    rng = np.random.RandomState(2)
    net = _rec_symbol(SERVE_VOCAB, SERVE_DIM, 64, 8)
    params = {
        "embed_weight": (rng.randn(SERVE_VOCAB, SERVE_DIM) *
                         0.1).astype(np.float32),
        "rfc1_weight": (rng.randn(64, SERVE_L * SERVE_DIM) *
                        0.05).astype(np.float32),
        "rfc1_bias": np.zeros(64, np.float32),
        "rfc2_weight": (rng.randn(8, 64) * 0.1).astype(np.float32),
        "rfc2_bias": np.zeros(8, np.float32),
    }
    shapes = {"ids": (SERVE_THREADS, SERVE_L),
              "softmax_label": (SERVE_THREADS,)}
    tdict = {"ids": np.int32}
    n = SERVE_THREADS * SERVE_REQS
    reqs = _hot_ids(rng, n * SERVE_L, HOT_IDS,
                    SERVE_VOCAB).reshape(n, SERVE_L)

    feed("rec-serve-warmup")
    eng = ServeEngine(net, dict(params), shapes, type_dict=dict(tdict),
                      embed_dedup=True, max_delay_ms=2.0,
                      deadline_ms=30000.0, name="rec_serve")
    pred = Predictor(net.tojson(), dict(params),
                     {"ids": (1, SERVE_L), "softmax_label": (1,)},
                     type_dict=dict(tdict))
    serial = []
    for i in range(n):
        pred.set_input("ids", reqs[i:i + 1])
        pred.forward()
        serial.append(np.array(pred.get_output(0)[0]))

    results = [None] * n
    errors = []

    def client(t):
        try:
            for j in range(SERVE_REQS):
                i = t * SERVE_REQS + j
                results[i] = eng.predict(reqs[i], timeout=60)
        except Exception as e:                    # pragma: no cover
            errors.append(e)

    feed("rec-serve-load")
    workers = [threading.Thread(target=client, args=(t,))
               for t in range(SERVE_THREADS)]
    t0 = time.perf_counter()
    for w in workers:
        w.start()
    for w in workers:
        w.join()
    wall = time.perf_counter() - t0
    eng.close()
    if errors:
        raise errors[0]
    for i in range(n):
        if not np.allclose(results[i], serial[i], atol=1e-4):
            raise AssertionError(
                "rec-serve output %d diverges from serial predict" % i)
    return {"rec_serve_qps": round(n / wall, 1)}


def run(feed=lambda *_: None):
    """Returns the embed bench metrics; each sub-leg degrades
    independently (a failed optional leg must not sink the others)."""
    import sys
    out = {}
    for leg in (update_leg, step_leg, rec_serve_leg):
        try:
            out.update(leg(feed=feed))
        except Exception as e:                    # pragma: no cover
            sys.stderr.write("bench_embed: %s failed (%s)\n"
                             % (leg.__name__, e))
    return out


if __name__ == "__main__":
    import json
    print(json.dumps(run()))
