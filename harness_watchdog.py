"""Heartbeat watchdog shared by the driver-facing harness scripts
(bench.py, __graft_entry__.py).

The hang worth guarding sits inside backend init or a compile that never
returns to the interpreter: a SIGALRM handler never runs there (measured),
but the blocked call releases the GIL, so a daemon thread still can emit a
parseable failure line and hard-exit instead of eating the driver's budget.

The deadline is a HEARTBEAT — each phase/step of the harness feeds it — so
slow-but-progressing runs (cold compiles, OOM retries) never trip it; only
sustained zero progress does.
"""
import os
import threading
import time


class HeartbeatWatchdog:
    """Daemon-thread deadline that `on_timeout(phase)` + os._exit()s when
    starved.  feed() extends the deadline and optionally names the phase."""

    def __init__(self, on_timeout, exit_code, budget_s=540, poll_s=5):
        self._on_timeout = on_timeout
        self._exit_code = exit_code
        self._budget_s = budget_s
        self._poll_s = poll_s
        self._deadline = None
        self._done = False
        self._gen = 0     # start() bumps it; stale loop threads retire
        self.phase = "init"

    def feed(self, phase=None, seconds=None):
        if phase is not None:
            self.phase = phase
        self._deadline = time.monotonic() + (
            self._budget_s if seconds is None else seconds)

    def start(self):
        self.feed()     # never start against a stale (expired) deadline
        self._done = False          # support repeat in-process runs
        self._gen += 1
        threading.Thread(target=self._loop, args=(self._gen,),
                         daemon=True).start()

    def stop(self):
        self._done = True

    def _loop(self, gen):
        while not self._done and gen == self._gen:
            time.sleep(self._poll_s)
            if self._done or gen != self._gen:
                return
            if time.monotonic() > self._deadline:
                try:
                    self._on_timeout(self.phase)
                finally:
                    os._exit(self._exit_code)
