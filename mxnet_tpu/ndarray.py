"""NDArray: imperative, asynchronously-dispatched tensor.

Reference: include/mxnet/ndarray.h (670 LoC), src/ndarray/ (1434 LoC),
python/mxnet/ndarray.py (1229 LoC).

TPU-native design, not a port.  The reference NDArray is a ref-counted
Chunk{Storage::Handle, Engine::Var}; every mutating op is pushed to the
dependency engine and the python thread never blocks (SURVEY §3.6).  JAX
already *is* that model: dispatch is async, results are futures, and
``asnumpy()``/``wait_to_read()`` are the sync points.  What JAX does not have
is mutability and views — so:

* a "chunk" here is the ``_data`` jax.Array of an **owner** NDArray; mutation
  swaps the buffer (functional update under the hood, ordering guaranteed by
  data dependence — the Var semantics collapse into SSA);
* ``Slice/At/Reshape`` views (zero-copy in the reference, ndarray.h:228-262)
  are write-through views: they record (base, spec), read lazily, and write
  back into the base chunk with ``.at[...].set`` — aliasing semantics
  preserved, XLA fuses the scatter.
"""
from __future__ import annotations

import io as _io
import pickle
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError, numeric_types
from .context import Context, cpu, current_context
from . import engine as _engine

__all__ = [
    "NDArray", "array", "empty", "zeros", "ones", "full", "arange",
    "load", "save", "concatenate", "concat", "onehot_encode", "clip", "dot",
    "batch_dot", "sum", "max", "min", "norm", "argmax_channel",
    "choose_element_0index", "waitall", "imdecode", "transpose",
]

# ---------------------------------------------------------------------------
# registry of ndarray functions (reference NDArrayFunctionReg, ndarray.h:483)
# populated here and extended by ops/ (SimpleOp dual registration).
_NDARRAY_FUNCS: Dict[str, Any] = {}


def register_ndarray_fn(name, fn):
    """MXNET_REGISTER_NDARRAY_FUN analogue; also exposes fn on this module."""
    _NDARRAY_FUNCS[name] = fn
    import sys
    mod = sys.modules[__name__]
    public = name.lstrip("_")
    if not hasattr(mod, public):
        setattr(mod, public, fn)
    setattr(mod, name, fn)
    return fn


def list_functions():
    """MXListFunctions analogue."""
    return sorted(_NDARRAY_FUNCS)


def _dev_put(arr, ctx: Optional[Context]):
    if ctx is None:
        return arr
    return jax.device_put(arr, ctx.jax_device())


def _ctx_of(jarr) -> Context:
    try:
        dev = list(jarr.devices())[0]
    except Exception:
        return current_context()
    if dev.platform == "cpu":
        return Context("cpu", dev.id)
    return Context("tpu", dev.id)


def _as_jax(value, dtype=None):
    if isinstance(value, NDArray):
        return value._get()
    if isinstance(value, (np.ndarray, jnp.ndarray, jax.Array)):
        return jnp.asarray(value, dtype=dtype)
    return jnp.asarray(value, dtype=dtype)


class NDArray:
    """Multi-dimensional array with async dispatch and mutable semantics."""

    __slots__ = ("_data", "_base", "_spec", "writable")

    def __init__(self, data=None, base: "NDArray" = None, spec=None, writable=True):
        self._data = data          # jax.Array when owner, None when view
        self._base = base          # owner NDArray when this is a view
        self._spec = spec          # ("slice", start, stop) | ("at", i) | ("reshape", shape)
        self.writable = writable

    # -- chunk access -------------------------------------------------------
    def _root(self) -> "NDArray":
        n = self
        while n._base is not None:
            n = n._base
        return n

    def _get(self):
        """Current jax.Array value (views computed from base)."""
        if self._base is None:
            return self._data
        parent = self._base._get()
        kind = self._spec[0]
        if kind == "slice":
            return parent[self._spec[1]:self._spec[2]]
        if kind == "at":
            return parent[self._spec[1]]
        if kind == "reshape":
            return parent.reshape(self._spec[1])
        raise MXNetError("unknown view spec %r" % (self._spec,))

    def _set(self, new):
        """Write a full new value into this array (write-through for views)."""
        if not self.writable:
            raise MXNetError("trying to write to a read-only NDArray")
        if self._base is None:
            if self._data is not None and tuple(new.shape) != tuple(self._data.shape):
                raise MXNetError(
                    "shape mismatch: cannot assign %s to NDArray of shape %s"
                    % (tuple(new.shape), tuple(self._data.shape)))
            if self._data is not None and new.dtype != self._data.dtype:
                new = new.astype(self._data.dtype)
            if self._data is not None:
                # a write mutates the chunk in place in the reference —
                # keep the buffer at its original PLACEMENT: the single
                # device it lived on, or (mesh-placed arrays, see
                # Executor.set_mesh) its multi-device sharding — a write
                # must not silently collapse a tp-sharded weight onto
                # one chip
                try:
                    old_devs = self._data.devices()
                    if len(old_devs) > 1:
                        old_sh = self._data.sharding
                        if getattr(new, "sharding", None) != old_sh:
                            new = jax.device_put(new, old_sh)
                    else:
                        old_dev = next(iter(old_devs))
                        if hasattr(new, "devices") and \
                                new.devices() != {old_dev}:
                            new = jax.device_put(new, old_dev)
                except Exception:
                    pass
            self._data = _engine.track(new)
            return
        parent = self._base._get()
        kind = self._spec[0]
        if kind == "slice":
            upd = parent.at[self._spec[1]:self._spec[2]].set(
                jnp.asarray(new, dtype=parent.dtype))
        elif kind == "at":
            upd = parent.at[self._spec[1]].set(jnp.asarray(new, dtype=parent.dtype))
        elif kind == "reshape":
            upd = jnp.asarray(new, dtype=parent.dtype).reshape(parent.shape)
        else:
            raise MXNetError("unknown view spec %r" % (self._spec,))
        self._base._set(upd)

    def _place(self, sharding):
        """Move the owning chunk to an explicit jax sharding (or device),
        keeping its value.  Later ``_set`` writes preserve the placement
        (see the multi-device branch there) — this is how
        ``Executor.set_mesh`` pins bound arrays to a mesh once and every
        subsequent ``set_input``/``set_params`` write stays sharded."""
        root = self._root()
        root._data = _engine.track(jax.device_put(root._get(), sharding))
        return self

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._get().shape)

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def dtype(self):
        return np.dtype(self._get().dtype)

    @property
    def context(self) -> Context:
        return _ctx_of(self._root()._data)

    ctx = context

    @property
    def T(self) -> "NDArray":
        return NDArray(jnp.transpose(self._get()))

    @property
    def handle(self):
        """Compat: the reference exposed a ctypes handle; here the jax.Array."""
        return self._get()

    # -- sync points --------------------------------------------------------
    def wait_to_read(self):
        """Block until all pending writes to this array complete
        (reference Engine::WaitForVar, ndarray.h WaitToRead)."""
        jax.block_until_ready(self._get())

    wait_to_write = wait_to_read

    def asnumpy(self) -> np.ndarray:
        """Copy to host numpy array — THE sync point (SURVEY §3.6)."""
        return np.array(self._get())

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    def astype(self, dtype) -> "NDArray":
        return NDArray(self._get().astype(np.dtype(dtype)))

    # -- copies / context moves --------------------------------------------
    def copy(self) -> "NDArray":
        return NDArray(jnp.array(self._get()))

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        """CopyFromTo (reference src/ndarray/ndarray.cc:226-286)."""
        if isinstance(other, NDArray):
            if other is self or (other._root() is self._root() and other._spec == self._spec):
                return other
            val = jnp.asarray(self._get(), dtype=other.dtype)
            if other.context != self.context:
                val = jax.device_put(val, other.context.jax_device())
            other._set(val)
            return other
        if isinstance(other, Context):
            return NDArray(_dev_put(self._get(), other))
        raise TypeError("copyto does not support type " + str(type(other)))

    def as_in_context(self, context: Context) -> "NDArray":
        if self.context == context:
            return self
        return self.copyto(context)

    # -- views (zero-copy in reference; write-through here) -----------------
    def reshape(self, new_shape) -> "NDArray":
        new_shape = tuple(int(x) for x in new_shape)
        if int(np.prod(new_shape)) != self.size:
            raise MXNetError("reshape size mismatch %s -> %s" % (self.shape, new_shape))
        return NDArray(None, base=self, spec=("reshape", new_shape), writable=self.writable)

    def _slice(self, start: int, stop: int) -> "NDArray":
        start, stop = int(start), int(stop)
        if not (0 <= start <= stop <= self.shape[0]):
            raise MXNetError("invalid slice [%d,%d) for shape %s" % (start, stop, self.shape))
        return NDArray(None, base=self, spec=("slice", start, stop), writable=self.writable)

    def _at(self, idx: int) -> "NDArray":
        idx = int(idx)
        if not 0 <= idx < self.shape[0]:
            raise MXNetError("index %d out of range" % idx)
        return NDArray(None, base=self, spec=("at", idx), writable=self.writable)

    def __getitem__(self, key):
        if isinstance(key, int):
            return self._at(key)
        if isinstance(key, slice):
            if key.step is not None and key.step != 1:
                raise MXNetError("slice step not supported")
            start = 0 if key.start is None else key.start
            stop = self.shape[0] if key.stop is None else key.stop
            return self._slice(start, stop)
        raise MXNetError("NDArray only supports int/contiguous-slice indexing; got %r" % (key,))

    def __setitem__(self, key, value):
        if isinstance(key, slice) and key.start is None and key.stop is None and key.step is None:
            target = self
        elif isinstance(key, (int, slice)):
            target = self[key]
        else:
            raise MXNetError("unsupported key %r" % (key,))
        if isinstance(value, NDArray):
            target._set(jnp.asarray(value._get(), dtype=target.dtype).reshape(target.shape)
                        if value.shape != target.shape and value.size == target.size
                        else jnp.asarray(value._get(), dtype=target.dtype))
        elif isinstance(value, numeric_types):
            target._set(jnp.full(target.shape, value, dtype=target.dtype))
        elif isinstance(value, (np.ndarray, np.generic, list, tuple)):
            target._set(jnp.asarray(value, dtype=target.dtype))
        else:
            raise TypeError("type %s not supported" % str(type(value)))

    def _sync_copyfrom(self, source_array):
        source_array = np.asarray(source_array, dtype=self.dtype)
        if source_array.shape != self.shape:
            raise MXNetError("array shape do not match %s vs %s"
                             % (source_array.shape, self.shape))
        self._set(jnp.asarray(source_array))

    # -- arithmetic ---------------------------------------------------------
    def _binary(self, other, fn, reverse=False):
        a = self._get()
        if isinstance(other, NDArray):
            b = other._get()
        elif isinstance(other, numeric_types):
            b = other
        else:
            raise TypeError("type %s not supported" % str(type(other)))
        out = fn(b, a) if reverse else fn(a, b)
        return NDArray(_engine.track(out))

    def __add__(self, other): return self._binary(other, jnp.add)
    def __radd__(self, other): return self._binary(other, jnp.add)
    def __sub__(self, other): return self._binary(other, jnp.subtract)
    def __rsub__(self, other): return self._binary(other, jnp.subtract, reverse=True)
    def __mul__(self, other): return self._binary(other, jnp.multiply)
    def __rmul__(self, other): return self._binary(other, jnp.multiply)
    def __div__(self, other): return self._binary(other, jnp.divide)
    def __rdiv__(self, other): return self._binary(other, jnp.divide, reverse=True)
    def __truediv__(self, other): return self._binary(other, jnp.divide)
    def __rtruediv__(self, other): return self._binary(other, jnp.divide, reverse=True)
    def __pow__(self, other): return self._binary(other, jnp.power)
    def __rpow__(self, other): return self._binary(other, jnp.power, reverse=True)
    def __mod__(self, other): return self._binary(other, jnp.mod)
    def __neg__(self): return NDArray(-self._get())

    def __iadd__(self, other):
        self._set(self._binary(other, jnp.add)._get())
        return self

    def __isub__(self, other):
        self._set(self._binary(other, jnp.subtract)._get())
        return self

    def __imul__(self, other):
        self._set(self._binary(other, jnp.multiply)._get())
        return self

    def __itruediv__(self, other):
        self._set(self._binary(other, jnp.divide)._get())
        return self

    __idiv__ = __itruediv__

    def __len__(self):
        return self.shape[0]

    def __repr__(self):
        return "<NDArray %s @%s>" % ("x".join(map(str, self.shape)), self.context)

    def __getstate__(self):
        return {"data": self.asnumpy()}

    def __setstate__(self, state):
        self._base = None
        self._spec = None
        self.writable = True
        self._data = jnp.asarray(state["data"])

    def broadcast_to(self, shape) -> "NDArray":
        shape = tuple(int(x) for x in shape)
        cur = self.shape
        # reference broadcasting rule: same ndim, dims equal or 1
        if len(cur) != len(shape):
            raise MXNetError("Broadcasting needs same ndim: %s vs %s" % (cur, shape))
        for c, s in zip(cur, shape):
            if c != s and c != 1:
                raise MXNetError("cannot broadcast %s to %s" % (cur, shape))
        return NDArray(jnp.broadcast_to(self._get(), shape))


# ---------------------------------------------------------------------------
# creation functions (reference python/mxnet/ndarray.py zeros/ones/array/...)

def _resolve_ctx(ctx: Optional[Context]) -> Context:
    return ctx if ctx is not None else current_context()


def empty(shape, ctx: Optional[Context] = None, dtype=np.float32) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    return zeros(shape, ctx=ctx, dtype=dtype)


def zeros(shape, ctx: Optional[Context] = None, dtype=np.float32) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    ctx = _resolve_ctx(ctx)
    return NDArray(_engine.track(_dev_put(jnp.zeros(shape, dtype=np.dtype(dtype)), ctx)))


def ones(shape, ctx: Optional[Context] = None, dtype=np.float32) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    ctx = _resolve_ctx(ctx)
    return NDArray(_engine.track(_dev_put(jnp.ones(shape, dtype=np.dtype(dtype)), ctx)))


def full(shape, val, ctx: Optional[Context] = None, dtype=np.float32) -> NDArray:
    if isinstance(shape, int):
        shape = (shape,)
    ctx = _resolve_ctx(ctx)
    return NDArray(_engine.track(_dev_put(jnp.full(shape, val, dtype=np.dtype(dtype)), ctx)))


def array(source_array, ctx: Optional[Context] = None, dtype=np.float32) -> NDArray:
    if isinstance(source_array, NDArray):
        source_array = source_array.asnumpy()
    arr = np.asarray(source_array, dtype=np.dtype(dtype))
    ctx = _resolve_ctx(ctx)
    return NDArray(_engine.track(_dev_put(jnp.asarray(arr), ctx)))


def arange(start, stop=None, step=1.0, ctx=None, dtype=np.float32) -> NDArray:
    ctx = _resolve_ctx(ctx)
    return NDArray(_dev_put(jnp.arange(start, stop, step, dtype=np.dtype(dtype)), ctx))


# ---------------------------------------------------------------------------
# save / load (reference NDArray::Save/Load dmlc::Stream format, ndarray.h:276)
# TPU build: self-describing binary container; same capability (named or listed
# arrays, one file), different byte format.

_SAVE_MAGIC = b"MXTPU001"


def save(fname: str, data) -> None:
    """Save list or dict of NDArray (reference python/mxnet/ndarray.py save).

    Local paths publish atomically (temp file + fsync + ``os.replace``,
    base.atomic_local_write): a crash mid-save can never leave a
    truncated file at the published name — the torn-``.params`` failure
    mode that used to break ``load_checkpoint``.  URI targets stream
    through their protocol driver unchanged."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    elif isinstance(data, (list, tuple)):
        names = None
        arrays = list(data)
    else:
        raise TypeError("save only accepts dict or list of NDArray")
    for a in arrays:
        if not isinstance(a, NDArray):
            raise TypeError("save only accepts dict or list of NDArray")
    raw = [a.asnumpy() for a in arrays]
    # npz has no bfloat16: store as uint16 bits + a dtype tag per array
    dtypes = [str(a.dtype) for a in raw]
    raw = [a.view(np.uint16) if d == "bfloat16" else a
           for a, d in zip(raw, dtypes)]

    def _write(f):
        f.write(_SAVE_MAGIC)
        np_bytes = _io.BytesIO()
        np.savez(np_bytes, *raw)
        meta = pickle.dumps({"names": names, "dtypes": dtypes})
        f.write(struct.pack("<Q", len(meta)))
        f.write(meta)
        f.write(np_bytes.getvalue())

    from .base import atomic_local_write, is_local_path, open_stream
    if is_local_path(fname):
        with atomic_local_write(fname, "wb") as f:
            _write(f)
    else:
        with open_stream(fname, "wb") as f:
            _write(f)


def load(fname: str):
    """Load NDArrays saved by :func:`save` (local paths or URIs — the
    reference's dmlc::Stream S3/HDFS transparency, via fsspec here)."""
    from .base import open_stream
    with open_stream(fname, "rb") as f:
        return loads(f.read(), name=fname)


def loads(buf: bytes, name: str = "<bytes>"):
    """Load NDArrays from an in-memory save() blob (the form the C predict
    ABI receives param blobs in, c_predict_api.h MXPredCreate)."""
    stream = _io.BytesIO(buf)
    magic = stream.read(len(_SAVE_MAGIC))
    if magic != _SAVE_MAGIC:
        raise MXNetError("invalid NDArray file %s" % name)
    (meta_len,) = struct.unpack("<Q", stream.read(8))
    meta = pickle.loads(stream.read(meta_len))
    if isinstance(meta, dict):
        names, dtypes = meta["names"], meta.get("dtypes")
    else:                      # blobs from older saves: names only
        names, dtypes = meta, None
    npz = np.load(_io.BytesIO(stream.read()))
    arrays = []
    for i in range(len(npz.files)):
        a = npz["arr_%d" % i]
        dt = dtypes[i] if dtypes else str(a.dtype)
        if dt == "bfloat16":
            import ml_dtypes
            a = a.view(ml_dtypes.bfloat16)
        arrays.append(array(a, dtype=dt))
    if names is None:
        return arrays
    return dict(zip(names, arrays))


# ---------------------------------------------------------------------------
# registered functions (reference src/ndarray/ndarray.cc registrations)

def concatenate(arrays: Sequence[NDArray], axis: int = 0, always_copy: bool = True) -> NDArray:
    if not arrays:
        raise MXNetError("need at least one array")
    if len(arrays) == 1 and not always_copy:
        return arrays[0]
    return NDArray(jnp.concatenate([a._get() for a in arrays], axis=axis))


def concat(*arrays, **kwargs):
    dim = kwargs.get("dim", 1)
    return concatenate(list(arrays), axis=dim)


def onehot_encode(indices: NDArray, out: NDArray) -> NDArray:
    """reference ndarray.cc onehot_encode: out[i, indices[i]] = 1."""
    n, k = out.shape
    idx = indices._get().astype(jnp.int32)
    out._set(jax.nn.one_hot(idx, k, dtype=out.dtype))
    return out


def clip(arr: NDArray, a_min, a_max) -> NDArray:
    return NDArray(jnp.clip(arr._get(), a_min, a_max))


def dot(lhs: NDArray, rhs: NDArray) -> NDArray:
    return NDArray(_engine.track(jnp.dot(lhs._get(), rhs._get())))


def batch_dot(lhs: NDArray, rhs: NDArray) -> NDArray:
    return NDArray(_engine.track(jnp.matmul(lhs._get(), rhs._get())))


def transpose(arr: NDArray, axes=None) -> NDArray:
    return NDArray(jnp.transpose(arr._get(), axes))


def sum(arr: NDArray, axis=None, keepdims=False) -> NDArray:
    return NDArray(jnp.sum(arr._get(), axis=axis, keepdims=keepdims).reshape(-1)
                   if axis is None and not keepdims
                   else jnp.sum(arr._get(), axis=axis, keepdims=keepdims))


def max(arr: NDArray, axis=None, keepdims=False) -> NDArray:  # noqa: A001
    return NDArray(jnp.max(arr._get(), axis=axis, keepdims=keepdims).reshape(-1)
                   if axis is None and not keepdims
                   else jnp.max(arr._get(), axis=axis, keepdims=keepdims))


def min(arr: NDArray, axis=None, keepdims=False) -> NDArray:  # noqa: A001
    return NDArray(jnp.min(arr._get(), axis=axis, keepdims=keepdims).reshape(-1)
                   if axis is None and not keepdims
                   else jnp.min(arr._get(), axis=axis, keepdims=keepdims))


def norm(arr: NDArray) -> NDArray:
    return NDArray(jnp.sqrt(jnp.sum(jnp.square(arr._get()))).reshape(1))


def argmax_channel(arr: NDArray) -> NDArray:
    return NDArray(jnp.argmax(arr._get(), axis=1).astype(arr._get().dtype))


def choose_element_0index(lhs: NDArray, rhs: NDArray) -> NDArray:
    """out[i] = lhs[i, rhs[i]] (reference ndarray choose_element_0index)."""
    a = lhs._get()
    idx = rhs._get().astype(jnp.int32)
    return NDArray(a[jnp.arange(a.shape[0]), idx])


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3, mean=None):
    """Decode an image (reference plugin/opencv). Uses PIL if available."""
    raise MXNetError("imdecode requires the opencv plugin; not available in this build")


def waitall():
    """Block until all pending async work completes (MXNDArrayWaitAll)."""
    _engine.wait_for_all()


for _name, _fn in [("_plus", lambda a, b: a + b), ("_minus", lambda a, b: a - b),
                   ("_mul", lambda a, b: a * b), ("_div", lambda a, b: a / b),
                   ("clip", clip), ("dot", dot), ("batch_dot", batch_dot),
                   ("onehot_encode", onehot_encode), ("sum", sum), ("max", max),
                   ("min", min), ("norm", norm), ("argmax_channel", argmax_channel),
                   ("choose_element_0index", choose_element_0index),
                   ("transpose", transpose)]:
    register_ndarray_fn(_name, _fn)
