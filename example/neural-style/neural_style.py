"""Neural style transfer (reference example/neural-style capability;
Gatys et al. 2015).

Optimizes the INPUT image through a VGG feature extractor: content loss on
deep features, style loss on Gram matrices — the gradient flows to the data
via inputs_need_grad/args_grad on the executor, the same mechanism the
reference used.  Load converted VGG-19 weights via --params for real runs
(random weights still demonstrate the full optimization loop).
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from mxnet_tpu import symbol as sym


def style_content_symbol():
    """VGG-ish trunk exposing style (relu1..4) + content (relu4) features."""
    data = sym.Variable("data")
    style_feats = []
    body = data
    for stage, (nf, n) in enumerate([(64, 2), (128, 2), (256, 3), (512, 3)]):
        for i in range(n):
            body = sym.Convolution(body, kernel=(3, 3), pad=(1, 1),
                                   num_filter=nf,
                                   name="conv%d_%d" % (stage + 1, i + 1))
            body = sym.Activation(body, act_type="relu",
                                  name="relu%d_%d" % (stage + 1, i + 1))
        style_feats.append(body)
        body = sym.Pooling(body, pool_type="avg", kernel=(2, 2), stride=(2, 2),
                           name="pool%d" % (stage + 1))
    content_feat = style_feats[-1]
    return sym.Group(style_feats), content_feat


def gram(feat):
    n = feat.shape[1]
    x = feat.asnumpy().reshape(n, -1)
    return x @ x.T / x.shape[1]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--content-image", type=str)
    parser.add_argument("--style-image", type=str)
    parser.add_argument("--params", type=str, help="converted VGG params file")
    parser.add_argument("--size", type=int, default=128)
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--style-weight", type=float, default=1.0)
    parser.add_argument("--content-weight", type=float, default=10.0)
    parser.add_argument("--output", type=str, default="out.npy")
    parser.add_argument("--tpus", type=str)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = mx.tpu(0) if args.tpus else mx.cpu()
    hw = (1, 3, args.size, args.size)

    def load_img(path):
        if path and os.path.exists(path):
            from mxnet_tpu.plugins import opencv as cv
            img = cv.imresize(cv.imread(path), args.size, args.size)
            return img.asnumpy().transpose(2, 0, 1)[None].astype(np.float32) / 255
        return np.random.rand(*hw).astype(np.float32)

    content = load_img(args.content_image)
    style = load_img(args.style_image)

    style_sym, content_sym = style_content_symbol()
    net = sym.Group([style_sym, content_sym])
    exe = net.bind(ctx, args={"data": mx.nd.array(content),
                              **{n: mx.nd.zeros(s) for n, s in zip(
                                  net.list_arguments()[1:],
                                  net.infer_shape(data=hw)[0][1:])}},
                   args_grad={"data": mx.nd.zeros(hw)}, grad_req={"data": "write"})
    init = mx.init.Xavier()
    for name in net.list_arguments()[1:]:
        init(name, exe.arg_dict[name])
    if args.params:
        exe.copy_params_from(
            {k: v for k, v in mx.nd.load(args.params).items()},
            allow_extra_params=True)

    n_style = len(net.list_outputs()) - 1
    # targets
    exe.arg_dict["data"][:] = mx.nd.array(style)
    exe.forward(is_train=False)
    style_targets = [gram(o) for o in exe.outputs[:n_style]]
    exe.arg_dict["data"][:] = mx.nd.array(content)
    exe.forward(is_train=False)
    content_target = exe.outputs[-1].asnumpy()

    img = mx.nd.array(content + np.random.randn(*hw).astype(np.float32) * 0.05)
    opt = mx.optimizer.Adam(learning_rate=args.lr)
    state = opt.create_state(0, img)
    for it in range(args.iters):
        exe.arg_dict["data"][:] = img
        exe.forward(is_train=True)
        # build head gradients: d(style+content loss)/d(features)
        head_grads = []
        loss = 0.0
        for o, tgt in zip(exe.outputs[:n_style], style_targets):
            feat = o.asnumpy()
            n = feat.shape[1]
            flat = feat.reshape(n, -1)
            g = flat @ flat.T / flat.shape[1] - tgt
            loss += args.style_weight * float((g ** 2).sum())
            gg = (2 * args.style_weight / flat.shape[1]) * (g @ flat)
            head_grads.append(mx.nd.array(gg.reshape(feat.shape)))
        cf = exe.outputs[-1].asnumpy()
        loss += args.content_weight * float(((cf - content_target) ** 2).mean())
        head_grads.append(mx.nd.array(
            2 * args.content_weight * (cf - content_target) / cf.size))
        exe.backward(head_grads)
        opt.update(0, img, exe.grad_dict["data"], state)
        img[:] = mx.nd.clip(img, 0.0, 1.0)
        if it % 10 == 0:
            logging.info("iter %d loss %.4f", it, loss)
    np.save(args.output, img.asnumpy())
    logging.info("saved %s", args.output)


if __name__ == "__main__":
    main()
