// Scala/JVM binding build. Requires a JDK (javac for JNI headers) and
// sbt; this image ships neither, so CI proves the JNI layer JVM-free
// instead (tests/cpp/test_jni_glue.cc under the mocked
// tests/cpp/jniheaders/jni.h). With a JVM present:
//
//   1. build the native glue:
//        g++ -O2 -std=c++14 -fPIC -shared \
//            -I$JAVA_HOME/include -I$JAVA_HOME/include/linux \
//            native/src/main/native/mxnet_tpu_jni.cc \
//            -o native/libmxnet_tpu_jni.so -ldl
//   2. sbt test   (with -Djava.library.path=native and
//                  MXNET_TPU_LIBRARY=/path/to/libmxtpu_capi.so)
name := "mxnet-tpu-core"

organization := "ml.dmlc"

version := "0.1.0-SNAPSHOT"

scalaVersion := "2.12.18"

Compile / scalaSource := baseDirectory.value / "core" / "src" / "main" / "scala"

Test / scalaSource := baseDirectory.value / "core" / "src" / "test" / "scala"

libraryDependencies += "org.scalatest" %% "scalatest" % "3.0.8" % Test

Test / fork := true

Test / javaOptions += s"-Djava.library.path=${baseDirectory.value / "native"}"
