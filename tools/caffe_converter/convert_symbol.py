"""Caffe prototxt -> mxnet_tpu Symbol converter (reference
tools/caffe_converter/convert_symbol.py capability).

Parses the prototxt text format directly (no caffe/protobuf dependency —
the reference compiled caffe.proto; here a small recursive-descent parser
reads the same surface) and emits the equivalent symbol graph for the
layer types the reference supported: Convolution, Pooling, InnerProduct,
ReLU/Sigmoid/TanH, LRN, BatchNorm, Dropout, Concat, Eltwise, Flatten,
SoftmaxWithLoss/Softmax.  Binary .caffemodel weight unpacking is out of
scope (reference used the compiled proto); load weights via
convert_model.py from an .npz instead.
"""
import argparse
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx


def parse_prototxt(text):
    """Parse prototxt into a list of {name,type,bottom[],top[],params{}}."""
    tokens = re.findall(r"[\w.\-+/]+|[{}:]|\"[^\"]*\"", text)
    pos = [0]

    def parse_block():
        out = {}
        while pos[0] < len(tokens):
            tok = tokens[pos[0]]
            if tok == "}":
                pos[0] += 1
                return out
            key = tok
            pos[0] += 1
            if tokens[pos[0]] == ":":
                pos[0] += 1
                val = tokens[pos[0]].strip('"')
                pos[0] += 1
                out.setdefault(key, []).append(val)
            elif tokens[pos[0]] == "{":
                pos[0] += 1
                out.setdefault(key, []).append(parse_block())
        return out

    top = parse_block()
    layers = []
    for layer in top.get("layer", []) + top.get("layers", []):
        layers.append(layer)
    return top, layers


def _first(d, key, default=None):
    v = d.get(key)
    if not v:
        return default
    return v[0]


def _int(d, key, default=0):
    return int(_first(d, key, default))


def convert_symbol(prototxt_path):
    """Return (symbol, input_name).  Mirrors the reference layer mapping."""
    with open(prototxt_path) as f:
        top, layers = parse_prototxt(f.read())

    input_name = _first(top, "input", "data")
    nodes = {input_name: mx.sym.Variable(input_name)}

    def get_bottom(layer):
        bots = layer.get("bottom", [input_name])
        return [nodes[b] for b in bots]

    for layer in layers:
        ltype = _first(layer, "type", "")
        name = _first(layer, "name", "layer%d" % len(nodes))
        tops = layer.get("top", [name])
        bots = get_bottom(layer)
        x = bots[0]

        if ltype in ("Convolution", "CONVOLUTION"):
            p = layer["convolution_param"][0]
            k = _int(p, "kernel_size", 1)
            net = mx.sym.Convolution(
                x, num_filter=_int(p, "num_output"),
                kernel=(k, k),
                stride=(_int(p, "stride", 1),) * 2,
                pad=(_int(p, "pad", 0),) * 2,
                no_bias=_first(p, "bias_term", "true") == "false",
                name=name)
        elif ltype in ("Pooling", "POOLING"):
            p = layer["pooling_param"][0]
            k = _int(p, "kernel_size", 2)
            pool = _first(p, "pool", "MAX").lower()
            net = mx.sym.Pooling(
                x, kernel=(k, k), stride=(_int(p, "stride", k),) * 2,
                pad=(_int(p, "pad", 0),) * 2,
                pool_type="avg" if pool == "ave" else pool, name=name)
        elif ltype in ("InnerProduct", "INNER_PRODUCT"):
            p = layer["inner_product_param"][0]
            net = mx.sym.FullyConnected(
                mx.sym.Flatten(x), num_hidden=_int(p, "num_output"),
                no_bias=_first(p, "bias_term", "true") == "false", name=name)
        elif ltype in ("ReLU", "RELU"):
            net = mx.sym.Activation(x, act_type="relu", name=name)
        elif ltype in ("Sigmoid", "SIGMOID"):
            net = mx.sym.Activation(x, act_type="sigmoid", name=name)
        elif ltype in ("TanH", "TANH"):
            net = mx.sym.Activation(x, act_type="tanh", name=name)
        elif ltype in ("LRN",):
            p = layer.get("lrn_param", [{}])[0]
            net = mx.sym.LRN(x, nsize=_int(p, "local_size", 5),
                             alpha=float(_first(p, "alpha", 1e-4)),
                             beta=float(_first(p, "beta", 0.75)), name=name)
        elif ltype in ("BatchNorm",):
            net = mx.sym.BatchNorm(x, name=name)
        elif ltype in ("Dropout", "DROPOUT"):
            p = layer.get("dropout_param", [{}])[0]
            net = mx.sym.Dropout(x, p=float(_first(p, "dropout_ratio", 0.5)),
                                 name=name)
        elif ltype in ("Concat", "CONCAT"):
            net = mx.sym.Concat(*bots, name=name)
        elif ltype in ("Eltwise",):
            net = bots[0]
            for b in bots[1:]:
                net = net + b
        elif ltype in ("Flatten", "FLATTEN"):
            net = mx.sym.Flatten(x, name=name)
        elif ltype in ("Softmax", "SOFTMAX", "SoftmaxWithLoss",
                       "SOFTMAX_LOSS"):
            net = mx.sym.SoftmaxOutput(x, name="softmax")
        elif ltype in ("Accuracy", "ACCURACY", "Data", "DATA", "Input"):
            continue
        else:
            raise ValueError("unsupported caffe layer type %r (%s)"
                             % (ltype, name))
        for t in tops:
            nodes[t] = net

    return net, input_name


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("prototxt")
    parser.add_argument("--output", type=str, help="write symbol json here")
    args = parser.parse_args()
    net, input_name = convert_symbol(args.prototxt)
    print("converted; arguments:", net.list_arguments())
    if args.output:
        net.save(args.output)
        print("saved", args.output)


if __name__ == "__main__":
    main()
