"""MoEServeParityPass: no-drop routing on the serving graph.

Capacity-factor token dropping is a TRAINING throughput trade: a
dropped token rides the residual path and the optimizer sees it again
next epoch.  At serve time there is no next epoch — a dropped token is
a corrupted response, and which tokens drop depends on what else is in
the batch (slot composition under continuous batching), so the same
request can answer differently run to run.  This pass rewrites every
``_moe_dispatch`` node to ``capacity_factor=0`` (bucket = worst case,
nothing folds to the sentinel), making routed serving bitwise parity
with the dense-gather reference — the contract ``bench_moe``'s
``moe_serve_tok_s`` leg asserts.

On by default for serving pipelines; ``MXNET_MOE_SERVE_EXACT=0`` keeps
the training capacity (a latency experiment, not a serving
configuration).  Attrs are preserved node-for-node — the pipeline's
round-trip verifier checks this like every other pass.
"""
from __future__ import annotations

from ..base import get_env
from .graph_passes import _make_node, rebuild
from .pipeline import Pass

__all__ = ["MoEServeParityPass", "default_moe_exact"]


def default_moe_exact() -> bool:
    """The ``MXNET_MOE_SERVE_EXACT`` default for serving pipelines."""
    return get_env("MXNET_MOE_SERVE_EXACT", True, bool)


class MoEServeParityPass(Pass):
    """``_moe_dispatch(capacity_factor=cf)`` -> ``capacity_factor=0``
    on every node still carrying a dropping capacity (see module
    docstring)."""

    name = "moe_serve_parity"
    # after quantize/fusion-feeding passes for the usual reason: earlier
    # passes match on the ORIGINAL op names and params
    order_after = ("quantize",)

    def apply(self, sym, params):
        rewritten = []

        def transform(node, new_inputs):
            if node.is_variable or \
                    getattr(node.op, "name", "") != "_moe_dispatch":
                return None
            p = node.params
            if not p.capacity_factor or p.capacity_factor <= 0:
                return None    # already no-drop
            new = _make_node(
                "_moe_dispatch", node.name,
                {"num_experts": p.num_experts, "k": p.k,
                 "capacity_factor": 0.0, "renormalize": p.renormalize},
                new_inputs, attrs=node.attrs)
            rewritten.append(node.name)
            return [(new, i) for i in range(node.num_outputs())]

        out = rebuild(sym, transform)
        self.summary = {"rewritten": len(rewritten), "nodes": rewritten}
        return (out if rewritten else sym), params
