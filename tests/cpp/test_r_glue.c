/*
 * Execute the R binding's C glue (R-package/src/mxnet_glue.c) against
 * the real libmxtpu_capi.so, with R's C API mocked (rmock.h).  Proves
 * the marshalling — ndarray round trips, registry invocation, symbol
 * construction/composition/shape inference, executor bind/forward/
 * backward, save/load — without an R installation.  When Rscript IS
 * present, tests/test_r_package.py additionally runs the real R stack.
 *
 * Usage: test_r_glue <path-to-libmxtpu_capi.so> <tmpdir>
 */
#include "rmock.h"
#include "../../R-package/src/mxnet_glue.c"

#include <math.h>

static SEXP mkstrvec(int n, const char **v) {
  SEXP s = Rf_allocVector(STRSXP, n);
  for (int i = 0; i < n; ++i) SET_STRING_ELT(s, i, Rf_mkChar(v[i]));
  return s;
}

static SEXP mkintvec(int n, const int *v) {
  SEXP s = Rf_allocVector(INTSXP, n);
  for (int i = 0; i < n; ++i) INTEGER(s)[i] = v[i];
  return s;
}

static SEXP mkrealvec(int n, const double *v) {
  SEXP s = Rf_allocVector(REALSXP, n);
  for (int i = 0; i < n; ++i) REAL(s)[i] = v[i];
  return s;
}

static int str_index(SEXP strs, const char *want) {
  for (int i = 0; i < LENGTH(strs); ++i)
    if (strcmp(CHAR(STRING_ELT(strs, i)), want) == 0) return i;
  fprintf(stderr, "missing name %s\n", want);
  exit(1);
}

#define CHECK(cond)                                          \
  do {                                                       \
    if (!(cond)) {                                           \
      fprintf(stderr, "CHECK failed at %d: %s\n", __LINE__, #cond); \
      exit(1);                                               \
    }                                                        \
  } while (0)

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s libmxtpu_capi.so tmpdir\n", argv[0]);
    return 2;
  }
  mxg_load(Rf_mkString(argv[1]));
  mxg_random_seed(Rf_ScalarInteger(7));

  /* ---- ndarray round trip ---- */
  int shp[2] = {2, 3};
  SEXP dev0 = Rf_ScalarInteger(1), id0 = Rf_ScalarInteger(0);
  SEXP a = mxg_nd_create(mkintvec(2, shp), dev0, id0);
  double vals[6] = {1, 2, 3, 4, 5, 6};
  mxg_nd_copy_from(a, mkrealvec(6, vals));
  SEXP got = mxg_nd_copy_to(a);
  for (int i = 0; i < 6; ++i) CHECK(REAL(got)[i] == vals[i]);
  SEXP shape = mxg_nd_shape(a);
  CHECK(LENGTH(shape) == 2 && INTEGER(shape)[0] == 2 &&
        INTEGER(shape)[1] == 3);

  /* ---- registry function invoke: _plus ---- */
  SEXP fnames = mxg_list_function_names();
  int plus_idx = str_index(fnames, "_plus");
  SEXP desc = mxg_func_describe(Rf_ScalarInteger(plus_idx));
  CHECK(INTEGER(desc)[0] == 2 && INTEGER(desc)[2] == 1);
  SEXP b = mxg_nd_create(mkintvec(2, shp), dev0, id0);
  mxg_nd_copy_from(b, mkrealvec(6, vals));
  SEXP out = mxg_nd_create(mkintvec(2, shp), dev0, id0);
  SEXP use = Rf_allocVector(VECSXP, 2);
  SET_VECTOR_ELT(use, 0, a);
  SET_VECTOR_ELT(use, 1, b);
  SEXP mut = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(mut, 0, out);
  mxg_func_invoke(Rf_ScalarInteger(plus_idx), use,
                  Rf_allocVector(REALSXP, 0), mut);
  got = mxg_nd_copy_to(out);
  for (int i = 0; i < 6; ++i) CHECK(REAL(got)[i] == 2 * vals[i]);

  /* ---- symbol: var -> FullyConnected -> SoftmaxOutput ---- */
  SEXP cnames = mxg_sym_list_creator_names();
  int fc_idx = str_index(cnames, "FullyConnected");
  int sm_idx = str_index(cnames, "SoftmaxOutput");
  SEXP data = mxg_sym_create_variable(Rf_mkString("data"));
  const char *fck[] = {"num_hidden"};
  const char *fcv[] = {"4"};
  SEXP fc = mxg_sym_create_atomic(Rf_ScalarInteger(fc_idx),
                                  mkstrvec(1, fck), mkstrvec(1, fcv));
  SEXP compose_args = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(compose_args, 0, data);
  const char *dk[] = {"data"};
  mxg_sym_compose(fc, Rf_mkString("fc1"), mkstrvec(1, dk), compose_args);
  SEXP net = mxg_sym_create_atomic(Rf_ScalarInteger(sm_idx),
                                   mkstrvec(0, NULL), mkstrvec(0, NULL));
  SEXP compose2 = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(compose2, 0, fc);
  mxg_sym_compose(net, Rf_mkString("softmax"), mkstrvec(1, dk), compose2);

  SEXP args = mxg_sym_list_arguments(net);
  CHECK(LENGTH(args) == 4); /* data, fc1_weight, fc1_bias, softmax_label */
  SEXP outs = mxg_sym_list_outputs(net);
  CHECK(LENGTH(outs) == 1);

  /* round-trip through json */
  SEXP json = mxg_sym_tojson(net);
  SEXP net2 = mxg_sym_from_json(json);
  CHECK(LENGTH(mxg_sym_list_arguments(net2)) == 4);

  /* ---- infer shape ---- */
  const char *ik[] = {"data"};
  int dshape[2] = {8, 5};
  SEXP shapes = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(shapes, 0, mkintvec(2, dshape));
  SEXP inf = mxg_sym_infer_shape(net, mkstrvec(1, ik), shapes);
  CHECK(Rf_asInteger(VECTOR_ELT(inf, 3)) == 1);
  SEXP argshapes = VECTOR_ELT(inf, 0);
  SEXP w = VECTOR_ELT(argshapes, str_index(args, "fc1_weight"));
  CHECK(INTEGER(w)[0] == 4 && INTEGER(w)[1] == 5);

  /* ---- executor: bind, forward, backward ---- */
  int n_args = LENGTH(args);
  SEXP in_args = Rf_allocVector(VECSXP, n_args);
  SEXP grads = Rf_allocVector(VECSXP, n_args);
  SEXP reqs = Rf_allocVector(INTSXP, n_args);
  for (int i = 0; i < n_args; ++i) {
    SEXP s = VECTOR_ELT(argshapes, i);
    SEXP nd = mxg_nd_create(s, dev0, id0);
    long total = 1;
    for (int j = 0; j < LENGTH(s); ++j) total *= INTEGER(s)[j];
    SEXP init = Rf_allocVector(REALSXP, total);
    for (long j = 0; j < total; ++j)
      REAL(init)[j] = 0.05 * (double)((j % 7) - 3);
    mxg_nd_copy_from(nd, init);
    SET_VECTOR_ELT(in_args, i, nd);
    const char *an = CHAR(STRING_ELT(args, i));
    if (strcmp(an, "data") == 0 || strcmp(an, "softmax_label") == 0) {
      SET_VECTOR_ELT(grads, i, R_NilValue);
      INTEGER(reqs)[i] = 0;
    } else {
      SET_VECTOR_ELT(grads, i, mxg_nd_create(s, dev0, id0));
      INTEGER(reqs)[i] = 1; /* write */
    }
  }
  SEXP ex = mxg_exec_bind(net, dev0, id0, in_args, grads, reqs,
                          Rf_allocVector(VECSXP, 0));
  mxg_exec_forward(ex, Rf_ScalarInteger(1));
  SEXP exouts = mxg_exec_outputs(ex);
  CHECK(LENGTH(exouts) == 1);
  SEXP probs = mxg_nd_copy_to(VECTOR_ELT(exouts, 0));
  double rowsum = 0;
  for (int j = 0; j < 4; ++j) rowsum += REAL(probs)[j];
  CHECK(fabs(rowsum - 1.0) < 1e-4); /* softmax row sums to one */
  mxg_exec_backward(ex, Rf_allocVector(VECSXP, 0));
  SEXP g = mxg_nd_copy_to(
      VECTOR_ELT(grads, str_index(args, "fc1_weight")));
  double gsum = 0;
  for (int j = 0; j < LENGTH(g); ++j) gsum += fabs(REAL(g)[j]);
  CHECK(gsum > 0); /* gradients flowed */

  /* ---- save / load ---- */
  char fname[512];
  snprintf(fname, sizeof(fname), "%s/rglue.params", argv[2]);
  SEXP save_h = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(save_h, 0, a);
  const char *keys[] = {"arg:a"};
  mxg_nd_save(Rf_mkString(fname), save_h, mkstrvec(1, keys));
  SEXP loaded = mxg_nd_load(Rf_mkString(fname));
  CHECK(LENGTH(VECTOR_ELT(loaded, 0)) == 1);
  CHECK(strcmp(CHAR(STRING_ELT(VECTOR_ELT(loaded, 1), 0)), "arg:a") == 0);
  got = mxg_nd_copy_to(VECTOR_ELT(VECTOR_ELT(loaded, 0), 0));
  for (int i = 0; i < 6; ++i) CHECK(REAL(got)[i] == vals[i]);

  /* ---- multi-output indexing (rnn builders' SliceChannel path) ---- */
  int sc_idx = str_index(cnames, "SliceChannel");
  const char *sck[] = {"num_outputs", "axis"};
  const char *scv[] = {"2", "1"};
  SEXP sc = mxg_sym_create_atomic(Rf_ScalarInteger(sc_idx),
                                  mkstrvec(2, sck), mkstrvec(2, scv));
  SEXP sc_args = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(sc_args, 0, mxg_sym_create_variable(Rf_mkString("x")));
  mxg_sym_compose(sc, Rf_mkString("split"), mkstrvec(1, dk), sc_args);
  CHECK(LENGTH(mxg_sym_list_outputs(sc)) == 2);
  SEXP half = mxg_sym_get_output(sc, Rf_ScalarInteger(1));
  CHECK(LENGTH(mxg_sym_list_outputs(half)) == 1);

  /* ---- kvstore + native optimizer through the glue ---- */
  SEXP kv = mxg_kv_create(Rf_mkString("local"));
  CHECK(strcmp(CHAR(STRING_ELT(mxg_kv_type(kv), 0)), "local") == 0);
  CHECK(Rf_asInteger(mxg_kv_rank(kv)) == 0);
  CHECK(Rf_asInteger(mxg_kv_num_workers(kv)) == 1);
  int wshape[1] = {4};
  SEXP kw = mxg_nd_create(mkintvec(1, wshape), dev0, id0);
  double zeros4[4] = {0, 0, 0, 0}, ones4[4] = {1, 1, 1, 1};
  mxg_nd_copy_from(kw, mkrealvec(4, zeros4));
  SEXP kg = mxg_nd_create(mkintvec(1, wshape), dev0, id0);
  mxg_nd_copy_from(kg, mkrealvec(4, ones4));
  int key3[1] = {3};
  SEXP kws = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(kws, 0, kw);
  SEXP kgs = Rf_allocVector(VECSXP, 1);
  SET_VECTOR_ELT(kgs, 0, kg);
  mxg_kv_init(kv, mkintvec(1, key3), kws);
  mxg_kv_push(kv, mkintvec(1, key3), kgs, Rf_ScalarInteger(0));
  mxg_kv_pull(kv, mkintvec(1, key3), kws, Rf_ScalarInteger(0));
  got = mxg_nd_copy_to(kw);
  CHECK(REAL(got)[0] == 1.0 && REAL(got)[3] == 1.0);

  const char *ok[] = {"momentum"};
  const char *ov[] = {"0.9"};
  SEXP opt = mxg_opt_create(Rf_mkString("sgd"), mkstrvec(1, ok),
                            mkstrvec(1, ov));
  mxg_opt_update(opt, Rf_ScalarInteger(0), kw, kg, Rf_ScalarReal(0.1),
                 Rf_ScalarReal(0.0));
  got = mxg_nd_copy_to(kw);
  CHECK(REAL(got)[0] < 1.0); /* sgd stepped downhill on +1 grads */

  /* round-5 surfaces: executor plan dump + internals view (the shape
   * annotation path graph.viz/mx.exec.debug.str drive) */
  SEXP dbg = mxg_exec_print(ex);
  CHECK(strlen(CHAR(STRING_ELT(dbg, 0))) > 0);
  SEXP internals = mxg_sym_get_internals(net);
  SEXP int_outs = mxg_sym_list_outputs(internals);
  CHECK(LENGTH(int_outs) > LENGTH(mxg_sym_list_outputs(net)));

  mxg_nd_waitall();
  printf("R GLUE TESTS PASSED\n");
  return 0;
}
