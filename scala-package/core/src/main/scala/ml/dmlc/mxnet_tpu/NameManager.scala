package ml.dmlc.mxnet_tpu

import scala.collection.mutable

/**
 * Automatic symbol naming (reference NameManager.scala): a user name
 * wins; otherwise `<hint><n>` with a per-hint counter — the same rule
 * the python NameManager applies, so auto-named graphs round-trip
 * between bindings.
 */
class NameManager {
  val counter: mutable.Map[String, Int] = mutable.HashMap.empty

  def get(name: Option[String], hint: String): String =
    name.getOrElse {
      val n = counter.getOrElse(hint, 0)
      counter(hint) = n + 1
      s"$hint$n"
    }

  def withScope[T](body: => T): T = {
    val outer = NameManager.current
    NameManager.setCurrentManager(this)
    try body finally NameManager.setCurrentManager(outer)
  }
}

object NameManager {
  private var _current = new NameManager()
  def current: NameManager = _current
  private[mxnet_tpu] def setCurrentManager(m: NameManager): Unit = {
    _current = m
  }
}
