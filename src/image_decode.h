// JPEG decode/encode + bilinear resize for the native IO pipeline.
// Reference analogue: the OpenCV imdecode/resize calls inside
// src/io/image_aug_default.cc and tools/im2rec.cc; here libjpeg (baked into
// the image) + a small bilinear kernel, no OpenCV dependency.
#ifndef MXTPU_IMAGE_DECODE_H_
#define MXTPU_IMAGE_DECODE_H_

#include <cstdint>
#include <cstddef>
#include <vector>

namespace mxtpu {

// True when buf starts with the JPEG SOI marker.
bool IsJPEG(const uint8_t* buf, size_t len);

// Decode a JPEG into packed RGB (HWC, 8-bit).  Returns false on corrupt
// input (libjpeg errors are trapped, never exit()).
bool DecodeJPEG(const uint8_t* buf, size_t len, std::vector<uint8_t>* rgb,
                int* h, int* w);

// Encode packed RGB (HWC, 8-bit) to JPEG at the given quality (1-100).
bool EncodeJPEG(const uint8_t* rgb, int h, int w, int quality,
                std::vector<uint8_t>* out);

// Bilinear resize of packed RGB (HWC) to (oh, ow).
void ResizeBilinear(const uint8_t* src, int h, int w, uint8_t* dst, int oh,
                    int ow, int channels = 3);

// Shorter-edge resize: scale so min(h, w) == target, preserving aspect.
// No-op (copy-free, returns false) when already at target.
bool ResizeShorterEdge(const std::vector<uint8_t>& src, int h, int w,
                       int target, std::vector<uint8_t>* dst, int* oh,
                       int* ow);

}  // namespace mxtpu

#endif  // MXTPU_IMAGE_DECODE_H_
