"""The span recorder: lock-light per-thread ring buffers.

Every recording thread owns one :class:`_ThreadBuf` — a preallocated
fixed-size list used as a circular buffer.  Appending an event is a few
bytecodes (tuple build + slot store + index bump) with NO lock: the GIL
makes the single slot store atomic, and each thread only ever writes its
own buffer.  The only lock in the module guards buffer *creation* and
the spill file; the hot path never touches it.  A full ring overwrites
its oldest events and counts them as drops — recording can never block,
allocate unboundedly, or crash the traced program.

Timestamps are ``time.perf_counter_ns()`` (CLOCK_MONOTONIC on Linux),
which is system-wide: spans recorded in forked worker processes land on
the same timeline as the parent's, so a merged trace lines up without
clock translation.

Cross-process collection: a worker process calls
:meth:`Recorder.configure_spill` with a file path; from then on its
events are appended to that file as Chrome-trace JSON lines (flushed
every ``MXNET_TRACE_SPILL_EVERY`` events and at ``flush_spill``), so a
worker killed with SIGKILL loses at most one flush window of spans.  The
parent registers the spill *directory* with the exporter and the merged
dump shows every process under its real pid.  An ``os.register_at_fork``
hook resets the child's inherited buffers (they belong to the parent's
timeline) and re-reads the pid.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from typing import Dict, List, Optional

from ..base import make_lock as _make_lock

__all__ = ["Recorder", "DEFAULT_BUF_EVENTS"]

DEFAULT_BUF_EVENTS = 65536

# event tuples: (ph, name, cat, ts_ns, dur_ns, async_id, args)
#   ph "X" complete   (dur_ns set)
#   ph "i" instant
#   ph "b"/"n"/"e" async begin / instant / end (async_id set)


def _spill_every() -> int:
    from ..base import get_env
    return max(1, get_env("MXNET_TRACE_SPILL_EVERY", 64, int))


def _spill_max() -> int:
    """Per-process cap on spilled events (MXNET_TRACE_SPILL_MAX_EVENTS,
    default 200k ≈ 25MB of JSONL): the spill file must honor the same
    bounded-resources contract as the rings — a week-long reader run
    must not fill the disk with decode spans."""
    from ..base import get_env
    return max(1, get_env("MXNET_TRACE_SPILL_MAX_EVENTS", 200000, int))


# dead-thread rings kept for the dump (short-lived threads' spans are
# exactly what a timeline is for) — but only this many; beyond it the
# oldest dead rings are pruned so thread-per-request workloads cannot
# leak one ring per client thread forever
MAX_DEAD_BUFS = 64


class _ThreadBuf:
    """One thread's event ring.  Only its owner thread writes; readers
    snapshot-copy (a torn read can at worst see one freshly overwritten
    slot, which is a newer valid event)."""

    __slots__ = ("tid", "thread_name", "cap", "buf", "n", "spilled",
                 "owner")

    def __init__(self, tid: int, thread_name: str, cap: int, owner=None):
        self.tid = tid
        self.thread_name = thread_name
        self.cap = cap
        self.buf: List = [None] * cap
        self.n = 0          # events ever recorded
        self.spilled = 0    # events already written to the spill file
        # weakly track the owning thread: liveness decides prunability
        self.owner = weakref.ref(owner) if owner is not None else None

    def alive(self) -> bool:
        t = self.owner() if self.owner is not None else None
        return bool(t is not None and t.is_alive())

    def drops(self) -> int:
        """Events lost to ring overwrite (never spilled, never
        snapshot-able)."""
        return max(0, self.n - self.spilled - self.cap)

    def pending(self):
        """(start_index, [events]) still held in the ring, oldest
        first."""
        n = self.n
        start = max(self.spilled, n - self.cap)
        cap = self.cap
        return start, [self.buf[i % cap] for i in range(start, n)]


class Recorder:
    """Process-wide registry of per-thread rings + optional spill sink."""

    def __init__(self, buf_events: int = DEFAULT_BUF_EVENTS):
        self.buf_events = max(16, int(buf_events))
        self.pid = os.getpid()
        self._lock = _make_lock("trace.recorder")
        self._bufs: List[_ThreadBuf] = []
        self._tls = threading.local()
        self._spill_path: Optional[str] = None
        self._spill_every = _spill_every()
        self._spill_max = _spill_max()
        self._spill_total = 0
        self._pruned_drops = 0

    # -- recording (hot path) ---------------------------------------------
    def _buf(self) -> _ThreadBuf:
        b = getattr(self._tls, "buf", None)
        if b is None:
            t = threading.current_thread()
            b = _ThreadBuf(t.ident or 0, t.name, self.buf_events, owner=t)
            self._tls.buf = b
            with self._lock:
                self._bufs.append(b)
                dead = [x for x in self._bufs if not x.alive()]
                if len(dead) > MAX_DEAD_BUFS:
                    # prune oldest dead rings (registration order): their
                    # un-snapshot events count as drops, same contract as
                    # ring overwrite
                    for x in dead[:len(dead) - MAX_DEAD_BUFS]:
                        _, pend = x.pending()
                        self._pruned_drops += x.drops() + len(pend)
                        self._bufs.remove(x)
        return b

    def add(self, ph: str, name: str, cat: str, ts_ns: int, dur_ns: int,
            async_id, args) -> None:
        b = self._buf()
        i = b.n
        b.buf[i % b.cap] = (ph, name, cat, ts_ns, dur_ns, async_id, args)
        b.n = i + 1
        if self._spill_path is not None and \
                b.n - b.spilled >= self._spill_every:
            self._spill_flush(b)

    # -- spill (worker processes) -----------------------------------------
    def configure_spill(self, path: str) -> None:
        """Route this process's spans to ``path`` (JSON lines, Chrome
        event dicts) so a parent process can merge them into its dump
        even after this process dies."""
        with self._lock:
            self._spill_path = path
            self._spill_every = _spill_every()
            self._spill_max = _spill_max()
            self._spill_total = 0

    def _spill_flush(self, b: _ThreadBuf) -> None:
        # the WHOLE read-compute-write-advance sequence holds the lock:
        # the owner thread's cadence flush can race a flush_spill() from
        # another thread, and two flushes reading the same pending
        # window would write every span twice
        with self._lock:
            path = self._spill_path
            if path is None:
                return
            start, events = b.pending()
            if not events:
                return
            room = self._spill_max - self._spill_total
            truncating = len(events) > room
            if truncating:
                events = events[:max(0, room)]
            lines = []
            for ev in events:
                if ev is None:
                    continue
                lines.append(json.dumps(
                    chrome_event(ev, self.pid, b.tid),
                    separators=(",", ":"), default=str))
            if truncating:
                # the cap is the bounded-disk contract: stop spilling,
                # say so IN the file (the merged dump shows where it
                # stops and why), and let the ring's own overwrite
                # bound take over
                last_ts = events[-1][3] / 1000.0 if events else 0.0
                lines.append(json.dumps(
                    {"name": "trace:spill_truncated", "cat": "trace",
                     "ph": "i", "s": "p", "ts": last_ts, "pid": self.pid,
                     "tid": b.tid, "args": {"limit": self._spill_max}},
                    separators=(",", ":")))
            try:
                if lines:
                    with open(path, "a") as f:
                        f.write("\n".join(lines) + "\n")
                        f.flush()
            except OSError:
                # a vanished spill dir must not kill the traced worker
                self._spill_path = None
                return
            if truncating:
                self._spill_path = None
            self._spill_total += len(events)
            b.spilled += len(events)

    def flush_spill(self) -> None:
        """Flush every thread's un-spilled events (worker exit path)."""
        if self._spill_path is None:
            return
        with self._lock:
            bufs = list(self._bufs)
        for b in bufs:
            self._spill_flush(b)

    # -- reading -----------------------------------------------------------
    def snapshot(self) -> List[Dict]:
        """Chrome-ready event dicts for every live ring (this process
        only; spill files are the other processes' halves)."""
        with self._lock:
            bufs = list(self._bufs)
        out = []
        for b in bufs:
            _, events = b.pending()
            for ev in events:
                if ev is not None:
                    out.append(chrome_event(ev, self.pid, b.tid))
        return out

    def thread_names(self) -> Dict[int, str]:
        with self._lock:
            return {b.tid: b.thread_name for b in self._bufs}

    def event_count(self) -> int:
        with self._lock:
            return sum(b.n for b in self._bufs)

    def drop_count(self) -> int:
        with self._lock:
            return self._pruned_drops + sum(b.drops() for b in self._bufs)

    # -- fork hygiene ------------------------------------------------------
    def reset_after_fork(self) -> None:
        """The child inherits the parent's rings and tls; its events must
        start fresh under its own pid (and never double-report the
        parent's)."""
        self.pid = os.getpid()
        self._lock = _make_lock("trace.recorder")
        self._bufs = []
        self._tls = threading.local()
        self._spill_path = None
        self._spill_total = 0
        self._pruned_drops = 0


def chrome_event(ev, pid: int, tid: int) -> Dict:
    """One recorder tuple -> one Chrome trace-event dict (ts/dur in
    microseconds, the format chrome://tracing and Perfetto load)."""
    ph, name, cat, ts_ns, dur_ns, async_id, args = ev
    d = {"name": name, "cat": cat, "ph": ph, "ts": ts_ns / 1000.0,
         "pid": pid, "tid": tid}
    if ph == "X":
        d["dur"] = dur_ns / 1000.0
    elif ph in ("b", "n", "e"):
        d["id"] = async_id
    elif ph == "i":
        d["s"] = "t"        # instant scope: thread
    if args:
        d["args"] = args
    return d


def now_ns() -> int:
    return time.perf_counter_ns()
