"""Multi-process bootstrap.

Joins the jax.distributed process group when launched by tools/launch.py
(MXNET_TPU_COORDINATOR / _NUM_WORKERS / _WORKER_ID envs — the TPU-native
replacement for the reference's DMLC_PS_ROOT_* rendezvous).  MUST run before
any JAX backend initialization, so mxnet_tpu/__init__ imports this first.
"""
from __future__ import annotations

import os

_done = False


def ensure() -> None:
    global _done
    if _done:
        return
    from .base import get_env
    coord = get_env("MXNET_TPU_COORDINATOR")
    if coord is None:
        _done = True
        return
    import jax
    try:
        jax.distributed.initialize(
            coordinator_address=coord,
            # lint: allow(raw-env) — rendezvous vars are a set: once
            # the coordinator is present, a missing peer var is a broken
            # launcher and must KeyError loudly, not default
            num_processes=int(os.environ["MXNET_TPU_NUM_WORKERS"]),
            process_id=int(os.environ["MXNET_TPU_WORKER_ID"]))
    except RuntimeError as e:
        if "already" not in str(e):
            raise
    _done = True


ensure()
