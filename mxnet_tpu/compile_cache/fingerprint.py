"""Cache keying: what makes two compilations interchangeable.

A serialized executable may be reused only when everything that went
into producing it is identical.  The key is a sha256 over:

* the lowered program text (StableHLO from ``jit(...).lower(...)``) —
  shapes, dtypes, donation/aliasing, compute_dtype casts, remat, the
  whole traced graph are all in here;
* jax + jaxlib versions (executable wire format is not stable across
  releases);
* backend platform + device kind + device topology (an executable
  compiled for one chip layout must never load on another);
* compile-relevant flags: ``XLA_FLAGS`` plus the ``MXNET_*`` knobs that
  steer program construction (belt and braces — they already change the
  lowered text, but a missed one must widen the key, not alias it).

Anything that does not match hashes to a different key, which reads as
a clean miss — the failure mode is always "compile again", never "run
the wrong program".
"""
from __future__ import annotations

import hashlib
import os
from typing import Iterable, Optional

# MXNET knobs that steer how programs are built/compiled.  Most alter the
# traced graph (and therefore the lowered text) anyway; keying on them
# directly costs nothing and protects against representation coincidences.
COMPILE_RELEVANT_ENV = (
    "MXNET_BACKWARD_DO_MIRROR",
    "MXNET_COMPUTE_DTYPE",
    "MXNET_EXEC_PREFER_BULK_EXEC",
    "MXNET_FUSED_TRAIN",
    "MXNET_FUSE_PALLAS",
    "MXNET_LSTM_SCAN",
    "MXNET_SHARD_WEIGHT_UPDATE",
    "MXNET_SUPERSTEP",
    "XLA_FLAGS",
)

_env_fp_cache: Optional[str] = None


def environment_fingerprint(refresh: bool = False) -> str:
    """One string describing everything key-relevant OUTSIDE the program
    text: versions, backend, topology, flags.  Computed once per process
    (the backend cannot change under us; env mutations mid-process are a
    test-only affair and use ``refresh=True``)."""
    global _env_fp_cache
    if _env_fp_cache is not None and not refresh:
        return _env_fp_cache
    import jax
    import jaxlib
    devs = jax.devices()
    parts = [
        "jax=%s" % jax.__version__,
        "jaxlib=%s" % jaxlib.__version__,
        "platform=%s" % devs[0].platform,
        "device_kind=%s" % getattr(devs[0], "device_kind", "?"),
        "topology=%s" % ",".join(str(d.id) for d in devs),
        "processes=%d" % jax.process_count(),
    ]
    for name in COMPILE_RELEVANT_ENV:
        # lint: allow(raw-env) — hashes the raw env VALUE bytes into the
        # compile key; get_env's typed defaults would fold unset into
        # default and alias distinct compile configurations
        parts.append("%s=%s" % (name, os.environ.get(name, "")))
    _env_fp_cache = ";".join(parts)
    return _env_fp_cache


_code_fp_cache: Optional[str] = None


def code_fingerprint(refresh: bool = False) -> str:
    """Hash over every mxnet_tpu python file's (path, size, mtime): the
    staleness guard for the trace-free fast-key index.  A fast key
    describes a program by what BUILT it (symbol graph, dtypes, flags)
    rather than by its lowered text — sound only while the building code
    itself is unchanged, so any edited/updated source file conservatively
    misses the whole index (the HLO-keyed entries still hit after one
    lowering)."""
    global _code_fp_cache
    if _code_fp_cache is not None and not refresh:
        return _code_fp_cache
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    h = hashlib.sha256()
    for dirpath, dirnames, filenames in sorted(os.walk(root)):
        dirnames.sort()
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            p = os.path.join(dirpath, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            h.update(("%s:%d:%d;" % (os.path.relpath(p, root), st.st_size,
                                     st.st_mtime_ns)).encode())
    _code_fp_cache = h.hexdigest()
    return _code_fp_cache


def fast_key(description: str, signature: str,
             env_fp: Optional[str] = None,
             code_fp: Optional[str] = None) -> str:
    """Key for the trace-free index: caller's program description (e.g.
    symbol json hash + dtypes + optimizer hparams) + the input-aval
    signature + environment + code fingerprints."""
    h = hashlib.sha256()
    h.update((env_fp if env_fp is not None
              else environment_fingerprint()).encode("utf-8"))
    h.update(b"\x00")
    h.update((code_fp if code_fp is not None
              else code_fingerprint()).encode("utf-8"))
    h.update(b"\x00")
    h.update(description.encode("utf-8"))
    h.update(b"\x00")
    h.update(signature.encode("utf-8"))
    return h.hexdigest()


def program_key(lowered_text: str, extras: Iterable[str] = (),
                env_fp: Optional[str] = None) -> str:
    """Key for one lowered program under the current environment."""
    h = hashlib.sha256()
    h.update((env_fp if env_fp is not None
              else environment_fingerprint()).encode("utf-8"))
    h.update(b"\x00")
    for e in extras:
        h.update(str(e).encode("utf-8"))
        h.update(b"\x00")
    h.update(lowered_text.encode("utf-8"))
    return h.hexdigest()


def blob_digest(blob: bytes) -> str:
    """Content checksum stored in the sidecar: a truncated or bit-flipped
    executable blob is detected BEFORE it reaches PJRT deserialization."""
    return hashlib.sha256(blob).hexdigest()
