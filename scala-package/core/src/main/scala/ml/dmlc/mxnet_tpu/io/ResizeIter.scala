package ml.dmlc.mxnet_tpu.io

import ml.dmlc.mxnet_tpu.{DataBatch, DataIter, Shape}

/**
 * Fixed-length epoch adapter (reference io/ResizeIter.scala; python
 * ResizeIter): presents exactly `size` batches per epoch regardless of
 * the wrapped iterator's length — short epochs wrap around (optionally
 * resetting the underlying iterator), long ones truncate.
 */
class ResizeIter(iter: DataIter, size: Int,
                 resetInternal: Boolean = true) extends DataIter {
  private var cur = 0

  def batchSize: Int = iter.batchSize
  def provideData: Map[String, Shape] = iter.provideData
  def provideLabel: Map[String, Shape] = iter.provideLabel

  def reset(): Unit = {
    cur = 0
    if (resetInternal) iter.reset()
  }

  def hasNext: Boolean = cur < size

  def next(): DataBatch = {
    if (!hasNext) throw new NoSuchElementException("epoch complete")
    if (!iter.hasNext) {
      iter.reset()   // wrap: the resized epoch is longer than the data
    }
    cur += 1
    iter.next()
  }
}
