"""Train a bidirectional LSTM to sort number sequences.

Capability parity with reference example/bi-lstm-sort/lstm_sort.py:1:
text-file corpus -> vocab -> bucketed iterator (labels are the sorted
row), FeedForward.fit with a numpy Perplexity metric, checkpoint saved
for infer_sort.py.  --synthetic generates the corpus in place of the
reference's downloaded data/sort.train.txt; an exact-match sort
accuracy sweep runs after training.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "rnn"))
import mxnet_tpu as mx

from lstm import bi_lstm_unroll
from sort_io import BucketSentenceIter, default_build_vocab, gen_sort_data
from bucket_io import perplexity_metric as Perplexity


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--train", default="./data/sort.train.txt")
    parser.add_argument("--valid", default="./data/sort.valid.txt")
    parser.add_argument("--synthetic", action="store_true")
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--num-hidden", type=int, default=300)
    parser.add_argument("--num-embed", type=int, default=512)
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--seq-len", type=int, default=5,
                        help="sequence length for --synthetic data")
    parser.add_argument("--vocab-size", type=int, default=100,
                        help="number range for --synthetic data")
    parser.add_argument("--num-examples", type=int, default=10000)
    parser.add_argument("--model-prefix", default="sort")
    args = parser.parse_args()
    logging.basicConfig(level=logging.DEBUG,
                        format="%(asctime)-15s %(message)s")

    if args.synthetic or not os.path.exists(args.train):
        os.makedirs(os.path.dirname(args.train) or ".", exist_ok=True)
        gen_sort_data(args.train, n_lines=args.num_examples,
                      min_len=args.seq_len, max_len=args.seq_len,
                      vocab_size=args.vocab_size, seed=0)
        gen_sort_data(args.valid, n_lines=args.num_examples // 10,
                      min_len=args.seq_len, max_len=args.seq_len,
                      vocab_size=args.vocab_size, seed=1)

    vocab = default_build_vocab(args.train)
    num_lstm_layer = 2

    init_states = [("l%d_init_%s" % (l, s),
                    (args.batch_size, args.num_hidden))
                   for l in range(num_lstm_layer) for s in "ch"]
    data_train = BucketSentenceIter(args.train, vocab, [], args.batch_size,
                                    init_states)
    data_val = BucketSentenceIter(args.valid, vocab, [], args.batch_size,
                                  init_states)

    def sym_gen(seq_len):
        return bi_lstm_unroll(seq_len, len(vocab),
                              num_hidden=args.num_hidden,
                              num_embed=args.num_embed,
                              num_label=len(vocab))

    buckets = data_train.buckets
    symbol = sym_gen(buckets[0]) if len(buckets) == 1 else sym_gen

    model = mx.model.FeedForward(
        ctx=[mx.cpu(0)], symbol=symbol, num_epoch=args.num_epochs,
        learning_rate=args.lr, momentum=args.momentum, wd=0.00001,
        initializer=mx.init.Xavier(factor_type="in", magnitude=2.34))
    model.fit(X=data_train, eval_data=data_val,
              eval_metric=mx.metric.np(Perplexity),
              batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                         50))
    model.save(args.model_prefix)

    # exact-match sort accuracy over the validation buckets.  The label
    # reaches SoftmaxOutput through transpose+reshape, so shape
    # inference needs the label shape — bind explicitly per bucket.
    correct = total = 0
    exes = {}
    data_val.reset()
    for batch in data_val:
        data = batch.data[0].asnumpy()
        truth = batch.label[0].asnumpy()
        seq_len = batch.bucket_key
        if seq_len not in exes:
            exe = sym_gen(seq_len).simple_bind(
                mx.cpu(), grad_req="null",
                data=(args.batch_size, seq_len),
                softmax_label=(args.batch_size, seq_len),
                **{n: s for n, s in init_states})
            exe.copy_params_from(model.arg_params, model.aux_params)
            exes[seq_len] = exe
        exe = exes[seq_len]
        exe.arg_dict["data"][:] = data
        probs = exe.forward(is_train=False)[0].asnumpy()
        # predictions come back time-major flattened: (seq*batch, vocab)
        pred = probs.argmax(axis=1).reshape(seq_len, len(data)).T
        correct += int((pred == truth).all(axis=1).sum())
        total += len(data)
    if total:
        print("exact-sort accuracy: %.3f" % (correct / total))


if __name__ == "__main__":
    main()
