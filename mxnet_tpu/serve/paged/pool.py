"""KVBlockPool: a device-resident pool of fixed-size KV-cache blocks.

The dense decode discipline (decode.py) gives every slot a state row
padded to max context, so device memory scales with
``num_slots * max_context`` even when most streams are short.  The pool
breaks that coupling: K/V live in ``num_blocks`` fixed-size blocks of
``block_tokens`` tokens each (``MXNET_KVPOOL_BLOCKS`` /
``MXNET_KVPOOL_BLOCK_TOKENS``), and each slot maps its logical context
onto physical blocks through a per-slot **page table** row.  Memory now
scales with the *live token count*, not with worst-case context.

Allocation discipline — exact reservation, lazy assignment:

* at admission the engine **reserves** the stream's worst-case block
  count (prompt + max_new tokens are both known at submit), so an
  admitted stream can never deadlock mid-generation waiting for blocks
  — the pool either has room for the whole stream or admission queues;
* physical blocks are **assigned lazily** as tokens actually land, so
  reserved-but-unused tail blocks of short streams never occupy
  physical pages... they do count against the reservation budget,
  which is what makes admission exact rather than optimistic;
* ``release`` returns a finished slot's blocks and its remaining
  reservation in one step.

Unassigned page-table entries hold the **sentinel** ``num_blocks`` — a
*positive* out-of-range index: device scatters use ``mode='drop'`` and
gathers clamp, so a sentinel can never silently wrap to block -1 the
way a negative index would (`.at[]` wraps negatives; see the PR 12
embedding-engine bug class).

Views: the target and draft models share ONE allocator and ONE page
table (a stream's logical block i is the same physical block id in
both), each with its own K/V arrays — ``add_view`` per model.  Shared
addressing is what lets speculative decode run the draft against the
same page table the target verifies through.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ...base import get_env, make_lock
from ..errors import ServeError

__all__ = ["KVBlockPool"]


class _View:
    """One model's K/V arrays over the shared block space:
    (layers, num_blocks + 1, block_tokens, heads, head_dim) — the +1 row
    is the sentinel block, a scatter/gather scratch page that no slot
    ever reads through the page table."""

    __slots__ = ("name", "kv_k", "kv_v")

    def __init__(self, name, kv_k, kv_v):
        self.name = name
        self.kv_k = kv_k
        self.kv_v = kv_v


class KVBlockPool:
    """Block allocator + page tables for ``num_slots`` decode slots.

    Parameters
    ----------
    num_slots : int
        Page-table rows (one per engine slot).
    max_blocks_per_slot : int
        Page-table row width: ``ceil(max_context / block_tokens)``.
    num_blocks / block_tokens : int, optional
        Pool geometry (``MXNET_KVPOOL_BLOCKS`` — default
        ``num_slots * max_blocks_per_slot``, i.e. dense-equivalent —
        and ``MXNET_KVPOOL_BLOCK_TOKENS``, default 16).
    dense : bool
        Dense mode: every slot statically owns its own full
        ``max_blocks_per_slot`` stripe (requires the dense-equivalent
        pool size).  This reproduces the dense DecodeEngine's
        max-context-per-slot layout through the same page-table code
        path — the bitwise parity baseline for the paged engine.
    """

    def __init__(self, num_slots: int, max_blocks_per_slot: int,
                 num_blocks=None, block_tokens=None, dense: bool = False):
        if block_tokens is None:
            block_tokens = get_env("MXNET_KVPOOL_BLOCK_TOKENS", 16, int)
        self.block_tokens = int(block_tokens)
        if self.block_tokens < 1:
            raise ServeError("block_tokens must be >= 1, got %d"
                             % self.block_tokens)
        self.num_slots = int(num_slots)
        self.max_blocks_per_slot = int(max_blocks_per_slot)
        dense_blocks = self.num_slots * self.max_blocks_per_slot
        if num_blocks is None:
            num_blocks = get_env("MXNET_KVPOOL_BLOCKS", dense_blocks, int)
        self.num_blocks = int(num_blocks)
        if self.num_blocks < self.max_blocks_per_slot:
            raise ServeError(
                "num_blocks %d cannot hold even one max-context stream "
                "(%d blocks)" % (self.num_blocks, self.max_blocks_per_slot))
        self.dense = bool(dense)
        if self.dense and self.num_blocks < dense_blocks:
            raise ServeError(
                "dense mode needs num_blocks >= num_slots * "
                "max_blocks_per_slot (%d), got %d"
                % (dense_blocks, self.num_blocks))
        self.sentinel = self.num_blocks
        self._lock = make_lock("serve.kvpool")
        self._views: Dict[str, _View] = {}
        # host page tables; shipped to device each step (tiny int32)
        self._pages = np.full((self.num_slots, self.max_blocks_per_slot),
                              self.sentinel, np.int32)
        self._free: List[int] = list(range(self.num_blocks))
        self._avail = self.num_blocks      # blocks not reserved
        self._reserved = [0] * self.num_slots
        self._assigned = [0] * self.num_slots
        if self.dense:
            # static full-stripe ownership: the page table is fixed for
            # the life of the pool, reservations always succeed
            for s in range(self.num_slots):
                lo = s * self.max_blocks_per_slot
                self._pages[s] = np.arange(
                    lo, lo + self.max_blocks_per_slot, dtype=np.int32)
            self._free = []
            self._avail = 0

    # -- device arrays -----------------------------------------------------
    def add_view(self, name: str, layers: int, heads: int, head_dim: int,
                 dtype=None) -> None:
        """Allocate one model's K/V arrays over the block space (the +1
        sentinel block absorbs dropped scatters)."""
        import jax.numpy as jnp
        if name in self._views:
            raise ServeError("kv view %r already exists" % name)
        shape = (int(layers), self.num_blocks + 1, self.block_tokens,
                 int(heads), int(head_dim))
        z = jnp.zeros(shape, dtype or jnp.float32)
        self._views[name] = _View(name, z, z)

    def view(self, name: str) -> Tuple:
        v = self._views[name]
        return v.kv_k, v.kv_v

    def set_view(self, name: str, kv_k, kv_v) -> None:
        v = self._views[name]
        v.kv_k, v.kv_v = kv_k, kv_v

    def device_bytes(self) -> int:
        return sum(int(v.kv_k.nbytes) + int(v.kv_v.nbytes)
                   for v in self._views.values())

    # -- allocation --------------------------------------------------------
    def blocks_for(self, tokens: int) -> int:
        return -(-int(tokens) // self.block_tokens)

    def can_reserve(self, n_blocks: int) -> bool:
        if self.dense:
            return n_blocks <= self.max_blocks_per_slot
        with self._lock:
            return n_blocks <= self._avail

    def reserve(self, slot: int, n_blocks: int) -> bool:
        """Reserve a stream's worst-case blocks for ``slot``; False when
        the pool cannot hold them (the caller keeps the request
        queued)."""
        if n_blocks > self.max_blocks_per_slot:
            raise ServeError(
                "reservation %d exceeds max_blocks_per_slot %d"
                % (n_blocks, self.max_blocks_per_slot))
        if self.dense:
            return True
        with self._lock:
            if self._reserved[slot]:
                raise ServeError("slot %d already holds a reservation"
                                 % slot)
            if n_blocks > self._avail:
                return False
            self._avail -= n_blocks
            self._reserved[slot] = n_blocks
            return True

    def ensure(self, slot: int, tokens: int) -> None:
        """Assign physical blocks so ``slot`` can hold ``tokens`` total
        tokens.  Always within the reservation — a failure here is an
        engine accounting bug, not load."""
        need = self.blocks_for(tokens)
        if self.dense:
            if need > self.max_blocks_per_slot:
                raise ServeError(
                    "slot %d needs %d blocks > stripe %d"
                    % (slot, need, self.max_blocks_per_slot))
            return
        with self._lock:
            if need > self._reserved[slot]:
                raise ServeError(
                    "slot %d needs %d blocks but reserved only %d"
                    % (slot, need, self._reserved[slot]))
            while self._assigned[slot] < need:
                blk = self._free.pop()
                self._pages[slot, self._assigned[slot]] = blk
                self._assigned[slot] += 1

    def release(self, slot: int) -> None:
        """Return ``slot``'s assigned blocks and drop its remaining
        reservation (stream finished or failed)."""
        if self.dense:
            return
        with self._lock:
            n = self._assigned[slot]
            for i in range(n):
                self._free.append(int(self._pages[slot, i]))
            self._pages[slot, :] = self.sentinel
            self._avail += self._reserved[slot]
            self._reserved[slot] = 0
            self._assigned[slot] = 0

    def available_blocks(self) -> int:
        """Blocks not yet reserved — the admission budget.  Dense mode
        returns the pool size: every slot statically owns a stripe, so
        any per-stream reservation (<= max_blocks_per_slot) fits."""
        if self.dense:
            return self.num_blocks
        with self._lock:
            return self._avail

    # -- introspection -----------------------------------------------------
    def page_table(self) -> np.ndarray:
        """The live (num_slots, max_blocks_per_slot) int32 page table
        (the engine ships a snapshot to device each step)."""
        return self._pages

    def used_blocks(self) -> int:
        with self._lock:
            if self.dense:
                return self.num_blocks
            return self.num_blocks - len(self._free)

    def reserved_blocks(self) -> int:
        with self._lock:
            if self.dense:
                return self.num_blocks
            return self.num_blocks - self._avail

    def utilization(self) -> float:
        return self.used_blocks() / float(self.num_blocks)
