"""Module-API walkthrough (reference example/module/mnist_mlp.py capability):
high-level fit, the manual bind/init/forward/backward/update loop, and
checkpoint save/resume — all three drive the same fused XLA train program.
"""
import argparse
import logging
import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from mxnet_tpu.models import get_mlp


def make_data(batch_size):
    rng = np.random.RandomState(0)
    means = 2.0 * rng.randn(10, 784).astype(np.float32)
    y = rng.randint(0, 10, size=6000)
    x = means[y] + rng.randn(6000, 784).astype(np.float32)
    y = y.astype(np.float32)
    return (mx.io.NDArrayIter(x[:5000], y[:5000], batch_size=batch_size,
                              shuffle=True),
            mx.io.NDArrayIter(x[5000:], y[5000:], batch_size=batch_size))


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--num-epochs", type=int, default=3)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    train, val = make_data(args.batch_size)
    net = get_mlp()

    # 1) high-level fit
    mod = mx.mod.Module(net, context=[mx.cpu()])
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            initializer=mx.init.Xavier(), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    acc = mx.metric.Accuracy()
    mod.score(val, acc)
    print("fit accuracy: %.3f" % acc.get()[1])

    # 2) the same loop written out by hand
    train.reset()
    mod2 = mx.mod.Module(net, context=[mx.cpu()])
    mod2.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod2.init_params(mx.init.Xavier())
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.1,
                                          "momentum": 0.9})
    metric = mx.metric.Accuracy()
    for epoch in range(args.num_epochs):
        train.reset()
        metric.reset()
        for batch in train:
            mod2.forward(batch, is_train=True)
            mod2.update_metric(metric, batch.label)
            mod2.backward()
            mod2.update()
        print("manual epoch %d, train %s=%.3f" % ((epoch,) + metric.get()))

    # 3) checkpoint + resume
    prefix = os.path.join(tempfile.mkdtemp(), "mnist_mlp")
    arg_params, aux_params = mod2.get_params()
    mx.model.save_checkpoint(prefix, args.num_epochs, net,
                             arg_params, aux_params)
    _, loaded_args, loaded_aux = mx.model.load_checkpoint(
        prefix, args.num_epochs)
    mod3 = mx.mod.Module(net, context=[mx.cpu()])
    mod3.bind(data_shapes=train.provide_data,
              label_shapes=train.provide_label)
    mod3.set_params(loaded_args, loaded_aux)
    acc = mx.metric.Accuracy()
    mod3.score(val, acc)
    print("resumed accuracy: %.3f" % acc.get()[1])
    assert acc.get()[1] > 0.8


if __name__ == "__main__":
    main()
