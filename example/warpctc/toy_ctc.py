"""CTC sequence labelling (reference example/warpctc/{toy_ctc.py,lstm_ocr.py}
capability): an LSTM reads a T-step sequence and WarpCTC aligns the
unsegmented label string.  The CTC loss/grad run inside the fused XLA
program (optax.ctc_loss under custom_vjp) — no warp-ctc CUDA kernel needed.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
import mxnet_tpu.plugins.warpctc  # registers sym.WarpCTC
from mxnet_tpu.models.lstm import lstm_cell, LSTMState, LSTMParam


def ctc_net(seq_len, num_hidden, num_classes, batch_size):
    """LSTM over seq_len steps -> per-step class scores -> WarpCTC."""
    data = mx.sym.Variable("data")            # (batch, seq_len, feat)
    label = mx.sym.Variable("label")          # (batch, num_label) 0-padded
    steps = mx.sym.SliceChannel(data, num_outputs=seq_len, axis=1,
                                squeeze_axis=True)
    param = LSTMParam(i2h_weight=mx.sym.Variable("i2h_weight"),
                      i2h_bias=mx.sym.Variable("i2h_bias"),
                      h2h_weight=mx.sym.Variable("h2h_weight"),
                      h2h_bias=mx.sym.Variable("h2h_bias"))
    state = LSTMState(c=mx.sym.Variable("init_c"),
                      h=mx.sym.Variable("init_h"))
    cls_weight = mx.sym.Variable("cls_weight")
    cls_bias = mx.sym.Variable("cls_bias")
    outs = []
    for t in range(seq_len):
        state = lstm_cell(num_hidden, indata=steps[t], prev_state=state,
                          param=param, seqidx=t, layeridx=0)
        outs.append(mx.sym.FullyConnected(
            state.h, weight=cls_weight, bias=cls_bias,
            num_hidden=num_classes, name="t%d_cls" % t))
    # WarpCTC wants (T*B, A) activations, time-major
    pred = mx.sym.Concat(*[mx.sym.Reshape(o, shape=(1, batch_size, num_classes))
                           for o in outs], dim=0)
    pred = mx.sym.Reshape(pred, shape=(seq_len * batch_size, num_classes))
    return mx.sym.WarpCTC(data=pred, label=label, label_length=4,
                          input_length=seq_len, name="ctc")


def make_data(n, seq_len, num_label, num_classes, seed=0):
    """Each 'digit' of the label paints a distinctive feature block."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(1, num_classes, size=(n, num_label))
    feat = np.zeros((n, seq_len, num_classes), np.float32)
    for i in range(n):
        # place each label token in order, 2 frames per token
        for j, tok in enumerate(labels[i]):
            feat[i, 2 * j:2 * j + 2, tok] = 4.0
    feat += 0.3 * rng.randn(*feat.shape).astype(np.float32)
    return feat, labels.astype(np.float32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--seq-len", type=int, default=10)
    parser.add_argument("--num-label", type=int, default=4)
    parser.add_argument("--num-classes", type=int, default=6)
    parser.add_argument("--num-hidden", type=int, default=64)
    parser.add_argument("--num-epochs", type=int, default=8)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    feat, labels = make_data(1024, args.seq_len, args.num_label,
                             args.num_classes)
    bs = args.batch_size
    iter_data = {
        "data": feat,
        "init_c": np.zeros((len(feat), args.num_hidden), np.float32),
        "init_h": np.zeros((len(feat), args.num_hidden), np.float32),
    }
    train = mx.io.NDArrayIter(iter_data, {"label": labels}, batch_size=bs,
                              shuffle=True)
    net = ctc_net(args.seq_len, args.num_hidden, args.num_classes, bs)
    mod = mx.mod.Module(net, context=[mx.cpu()],
                        data_names=("data", "init_c", "init_h"),
                        label_names=("label",))
    mod.fit(train, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 1e-2},
            eval_metric=mx.metric.Torch())

    # greedy CTC decode on one batch: collapse repeats, drop blanks
    train.reset()
    batch = next(iter(train))
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0].asnumpy()      # (T*B, A) softmax
    T, B = args.seq_len, bs
    path = out.reshape(T, B, -1).argmax(axis=2)   # (T, B)
    correct = 0
    truth = batch.label[0].asnumpy().astype(int)
    for b in range(B):
        seq, prev = [], -1
        for t in range(T):
            tok = path[t, b]
            if tok != prev and tok != 0:
                seq.append(tok)
            prev = tok
        if seq == [t for t in truth[b] if t != 0]:
            correct += 1
    print("exact-decode accuracy: %.3f" % (correct / B))


if __name__ == "__main__":
    main()
