# LSTM builders (reference R-package/R/lstm.R): the gated cell unrolled
# through the shared rnn.R graph helper.  State is (c, h); gates come
# from one fused 4x projection whose weights are created ONCE and
# composed into every timestep (op names time-distinct, params shared —
# the same layout mxnet_tpu/models/lstm.py uses).

mx.lstm.param <- function(param.prefix, layeridx = 0) {
  nm <- function(part) sprintf("%s_l%d_%s", param.prefix, layeridx, part)
  list(i2h.w = mx.symbol.Variable(nm("i2h_weight")),
       i2h.b = mx.symbol.Variable(nm("i2h_bias")),
       h2h.w = mx.symbol.Variable(nm("h2h_weight")),
       h2h.b = mx.symbol.Variable(nm("h2h_bias")))
}

mx.lstm.cell <- function(num.hidden, indata, prev.state, param,
                         param.prefix, layeridx = 0, seqidx = 0) {
  nm <- function(part) sprintf("%s_l%d_%s_t%d", param.prefix, layeridx,
                               part, seqidx)
  i2h <- mx.symbol.internal.create("FullyConnected", list(
    data = indata, weight = param$i2h.w, bias = param$i2h.b,
    num_hidden = num.hidden * 4, name = nm("i2h")))
  h2h <- mx.symbol.internal.create("FullyConnected", list(
    data = prev.state$h, weight = param$h2h.w, bias = param$h2h.b,
    num_hidden = num.hidden * 4, name = nm("h2h")))
  gates <- mx.symbol.internal.create("ElementWiseSum", list(
    i2h, h2h, name = nm("gates")))
  sliced <- mx.symbol.internal.create("SliceChannel", list(
    data = gates, num_outputs = 4, axis = 1, name = nm("slice")))
  act <- function(i, type, part) {
    mx.symbol.internal.create("Activation", list(
      data = .mx.symbol.pick(sliced, i), act_type = type,
      name = nm(part)))
  }
  in.gate <- act(0, "sigmoid", "i")
  in.trans <- act(1, "tanh", "g")
  forget.gate <- act(2, "sigmoid", "f")
  out.gate <- act(3, "sigmoid", "o")
  next.c <- (forget.gate * prev.state$c) + (in.gate * in.trans)
  tanh.c <- mx.symbol.internal.create("Activation", list(
    data = next.c, act_type = "tanh", name = nm("tc")))
  list(c = next.c, h = out.gate * tanh.c)
}

mx.lstm <- function(seq.len, num.hidden, num.label) {
  param <- mx.lstm.param("lstm")
  data <- mx.symbol.Variable("data")
  slices <- mx.symbol.internal.create("SliceChannel", list(
    data = data, num_outputs = seq.len, axis = 1, name = "lstm_slice"))
  state <- list(c = mx.symbol.Variable("lstm_init_c"),
                h = mx.symbol.Variable("lstm_init_h"))
  for (t in seq_len(seq.len)) {
    xt <- mx.symbol.internal.create("Flatten", list(
      data = .mx.symbol.pick(slices, t - 1),
      name = sprintf("lstm_flat_t%d", t)))
    state <- mx.lstm.cell(num.hidden, xt, state, param, "lstm",
                          seqidx = t)
  }
  fc <- mx.symbol.internal.create("FullyConnected", list(
    data = state$h, num_hidden = num.label, name = "lstm_cls"))
  mx.symbol.internal.create("SoftmaxOutput", list(
    data = fc, name = "softmax"))
}
