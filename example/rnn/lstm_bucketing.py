"""PTB LSTM with bucketing (reference example/rnn/lstm_bucketing.py
capability).  Uses BucketingModule: one jit-compiled program per bucket
length, parameters shared."""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from mxnet_tpu.models import lstm_unroll
from bucket_io import BucketSentenceIter, default_build_vocab


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data", type=str, default="ptb.train.txt")
    parser.add_argument("--tpus", type=str)
    parser.add_argument("--num-hidden", type=int, default=200)
    parser.add_argument("--num-embed", type=int, default=200)
    parser.add_argument("--num-lstm-layer", type=int, default=2)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--lr", type=float, default=0.1)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    buckets = [10, 20, 30, 40]
    vocab = default_build_vocab(args.data)
    init_states = [("l%d_init_c" % l, (args.batch_size, args.num_hidden))
                   for l in range(args.num_lstm_layer)] + \
                  [("l%d_init_h" % l, (args.batch_size, args.num_hidden))
                   for l in range(args.num_lstm_layer)]
    data_train = BucketSentenceIter(args.data, vocab, buckets,
                                    args.batch_size, init_states)

    def sym_gen(seq_len):
        sym = lstm_unroll(args.num_lstm_layer, seq_len, len(vocab),
                          args.num_hidden, args.num_embed, len(vocab))
        data_names = ["data"] + [n for n, _ in init_states]
        return (sym, tuple(data_names), ("softmax_label",))

    ctx = [mx.tpu(int(i)) for i in args.tpus.split(",")] if args.tpus \
        else [mx.cpu()]
    mod = mx.mod.BucketingModule(sym_gen,
                                 default_bucket_key=data_train.default_bucket_key,
                                 context=ctx)
    # compile every bucket's program before the hot loop: no mid-epoch
    # XLA-compile stalls when a new sequence length first appears
    mod.bind(data_shapes=data_train.provide_data,
             label_shapes=data_train.provide_label)
    mod.init_params()
    mod.prepare(data_train.provide_bucket_shapes())
    mod.fit(data_train, num_epoch=args.num_epochs,
            eval_metric="ce",
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 50),
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                              "wd": 1e-5})


if __name__ == "__main__":
    main()
