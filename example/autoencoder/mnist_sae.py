"""Train the stacked autoencoder on (synthetic) MNIST.

Capability parity with reference example/autoencoder/mnist_sae.py:1:
784-500-500-2000-10 SAE with layerwise pretraining, finetuning,
save/load round-trip, and train/val reconstruction error.  Iteration
counts and layer widths are CLI-scalable so the same script serves CI.
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx

import data
from autoencoder import AutoEncoderModel


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--dims", type=int, nargs="+",
                        default=[784, 500, 500, 2000, 10])
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--pretrain-iters", type=int, default=50000)
    parser.add_argument("--finetune-iters", type=int, default=100000)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--lr-step", type=int, default=20000)
    parser.add_argument("--num-examples", type=int, default=70000)
    parser.add_argument("--save", default="mnist_pt.arg")
    args = parser.parse_args()
    logging.basicConfig(level=logging.DEBUG)

    ae_model = AutoEncoderModel(mx.cpu(), args.dims, pt_dropout=0.2,
                                internal_act="relu", output_act="relu")

    X, _ = data.get_mnist(n=args.num_examples)
    cut = int(len(X) * 6 / 7)
    train_X, val_X = X[:cut], X[cut:]

    ae_model.layerwise_pretrain(
        train_X, args.batch_size, args.pretrain_iters, "sgd",
        l_rate=args.lr, decay=0.0,
        lr_scheduler=mx.lr_scheduler.FactorScheduler(args.lr_step, 0.1))
    ae_model.finetune(
        train_X, args.batch_size, args.finetune_iters, "sgd",
        l_rate=args.lr, decay=0.0,
        lr_scheduler=mx.lr_scheduler.FactorScheduler(args.lr_step, 0.1))
    ae_model.save(args.save)
    ae_model.load(args.save)
    train_err = ae_model.eval(train_X)
    val_err = ae_model.eval(val_X)
    print("Training error: %.6f" % train_err)
    print("Validation error: %.6f" % val_err)


if __name__ == "__main__":
    main()
