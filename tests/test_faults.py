"""mxnet_tpu.faults: the seeded chaos suite (ISSUE 15, tier-1).

Covers the three layers end to end:

* the **plane** — deterministic seeded schedules (same seed => same
  faults, attempt folding changes them), every kind (error/delay/torn;
  crash is exercised by the subprocess legs), point/stage filtering,
  ``after``/``max`` budgets, env-spec parsing, ``fault:`` trace
  instants, the profiler report, and near-zero disabled cost;
* **retry** — Backoff determinism/reset/interruptible sleep,
  RestartWindow sliding expiry, retry_call semantics;
* **supervisor + recovery** — restart-until-success with backoff,
  give-up budget, hang watchdog; and THE acceptance scenario: a
  fit + checkpoint + 2-process ParallelReader run under a schedule
  that SIGKILLs a reader worker, tears a shard write, and kills the
  committer mid-protocol across two attempts — the supervised run's
  final committed state is BITWISE identical to a fault-free run
  (params, optimizer state, RNG, feed cursor);
* **self-healing serve** — a router flood under injected dispatch
  faults completes with zero dropped requests while replicas trip and
  probe back in; a crash-looping reader worker burns its sliding
  restart window through Backoff waits instead of hot-spinning, with
  the parent responsive throughout.
"""
import os
import signal
import subprocess
import sys
import time
from concurrent.futures import Future

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu import faults, feed, recordio
from mxnet_tpu import trace as mtrace
from mxnet_tpu.base import MXNetError
from mxnet_tpu.faults import (Backoff, FaultPlan, InjectedFault,
                              RestartWindow, Rule, retry_call)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_plan():
    yield
    faults.clear()


# -- the plane ---------------------------------------------------------------

def _fire_pattern(plan, n=60, point="x.y"):
    return [plan.decide(point, {}) is not None for _ in range(n)]


def test_schedule_is_deterministic_and_seed_sensitive():
    mk = lambda s: FaultPlan([Rule(rate=0.25, kinds="error")], seed=s)
    assert _fire_pattern(mk(7)) == _fire_pattern(mk(7))
    assert _fire_pattern(mk(7)) != _fire_pattern(mk(8))
    # distinct points draw from distinct streams
    p = mk(7)
    assert _fire_pattern(p, point="a.b") != _fire_pattern(p, point="c.d")


def test_attempt_folds_into_the_stream(monkeypatch):
    monkeypatch.setenv("MXNET_FAULTS_ATTEMPT", "0")
    p0 = _fire_pattern(FaultPlan([Rule(rate=0.25)], seed=7))
    monkeypatch.setenv("MXNET_FAULTS_ATTEMPT", "1")
    p1 = _fire_pattern(FaultPlan([Rule(rate=0.25)], seed=7))
    assert p0 != p1


def test_error_kind_raises_and_traces_and_counts():
    faults.install("seed=1,rate=1,kinds=error,points=t.err")
    before = len(mtrace.instant_events(prefix="fault:t.err"))
    with pytest.raises(InjectedFault, match="t.err"):
        faults.point("t.err", step=3)
    faults.point("other.point")          # filtered: silent
    evs = mtrace.instant_events(prefix="fault:t.err")
    assert len(evs) == before + 1
    assert evs[-1]["args"]["kind"] == "error"
    assert evs[-1]["args"]["step"] == 3
    rep = mx.profiler.faults_report()
    plane_rows = [r for r in rep.values() if r.get("kind") == "plane"]
    assert plane_rows and plane_rows[0]["by_point"].get("t.err", 0) >= 1


def test_delay_kind_sleeps_then_continues():
    faults.install(FaultPlan([Rule(points="t.slow", kinds="delay",
                                   delay_s=0.05)]))
    t0 = time.perf_counter()
    faults.point("t.slow")               # no raise
    assert time.perf_counter() - t0 >= 0.045


def test_torn_kind_truncates_the_path_then_raises(tmp_path):
    victim = tmp_path / "shard.npy"
    victim.write_bytes(b"x" * 1000)
    faults.install(FaultPlan([Rule(points="t.write", kinds="torn")]))
    with pytest.raises(InjectedFault, match="torn"):
        faults.point("t.write", path=str(victim))
    assert victim.stat().st_size == 500


def test_stage_filter_after_and_max():
    faults.install(FaultPlan([Rule(points="c.commit@rename", kinds="error",
                                   after=1, max_faults=1)], seed=3))
    faults.point("c.commit", stage="shards")      # wrong stage
    faults.point("c.commit", stage="rename")      # 1st eligible: after=1
    with pytest.raises(InjectedFault):
        faults.point("c.commit", stage="rename")  # 2nd: fires
    faults.point("c.commit", stage="rename")      # max=1 spent


def test_env_spec_parse_and_reject():
    plan = faults.parse_spec(
        "seed=9,rate=0.5,kinds=crash|delay,points=a.b|c.d@s,max=2,"
        "after=3,attempts=0|2,delay_ms=5")
    r = plan.rules[0]
    assert plan.seed == 9 and r.rate == 0.5
    assert r.kinds == ("crash", "delay")
    assert r.points == [("a.b", None), ("c.d", "s")]
    assert r.max_faults == 2 and r.after == 3
    assert r.attempts == {0, 2} and abs(r.delay_s - 0.005) < 1e-9
    with pytest.raises(MXNetError, match="unknown key"):
        faults.parse_spec("rate=1,bogus=2")
    with pytest.raises(MXNetError, match="unknown fault kind"):
        faults.parse_spec("kinds=meteor")


def test_env_spec_installs_at_import():
    """A process born with MXNET_FAULTS set has the plan armed before
    any user code runs — forked readers and supervisor children
    inherit chaos schedules with zero wiring."""
    code = ("import mxnet_tpu as mx, sys; "
            "sys.exit(0 if mx.faults.enabled() "
            "and mx.faults.attempt() == 3 else 1)")
    r = subprocess.run(
        [sys.executable, "-c", code], cwd=ROOT, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 MXNET_FAULTS="seed=5,rate=0,kinds=error",
                 MXNET_FAULTS_ATTEMPT="3"))
    assert r.returncode == 0


def test_disabled_point_is_effectively_free():
    faults.clear()
    t0 = time.perf_counter()
    for _ in range(200_000):
        faults.point("hot.path")
    dt = time.perf_counter() - t0
    # one `is None` check + kwargs-free call: generous ceiling, the
    # real number is tens of ns — the bench leg reports the fraction
    assert dt < 1.0, "disabled faults.point too slow: %.1fus/call" \
        % (dt / 200_000 * 1e6)


# -- retry primitives --------------------------------------------------------

def test_backoff_deterministic_caps_and_reset():
    b = Backoff(base_s=0.1, factor=2.0, max_s=0.8, jitter=0.5, seed=4)
    seq = [b.next_wait() for _ in range(8)]
    b.reset()
    assert seq == [b.next_wait() for _ in range(8)]
    for i, w in enumerate(seq):
        raw = min(0.1 * 2.0 ** i, 0.8)
        assert raw * 0.5 <= w <= raw * 1.5
    assert Backoff(base_s=0.1, jitter=0.0, seed=1).next_wait() == 0.1


def test_backoff_sleep_is_interruptible():
    b = Backoff(base_s=5.0, jitter=0.0)
    stop = {"v": False}
    t0 = time.perf_counter()
    import threading
    threading.Timer(0.1, lambda: stop.update(v=True)).start()
    b.sleep(should_stop=lambda: stop["v"], poll_s=0.01)
    assert time.perf_counter() - t0 < 1.0   # nowhere near 5s


def test_restart_window_slides():
    rw = RestartWindow(2, window_s=0.15)
    assert rw.note() == 1 and rw.note() == 2
    assert not rw.exceeded()
    assert rw.note() == 3 and rw.exceeded()
    time.sleep(0.2)
    assert rw.count() == 0 and not rw.exceeded()
    assert rw.total == 3


def test_retry_call_budget_and_reraise():
    calls = {"n": 0}

    def flaky(limit):
        calls["n"] += 1
        if calls["n"] < limit:
            raise ValueError("flake %d" % calls["n"])
        return "ok"

    b = Backoff(base_s=0.001, jitter=0.0)
    assert retry_call(flaky, 3, retries=5, backoff=b) == "ok"
    assert calls["n"] == 3
    calls["n"] = 0
    with pytest.raises(ValueError, match="flake 3"):
        retry_call(flaky, 99, retries=2,
                   backoff=Backoff(base_s=0.001, jitter=0.0))
    with pytest.raises(KeyError):     # not in retry_on: no retry
        retry_call(lambda: {}[0], retries=3, retry_on=(ValueError,))


# -- supervisor --------------------------------------------------------------

_CHILD_RC_BY_ATTEMPT = ("import os, sys; "
                        "a = int(os.environ['MXNET_FAULTS_ATTEMPT']); "
                        "sys.exit(0 if a >= %d else 1)")


def _sup(argv, **kw):
    kw.setdefault("backoff", Backoff(base_s=0.01, jitter=0.0))
    return faults.Supervisor(argv, **kw)


def test_supervisor_restarts_until_success():
    sup = _sup([sys.executable, "-c", _CHILD_RC_BY_ATTEMPT % 2],
               max_restarts=5)
    assert sup.run() == 0
    r = sup.stats.report()
    assert r["attempts"] == 3 and r["restarts"] == 2
    assert r["backoff_wait_s"] > 0 and r["last_rc"] == 0
    assert not r["gave_up"]


def test_supervisor_gives_up_after_budget():
    sup = _sup([sys.executable, "-c", "import sys; sys.exit(3)"],
               max_restarts=1)
    with pytest.raises(MXNetError, match="restart budget"):
        sup.run()
    r = sup.stats.report()
    assert r["gave_up"] and r["attempts"] == 2 and r["last_rc"] == 3


def test_supervisor_watchdog_kills_a_hang():
    sup = _sup([sys.executable, "-c", "import time; time.sleep(60)"],
               max_restarts=0, timeout_s=0.5)
    t0 = time.perf_counter()
    with pytest.raises(MXNetError, match="restart budget"):
        sup.run()
    assert time.perf_counter() - t0 < 10.0
    assert sup.stats.report()["last_rc"] == -9


# -- THE chaos acceptance: supervised fit resumes bitwise --------------------

_CHAOS_FIT = """
import os, sys
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import faults, feed

rec, store, markers = sys.argv[1], sys.argv[2], sys.argv[3]

def once(name):
    # cross-attempt (and cross-worker-process) exactly-once: O_EXCL
    # creation is atomic and the marker survives the crash
    try:
        os.close(os.open(os.path.join(markers, name),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        return True
    except FileExistsError:
        return False

faults.install(faults.FaultPlan([
    # SIGKILL one reader worker mid-epoch-0 (exactly once across the
    # whole chaos run): the refork must re-enter the stream exactly
    faults.Rule(points="feed.worker_decode", kinds="crash",
                when=lambda ctx: ctx["epoch"] == 0 and ctx["seq"] == 3
                and once("worker_kill")),
    # attempt 0: tear a shard file of an early save -> the async
    # writer dies, fit crashes, the supervisor restarts it
    faults.Rule(points="storage.write", kinds="torn", attempts=[0],
                after=6, max_faults=1),
    # attempt 1: SIGKILL between shards-written and rename on its FIRST
    # commit (attempt 0's torn save surfaces at the NEXT submit, so
    # attempt 1 resumes late in the run with one save left) -> torn tmp
    # wreckage on disk that discovery and attempt 2 must skip
    faults.Rule(points="checkpoint.commit@shards_written", kinds="crash",
                attempts=[1], max_faults=1),
], seed=7))

mx.random.seed(123)
it = feed.record_pipeline(rec, 8, (3, 8, 8), reader_procs=2,
                          shuffle_window=4, seed=5, scale=1.0 / 255,
                          max_epochs=8, to_device=False,
                          device_augment=False)
d = mx.sym.Variable("data")
n = mx.sym.FullyConnected(mx.sym.Flatten(d), num_hidden=4, name="fc")
net = mx.sym.SoftmaxOutput(n, name="softmax")
init = {"fc_weight": mx.nd.array(
    np.random.RandomState(7).uniform(-0.05, 0.05, (4, 192))
    .astype(np.float32)), "fc_bias": mx.nd.zeros((4,))}
m = mx.mod.Module(net, context=mx.cpu(0))
m.fit(it, num_epoch=2, arg_params=init,
      optimizer="sgd",
      optimizer_params=(("learning_rate", 0.05), ("momentum", 0.9)),
      checkpoint=store, checkpoint_every=3, resume=True)
it.close()
sys.exit(0)
"""


def _write_rec(path, n=32, shape=(3, 8, 8)):
    rng = np.random.RandomState(0)
    w = recordio.MXRecordIO(str(path), "w")
    for i in range(n):
        arr = rng.randint(0, 255, shape).astype(np.uint8)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 4), i, 0),
                              arr.tobytes()))
    w.close()
    return str(path)


def _tree_equal(a, b, path=""):
    if isinstance(a, dict):
        assert isinstance(b, dict) and set(a) == set(b), path
        for k in a:
            _tree_equal(a[k], b[k], path + "/" + str(k))
        return
    if isinstance(a, (list, tuple)):
        assert len(a) == len(b), path
        for i, (x, y) in enumerate(zip(a, b)):
            _tree_equal(x, y, "%s[%d]" % (path, i))
        return
    if a is None:
        assert b is None, path
        return
    assert np.array_equal(np.asarray(a), np.asarray(b)), \
        "mismatch at %s" % path


def test_chaos_fit_supervised_recovery_is_bitwise(tmp_path):
    """The ISSUE 15 acceptance scenario: one seeded schedule SIGKILLs a
    reader worker, tears a checkpoint shard write (attempt 0), and
    SIGKILLs the committer mid-protocol (attempt 1); the supervisor
    restarts the job from the latest committed step each time, and the
    final committed train state — params, momentum slots, RNG, feed
    cursor — is bitwise identical to an uninterrupted run."""
    from mxnet_tpu import checkpoint as ck
    rec = _write_rec(tmp_path / "chaos.rec")

    # fault-free reference, in-process (same seeds/pipeline/config)
    ref_store = str(tmp_path / "ck_ref")
    mx.random.seed(123)
    it = feed.record_pipeline(rec, 8, (3, 8, 8), reader_procs=2,
                              shuffle_window=4, seed=5, scale=1.0 / 255,
                              max_epochs=8, to_device=False,
                              device_augment=False)
    d = mx.sym.Variable("data")
    n = mx.sym.FullyConnected(mx.sym.Flatten(d), num_hidden=4, name="fc")
    net = mx.sym.SoftmaxOutput(n, name="softmax")
    init = {"fc_weight": mx.nd.array(
        np.random.RandomState(7).uniform(-0.05, 0.05, (4, 192))
        .astype(np.float32)), "fc_bias": mx.nd.zeros((4,))}
    m = mx.mod.Module(net, context=mx.cpu(0))
    m.fit(it, num_epoch=2, arg_params=init, optimizer="sgd",
          optimizer_params=(("learning_rate", 0.05), ("momentum", 0.9)),
          checkpoint=ref_store, checkpoint_every=3)
    it.close()

    # chaos run under the supervisor (argv children: fresh jax runtime
    # per attempt, the production shape)
    script = tmp_path / "chaos_child.py"
    script.write_text(_CHAOS_FIT % {"root": ROOT})
    store = str(tmp_path / "ck_chaos")
    markers = tmp_path / "markers"
    markers.mkdir()
    env = {"JAX_PLATFORMS": "cpu"}
    sup = faults.Supervisor(
        [sys.executable, str(script), rec, store, str(markers)],
        max_restarts=4, backoff=Backoff(base_s=0.05, jitter=0.0),
        timeout_s=180.0, checkpoint_dir=store, env=env, name="chaos-fit")
    assert sup.run() == 0
    r = sup.stats.report()
    # attempt 0 died (torn shard write), attempt 1 died (crash mid-
    # commit), attempt 2 finished: exactly two supervised recoveries
    assert r["restarts"] == 2, r
    assert r["recovery_s"] > 0 and r["last_recovery_s"] > 0
    assert os.path.exists(markers / "worker_kill")   # the SIGKILL fired

    ref_mgr = ck.CheckpointManager(ref_store, keep_last_n=None)
    chaos_mgr = ck.CheckpointManager(store, keep_last_n=None)
    try:
        assert ref_mgr.latest_step() == chaos_mgr.latest_step() == 8
        ref_tree, ref_meta = ref_mgr.restore()
        chaos_tree, chaos_meta = chaos_mgr.restore()
        _tree_equal(ref_tree, chaos_tree)
        for k in ("global_step", "epoch", "nbatch", "feed"):
            assert ref_meta.get(k) == chaos_meta.get(k), k
    finally:
        ref_mgr.close()
        chaos_mgr.close()


# -- self-healing serve under chaos ------------------------------------------

class _ChaosReplica:
    """Fake replica whose dispatch rides the REAL serve.dispatch fault
    point — injected faults surface exactly like a broken engine."""

    def __init__(self, index):
        self.index = index

    def submit(self, data, deadline_ms=None, **kw):
        fut = Future()
        try:
            faults.point("serve.dispatch", replica=self.index)
        except InjectedFault as e:
            fut.set_exception(e)
            return fut
        fut.set_result(np.asarray(data, np.float32) + 1.0)
        return fut

    def pending_requests(self):
        return 0

    def outstanding(self):
        return 0

    def close(self, drain=True):
        pass


def test_router_chaos_flood_zero_dropped():
    """A 300-request flood against 3 replicas while the fault plane
    fails ~12%% of dispatches: replicas trip, the breaker probes them
    back in, the retry budget absorbs every injected failure — ZERO
    dropped requests, every answer correct."""
    from mxnet_tpu.serve import ServeRouter
    faults.install("seed=11,rate=0.12,kinds=error,points=serve.dispatch")
    # budget sized for the injected rate: this seed's stream contains a
    # 4-deep failure run, and the router must be configured to survive
    # the chaos it is asked to survive (retries=3 drops exactly one)
    router = ServeRouter(lambda i: _ChaosReplica(i), replicas=3,
                         unhealthy_after=4, retries=5,
                         probe_after_s=0.02, name="chaos-flood")
    dropped = 0
    try:
        x = np.arange(4, dtype=np.float32)
        for i in range(300):
            try:
                out = router.submit(x).result(timeout=30)
                assert np.array_equal(out, x + 1.0)
            except Exception:
                dropped += 1
            if i % 50 == 49:
                time.sleep(0.03)    # let probe timers breathe
        assert dropped == 0
        r = router.stats.report()
        assert r["retried"] >= 1          # injected faults were absorbed
        plane = [row for row in mx.profiler.faults_report().values()
                 if row.get("kind") == "plane"][0]
        assert plane["by_point"].get("serve.dispatch", 0) >= 10
        if r["downs"]:                    # tripped replicas healed
            assert r["reinstated"] >= 1 or \
                "down" not in router.replica_states()
    finally:
        router.close()


def test_reader_crash_loop_burns_window_with_backoff(tmp_path):
    """A decode bug that kills the worker instantly must not hot-loop
    the fork spinner: each refork waits out the seeded Backoff, the
    sliding window (MXNET_FEED_MAX_RESTARTS) bounds the attempts, the
    parent raises a crash-loop error and stays responsive (close
    returns promptly)."""
    rec = _write_rec(tmp_path / "loop.rec", n=12, shape=(3, 4, 4))

    def suicide_decode(item):
        os.kill(os.getpid(), signal.SIGKILL)

    reader = feed.ParallelReader(rec, suicide_decode, workers=1,
                                 sample_shape=(3, 4, 4),
                                 sample_dtype=np.float32,
                                 max_restarts=2, seed=3, name="loop")
    pipe = feed.Pipeline([reader, feed.BatchStage(4)], name="looppipe")
    it = feed.FeedDataIter(pipe, (3, 4, 4), 4)
    t0 = time.perf_counter()
    with pytest.raises(MXNetError, match="crash loop"):
        it.next()
    waited = time.perf_counter() - t0
    # two reforks waited ~0.05 and ~0.1s (jitter 0.25): the loop is
    # paced, not hot
    assert waited >= 0.08, waited
    assert reader.restarts[0] >= 2
    t1 = time.perf_counter()
    it.close()
    assert time.perf_counter() - t1 < 5.0


def test_fault_points_add_no_steady_loop_compiles(tmp_path):
    """MXNET_FAULTS armed (rate=0: plan installed, never fires) must
    not perturb the fused step: the points are host-side — zero new
    steady-loop compiles, bit-identical dispatch path."""
    from compile_guard import assert_no_compiles
    rng = np.random.RandomState(0)
    X = rng.rand(64, 10).astype(np.float32)
    y = rng.randint(0, 3, 64).astype(np.float32)
    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                              name="fc"), name="softmax")
    m = mx.mod.Module(net, context=mx.cpu(0))
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    m.fit(it, num_epoch=1, optimizer_params=(("learning_rate", 0.05),))
    kv = mx.kvstore.create("local")
    kv.init(0, mx.nd.zeros((3,)))
    faults.install("rate=0,kinds=error")
    with assert_no_compiles("fused loop with fault plane armed"):
        it.reset()
        for batch in it:
            m.forward_backward(batch)
            m.update()
            kv.push(0, mx.nd.ones((3,)))   # the kvstore.push point
    faults.clear()


def test_faults_in_unified_report():
    faults.install("rate=0")
    rep = mx.profiler.unified_report()
    assert "faults" in rep
    assert "fault plane" in mx.profiler.faults_report_str()


def test_fork_mode_child_keeps_programmatic_plan():
    """ISSUE 15 review: fork-mode children used to WIPE a
    programmatically installed plan (reload_from_env cleared it when
    MXNET_FAULTS was unset) — an attempts-targeted chaos schedule then
    silently tested nothing.  The fork child must keep the inherited
    plan with only the attempt index refreshed."""
    faults.install(FaultPlan([Rule(points="fork.pt", kinds="error",
                                   attempts=[1])], seed=5))

    def target():
        # jax-free target: plane + numpy only, safe to fork
        try:
            faults.point("fork.pt")
        except InjectedFault:
            return 0 if faults.attempt() == 1 else 9
        return 1    # not injected: attempt 0 by schedule -> "crash"

    sup = faults.Supervisor(target, max_restarts=3,
                            backoff=Backoff(base_s=0.01, jitter=0.0),
                            name="fork-plan")
    assert sup.run() == 0                   # attempt 1 DID inject
    assert sup.stats.report()["restarts"] == 1


def test_supervisor_stop_interrupts_backoff_and_child():
    """stop() from another thread cuts the backoff wait short and
    kills the running child — run() returns without further
    restarts."""
    import threading
    sup = _sup([sys.executable, "-c", "import time; time.sleep(60)"],
               max_restarts=5,
               backoff=Backoff(base_s=30.0, jitter=0.0))
    threading.Timer(0.3, sup.stop).start()
    t0 = time.perf_counter()
    rc = sup.run()
    assert time.perf_counter() - t0 < 20.0
    assert rc == -9 and sup.stats.report()["restarts"] == 0
