"""mxnet_tpu.faults: deterministic fault injection + elastic recovery.

The robustness plane (ISSUE 15).  Three layers, smallest first:

* **retry** (retry.py) — :class:`Backoff` (jittered exponential,
  deterministic seeded jitter, interruptible sleep),
  :class:`RestartWindow` (sliding-window restart budgets) and
  :func:`retry_call`: THE retry primitive for the repo.  Bare
  sleep-in-a-loop retries are a lint error (``raw-retry``).
* **plane** (plane.py) — named fault points at the recovery seams
  (``checkpoint.commit``, ``storage.write``, ``feed.worker_decode``,
  ``serve.dispatch``, ``decode.step``, ``kvstore.push``) driven by a
  seeded schedule (``MXNET_FAULTS="seed=7,rate=0.02,kinds=crash|torn|
  delay|error"``): any chaos run is exactly reproducible, every
  injected fault is a ``fault:`` instant in the PR 8 timeline, a
  disabled plane costs one ``is None`` check per point.
* **supervisor** (supervisor.py) — run training under a watchdog:
  crash/preemption/hang -> bounded, backed-off restart from the latest
  committed checkpoint, with the feed cursor making the recovered
  stream bitwise identical to a fault-free run.

``mx.profiler.faults_report()`` aggregates plane + supervisor counters.
See docs/robustness.md for the fault-point catalog and workflows.
"""
from __future__ import annotations

from .plane import (KINDS, FaultPlan, FaultStats, InjectedFault, Rule,
                    active, attempt, clear, enabled, install, parse_spec,
                    point, refresh_attempt, reload_from_env, stats)
from .retry import Backoff, RestartWindow, retry_call
from .supervisor import Supervisor, SupervisorStats

__all__ = ["point", "install", "clear", "active", "enabled", "attempt",
           "parse_spec", "reload_from_env", "refresh_attempt", "stats",
           "KINDS",
           "FaultPlan", "FaultStats", "InjectedFault", "Rule",
           "Backoff", "RestartWindow", "retry_call",
           "Supervisor", "SupervisorStats"]
