"""DCGAN training (reference example/gan/dcgan.py capability).

Generator and discriminator trained adversarially with the Module API;
the generator gradient comes from the discriminator's input grads
(inputs_need_grad=True), exactly the reference flow.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from mxnet_tpu.models.dcgan import make_generator, make_discriminator
from mxnet_tpu.io import DataBatch


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--tpus", type=str)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--code-dim", type=int, default=100)
    parser.add_argument("--num-iters", type=int, default=200)
    parser.add_argument("--lr", type=float, default=0.0002)
    parser.add_argument("--image-size", type=int, default=64)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    ctx = [mx.tpu(int(i)) for i in args.tpus.split(",")] if args.tpus \
        else [mx.cpu()]
    bs = args.batch_size

    gen = mx.mod.Module(make_generator(code_dim=args.code_dim),
                        data_names=("rand",), label_names=None, context=ctx)
    gen.bind(data_shapes=[("rand", (bs, args.code_dim, 1, 1))],
             label_shapes=None, for_training=True, inputs_need_grad=False)
    gen.init_params(mx.init.Normal(0.02))
    gen.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": args.lr,
                                         "beta1": 0.5})

    disc = mx.mod.Module(make_discriminator(),
                         data_names=("data",), label_names=("label",),
                         context=ctx)
    disc.bind(data_shapes=[("data", (bs, 3, args.image_size, args.image_size))],
              label_shapes=[("label", (bs,))],
              for_training=True, inputs_need_grad=True)
    disc.init_params(mx.init.Normal(0.02))
    disc.init_optimizer(optimizer="adam",
                        optimizer_params={"learning_rate": args.lr,
                                          "beta1": 0.5})

    rng = np.random.RandomState(0)
    for it in range(args.num_iters):
        # synthetic "real" data stand-in; plug an ImageRecordIter here
        real = rng.rand(bs, 3, args.image_size, args.image_size).astype("f") * 2 - 1
        z = rng.randn(bs, args.code_dim, 1, 1).astype("f")

        # G forward
        gen.forward(DataBatch(data=[mx.nd.array(z)], label=[]), is_train=True)
        fake = gen.get_outputs()[0]

        # D on fake (label 0), backprop into inputs
        disc.forward(DataBatch(data=[fake], label=[mx.nd.zeros((bs,))]),
                     is_train=True)
        disc.backward()
        grad_d_fake = [[g.copy() for g in grads]
                       for grads in disc._exec_group.grad_arrays]
        # D on real (label 1)
        disc.forward(DataBatch(data=[mx.nd.array(real)],
                               label=[mx.nd.ones((bs,))]), is_train=True)
        disc.backward()
        # accumulate D grads (fake + real) then update
        for gw, gf in zip(disc._exec_group.grad_arrays, grad_d_fake):
            for a, b in zip(gw, gf):
                if a is not None:
                    a[:] = a + b
        disc.update()

        # G step: D(fake) with label 1, take input grads back through G
        disc.forward(DataBatch(data=[fake], label=[mx.nd.ones((bs,))]),
                     is_train=True)
        disc.backward()
        diff = disc.get_input_grads()[0]
        gen.backward([diff])
        gen.update()

        if it % 20 == 0:
            d_out = disc.get_outputs()[0].asnumpy()
            logging.info("iter %d  D(G(z))=%.3f", it, d_out.mean())


if __name__ == "__main__":
    main()
