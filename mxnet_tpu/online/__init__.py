"""mxnet_tpu.online — continuous training on live serve traffic
(ISSUE 17).

The closed loop the system papers promise: the same substrate trains
and serves, and models move from learner to server continuously ::

    serve --> capture --> replay --> fine-tune --> gate --> promote
      ^                                                        |
      +------------- rolling_restart (zero drops) -------------+

* :mod:`capture`  — sampled request/response capture at the router
  seam into crash-tolerant SEALED shards (``ServeRouter(capture=w)``).
* :mod:`replay`   — sealed shards back into a ``FeedDataIter`` whose
  checkpointed cursor resumes exactly.
* :mod:`trainer`  — ``OnlineTrainer``: cumulative ``Module.fit``
  rounds against one checkpoint store, Supervisor-restartable bitwise.
* :mod:`promote`  — ``PromotionGate`` (held-out quality + drift) and
  the zero-drop ``rolling_restart`` promotion, with embed-table
  freshness carried forward.

Every stage rides the fault plane (``online.capture@seal``,
``online.train@round``, ``online.promote@decide/restart/record``), and
the whole loop is chaos-acceptance-tested: torn capture shard, worker
SIGKILL mid-fit, crash mid-promotion — the promoted weights stay
bitwise equal to a fault-free run.  See docs/online.md.
"""
from . import capture
from . import replay
from . import trainer
from . import promote

from .capture import (CaptureWriter, is_sealed, sealed_shards,
                      shard_path, seal_path)
from .replay import (UnsealedShardError, load_shard, replay_pipeline,
                     replay_source)
from .trainer import OnlineTrainer
from .promote import (PromotionGate, freshen_embed, promote as
                      promote_checkpoint, quarantine, read_record,
                      PROMOTED_RECORD, QUARANTINED_RECORD)

__all__ = [
    "capture", "replay", "trainer", "promote",
    "CaptureWriter", "is_sealed", "sealed_shards", "shard_path",
    "seal_path",
    "UnsealedShardError", "load_shard", "replay_pipeline",
    "replay_source",
    "OnlineTrainer",
    "PromotionGate", "freshen_embed", "promote_checkpoint",
    "quarantine", "read_record", "PROMOTED_RECORD",
    "QUARANTINED_RECORD",
]
