"""Feed-forward style-transfer generators (reference
example/neural-style/end_to_end/gen_v3.py / gen_v4.py; Johnson et al.
2016): conv-BN-LeakyReLU downsampling, deconv upsampling back to image
resolution, tanh output scaled to pixel range.  One forward pass
stylizes an image — no per-image optimization loop."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                "..", "..", ".."))
import mxnet_tpu as mx


def _conv(data, nf, name, kernel=(5, 5), stride=(2, 2), pad=(2, 2)):
    body = mx.sym.Convolution(data, num_filter=nf, kernel=kernel,
                              stride=stride, pad=pad, name=name + "_conv")
    body = mx.sym.BatchNorm(body, fix_gamma=False, name=name + "_bn")
    return mx.sym.LeakyReLU(body, act_type="leaky", name=name + "_act")


def _deconv(data, nf, name, kernel=(6, 6), stride=(2, 2), pad=(2, 2),
            out=False):
    body = mx.sym.Deconvolution(data, num_filter=nf, kernel=kernel,
                                stride=stride, pad=pad, no_bias=True,
                                name=name + "_deconv")
    body = mx.sym.BatchNorm(body, fix_gamma=False, name=name + "_bn")
    if out:
        # tanh -> pixel range, as the reference's output head
        return mx.sym.Activation(body, act_type="tanh", name=name + "_tanh")
    return mx.sym.LeakyReLU(body, act_type="leaky", name=name + "_act")


def generator_v3(prefix="g3"):
    """3-down/3-up encoder-decoder (reference gen_v3)."""
    data = mx.sym.Variable("data")
    body = _conv(data, 32, prefix + "_c1")
    body = _conv(body, 64, prefix + "_c2")
    body = _conv(body, 128, prefix + "_c3")
    body = _deconv(body, 64, prefix + "_d1")
    body = _deconv(body, 32, prefix + "_d2")
    out = _deconv(body, 3, prefix + "_d3", out=True)
    # [-1, 1] -> [0, 255]-ish pixel range
    return out * 127.0 + 128.0


def generator_v4(prefix="g4"):
    """v3 plus a stride-1 refinement stage and a residual-style skip
    from the input (reference gen_v4's deeper variant)."""
    data = mx.sym.Variable("data")
    body = _conv(data, 32, prefix + "_c1")
    body = _conv(body, 64, prefix + "_c2")
    body = _conv(body, 128, prefix + "_c3")
    body = _deconv(body, 64, prefix + "_d1")
    body = _deconv(body, 32, prefix + "_d2")
    body = _deconv(body, 16, prefix + "_d3")
    body = _conv(body, 16, prefix + "_r1", kernel=(3, 3), stride=(1, 1),
                 pad=(1, 1))
    raw = mx.sym.Convolution(body, num_filter=3, kernel=(3, 3),
                             stride=(1, 1), pad=(1, 1),
                             name=prefix + "_out_conv")
    out = mx.sym.Activation(raw, act_type="tanh", name=prefix + "_tanh")
    # residual around the input keeps colors anchored to the content
    return out * 127.0 + data * 0.5 + 64.0
