"""One-pass stylization with a trained generator (reference
end_to_end/boost_inference.py): load the generator checkpoint, forward
images through it, write the stylized result — no optimization loop.

    python boost_inference.py --model-prefix /tmp/style_gen --epoch 4 \
        --out /tmp/styled.npy [--image photo.jpg]
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                "..", "..", ".."))
import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix", required=True)
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--size", type=int, default=64)
    ap.add_argument("--image", help="input image (needs Pillow); omitted "
                    "= a synthetic test image")
    ap.add_argument("--out", default="/tmp/styled.npy")
    args = ap.parse_args()

    net, arg_p, aux_p = mx.model.load_checkpoint(args.model_prefix,
                                                 args.epoch)
    if args.image:
        from PIL import Image
        img = Image.open(args.image).convert("RGB").resize(
            (args.size, args.size))
        data = np.asarray(img, np.float32).transpose(2, 0, 1)[None]
    else:
        rng = np.random.RandomState(1)
        from boost_train import synthetic_content
        data = synthetic_content(rng, 1, args.size)

    mod = mx.mod.Module(net, data_names=["data"], label_names=[],
                        context=mx.current_context())
    mod.bind([("data", (1, 3, args.size, args.size))], None,
             for_training=False)
    mod.init_params(arg_params=arg_p, aux_params=aux_p, allow_missing=True)
    mod.forward(mx.io.DataBatch(data=[mx.nd.array(data)], label=[]),
                is_train=False)
    styled = mod.get_outputs()[0].asnumpy()
    np.save(args.out, styled)
    print("styled image %s -> %s (range %.1f..%.1f)"
          % (styled.shape, args.out, styled.min(), styled.max()))
    print("BOOST-INFERENCE-OK")


if __name__ == "__main__":
    main()
