// Native threaded batch loader: the TPU-native equivalent of the reference's
// C++ IO stack (src/io/iter_image_recordio.cc ImageRecordIOParser with N OMP
// decode threads + iter_normalize.h + iter_batchloader.h + iter_prefetcher.h).
//
// Pipeline: RecordFile index -> worker threads decode raw CHW payloads and
// apply crop/mirror/mean/scale -> completed float32 batches land in a bounded
// double-buffer queue -> python (ctypes) copies a batch out and hands it to
// jax.device_put (PJRT's async H2D replaces the reference's copy workers).
//
// Exposed as a C ABI (ctypes; no pybind11 in this image).
#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#include "recordio.h"

namespace mxtpu {

struct Batch {
  std::vector<float> data;
  std::vector<float> label;
  int pad = 0;
};

class BatchLoader {
 public:
  BatchLoader(const char* path, int batch, int c, int h, int w,
              int label_width, int threads, int shuffle, int rand_crop,
              int rand_mirror, const float* mean_rgb, float scale,
              int part_index, int num_parts, int seed, int queue_depth)
      : batch_(batch), c_(c), h_(h), w_(w), label_width_(label_width),
        shuffle_(shuffle), rand_crop_(rand_crop), rand_mirror_(rand_mirror),
        scale_(scale), queue_depth_(queue_depth), rng_(seed) {
    ok_ = rec_.Open(path);
    if (!ok_) return;
    if (mean_rgb) {
      mean_[0] = mean_rgb[0]; mean_[1] = mean_rgb[1]; mean_[2] = mean_rgb[2];
      has_mean_ = true;
    }
    size_t n = rec_.size();
    size_t shard = num_parts > 1 ? n / num_parts : n;
    size_t begin = num_parts > 1 ? shard * part_index : 0;
    for (size_t i = begin; i < begin + shard && i < n; ++i)
      order_.push_back(i);
    n_threads_ = threads > 0 ? threads : 4;
    Reset();
  }

  ~BatchLoader() { Stop(); }

  bool ok() const { return ok_; }
  size_t num_records() const { return order_.size(); }

  void Reset() {
    Stop();
    if (shuffle_) {
      std::shuffle(order_.begin(), order_.end(), rng_);
    }
    cursor_.store(0);
    eof_produced_.store(false);
    stop_.store(false);
    for (int i = 0; i < n_threads_; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  // Returns 0 and fills data/label on success; 1 at end of epoch.
  int Next(float* data, float* label, int* pad) {
    std::unique_lock<std::mutex> lk(mu_);
    not_empty_.wait(lk, [this] {
      return !queue_.empty() || (eof_produced_.load() && in_flight_ == 0);
    });
    if (queue_.empty()) return 1;
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    lk.unlock();
    not_full_.notify_all();
    memcpy(data, b.data.data(), b.data.size() * sizeof(float));
    memcpy(label, b.label.data(), b.label.size() * sizeof(float));
    *pad = b.pad;
    return 0;
  }

 private:
  void Stop() {
    stop_.store(true);
    not_full_.notify_all();
    not_empty_.notify_all();
    for (auto& t : workers_) t.join();
    workers_.clear();
    queue_.clear();
    in_flight_ = 0;
  }

  void DecodeInto(size_t rec_idx, float* out, float* label_out,
                  std::mt19937* rng) {
    ImageRecord r;
    if (!rec_.Get(order_[rec_idx % order_.size()], &r)) return;
    // raw-packed payload: uint8 CHW at source resolution (>= target)
    size_t want = static_cast<size_t>(c_) * h_ * w_;
    int src_h = h_, src_w = w_;
    if (r.payload_size > want) {
      // payload stores uint16 src_h, src_w prefix when larger than target
      // (im2rec --resize writes exact size, so this is the uncommon path)
      src_h = r.payload[0] | (r.payload[1] << 8);
      src_w = r.payload[2] | (r.payload[3] << 8);
    }
    const uint8_t* px = r.payload;
    size_t header = (r.payload_size > want) ? 4 : 0;
    int dy = 0, dx = 0;
    if (src_h > h_ || src_w > w_) {
      if (rand_crop_) {
        dy = (*rng)() % (src_h - h_ + 1);
        dx = (*rng)() % (src_w - w_ + 1);
      } else {
        dy = (src_h - h_) / 2;
        dx = (src_w - w_) / 2;
      }
    }
    bool mirror = rand_mirror_ && ((*rng)() & 1);
    for (int ch = 0; ch < c_; ++ch) {
      float mean = has_mean_ ? mean_[ch % 3] : 0.f;
      for (int y = 0; y < h_; ++y) {
        const uint8_t* row =
            px + header + (static_cast<size_t>(ch) * src_h + y + dy) * src_w + dx;
        float* dst = out + (static_cast<size_t>(ch) * h_ + y) * w_;
        if (!mirror) {
          for (int x = 0; x < w_; ++x)
            dst[x] = (static_cast<float>(row[x]) - mean) * scale_;
        } else {
          for (int x = 0; x < w_; ++x)
            dst[x] = (static_cast<float>(row[w_ - 1 - x]) - mean) * scale_;
        }
      }
    }
    for (int l = 0; l < label_width_; ++l)
      label_out[l] = l < static_cast<int>(r.labels.size()) ? r.labels[l] : 0.f;
  }

  void WorkerLoop() {
    std::mt19937 rng(rng_());
    const size_t n = order_.size();
    const size_t img_sz = static_cast<size_t>(c_) * h_ * w_;
    while (!stop_.load()) {
      size_t start = cursor_.fetch_add(batch_);
      if (start >= n) {
        eof_produced_.store(true);
        not_empty_.notify_all();
        return;
      }
      {
        std::unique_lock<std::mutex> lk(mu_);
        not_full_.wait(lk, [this] {
          return static_cast<int>(queue_.size()) + in_flight_ < queue_depth_
                 || stop_.load();
        });
        if (stop_.load()) return;
        ++in_flight_;
      }
      Batch b;
      b.data.resize(static_cast<size_t>(batch_) * img_sz);
      b.label.resize(static_cast<size_t>(batch_) * label_width_);
      b.pad = start + batch_ > n ? static_cast<int>(start + batch_ - n) : 0;
      for (int i = 0; i < batch_; ++i) {
        DecodeInto(start + i, b.data.data() + i * img_sz,
                   b.label.data() + i * label_width_, &rng);
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        queue_.push_back(std::move(b));
        --in_flight_;
      }
      not_empty_.notify_one();
    }
  }

  RecordFile rec_;
  std::vector<size_t> order_;
  int batch_, c_, h_, w_, label_width_;
  int shuffle_, rand_crop_, rand_mirror_;
  float scale_;
  float mean_[3] = {0, 0, 0};
  bool has_mean_ = false;
  bool ok_ = false;
  int n_threads_ = 4;
  int queue_depth_;
  std::mt19937 rng_;

  std::vector<std::thread> workers_;
  std::deque<Batch> queue_;
  int in_flight_ = 0;
  std::mutex mu_;
  std::condition_variable not_empty_, not_full_;
  std::atomic<size_t> cursor_{0};
  std::atomic<bool> eof_produced_{false};
  std::atomic<bool> stop_{false};
};

}  // namespace mxtpu

extern "C" {

void* mxtpu_loader_create(const char* path, int batch, int c, int h, int w,
                          int label_width, int threads, int shuffle,
                          int rand_crop, int rand_mirror,
                          const float* mean_rgb, float scale, int part_index,
                          int num_parts, int seed, int queue_depth) {
  auto* l = new mxtpu::BatchLoader(path, batch, c, h, w, label_width, threads,
                                   shuffle, rand_crop, rand_mirror, mean_rgb,
                                   scale, part_index, num_parts, seed,
                                   queue_depth > 0 ? queue_depth : 4);
  if (!l->ok()) {
    delete l;
    return nullptr;
  }
  return l;
}

long mxtpu_loader_num_records(void* handle) {
  return static_cast<long>(static_cast<mxtpu::BatchLoader*>(handle)->num_records());
}

int mxtpu_loader_next(void* handle, float* data, float* label, int* pad) {
  return static_cast<mxtpu::BatchLoader*>(handle)->Next(data, label, pad);
}

void mxtpu_loader_reset(void* handle) {
  static_cast<mxtpu::BatchLoader*>(handle)->Reset();
}

void mxtpu_loader_free(void* handle) {
  delete static_cast<mxtpu::BatchLoader*>(handle);
}

// ---- recordio writer (im2rec core) ----
void* mxtpu_writer_create(const char* path) {
  auto* w = new mxtpu::RecordWriter(path);
  if (!w->ok()) { delete w; return nullptr; }
  return w;
}

void mxtpu_writer_write_image(void* handle, float label, unsigned long id,
                              const unsigned char* payload, long len) {
  static_cast<mxtpu::RecordWriter*>(handle)->WriteImageRecord(
      label, id, payload, static_cast<size_t>(len));
}

void mxtpu_writer_write_raw(void* handle, const unsigned char* buf, long len) {
  static_cast<mxtpu::RecordWriter*>(handle)->Write(buf, static_cast<size_t>(len));
}

void mxtpu_writer_free(void* handle) {
  delete static_cast<mxtpu::RecordWriter*>(handle);
}

}  // extern "C"
