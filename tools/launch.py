#!/usr/bin/env python
"""Launch distributed jobs (reference tools/launch.py:27-70 capability,
re-designed for TPU).

The reference launched scheduler + server + worker processes over
ssh/mpi/sge/yarn via dmlc-tracker.  The TPU-native stack has NO server or
scheduler roles — every process is a worker participating in XLA collectives
(SURVEY §5.8).  This launcher covers:

* local  : fork N worker processes on this host (jax.distributed rendezvous
           via a local coordinator) — the analogue of the reference's local
           launcher used by tests/nightly/test_all.sh.
* ssh    : start one worker per host in a hostfile, pointing all of them at
           the rank-0 coordinator address.
* tpu-pod: on Cloud-TPU-style pods the runtime injects topology env vars and
           every host just runs the same command (documented passthrough).
"""
import argparse
import os
import signal
import subprocess
import sys


def local_launch(args, cmd):
    procs = []
    env = dict(os.environ)
    env["MXNET_TPU_COORDINATOR"] = "127.0.0.1:%d" % args.port
    env["MXNET_TPU_NUM_WORKERS"] = str(args.num_workers)
    for rank in range(args.num_workers):
        worker_env = dict(env)
        worker_env["MXNET_TPU_WORKER_ID"] = str(rank)
        # reference-compat aliases so ports of reference scripts work
        worker_env["DMLC_ROLE"] = "worker"
        worker_env["DMLC_NUM_WORKER"] = str(args.num_workers)
        procs.append(subprocess.Popen(cmd, shell=True, env=worker_env))
    code = 0
    try:
        for p in procs:
            code = p.wait() or code
    except KeyboardInterrupt:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        code = 1
    return code


def ssh_launch(args, cmd):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    hosts = hosts[:args.num_workers]
    coordinator = "%s:%d" % (hosts[0], args.port)
    procs = []
    for rank, host in enumerate(hosts):
        env = ("MXNET_TPU_COORDINATOR=%s MXNET_TPU_NUM_WORKERS=%d "
               "MXNET_TPU_WORKER_ID=%d" % (coordinator, len(hosts), rank))
        procs.append(subprocess.Popen(
            ["ssh", "-o", "StrictHostKeyChecking=no", host,
             "cd %s && %s %s" % (os.getcwd(), env, cmd)]))
    code = 0
    for p in procs:
        code = p.wait() or code
    return code


def main():
    parser = argparse.ArgumentParser(
        description="Launch a distributed job (TPU-native: workers only)")
    parser.add_argument("-n", "--num-workers", required=True, type=int,
                        help="number of worker processes")
    parser.add_argument("-s", "--num-servers", type=int, default=0,
                        help="accepted for reference compatibility; must be 0 "
                             "(no server role on TPU)")
    parser.add_argument("--launcher", type=str, default="local",
                        choices=["local", "ssh", "tpu-pod"])
    parser.add_argument("-H", "--hostfile", type=str,
                        help="hostfile for ssh launcher")
    parser.add_argument("--port", type=int, default=9091)
    parser.add_argument("command", nargs="+", help="command to launch")
    args = parser.parse_args()

    if args.num_servers:
        sys.stderr.write("warning: -s %d ignored — TPU kvstore has no server "
                         "processes (aggregation is an XLA collective)\n"
                         % args.num_servers)
    cmd = " ".join(args.command)
    if args.launcher == "local":
        sys.exit(local_launch(args, cmd))
    elif args.launcher == "ssh":
        sys.exit(ssh_launch(args, cmd))
    else:
        sys.stderr.write("tpu-pod: run the same command on every pod host; "
                         "the TPU runtime provides rendezvous.\n")
        sys.exit(subprocess.call(cmd, shell=True))


if __name__ == "__main__":
    main()
