"""Systematic per-op finite-difference gradient checks.

Reference model: tests/python/unittest/test_operator.py (1519 LoC) runs
check_numeric_gradient over every op family.  This file sweeps the whole
registry: each family gets FD-vs-autodiff agreement on tiny tensors, the
zero-gradient ops get exact-zero assertions, and the loss layers are checked
against their analytic backward definitions (reference softmax_output-inl.h,
regression_output-inl.h semantics: backward ignores head grads and emits
prediction - label).

Inputs are kept away from kinks (|x| bounded below for abs/relu/max, ties
separated for max-pool/reductions) so finite differences are well-defined.
"""
import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import numpy as np
import pytest

import mxnet_tpu as mx
from check_utils import reldiff, check_numeric_gradient

rng = np.random.RandomState(1234)


def _away_from(x, lo=0.15):
    """Push values away from 0 so kinked functions are locally smooth."""
    return np.where(np.abs(x) < lo, lo * np.sign(x) + (x == 0) * lo, x)


def _distinct(shape, lo=0.0, hi=1.0):
    """Random values with all-distinct entries (no max/min ties)."""
    n = int(np.prod(shape))
    vals = np.linspace(lo, hi, n, dtype=np.float32)
    return rng.permutation(vals).reshape(shape)


def _sym_grads(sym, location, grad_nodes=None, out_grads=None):
    """Bind, forward(train), backward; return grad dict."""
    shapes = {k: v.shape for k, v in location.items()}
    names = sym.list_arguments()
    grad_nodes = grad_nodes or list(location)
    req = {n: ("write" if n in grad_nodes else "null") for n in names}
    ex = sym.simple_bind(mx.current_context(), grad_req=req, **shapes)
    for k, v in location.items():
        ex.arg_dict[k][:] = np.asarray(v, np.float32)
    ex.forward(is_train=True)
    ex.backward(out_grads)
    return {k: ex.grad_dict[k].asnumpy() for k in grad_nodes}, \
        [o.asnumpy() for o in ex.outputs]


# ---------------------------------------------------------------- unary ----

SMOOTH_UNARY = {
    "exp": (lambda s: s.exp, -1.0, 1.0),
    "log": (lambda s: s.log, 0.3, 2.0),
    "sin": (lambda s: s.sin, -1.2, 1.2),
    "cos": (lambda s: s.cos, -1.2, 1.2),
    "sqrt": (lambda s: s.sqrt, 0.3, 2.0),
    "rsqrt": (lambda s: s.rsqrt, 0.3, 2.0),
    "square": (lambda s: s.square, -1.0, 1.0),
    "abs": (lambda s: s.abs, None, None),   # needs away-from-zero input
}


@pytest.mark.parametrize("name", sorted(SMOOTH_UNARY))
def test_unary_grad(name):
    get, lo, hi = SMOOTH_UNARY[name]
    x = mx.sym.Variable("x")
    if lo is None:
        data = _away_from(rng.uniform(-1, 1, (3, 4)).astype(np.float32))
    else:
        data = rng.uniform(lo, hi, (3, 4)).astype(np.float32)
    check_numeric_gradient(get(mx.sym)(x), {"x": data}, numeric_eps=1e-3)


@pytest.mark.parametrize("name", ["floor", "ceil", "round", "sign"])
def test_step_unary_zero_grad(name):
    """Piecewise-constant ops propagate exactly zero gradient
    (reference mshadow_op.h: floor/ceil/round/sign grad functors)."""
    x = mx.sym.Variable("x")
    sym = getattr(mx.sym, name)(x)
    data = rng.uniform(0.1, 0.9, (3, 4)).astype(np.float32) + 2.0
    grads, _ = _sym_grads(sym, {"x": data})
    assert np.all(grads["x"] == 0)


# --------------------------------------------------------------- binary ----

@pytest.mark.parametrize("name", ["plus", "minus", "mul", "div", "power",
                                  "maximum", "minimum"])
def test_binary_grad(name):
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = getattr(mx.sym, name)(a, b)
    if name == "power":
        av = rng.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
        bv = rng.uniform(-1.0, 2.0, (3, 4)).astype(np.float32)
    elif name in ("maximum", "minimum"):
        av = _distinct((3, 4), 0.0, 1.0)
        bv = _distinct((3, 4), 0.02, 1.02)  # offset grid: no exact ties
    else:
        av = rng.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
        bv = rng.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    check_numeric_gradient(sym, {"a": av, "b": bv}, numeric_eps=1e-3)


@pytest.mark.parametrize("name", ["plus_scalar", "minus_scalar",
                                  "rminus_scalar", "mul_scalar", "div_scalar",
                                  "rdiv_scalar", "power_scalar",
                                  "rpower_scalar", "maximum_scalar",
                                  "minimum_scalar"])
def test_scalar_op_grad(name):
    x = mx.sym.Variable("x")
    sym = getattr(mx.sym, name)(x, scalar=1.5)
    data = rng.uniform(0.5, 1.3, (3, 4)).astype(np.float32)
    if name in ("maximum_scalar", "minimum_scalar"):
        data = _distinct((3, 4), 0.8, 2.2)  # straddle 1.5 without touching it
        data = np.where(np.abs(data - 1.5) < 0.02, data + 0.05, data)
    check_numeric_gradient(sym, {"x": data}, numeric_eps=1e-3)


# ------------------------------------------------------------ broadcast ----

@pytest.mark.parametrize("name", ["broadcast_plus", "broadcast_minus",
                                  "broadcast_mul", "broadcast_div",
                                  "broadcast_power"])
def test_broadcast_binary_grad(name):
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = getattr(mx.sym, name)(a, b)
    av = rng.uniform(0.5, 2.0, (2, 3, 4)).astype(np.float32)
    bv = rng.uniform(0.5, 2.0, (2, 1, 4)).astype(np.float32)
    check_numeric_gradient(sym, {"a": av, "b": bv}, numeric_eps=1e-3)


def test_broadcast_axis_grad():
    x = mx.sym.Variable("x")
    sym = mx.sym.broadcast_axis(x, axis=1, size=4)
    check_numeric_gradient(
        sym, {"x": rng.uniform(0.5, 1.5, (2, 1, 3)).astype(np.float32)})


def test_broadcast_to_grad():
    x = mx.sym.Variable("x")
    sym = mx.sym.broadcast_to(x, shape=(2, 4, 3))
    check_numeric_gradient(
        sym, {"x": rng.uniform(0.5, 1.5, (2, 1, 3)).astype(np.float32)})


# ----------------------------------------------------------- reductions ----

@pytest.mark.parametrize("name,kwargs", [
    ("sum", {}),
    ("sum_axis", {"axis": 1}),
    ("max", {}),
    ("max_axis", {"axis": 1}),
    ("min", {}),
    ("min_axis", {"axis": 1}),
    ("norm", {}),
])
def test_reduction_grad(name, kwargs):
    x = mx.sym.Variable("x")
    sym = getattr(mx.sym, name)(x, **kwargs)
    data = _distinct((3, 4, 2), 0.5, 2.0)
    check_numeric_gradient(sym, {"x": data}, numeric_eps=1e-3)


# --------------------------------------------------------------- matrix ----

def test_dot_grad():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    check_numeric_gradient(mx.sym.dot(a, b), {
        "a": rng.uniform(-1, 1, (3, 4)).astype(np.float32),
        "b": rng.uniform(-1, 1, (4, 2)).astype(np.float32)})


def test_batch_dot_grad():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    check_numeric_gradient(mx.sym.batch_dot(a, b), {
        "a": rng.uniform(-1, 1, (2, 3, 4)).astype(np.float32),
        "b": rng.uniform(-1, 1, (2, 4, 2)).astype(np.float32)})


@pytest.mark.parametrize("make", [
    lambda x: mx.sym.transpose(x, axes=(1, 0, 2)),
    lambda x: mx.sym.expand_dims(x, axis=1),
    lambda x: mx.sym.slice_axis(x, axis=1, begin=1, end=3),
    lambda x: mx.sym.flip(x, axis=1),
    lambda x: mx.sym.SwapAxis(x, dim1=0, dim2=2),
    lambda x: mx.sym.Reshape(x, target_shape=(2, 12)),
    lambda x: mx.sym.Flatten(x),
])
def test_shape_op_grad(make):
    x = mx.sym.Variable("x")
    data = rng.uniform(0.5, 1.5, (2, 3, 4)).astype(np.float32)
    check_numeric_gradient(make(x), {"x": data})


def test_crop_simpleop_grad():
    x = mx.sym.Variable("x")
    sym = mx.sym.crop(x, begin=(0, 1, 1), end=(2, 3, 3))
    data = rng.uniform(0.5, 1.5, (2, 4, 4)).astype(np.float32)
    check_numeric_gradient(sym, {"x": data})


def test_concat_grad():
    a, b = mx.sym.Variable("a"), mx.sym.Variable("b")
    sym = mx.sym.Concat(a, b, num_args=2, dim=1)
    check_numeric_gradient(sym, {
        "a": rng.uniform(0.5, 1.5, (2, 2, 3)).astype(np.float32),
        "b": rng.uniform(0.5, 1.5, (2, 4, 3)).astype(np.float32)})


def test_slice_channel_grad():
    x = mx.sym.Variable("x")
    sym = mx.sym.SliceChannel(x, num_outputs=3)
    data = rng.uniform(0.5, 1.5, (2, 6)).astype(np.float32)
    # FD covers sum(outputs[0]); feed zero head grads to the other outputs
    check_numeric_gradient(sym[0], {"x": data})


def test_element_wise_sum_grad():
    a, b, c = (mx.sym.Variable(n) for n in "abc")
    sym = mx.sym.ElementWiseSum(a, b, c, num_args=3)
    loc = {n: rng.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
           for n in "abc"}
    check_numeric_gradient(sym, loc)


# ------------------------------------------------------------- nn layers ----

def test_fully_connected_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(x, num_hidden=5, name="fc")
    check_numeric_gradient(sym, {
        "data": rng.uniform(-1, 1, (4, 6)).astype(np.float32),
        "fc_weight": rng.uniform(-1, 1, (5, 6)).astype(np.float32),
        "fc_bias": rng.uniform(-1, 1, (5,)).astype(np.float32)})


def test_fully_connected_nobias_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.FullyConnected(x, num_hidden=5, no_bias=True, name="fc")
    check_numeric_gradient(sym, {
        "data": rng.uniform(-1, 1, (4, 6)).astype(np.float32),
        "fc_weight": rng.uniform(-1, 1, (5, 6)).astype(np.float32)})


@pytest.mark.parametrize("kwargs", [
    {"kernel": (3, 3), "num_filter": 2, "pad": (1, 1)},
    {"kernel": (2, 2), "num_filter": 2, "stride": (2, 2)},
    {"kernel": (3, 3), "num_filter": 4, "num_group": 2, "pad": (1, 1)},
])
def test_convolution_grad(kwargs):
    x = mx.sym.Variable("data")
    sym = mx.sym.Convolution(x, name="c", **kwargs)
    cin = 2 if kwargs.get("num_group", 1) == 1 else 4
    kh, kw = kwargs["kernel"]
    loc = {"data": rng.uniform(-1, 1, (2, cin, 6, 6)).astype(np.float32),
           "c_weight": rng.uniform(-0.5, 0.5,
                                   (kwargs["num_filter"],
                                    cin // kwargs.get("num_group", 1),
                                    kh, kw)).astype(np.float32),
           "c_bias": rng.uniform(-0.5, 0.5,
                                 (kwargs["num_filter"],)).astype(np.float32)}
    check_numeric_gradient(sym, loc, numeric_eps=1e-2, check_eps=0.08)


def test_deconvolution_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.Deconvolution(x, kernel=(3, 3), num_filter=2, stride=(2, 2),
                               pad=(1, 1), name="dc")
    loc = {"data": rng.uniform(-1, 1, (1, 2, 4, 4)).astype(np.float32),
           "dc_weight": rng.uniform(-0.5, 0.5, (2, 2, 3, 3)).astype(np.float32)}
    check_numeric_gradient(sym, loc, numeric_eps=1e-2, check_eps=0.08)


@pytest.mark.parametrize("pool_type", ["max", "avg", "sum"])
def test_pooling_grad(pool_type):
    x = mx.sym.Variable("data")
    sym = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2),
                         pool_type=pool_type)
    data = _distinct((1, 2, 4, 4), 0.0, 4.0)
    check_numeric_gradient(sym, {"data": data}, numeric_eps=1e-3)


def test_lrn_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.LRN(x, nsize=3)
    data = rng.uniform(0.5, 1.5, (1, 4, 3, 3)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data}, numeric_eps=1e-3)


def test_l2_normalization_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.L2Normalization(x)
    data = rng.uniform(0.5, 1.5, (3, 6)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data}, numeric_eps=1e-3)


@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "softrelu"])
def test_activation_grad(act):
    x = mx.sym.Variable("data")
    sym = mx.sym.Activation(x, act_type=act)
    data = rng.uniform(-2, 2, (3, 4)).astype(np.float32)
    if act == "relu":
        data = _away_from(data)
    check_numeric_gradient(sym, {"data": data}, numeric_eps=1e-3)


@pytest.mark.parametrize("act", ["leaky", "elu"])
def test_leaky_relu_grad(act):
    x = mx.sym.Variable("data")
    sym = mx.sym.LeakyReLU(x, act_type=act, slope=0.3)
    data = _away_from(rng.uniform(-2, 2, (3, 4)).astype(np.float32))
    check_numeric_gradient(sym, {"data": data}, numeric_eps=1e-3)


def test_prelu_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.LeakyReLU(x, act_type="prelu", name="pr")
    data = _away_from(rng.uniform(-2, 2, (3, 4)).astype(np.float32))
    gamma = rng.uniform(0.1, 0.4, (4,)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data, "pr_gamma": gamma},
                           numeric_eps=1e-3)


def test_softmax_activation_grad():
    # sum(softmax(x)) is constant, so weight the outputs to get a
    # non-degenerate objective before finite-differencing
    x = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    sym = mx.sym.mul(mx.sym.SoftmaxActivation(x), w)
    data = rng.uniform(-1, 1, (3, 5)).astype(np.float32)
    wv = rng.uniform(0.5, 1.5, (3, 5)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data, "w": wv},
                           grad_nodes=["data"], numeric_eps=1e-3)


def test_batchnorm_grad():
    # sum(BN(x)) is ~independent of data (normalization), so weight the
    # outputs to make the FD objective sensitive to every input
    x = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    sym = mx.sym.mul(mx.sym.BatchNorm(x, fix_gamma=False, name="bn"), w)
    loc = {"data": rng.uniform(-1, 1, (4, 3)).astype(np.float32),
           "bn_gamma": rng.uniform(0.5, 1.5, (3,)).astype(np.float32),
           "bn_beta": rng.uniform(-0.5, 0.5, (3,)).astype(np.float32),
           "w": rng.uniform(0.5, 1.5, (4, 3)).astype(np.float32)}
    aux = {"bn_moving_mean": np.zeros(3, np.float32),
           "bn_moving_var": np.ones(3, np.float32)}
    check_numeric_gradient(sym, loc, aux_states=aux, numeric_eps=1e-3,
                           check_eps=0.08, fd_is_train=True,
                           grad_nodes=["data", "bn_gamma", "bn_beta"])


def test_dropout_eval_identity_and_train_scale():
    """Eval mode is the identity; train mode zeroes with keep-scale
    (reference dropout-inl.h)."""
    x = mx.sym.Variable("data")
    sym = mx.sym.Dropout(x, p=0.5)
    data = rng.uniform(0.5, 1.5, (20, 20)).astype(np.float32)
    ex = sym.simple_bind(mx.current_context(), grad_req="write",
                         data=data.shape)
    ex.arg_dict["data"][:] = data
    ex.forward(is_train=False)
    assert np.allclose(ex.outputs[0].asnumpy(), data, atol=1e-6)
    ex.forward(is_train=True)
    out = ex.outputs[0].asnumpy()
    kept = out != 0
    assert 0.3 < kept.mean() < 0.7
    assert np.allclose(out[kept], (data * 2)[kept], rtol=1e-5)


def test_embedding_grad():
    ids = mx.sym.Variable("ids")
    sym = mx.sym.Embedding(ids, input_dim=7, output_dim=3, name="emb")
    idv = rng.randint(0, 7, (4,)).astype(np.float32)
    wv = rng.uniform(-1, 1, (7, 3)).astype(np.float32)
    check_numeric_gradient(sym, {"ids": idv, "emb_weight": wv},
                           grad_nodes=["emb_weight"])


def test_upsampling_nearest_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.UpSampling(x, scale=2, sample_type="nearest")
    data = rng.uniform(0.5, 1.5, (1, 2, 3, 3)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data})


def test_upsampling_bilinear_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.UpSampling(x, scale=2, sample_type="bilinear",
                            num_filter=2, name="up")
    data = rng.uniform(0.5, 1.5, (1, 2, 3, 3)).astype(np.float32)
    w = rng.uniform(0.1, 1.0, (2, 1, 4, 4)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data, "up_weight": w},
                           numeric_eps=1e-2, check_eps=0.08)


def test_spatial_transformer_grad():
    x = mx.sym.Variable("data")
    loc = mx.sym.Variable("loc")
    sym = mx.sym.SpatialTransformer(x, loc, target_shape=(4, 4))
    data = rng.uniform(0.5, 1.5, (2, 2, 5, 5)).astype(np.float32)
    # identity-ish transform, interior sampling points: smooth neighborhood
    theta = np.tile(np.array([0.7, 0.05, 0.03, -0.05, 0.7, 0.02],
                             np.float32), (2, 1))
    check_numeric_gradient(sym, {"data": data, "loc": theta},
                           numeric_eps=1e-3, check_eps=0.08)


def test_roi_pooling_grad():
    x = mx.sym.Variable("data")
    rois = mx.sym.Variable("rois")
    sym = mx.sym.ROIPooling(x, rois, pooled_size=(2, 2), spatial_scale=1.0)
    data = _distinct((1, 2, 6, 6), 0.0, 5.0)
    rv = np.array([[0, 0, 0, 4, 4], [0, 1, 1, 5, 5]], np.float32)
    check_numeric_gradient(sym, {"data": data, "rois": rv},
                           grad_nodes=["data"], numeric_eps=1e-3)


def test_correlation_grad():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    sym = mx.sym.Correlation(a, b, kernel_size=1, max_displacement=1,
                             stride1=1, stride2=1, pad_size=1)
    av = rng.uniform(0.5, 1.5, (1, 2, 4, 4)).astype(np.float32)
    bv = rng.uniform(0.5, 1.5, (1, 2, 4, 4)).astype(np.float32)
    check_numeric_gradient(sym, {"a": av, "b": bv}, numeric_eps=1e-2,
                           check_eps=0.08)


def test_swapaxis_crop_op_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.Crop(x, offset=(1, 1), h_w=(3, 3), num_args=1)
    data = rng.uniform(0.5, 1.5, (1, 2, 5, 5)).astype(np.float32)
    check_numeric_gradient(sym, {"data": data})


def test_identity_attach_kl_sparse_reg_grad():
    x = mx.sym.Variable("data")
    sym = mx.sym.IdentityAttachKLSparseReg(x, sparseness_target=0.1,
                                           penalty=0.001)
    data = rng.uniform(0.1, 0.9, (3, 4)).astype(np.float32)
    grads, outs = _sym_grads(sym, {"data": data})
    assert np.allclose(outs[0], data, atol=1e-6)  # identity forward
    assert grads["data"].shape == data.shape


# --------------------------------------------------------------- losses ----

def test_block_grad_zero():
    x = mx.sym.Variable("x")
    sym = mx.sym.BlockGrad(x)
    data = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
    grads, outs = _sym_grads(sym, {"x": data})
    assert np.allclose(outs[0], data)
    assert np.all(grads["x"] == 0)


def test_softmax_output_analytic_grad():
    """Backward = softmax(pred) - onehot(label), scaled by grad_scale
    (reference softmax_output-inl.h)."""
    x = mx.sym.Variable("data")
    lab = mx.sym.Variable("softmax_label")
    sym = mx.sym.SoftmaxOutput(x, lab, name="softmax")
    data = rng.uniform(-1, 1, (4, 5)).astype(np.float32)
    label = rng.randint(0, 5, (4,)).astype(np.float32)
    grads, outs = _sym_grads(sym, {"data": data, "softmax_label": label},
                             grad_nodes=["data"])
    prob = outs[0]
    expect = prob.copy()
    expect[np.arange(4), label.astype(int)] -= 1.0
    assert reldiff(grads["data"], expect) < 1e-4


def test_softmax_output_ignore_label():
    x = mx.sym.Variable("data")
    lab = mx.sym.Variable("softmax_label")
    sym = mx.sym.SoftmaxOutput(x, lab, use_ignore=True, ignore_label=2,
                               name="softmax")
    data = rng.uniform(-1, 1, (4, 5)).astype(np.float32)
    label = np.array([0, 2, 1, 2], np.float32)
    grads, _ = _sym_grads(sym, {"data": data, "softmax_label": label},
                          grad_nodes=["data"])
    assert np.all(grads["data"][1] == 0)
    assert np.all(grads["data"][3] == 0)
    assert np.any(grads["data"][0] != 0)


def test_regression_output_grads():
    """LinearRegression: pred - label; MAERegression: sign(pred - label);
    LogisticRegression: sigmoid(pred) - label (reference
    regression_output-inl.h BackwardOp definitions)."""
    data = rng.uniform(-1, 1, (4, 3)).astype(np.float32)
    label = rng.uniform(-1, 1, (4, 3)).astype(np.float32)
    x = mx.sym.Variable("data")
    y = mx.sym.Variable("label")

    num_output = data.shape[1]  # grad is scaled by grad_scale/num_output

    g, _ = _sym_grads(mx.sym.LinearRegressionOutput(x, y, name="lro"),
                      {"data": data, "label": label}, grad_nodes=["data"])
    assert reldiff(g["data"], (data - label) / num_output) < 1e-4

    g, _ = _sym_grads(mx.sym.MAERegressionOutput(x, y, name="mae"),
                      {"data": data, "label": label}, grad_nodes=["data"])
    assert reldiff(g["data"], np.sign(data - label) / num_output) < 1e-4

    g, _ = _sym_grads(mx.sym.LogisticRegressionOutput(x, y, name="lgr"),
                      {"data": data, "label": label}, grad_nodes=["data"])
    sig = 1.0 / (1.0 + np.exp(-data))
    assert reldiff(g["data"], (sig - label) / num_output) < 1e-4


def test_svm_output_grad_shape():
    x = mx.sym.Variable("data")
    y = mx.sym.Variable("label")
    sym = mx.sym.SVMOutput(x, y, name="svm")
    data = rng.uniform(-1, 1, (4, 3)).astype(np.float32)
    label = rng.randint(0, 3, (4,)).astype(np.float32)
    g, _ = _sym_grads(sym, {"data": data, "label": label},
                      grad_nodes=["data"])
    assert g["data"].shape == data.shape
    assert np.any(g["data"] != 0)


def test_make_loss_grad():
    """MakeLoss backward emits grad_scale regardless of head grads
    (reference make_loss-inl.h)."""
    x = mx.sym.Variable("x")
    loss = mx.sym.MakeLoss(mx.sym.square(x))
    data = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
    grads, _ = _sym_grads(loss, {"x": data})
    assert reldiff(grads["x"], 2 * data) < 1e-4


def test_smooth_l1_grad():
    x = mx.sym.Variable("x")
    sym = mx.sym.smooth_l1(x, sigma=1.0)
    data = _away_from(rng.uniform(-2, 2, (3, 4)).astype(np.float32), lo=0.2)
    data = np.where(np.abs(np.abs(data) - 1.0) < 0.05, data * 1.2, data)
    check_numeric_gradient(sym, {"x": data}, numeric_eps=1e-3)


def test_softmax_cross_entropy_grad():
    x = mx.sym.Variable("x")
    lab = mx.sym.Variable("label")
    sym = mx.sym.softmax_cross_entropy(x, lab)
    data = rng.uniform(-1, 1, (4, 5)).astype(np.float32)
    label = rng.randint(0, 5, (4,)).astype(np.float32)
    g, _ = _sym_grads(sym, {"x": data, "label": label}, grad_nodes=["x"])
    e = np.exp(data - data.max(1, keepdims=True))
    prob = e / e.sum(1, keepdims=True)
    expect = prob.copy()
    expect[np.arange(4), label.astype(int)] -= 1.0
    assert reldiff(g["x"], expect) < 1e-3


def test_cast_forward_and_grad_pass_through():
    x = mx.sym.Variable("x")
    sym = mx.sym.Cast(x, dtype="float16")
    data = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
    grads, outs = _sym_grads(sym, {"x": data})
    assert outs[0].dtype == np.float16
    assert np.allclose(grads["x"], np.ones_like(data))


def test_argmax_channel_zero_grad():
    x = mx.sym.Variable("x")
    sym = mx.sym.argmax_channel(x)
    data = _distinct((3, 4), 0.0, 1.0)
    grads, _ = _sym_grads(sym, {"x": data})
    assert np.all(grads["x"] == 0)
