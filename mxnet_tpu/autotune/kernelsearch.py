"""Pallas kernel search: tiling/layout candidates, parity-gated,
cost-model-ranked, persisted per device-kind.

PR 11 shipped ONE hand-tuned tiling per Pallas kernel.  This module
turns that into a small kernel generator over MXU-aligned candidate
spaces (pallas_guide: f32 min tile (8, 128), int8 (32, 128), MXU
128x128, last dim always 128):

* ``flash_attention`` — (block_q, block_k) tile pairs;
* ``fused_fc_epilogue`` — the N-block width;
* ``paged_attention`` — implementation choice (page-walk kernel vs the
  dense-gather reference; the kernel's blocking is fixed by the pool's
  page size, so the search is WHICH program, not which tile).

Every candidate must pass the PARITY GATE before it may win: the kernel
runs in interpret mode on a deterministic input and must be **bitwise
equal** (``np.array_equal``) to a pure-jnp twin that mirrors the
kernel's exact blockwise op sequence, AND close (allclose) to the
independent dense reference — the twin proves the tiling permutes no
arithmetic, the reference proves the twin itself is attention/FC.  Gate
failures are logged in the audit trail (``"parity": False``) and can
never be selected.

Survivors are ranked by the shared cost model
(:mod:`~mxnet_tpu.autotune.costmodel` — per-candidate HBM-traffic
features: a smaller q-block re-reads K/V more often), the shortlist is
measured, and the winner persists under a (family, shape-class,
backend-descriptor) tuning key — per device-kind, like every autotune
config.  ``ops.pallas_kernels`` loads winners at call time when
``MXNET_KERNEL_SEARCH=1``.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import get_env, make_lock
from .costmodel import COSTMODEL_VERSION, clean_config, features
from .joint import JointTuner
from .measure import measure_candidate, tuning_key
from .store import load_config

__all__ = ["search_flash", "search_fc", "search_paged", "best_config",
           "flash_class", "fc_class", "paged_class", "parity_fail_total"]

Config = Dict[str, Any]

_FLASH_BLOCK_Q = (32, 64, 128, 256)
_FLASH_BLOCK_K = (128, 256)
_FC_BLOCK_N = (128, 256, 512)

_parity_fail = 0
_pf_lock = make_lock("autotune.kernelsearch")


def parity_fail_total() -> int:
    """Parity-gate failures across every search this process ran (the
    bench gate's ``kernelsearch_parity_fail`` ZERO_FLOOR metric)."""
    return _parity_fail


def _note_parity_fail(n: int) -> None:
    global _parity_fail
    with _pf_lock:
        _parity_fail += n


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, (int(x) - 1).bit_length())


# -- shape classes (what a winner generalizes over) --------------------------

def flash_class(t: int, d: int, causal: bool, dtype) -> Tuple:
    """Sequence length buckets to its pow2 ceiling: the winning tiles
    for T=200 and T=256 are the same search problem."""
    return ("flash", str(np.dtype(dtype)), _pow2_ceil(t), int(d),
            bool(causal))


def fc_class(n: int, k: int, act_type: str, int8: bool, dtype) -> Tuple:
    return ("fc_epilogue", str(np.dtype(dtype)), int(n), int(k),
            str(act_type), bool(int8))


def paged_class(bt: int, d: int, causal: bool, dtype) -> Tuple:
    return ("paged", str(np.dtype(dtype)), int(bt), int(d), bool(causal))


# -- winner lookup (the pallas_kernels call-time path) -----------------------

_best_cache: Dict[str, Optional[Config]] = {}
_cache_lock = make_lock("autotune.kernelsearch")


def _class_key(cls: Sequence) -> str:
    return tuning_key("kernelsearch:%s" % cls[0], tuple(cls))


def best_config(cls: Sequence) -> Optional[Config]:
    """The persisted winner for a shape class, or None — LOAD-ONLY (no
    search, no measurement; callers on the hot path must never block on
    a search).  Process-cached, negative results included."""
    key = _class_key(cls)
    with _cache_lock:
        if key in _best_cache:
            return _best_cache[key]
    doc = load_config(key, model_version=COSTMODEL_VERSION)
    cfg = clean_config(doc["config"]) if doc else None
    with _cache_lock:
        _best_cache[key] = cfg
    return cfg


def _forget(key: str) -> None:
    with _cache_lock:
        _best_cache.pop(key, None)


# -- pure-jnp twins: the kernels' exact blockwise op sequences ---------------
# (bitwise parity verified in tests/test_kernelsearch.py for every
# candidate shape class; tolerant parity vs the independent dense
# references guards the twins themselves)
#
# Each twin runs UNDER ONE jit: interpret-mode pallas_call executes the
# kernel inside a jit computation, and XLA CPU fuses mul+add chains
# (the online-softmax rescale) into FMAs there — an eager twin computes
# the same graph op-by-op with different roundings.  Tracing the whole
# twin gives XLA the same fusion opportunities, and bitwise equality
# holds (verified: an eager paged twin differs by ~3e-8, a jitted one
# by exactly 0).

def _flash_twin(q, k, v, causal: bool, block_q: int, block_k: int):
    """``_flash_kernel``'s online softmax replayed block-by-block in
    plain jnp — same pad/clip, same masking, same accumulation order."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ..ops.pallas_kernels import _round_up
    b, t, h, d = q.shape
    block_q = min(block_q, _round_up(t, 8))
    block_k = min(block_k, _round_up(t, 8))
    tp = _round_up(t, block_q * block_k // math.gcd(block_q, block_k))
    scale = 1.0 / math.sqrt(d)
    nk = tp // block_k

    def twin(q, k, v):
        if tp != t:
            pad = [(0, 0), (0, tp - t), (0, 0), (0, 0)]
            q, k, v = jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad)
        qf = q.transpose(0, 2, 1, 3).reshape(b * h, tp, d)
        kf = k.transpose(0, 2, 1, 3).reshape(b * h, tp, d)
        vf = v.transpose(0, 2, 1, 3).reshape(b * h, tp, d)
        rows = []
        for bh in range(b * h):
            blocks = []
            for qi in range(tp // block_q):
                qblk = qf[bh, qi * block_q:(qi + 1) * block_q].astype(
                    jnp.float32)
                if causal:
                    nk_run = min((qi * block_q + block_q + block_k - 1)
                                 // block_k, nk)
                else:
                    nk_run = nk

                def body(kb, carry, qblk=qblk, qi=qi, bh=bh):
                    m, l, acc = carry
                    kblk = lax.dynamic_slice(
                        kf[bh], (kb * block_k, 0),
                        (block_k, d)).astype(jnp.float32)
                    vblk = lax.dynamic_slice(
                        vf[bh], (kb * block_k, 0),
                        (block_k, d)).astype(jnp.float32)
                    s = jnp.dot(qblk, kblk.T,
                                preferred_element_type=jnp.float32) * scale
                    k_pos = kb * block_k + lax.broadcasted_iota(
                        jnp.int32, (block_q, block_k), 1)
                    if t < tp:
                        s = jnp.where(k_pos < t, s, -jnp.inf)
                    if causal:
                        q_pos = qi * block_q + lax.broadcasted_iota(
                            jnp.int32, (block_q, block_k), 0)
                        s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
                    blk_max = jnp.max(s, axis=-1)
                    new_m = jnp.maximum(m, blk_max)
                    safe_m = jnp.where(jnp.isinf(new_m), 0.0, new_m)
                    p = jnp.where(jnp.isinf(s), 0.0,
                                  jnp.exp(s - safe_m[:, None]))
                    corr = jnp.where(jnp.isinf(m), 0.0,
                                     jnp.exp(m - safe_m))
                    l2 = l * corr + jnp.sum(p, axis=-1)
                    acc2 = acc * corr[:, None] + jnp.dot(
                        p, vblk, preferred_element_type=jnp.float32)
                    return new_m, l2, acc2

                m0 = jnp.full((block_q,), -jnp.inf, jnp.float32)
                l0 = jnp.zeros((block_q,), jnp.float32)
                a0 = jnp.zeros((block_q, d), jnp.float32)
                _m, l, acc = lax.fori_loop(0, nk_run, body, (m0, l0, a0))
                l = jnp.maximum(l, 1e-20)
                blocks.append(acc / l[:, None])
            rows.append(jnp.concatenate(blocks, axis=0))
        out = jnp.stack(rows).astype(q.dtype)
        out = out.reshape(b, h, tp, d).transpose(0, 2, 1, 3)
        return out[:, :t] if tp != t else out

    # lint: allow(raw-jit) — parity-gate twin over fixed probe shapes;
    # one throwaway trace, never a steady-state dispatch
    return jax.jit(twin)(q, k, v)


def _fc_twin(x, w, b, act_type: str, out_scale, block_n: int):
    """``_fc_epilogue_kernel`` replayed one N-block at a time."""
    import jax
    import jax.numpy as jnp
    from ..ops.quantized import INT8_QMAX
    n = w.shape[0]

    def twin(x, w, b):
        xf = x.astype(jnp.float32)
        cols = []
        for i in range(n // block_n):
            wblk = w[i * block_n:(i + 1) * block_n].astype(jnp.float32)
            bblk = b[i * block_n:(i + 1) * block_n]
            acc = jnp.dot(xf, wblk.T, preferred_element_type=jnp.float32)
            acc = acc + bblk[None, :]
            if act_type == "relu":
                acc = jnp.maximum(acc, 0.0)
            elif act_type == "sigmoid":
                acc = jax.nn.sigmoid(acc)
            elif act_type == "tanh":
                acc = jnp.tanh(acc)
            elif act_type == "softrelu":
                acc = jax.nn.softplus(acc)
            if out_scale is not None:
                acc = jnp.clip(jnp.round(acc / out_scale),
                               -INT8_QMAX, INT8_QMAX)
            cols.append(acc)
        dtype = jnp.int8 if out_scale is not None else x.dtype
        return jnp.concatenate(cols, axis=1).astype(dtype)

    # lint: allow(raw-jit) — parity-gate twin (see _flash_twin)
    return jax.jit(twin)(x, w, b)


def _paged_twin(q, k_pool, v_pool, pages, lengths, q_pos, causal: bool):
    """``_paged_kernel``'s page walk replayed slot-by-slot in plain
    jnp — same clamp, same per-block online softmax."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    n, bt = k_pool.shape[0], k_pool.shape[1]
    s_, c, h, d = q.shape
    nb = pages.shape[1]
    scale = 1.0 / math.sqrt(d)

    def twin(q, k_pool, v_pool, pages, lengths, q_pos):
        outs = []
        for sl in range(s_):
            qh = q[sl].astype(jnp.float32).transpose(1, 0, 2)   # (H, C, D)
            m = jnp.full((h, c), -jnp.inf, jnp.float32)
            l = jnp.zeros((h, c), jnp.float32)
            acc = jnp.zeros((h, c, d), jnp.float32)
            for bi in range(nb):
                page = jnp.minimum(pages[sl, bi], n - 1)
                kh = k_pool[page].astype(jnp.float32).transpose(1, 0, 2)
                vh = v_pool[page].astype(jnp.float32).transpose(1, 0, 2)
                s = jnp.einsum("hcd,hkd->hck", qh, kh,
                               preferred_element_type=jnp.float32) * scale
                k_pos = bi * bt + lax.broadcasted_iota(jnp.int32,
                                                       s.shape, 2)
                mask = k_pos < lengths[sl]
                if causal:
                    mask = mask & (k_pos <= q_pos[sl][None, :, None])
                s = jnp.where(mask, s, -jnp.inf)
                new_m = jnp.maximum(m, jnp.max(s, axis=-1))
                safe_m = jnp.where(jnp.isinf(new_m), 0.0, new_m)
                p = jnp.where(jnp.isinf(s), 0.0,
                              jnp.exp(s - safe_m[..., None]))
                corr = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - safe_m))
                m = new_m
                l = l * corr + jnp.sum(p, axis=-1)
                acc = acc * corr[..., None] + jnp.einsum(
                    "hck,hkd->hcd", p, vh,
                    preferred_element_type=jnp.float32)
            l = jnp.maximum(l, 1e-20)
            outs.append((acc / l[..., None]).transpose(1, 0, 2)
                        .astype(q.dtype))
        return jnp.stack(outs)

    # lint: allow(raw-jit) — parity-gate twin (see _flash_twin)
    return jax.jit(twin)(q, k_pool, v_pool, pages, lengths, q_pos)


# -- the searches ------------------------------------------------------------

def _itemsize(dtype) -> int:
    return int(np.dtype(str(dtype)).itemsize) if not hasattr(dtype, "itemsize") \
        else int(np.dtype(dtype).itemsize)


def search_flash(b: int, t: int, h: int, d: int, causal: bool = False,
                 dtype=np.float32, trials: int = 2, persist: bool = True,
                 shortlist: Optional[int] = None) -> Config:
    """Search (block_q, block_k) for one flash shape class; returns the
    winning ``{"block_q", "block_k"}`` (persisted; subsequent runs and
    ``flash_attention`` call-time resolution load it with zero
    measurements)."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas_kernels import _round_up, flash_attention
    from ..parallel.ring import attention_reference
    cls = flash_class(t, d, causal, dtype)
    lim = _round_up(t, 8)
    seen, cands = set(), []
    for bq in _FLASH_BLOCK_Q:
        for bk in _FLASH_BLOCK_K:
            eff = (min(bq, lim), min(bk, lim))
            if eff in seen:
                continue
            seen.add(eff)
            cands.append({"block_q": int(eff[0]), "block_k": int(eff[1])})
    rng = np.random.RandomState(0)
    probe = [jnp.asarray(rng.randn(b, t, h, d).astype(np.dtype(dtype)))
             for _ in range(3)]
    ref = attention_reference(probe[0], probe[1], probe[2], causal=causal)
    on_tpu = jax.default_backend() == "tpu"

    def gate(cfg: Config) -> bool:
        got = flash_attention(probe[0], probe[1], probe[2], causal=causal,
                              block_q=cfg["block_q"], block_k=cfg["block_k"],
                              interpret=True)
        twin = _flash_twin(probe[0], probe[1], probe[2], causal,
                           cfg["block_q"], cfg["block_k"])
        return np.array_equal(np.asarray(got), np.asarray(twin)) \
            and np.allclose(np.asarray(got), np.asarray(ref), atol=2e-5)

    kv_bytes = 2 * t * d * _itemsize(dtype)          # one head's K+V

    def featurize(cfg: Config) -> List[float]:
        n_q_blocks = -(-_round_up(t, cfg["block_q"]) // cfg["block_q"])
        traffic = b * h * (2 * t * d * _itemsize(dtype)       # Q read + O write
                           + kv_bytes * n_q_blocks)           # K/V per q-block
        return features(gflops=4.0 * b * h * t * t * d / 1e9,
                        hbm_gb=traffic / 1e9,
                        block_q=cfg["block_q"], block_k=cfg["block_k"])

    def measure(cfg: Config) -> float:
        def run():
            out = flash_attention(
                probe[0], probe[1], probe[2], causal=causal,
                block_q=cfg["block_q"], block_k=cfg["block_k"],
                interpret=not on_tpu)
            jax.block_until_ready(out)
        return measure_candidate(run, label="flash:%(block_q)dx%(block_k)d"
                                 % cfg, trials=trials, warmup=1)

    key = _class_key(cls)
    tuner = JointTuner("kernelsearch:flash", key, persist=persist,
                       shortlist=shortlist)
    try:
        best, _cost = tuner.tune(cands, featurize, measure,
                                 meta={"class": list(cls)}, gate=gate)
    finally:
        # count gate failures even when EVERY candidate failed and the
        # search aborted — that is exactly the case the bench gate's
        # zero-floor metric must see
        _note_parity_fail(tuner.gate_failures)
    _forget(key)
    return best


def search_fc(m: int, k: int, n: int, act_type: str = "relu",
              out_scale: Optional[float] = None, dtype=np.float32,
              trials: int = 2, persist: bool = True,
              shortlist: Optional[int] = None) -> Config:
    """Search the N-block width for one fused_fc_epilogue shape class;
    returns the winning ``{"block_n"}``."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas_kernels import fused_fc_epilogue
    cls = fc_class(n, k, act_type, out_scale is not None, dtype)
    cands = [{"block_n": int(bn)} for bn in _FC_BLOCK_N if n % bn == 0]
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k).astype(np.dtype(dtype)))
    w = jnp.asarray(rng.randn(n, k).astype(np.dtype(dtype)))
    bias = jnp.asarray(rng.randn(n).astype(np.float32))
    on_tpu = jax.default_backend() == "tpu"

    def gate(cfg: Config) -> bool:
        got = fused_fc_epilogue(x, w, bias, act_type, out_scale=out_scale,
                                block_n=cfg["block_n"], interpret=True)
        if got is None:
            return False
        twin = _fc_twin(x, w, bias, act_type, out_scale, cfg["block_n"])
        return np.array_equal(np.asarray(got), np.asarray(twin))

    def featurize(cfg: Config) -> List[float]:
        x_bytes = m * k * _itemsize(dtype)
        traffic = x_bytes * (n // cfg["block_n"]) \
            + n * k * _itemsize(dtype) + m * n * 4
        return features(gflops=2.0 * m * n * k / 1e9,
                        hbm_gb=traffic / 1e9, block_n=cfg["block_n"])

    def measure(cfg: Config) -> float:
        def run():
            out = fused_fc_epilogue(x, w, bias, act_type,
                                    out_scale=out_scale,
                                    block_n=cfg["block_n"],
                                    interpret=not on_tpu)
            jax.block_until_ready(out)
        return measure_candidate(run, label="fc:n%(block_n)d" % cfg,
                                 trials=trials, warmup=1)

    key = _class_key(cls)
    tuner = JointTuner("kernelsearch:fc", key, persist=persist,
                       shortlist=shortlist)
    try:
        best, _cost = tuner.tune(cands, featurize, measure,
                                 meta={"class": list(cls)}, gate=gate)
    finally:
        _note_parity_fail(tuner.gate_failures)   # see search_flash
    _forget(key)
    return best


def search_paged(s: int, c: int, h: int, d: int, n_blocks: int = 8,
                 bt: int = 16, causal: bool = True, dtype=np.float32,
                 trials: int = 2, persist: bool = True,
                 shortlist: Optional[int] = None) -> Config:
    """Choose the paged-attention implementation (page-walk kernel vs
    dense gather) for one shape class; returns ``{"impl"}``.  The
    kernel's blocking is the pool's page size — there is no free tile
    here, only which program wins on this backend."""
    import jax
    import jax.numpy as jnp
    from ..ops.pallas_kernels import _paged_attention_dense, paged_attention
    cls = paged_class(bt, d, causal, dtype)
    cands = [{"impl": "kernel"}, {"impl": "dense"}]
    rng = np.random.RandomState(0)
    k_pool = jnp.asarray(rng.randn(n_blocks, bt, h, d).astype(np.dtype(dtype)))
    v_pool = jnp.asarray(rng.randn(n_blocks, bt, h, d).astype(np.dtype(dtype)))
    q = jnp.asarray(rng.randn(s, c, h, d).astype(np.dtype(dtype)))
    nb = max(1, (n_blocks - 1) // max(1, s))
    pages = jnp.asarray(
        rng.permutation(n_blocks - 1)[:s * nb].reshape(s, nb).astype(np.int32))
    lengths = jnp.asarray(
        rng.randint(c, nb * bt + 1, size=(s,)).astype(np.int32))
    q_pos = lengths[:, None] - c + jnp.arange(c, dtype=jnp.int32)[None]
    ref = _paged_attention_dense(q, k_pool, v_pool, pages, lengths, q_pos,
                                 causal=causal)
    on_tpu = jax.default_backend() == "tpu"

    def gate(cfg: Config) -> bool:
        if cfg["impl"] == "dense":
            return True             # the dense path IS the reference
        got = paged_attention(q, k_pool, v_pool, pages, lengths,
                              q_pos=q_pos, causal=causal, interpret=True)
        twin = _paged_twin(q, k_pool, v_pool, pages, lengths, q_pos, causal)
        return np.array_equal(np.asarray(got), np.asarray(twin)) \
            and np.allclose(np.asarray(got), np.asarray(ref), atol=3e-5)

    ctx_bytes = s * nb * bt * h * d * _itemsize(dtype)

    def featurize(cfg: Config) -> List[float]:
        qo = 2 * s * c * h * d * _itemsize(dtype)
        if cfg["impl"] == "kernel":
            traffic = qo + 2 * ctx_bytes            # stream each page once
        else:
            traffic = qo + 4 * ctx_bytes            # gather materializes K/V
        return features(gflops=4.0 * s * c * h * d * nb * bt / 1e9,
                        hbm_gb=traffic / 1e9)

    def measure(cfg: Config) -> float:
        if cfg["impl"] == "dense":
            # lint: allow(raw-jit) — throwaway measurement closure over
            # fixed probe arrays; never a steady-state dispatch worth a
            # disk cache entry
            fn = jax.jit(lambda: _paged_attention_dense(
                q, k_pool, v_pool, pages, lengths, q_pos, causal=causal))
        else:
            def fn():
                return paged_attention(q, k_pool, v_pool, pages, lengths,
                                       q_pos=q_pos, causal=causal,
                                       interpret=not on_tpu)

        def run():
            jax.block_until_ready(fn())
        return measure_candidate(run, label="paged:%(impl)s" % cfg,
                                 trials=trials, warmup=1)

    key = _class_key(cls)
    tuner = JointTuner("kernelsearch:paged", key, persist=persist,
                       shortlist=shortlist)
    try:
        best, _cost = tuner.tune(cands, featurize, measure,
                                 meta={"class": list(cls)}, gate=gate)
    finally:
        _note_parity_fail(tuner.gate_failures)   # see search_flash
    _forget(key)
    return best
