"""Feature-file readers (reference io_func/feat_readers/): one reader
class per on-disk format, a common (features, labels) protocol, and the
corpus statistics accumulator."""
from .common import BaseReader, ByteOrder, FeatureException  # noqa: F401
from .stats import FeatureStats, StreamingVariance  # noqa: F401


def get_reader(file_format, feature_file, label_file=None):
    """Format-dispatched reader construction (reference common.getReader)."""
    fmt = file_format.lower()
    if fmt == "htk":
        from .reader_htk import HtkReader
        return HtkReader(feature_file, label_file, ByteOrder.BigEndian)
    if fmt == "htk_little":
        from .reader_htk import HtkReader
        return HtkReader(feature_file, label_file, ByteOrder.LittleEndian)
    if fmt == "bvec":
        from .reader_bvec import BvecReader
        return BvecReader(feature_file, label_file)
    if fmt == "atrack":
        from .reader_atrack import AtrackReader
        return AtrackReader(feature_file, label_file)
    if fmt == "kaldi":
        from .reader_kaldi import KaldiReader
        return KaldiReader(feature_file, label_file)
    raise ValueError("unsupported feature format %r" % file_format)
