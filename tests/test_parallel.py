"""mxnet_tpu.parallel under tier-1: mesh construction, the standalone
sharded train steps (DPTrainStep, GPipeTrainStep), and sequence
parallelism (ring / Ulysses attention) on the 8 forced host devices —
previously only the out-of-band MULTICHIP dryrun exercised any of this.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.parallel import PartitionSpec as P
from mxnet_tpu.parallel.ring import (attention_reference, make_ring_attention)


# -- mesh construction -------------------------------------------------------

def test_make_mesh_axes():
    mesh = parallel.make_mesh([("dp", 4), ("tp", 2)])
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}
    assert mesh.devices.shape == (4, 2)


def test_make_mesh_absorb():
    mesh = parallel.make_mesh([("dp", -1), ("tp", 2)])
    assert dict(mesh.shape) == {"dp": 4, "tp": 2}


def test_make_mesh_too_many_devices():
    with pytest.raises(ValueError):
        parallel.make_mesh([("dp", 16)])


def test_make_mesh_two_absorb_axes():
    with pytest.raises(ValueError):
        parallel.make_mesh([("dp", -1), ("tp", -1)])


def test_parse_mesh_spec():
    assert parallel.parse_mesh_spec("dp=4,tp=2") == [("dp", 4), ("tp", 2)]
    assert parallel.parse_mesh_spec("dp=-1") == [("dp", -1)]
    with pytest.raises(ValueError):
        parallel.parse_mesh_spec("dp:4")
    with pytest.raises(ValueError):
        parallel.parse_mesh_spec("")


def test_make_mesh_string_form():
    mesh = parallel.make_mesh("dp=2,tp=2")
    assert dict(mesh.shape) == {"dp": 2, "tp": 2}


def test_mesh_from_env(monkeypatch):
    monkeypatch.setenv("MXNET_MESH", "dp=8")
    mesh = parallel.mesh_from_env()
    assert dict(mesh.shape) == {"dp": 8}
    monkeypatch.setenv("MXNET_MESH", "")
    assert parallel.mesh_from_env() is None


def test_normalize_spec_forms():
    assert tuple(parallel.normalize_spec(None)) == ()
    assert tuple(parallel.normalize_spec(P("dp", None))) == ("dp", None)
    assert tuple(parallel.normalize_spec("None,tp")) == (None, "tp")
    assert tuple(parallel.normalize_spec(("tp", None))) == ("tp", None)
    with pytest.raises(ValueError):
        parallel.normalize_spec(3.14)


def test_sharding_attrs_from_symbol():
    w = mx.sym.Variable("fc_weight", attr={"__sharding__": "None,tp"})
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, weight=w, num_hidden=8, name="fc")
    specs = parallel.sharding_attrs(net)
    assert tuple(specs["fc_weight"]) == (None, "tp")


def test_dp_sharding_and_replicated():
    mesh = parallel.make_mesh([("dp", 8)])
    assert tuple(parallel.dp_sharding(mesh).spec) == ("dp",)
    assert tuple(parallel.replicated(mesh).spec) == ()


# -- DPTrainStep -------------------------------------------------------------

def _mlp_sym():
    data = mx.sym.Variable("data")
    h = mx.sym.Activation(
        mx.sym.FullyConnected(data, num_hidden=8, name="fc1"),
        act_type="relu")
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(h, num_hidden=2, name="fc2"), name="softmax")


def _mlp_params(rng):
    return {
        "fc1_weight": rng.randn(8, 6).astype(np.float32) * 0.1,
        "fc1_bias": np.zeros(8, np.float32),
        "fc2_weight": rng.randn(2, 8).astype(np.float32) * 0.1,
        "fc2_bias": np.zeros(2, np.float32),
    }


def _dp_train(mesh, param_specs=None, steps=4):
    rng = np.random.RandomState(3)
    step = parallel.DPTrainStep(_mlp_sym(), mesh,
                                learning_rate=0.5, momentum=0.9,
                                weight_decay=0.0,
                                param_specs=param_specs)
    state = step.init(_mlp_params(rng), {})
    key = jax.random.PRNGKey(0)
    for i in range(steps):
        X = rng.randn(16, 6).astype(np.float32)
        y = (X.sum(axis=1) > 0).astype(np.float32)
        batch = step.shard_batch({"data": X, "softmax_label": y})
        state, outs = step(state, batch, rng=key)
    return {k: np.asarray(v) for k, v in state["params"].items()}


def test_dp_train_step_dp8_matches_single():
    p8 = _dp_train(parallel.make_mesh([("dp", 8)]))
    p1 = _dp_train(parallel.make_mesh([("dp", 1)], devices=jax.devices()[:1]))
    for k in p1:
        assert np.abs(p1[k] - p8[k]).max() < 1e-4, k
        assert np.isfinite(p8[k]).all()


def test_dp_train_step_param_specs_tp():
    mesh = parallel.make_mesh([("dp", 4), ("tp", 2)])
    pt = _dp_train(mesh, param_specs={"fc1_weight": P("tp", None)})
    p1 = _dp_train(parallel.make_mesh([("dp", 1)], devices=jax.devices()[:1]))
    for k in p1:
        assert np.abs(p1[k] - pt[k]).max() < 1e-4, k


# -- GPipeTrainStep ----------------------------------------------------------

def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def test_pipeline_apply_matches_sequential():
    S, M, B, D = 4, 8, 2, 8
    mesh = parallel.make_mesh([("pp", S)])
    rng = np.random.RandomState(0)
    params = {"w": jnp.asarray(rng.randn(S, D, D) * 0.3, jnp.float32),
              "b": jnp.zeros((S, D), jnp.float32)}
    micros = jnp.asarray(rng.randn(M, B, D), jnp.float32)
    outs = parallel.pipeline_apply(_stage_fn, mesh, params, micros)
    # sequential reference: run each microbatch through the S stages
    ref = []
    for m in range(M):
        h = micros[m]
        for s in range(S):
            h = _stage_fn({"w": params["w"][s], "b": params["b"][s]}, h)
        ref.append(h)
    ref = jnp.stack(ref)
    assert np.abs(np.asarray(outs) - np.asarray(ref)).max() < 1e-5


def test_pipeline_apply_stage_count_mismatch():
    mesh = parallel.make_mesh([("pp", 4)])
    params = {"w": jnp.zeros((3, 4, 4)), "b": jnp.zeros((3, 4))}
    with pytest.raises(ValueError):
        parallel.pipeline_apply(_stage_fn, mesh,
                                params, jnp.zeros((8, 2, 4)))


def test_gpipe_train_step_loss_decreases():
    S, M, B, D = 4, 4, 8, 8
    mesh = parallel.make_mesh([("pp", S)])
    rng = np.random.RandomState(1)

    def loss_fn(tail, h, labels):
        logits = h @ tail["w"]
        return jnp.mean((logits[:, 0] - labels) ** 2)

    step = parallel.GPipeTrainStep(_stage_fn, loss_fn, mesh, num_micro=M,
                                   learning_rate=0.1)
    params = step.init(
        {"w": rng.randn(S, D, D).astype(np.float32) * 0.3,
         "b": np.zeros((S, D), np.float32)},
        {"w": rng.randn(D, 1).astype(np.float32) * 0.3})
    X = rng.randn(B * M, D).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    losses = []
    for _ in range(8):
        params, loss = step(params, X, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_gpipe_batch_not_divisible():
    mesh = parallel.make_mesh([("pp", 4)])
    step = parallel.GPipeTrainStep(_stage_fn, lambda t, h, l: jnp.sum(h),
                                   mesh, num_micro=4)
    with pytest.raises(ValueError):
        step(None, np.zeros((6, 8), np.float32), np.zeros(6, np.float32))


# -- ring / Ulysses attention ------------------------------------------------

@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_reference(causal):
    mesh = parallel.make_mesh([("sp", 8)])
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 32, 4, 8
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))
    fn = make_ring_attention(mesh, causal=causal)
    out = fn(q, k, v)
    ref = attention_reference(q, k, v, causal=causal)
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() < 1e-4


def test_ulysses_attention_matches_reference():
    mesh = parallel.make_mesh([("sp", 4)], devices=jax.devices()[:4])
    rng = np.random.RandomState(0)
    B, T, H, D = 2, 16, 4, 8     # H divisible by sp
    q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
               for _ in range(3))
    fn = make_ring_attention(mesh, axis="sp", impl="ulysses")
    out = fn(q, k, v)
    ref = attention_reference(q, k, v)
    assert np.abs(np.asarray(out) - np.asarray(ref)).max() < 1e-4


def test_make_mesh_zero_size_refused():
    with pytest.raises(ValueError, match="positive"):
        parallel.make_mesh("dp=0")
    with pytest.raises(ValueError, match="positive"):
        parallel.make_mesh([("dp", -2)])


def test_validate_spec_tuple_entry_uses_axis_product():
    """A tuple spec entry shards one dim over the PRODUCT of its axes:
    12 over ('dp','tp') on dp=4 x tp=2 is 8-way — uneven — and must be
    refused even though 12 divides by 4 and by 2 separately."""
    from mxnet_tpu.base import MXNetError
    mesh = parallel.make_mesh([("dp", 4), ("tp", 2)])
    spec = P(("dp", "tp"))
    with pytest.raises(MXNetError, match="8 ways"):
        parallel.validate_spec("w", spec, mesh, shape=(12,))
    parallel.validate_spec("w", spec, mesh, shape=(16,))   # 16 % 8 == 0


def test_validate_spec_overlong_refused():
    from mxnet_tpu.base import MXNetError
    mesh = parallel.make_mesh([("tp", 2)])
    with pytest.raises(MXNetError, match="entries"):
        parallel.validate_spec("b", P("tp", None), mesh, shape=(8,))


def test_mesh_axes_serialization():
    mesh = parallel.make_mesh([("dp", 4), ("tp", 2)])
    from mxnet_tpu.parallel.mesh import mesh_axes
    assert mesh_axes(mesh) == (("dp", 4), ("tp", 2))
