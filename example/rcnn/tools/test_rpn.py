"""Stage tool: evaluate a trained RPN and emit its proposals (reference
tools/test_rpn.py + rcnn/rpn/generate.py): reports ground-truth recall
at IoU 0.5 and saves the proposal set the next stage trains on.

  python tools/test_rpn.py --prefix /tmp/rpn1 --epoch 8 \
      --proposals /tmp/props1.npz
"""
from common import base_parser, setup, test_set, train_set


def main():
    ap = base_parser("evaluate RPN proposals + recall")
    ap.add_argument("--prefix", required=True)
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--proposals", required=True,
                    help="npz path to write the proposal set to")
    ap.add_argument("--recall-gate", type=float, default=0.0)
    ap.add_argument("--on-test-set", action="store_true",
                    help="generate over the held-out set (for "
                         "tools/test_rcnn.py) instead of the train set")
    args = ap.parse_args()
    mx, cfg, ctx = setup(args)

    from rcnn.tester import (generate_proposals, load_rpn_test,
                             proposal_recall, save_proposals)

    _, arg_params, aux_params = mx.model.load_checkpoint(args.prefix,
                                                         args.epoch)
    rpn = load_rpn_test(cfg, arg_params, aux_params, ctx=ctx)
    if args.on_test_set:
        dataset = test_set(cfg, args)
        n_images, seed = args.test_images, args.test_seed
    else:
        dataset = train_set(cfg, args)
        n_images, seed = args.train_images, args.data_seed
    proposals = generate_proposals(rpn, dataset, cfg)
    recall = proposal_recall(proposals, dataset, cfg)
    save_proposals(args.proposals, proposals,
                   n_images=n_images, data_seed=seed)
    print("recall@0.5=%.4f" % recall)
    if args.recall_gate:
        assert recall >= args.recall_gate, \
            "recall gate failed: %.4f < %.2f" % (recall, args.recall_gate)
        print("PASSED")


if __name__ == "__main__":
    main()
