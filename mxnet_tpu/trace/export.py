"""Chrome/Perfetto trace-event export: merge every process's spans into
one loadable JSON file.

The exported file is the `Trace Event Format
<https://docs.google.com/document/d/1CvAClvFfyA5R-PhYUmn5OOQtYMH4h6I0nSsKchNAySU>`_
"JSON object" flavor: ``{"traceEvents": [...], "displayTimeUnit": "ms"}``
with

* ``ph:"X"`` complete events for synchronous spans (``ts``/``dur`` in
  microseconds on the shared CLOCK_MONOTONIC timeline),
* ``ph:"b"/"n"/"e"`` async events for request lifecycles (same ``cat`` +
  ``id`` draws the flow arrows linking a serve request from ``submit()``
  through batcher, dispatch, D2H and future-resolve),
* ``ph:"M"`` metadata naming each pid lane (parent vs reader worker
  processes) and tid lane (thread names), so Perfetto shows one labeled
  track per process/thread.

Sources merged per dump: this process's live rings (the recorder
snapshot) plus every spill file under the registered spill directories —
the per-worker JSONL files ParallelReader workers append to, which
survive the worker (even a SIGKILL'd one) because the parent owns the
directory.
"""
from __future__ import annotations

import glob
import json
import os
from typing import Dict, List, Optional

__all__ = ["export_chrome", "read_spill_dir"]


def read_spill_dir(directory: str) -> List[Dict]:
    """Every event from every ``*.jsonl`` spill file under
    ``directory``.  A torn final line (the writer died mid-write) is
    skipped, not fatal."""
    events: List[Dict] = []
    for path in sorted(glob.glob(os.path.join(directory, "*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        ev = json.loads(line)
                    except ValueError:
                        continue       # torn tail from a killed writer
                    if isinstance(ev, dict):
                        events.append(ev)
        except OSError:
            continue
    return events


def _metadata(events: List[Dict], main_pid: int,
              thread_names: Dict[int, str],
              process_labels: Optional[Dict[int, str]] = None) -> List[Dict]:
    """process_name / thread_name metadata records for every (pid, tid)
    seen in ``events``."""
    labels = dict(process_labels or {})
    pids = {}
    for ev in events:
        pids.setdefault(ev["pid"], set()).add(ev["tid"])
    meta = []
    for pid, tids in sorted(pids.items()):
        if pid == main_pid:
            pname = labels.get(pid, "mxnet-tpu (main)")
        else:
            pname = labels.get(pid, "mxnet-tpu worker pid=%d" % pid)
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": pname}})
        for tid in sorted(tids):
            tname = thread_names.get(tid) if pid == main_pid else None
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid,
                         "args": {"name": tname or "tid=%d" % tid}})
    return meta


def export_chrome(path: str, recorder, spill_dirs, drops: int = 0,
                  process_labels: Optional[Dict[int, str]] = None) -> str:
    """Write the merged trace to ``path``; returns ``path``."""
    events = recorder.snapshot()
    for d in spill_dirs:
        events.extend(read_spill_dir(d))
    events.sort(key=lambda ev: ev.get("ts", 0.0))
    meta = _metadata(events, recorder.pid, recorder.thread_names(),
                     process_labels)
    if drops:
        # surface lost events IN the trace, where the person reading it
        # will look, not only in a report dict
        events.append({"name": "trace:dropped_events", "cat": "trace",
                      "ph": "i", "s": "g", "ts": events[-1]["ts"]
                       if events else 0.0, "pid": recorder.pid, "tid": 0,
                       "args": {"dropped": drops}})
    doc = {"traceEvents": meta + events, "displayTimeUnit": "ms"}
    out_dir = os.path.dirname(os.path.abspath(path))
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    with open(path, "w") as f:
        # attrs are arbitrary **kwargs; one np.float32 must not cost the
        # whole trace (default=str matches the journal's policy)
        json.dump(doc, f, default=str)
    return path
