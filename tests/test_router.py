"""mxnet_tpu.serve.ServeRouter: the multi-replica front door (tier-1).

Covers queue-depth-aware dispatch with parity, overload walking, the
draining restart (weight hot-swap AND full rebuild) with zero dropped
requests — including the ISSUE 13 satellite: a draining restart under a
closed-loop flood in a SUBPROCESS drops nothing — routing around a
crashed replica with health-based removal, retry-on-replica-failure,
and the router rollup row in serve_report.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu.serve import (ServeClosedError, ServeEngine,
                             ServeOverloadError, ServeRouter,
                             ServeUnavailableError)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
IN_DIM, HID, CLASSES = 6, 8, 3
SHAPES = {"data": (1, IN_DIM), "softmax_label": (1,)}


def _net():
    data = mx.sym.Variable("data")
    n = mx.sym.FullyConnected(data, num_hidden=HID, name="fc1")
    n = mx.sym.Activation(n, act_type="relu")
    n = mx.sym.FullyConnected(n, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(n, name="softmax")


def _params(seed=0):
    rng = np.random.RandomState(seed)
    return {"fc1_weight": rng.randn(HID, IN_DIM).astype(np.float32),
            "fc1_bias": np.zeros(HID, np.float32),
            "fc2_weight": rng.randn(CLASSES, HID).astype(np.float32),
            "fc2_bias": np.zeros(CLASSES, np.float32)}


def _factory(seed=0, **kw):
    def build(i):
        eng_kw = dict(batch_buckets=(1, 2, 4), max_delay_ms=2.0,
                      name="rep%d" % i)
        eng_kw.update(kw)
        return ServeEngine(_net(), _params(seed), SHAPES, **eng_kw)
    return build


@pytest.fixture(scope="module")
def X():
    return np.random.RandomState(7).randn(24, IN_DIM).astype(np.float32)


def test_dispatch_balances_and_parity(X):
    router = ServeRouter(_factory(), replicas=2, name="balance")
    try:
        ref = router.predict(X[0], timeout=30)
        futs = [router.submit(X[0]) for _ in range(24)]
        for f in futs:
            assert np.allclose(f.result(timeout=30), ref, atol=1e-5)
        r = router.stats.report()
        assert r["kind"] == "router" and r["replicas"] == 2
        assert r["failed"] == 0
        # both replicas took traffic (least-loaded dispatch spreads a
        # concurrent burst; exact split is load-dependent)
        dispatched = [row["dispatched"] for row in r["per_replica"].values()]
        assert all(d > 0 for d in dispatched), dispatched
        assert sum(dispatched) == 25
    finally:
        router.close()


def test_restart_full_rebuild_and_weight_reload(X):
    params2 = _params(seed=9)
    router = ServeRouter(_factory(), replicas=2, name="restart")
    try:
        ref1 = router.predict(X[0], timeout=30)
        # weight hot-swap restart on every replica: answers flip to v2
        router.rolling_restart(reload=params2, timeout=60)
        eng = ServeEngine(_net(), _params(seed=9), SHAPES,
                          batch_buckets=(1,), name="ref2")
        ref2 = eng.predict(X[0], timeout=30)
        eng.close()
        assert not np.allclose(ref1, ref2, atol=1e-3)
        got = router.predict(X[0], timeout=30)
        assert np.allclose(got, ref2, atol=1e-5)
        # full-rebuild restart via a new factory: back to v1
        router.restart(0, factory=_factory(), timeout=60)
        router.restart(1, factory=_factory(), timeout=60)
        assert np.allclose(router.predict(X[0], timeout=30), ref1,
                           atol=1e-5)
        r = router.stats.report()
        assert r["drains"] == 4
        assert all(row["restarts"] == 2
                   for row in r["per_replica"].values())
        assert router.replica_states() == ["live", "live"]
    finally:
        router.close()


def test_drain_marks_unavailable_single_replica(X):
    router = ServeRouter(_factory(), replicas=1, name="drain1")
    try:
        router.predict(X[0], timeout=30)
        router.drain(0, timeout=30)
        assert router.replica_states() == ["draining"]
        with pytest.raises(ServeUnavailableError):
            router.submit(X[0])
        router.restart(0, reload=_params(), timeout=60)  # re-enters rotation
        assert router.replica_states() == ["live"]
        router.predict(X[0], timeout=30)
    finally:
        router.close()


def test_overload_walks_all_replicas(X):
    router = ServeRouter(_factory(queue_depth=1, max_delay_ms=200.0),
                         replicas=2, name="overload")
    try:
        with router.replica(0).pause(), router.replica(1).pause():
            admitted = []
            with pytest.raises(ServeOverloadError):
                for _ in range(32):
                    admitted.append(router.submit(X[0]))
            assert router.stats.report()["rejected"] >= 1
        for f in admitted:
            f.result(timeout=30)        # everything admitted completes
    finally:
        router.close()


def test_crashed_replica_routed_around_and_marked_down(X):
    """A replica closed underneath the router (simulated crash) must
    not surface to clients: submits walk to the healthy replica, the
    dead one's failures mark it down and out of rotation."""
    router = ServeRouter(_factory(), replicas=2, name="crash",
                         unhealthy_after=2)
    try:
        ref = router.predict(X[0], timeout=30)
        router.replica(0).close(drain=False)        # crash replica 0
        for _ in range(12):
            assert np.allclose(router.predict(X[0], timeout=30), ref,
                               atol=1e-5)
        states = router.replica_states()
        assert "down" in states, states             # 0 left rotation
        assert router.stats.report()["downs"] == 1
        # an operator restart (rebuild) brings it back
        idx = states.index("down")
        router.restart(idx, timeout=60)
        assert router.replica_states() == ["live", "live"]
        assert np.allclose(router.predict(X[0], timeout=30), ref,
                           atol=1e-5)
    finally:
        router.close()


def test_closed_router_and_report_str(X):
    router = ServeRouter(_factory(), replicas=1, name="closing")
    router.predict(X[0], timeout=30)
    s = mx.profiler.serve_report_str()
    assert "serve router 'closing'" in s and "rollup" in s
    router.close()
    with pytest.raises(ServeClosedError):
        router.submit(X[0])
    router.close()                      # idempotent


def test_draining_restart_under_flood_subprocess(X, tmp_path):
    """ISSUE 13 satellite: a closed-loop flood against a 3-replica
    router while one replica does a full draining restart mid-flood —
    ZERO dropped requests, every answer parity-checked.  Runs in a
    subprocess so the whole lifecycle (threads, engines, router) is
    also leak-checked by process exit."""
    script = os.path.join(ROOT, "tests", "_router_flood.py")
    res = subprocess.run(
        [sys.executable, script], cwd=ROOT, capture_output=True,
        text=True, timeout=540,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert res.returncode == 0, \
        "router flood subprocess failed:\n%s\n%s" % (res.stdout[-2000:],
                                                     res.stderr[-2000:])
    doc = json.loads(res.stdout.strip().splitlines()[-1])
    assert doc["errors"] == 0
    assert doc["dropped"] == 0
    assert doc["completed"] == doc["expected"]
    assert doc["restarts"] >= 1
    assert doc["parity_failures"] == 0


# -- half-open circuit breaker (ISSUE 15) -------------------------------------

class _FakeEngine:
    """Minimal replica surface with a flippable failure mode — the
    breaker tests need exact control over when a replica is broken."""

    def __init__(self):
        self.fail = False
        self.submitted = 0

    def submit(self, data, deadline_ms=None, **kw):
        from concurrent.futures import Future
        self.submitted += 1
        fut = Future()
        from mxnet_tpu.serve import ServeError as _SE
        if self.fail:
            fut.set_exception(_SE("injected replica failure"))
        else:
            fut.set_result(np.asarray(data, np.float32) * 2)
        return fut

    def pending_requests(self):
        return 0

    def outstanding(self):
        return 0

    def close(self, drain=True):
        pass


def _fake_router(**kw):
    engines = {}

    def factory(i):
        engines[i] = _FakeEngine()
        return engines[i]

    return ServeRouter(factory, **kw), engines


def test_half_open_probe_failure_retrips_then_success_reinstates():
    """ISSUE 15 satellite: health-removed replicas used to stay out of
    rotation until a manual restart().  Now a down replica gets ONE
    probe request after a backed-off interval: a failing probe re-trips
    the breaker (doubled interval, client shielded by the retry
    budget); a succeeding probe reinstates the replica with a clean
    health record — no operator involved."""
    from mxnet_tpu.serve import ServeError
    router, engines = _fake_router(
        replicas=2, unhealthy_after=2, retries=2,
        probe_after_s=0.05, name="probe")
    x = np.zeros(2, np.float32)
    try:
        engines[0].fail = True
        for _ in range(8):      # retried on the healthy replica
            assert router.submit(x).result(timeout=10) is not None
        assert router.replica_states()[0] == "down"
        down_submits = engines[0].submitted

        # wait out the probe interval; the next request PROBES replica
        # 0, which still fails -> stays down, interval doubles, and the
        # client still gets an answer (retry on replica 1)
        time.sleep(0.12)
        assert router.submit(x).result(timeout=10) is not None
        assert engines[0].submitted == down_submits + 1   # the probe
        assert router.replica_states()[0] == "down"
        r = router.stats.report()
        assert r["probes"] >= 1 and r["reinstated"] == 0

        # heal it; after the re-tripped interval the next probe
        # succeeds and the replica re-enters rotation
        engines[0].fail = False
        deadline = time.perf_counter() + 10.0
        while router.replica_states()[0] != "live":
            assert time.perf_counter() < deadline, router.stats.report()
            router.submit(x).result(timeout=10)
            time.sleep(0.02)
        r = router.stats.report()
        assert r["reinstated"] == 1
        assert r["per_replica"][0]["failures"] == 0
        # reinstated replica takes real traffic again
        before = engines[0].submitted
        for _ in range(6):
            router.submit(x).result(timeout=10)
        assert engines[0].submitted > before
    finally:
        router.close()


def test_probe_disabled_keeps_legacy_manual_restart_semantics():
    """probe_after_s=0: a down replica stays down until restart()."""
    router, engines = _fake_router(
        replicas=2, unhealthy_after=1, retries=1, probe_after_s=0,
        name="noprobe")
    x = np.zeros(2, np.float32)
    try:
        engines[0].fail = True
        router.submit(x).result(timeout=10)
        assert router.replica_states()[0] == "down"
        time.sleep(0.2)
        down_submits = engines[0].submitted
        for _ in range(4):
            router.submit(x).result(timeout=10)
        assert engines[0].submitted == down_submits   # never probed
        assert router.replica_states()[0] == "down"
        engines[0].fail = False
        router.restart(0, reload=None, factory=lambda i: engines[0],
                       timeout=10)
        assert router.replica_states()[0] == "live"
    finally:
        router.close()


def test_retry_budget_configurable():
    """retries=0 surfaces the first engine failure; the default budget
    (env-driven) retries it away."""
    from mxnet_tpu.serve import ServeError
    router, engines = _fake_router(
        replicas=2, unhealthy_after=0, retries=0, probe_after_s=0,
        name="budget0")
    x = np.zeros(2, np.float32)
    try:
        engines[0].fail = True      # least-loaded picks replica 0 first
        with pytest.raises(ServeError, match="injected"):
            router.submit(x).result(timeout=10)
    finally:
        router.close()
    router2, engines2 = _fake_router(
        replicas=2, unhealthy_after=0, retries=2, probe_after_s=0,
        name="budget2")
    try:
        engines2[0].fail = True
        assert router2.submit(x).result(timeout=10) is not None
        assert router2.stats.report()["retried"] >= 1
        assert router2.stats.report()["retry_wait_s"] > 0
    finally:
        router2.close()


def test_probe_requires_a_retry_budget():
    """ISSUE 15 review: a probe drafts a real client request and the
    retry budget is what shields it — with retries=0 the breaker must
    not probe (the drafted client would eat the failure)."""
    router, engines = _fake_router(
        replicas=2, unhealthy_after=1, retries=0, probe_after_s=0.02,
        name="probe-nobudget")
    x = np.zeros(2, np.float32)
    try:
        engines[0].fail = True
        try:
            router.submit(x).result(timeout=10)
        except Exception:
            pass                        # retries=0: failure surfaces
        assert router.replica_states()[0] == "down"
        time.sleep(0.1)
        down_submits = engines[0].submitted
        for _ in range(5):
            router.submit(x).result(timeout=10)
        assert engines[0].submitted == down_submits   # never probed
        assert router.stats.report()["probes"] == 0
    finally:
        router.close()
