"""Async snapshot machinery: take the save off the training critical path.

A save has three phases with very different costs:

1. **snapshot** (train thread, ~one step of stall): every device leaf is
   copied on-device (``jnp.copy`` — the live train state is DONATED to
   the next step's program, so the snapshot must own its buffers) and
   its D2H transfer is started (``copy_to_host_async``).  The train loop
   then continues; the DMA overlaps the next steps.
2. **serialize** (writer thread): ``np.asarray`` each leaf (blocks only
   the writer until its transfer lands) and write the shard files.
3. **commit** (writer thread): the layout.py rename + marker protocol.

:class:`AsyncWriter` is one daemon thread draining a bounded queue of
save jobs — a second save issued while ``max_pending`` are in flight
blocks the caller (backpressure, charged to the overhead counter) rather
than queueing unbounded device copies.  A writer exception is stashed
and re-raised on the next ``save``/``wait`` so failures cannot pass
silently.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional

from ..base import make_rlock

__all__ = ["snapshot_tree", "AsyncWriter"]


def _map_structure(fn, node):
    """Structure-preserving map over the dict/tuple/list/None trees the
    train state uses (jax.tree_map would skip None and rebuild customs)."""
    if node is None:
        return None
    if isinstance(node, dict):
        return {k: _map_structure(fn, v) for k, v in node.items()}
    if isinstance(node, (tuple, list)):
        vals = [_map_structure(fn, v) for v in node]
        return tuple(vals) if isinstance(node, tuple) else vals
    return fn(node)


def snapshot_tree(tree):
    """Device-copy every jax leaf and start its D2H transfer; host leaves
    are copied so later caller mutation cannot race the writer."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from ..ndarray import NDArray
    from ..random import key_data_of

    def snap(x):
        if isinstance(x, NDArray):
            x = x._get()
        if isinstance(x, jax.Array):
            if jnp.issubdtype(x.dtype, jax.dtypes.prng_key):
                return key_data_of(x)   # 8 bytes: host copy is free
            y = jnp.copy(x)
            try:
                y.copy_to_host_async()
            except (AttributeError, RuntimeError):
                pass
            return y
        return np.array(x, copy=True)

    return _map_structure(snap, tree)


class AsyncWriter:
    """One background writer thread with bounded in-flight saves."""

    def __init__(self, name: str = "ckpt-writer", max_pending: int = 2):
        assert max_pending >= 1
        self._max_pending = max_pending
        self._jobs: List[Callable[[], None]] = []
        # RLock: a SIGTERM handler runs on the main thread between
        # bytecodes and may interrupt submit() WHILE it holds this lock;
        # the handler's blocking save then re-enters wait()/submit() on
        # the same thread — a plain Lock would self-deadlock and eat the
        # preemption grace period (Condition handles RLock re-entrancy
        # via _release_save/_acquire_restore)
        self._lock = make_rlock("checkpoint.async_writer")
        self._cv = threading.Condition(self._lock)
        self._error: Optional[BaseException] = None
        self._closed = False
        self._busy = False     # a popped job still running
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            with self._cv:
                while not self._jobs and not self._closed:
                    self._cv.wait(0.1)
                if not self._jobs:
                    return
                job = self._jobs.pop(0)
                self._busy = True
            try:
                job()
            except BaseException as exc:   # noqa: BLE001 — re-raised at caller
                with self._cv:
                    self._error = exc
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()

    def _raise_pending(self):
        if self._error is not None:
            exc, self._error = self._error, None
            raise exc

    def submit(self, job: Callable[[], None]) -> None:
        """Enqueue a save job; blocks while ``max_pending`` are in flight
        (the caller times the whole call to charge its overhead counter).
        Re-raises any previous job's failure."""
        with self._cv:
            self._raise_pending()
            if self._closed:
                raise RuntimeError("AsyncWriter is closed")
            while len(self._jobs) + (1 if self._busy else 0) \
                    >= self._max_pending:
                self._cv.wait(0.1)
                self._raise_pending()
            self._jobs.append(job)
            self._cv.notify_all()

    def wait(self) -> None:
        """Drain every queued job; re-raise a writer failure."""
        with self._cv:
            while self._jobs or self._busy:
                self._cv.wait(0.1)
            self._raise_pending()

    def close(self, join: bool = True) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if join and self._thread.is_alive():
            self._thread.join(30.0)
        with self._cv:
            self._raise_pending()
