"""Python custom operators — all three reference generations.

Reference: python/mxnet/operator.py (802 LoC): PythonOp/NumpyOp (ctypes
callbacks into numpy), NDArrayOp (async NDArray in/out), CustomOp/CustomOpProp
+ register (newest, used with sym.Custom), plus the _Native/_NDArray symbol
ops (src/operator/native_op-inl.h, ndarray_op-inl.h, custom-inl.h:211).

TPU-native: a python custom op inside a compiled graph is a
``jax.pure_callback`` (forward) + ``jax.custom_vjp`` whose backward is a
second pure_callback — shape contracts come from the op's infer_shape, which
is required exactly as in the reference (SURVEY §7 hard-part 5).  The
callback runs on host; XLA overlaps it with device work where possible.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray
from .ops.registry import OpDef, Param, register_op, get_op
from . import symbol as _symbol

__all__ = ["PythonOp", "NumpyOp", "NDArrayOp", "CustomOp", "CustomOpProp",
           "register", "get_all_registered_operators"]

_CUSTOM_REGISTRY: Dict[str, type] = {}


class PythonOp:
    """Base class for python-side ops (reference operator.py:20-122)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError("Must override this")

    def __call__(self, *args, **kwargs):
        # reference ops are applied by calling the instance
        # (operator.py: __call__ = get_symbol)
        return self.get_symbol(*args, **kwargs)

    def forward(self, in_data, out_data):
        raise NotImplementedError("Must override this")

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError("Must override this")

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_


class NumpyOp(PythonOp):
    """Numpy-callback op (reference operator.py:122-222).  Define
    forward/backward on numpy arrays; get_symbol() returns a Symbol whose
    compiled forward runs through pure_callback."""

    def get_symbol(self, *args, **kwargs):
        op_ref = self

        class _NumpyOpDef(OpDef):
            needs_rng = False

            def list_arguments(self, p):
                return op_ref.list_arguments()

            def list_outputs(self, p):
                return op_ref.list_outputs()

            def infer_shape(self, p, in_shapes):
                if in_shapes[0] is None:
                    return in_shapes, [None] * len(op_ref.list_outputs()), []
                # secondary inputs (labels) may be unknown — the op's own
                # infer_shape derives them from the data shape, exactly
                # the reference contract (operator.py PythonOp infer).
                # Only that partial-shape case gets the lenient fallback;
                # a raise with fully-known shapes is a real user bug and
                # must propagate.
                partial = any(s is None for s in in_shapes[1:])
                shapes_arg = [list(s) if s is not None else None
                              for s in in_shapes]
                if partial:
                    try:
                        ins, outs = op_ref.infer_shape(shapes_arg)
                    except (TypeError, ValueError, IndexError,
                            AttributeError) as e:
                        # the expected failure mode: user infer_shape
                        # indexing a still-None secondary shape.  Other
                        # exception types are real bugs and propagate.
                        logging.debug(
                            "NumpyOp %s.infer_shape deferred on partial "
                            "shapes (%s); retrying when known", op_name, e)
                        return (in_shapes,
                                [None] * len(op_ref.list_outputs()), [])
                else:
                    ins, outs = op_ref.infer_shape(shapes_arg)
                return ([tuple(s) for s in ins], [tuple(s) for s in outs], [])

            def forward(self, p, inputs, aux, ctx):
                in_shapes = [tuple(x.shape) for x in inputs]
                _, out_shapes = op_ref.infer_shape([list(s) for s in in_shapes])
                out_shapes = [tuple(s) for s in out_shapes]
                dtypes = [jnp.float32] * len(out_shapes)

                def host_fwd(*np_inputs):
                    outs = [np.zeros(s, dtype=np.float32) for s in out_shapes]
                    op_ref.forward(in_data=[np.asarray(x) for x in np_inputs],
                                   out_data=outs)
                    return tuple(outs)

                def host_bwd(np_inputs, np_outputs, np_ograds):
                    in_grads = [np.zeros(s, dtype=np.float32) for s in in_shapes]
                    op_ref.backward(out_grad=[np.asarray(g) for g in np_ograds],
                                    in_data=[np.asarray(x) for x in np_inputs],
                                    out_data=[np.asarray(o) for o in np_outputs],
                                    in_grad=in_grads)
                    return tuple(in_grads)

                result_shape = tuple(
                    jax.ShapeDtypeStruct(s, d) for s, d in zip(out_shapes, dtypes))

                @jax.custom_vjp
                def f(*ins):
                    return jax.pure_callback(host_fwd, result_shape, *ins)

                def f_fwd(*ins):
                    outs = jax.pure_callback(host_fwd, result_shape, *ins)
                    return outs, (ins, outs)

                def f_bwd(res, g):
                    ins, outs = res
                    in_struct = tuple(jax.ShapeDtypeStruct(s, jnp.float32)
                                      for s in in_shapes)
                    grads = jax.pure_callback(host_bwd, in_struct, ins, outs, g)
                    return tuple(grads)

                f.defvjp(f_fwd, f_bwd)
                outs = f(*inputs)
                return list(outs)

        name = kwargs.pop("name", None)
        op_name = "_numpy_op_%d" % id(self)
        cls = type("_NumpyOp_%d" % id(self), (_NumpyOpDef,), {})
        register_op(op_name, hint="numpyop")(cls)
        input_syms = [a for a in args if isinstance(a, _symbol.Symbol)]
        sym_kwargs = {k: v for k, v in kwargs.items()
                      if isinstance(v, _symbol.Symbol)}
        return _symbol._create(op_name, input_syms, name=name, **sym_kwargs)


class NDArrayOp(NumpyOp):
    """Async NDArray custom op (reference operator.py:222+).  On TPU the
    numpy-callback path already overlaps via XLA host callbacks, so this
    shares the NumpyOp bridge while keeping the NDArray-flavored override
    points."""

    def forward(self, in_data, out_data):
        raise NotImplementedError("Must override this")

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError("Must override this")


class CustomOp:
    """Newest-generation custom op (reference operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError()

    def assign(self, dst, req, src):
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp:
    """Property class for CustomOp (reference operator.py CustomOpProp)."""

    def __init__(self, need_top_grad: bool = True):
        self.need_top_grad_ = need_top_grad
        self.kwargs: Dict[str, str] = {}

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Tensors backward depends on (reference operator.py:~540): the
        dependency-pruning hook.  XLA dead-code-eliminates unused inputs in
        the compiled vjp, so this surface exists for API parity and for
        ABI-registered props to expose their declaration; the executor
        always materializes the full set (documented divergence)."""
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes) -> CustomOp:
        raise NotImplementedError()


def register(reg_name: str):
    """Register a CustomOpProp subclass under sym.Custom(op_type=reg_name)
    (reference operator.py register)."""
    def do_register(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls
    return do_register


def get_all_registered_operators():
    return sorted(_CUSTOM_REGISTRY)


@register_op("_NDArray", hint="ndarrayop")
class _NDArrayShimOp(OpDef):
    """reference ndarray_op-inl.h: handle-passing symbol for NDArrayOp.
    In this build NDArrayOp.get_symbol registers a dedicated op per instance
    (no raw pointers across an ABI), so this shim only reports the path."""
    params = [Param("info", str, default="")]

    def forward(self, p, inputs, aux, ctx):
        raise MXNetError("_NDArray pointer-passing is not used in the TPU "
                         "build; construct the symbol via NDArrayOp.get_symbol")


@register_op("_Native", hint="nativeop")
class _NativeShimOp(_NDArrayShimOp):
    """reference native_op-inl.h — see _NDArray shim; use NumpyOp.get_symbol."""

    def forward(self, p, inputs, aux, ctx):
        raise MXNetError("_Native pointer-passing is not used in the TPU "
                         "build; construct the symbol via NumpyOp.get_symbol")


@register_op("Custom", hint="custom")
class CustomSymbolOp(OpDef):
    """sym.Custom(..., op_type='name') (reference custom-inl.h:211).
    Extra kwargs beyond op_type flow to the prop constructor as strings
    (reference keeps them as the kwargs_ vector handed to the creator)."""
    params = [Param("op_type", str, required=True)]
    allow_extra_params = True

    def _prop(self, p) -> CustomOpProp:
        if p.op_type not in _CUSTOM_REGISTRY:
            raise MXNetError("custom op %r not registered (have %s)"
                             % (p.op_type, get_all_registered_operators()))
        prop = _CUSTOM_REGISTRY[p.op_type](**(p.get("_extras") or {}))
        return prop

    def list_arguments(self, p):
        return self._prop(p).list_arguments()

    def list_outputs(self, p):
        return self._prop(p).list_outputs()

    def list_auxiliary_states(self, p):
        return self._prop(p).list_auxiliary_states()

    def infer_shape(self, p, in_shapes):
        if any(s is None for s in in_shapes):
            return in_shapes, [None] * len(self.list_outputs(p)), []
        prop = self._prop(p)
        res = prop.infer_shape([list(s) for s in in_shapes])
        ins, outs = res[0], res[1]
        aux = res[2] if len(res) > 2 else []
        return ([tuple(s) for s in ins], [tuple(s) for s in outs],
                [tuple(s) for s in aux])

    def forward(self, p, inputs, aux, ctx):
        prop = self._prop(p)
        in_shapes = [tuple(x.shape) for x in inputs]
        res = prop.infer_shape([list(s) for s in in_shapes])
        out_shapes = [tuple(s) for s in res[1]]
        op = prop.create_operator(None, in_shapes, [np.float32] * len(in_shapes))

        def host_fwd(*np_ins):
            ins_nd = [NDArray(jnp.asarray(x)) for x in np_ins]
            outs_nd = [NDArray(jnp.zeros(s, jnp.float32)) for s in out_shapes]
            op.forward(is_train=ctx.is_train, req=["write"] * len(outs_nd),
                       in_data=ins_nd, out_data=outs_nd, aux=[])
            return tuple(o.asnumpy() for o in outs_nd)

        def host_bwd(np_ins, np_outs, np_ogs):
            ins_nd = [NDArray(jnp.asarray(x)) for x in np_ins]
            outs_nd = [NDArray(jnp.asarray(x)) for x in np_outs]
            ogs_nd = [NDArray(jnp.asarray(x)) for x in np_ogs]
            igs_nd = [NDArray(jnp.zeros(s, jnp.float32)) for s in in_shapes]
            op.backward(req=["write"] * len(igs_nd), out_grad=ogs_nd,
                        in_data=ins_nd, out_data=outs_nd, in_grad=igs_nd, aux=[])
            return tuple(g.asnumpy() for g in igs_nd)

        result_struct = tuple(jax.ShapeDtypeStruct(s, jnp.float32)
                              for s in out_shapes)

        @jax.custom_vjp
        def f(*ins):
            return jax.pure_callback(host_fwd, result_struct, *ins)

        def f_fwd(*ins):
            outs = jax.pure_callback(host_fwd, result_struct, *ins)
            return outs, (ins, outs)

        def f_bwd(res_, g):
            ins, outs = res_
            in_struct = tuple(jax.ShapeDtypeStruct(s, jnp.float32)
                              for s in in_shapes)
            grads = jax.pure_callback(host_bwd, in_struct, ins, outs, g)
            return tuple(grads)

        f.defvjp(f_fwd, f_bwd)
        return list(f(*inputs))
