"""Input-pipeline benchmark legs: RecordIO -> decode -> device -> train.

Measures what bench.py's device-only number deliberately excludes: the
host-side cost of feeding the chip.  Legs over synthetic .rec files built
at bench time (self-contained, no dataset on disk):

  jpeg:     training-resolution PHOTO-ENTROPY JPEGs (high-frequency
            content at realistic ~100KB/file — an upscaled-noise-free
            workload; VERDICT r5 #2 showed 8x8-upscaled images decode
            several times cheaper than real photos) through the native
            loader's libjpeg worker threads + crop/mirror/normalize.
  scaling:  the same jpeg leg at 1 thread and at >=2 threads, so every
            BENCH artifact carries a thread-scaling datum even from a
            1-core tunnel host (io_thread_speedup).
  nproc:    the same JPEG decode through 1/2/4 forked SHARDED READER
            PROCESSES (feed.ParallelReader) — the past-the-GIL scaling
            datum (io_jpeg_img_s_nproc, io_reader_scaling) that
            io_feed_headroom is recomputed against.
  u8:       the compact-wire decode rate (uint8 HWC out, augmentation
            on device) and the H2D probe in BOTH wire formats
            (io_h2d_mb_s / io_h2d_mb_s_u8, io_h2d_bytes_ratio ~ 4).
  raw:      raw-CHW-packed records (decode-free), isolating framing +
            normalize cost.
  pipeline: the COMBINED loader -> Module.fit leg: NativeImageRecordIter
            feeding a small conv net through the feed subsystem's
            prefetch-to-device staging (mxnet_tpu.feed), recording
            io_pipeline_img_s (end-to-end trained img/s),
            io_train_img_s (same step on a pre-staged batch: the chip's
            demand), and io_feed_headroom = feed capacity / train demand
            — >1 means the input side keeps pace with the compute side.

Throughput scales with host cores (each worker owns a full decode
chain); `io_host_cores` is reported so a 1-core tunnel host and a
32-core production host are both interpretable.
"""
import os
import tempfile
import time

import numpy as np


def _build_jpeg_rec(path, n=160, edge=256, quality=95, seed=0):
    """Pack n photo-entropy JPEGs (shorter edge = `edge`) into a .rec.

    Content = smooth low-frequency base + mid-frequency gratings +
    per-pixel texture noise: energy across the whole spectrum, like a
    detailed photograph, costing libjpeg real Huffman + IDCT work
    (~90-100KB/file at q95 and 256-edge — what im2rec --resize 256
    produces from ImageNet).  The old upscaled-8x8 images had nearly
    flat DCT blocks and decoded several times cheaper (VERDICT r5 #2).
    Returns mean encoded KB per file."""
    import io as _io
    from PIL import Image
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    total = 0
    for i in range(n):
        h, wd = edge, edge + int(rng.randint(0, 96))
        if rng.rand() < 0.5:
            h, wd = wd, h
        base = rng.randint(0, 255, (32, 32, 3)).astype(np.uint8)
        smooth = np.asarray(Image.fromarray(base).resize((wd, h),
                                                         Image.BILINEAR),
                            np.float32)
        yy, xx = np.mgrid[0:h, 0:wd].astype(np.float32)
        grating = sum(40.0 * np.sin(2 * np.pi * (xx * fx + yy * fy))
                      for fx, fy in ((0.11, 0.07), (0.23, 0.31),
                                     (0.43, 0.17)))
        texture = rng.normal(0.0, 45.0, (h, wd, 3)).astype(np.float32)
        img = np.clip(smooth + grating[..., None] + texture,
                      0, 255).astype(np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(img).save(buf, format="JPEG", quality=quality)
        payload = buf.getvalue()
        total += len(payload)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 1000), i, 0),
                              payload))
    w.close()
    return total / n / 1024.0


def _build_raw_rec(path, n=160, shape=(3, 224, 224), seed=0):
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        arr = rng.randint(0, 255, shape).astype(np.uint8)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 1000), i, 0),
                              arr.tobytes()))
    w.close()


def _pump(loader, seconds=4.0):
    """Drain epochs for ~seconds; returns host-pipeline img/s (decoded
    float32 batches staged in host RAM, ready for H2D)."""
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        out = loader.next()
        if out is None:
            loader.reset()
            continue
        n += out[0].shape[0]
    return n / (time.perf_counter() - t0)


def _jpeg_rate(jpeg_rec, batch, threads, seconds):
    from mxnet_tpu.native_io import NativeBatchLoader
    ld = NativeBatchLoader(jpeg_rec, batch, (3, 224, 224), threads=threads,
                           shuffle=True, rand_crop=True, rand_mirror=True,
                           scale=1.0 / 255)
    rate = _pump(ld, seconds=seconds)
    del ld
    return rate


def _h2d_probe(batch=128, iters=8, dtype="f32"):
    """Host->device bandwidth for one training batch (MB/s) plus its
    per-batch byte count.  Two legs: the classic ``f32`` CHW batch and
    the compact ``u8`` HWC batch the device-augment feed ships — same
    image payload, 4x fewer bytes on the wire (the win the f32-only
    number used to hide).  Reported separately from the pipeline rate:
    on a production TPU host this is a local DMA that overlaps compute
    (PJRT async dispatch); through the bench tunnel it is a network hop
    and would dominate any combined number, which is why the
    device-side bench pre-stages batches."""
    import jax
    if dtype == "u8":
        x = np.random.randint(0, 256, (batch, 224, 224, 3),
                              dtype=np.uint8)
    else:
        x = np.random.rand(batch, 3, 224, 224).astype(np.float32)
    jax.block_until_ready(jax.device_put(x))  # warm path
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(jax.device_put(x))
    dt = time.perf_counter() - t0
    return x.nbytes * iters / dt / 1e6, x.nbytes


def _pump_feed(it, seconds):
    """Drain a FeedDataIter for ~seconds (rolling epochs); img/s."""
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        try:
            batch = it.next()
        except StopIteration:
            it.reset()
            continue
        n += batch.data[0].shape[0] - batch.pad
    return n / (time.perf_counter() - t0)


def _reader_rate(jpeg_rec, batch, procs, seconds, device_augment=False):
    """Multi-PROCESS sharded-reader rate (mxnet_tpu.feed.ParallelReader):
    .rec -> N forked decode workers -> shuffle window -> host batches.
    The process sweep is the datum the thread sweep cannot give — PIL
    decode holds the GIL, so threads cap near 1 core while processes
    scale with the host."""
    from mxnet_tpu import feed
    it = feed.record_pipeline(
        jpeg_rec, batch, (3, 224, 224), resize=256, rand_crop=True,
        rand_mirror=True, scale=1.0 / 255, reader_procs=procs,
        shuffle_window=64, device_augment=device_augment, seed=0,
        to_device=False, name="bench_reader_%dp" % procs)
    try:
        # one warm batch first: worker fork + first chunked pread out of
        # the measured window
        it.next()
        return _pump_feed(it, seconds)
    finally:
        it.close()


def _bench_net():
    """Small conv net for the combined leg: enough MXU/ALU work to be a
    believable consumer, small enough that the leg measures the FEED."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, num_filter=16, kernel=(7, 7),
                             stride=(4, 4), name="conv0")
    net = mx.sym.Pooling(net, kernel=(7, 7), stride=(7, 7), pool_type="avg",
                         name="pool0")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=100, name="fc0")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _sync_module(mod):
    import jax
    if getattr(mod, "_fused_state", None) is not None:
        jax.block_until_ready(next(iter(mod._fused_state["params"].values())))
    else:
        mod.get_outputs()[0].asnumpy()


def _pipeline_leg(jpeg_rec, batch, threads, seconds, feed):
    """Combined loader -> Module.fit leg through feed.prefetch-to-device.

    Epoch 0 warms up (compiles the fused step); epoch 1 is measured
    batch-end to batch-end.  Returns io_pipeline_img_s (end-to-end),
    io_train_img_s (pre-staged step rate), io_feed_headroom (host feed
    capacity / chip demand), and io_h2d_stall_s (time the device feed
    spent starved by the host pipeline during the measured epoch)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.io import NativeImageRecordIter, ResizeIter

    ctx = mx.tpu(0) if jax.devices()[0].platform != "cpu" else mx.cpu(0)
    steps = max(4, int(2 * seconds))
    src = NativeImageRecordIter(jpeg_rec, (3, 224, 224), batch,
                                preprocess_threads=threads, shuffle=True,
                                rand_crop=True, rand_mirror=True,
                                scale=1.0 / 255)
    it = ResizeIter(src, steps)
    mod = mx.mod.Module(_bench_net(), context=ctx)
    marks = {"n": 0}

    def cb(param):
        feed("io-pipeline")
        if param.epoch == 1:
            if param.nbatch == 0:
                marks["t0"] = time.perf_counter()
                marks["stall0"] = \
                    wrapped.stats.report()["h2d"]["stall_in_s"]
            marks["n"] = param.nbatch + 1
            marks["t1"] = time.perf_counter()

    # wrap OURSELVES (not via fit(prefetch_to_device=True)) and keep the
    # wrapper alive: its stats registration is weak, and a wrapper local
    # to fit()'s frame would be gone — stall counters with it — before
    # this leg could read them.  Sharding still resolves lazily from the
    # module's fused step, which exists by the first staged batch.
    wrapped = mx.feed.device_feed(it, module=mod, depth=2)
    mod.fit(wrapped, num_epoch=2, batch_end_callback=cb,
            optimizer_params=(("learning_rate", 0.01),))
    out = {}
    if marks["n"] > 1:
        wall = marks["t1"] - marks["t0"]
        out["io_pipeline_img_s"] = round((marks["n"] - 1) * batch / wall, 1)
    # the h2d stall counter: how long the chip-side consumer waited on
    # the host pipeline during the MEASURED epoch (epoch 0 is warm-up/
    # compile, so the cumulative counter is snapshotted at epoch-1 start)
    out["io_h2d_stall_s"] = round(
        wrapped.stats.report()["h2d"]["stall_in_s"]
        - marks.get("stall0", 0.0), 4)

    # chip demand: the same step on one pre-staged resident batch
    feed("io-train-only")
    staged = mod.prefetch_to_device(ResizeIter(src, 1), depth=1).next()
    for _ in range(2):
        mod.forward(staged, is_train=True)
        mod.backward()
        mod.update()
    _sync_module(mod)
    t0 = time.perf_counter()
    for _ in range(steps):
        mod.forward(staged, is_train=True)
        mod.backward()
        mod.update()
    _sync_module(mod)
    out["io_train_img_s"] = round(
        steps * batch / (time.perf_counter() - t0), 1)
    return out


def run(batch=128, threads=None, seconds=4.0, feed=lambda *_: None,
        pipeline=True):
    """Returns dict of io_* metrics.  `feed` is the watchdog heartbeat."""
    from mxnet_tpu.native_io import lib_available, NativeBatchLoader
    if not lib_available():
        raise RuntimeError("libmxtpu.so not built")
    cores = os.cpu_count() or 1
    threads = threads or cores
    out = {"io_host_cores": cores, "io_threads": threads}
    with tempfile.TemporaryDirectory() as tmp:
        feed("io-build")
        jpeg_rec = os.path.join(tmp, "bench_jpeg.rec")
        raw_rec = os.path.join(tmp, "bench_raw.rec")
        out["io_jpeg_kb_mean"] = round(_build_jpeg_rec(jpeg_rec), 1)
        _build_raw_rec(raw_rec)
        feed("io-jpeg")
        out["io_jpeg_img_s"] = round(
            _jpeg_rate(jpeg_rec, batch, threads, seconds), 1)
        # thread-scaling datum (VERDICT r5 weak #2): 1 thread vs >=2, so
        # the decode pipeline's parallel speedup is measured every round
        # even when the main leg runs single-threaded
        mt = max(2, threads)
        feed("io-jpeg-scaling")
        t1_rate = (out["io_jpeg_img_s"] if threads == 1 else
                   round(_jpeg_rate(jpeg_rec, batch, 1, seconds / 2), 1))
        mt_rate = (out["io_jpeg_img_s"] if threads == mt else
                   round(_jpeg_rate(jpeg_rec, batch, mt, seconds / 2), 1))
        out["io_jpeg_img_s_1t"] = t1_rate
        out["io_jpeg_img_s_mt"] = mt_rate
        out["io_threads_mt"] = mt
        if t1_rate:
            out["io_thread_speedup"] = round(mt_rate / t1_rate, 2)
        # reader-PROCESS scaling sweep (the tentpole datum): the same
        # JPEG decode through 1/2/4 forked sharded readers.  Threads cap
        # near one core (GIL); io_feed_headroom below is recomputed
        # against the best multi-process rate, because that is what a
        # production host would actually run.
        nproc_rates = {}
        for procs in (1, 2, 4):
            feed("io-reader-%dp" % procs)
            try:
                nproc_rates[str(procs)] = round(
                    _reader_rate(jpeg_rec, batch, procs, seconds / 2), 1)
            except Exception as e:
                import sys
                sys.stderr.write("bench_io: %d-proc reader leg failed "
                                 "(%s)\n" % (procs, e))
        if nproc_rates:
            out["io_jpeg_img_s_nproc"] = nproc_rates
            if nproc_rates.get("1"):
                best = max(nproc_rates.values())
                out["io_reader_scaling"] = round(
                    best / nproc_rates["1"], 2)
        # compact-wire decode rate: same readers, uint8 HWC output (the
        # device-augment path's host-side cost — no float convert, no
        # python crop/flip/normalize)
        feed("io-reader-u8")
        try:
            out["io_jpeg_u8_img_s"] = round(_reader_rate(
                jpeg_rec, batch, min(4, max(2, cores)), seconds / 2,
                device_augment=True), 1)
        except Exception as e:
            import sys
            sys.stderr.write("bench_io: u8 reader leg failed (%s)\n" % e)
        feed("io-raw")
        ld = NativeBatchLoader(raw_rec, batch, (3, 224, 224),
                               threads=threads, shuffle=True)
        out["io_raw_img_s"] = round(_pump(ld, seconds=seconds), 1)
        del ld
        if pipeline:
            feed("io-pipeline")
            try:
                out.update(_pipeline_leg(jpeg_rec, batch, threads, seconds,
                                         feed))
                if out.get("io_train_img_s"):
                    # headroom against the BEST feed the host can mount:
                    # multi-process sharded readers when they beat the
                    # native thread loader (>1 = the chip stays fed)
                    rates = [out["io_jpeg_img_s"]]
                    rates += [r for r in
                              out.get("io_jpeg_img_s_nproc", {}).values()
                              if r]
                    out["io_feed_img_s_best"] = max(rates)
                    out["io_feed_headroom"] = round(
                        out["io_feed_img_s_best"]
                        / out["io_train_img_s"], 3)
            except Exception as e:   # combined leg is additive, never fatal
                import sys
                sys.stderr.write("bench_io: pipeline leg failed (%s)\n" % e)
    feed("io-h2d")
    try:
        # both wire formats: f32 CHW (the classic feed) and uint8 HWC
        # (the device-augment feed) — the byte ratio IS the compact-H2D
        # win, and the f32-only number used to hide it
        mb_f32, bytes_f32 = _h2d_probe(batch, dtype="f32")
        mb_u8, bytes_u8 = _h2d_probe(batch, dtype="u8")
        out["io_h2d_mb_s"] = round(mb_f32, 1)
        out["io_h2d_mb_s_u8"] = round(mb_u8, 1)
        out["io_h2d_batch_bytes_f32"] = bytes_f32
        out["io_h2d_batch_bytes_u8"] = bytes_u8
        out["io_h2d_bytes_ratio"] = round(bytes_f32 / bytes_u8, 2)
    except Exception:
        pass
    return out


if __name__ == "__main__":
    import json
    print(json.dumps(run()))
