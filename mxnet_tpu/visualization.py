"""Network visualization. Reference: python/mxnet/visualization.py (152 LoC)."""
from __future__ import annotations

import json
from typing import Dict, Optional

from .base import MXNetError
from .symbol import Symbol

__all__ = ["plot_network", "print_summary"]


def print_summary(symbol: Symbol, shape: Optional[Dict] = None):
    """Print layer summary table (reference visualization.py print_summary)."""
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if shape is not None:
        _, out_shapes, _ = symbol.get_internals().infer_shape(**shape)
    print("%-30s %-20s %-20s" % ("Layer (type)", "Op", "Param"))
    print("=" * 72)
    total = 0
    for node in nodes:
        if node["op"] == "null":
            continue
        print("%-30s %-20s %-20s" % (node["name"], node["op"],
                                     str(node.get("param", {}))))
    print("=" * 72)


def plot_network(symbol: Symbol, title="plot", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot (reference visualization.py plot_network)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires graphviz; "
                         "use print_summary for a text view")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    for i, node in enumerate(nodes):
        name = node["name"]
        if node["op"] == "null":
            if hide_weights and (name.endswith("weight") or name.endswith("bias")
                                 or name.endswith("gamma") or name.endswith("beta")):
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label="%s\n%s" % (name, node["op"]), shape="box")
    for node in nodes:
        if node["op"] == "null":
            continue
        for (j, _) in node["inputs"]:
            src = nodes[j]["name"]
            dot.edge(tail_name=src, head_name=node["name"])
    return dot
