"""``mxnet_tpu.autotune`` — measurement-driven search over the knob
space the repo already exposes.

A dozen performance knobs ship hand-tuned per model (superstep K, serve
bucket grids, pass-pipeline variants, quantize op sets, warmup threads);
this package closes ROADMAP item 3's second half by SEARCHING that space
with measurements instead of folklore, on the infrastructure PRs 5/8/9
built:

* **candidate evaluation is cheap** — every candidate program rides
  ``compile_cache``, so a warm candidate costs one dispatch, not one
  XLA compile;
* **cost comes from trace spans** — candidates run under
  ``autotune:candidate`` spans and the tuner reads the durations back
  from the recorder (``trace.span_events``): the numbers in
  ``mx.profiler.autotune_report()`` are the numbers in the exported
  Perfetto timeline;
* **winners persist** — per (model-symbol digest, input shapes,
  backend topology) fingerprint, atomically
  (``base.atomic_local_write``), under ``MXNET_AUTOTUNE_DIR``; a fresh
  process loads the config with zero measurements;
* **selection is deterministic** — ``select_best`` is a pure function
  of the measurement log (min cost, ties by order), so a stored log
  replays to the stored winner.

Entry points::

    Module.fit(..., autotune=True)     # tunes superstep K
    ServeEngine(..., autotune=True)    # tunes the pass-pipeline variant
    Module.fit(autotune="joint")       # joint space, cost-model-ranked
    ServeEngine(autotune="joint")      # fuse x bucket grid x quantize ops
    MXNET_AUTOTUNE=1 / =joint          # same, via env
    mx.profiler.autotune_report_str()  # what was decided, from what

See docs/autotune.md for the joint-space workflow and the cost-model
lifecycle; docs/fusion.md ("Autotuning") for the per-axis tuners.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import get_env
from .measure import (CANDIDATE_SPAN, backend_descriptor, measure_candidate,
                      timed_span, tuning_key)
from .store import (config_path, list_configs, load_config, save_config,
                    store_dir)
from .tuner import Autotuner, AutotuneStats, select_best

__all__ = ["Autotuner", "AutotuneStats", "select_best", "tuning_key",
           "backend_descriptor", "measure_candidate", "timed_span",
           "store_dir", "config_path", "load_config", "save_config",
           "list_configs", "enabled", "mode", "tune_superstep",
           "tune_serve_pipeline", "JointTuner", "tune_fit_joint",
           "tune_serve_joint", "default_shortlist", "CANDIDATE_SPAN"]

# the profiler registry holds stats weakly (live-object reporting); a
# tuning run is an EVENT, so keep the last N strongly here or every
# report after fit returns would be empty
_MAX_KEPT = 64
_kept_stats: List[AutotuneStats] = []


def _register_stats(stats: AutotuneStats) -> None:
    from .. import profiler
    _kept_stats.append(stats)
    del _kept_stats[:-_MAX_KEPT]
    profiler.register_autotune_stats(stats)


def enabled(flag=None) -> bool:
    """Resolve an ``autotune=`` argument: an explicit True/False wins;
    None falls back to the ``MXNET_AUTOTUNE`` env knob (default off)."""
    if flag is not None:
        return bool(flag)
    return get_env("MXNET_AUTOTUNE", False, bool)


def mode(flag=None):
    """Resolve an ``autotune=`` argument to a tuning MODE: ``"joint"``
    (rank the joint space with the cost model, measure a shortlist),
    ``"measure"`` (PR 11's brute per-axis measurement — what ``True``
    means), or None (off).  ``MXNET_AUTOTUNE=joint`` selects joint via
    env, any other truthy env value selects measure."""
    if flag is None:
        env = get_env("MXNET_AUTOTUNE", "", str)
        if env in ("", "0", "false", "False"):
            return None
        return "joint" if env == "joint" else "measure"
    if isinstance(flag, str):
        if not flag:
            return None
        return flag if flag == "joint" else "measure"
    return "measure" if flag else None


# -- fit-side tuning: superstep K --------------------------------------------

def _zero_batch(module):
    """A zero DataBatch at the module's bound shapes — superstep cost
    does not depend on data values, so measurement needs no real feed
    (the same trick Module.prepare uses), including the compact uint8
    wire when on-device augmentation is active."""
    from ..io import DataBatch
    from ..ndarray import NDArray, zeros as nd_zeros
    import jax.numpy as jnp
    spec = getattr(module._fused, "device_augment", None)
    if spec is not None:
        batch = module._data_shapes[0][1][0]
        data = [NDArray(jnp.zeros((batch,) + spec.pre_shape, jnp.uint8))]
        data += [nd_zeros(s) for _, s in module._data_shapes[1:]]
    else:
        data = [nd_zeros(s) for _, s in module._data_shapes]
    return DataBatch(data=data,
                     label=[nd_zeros(s)
                            for _, s in (module._label_shapes or [])])


def _measure_superstep(module, k: int, trials: int,
                       unroll: int = 1) -> float:
    """Seconds per TRAINING STEP at superstep K, measured by dispatching
    the real (warm) program on a COPY of the live train state — the
    donated copy is discarded, so measurement never advances training
    (no param, optimizer-slot, step-counter or RNG drift)."""
    import jax
    import jax.numpy as jnp
    fused = module._fused
    state = module._fused_state
    key = module._fused_key
    holder: Dict[str, Any] = {}

    def setup():
        holder["state"] = jax.tree_util.tree_map(jnp.copy, state)

    if k == 1:
        pend = fused.make_batch(_zero_batch(module))

        def run():
            new_state, _outs = fused.step(holder.pop("state"), pend, key)
            jax.block_until_ready(
                next(iter(new_state["params"].values()), new_state["t"]))

        return measure_candidate(run, label="superstep=1", trials=trials,
                                 warmup=1, setup=setup)
    _k, mega = fused.make_megabatch([_zero_batch(module)
                                     for _ in range(k)])
    prog = fused.build_superstep(k, None, unroll=unroll)
    lr = float(module._optimizer.base_lr())
    lrs = jax.device_put(np.asarray([lr] * k, np.float32),
                         fused._replicated())

    def run():
        new_state, _acc = prog(holder.pop("state"), mega, lrs, key, ())
        jax.block_until_ready(
            next(iter(new_state["params"].values()), new_state["t"]))

    return measure_candidate(run, label="superstep=%d,unroll=%d"
                             % (k, unroll), trials=trials,
                             warmup=1, setup=setup) / k


def tune_superstep(module, candidates: Sequence[int] = (1, 2, 4, 8),
                   viable: Optional[Callable[[int], Optional[str]]] = None,
                   trials: int = 2, persist: bool = True) -> int:
    """Pick superstep K by measuring — the fit-side autotune entry
    (``Module.fit(autotune=True)`` calls this when neither the
    ``superstep=`` argument nor ``MXNET_SUPERSTEP`` chose).

    ``viable(k)`` returns a blocker string (Module._superstep_blockers)
    or None; blocked Ks leave the candidate list.  Returns 1 when the
    fused path is off or nothing beyond K=1 survives.  The winner
    persists per (symbol, shapes, optimizer, K-space, topology) key and
    a fresh process reloads it without measuring."""
    fused = getattr(module, "_fused", None)
    if fused is None or not module.optimizer_initialized:
        return 1
    ks = sorted({int(k) for k in candidates if int(k) >= 1})
    if viable is not None:
        ks = [k for k in ks if k == 1 or viable(k) is None]
    if not ks:
        return 1
    if ks == [1]:
        return 1
    key = tuning_key(
        "fit:superstep", module._symbol.tojson(),
        sorted(module._data_shapes), sorted(module._label_shapes or []),
        type(module._optimizer).__name__, fused.hparam_signature(),
        tuple(ks))
    module._fused_ensure_state()
    tuner = Autotuner("fit:superstep", key, persist=persist)
    best, _cost = tuner.tune(
        [{"superstep": k} for k in ks],
        lambda cfg: _measure_superstep(module, cfg["superstep"], trials),
        meta={"candidates": ks, "backend": backend_descriptor()})
    return int(best["superstep"])


# -- serve-side tuning: pass-pipeline variant --------------------------------

def _quantize_tag(quantize) -> str:
    """Stable digest material for a ServeEngine ``quantize=`` argument
    (str mode, falsy, or a kwargs dict whose array values must not join
    the key)."""
    if not quantize:
        return "-"
    if isinstance(quantize, str):
        return quantize
    if isinstance(quantize, dict):
        return ";".join(
            "%s=%r" % (k, v) for k, v in sorted(quantize.items())
            if isinstance(v, (str, int, float, bool, tuple)))
    return type(quantize).__name__


def tune_serve_pipeline(symbol_json: str, params: Dict,
                        shapes: Dict[str, Tuple[int, ...]],
                        data_name: str = "data", quantize=None,
                        calib_data=None, u8_wire=None,
                        dev: Tuple[str, int] = ("cpu", 0),
                        name: str = "autotune",
                        trials: int = 5, persist: bool = True):
    """Pick the serving pass-pipeline variant by measuring — the
    ``ServeEngine(autotune=True)`` entry.  Candidates are the fusion
    variants (``fuse`` on/off around the same fold/CSE/DCE/quantize
    spine); each builds a Predictor at the engine's max bucket through
    ``compile_cache`` and is timed over warm steady-state forwards.

    Returns ``(fuse, pipeline)``: the winning ``fuse`` setting plus the
    winner's already-built PassPipeline when this call measured (so the
    caller skips a third build — with int8 that is a full calibration
    pass), or None on a store hit (the caller builds one; persisted per
    (symbol, shapes, quantize mode, wire, topology))."""
    from ..passes import build_serving_pipeline
    from ..predictor import Predictor
    key = tuning_key("serve:pipeline", symbol_json,
                     sorted((k, tuple(v)) for k, v in shapes.items()),
                     data_name, _quantize_tag(quantize), bool(u8_wire))
    tuner = Autotuner("serve:pipeline", key, persist=persist)
    built: Dict[bool, Any] = {}

    def measure(cfg):
        pipe = build_serving_pipeline(
            quantize=quantize, calib_data=calib_data,
            calib_shapes=dict(shapes), data_name=data_name,
            u8_wire=u8_wire, fuse=cfg["fuse"], name=name)
        built[bool(cfg["fuse"])] = pipe
        p = Predictor(symbol_json, dict(params), dict(shapes),
                      dev[0], dev[1], pipeline=pipe)
        arr = p._exec.arg_dict[data_name]
        data = np.zeros(tuple(arr.shape), np.dtype(arr.dtype))

        def run():
            p.set_input(data_name, data)
            p.forward()
            p.get_output(0)

        return measure_candidate(run, label="fuse=%s" % cfg["fuse"],
                                 trials=trials, warmup=2)

    best, _cost = tuner.tune(
        [{"fuse": True}, {"fuse": False}], measure,
        meta={"quantize": _quantize_tag(quantize),
              "backend": backend_descriptor()})
    fuse = bool(best["fuse"])
    return fuse, built.get(fuse)


# -- joint-space tuning (cost-model-ranked; see joint.py) --------------------
# imported LAST: joint builds on everything above (and lazily imports
# _measure_superstep/_zero_batch back from here)
from .joint import (JointTuner, default_shortlist,  # noqa: E402
                    tune_fit_joint, tune_serve_joint)
