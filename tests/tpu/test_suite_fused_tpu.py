"""TPU re-run of tests/test_fused.py (reference: tests/python/gpu/
test_operator_gpu.py re-collects the unit suite on the accelerator)."""
from _mirror import tpu_gate

pytestmark = tpu_gate()

from test_fused import *  # noqa: F401,F403,E402

# need the 8-device CPU mesh; the TPU session exposes a single host device
del test_fused_multi_device_matches_single  # noqa: F821
del test_sharded_weight_update_matches_replicated  # noqa: F821
del test_sharded_update_survives_classic_fallback  # noqa: F821
