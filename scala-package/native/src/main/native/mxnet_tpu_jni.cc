/*
 * JNI glue for the Scala/JVM binding: marshals between JVM arrays and the
 * C ABI (include/c_api.h), loaded at runtime with dlopen like the R glue
 * (R-package/src/mxnet_glue.c).  Reference counterpart:
 * scala-package/native/src/main/native/ml_dmlc_mxnet_native_c_api.cc —
 * but where the reference calls back into Scala collection methods
 * (ListBuffer.append per element), this glue exchanges flat primitive
 * arrays in single JNI calls: fewer JVM crossings per ABI call, and the
 * whole surface is drivable under a mocked jni.h (tests/cpp/jniheaders/)
 * in images with no JVM.
 *
 * Conventions:
 *   - handles are jlong (pointer-sized on every JVM);
 *   - int-returning natives pass the ABI rc through (0 ok, -1 error,
 *     message via mxGetLastError);
 *   - natives returning jstring/array objects return null on error;
 *   - out-handles land in a caller-allocated jlongArray of length 1.
 */
#include <jni.h>

#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include <string>
#include <vector>

typedef uint32_t mx_uint;
typedef float mx_float;
typedef void *NDArrayHandle;
typedef const void *FunctionHandle;
typedef const void *AtomicSymbolCreator;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *KVStoreHandle;
typedef void *OptimizerHandle;
typedef const void *OptimizerCreator;

/* ---- resolved ABI ---------------------------------------------------- */
static struct {
  void *dl;
  const char *(*GetLastError)();
  int (*RandomSeed)(int);
  int (*NotifyShutdown)();
  int (*NDArrayCreateEx)(const mx_uint *, mx_uint, int, int, int, int,
                         NDArrayHandle *);
  int (*NDArrayCreateNone)(NDArrayHandle *);
  int (*NDArrayFree)(NDArrayHandle);
  int (*NDArrayWaitAll)();
  int (*NDArrayWaitToRead)(NDArrayHandle);
  int (*NDArraySyncCopyFromCPU)(NDArrayHandle, const void *, size_t);
  int (*NDArraySyncCopyToCPU)(NDArrayHandle, void *, size_t);
  int (*NDArrayGetShape)(NDArrayHandle, mx_uint *, const mx_uint **);
  int (*NDArrayGetContext)(NDArrayHandle, int *, int *);
  int (*NDArraySlice)(NDArrayHandle, mx_uint, mx_uint, NDArrayHandle *);
  int (*NDArrayAt)(NDArrayHandle, mx_uint, NDArrayHandle *);
  int (*NDArrayReshape)(NDArrayHandle, int, int *, NDArrayHandle *);
  int (*NDArraySave)(const char *, mx_uint, NDArrayHandle *, const char **);
  int (*NDArrayLoad)(const char *, mx_uint *, NDArrayHandle **, mx_uint *,
                     const char ***);
  int (*ListFunctions)(mx_uint *, FunctionHandle **);
  int (*GetFunction)(const char *, FunctionHandle *);
  int (*FuncGetInfo)(FunctionHandle, const char **, const char **, mx_uint *,
                     const char ***, const char ***, const char ***);
  int (*FuncDescribe)(FunctionHandle, mx_uint *, mx_uint *, mx_uint *, int *);
  int (*FuncInvoke)(FunctionHandle, NDArrayHandle *, mx_float *,
                    NDArrayHandle *);
  int (*SymbolListAtomicSymbolCreators)(mx_uint *, AtomicSymbolCreator **);
  int (*SymbolGetAtomicSymbolInfo)(AtomicSymbolCreator, const char **,
                                   const char **, mx_uint *, const char ***,
                                   const char ***, const char ***,
                                   const char **);
  int (*SymbolCreateAtomicSymbol)(AtomicSymbolCreator, mx_uint, const char **,
                                  const char **, SymbolHandle *);
  int (*SymbolCreateVariable)(const char *, SymbolHandle *);
  int (*SymbolCreateGroup)(mx_uint, SymbolHandle *, SymbolHandle *);
  int (*SymbolCreateFromJSON)(const char *, SymbolHandle *);
  int (*SymbolSaveToJSON)(SymbolHandle, const char **);
  int (*SymbolFree)(SymbolHandle);
  int (*SymbolCopy)(SymbolHandle, SymbolHandle *);
  int (*SymbolCompose)(SymbolHandle, const char *, mx_uint, const char **,
                       SymbolHandle *);
  int (*SymbolListArguments)(SymbolHandle, mx_uint *, const char ***);
  int (*SymbolListOutputs)(SymbolHandle, mx_uint *, const char ***);
  int (*SymbolListAuxiliaryStates)(SymbolHandle, mx_uint *, const char ***);
  int (*SymbolGetAttr)(SymbolHandle, const char *, const char **, int *);
  int (*SymbolSetAttr)(SymbolHandle, const char *, const char *);
  int (*SymbolGetInternals)(SymbolHandle, SymbolHandle *);
  int (*SymbolGetOutput)(SymbolHandle, mx_uint, SymbolHandle *);
  int (*SymbolInferShape)(SymbolHandle, mx_uint, const char **,
                          const mx_uint *, const mx_uint *, mx_uint *,
                          const mx_uint **, const mx_uint ***, mx_uint *,
                          const mx_uint **, const mx_uint ***, mx_uint *,
                          const mx_uint **, const mx_uint ***, int *);
  int (*ExecutorBindX)(SymbolHandle, int, int, mx_uint, const char **,
                       const int *, const int *, mx_uint, NDArrayHandle *,
                       NDArrayHandle *, mx_uint *, mx_uint, NDArrayHandle *,
                       ExecutorHandle *);
  int (*ExecutorForward)(ExecutorHandle, int);
  int (*ExecutorBackward)(ExecutorHandle, mx_uint, NDArrayHandle *);
  int (*ExecutorOutputs)(ExecutorHandle, mx_uint *, NDArrayHandle **);
  int (*ExecutorFree)(ExecutorHandle);
  int (*OptimizerFindCreator)(const char *, OptimizerCreator *);
  int (*OptimizerCreateOptimizer)(OptimizerCreator, mx_uint, const char **,
                                  const char **, OptimizerHandle *);
  int (*OptimizerFree)(OptimizerHandle);
  int (*OptimizerUpdate)(OptimizerHandle, int, NDArrayHandle, NDArrayHandle,
                         mx_float, mx_float);
  int (*KVStoreCreate)(const char *, KVStoreHandle *);
  int (*KVStoreFree)(KVStoreHandle);
  int (*KVStoreInit)(KVStoreHandle, mx_uint, const int *, NDArrayHandle *);
  int (*KVStorePush)(KVStoreHandle, mx_uint, const int *, NDArrayHandle *,
                     int);
  int (*KVStorePull)(KVStoreHandle, mx_uint, const int *, NDArrayHandle *,
                     int);
  int (*KVStoreGetType)(KVStoreHandle, const char **);
  int (*KVStoreGetRank)(KVStoreHandle, int *);
  int (*KVStoreGetGroupSize)(KVStoreHandle, int *);
  int (*KVStoreBarrier)(KVStoreHandle);
  int (*KVStoreRunServer)(KVStoreHandle);
  int (*KVStoreIsWorkerNode)(int *);
  int (*KVStoreIsServerNode)(int *);
  int (*KVStoreIsSchedulerNode)(int *);
  int (*KVStoreSendCommmandToServers)(KVStoreHandle, int, const char *);
  int (*NDArraySaveRawBytes)(NDArrayHandle, size_t *, const char **);
  int (*NDArrayLoadFromRawBytes)(const void *, size_t, NDArrayHandle *);
  int (*NDArrayGetDType)(NDArrayHandle, int *);
  int (*FuncInvokeEx)(FunctionHandle, NDArrayHandle *, mx_float *,
                      NDArrayHandle *, int, char **, char **);
  int (*SymbolGetName)(SymbolHandle, const char **, int *);
  int (*SymbolListAttr)(SymbolHandle, mx_uint *, const char ***);
  int (*SymbolListAttrShallow)(SymbolHandle, mx_uint *, const char ***);
  int (*ExecutorPrint)(ExecutorHandle, const char **);
  int (*ListDataIters)(mx_uint *, const void ***);
  int (*DataIterGetIterInfo)(const void *, const char **, const char **,
                             mx_uint *, const char ***, const char ***,
                             const char ***);
  int (*DataIterCreateIter)(const void *, mx_uint, const char **,
                            const char **, void **);
  int (*DataIterFree)(void *);
  int (*DataIterNext)(void *, int *);
  int (*DataIterBeforeFirst)(void *);
  int (*DataIterGetData)(void *, NDArrayHandle *);
  int (*DataIterGetLabel)(void *, NDArrayHandle *);
  int (*DataIterGetPadNum)(void *, int *);
  int loaded;
} jx;

#define JX_RESOLVE(field, name)                            \
  do {                                                     \
    *(void **)(&jx.field) = dlsym(jx.dl, name);            \
    if (jx.field == NULL) {                                \
      snprintf(jx_init_err, sizeof(jx_init_err),           \
               "missing symbol %s", name);                 \
      return -1;                                           \
    }                                                      \
  } while (0)

static char jx_init_err[256];

/* ---- small marshalling helpers --------------------------------------- */
namespace {

struct JString {      // scoped UTF chars
  JNIEnv *env;
  jstring js;
  const char *c;
  JString(JNIEnv *e, jstring s) : env(e), js(s) {
    c = s ? e->GetStringUTFChars(s, nullptr) : nullptr;
  }
  ~JString() {
    if (c) env->ReleaseStringUTFChars(js, c);
  }
};

// jobjectArray of jstring -> vector<string> (+ stable char* view)
struct JStringArray {
  std::vector<std::string> store;
  std::vector<const char *> ptrs;
  JStringArray(JNIEnv *env, jobjectArray arr) {
    int n = arr ? env->GetArrayLength(arr) : 0;
    store.reserve(n);
    for (int i = 0; i < n; ++i) {
      jstring js = (jstring)env->GetObjectArrayElement(arr, i);
      const char *c = env->GetStringUTFChars(js, nullptr);
      store.emplace_back(c ? c : "");
      env->ReleaseStringUTFChars(js, c);
    }
    for (auto &s : store) ptrs.push_back(s.c_str());
  }
  mx_uint size() const { return (mx_uint)store.size(); }
  const char **data() { return ptrs.empty() ? nullptr : ptrs.data(); }
};

std::vector<void *> handles_in(JNIEnv *env, jlongArray arr) {
  std::vector<void *> v;
  int n = arr ? env->GetArrayLength(arr) : 0;
  if (n) {
    std::vector<jlong> tmp(n);
    env->GetLongArrayRegion(arr, 0, n, tmp.data());
    for (jlong h : tmp) v.push_back((void *)(intptr_t)h);
  }
  return v;
}

void handle_out(JNIEnv *env, jlongArray out, void *h) {
  jlong v = (jlong)(intptr_t)h;
  env->SetLongArrayRegion(out, 0, 1, &v);
}

jlongArray handles_new(JNIEnv *env, mx_uint n, void *const *hs) {
  jlongArray arr = env->NewLongArray(n);
  std::vector<jlong> tmp(n);
  for (mx_uint i = 0; i < n; ++i) tmp[i] = (jlong)(intptr_t)hs[i];
  if (n) env->SetLongArrayRegion(arr, 0, n, tmp.data());
  return arr;
}

jobjectArray strings_new(JNIEnv *env, mx_uint n, const char *const *ss) {
  jclass scls = env->FindClass("java/lang/String");
  jobjectArray arr = env->NewObjectArray(n, scls, nullptr);
  for (mx_uint i = 0; i < n; ++i)
    env->SetObjectArrayElement(arr, i, env->NewStringUTF(ss[i]));
  return arr;
}

// one shape group (n arrays, each ndim[i] ints) -> jobjectArray of jintArray
jobjectArray shapes_new(JNIEnv *env, mx_uint n, const mx_uint *ndims,
                        const mx_uint *const *data) {
  jclass icls = env->FindClass("[I");
  jobjectArray arr = env->NewObjectArray(n, icls, nullptr);
  for (mx_uint i = 0; i < n; ++i) {
    jintArray s = env->NewIntArray(ndims[i]);
    std::vector<jint> tmp(ndims[i]);
    for (mx_uint j = 0; j < ndims[i]; ++j) tmp[j] = (jint)data[i][j];
    if (ndims[i]) env->SetIntArrayRegion(s, 0, ndims[i], tmp.data());
    env->SetObjectArrayElement(arr, i, s);
  }
  return arr;
}

}  // namespace

#define H(x) ((void *)(intptr_t)(x))

extern "C" {

/* ---- init / error ---------------------------------------------------- */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_nativeLibInit(
    JNIEnv *env, jobject, jstring jpath) {
  if (jx.loaded) return 0;
  JString path(env, jpath);
  if (jx.dl != NULL) dlclose(jx.dl);  /* failed half-load retry */
  jx.dl = dlopen(path.c, RTLD_NOW | RTLD_GLOBAL);
  if (jx.dl == NULL) {
    snprintf(jx_init_err, sizeof(jx_init_err), "dlopen: %s", dlerror());
    return -1;
  }
  JX_RESOLVE(GetLastError, "MXGetLastError");
  JX_RESOLVE(RandomSeed, "MXRandomSeed");
  JX_RESOLVE(NotifyShutdown, "MXNotifyShutdown");
  JX_RESOLVE(NDArrayCreateEx, "MXNDArrayCreateEx");
  JX_RESOLVE(NDArrayCreateNone, "MXNDArrayCreateNone");
  JX_RESOLVE(NDArrayFree, "MXNDArrayFree");
  JX_RESOLVE(NDArrayWaitAll, "MXNDArrayWaitAll");
  JX_RESOLVE(NDArrayWaitToRead, "MXNDArrayWaitToRead");
  JX_RESOLVE(NDArraySyncCopyFromCPU, "MXNDArraySyncCopyFromCPU");
  JX_RESOLVE(NDArraySyncCopyToCPU, "MXNDArraySyncCopyToCPU");
  JX_RESOLVE(NDArrayGetShape, "MXNDArrayGetShape");
  JX_RESOLVE(NDArrayGetContext, "MXNDArrayGetContext");
  JX_RESOLVE(NDArraySlice, "MXNDArraySlice");
  JX_RESOLVE(NDArrayAt, "MXNDArrayAt");
  JX_RESOLVE(NDArrayReshape, "MXNDArrayReshape");
  JX_RESOLVE(NDArraySave, "MXNDArraySave");
  JX_RESOLVE(NDArrayLoad, "MXNDArrayLoad");
  JX_RESOLVE(ListFunctions, "MXListFunctions");
  JX_RESOLVE(GetFunction, "MXGetFunction");
  JX_RESOLVE(FuncGetInfo, "MXFuncGetInfo");
  JX_RESOLVE(FuncDescribe, "MXFuncDescribe");
  JX_RESOLVE(FuncInvoke, "MXFuncInvoke");
  JX_RESOLVE(SymbolListAtomicSymbolCreators, "MXSymbolListAtomicSymbolCreators");
  JX_RESOLVE(SymbolGetAtomicSymbolInfo, "MXSymbolGetAtomicSymbolInfo");
  JX_RESOLVE(SymbolCreateAtomicSymbol, "MXSymbolCreateAtomicSymbol");
  JX_RESOLVE(SymbolCreateVariable, "MXSymbolCreateVariable");
  JX_RESOLVE(SymbolCreateGroup, "MXSymbolCreateGroup");
  JX_RESOLVE(SymbolCreateFromJSON, "MXSymbolCreateFromJSON");
  JX_RESOLVE(SymbolSaveToJSON, "MXSymbolSaveToJSON");
  JX_RESOLVE(SymbolFree, "MXSymbolFree");
  JX_RESOLVE(SymbolCopy, "MXSymbolCopy");
  JX_RESOLVE(SymbolCompose, "MXSymbolCompose");
  JX_RESOLVE(SymbolListArguments, "MXSymbolListArguments");
  JX_RESOLVE(SymbolListOutputs, "MXSymbolListOutputs");
  JX_RESOLVE(SymbolListAuxiliaryStates, "MXSymbolListAuxiliaryStates");
  JX_RESOLVE(SymbolGetAttr, "MXSymbolGetAttr");
  JX_RESOLVE(SymbolSetAttr, "MXSymbolSetAttr");
  JX_RESOLVE(SymbolGetInternals, "MXSymbolGetInternals");
  JX_RESOLVE(SymbolGetOutput, "MXSymbolGetOutput");
  JX_RESOLVE(SymbolInferShape, "MXSymbolInferShape");
  JX_RESOLVE(ExecutorBindX, "MXExecutorBindX");
  JX_RESOLVE(ExecutorForward, "MXExecutorForward");
  JX_RESOLVE(ExecutorBackward, "MXExecutorBackward");
  JX_RESOLVE(ExecutorOutputs, "MXExecutorOutputs");
  JX_RESOLVE(ExecutorFree, "MXExecutorFree");
  JX_RESOLVE(OptimizerFindCreator, "MXOptimizerFindCreator");
  JX_RESOLVE(OptimizerCreateOptimizer, "MXOptimizerCreateOptimizer");
  JX_RESOLVE(OptimizerFree, "MXOptimizerFree");
  JX_RESOLVE(OptimizerUpdate, "MXOptimizerUpdate");
  JX_RESOLVE(KVStoreCreate, "MXKVStoreCreate");
  JX_RESOLVE(KVStoreFree, "MXKVStoreFree");
  JX_RESOLVE(KVStoreInit, "MXKVStoreInit");
  JX_RESOLVE(KVStorePush, "MXKVStorePush");
  JX_RESOLVE(KVStorePull, "MXKVStorePull");
  JX_RESOLVE(KVStoreGetType, "MXKVStoreGetType");
  JX_RESOLVE(KVStoreGetRank, "MXKVStoreGetRank");
  JX_RESOLVE(KVStoreGetGroupSize, "MXKVStoreGetGroupSize");
  JX_RESOLVE(KVStoreBarrier, "MXKVStoreBarrier");
  JX_RESOLVE(KVStoreRunServer, "MXKVStoreRunServer");
  JX_RESOLVE(KVStoreIsWorkerNode, "MXKVStoreIsWorkerNode");
  JX_RESOLVE(KVStoreIsServerNode, "MXKVStoreIsServerNode");
  JX_RESOLVE(KVStoreIsSchedulerNode, "MXKVStoreIsSchedulerNode");
  JX_RESOLVE(KVStoreSendCommmandToServers, "MXKVStoreSendCommmandToServers");
  JX_RESOLVE(NDArraySaveRawBytes, "MXNDArraySaveRawBytes");
  JX_RESOLVE(NDArrayLoadFromRawBytes, "MXNDArrayLoadFromRawBytes");
  JX_RESOLVE(NDArrayGetDType, "MXNDArrayGetDType");
  JX_RESOLVE(FuncInvokeEx, "MXFuncInvokeEx");
  JX_RESOLVE(SymbolGetName, "MXSymbolGetName");
  JX_RESOLVE(SymbolListAttr, "MXSymbolListAttr");
  JX_RESOLVE(SymbolListAttrShallow, "MXSymbolListAttrShallow");
  JX_RESOLVE(ExecutorPrint, "MXExecutorPrint");
  JX_RESOLVE(ListDataIters, "MXListDataIters");
  JX_RESOLVE(DataIterGetIterInfo, "MXDataIterGetIterInfo");
  JX_RESOLVE(DataIterCreateIter, "MXDataIterCreateIter");
  JX_RESOLVE(DataIterFree, "MXDataIterFree");
  JX_RESOLVE(DataIterNext, "MXDataIterNext");
  JX_RESOLVE(DataIterBeforeFirst, "MXDataIterBeforeFirst");
  JX_RESOLVE(DataIterGetData, "MXDataIterGetData");
  JX_RESOLVE(DataIterGetLabel, "MXDataIterGetLabel");
  JX_RESOLVE(DataIterGetPadNum, "MXDataIterGetPadNum");
  jx.loaded = 1;
  return 0;
}

JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxGetLastError(
    JNIEnv *env, jobject) {
  if (!jx.loaded) return env->NewStringUTF(jx_init_err);
  return env->NewStringUTF(jx.GetLastError());
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxRandomSeed(
    JNIEnv *, jobject, jint seed) {
  return jx.RandomSeed(seed);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNotifyShutdown(
    JNIEnv *, jobject) {
  return jx.NotifyShutdown();
}

/* ---- ndarray --------------------------------------------------------- */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayCreateEx(
    JNIEnv *env, jobject, jintArray jshape, jint devType, jint devId,
    jint delayAlloc, jint dtype, jlongArray out) {
  int ndim = env->GetArrayLength(jshape);
  std::vector<jint> tmp(ndim);
  env->GetIntArrayRegion(jshape, 0, ndim, tmp.data());
  std::vector<mx_uint> shape(tmp.begin(), tmp.end());
  NDArrayHandle h = nullptr;
  int rc = jx.NDArrayCreateEx(shape.data(), (mx_uint)ndim, devType, devId,
                              delayAlloc, dtype, &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayCreateNone(
    JNIEnv *env, jobject, jlongArray out) {
  NDArrayHandle h = nullptr;
  int rc = jx.NDArrayCreateNone(&h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayFree(
    JNIEnv *, jobject, jlong h) {
  return jx.NDArrayFree(H(h));
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayWaitAll(
    JNIEnv *, jobject) {
  return jx.NDArrayWaitAll();
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayWaitToRead(
    JNIEnv *, jobject, jlong h) {
  return jx.NDArrayWaitToRead(H(h));
}

JNIEXPORT jint JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySyncCopyFromCPU(
    JNIEnv *env, jobject, jlong h, jfloatArray jdata, jint size) {
  jfloat *data = env->GetFloatArrayElements(jdata, nullptr);
  int rc = jx.NDArraySyncCopyFromCPU(H(h), data, (size_t)size);
  env->ReleaseFloatArrayElements(jdata, data, JNI_ABORT);  /* no copy-back */
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySyncCopyToCPU(
    JNIEnv *env, jobject, jlong h, jfloatArray jdata, jint size) {
  jfloat *data = env->GetFloatArrayElements(jdata, nullptr);
  int rc = jx.NDArraySyncCopyToCPU(H(h), data, (size_t)size);
  env->ReleaseFloatArrayElements(jdata, data, 0);  /* commit */
  return rc;
}

JNIEXPORT jintArray JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayGetShape(
    JNIEnv *env, jobject, jlong h) {
  mx_uint ndim = 0;
  const mx_uint *data = nullptr;
  if (jx.NDArrayGetShape(H(h), &ndim, &data) != 0) return nullptr;
  jintArray out = env->NewIntArray(ndim);
  std::vector<jint> tmp(data, data + ndim);
  if (ndim) env->SetIntArrayRegion(out, 0, ndim, tmp.data());
  return out;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayGetContext(
    JNIEnv *env, jobject, jlong h, jintArray out2) {
  int dt = 0, di = 0;
  int rc = jx.NDArrayGetContext(H(h), &dt, &di);
  if (rc == 0) {
    jint v[2] = {dt, di};
    env->SetIntArrayRegion(out2, 0, 2, v);
  }
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySlice(
    JNIEnv *env, jobject, jlong h, jint begin, jint end, jlongArray out) {
  NDArrayHandle s = nullptr;
  int rc = jx.NDArraySlice(H(h), begin, end, &s);
  if (rc == 0) handle_out(env, out, s);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayAt(
    JNIEnv *env, jobject, jlong h, jint idx, jlongArray out) {
  NDArrayHandle s = nullptr;
  int rc = jx.NDArrayAt(H(h), idx, &s);
  if (rc == 0) handle_out(env, out, s);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayReshape(
    JNIEnv *env, jobject, jlong h, jintArray jdims, jlongArray out) {
  int ndim = env->GetArrayLength(jdims);
  std::vector<jint> tmp(ndim);
  env->GetIntArrayRegion(jdims, 0, ndim, tmp.data());
  std::vector<int> dims(tmp.begin(), tmp.end());
  NDArrayHandle s = nullptr;
  int rc = jx.NDArrayReshape(H(h), ndim, dims.data(), &s);
  if (rc == 0) handle_out(env, out, s);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySave(
    JNIEnv *env, jobject, jstring jfname, jlongArray jhandles,
    jobjectArray jkeys) {
  JString fname(env, jfname);
  std::vector<void *> hs = handles_in(env, jhandles);
  JStringArray keys(env, jkeys);
  return jx.NDArraySave(fname.c, (mx_uint)hs.size(),
                        hs.empty() ? nullptr : hs.data(),
                        keys.size() ? keys.data() : nullptr);
}

/* out2[0] <- jlongArray handles, out2[1] <- jobjectArray names */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayLoad(
    JNIEnv *env, jobject, jstring jfname, jobjectArray out2) {
  JString fname(env, jfname);
  mx_uint n = 0, nn = 0;
  NDArrayHandle *arrs = nullptr;
  const char **names = nullptr;
  int rc = jx.NDArrayLoad(fname.c, &n, &arrs, &nn, &names);
  if (rc != 0) return rc;
  env->SetObjectArrayElement(out2, 0, handles_new(env, n, arrs));
  env->SetObjectArrayElement(out2, 1, strings_new(env, nn, names));
  return 0;
}

/* ---- function registry ----------------------------------------------- */
JNIEXPORT jlongArray JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxListFunctions(
    JNIEnv *env, jobject) {
  mx_uint n = 0;
  FunctionHandle *fns = nullptr;
  if (jx.ListFunctions(&n, &fns) != 0) return nullptr;
  return handles_new(env, n, (void *const *)fns);
}

JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncGetName(
    JNIEnv *env, jobject, jlong h) {
  const char *name = nullptr, *desc = nullptr;
  mx_uint na = 0;
  const char **an = nullptr, **at = nullptr, **ad = nullptr;
  if (jx.FuncGetInfo((FunctionHandle)H(h), &name, &desc, &na, &an, &at, &ad)
      != 0)
    return nullptr;
  return env->NewStringUTF(name);
}

/* out4 <- [num_use_vars, num_scalars, num_mutate_vars, type_mask] */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncDescribe(
    JNIEnv *env, jobject, jlong h, jintArray out4) {
  mx_uint nu = 0, ns = 0, nm = 0;
  int mask = 0;
  int rc = jx.FuncDescribe((FunctionHandle)H(h), &nu, &ns, &nm, &mask);
  if (rc == 0) {
    jint v[4] = {(jint)nu, (jint)ns, (jint)nm, mask};
    env->SetIntArrayRegion(out4, 0, 4, v);
  }
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncInvoke(
    JNIEnv *env, jobject, jlong fn, jlongArray juse, jfloatArray jscalars,
    jlongArray jmut) {
  std::vector<void *> use = handles_in(env, juse);
  std::vector<void *> mut = handles_in(env, jmut);
  int ns = jscalars ? env->GetArrayLength(jscalars) : 0;
  std::vector<jfloat> sc(ns);
  if (ns) env->GetFloatArrayRegion(jscalars, 0, ns, sc.data());
  return jx.FuncInvoke((FunctionHandle)H(fn),
                       use.empty() ? nullptr : use.data(),
                       sc.empty() ? nullptr : sc.data(),
                       mut.empty() ? nullptr : mut.data());
}

/* ---- symbol ---------------------------------------------------------- */
JNIEXPORT jlongArray JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAtomicSymbolCreators(
    JNIEnv *env, jobject) {
  mx_uint n = 0;
  AtomicSymbolCreator *cs = nullptr;
  if (jx.SymbolListAtomicSymbolCreators(&n, &cs) != 0) return nullptr;
  return handles_new(env, n, (void *const *)cs);
}

JNIEXPORT jstring JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetAtomicSymbolName(
    JNIEnv *env, jobject, jlong h) {
  const char *name = nullptr, *desc = nullptr, *kv = nullptr;
  mx_uint na = 0;
  const char **an = nullptr, **at = nullptr, **ad = nullptr;
  if (jx.SymbolGetAtomicSymbolInfo((AtomicSymbolCreator)H(h), &name, &desc,
                                   &na, &an, &at, &ad, &kv) != 0)
    return nullptr;
  return env->NewStringUTF(name);
}

JNIEXPORT jint JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateAtomicSymbol(
    JNIEnv *env, jobject, jlong creator, jobjectArray jkeys,
    jobjectArray jvals, jlongArray out) {
  JStringArray keys(env, jkeys), vals(env, jvals);
  SymbolHandle h = nullptr;
  int rc = jx.SymbolCreateAtomicSymbol((AtomicSymbolCreator)H(creator),
                                       keys.size(), keys.data(), vals.data(),
                                       &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateVariable(
    JNIEnv *env, jobject, jstring jname, jlongArray out) {
  JString name(env, jname);
  SymbolHandle h = nullptr;
  int rc = jx.SymbolCreateVariable(name.c, &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateGroup(
    JNIEnv *env, jobject, jlongArray jsyms, jlongArray out) {
  std::vector<void *> syms = handles_in(env, jsyms);
  SymbolHandle h = nullptr;
  int rc = jx.SymbolCreateGroup((mx_uint)syms.size(),
                                syms.empty() ? nullptr : syms.data(), &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateFromJSON(
    JNIEnv *env, jobject, jstring jjson, jlongArray out) {
  JString json(env, jjson);
  SymbolHandle h = nullptr;
  int rc = jx.SymbolCreateFromJSON(json.c, &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolSaveToJSON(
    JNIEnv *env, jobject, jlong h) {
  const char *json = nullptr;
  if (jx.SymbolSaveToJSON(H(h), &json) != 0) return nullptr;
  return env->NewStringUTF(json);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolFree(
    JNIEnv *, jobject, jlong h) {
  return jx.SymbolFree(H(h));
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCopy(
    JNIEnv *env, jobject, jlong h, jlongArray out) {
  SymbolHandle c = nullptr;
  int rc = jx.SymbolCopy(H(h), &c);
  if (rc == 0) handle_out(env, out, c);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCompose(
    JNIEnv *env, jobject, jlong h, jstring jname, jobjectArray jkeys,
    jlongArray jargs) {
  JString name(env, jname);
  JStringArray keys(env, jkeys);
  std::vector<void *> args = handles_in(env, jargs);
  return jx.SymbolCompose(H(h), name.c, (mx_uint)args.size(),
                          keys.size() ? keys.data() : nullptr,
                          args.empty() ? nullptr : args.data());
}

JNIEXPORT jobjectArray JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListArguments(JNIEnv *env, jobject,
                                                      jlong h) {
  mx_uint n = 0;
  const char **ss = nullptr;
  if (jx.SymbolListArguments(H(h), &n, &ss) != 0) return nullptr;
  return strings_new(env, n, ss);
}

JNIEXPORT jobjectArray JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListOutputs(JNIEnv *env, jobject,
                                                    jlong h) {
  mx_uint n = 0;
  const char **ss = nullptr;
  if (jx.SymbolListOutputs(H(h), &n, &ss) != 0) return nullptr;
  return strings_new(env, n, ss);
}

JNIEXPORT jobjectArray JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAuxiliaryStates(
    JNIEnv *env, jobject, jlong h) {
  mx_uint n = 0;
  const char **ss = nullptr;
  if (jx.SymbolListAuxiliaryStates(H(h), &n, &ss) != 0) return nullptr;
  return strings_new(env, n, ss);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolSetAttr(
    JNIEnv *env, jobject, jlong h, jstring jkey, jstring jval) {
  JString key(env, jkey), val(env, jval);
  return jx.SymbolSetAttr(H(h), key.c, val.c);
}

JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetAttr(
    JNIEnv *env, jobject, jlong h, jstring jkey) {
  JString key(env, jkey);
  const char *out = nullptr;
  int ok = 0;
  if (jx.SymbolGetAttr(H(h), key.c, &out, &ok) != 0 || !ok) return nullptr;
  return env->NewStringUTF(out);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetInternals(
    JNIEnv *env, jobject, jlong h, jlongArray out) {
  SymbolHandle s = nullptr;
  int rc = jx.SymbolGetInternals(H(h), &s);
  if (rc == 0) handle_out(env, out, s);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetOutput(
    JNIEnv *env, jobject, jlong h, jint idx, jlongArray out) {
  SymbolHandle s = nullptr;
  int rc = jx.SymbolGetOutput(H(h), (mx_uint)idx, &s);
  if (rc == 0) handle_out(env, out, s);
  return rc;
}

/* result <- [argShapes, outShapes, auxShapes] (each jobjectArray of
 * jintArray), returns complete flag in out1[0]; null groups on error */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolInferShape(
    JNIEnv *env, jobject, jlong h, jobjectArray jkeys, jobjectArray jshapes,
    jobjectArray out3, jintArray jcomplete) {
  JStringArray keys(env, jkeys);
  mx_uint nk = keys.size();
  std::vector<mx_uint> ind(1, 0), flat;
  for (mx_uint i = 0; i < nk; ++i) {
    jintArray s = (jintArray)env->GetObjectArrayElement(jshapes, i);
    int sn = env->GetArrayLength(s);
    std::vector<jint> tmp(sn);
    env->GetIntArrayRegion(s, 0, sn, tmp.data());
    for (int j = 0; j < sn; ++j) flat.push_back((mx_uint)tmp[j]);
    ind.push_back((mx_uint)flat.size());
  }
  mx_uint in_n = 0, out_n = 0, aux_n = 0;
  const mx_uint *in_nd = nullptr, *out_nd = nullptr, *aux_nd = nullptr;
  const mx_uint **in_d = nullptr, **out_d = nullptr, **aux_d = nullptr;
  int complete = 0;
  int rc = jx.SymbolInferShape(
      H(h), nk, keys.data(), ind.data(), flat.data(), &in_n, &in_nd,
      (const mx_uint ***)&in_d, &out_n, &out_nd, (const mx_uint ***)&out_d,
      &aux_n, &aux_nd, (const mx_uint ***)&aux_d, &complete);
  if (rc != 0) return rc;
  env->SetObjectArrayElement(out3, 0, shapes_new(env, in_n, in_nd, in_d));
  env->SetObjectArrayElement(out3, 1, shapes_new(env, out_n, out_nd, out_d));
  env->SetObjectArrayElement(out3, 2, shapes_new(env, aux_n, aux_nd, aux_d));
  jint c = complete;
  env->SetIntArrayRegion(jcomplete, 0, 1, &c);
  return 0;
}

/* ---- executor -------------------------------------------------------- */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorBindX(
    JNIEnv *env, jobject, jlong sym, jint devType, jint devId,
    jobjectArray jmapKeys, jintArray jmapDevTypes, jintArray jmapDevIds,
    jlongArray jinArgs, jlongArray jargGrads, jintArray jgradReqs,
    jlongArray jauxStates, jlongArray out) {
  JStringArray mapKeys(env, jmapKeys);
  mx_uint nmap = mapKeys.size();
  std::vector<jint> mdt(nmap), mdi(nmap);
  if (nmap) {
    env->GetIntArrayRegion(jmapDevTypes, 0, nmap, mdt.data());
    env->GetIntArrayRegion(jmapDevIds, 0, nmap, mdi.data());
  }
  std::vector<int> map_dt(mdt.begin(), mdt.end());
  std::vector<int> map_di(mdi.begin(), mdi.end());
  std::vector<void *> in_args = handles_in(env, jinArgs);
  std::vector<void *> grads = handles_in(env, jargGrads);
  std::vector<void *> aux = handles_in(env, jauxStates);
  int nreq = env->GetArrayLength(jgradReqs);
  std::vector<jint> reqs_j(nreq);
  env->GetIntArrayRegion(jgradReqs, 0, nreq, reqs_j.data());
  std::vector<mx_uint> reqs(reqs_j.begin(), reqs_j.end());
  ExecutorHandle ex = nullptr;
  int rc = jx.ExecutorBindX(
      H(sym), devType, devId, nmap, mapKeys.data(),
      nmap ? map_dt.data() : nullptr, nmap ? map_di.data() : nullptr,
      (mx_uint)in_args.size(), in_args.data(),
      grads.empty() ? nullptr : grads.data(), reqs.data(),
      (mx_uint)aux.size(), aux.empty() ? nullptr : aux.data(), &ex);
  if (rc == 0) handle_out(env, out, ex);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorForward(
    JNIEnv *, jobject, jlong ex, jint isTrain) {
  return jx.ExecutorForward(H(ex), isTrain);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorBackward(
    JNIEnv *env, jobject, jlong ex, jlongArray jheads) {
  std::vector<void *> heads = handles_in(env, jheads);
  return jx.ExecutorBackward(H(ex), (mx_uint)heads.size(),
                             heads.empty() ? nullptr : heads.data());
}

JNIEXPORT jlongArray JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorOutputs(
    JNIEnv *env, jobject, jlong ex) {
  mx_uint n = 0;
  NDArrayHandle *outs = nullptr;
  if (jx.ExecutorOutputs(H(ex), &n, &outs) != 0) return nullptr;
  return handles_new(env, n, outs);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorFree(
    JNIEnv *, jobject, jlong ex) {
  return jx.ExecutorFree(H(ex));
}

/* ---- optimizer ------------------------------------------------------- */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerFindCreator(
    JNIEnv *env, jobject, jstring jname, jlongArray out) {
  JString name(env, jname);
  OptimizerCreator c = nullptr;
  int rc = jx.OptimizerFindCreator(name.c, &c);
  if (rc == 0) handle_out(env, out, (void *)c);
  return rc;
}

JNIEXPORT jint JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerCreateOptimizer(
    JNIEnv *env, jobject, jlong creator, jobjectArray jkeys,
    jobjectArray jvals, jlongArray out) {
  JStringArray keys(env, jkeys), vals(env, jvals);
  OptimizerHandle h = nullptr;
  int rc = jx.OptimizerCreateOptimizer((OptimizerCreator)H(creator),
                                       keys.size(), keys.data(), vals.data(),
                                       &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerUpdate(
    JNIEnv *, jobject, jlong h, jint index, jlong w, jlong g, jfloat lr,
    jfloat wd) {
  return jx.OptimizerUpdate(H(h), index, H(w), H(g), lr, wd);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerFree(
    JNIEnv *, jobject, jlong h) {
  return jx.OptimizerFree(H(h));
}

/* ---- kvstore --------------------------------------------------------- */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreCreate(
    JNIEnv *env, jobject, jstring jtype, jlongArray out) {
  JString type(env, jtype);
  KVStoreHandle h = nullptr;
  int rc = jx.KVStoreCreate(type.c, &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreFree(
    JNIEnv *, jobject, jlong h) {
  return jx.KVStoreFree(H(h));
}

static int kv_keys_vals(JNIEnv *env, jintArray jkeys, jlongArray jvals,
                        std::vector<int> *keys, std::vector<void *> *vals) {
  int n = env->GetArrayLength(jkeys);
  std::vector<jint> tmp(n);
  env->GetIntArrayRegion(jkeys, 0, n, tmp.data());
  keys->assign(tmp.begin(), tmp.end());
  *vals = handles_in(env, jvals);
  return n;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreInit(
    JNIEnv *env, jobject, jlong h, jintArray jkeys, jlongArray jvals) {
  std::vector<int> keys;
  std::vector<void *> vals;
  int n = kv_keys_vals(env, jkeys, jvals, &keys, &vals);
  return jx.KVStoreInit(H(h), (mx_uint)n, keys.data(), vals.data());
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStorePush(
    JNIEnv *env, jobject, jlong h, jintArray jkeys, jlongArray jvals,
    jint priority) {
  std::vector<int> keys;
  std::vector<void *> vals;
  int n = kv_keys_vals(env, jkeys, jvals, &keys, &vals);
  return jx.KVStorePush(H(h), (mx_uint)n, keys.data(), vals.data(), priority);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStorePull(
    JNIEnv *env, jobject, jlong h, jintArray jkeys, jlongArray jvals,
    jint priority) {
  std::vector<int> keys;
  std::vector<void *> vals;
  int n = kv_keys_vals(env, jkeys, jvals, &keys, &vals);
  return jx.KVStorePull(H(h), (mx_uint)n, keys.data(), vals.data(), priority);
}

JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreGetType(
    JNIEnv *env, jobject, jlong h) {
  const char *t = nullptr;
  if (jx.KVStoreGetType(H(h), &t) != 0) return nullptr;
  return env->NewStringUTF(t);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreGetRank(
    JNIEnv *env, jobject, jlong h, jintArray out) {
  int r = 0;
  int rc = jx.KVStoreGetRank(H(h), &r);
  if (rc == 0) {
    jint v = r;
    env->SetIntArrayRegion(out, 0, 1, &v);
  }
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreGetGroupSize(
    JNIEnv *env, jobject, jlong h, jintArray out) {
  int r = 0;
  int rc = jx.KVStoreGetGroupSize(H(h), &r);
  if (rc == 0) {
    jint v = r;
    env->SetIntArrayRegion(out, 0, 1, &v);
  }
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreBarrier(
    JNIEnv *, jobject, jlong h) {
  return jx.KVStoreBarrier(H(h));
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreRunServer(
    JNIEnv *, jobject, jlong h) {
  // blocks in the native PS loop until the scheduler finishes the job
  return jx.KVStoreRunServer(H(h));
}

static jint role_query(JNIEnv *env, int (*fn)(int *), jintArray out) {
  int r = 0;
  int rc = fn(&r);
  if (rc == 0) {
    jint v = r;
    env->SetIntArrayRegion(out, 0, 1, &v);
  }
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreIsWorkerNode(
    JNIEnv *env, jobject, jintArray out) {
  return role_query(env, jx.KVStoreIsWorkerNode, out);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreIsServerNode(
    JNIEnv *env, jobject, jintArray out) {
  return role_query(env, jx.KVStoreIsServerNode, out);
}

JNIEXPORT jint JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreIsSchedulerNode(
    JNIEnv *env, jobject, jintArray out) {
  return role_query(env, jx.KVStoreIsSchedulerNode, out);
}

JNIEXPORT jint JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreSendCommmandToServers(
    JNIEnv *env, jobject, jlong h, jint head, jstring jbody) {
  JString body(env, jbody);
  return jx.KVStoreSendCommmandToServers(H(h), head, body.c);
}

/* ---- raw-byte NDArray serialization ---------------------------------- */
JNIEXPORT jbyteArray JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySaveRawBytes(
    JNIEnv *env, jobject, jlong h) {
  size_t n = 0;
  const char *buf = NULL;
  if (jx.NDArraySaveRawBytes(H(h), &n, &buf) != 0) return NULL;
  jbyteArray out = env->NewByteArray((jsize)n);
  env->SetByteArrayRegion(out, 0, (jsize)n, (const jbyte *)buf);
  return out;
}

JNIEXPORT jint JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayLoadFromRawBytes(
    JNIEnv *env, jobject, jbyteArray jbuf, jlongArray out) {
  int n = env->GetArrayLength(jbuf);
  std::vector<jbyte> buf(n);
  env->GetByteArrayRegion(jbuf, 0, n, buf.data());
  NDArrayHandle h = NULL;
  int rc = jx.NDArrayLoadFromRawBytes(buf.data(), (size_t)n, &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayGetDType(
    JNIEnv *env, jobject, jlong h, jintArray out) {
  int dt = 0;
  int rc = jx.NDArrayGetDType(H(h), &dt);
  if (rc == 0) {
    jint v = dt;
    env->SetIntArrayRegion(out, 0, 1, &v);
  }
  return rc;
}

/* ---- function registry: kwargs channel ------------------------------- */
JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncInvokeEx(
    JNIEnv *env, jobject, jlong fn, jlongArray juse, jfloatArray jscalars,
    jlongArray jmutate, jobjectArray jkeys, jobjectArray jvals) {
  std::vector<void *> use = handles_in(env, juse);
  std::vector<void *> mutate = handles_in(env, jmutate);
  int ns = jscalars ? env->GetArrayLength(jscalars) : 0;
  std::vector<jfloat> scalars(ns);
  if (ns) env->GetFloatArrayRegion(jscalars, 0, ns, scalars.data());
  JStringArray keys(env, jkeys), vals(env, jvals);
  return jx.FuncInvokeEx(
      (FunctionHandle)(intptr_t)fn, use.data(), scalars.data(),
      mutate.data(), (int)keys.size(),
      const_cast<char **>(keys.data()), const_cast<char **>(vals.data()));
}

/* ---- symbol names + attributes --------------------------------------- */
JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetName(
    JNIEnv *env, jobject, jlong h) {
  const char *name = NULL;
  int ok = 0;
  if (jx.SymbolGetName(H(h), &name, &ok) != 0) return NULL;
  return ok ? env->NewStringUTF(name) : NULL;
}

static jobjectArray list_attr(JNIEnv *env,
                              int (*fn)(SymbolHandle, mx_uint *,
                                        const char ***),
                              jlong h) {
  mx_uint n = 0;
  const char **kv = NULL;
  if (fn(H(h), &n, &kv) != 0) return NULL;
  return strings_new(env, 2 * n, kv);  /* flat [k0,v0,k1,v1,...] */
}

JNIEXPORT jobjectArray JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAttr(
    JNIEnv *env, jobject, jlong h) {
  return list_attr(env, jx.SymbolListAttr, h);
}

JNIEXPORT jobjectArray JNICALL
Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAttrShallow(
    JNIEnv *env, jobject, jlong h) {
  return list_attr(env, jx.SymbolListAttrShallow, h);
}

/* ---- executor debug -------------------------------------------------- */
JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorPrint(
    JNIEnv *env, jobject, jlong h) {
  const char *s = NULL;
  if (jx.ExecutorPrint(H(h), &s) != 0) return NULL;
  return env->NewStringUTF(s);
}

/* ---- data iterators -------------------------------------------------- */
JNIEXPORT jlongArray JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxListDataIters(
    JNIEnv *env, jobject) {
  mx_uint n = 0;
  const void **creators = NULL;
  if (jx.ListDataIters(&n, &creators) != 0) return NULL;
  return handles_new(env, n, const_cast<void *const *>(creators));
}

JNIEXPORT jstring JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterGetName(
    JNIEnv *env, jobject, jlong creator) {
  const char *name = NULL, *desc = NULL;
  mx_uint nargs = 0;
  const char **anames = NULL, **atypes = NULL, **adescs = NULL;
  if (jx.DataIterGetIterInfo((const void *)(intptr_t)creator, &name, &desc,
                             &nargs, &anames, &atypes, &adescs) != 0)
    return NULL;
  return env->NewStringUTF(name);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterCreateIter(
    JNIEnv *env, jobject, jlong creator, jobjectArray jkeys,
    jobjectArray jvals, jlongArray out) {
  JStringArray keys(env, jkeys), vals(env, jvals);
  void *h = NULL;
  int rc = jx.DataIterCreateIter((const void *)(intptr_t)creator,
                                 keys.size(), keys.data(), vals.data(), &h);
  if (rc == 0) handle_out(env, out, h);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterFree(
    JNIEnv *, jobject, jlong h) {
  return jx.DataIterFree(H(h));
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterNext(
    JNIEnv *env, jobject, jlong h, jintArray out) {
  int has = 0;
  int rc = jx.DataIterNext(H(h), &has);
  if (rc == 0) {
    jint v = has;
    env->SetIntArrayRegion(out, 0, 1, &v);
  }
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterBeforeFirst(
    JNIEnv *, jobject, jlong h) {
  return jx.DataIterBeforeFirst(H(h));
}

static jint iter_get_array(JNIEnv *env, int (*fn)(void *, NDArrayHandle *),
                           jlong h, jlongArray out) {
  NDArrayHandle a = NULL;
  int rc = fn(H(h), &a);
  if (rc == 0) handle_out(env, out, a);
  return rc;
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterGetData(
    JNIEnv *env, jobject, jlong h, jlongArray out) {
  return iter_get_array(env, jx.DataIterGetData, h, out);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterGetLabel(
    JNIEnv *env, jobject, jlong h, jlongArray out) {
  return iter_get_array(env, jx.DataIterGetLabel, h, out);
}

JNIEXPORT jint JNICALL Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterGetPadNum(
    JNIEnv *env, jobject, jlong h, jintArray out) {
  int pad = 0;
  int rc = jx.DataIterGetPadNum(H(h), &pad);
  if (rc == 0) {
    jint v = pad;
    env->SetIntArrayRegion(out, 0, 1, &v);
  }
  return rc;
}

}  /* extern "C" */
