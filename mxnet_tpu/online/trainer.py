"""Supervised fine-tune rounds over captured serve traffic (ISSUE 17).

:class:`OnlineTrainer` is the retrain leg of the online loop: it turns
the sealed capture shards into a replay feed
(:func:`mxnet_tpu.online.replay.replay_pipeline`) and runs
``Module.fit`` against one persistent checkpoint store with
``resume=True`` — so a round interrupted by preemption, a torn save or
a SIGKILL resumes **bitwise** from the latest committed step when the
PR 15 :class:`mxnet_tpu.faults.Supervisor` restarts the process.  The
candidate the promotion gate evaluates is simply the newest committed
checkpoint step.

Rounds are cumulative: ``round(num_epoch=N)`` trains *up to* epoch N
over the current shard snapshot.  Passing the cumulative target (rather
than a per-round increment) keeps a restarted attempt idempotent — an
attempt that crashed after finishing its epochs re-enters ``fit``,
finds ``begin_epoch == num_epoch`` restored from the store, and falls
straight through to the next loop phase.
"""
from __future__ import annotations

import time

from ..base import MXNetError, make_lock
from ..faults import point as _fault_point
from .replay import replay_pipeline

__all__ = ["OnlineTrainer"]


class OnlineTrainer:
    """Fine-tune ``symbol`` on sealed capture shards, checkpointing
    into ``checkpoint_dir``.

    Parameters mirror ``Module.fit``: ``optimizer``/
    ``optimizer_params``/``eval_metric``/``superstep`` pass straight
    through; ``arg_params`` seeds the FIRST round only (later rounds
    resume from the store).  ``context`` defaults to ``cpu(0)``.
    """

    def __init__(self, symbol, capture_dir: str, checkpoint_dir: str, *,
                 batch_size: int, optimizer: str = "sgd",
                 optimizer_params=None, arg_params=None,
                 eval_metric="acc", checkpoint_every: int = 1,
                 superstep=None, context=None, to_device: bool = False,
                 name: str = "online-trainer"):
        self.name = name
        self.symbol = symbol
        self.capture_dir = str(capture_dir)
        self.checkpoint_dir = str(checkpoint_dir)
        self.batch_size = int(batch_size)
        self.optimizer = optimizer
        self.optimizer_params = optimizer_params
        self.arg_params = arg_params
        self.eval_metric = eval_metric
        self.checkpoint_every = int(checkpoint_every)
        self.superstep = superstep
        self.context = context
        self.to_device = to_device
        self._lock = make_lock("online.trainer")
        self._rounds = 0
        self._fit_s = 0.0
        self._last_step = None
        from .. import profiler
        profiler.register_online_stats(self)

    def round(self, num_epoch: int, shards=None) -> dict:
        """One supervised fine-tune round: train up to cumulative epoch
        ``num_epoch`` on the current sealed-shard snapshot (or an
        explicit ``shards`` list, pinned for cross-attempt
        determinism), resuming from the checkpoint store.  -> summary
        dict with the candidate's committed ``step``."""
        from ..context import cpu
        from ..module import Module
        from .. import checkpoint as ck
        _fault_point("online.train", stage="round",
                     num_epoch=int(num_epoch))
        it = replay_pipeline(self.capture_dir, self.batch_size,
                             shards=shards, to_device=self.to_device)
        t0 = time.perf_counter()
        try:
            mod = Module(self.symbol,
                         context=self.context or cpu(0))
            mod.fit(it, num_epoch=int(num_epoch),
                    arg_params=self.arg_params,
                    eval_metric=self.eval_metric,
                    optimizer=self.optimizer,
                    optimizer_params=self.optimizer_params,
                    checkpoint=self.checkpoint_dir,
                    checkpoint_every=self.checkpoint_every,
                    superstep=self.superstep,
                    resume=True)
        finally:
            it.close()
        mgr = ck.CheckpointManager(self.checkpoint_dir, keep_last_n=None)
        try:
            step = mgr.latest_step()
        finally:
            mgr.close()
        if step is None:
            raise MXNetError(
                "online round committed no checkpoint step — nothing "
                "for the promotion gate to evaluate (capture empty?)")
        with self._lock:
            self._rounds += 1
            self._fit_s += time.perf_counter() - t0
            self._last_step = step
        return {"step": step, "num_epoch": int(num_epoch)}

    def supervisor(self, argv, **kw):
        """A :class:`mxnet_tpu.faults.Supervisor` wired to this
        trainer's checkpoint store (recovery is measured against commit
        progress there)."""
        from ..faults import Supervisor
        kw.setdefault("checkpoint_dir", self.checkpoint_dir)
        kw.setdefault("name", self.name)
        return Supervisor(argv, **kw)

    # -- introspection -----------------------------------------------------
    def report(self) -> dict:
        with self._lock:
            return {
                "kind": "trainer",
                "rounds": self._rounds,
                "fit_s": round(self._fit_s, 4),
                "last_step": self._last_step,
            }

    def report_str(self) -> str:
        r = self.report()
        return ("online trainer %r: %d rounds (%.2fs fit), last step %s"
                % (self.name, r["rounds"], r["fit_s"], r["last_step"]))
