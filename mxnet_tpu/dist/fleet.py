"""Fleet supervisor: the PR 15 elastic-training watchdog generalized to
multi-host meshes.

:class:`~mxnet_tpu.faults.Supervisor` watches ONE training process.  A
multi-host job is N processes joined through one ``jax.distributed``
coordinator — and a synchronous collective mesh has no partial-failure
mode: when one host dies mid-allreduce the survivors are wedged inside a
collective that will never complete.  So the fleet supervisor's unit of
restart is the FLEET, not the process:

1. spawn N workers wired to a fresh local coordinator (the same
   ``MXNET_TPU_COORDINATOR`` / ``_NUM_WORKERS`` / ``_WORKER_ID``
   rendezvous ``tools/launch.py`` uses, booted by ``dist.boot`` at
   ``import mxnet_tpu``);
2. on any worker death (SIGKILL'd host, injected ``dist.host`` fault,
   hang past ``timeout_s``) — kill the survivors, wait out the jittered
   :class:`~mxnet_tpu.faults.retry.Backoff`, and re-form the fleet with
   ``MXNET_FAULTS_ATTEMPT`` advanced;
3. the re-formed fleet restores from the latest checkpoint COMMIT
   (multiprocess saves are commit-or-nothing, PR 6), so the recovered
   run is bitwise identical to a fault-free one.

Two loss policies:

* ``on_loss="rejoin"`` (default): restart at full strength — the lost
  rank rejoins from the commit store.
* ``on_loss="shrink"``: re-form one host smaller (never below
  ``min_workers``) — survivors ride the elastic-remesh path: the
  restore lands the committed state on the new, smaller global mesh,
  exactly the single-process ``set_mesh`` contract at fleet scale.

``recovery_s`` mirrors the single-host supervisor: death detection ->
the re-formed fleet COMMITTING a step past the pre-crash high water
(training provably moving, not merely processes existing).

::

    sup = dist.FleetSupervisor(
        [sys.executable, "train.py"], nworkers=2,
        checkpoint_dir="/ckpt/run7", max_restarts=3)
    rc = sup.run()
    print(mx.profiler.faults_report_str())
"""
from __future__ import annotations

import os
import socket
import subprocess
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError, get_env, make_lock
from .. import trace as _trace
from ..faults.retry import Backoff, RestartWindow

__all__ = ["FleetSupervisor", "FleetStats", "free_port"]

_POLL_S = 0.05


def free_port() -> int:
    """An OS-allocated free TCP port (each attempt gets a fresh
    coordinator port so a lingering socket from the killed fleet can
    never wedge the next rendezvous)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FleetStats:
    """Restart/recovery counters for one fleet; one row (kind
    ``fleet``) in ``mx.profiler.faults_report()``."""

    def __init__(self, name: str):
        self.name = name
        self._lock = make_lock("dist.fleet")
        self._c: Dict = {
            "attempts": 0, "restarts": 0, "lost_hosts": 0,
            "gave_up": False, "backoff_wait_s": 0.0, "recovery_s": 0.0,
            "last_recovery_s": 0.0, "last_rc": None, "last_nworkers": 0,
            "run_s": 0.0,
        }

    def add(self, **kw) -> None:
        with self._lock:
            for k, v in kw.items():
                if k in ("gave_up", "last_rc") or k.startswith("last_"):
                    self._c[k] = v
                elif isinstance(self._c[k], bool):
                    self._c[k] = v
                else:
                    self._c[k] += v

    def report(self) -> Dict:
        with self._lock:
            out = dict(self._c)
        out["kind"] = "fleet"
        for k in ("backoff_wait_s", "recovery_s", "last_recovery_s",
                  "run_s"):
            out[k] = round(out[k], 4)
        return out

    def report_str(self) -> str:
        r = self.report()
        return ("fleet %r: %d attempts, %d restarts, %d hosts lost%s\n"
                "  %d workers last; backoff wait %.2fs total; recovery "
                "%.2fs last / %.2fs total; last rc=%s; wall %.2fs"
                % (self.name, r["attempts"], r["restarts"],
                   r["lost_hosts"], " (GAVE UP)" if r["gave_up"] else "",
                   r["last_nworkers"], r["backoff_wait_s"],
                   r["last_recovery_s"], r["recovery_s"], r["last_rc"],
                   r["run_s"]))


class FleetSupervisor:
    """Bounded-retry watchdog over an N-worker collective fleet (see
    module docstring).

    Parameters
    ----------
    target : argv list
        What every worker runs (argv mode only: each rank must be a
        fresh process with its own jax runtime).  Rank identity arrives
        via the standard rendezvous envs.
    nworkers : int
        Fleet size for the first attempt.
    on_loss : "rejoin" | "shrink"
        Re-form at full strength (the lost rank rejoins from the commit
        store) or one host smaller (elastic remesh; never below
        ``min_workers``).
    min_workers : int
        Floor for ``on_loss="shrink"`` (default 1).
    max_restarts / restart_window_s / backoff / timeout_s /
    checkpoint_dir / env / success_codes
        As :class:`~mxnet_tpu.faults.Supervisor` — the budget counts
        FLEET restarts over a sliding window; ``checkpoint_dir``
        enables the commit-based ``recovery_s`` watch; ``timeout_s``
        SIGKILLs a fleet whose attempt outlives it (hang detection —
        a wedged collective never exits on its own).
    """

    def __init__(self, target: Sequence[str], nworkers: int, *,
                 on_loss: str = "rejoin", min_workers: int = 1,
                 max_restarts: Optional[int] = None,
                 restart_window_s: Optional[float] = None,
                 backoff: Optional[Backoff] = None,
                 timeout_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None,
                 success_codes=(0,), name: str = "fleet"):
        if callable(target) or not isinstance(target, (list, tuple)):
            raise MXNetError(
                "FleetSupervisor target must be an argv list (every rank "
                "needs a fresh process with its own jax runtime), got %r"
                % (target,))
        if on_loss not in ("rejoin", "shrink"):
            raise MXNetError("on_loss must be 'rejoin' or 'shrink', got %r"
                             % (on_loss,))
        if int(nworkers) < 1:
            raise MXNetError("nworkers must be >= 1, got %r" % (nworkers,))
        self.target = list(target)
        self.nworkers = int(nworkers)
        self.on_loss = on_loss
        self.min_workers = max(1, int(min_workers))
        if max_restarts is None:
            max_restarts = get_env("MXNET_DIST_FLEET_MAX_RESTARTS", 5, int)
        self.max_restarts = max(0, int(max_restarts))
        if restart_window_s is None:
            restart_window_s = get_env("MXNET_DIST_FLEET_WINDOW_S",
                                       3600.0, float)
        self.restart_window_s = float(restart_window_s)
        if backoff is None:
            backoff = Backoff(
                base_s=get_env("MXNET_DIST_FLEET_BACKOFF_S", 0.5, float),
                factor=2.0, max_s=30.0, jitter=0.5, seed=0, name="fleet")
        self.backoff = backoff
        self.timeout_s = timeout_s
        self.checkpoint_dir = checkpoint_dir
        self.env = dict(env or {})
        self.success_codes = set(success_codes)
        self.name = name
        self.stats = FleetStats(name)
        self._stopping = False
        from .. import profiler
        profiler.register_faults_stats(self.stats)

    # -- one attempt -------------------------------------------------------
    def _latest_step(self) -> int:
        if self.checkpoint_dir is None:
            return -1
        from ..checkpoint import layout
        s = layout.latest_step(self.checkpoint_dir)
        return -1 if s is None else s

    def _spawn_fleet(self, attempt: int) -> List[subprocess.Popen]:
        port = free_port()
        base = dict(os.environ)
        base.update(self.env)
        base["MXNET_TPU_COORDINATOR"] = "127.0.0.1:%d" % port
        base["MXNET_TPU_NUM_WORKERS"] = str(self.nworkers)
        base["MXNET_FAULTS_ATTEMPT"] = str(attempt)
        procs = []
        for rank in range(self.nworkers):
            env = dict(base)
            env["MXNET_TPU_WORKER_ID"] = str(rank)
            procs.append(subprocess.Popen(list(self.target), env=env))
        return procs

    def _kill_fleet(self, procs: List[subprocess.Popen]) -> None:
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except Exception:
                    pass
        deadline = time.perf_counter() + 5.0
        for p in procs:
            if p.poll() is None:
                try:
                    p.wait(timeout=max(0.0,
                                       deadline - time.perf_counter()))
                except Exception:
                    pass
        for p in procs:
            if p.poll() is None:
                try:
                    p.kill()
                    p.wait(timeout=10.0)
                except Exception:
                    pass

    def _attempt(self, attempt: int, watch_from: int,
                 died_t: Optional[float]) -> Tuple[int, bool]:
        """Run one fleet to completion; returns ``(rc, recovered)``.
        Success = every rank exits with a success code; the first
        non-success exit takes the fleet down (kill the survivors —
        they are wedged in a collective that will never complete)."""
        procs = self._spawn_fleet(attempt)
        self.stats.add(attempts=1, last_nworkers=self.nworkers)
        t0 = time.perf_counter()
        recovered = died_t is None
        next_ckpt_poll = 0.0
        pending = list(procs)
        rc = 0
        while True:
            for p in list(pending):
                prc = p.poll()
                if prc is None:
                    continue
                pending.remove(p)
                if prc not in self.success_codes:
                    # one host down = the fleet is down: survivors are
                    # blocked inside a collective missing a participant
                    self.stats.add(lost_hosts=1)
                    self._kill_fleet(pending)
                    return prc, recovered and died_t is not None
            now = time.perf_counter()
            if not recovered and now >= next_ckpt_poll:
                next_ckpt_poll = now + 0.25
                if self._latest_step() > watch_from:
                    dt = now - died_t
                    self.stats.add(recovery_s=dt, last_recovery_s=dt)
                    _trace.instant("fault:fleet_recovered", cat="faults",
                                   attempt=attempt,
                                   nworkers=self.nworkers,
                                   recovery_s=round(dt, 4))
                    recovered = True
            if not pending:
                if not recovered and rc in self.success_codes \
                        and died_t is not None:
                    dt = time.perf_counter() - died_t
                    self.stats.add(recovery_s=dt, last_recovery_s=dt)
                    recovered = True
                return rc, recovered and died_t is not None
            if self._stopping:
                self._kill_fleet(pending)
                return -9, recovered and died_t is not None
            if self.timeout_s is not None and now - t0 > self.timeout_s:
                self._kill_fleet(pending)
                return -9, recovered and died_t is not None
            time.sleep(_POLL_S)

    # -- the loop ----------------------------------------------------------
    def stop(self) -> None:
        """Ask a concurrent :meth:`run` to wind down: the current fleet
        is killed, backoff waits are cut short, run() returns without
        further restarts."""
        self._stopping = True

    def run(self) -> int:
        """Run fleet attempts until one finishes clean (every rank
        exits a success code); returns that code.  Raises
        :class:`MXNetError` when the in-window restart budget is
        exhausted."""
        t_run = time.perf_counter()
        attempt = 0
        window = RestartWindow(self.max_restarts, self.restart_window_s)
        died_t: Optional[float] = None
        watch_from = self._latest_step()
        try:
            while True:
                rc, recovered = self._attempt(attempt, watch_from,
                                              died_t)
                self.stats.add(last_rc=rc)
                if recovered:
                    self.backoff.reset()
                if rc in self.success_codes or self._stopping:
                    return rc
                died_t = time.perf_counter()
                watch_from = self._latest_step()
                if self.on_loss == "shrink" \
                        and self.nworkers > self.min_workers:
                    self.nworkers -= 1
                in_window = window.note()
                if in_window > self.max_restarts:
                    self.stats.add(gave_up=True)
                    raise MXNetError(
                        "fleet %r: lost a host %d times within %.0fs "
                        "(restart budget %d, MXNET_DIST_FLEET_MAX_"
                        "RESTARTS over MXNET_DIST_FLEET_WINDOW_S); last "
                        "exit code %s — the fleet is not recovering, "
                        "stop re-forming it"
                        % (self.name, in_window, self.restart_window_s,
                           self.max_restarts, rc))
                wait = self.backoff.next_wait()
                _trace.instant("fault:fleet_restart", cat="faults",
                               attempt=attempt, rc=rc,
                               nworkers=self.nworkers,
                               wait_s=round(wait, 4))
                attempt += 1
                self.stats.add(restarts=1, backoff_wait_s=wait)
                self.backoff.sleep(wait,
                                   should_stop=lambda: self._stopping)
        finally:
            self.stats.add(run_s=time.perf_counter() - t_run)
