"""Fused scan-based RNN operator (ops/rnn.py): parity with the unrolled
cells, gradients, and the drop-in lstm_unroll_scan builder."""
import sys, os
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.models import lstm_unroll, lstm_unroll_scan
from check_utils import check_numeric_gradient, reldiff

rng = np.random.RandomState(42)


def _rnn_location(mode, T=3, B=2, E=4, H=5, L=1):
    gates = {"rnn_relu": 1, "rnn_tanh": 1, "gru": 3, "lstm": 4}[mode]
    loc = {"data": rng.uniform(-0.5, 0.5, (T, B, E)).astype(np.float32)}
    for i in range(L):
        in_dim = E if i == 0 else H
        loc["l%d_i2h_weight" % i] = rng.uniform(
            -0.3, 0.3, (gates * H, in_dim)).astype(np.float32)
        loc["l%d_i2h_bias" % i] = rng.uniform(
            -0.1, 0.1, (gates * H,)).astype(np.float32)
        loc["l%d_h2h_weight" % i] = rng.uniform(
            -0.3, 0.3, (gates * H, H)).astype(np.float32)
        loc["l%d_h2h_bias" % i] = rng.uniform(
            -0.1, 0.1, (gates * H,)).astype(np.float32)
    loc["state"] = rng.uniform(-0.2, 0.2, (L, B, H)).astype(np.float32)
    if mode == "lstm":
        loc["state_cell"] = rng.uniform(-0.2, 0.2,
                                        (L, B, H)).astype(np.float32)
    return loc


@pytest.mark.parametrize("mode", ["rnn_tanh", "gru", "lstm"])
def test_rnn_op_shapes_and_grad(mode):
    x = mx.sym.Variable("data")
    sym = mx.sym.RNN(x, state_size=5, num_layers=1, mode=mode, name="r")
    loc = _rnn_location(mode)
    shapes = {k: v.shape for k, v in loc.items()}
    # rename auto-created arg names to match location keys
    args = sym.list_arguments()
    loc2 = {}
    for a in args:
        base = a.replace("r_", "", 1) if a.startswith("r_") else a
        loc2[a] = loc[base]
    _, out_shapes, _ = sym.infer_shape(
        **{k: v.shape for k, v in loc2.items()})
    assert tuple(out_shapes[0]) == (3, 2, 5)
    check_numeric_gradient(sym, loc2, numeric_eps=1e-2, check_eps=0.08)


def test_rnn_op_state_outputs():
    x = mx.sym.Variable("data")
    sym = mx.sym.RNN(x, state_size=5, num_layers=2, mode="lstm",
                     state_outputs=True, name="r")
    loc = _rnn_location("lstm", L=2)
    args = sym.list_arguments()
    loc2 = {a: loc[a.replace("r_", "", 1) if a.startswith("r_") else a]
            for a in args}
    ex = sym.simple_bind(mx.current_context(), grad_req="null",
                         **{k: v.shape for k, v in loc2.items()})
    for k, v in loc2.items():
        ex.arg_dict[k][:] = v
    ex.forward(is_train=False)
    assert len(ex.outputs) == 3
    assert ex.outputs[0].shape == (3, 2, 5)   # output
    assert ex.outputs[1].shape == (2, 2, 5)   # final h, both layers
    assert ex.outputs[2].shape == (2, 2, 5)   # final c
    # final h of the last layer equals output at the last timestep
    assert np.allclose(ex.outputs[1].asnumpy()[-1],
                       ex.outputs[0].asnumpy()[-1], atol=1e-6)


@pytest.mark.parametrize("layers", [1, 2])
def test_scan_lstm_matches_unrolled(layers):
    """lstm_unroll_scan and lstm_unroll share weight names, gate layout,
    and semantics: identical params -> identical outputs and gradients."""
    T, B, V, H, E = 4, 3, 11, 6, 5
    net_a = lstm_unroll(layers, T, V, H, E, V)
    net_b = lstm_unroll_scan(layers, T, V, H, E, V)

    shapes = {"data": (B, T), "softmax_label": (B, T)}
    for i in range(layers):
        shapes["l%d_init_c" % i] = (B, H)
        shapes["l%d_init_h" % i] = (B, H)

    vals = {"data": rng.randint(0, V, (B, T)).astype(np.float32),
            "softmax_label": rng.randint(0, V, (B, T)).astype(np.float32)}
    for i in range(layers):
        vals["l%d_init_c" % i] = np.zeros((B, H), np.float32)
        vals["l%d_init_h" % i] = np.zeros((B, H), np.float32)

    outs, grads = [], []
    for net in (net_a, net_b):
        arg_shapes, _, _ = net.infer_shape(**shapes)
        names = net.list_arguments()
        ex = net.simple_bind(mx.current_context(), grad_req="write",
                             **shapes)
        prng = np.random.RandomState(7)
        for n, s in zip(names, arg_shapes):
            if n in vals:
                ex.arg_dict[n][:] = vals[n]
            else:
                ex.arg_dict[n][:] = prng.uniform(-0.2, 0.2, s)
        ex.forward(is_train=True)
        ex.backward()
        outs.append(ex.outputs[0].asnumpy())
        grads.append({n: ex.grad_dict[n].asnumpy() for n in names
                      if ex.grad_dict.get(n) is not None
                      and "init" not in n and n != "data"
                      and n != "softmax_label"})
    assert reldiff(outs[0], outs[1]) < 1e-4
    for k in grads[0]:
        assert reldiff(grads[0][k], grads[1][k]) < 1e-3, k


def test_scan_lstm_trains():
    """End-to-end: the scan form converges on a toy copy task through the
    fused Module path."""
    T, B, V, H, E = 6, 8, 5, 32, 16
    mx.random.seed(0)
    net = lstm_unroll_scan(1, T, V, H, E, V)
    n = 128
    X = rng.randint(1, V, (n, T)).astype(np.float32)
    y = X.copy()   # predict the input token (easy memorization)
    data = {"data": X,
            "l0_init_c": np.zeros((n, H), np.float32),
            "l0_init_h": np.zeros((n, H), np.float32)}
    it = mx.io.NDArrayIter(data, {"softmax_label": y}, batch_size=B)
    mod = mx.mod.Module(net, data_names=("data", "l0_init_c", "l0_init_h"),
                        context=mx.current_context())
    mod.fit(it, num_epoch=25, eval_metric="ce", optimizer="adam",
            optimizer_params={"learning_rate": 0.01})
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0].asnumpy()       # (T*B, V) t-major
    pred = out.reshape(T, B, V).argmax(axis=2).T
    acc = (pred == batch.label[0].asnumpy()).mean()
    assert acc > 0.9, acc


def test_rnn_dropout_without_rng_raises():
    """p>0 inter-layer dropout at training time with no rng threaded in
    must fail loudly — silently training unregularized would be invisible."""
    from mxnet_tpu.ops.registry import _OP_REGISTRY, OpContext
    op = _OP_REGISTRY["RNN"]
    p = op.parse_params({"state_size": 5, "num_layers": 2, "mode": "lstm",
                         "p": 0.5})
    loc = _rnn_location("lstm", L=2)
    inputs = [loc[n] for n in op.list_arguments(p) if n != "data"]
    inputs.insert(0, loc["data"])
    with pytest.raises(ValueError, match="dropout requires an rng"):
        op.forward(p, inputs, [], OpContext(is_train=True, rng=None))
    # eval mode needs no rng (dropout is identity)
    outs = op.forward(p, inputs, [], OpContext(is_train=False, rng=None))
    assert outs[0].shape == (3, 2, 5)
    # single-layer nets have no inter-layer dropout to lose: no raise
    p1 = op.parse_params({"state_size": 5, "num_layers": 1, "mode": "lstm",
                          "p": 0.5})
    loc1 = _rnn_location("lstm", L=1)
    ins1 = [loc1[n] for n in op.list_arguments(p1)]
    op.forward(p1, ins1, [], OpContext(is_train=True, rng=None))
