"""Example-corpus integration tests: every flagship example must run
end-to-end from the command line in its CI-light (synthetic-data) mode.
The reference used its examples as de-facto integration tests (nightly
test_all.sh drove train_mnist/train_cifar10); this file does the same."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(rel_dir, argv, timeout=420):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ""
    return subprocess.run([sys.executable] + argv, capture_output=True,
                          text=True, timeout=timeout, env=env,
                          cwd=os.path.join(ROOT, rel_dir))


def test_mnist_bucket_example():
    res = _run("example/image-classification",
               ["mnist_bucket.py", "--synthetic", "--num-epochs", "1"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "bucket usage counts" in res.stderr + res.stdout


def test_char_rnn_example_trains_and_samples():
    res = _run("example/rnn",
               ["char_rnn.py", "--num-epochs", "1", "--seq-len", "8",
                "--num-hidden", "32", "--num-embed", "16", "--sample", "20"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "SAMPLE>" in res.stdout, res.stdout + res.stderr


def test_speech_demo_pipeline(tmp_path):
    arch = str(tmp_path / "train.npz")
    prefix = str(tmp_path / "am")
    # a missing archive path is auto-filled with synthetic utterances
    res = _run("example/speech-demo",
               ["train_lstm_proj.py", "--num-epochs", "4",
                "--train-archive", arch, "--model-prefix", prefix])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "frame accuracy" in res.stdout, res.stdout + res.stderr

    res = _run("example/speech-demo",
               ["decode_mxnet.py", "--archive", arch, "--epoch", "4",
                "--model-prefix", prefix,
                "--output", str(tmp_path / "post.npz")])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "DECODED" in res.stdout, res.stdout + res.stderr


def test_ndsb_list_and_submission(tmp_path):
    import shutil
    try:
        res = _run("example/kaggle-ndsb1",
                   ["gen_img_list.py", "--demo", "--stratified"])
        assert res.returncode == 0, res.stdout + res.stderr
        assert "train" in res.stdout
        res = _run("example/kaggle-ndsb1", ["submission_dsb.py"])
        assert res.returncode == 0, res.stdout + res.stderr
    finally:
        base = os.path.join(ROOT, "example", "kaggle-ndsb1")
        shutil.rmtree(os.path.join(base, "demo_tree"), ignore_errors=True)
        for fn in ("smoke_test.lst", "submission.csv"):
            try:
                os.remove(os.path.join(base, fn))
            except OSError:
                pass


@pytest.mark.slow
def test_train_cifar10_synthetic():
    res = _run("example/image-classification",
               ["train_cifar10.py", "--synthetic", "--num-epochs", "1",
                "--batch-size", "16", "--num-examples", "64"], timeout=560)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Train-accuracy" in res.stderr + res.stdout


@pytest.mark.slow
def test_train_cifar10_mirroring_synthetic():
    res = _run("example/image-classification",
               ["train_cifar10_mirroring.py", "--synthetic",
                "--num-epochs", "1", "--batch-size", "16",
                "--num-examples", "64"], timeout=560)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Train-accuracy" in res.stderr + res.stdout


@pytest.mark.slow
def test_rcnn_train_and_demo():
    """Fast R-CNN example: synthetic ROI training to an accuracy gate,
    then the dense-proposal detection demo finds the planted object."""
    res = _run("example/rcnn",
               ["train_fast_rcnn.py", "--num-epochs", "10",
                "--model-prefix", "/tmp/rcnn_ci"], timeout=560)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "final roi accuracy" in res.stdout
    res = _run("example/rcnn",
               ["demo.py", "--model-prefix", "/tmp/rcnn_ci",
                "--epoch", "10"], timeout=560)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "DEMO-OK" in res.stdout, res.stdout + res.stderr


@pytest.mark.slow
def test_neural_style_end_to_end_generator(tmp_path):
    """Feed-forward style transfer (end_to_end/): perceptual-loss
    generator training must reduce the loss, and the saved generator
    must stylize a fresh image in one forward pass."""
    prefix = str(tmp_path / "gen")
    res = _run("example/neural-style/end_to_end",
               ["boost_train.py", "--epochs", "3",
                "--batches-per-epoch", "6", "--model-prefix", prefix],
               timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "BOOST-TRAIN-OK" in res.stdout
    res = _run("example/neural-style/end_to_end",
               ["boost_inference.py", "--model-prefix", prefix,
                "--epoch", "3", "--out", str(tmp_path / "styled.npy")],
               timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "BOOST-INFERENCE-OK" in res.stdout
    import numpy as np
    styled = np.load(str(tmp_path / "styled.npy"))
    assert styled.shape == (1, 3, 64, 64)
    assert 0 <= styled.min() and styled.max() <= 300  # pixel-ish range


@pytest.mark.slow
def test_neural_style_generator_v4(tmp_path):
    """The deeper residual generator variant trains too."""
    prefix = str(tmp_path / "gen4")
    res = _run("example/neural-style/end_to_end",
               ["boost_train.py", "--generator", "v4", "--epochs", "2",
                "--batches-per-epoch", "4", "--model-prefix", prefix],
               timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "BOOST-TRAIN-OK" in res.stdout


@pytest.mark.slow
def test_train_cifar10_resnet_synthetic():
    """The 6n+2 CIFAR residual network (reference
    train_cifar10_resnet.py reproduction) trains CI-light."""
    res = _run("example/image-classification",
               ["train_cifar10_resnet.py", "--depth", "20", "--synthetic",
                "--num-epochs", "2", "--batch-size", "32",
                "--num-examples", "256"], timeout=420)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "Train-accuracy" in res.stderr + res.stdout
