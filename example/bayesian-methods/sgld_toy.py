"""Stochastic Gradient Langevin Dynamics demo (reference
example/bayesian-methods/{sgld.ipynb,algos.py} capability).

Samples from the posterior of a 2-parameter Gaussian-mixture toy problem
(Welling & Teh 2011's running example) with the built-in SGLD optimizer and
checks the posterior mean; the injected Gaussian noise comes from the
framework RNG so runs are seed-reproducible.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-samples", type=int, default=2000)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-steps", type=int, default=3000)
    parser.add_argument("--lr", type=float, default=1e-2)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(42)

    # data ~ N(theta, 1) with true theta = 1.5; prior theta ~ N(0, 10)
    true_theta = 1.5
    rng = np.random.RandomState(0)
    data = (true_theta + rng.randn(args.num_samples)).astype(np.float32)

    x = mx.sym.Variable("x")
    theta = mx.sym.Variable("theta")
    # negative log joint (up to const): theta^2/(2*10) + sum (x-theta)^2/2
    # scaled so grad matches a minibatch estimate of the full dataset
    diff = mx.sym.broadcast_minus(x, theta)
    loss = mx.sym.MakeLoss(
        mx.sym.sum(diff * diff) * (args.num_samples /
                                   (2.0 * args.batch_size))
        + mx.sym.sum(theta * theta) * (1.0 / 20.0))

    exe = loss.simple_bind(ctx=mx.cpu(), grad_req="write",
                           x=(args.batch_size,), theta=(1,))
    exe.arg_dict["theta"][:] = 0.0

    opt = mx.optimizer.SGLD(learning_rate=args.lr / args.num_samples,
                            rescale_grad=1.0)
    state = opt.create_state(0, exe.arg_dict["theta"])
    samples = []
    for step in range(args.num_steps):
        idx = rng.randint(0, args.num_samples, size=args.batch_size)
        exe.arg_dict["x"][:] = data[idx]
        exe.forward(is_train=True)
        exe.backward()
        opt.update(0, exe.arg_dict["theta"], exe.grad_dict["theta"], state)
        if step > args.num_steps // 2:          # burn-in discard
            samples.append(float(exe.arg_dict["theta"].asnumpy()[0]))

    post_mean = float(np.mean(samples))
    post_std = float(np.std(samples))
    print("posterior mean %.3f (true %.3f), std %.4f over %d samples"
          % (post_mean, true_theta, post_std, len(samples)))
    assert abs(post_mean - true_theta) < 0.25


if __name__ == "__main__":
    main()
