classdef model < handle
%MODEL Load a trained checkpoint and run prediction from MATLAB.
%
% The MATLAB surface the reference shipped (matlab/+mxnet/model.m),
% rebuilt over this framework's predict ABI (include/c_predict_api.h,
% libmxtpu_predict.so).  Prediction only, like the reference: load the
% two checkpoint artifacts, set input, forward, read outputs.
%
%   model = mxnet.model;
%   model.load('mlp', 10);               % mlp-symbol.json + mlp-0010.params
%   probs = model.forward(X);            % X: (features, batch) single
%
% MATLAB-only (Octave lacks loadlibrary/calllib).  This image ships
% no MATLAB, so the package is
% untested here (same status the reference's matlab binding had -- no CI
% ever ran it).  The ABI underneath is exercised by tests/test_c_api.py
% and the amalgamation tests; callmxnet.m documents the library setup.

properties
  symbol    % symbol JSON text
  params    % raw bytes of the .params blob
  verbose   % print timing info
end

properties (Access = private)
  predictor        % libpointer to the PredictorHandle
  prev_input_size  % re-create the predictor only when shapes change
end

methods
  function obj = model()
    obj.predictor = libpointer('voidPtr', 0);
    obj.prev_input_size = [];
    obj.verbose = false;
  end

  function delete(obj)
    obj.free_predictor();
  end

  function load(obj, prefix, epoch)
  %LOAD read prefix-symbol.json and prefix-%04d.params
    fid = fopen([prefix, '-symbol.json'], 'r');
    assert(fid >= 0, ['cannot open ', prefix, '-symbol.json']);
    obj.symbol = fread(fid, inf, 'char=>char')';
    fclose(fid);
    fid = fopen(sprintf('%s-%04d.params', prefix, epoch), 'rb');
    assert(fid >= 0, 'cannot open the params blob');
    obj.params = fread(fid, inf, 'uint8=>uint8');
    fclose(fid);
    obj.free_predictor();
  end

  function out = forward(obj, data)
  %FORWARD run one batch through the net; data is (features..., batch)
  % in MATLAB column-major order — exactly the row-major (batch,
  % features...) layout the framework expects, memory verbatim.
    siz = size(data);
    if ~isequal(siz, obj.prev_input_size)
      obj.free_predictor();
      obj.prev_input_size = siz;
    end
    if obj.predictor.Value == 0
      if obj.verbose
        fprintf('create predictor with input size [%s]\n', ...
                num2str(siz));
      end
      % MATLAB dims reversed = framework shape
      shape = uint32(fliplr(siz));
      indptr = uint32([0, numel(shape)]);
      callmxnet('MXPredCreate', obj.symbol, ...
                libpointer('voidPtr', obj.params), ...
                int32(numel(obj.params)), int32(1), int32(0), ...
                uint32(1), {'data'}, indptr, shape, obj.predictor);
    end
    callmxnet('MXPredSetInput', obj.predictor, 'data', ...
              single(data(:)), uint32(numel(data)));
    callmxnet('MXPredForward', obj.predictor);

    % read output 0
    shape_ptr = libpointer('uint32PtrPtr');
    ndim = libpointer('uint32Ptr', 0);
    callmxnet('MXPredGetOutputShape', obj.predictor, uint32(0), ...
              shape_ptr, ndim);
    setdatatype(shape_ptr.Value, 'uint32Ptr', double(ndim.Value));
    oshape = double(shape_ptr.Value.Value');
    n = prod(oshape);
    buf = libpointer('singlePtr', single(zeros(1, n)));
    callmxnet('MXPredGetOutput', obj.predictor, uint32(0), buf, ...
              uint32(n));
    setdatatype(buf, 'singlePtr', n);
    % framework row-major -> MATLAB column-major under reversed dims
    % (pad 1-d outputs: reshape needs at least two size elements)
    out = reshape(buf.Value, [fliplr(oshape), 1]);
  end
end

methods (Access = private)
  function free_predictor(obj)
    if obj.predictor.Value ~= 0
      callmxnet('MXPredFree', obj.predictor);
      obj.predictor = libpointer('voidPtr', 0);
    end
  end
end

end
