"""Runtime kernel authoring from Python.

Reference: src/common/mxrtc.cc + python/mxnet/rtc.py — NVRTC-compiled CUDA
kernels launched on NDArrays.

TPU-native: Pallas IS the runtime-kernel system (SURVEY §2.1 RTC row): users
author kernels in Python against ``pl.BlockSpec`` grids instead of CUDA
source strings; compilation and caching are handled by XLA.  ``Rtc`` keeps
the reference's (name, inputs, outputs, kernel) constructor shape but takes
a python kernel function, not CUDA source.
"""
from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ndarray import NDArray

try:
    from jax.experimental import pallas as pl
    HAS_PALLAS = True
except Exception:  # pragma: no cover
    pl = None
    HAS_PALLAS = False

__all__ = ["Rtc", "pallas_call", "HAS_PALLAS"]


def pallas_call(kernel, out_shape, **kwargs):
    """Thin passthrough to pl.pallas_call for user kernels."""
    if not HAS_PALLAS:
        raise MXNetError("pallas unavailable in this JAX build")
    # lint: allow(raw-pallas-call) — the rtc API surface IS the
    # user-kernel passthrough; user kernels cannot ride the searched/
    # parity-gated ops/pallas_kernels module
    return pl.pallas_call(kernel, out_shape=out_shape, **kwargs)


class Rtc:
    """Python-authored device kernel (reference rtc.py:9-61 reimagined).

    Parameters
    ----------
    name : str
        kernel name (for caches/debugging).
    inputs : list of (name, NDArray)
        prototype inputs fixing shapes/dtypes.
    outputs : list of (name, NDArray)
        prototype outputs fixing shapes/dtypes.
    kernel : callable
        either a Pallas kernel ``kernel(*in_refs, *out_refs)`` (used when
        ``use_pallas=True``) or a jnp function ``kernel(*inputs) -> outputs``.
    """

    def __init__(self, name: str, inputs, outputs, kernel: Callable,
                 use_pallas: bool = False):
        self.name = name
        self._in_proto = [(n, a.shape, a.dtype) for n, a in inputs]
        self._out_proto = [(n, a.shape, a.dtype) for n, a in outputs]
        self._use_pallas = use_pallas
        if use_pallas:
            if not HAS_PALLAS:
                raise MXNetError("pallas unavailable in this JAX build")
            out_shape = [jax.ShapeDtypeStruct(s, d) for (_, s, d) in self._out_proto]
            # lint: allow(raw-jit) — pallas_call executables do not
            # round-trip PJRT serialize_executable; rtc kernels are
            # user-supplied one-offs, not warm-restart hot paths
            # lint: allow(raw-pallas-call) — user-supplied kernel; the
            # rtc passthrough cannot ride the gated ops/pallas_kernels
            self._fn = jax.jit(pl.pallas_call(kernel, out_shape=out_shape))
        else:
            # lint: allow(raw-jit) — same: user-supplied one-off kernel
            self._fn = jax.jit(kernel)

    def push(self, ins: Sequence[NDArray], outs: Sequence[NDArray],
             grid_dims: Tuple[int, ...] = None, block_dims: Tuple[int, ...] = None):
        """Run the kernel (reference rtc.py push; grid/block dims accepted for
        API compatibility — XLA/Mosaic choose the schedule)."""
        res = self._fn(*[a._get() for a in ins])
        if not isinstance(res, (tuple, list)):
            res = [res]
        if len(res) != len(outs):
            raise MXNetError("kernel produced %d outputs, expected %d"
                             % (len(res), len(outs)))
        for o, r in zip(outs, res):
            o._set(jnp.asarray(r, dtype=o.dtype))
