"""dist_async parameter-server test (reference tests/nightly pattern:
launched by tools/launch.py -n W -s S with the local launcher).

Asserts exact arithmetic of the async server's default accumulate mode
(stored += merged, kvstore_dist_server.h default), big-array striping
across servers, and server-side optimizer updates (pickled SGD shipped via
the command channel).  Determinism argument: each worker's own push→pull on
one FIFO connection flushes its pushes; the barrier then orders all
workers' flushed pushes before the final pull, and accumulation/SGD(+wd=0)
updates are commutative.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# small stripe threshold so the "big array" path is cheap to test
os.environ.setdefault("MXNET_KVSTORE_BIGARRAY_BOUND", "1000")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
# CPU multi-process: drop the axon sitecustomize pin so JAX_PLATFORMS wins
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]

import numpy as np
import mxnet_tpu as mx


def main():
    kv = mx.create_kvstore("dist_async")
    rank = kv.rank
    nworker = kv.num_workers
    nrepeat = 3

    # -- accumulate mode, small key ----------------------------------------
    shape = (4, 5)
    kv.init(3, mx.nd.ones(shape))
    for _ in range(nrepeat):
        kv.push(3, mx.nd.ones(shape) * (rank + 1))
    out = mx.nd.zeros(shape)
    kv.pull(3, out=out)          # flushes this worker's pushes
    kv.barrier()
    kv.pull(3, out=out)
    expected = 1 + nrepeat * sum(r + 1 for r in range(nworker))
    assert np.allclose(out.asnumpy(), expected), (out.asnumpy().flat[0],
                                                  expected)

    # -- big array: striped across all servers -----------------------------
    big_shape = (50, 60)         # 3000 > bound => striped
    kv.init(99, mx.nd.ones(big_shape))
    for _ in range(nrepeat):
        kv.push(99, mx.nd.ones(big_shape) * (rank + 1))
    big_out = mx.nd.zeros(big_shape)
    kv.pull(99, out=big_out)
    kv.barrier()
    kv.pull(99, out=big_out)
    assert np.allclose(big_out.asnumpy(), expected), (
        big_out.asnumpy().flat[0], expected)

    # -- server-side optimizer (async update-per-push) ---------------------
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, wd=0.0,
                                      rescale_grad=1.0))
    kv.init(7, mx.nd.ones(shape))
    for _ in range(nrepeat):
        kv.push(7, mx.nd.ones(shape))          # grad = 1 per push
    w = mx.nd.zeros(shape)
    kv.pull(7, out=w)
    kv.barrier()
    kv.pull(7, out=w)
    w_expected = 1.0 - 0.1 * nrepeat * nworker
    assert np.allclose(w.asnumpy(), w_expected, atol=1e-6), (
        w.asnumpy().flat[0], w_expected)

    kv.barrier()
    kv.close()
    print("PASSED dist_async rank %d/%d" % (rank, nworker))


if __name__ == "__main__":
    main()
