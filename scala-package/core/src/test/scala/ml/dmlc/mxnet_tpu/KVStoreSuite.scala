package ml.dmlc.mxnet_tpu

import org.scalatest.FunSuite

/** Reference KVStoreSuite.scala analogue. */
class KVStoreSuite extends FunSuite {

  test("init, push, pull through the local store") {
    val kv = KVStore.create("local")
    assert(kv.`type` == "local")
    val w = NDArray.zeros(Shape(4))
    kv.init(Array(3), Array(w))
    val g = NDArray.ones(Shape(4))
    kv.push(Array(3), Array(g))
    val out = NDArray.zeros(Shape(4))
    kv.pull(Array(3), Array(out))
    assert(out.toArray.forall(_ == 1f))
    kv.dispose()
  }

  test("aggregate: two pushes before a pull sum") {
    val kv = KVStore.create("local")
    val w = NDArray.zeros(Shape(2))
    kv.init(Array(9), Array(w))
    kv.push(Array(9), Array(NDArray.ones(Shape(2))))
    kv.push(Array(9), Array(NDArray.ones(Shape(2)) * 2f))
    val out = NDArray.zeros(Shape(2))
    kv.pull(Array(9), Array(out))
    // single-worker local store applies pushes in order; the pulled
    // value reflects the merged updates
    assert(out.toArray.forall(_ >= 2f))
    kv.dispose()
  }

  test("rank and world size on a local store") {
    val kv = KVStore.create("local")
    assert(kv.rank == 0)
    assert(kv.numWorkers == 1)
    kv.dispose()
  }

  test("role queries default to worker") {
    assert(KVStore.isWorkerNode)
    assert(!KVStore.isServerNode)
    assert(!KVStore.isSchedulerNode)
  }
}
