"""Predictor (c_predict_api parity) + engine semantics tests."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.predictor import Predictor, create_predictor


def _train_tiny(tmp_path):
    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(64, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer_params={"learning_rate": 0.5})
    arg, aux = mod.get_params()
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 3, net, arg, aux)
    return prefix, X, mod, it


def test_predictor_matches_module(tmp_path):
    prefix, X, mod, it = _train_tiny(tmp_path)
    pred = create_predictor(prefix, 3, {"data": (16, 6),
                                        "softmax_label": (16,)})
    out = pred.predict(X[:16])
    module_out = mod.predict(it, num_batch=1).asnumpy()
    assert np.allclose(out, module_out, atol=1e-5)


def test_predictor_reshape(tmp_path):
    prefix, X, _, _ = _train_tiny(tmp_path)
    pred = create_predictor(prefix, 3, {"data": (16, 6),
                                        "softmax_label": (16,)})
    out16 = pred.predict(X[:16])
    pred.reshape({"data": (4, 6), "softmax_label": (4,)})
    out4 = pred.predict(X[:4])
    assert np.allclose(out16[:4], out4, atol=1e-5)


def test_engine_naive_mode():
    """NaiveEngine-equivalent sync mode (reference MXNET_ENGINE_TYPE)."""
    from mxnet_tpu import engine
    with engine.naive_mode():
        assert engine.engine().is_naive
        a = mx.nd.ones((4, 4)) * 3
        assert (a.asnumpy() == 3).all()
    assert not engine.engine().is_naive


def test_engine_waitall_and_ordering():
    """Writes to a chunk serialize; wait_for_all drains pending work
    (reference threaded_engine_test.cc semantics)."""
    a = mx.nd.zeros((100, 100))
    for i in range(10):
        a += 1  # each write depends on the previous buffer
    mx.nd.waitall()
    assert (a.asnumpy() == 10).all()
    # read-after-write through a view
    v = a[5:10]
    a *= 2
    assert (v.asnumpy() == 20).all()


def test_profiler_trace(tmp_path):
    """mx.profiler: start/stop produces a trace dir; scope annotates."""
    out = str(tmp_path / "trace")
    mx.profiler.profiler_set_config(filename=out)
    mx.profiler.profiler_set_state("run")
    with mx.profiler.scope("work"):
        (mx.nd.ones((64, 64)) * 2).asnumpy()
    mx.profiler.profiler_set_state("stop")
    assert mx.profiler.state() == "stop"
    import os as _os
    found = []
    for root, _, files in _os.walk(out):
        found += files
    assert found, "no trace files written"
