# FeedForward training (reference R-package/R/model.R
# mx.model.FeedForward.create): executor-level training loop with an
# R-side SGD(+momentum) updater — the reference R binding likewise ran
# its updater through the binding layer rather than a server process.

mx.model.init.params <- function(symbol, input.shapes, initializer.scale) {
  inferred <- do.call(mx.symbol.infer.shape,
                      c(list(symbol), input.shapes))
  arg.names <- arguments.MXSymbol(symbol)
  params <- list()
  for (n in arg.names) {
    if (n %in% names(input.shapes)) next
    shape <- inferred$arg.shapes[[n]]
    if (grepl("bias$|beta$", n)) {
      params[[n]] <- array(0, dim = shape)
    } else if (grepl("gamma$", n)) {
      params[[n]] <- array(1, dim = shape)
    } else {
      fan.in <- prod(shape) / shape[[length(shape)]]
      sd <- sqrt(2.0 / fan.in)
      params[[n]] <- array(rnorm(prod(shape), sd = sd), dim = shape)
    }
  }
  params
}

mx.model.FeedForward.create <- function(symbol, X, y, ctx = mx.cpu(),
                                        num.round = 10,
                                        optimizer = NULL,
                                        learning.rate = 0.1,
                                        momentum = 0.9,
                                        array.batch.size = 32,
                                        eval.data = NULL,
                                        eval.metric = mx.metric.accuracy,
                                        initializer = NULL,
                                        arg.params = NULL,
                                        begin.round = 1,
                                        batch.end.callback = NULL,
                                        epoch.end.callback = NULL,
                                        verbose = TRUE) {
  # Reference mx.model.FeedForward.create surface: optimizer may be an
  # MXOptimizer (native registry update path) or NULL (the in-R
  # SGD+momentum loop); eval.data = list(data=, label=) scores a
  # validation split each round; arg.params + begin.round resume a
  # loaded checkpoint.
  batch <- array.batch.size
  feat <- ncol(X)
  # R dim order is the REVERSE of the framework's (column-major vs
  # row-major, reference R binding convention): framework (batch, feat)
  # is R c(feat, batch)
  input.shapes <- list(data = c(feat, batch),
                       softmax_label = batch)
  exec <- do.call(mx.simple.bind,
                  c(list(symbol, ctx = ctx, grad.req = "write"),
                    input.shapes))
  params <- if (!is.null(arg.params)) {
    mx.util.filter.params(arg.params, symbol)
  } else if (is.null(initializer)) {
    mx.model.init.params(symbol, input.shapes, 0.07)
  } else {
    mx.init.create(initializer, symbol, input.shapes)
  }
  for (n in names(params)) mx.exec.update.arg(exec, n, params[[n]])
  updater <- NULL
  momenta <- NULL
  if (is.character(optimizer)) {
    # reference semantics: a NAME creates the optimizer here, with the
    # loss-head batch-sum normalized (rescale_grad = 1/batch) — the
    # dynamics then match the in-R default loop exactly
    optimizer <- mx.opt.create(optimizer, learning.rate = learning.rate,
                               momentum = momentum,
                               rescale.grad = 1 / batch)
  }
  if (!is.null(optimizer)) {
    # an MXOptimizer object is used as-is: its creator owns rescale.grad
    updater <- mx.opt.get.updater(optimizer)
  } else {
    momenta <- lapply(params, function(p) array(0, dim = dim(p)))
  }

  iter <- mx.io.arrayiter(X, y, batch.size = batch, shuffle = TRUE)
  keep.going <- TRUE
  if (begin.round > num.round) {
    stop("begin.round exceeds num.round: nothing to train")
  }
  # num.round is the FINAL round number (reference resume semantics):
  # begin.round=6, num.round=10 trains rounds 6..10
  for (round in begin.round:num.round) {
    if (!keep.going) break
    state <- eval.metric$init()
    mx.io.reset(iter)
    nbatch <- 0L
    repeat {
      b <- mx.io.next(iter)
      if (is.null(b)) break
      nbatch <- nbatch + 1L
      # row-major batch: feed t(data) so R's column-major memory lines
      # up with the framework's (batch, feat) layout
      mx.exec.update.arg(exec, "data", t(b$data))
      mx.exec.update.arg(exec, "softmax_label", b$label)
      mx.exec.forward(exec, is.train = TRUE)
      mx.exec.backward(exec)
      probs <- t(as.array(mx.exec.outputs(exec)[[1]]))
      state <- eval.metric$update(state, b$label, probs)
      if (!is.null(updater)) {
        idx <- 0L
        for (n in names(params)) {
          idx <- idx + 1L
          updater(idx, exec$arg.arrays[[n]], exec$grad.arrays[[n]])
          p <- as.array(exec$arg.arrays[[n]])
          dim(p) <- dim(params[[n]])
          params[[n]] <- p
        }
      } else {
        for (n in names(params)) {
          g <- as.array(exec$grad.arrays[[n]])
          dim(g) <- dim(params[[n]])
          momenta[[n]] <- momentum * momenta[[n]] -
            learning.rate * (g / batch)
          params[[n]] <- params[[n]] + momenta[[n]]
          mx.exec.update.arg(exec, n, params[[n]])
        }
      }
      if (!is.null(batch.end.callback)) {
        ok <- batch.end.callback(round, nbatch, eval.metric$get(state))
        if (identical(ok, FALSE)) keep.going <- FALSE
      }
    }
    if (verbose) {
      cat(sprintf("Round [%d] Train-accuracy=%.4f\n", round,
                  eval.metric$get(state)))
    }
    model.now <- structure(list(symbol = symbol, params = params,
                                exec = exec, batch = batch),
                           class = "MXFeedForwardModel")
    if (!is.null(eval.data)) {
      val.probs <- predict(model.now, eval.data$data)
      val.state <- eval.metric$init()
      val.state <- eval.metric$update(val.state, eval.data$label,
                                      val.probs)
      if (verbose) {
        cat(sprintf("Round [%d] Validation-accuracy=%.4f\n", round,
                    eval.metric$get(val.state)))
      }
    }
    if (!is.null(epoch.end.callback)) {
      ok <- epoch.end.callback(model.now, round)
      if (identical(ok, FALSE)) keep.going <- FALSE
    }
  }
  structure(list(symbol = symbol, params = params, exec = exec,
                 batch = batch), class = "MXFeedForwardModel")
}

predict.MXFeedForwardModel <- function(object, X, ...) {
  exec <- object$exec
  batch <- object$batch
  n <- nrow(X)
  out <- NULL
  i <- 1
  while (i <= n) {
    idx <- i:min(i + batch - 1, n)
    chunk <- X[idx, , drop = FALSE]
    if (nrow(chunk) < batch) {
      # the executor's batch shape is fixed: pad the tail, trim after
      pad <- matrix(0, batch - nrow(chunk), ncol(X))
      chunk <- rbind(chunk, pad)
    }
    mx.exec.update.arg(exec, "data", t(chunk))
    mx.exec.forward(exec, is.train = FALSE)
    probs <- t(as.array(mx.exec.outputs(exec)[[1]]))
    out <- rbind(out, probs[seq_along(idx), , drop = FALSE])
    i <- i + batch
  }
  out
}

mx.model.save <- function(model, prefix, iteration) {
  mx.symbol.save(model$symbol, sprintf("%s-symbol.json", prefix))
  nds <- lapply(model$params, mx.nd.array)
  names(nds) <- paste0("arg:", names(model$params))
  mx.nd.save(nds, sprintf("%s-%04d.params", prefix, iteration))
  invisible(TRUE)
}

mx.model.load <- function(prefix, iteration) {
  symbol <- mx.symbol.load(sprintf("%s-symbol.json", prefix))
  nds <- mx.nd.load(sprintf("%s-%04d.params", prefix, iteration))
  params <- lapply(nds, as.array)
  names(params) <- sub("^arg:", "", names(params))
  # a checkpoint from another binding may carry entries this symbol
  # does not declare: drop them loudly rather than bind-time cryptically
  params <- mx.util.filter.params(params, symbol)
  list(symbol = symbol, params = params)
}
