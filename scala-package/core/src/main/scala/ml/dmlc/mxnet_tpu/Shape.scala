package ml.dmlc.mxnet_tpu

/** Immutable tensor shape (reference Shape.scala). */
class Shape(dims: Seq[Int]) extends Serializable {
  private val shape = dims.toVector

  def this(dims: Int*)(implicit d: DummyImplicit) = this(dims.toSeq)

  def apply(i: Int): Int = shape(i)
  def length: Int = shape.length
  def product: Int = shape.foldLeft(1)(_ * _)
  def toArray: Array[Int] = shape.toArray
  def toVector: Vector[Int] = shape
  def drop(n: Int): Shape = new Shape(shape.drop(n))
  def slice(from: Int, until: Int): Shape = new Shape(shape.slice(from, until))
  def head: Int = shape.head

  override def equals(o: Any): Boolean = o match {
    case s: Shape => s.toVector == shape
    case _ => false
  }
  override def hashCode(): Int = shape.hashCode()
  override def toString: String = s"(${shape.mkString(",")})"
}

object Shape {
  def apply(dims: Int*): Shape = new Shape(dims.toSeq)
  def apply(dims: Seq[Int])(implicit d: DummyImplicit): Shape =
    new Shape(dims)
}
