package ml.dmlc.mxnet_tpu

/**
 * Handle types, the resolved native library, and the error protocol.
 * Reference counterpart: scala-package/core/.../Base.scala — here handles
 * are plain Longs over the flat-array JNI surface (see
 * native/src/main/native/mxnet_tpu_jni.cc) instead of wrapper classes fed
 * by per-element JNI callbacks.
 */
object Base {
  type NDArrayHandle = Long
  type FunctionHandle = Long
  type SymbolHandle = Long
  type ExecutorHandle = Long
  type KVStoreHandle = Long
  type OptimizerHandle = Long
  type DataIterHandle = Long

  class MXNetError(val message: String) extends Exception(message)

  private[mxnet_tpu] val _LIB = new LibInfo

  {
    // so files are searched next to the loaded jni library; the path to
    // libmxtpu_capi.so comes from MXNET_TPU_LIBRARY or the default layout
    val lib = sys.env.getOrElse("MXNET_TPU_LIBRARY",
      "mxnet_tpu/libmxtpu_capi.so")
    System.loadLibrary("mxnet_tpu_jni")
    checkCall(_LIB.nativeLibInit(lib))
  }

  def checkCall(ret: Int): Unit = {
    if (ret != 0) {
      throw new MXNetError(_LIB.mxGetLastError())
    }
  }

  def notifyShutdown(): Unit = checkCall(_LIB.mxNotifyShutdown())
}
