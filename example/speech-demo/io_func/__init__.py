"""Kaldi-format feature IO (reference example/speech-demo/io_func/):

- kaldi_io: the byte-level ark/scp format (binary + text archives);
- feat_readers/: per-format readers (kaldi, htk, bvec, atrack) behind a
  common (features, labels) protocol + corpus statistics;
- feat_io: partitioned streaming reads over list files (DataReadStream);
- kaldi_parser / model_io / convert2kaldi: nnet1 text interchange so
  Kaldi's nnet-forward can decode networks trained here.

The higher-level iterators in ../io_util.py consume these archives or
the portable .npz ones."""
from .feat_io import DataReadStream  # noqa: F401
from .feat_readers import FeatureStats, get_reader  # noqa: F401
from .kaldi_io import (read_ark, read_ark_ascii, read_mat,  # noqa: F401
                       read_scp, read_vec, write_ark_ascii,
                       write_ark_scp, write_mat, write_vec)
