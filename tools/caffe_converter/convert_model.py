"""Attach converted weights to a caffe-converted symbol (reference
tools/caffe_converter/convert_model.py capability).

The reference unpacked .caffemodel protobufs; binary protobuf parsing is
out of scope here, so weights come from an .npz whose keys are caffe layer
names mapping to [weight, bias] pairs saved as `<layer>_0` / `<layer>_1`
(the standard caffe-extract convention).  Writes a standard checkpoint
(prefix-symbol.json + prefix-0000.params).
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from convert_symbol import convert_symbol


def convert_model(prototxt, npz_path, prefix):
    net, input_name = convert_symbol(prototxt)
    blobs = np.load(npz_path)
    arg_params = {}
    for key in blobs.files:
        if key.endswith("_0"):
            arg_params[key[:-2] + "_weight"] = mx.nd.array(blobs[key])
        elif key.endswith("_1"):
            arg_params[key[:-2] + "_bias"] = mx.nd.array(blobs[key])
    known = set(net.list_arguments())
    arg_params = {k: v for k, v in arg_params.items() if k in known}
    mx.model.save_checkpoint(prefix, 0, net, arg_params, {})
    print("saved %s-symbol.json and %s-0000.params (%d arrays)"
          % (prefix, prefix, len(arg_params)))
    return net, arg_params


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("prototxt")
    parser.add_argument("npz", help="caffe blobs exported as npz")
    parser.add_argument("prefix", help="output checkpoint prefix")
    args = parser.parse_args()
    convert_model(args.prototxt, args.npz, args.prefix)


if __name__ == "__main__":
    main()
