"""Rank selection (reference tools/accnn/rank_selection.py: DP over layers
maximizing accuracy proxy under a FLOPs budget).

Per-layer spectral-energy proxy: the loss of truncating to rank r is the
discarded squared singular mass; pick the smallest ranks whose combined
FLOPs meet `--ratio` while distributing energy loss evenly (waterfilling
over the sorted spectra — the DP of the reference collapses to this under
the additive-energy model)."""
import numpy as np


def layer_flops(node, weight_shape):
    if node["op"] == "Convolution":
        cout, cin, kh, kw = weight_shape
        return cout * cin * kh * kw
    n, m = weight_shape
    return n * m


def decomposed_flops(node, weight_shape, rank):
    if node["op"] == "Convolution":
        cout, cin, kh, kw = weight_shape
        return rank * (cin * kh * kw + cout)
    n, m = weight_shape
    return rank * (n + m)


def select_ranks(layers, ratio):
    """layers: [(node, weight ndarray)] -> {name: rank}.

    Greedy waterfilling: repeatedly drop the singular value with the
    smallest energy-per-FLOP-saved until total decomposed FLOPs <=
    original/ratio."""
    spectra = {}
    ranks = {}
    budget = 0
    for node, W in layers:
        mat = W.asnumpy().reshape(W.shape[0], -1)
        s = np.linalg.svd(mat, compute_uv=False)
        spectra[node["name"]] = (node, W.shape, s ** 2)
        ranks[node["name"]] = len(s)
        budget += layer_flops(node, W.shape)
    target = budget / float(ratio)

    def total():
        return sum(decomposed_flops(n, shp, ranks[name])
                   for name, (n, shp, _) in spectra.items())

    while total() > target:
        best, best_cost = None, None
        for name, (node, shp, energy) in spectra.items():
            r = ranks[name]
            if r <= 1:
                continue
            saved = (decomposed_flops(node, shp, r)
                     - decomposed_flops(node, shp, r - 1))
            cost = energy[r - 1] / max(saved, 1)
            if best_cost is None or cost < best_cost:
                best, best_cost = name, cost
        if best is None:
            break
        ranks[best] -= 1
    return ranks
