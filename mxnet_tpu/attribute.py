"""AttrScope: with-scope symbol attributes. Reference: python/mxnet/attribute.py.

Attributes like ``ctx_group`` (model parallel placement), ``lr_mult``,
``wd_mult``, ``force_mirroring`` (remat) attach to symbols created inside the
scope — the mechanism the reference uses to drive device placement
(graph_executor.cc AssignContext) and memonger.  Here they drive sharding /
jax.checkpoint policies.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope"]


class AttrScope:
    """Attribute manager for scoping (reference attribute.py:10-62)."""

    _current = threading.local()

    def __init__(self, **kwargs):
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be a string")
        self._attr = kwargs
        self._old_scope = None

    def get(self, attr):
        """Merge user-supplied attr dict with the scope's attributes."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    @classmethod
    def current(cls) -> "AttrScope":
        cur = getattr(cls._current, "value", None)
        if cur is None:
            cur = AttrScope()
            cls._current.value = cur
        return cur

    def __enter__(self):
        self._old_scope = AttrScope.current()
        attr = self._old_scope._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope is not None
        AttrScope._current.value = self._old_scope
