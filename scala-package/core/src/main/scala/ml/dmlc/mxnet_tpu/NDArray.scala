package ml.dmlc.mxnet_tpu

import ml.dmlc.mxnet_tpu.Base._

/**
 * Imperative n-dimensional array over the C ABI (reference NDArray.scala).
 * Arithmetic dispatches through the registered function table
 * (MXListFunctions / MXFuncInvoke), the same registry the R and C++
 * bindings drive; data moves as flat float arrays in one JNI crossing.
 */
class NDArray private[mxnet_tpu](private[mxnet_tpu] val handle: NDArrayHandle,
                                 val writable: Boolean = true)
    extends Serializable {

  def shape: Shape = {
    val s = _LIB.mxNDArrayGetShape(handle)
    require(s != null, _LIB.mxGetLastError())
    Shape(s.toSeq)
  }

  def size: Int = shape.product

  def context: Context = {
    val out = new Array[Int](2)
    checkCall(_LIB.mxNDArrayGetContext(handle, out))
    new Context(if (out(0) == 1) "cpu" else "tpu", out(1))
  }

  def toArray: Array[Float] = {
    val data = new Array[Float](size)
    checkCall(_LIB.mxNDArraySyncCopyToCPU(handle, data, data.length))
    data
  }

  def toScalar: Float = {
    require(size == 1, "array is not a scalar")
    toArray(0)
  }

  def set(values: Array[Float]): NDArray = {
    require(writable, "array is not writable")
    checkCall(_LIB.mxNDArraySyncCopyFromCPU(handle, values, values.length))
    this
  }

  def set(value: Float): NDArray = set(Array.fill(size)(value))

  def slice(begin: Int, end: Int): NDArray = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxNDArraySlice(handle, begin, end, out))
    new NDArray(out(0), writable)
  }

  def at(idx: Int): NDArray = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxNDArrayAt(handle, idx, out))
    new NDArray(out(0), writable)
  }

  def reshape(dims: Shape): NDArray = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxNDArrayReshape(handle, dims.toArray, out))
    new NDArray(out(0), writable)
  }

  def copyTo(other: NDArray): NDArray = {
    // identity through the registry (this + 0 -> other); the registry has
    // no separate _copyto: cross-device movement is the executor's job
    NDArray.invoke("_plus_scalar", Array(this), Array(other), Array(0f))
    other
  }

  def copy(): NDArray = copyTo(NDArray.empty(shape, context))

  def waitToRead(): Unit = checkCall(_LIB.mxNDArrayWaitToRead(handle))

  def +(other: NDArray): NDArray = NDArray.binary("_plus", this, other)
  def -(other: NDArray): NDArray = NDArray.binary("_minus", this, other)
  def *(other: NDArray): NDArray = NDArray.binary("_mul", this, other)
  def /(other: NDArray): NDArray = NDArray.binary("_div", this, other)
  def +(s: Float): NDArray = NDArray.scalarOp("_plus_scalar", this, s)
  def -(s: Float): NDArray = NDArray.scalarOp("_minus_scalar", this, s)
  def *(s: Float): NDArray = NDArray.scalarOp("_mul_scalar", this, s)
  def /(s: Float): NDArray = NDArray.scalarOp("_div_scalar", this, s)

  def +=(other: NDArray): NDArray = {
    NDArray.invoke("_plus", Array(this, other), Array(this)); this
  }
  def -=(other: NDArray): NDArray = {
    NDArray.invoke("_minus", Array(this, other), Array(this)); this
  }
  def *=(other: NDArray): NDArray = {
    NDArray.invoke("_mul", Array(this, other), Array(this)); this
  }
  def /=(other: NDArray): NDArray = {
    NDArray.invoke("_div", Array(this, other), Array(this)); this
  }

  def unary_- : NDArray = this * -1f

  def dtype: Int = {
    val out = new Array[Int](1)
    checkCall(_LIB.mxNDArrayGetDType(handle, out))
    out(0)
  }

  // registry names carry the SimpleOp underscore prefix (_sqrt etc.)
  def sqrt: NDArray = NDArray.unary("_sqrt", this)
  def square: NDArray = NDArray.unary("_square", this)
  def exp: NDArray = NDArray.unary("_exp", this)
  def log: NDArray = NDArray.unary("_log", this)
  def abs: NDArray = NDArray.unary("_abs", this)
  def sign: NDArray = NDArray.unary("_sign", this)

  /** Scalar-valued reductions computed on device, read back as Float
   * (reference NDArray.scala sum/max/min/norm). */
  def sum: Float = NDArray.reduceToScalar("sum", this)
  def max: Float = NDArray.reduceToScalar("max", this)
  def min: Float = NDArray.reduceToScalar("min", this)
  def norm: Float = NDArray.reduceToScalar("norm", this)

  /** Self-describing raw bytes (MXNDArraySaveRawBytes framing): the
   * cross-process / RDD-shuffle serialization format. */
  def serialize(): Array[Byte] = {
    val bytes = _LIB.mxNDArraySaveRawBytes(handle)
    require(bytes != null, _LIB.mxGetLastError())
    bytes
  }

  def dispose(): Unit = checkCall(_LIB.mxNDArrayFree(handle))
}

object NDArray {
  private lazy val functions: Map[String, FunctionHandle] = {
    val handles = _LIB.mxListFunctions()
    require(handles != null, _LIB.mxGetLastError())
    handles.map(h => _LIB.mxFuncGetName(h) -> h).toMap
  }

  private[mxnet_tpu] def invoke(name: String, useVars: Array[NDArray],
                                mutateVars: Array[NDArray],
                                scalars: Array[Float] = Array.empty): Unit = {
    val fn = functions.getOrElse(name,
      throw new MXNetError(s"unknown ndarray function $name"))
    checkCall(_LIB.mxFuncInvoke(fn, useVars.map(_.handle), scalars,
                                mutateVars.map(_.handle)))
  }

  private def binary(name: String, lhs: NDArray, rhs: NDArray): NDArray = {
    val out = empty(lhs.shape, lhs.context)
    invoke(name, Array(lhs, rhs), Array(out))
    out
  }

  private def scalarOp(name: String, lhs: NDArray, s: Float): NDArray = {
    val out = empty(lhs.shape, lhs.context)
    invoke(name, Array(lhs), Array(out), Array(s))
    out
  }

  private[mxnet_tpu] def unary(name: String, src: NDArray): NDArray = {
    val out = empty(src.shape, src.context)
    invoke(name, Array(src), Array(out))
    out
  }

  private[mxnet_tpu] def reduceToScalar(name: String,
                                        src: NDArray): Float = {
    val out = empty(Shape(1), src.context)
    invoke(name, Array(src), Array(out))
    out.toScalar
  }

  /** 2D matrix product through the registry (reference NDArray.dot). */
  def dot(lhs: NDArray, rhs: NDArray): NDArray = {
    require(lhs.shape.length == 2 && rhs.shape.length == 2,
            "dot expects 2D inputs")
    val out = empty(Shape(lhs.shape(0), rhs.shape(1)), lhs.context)
    invoke("dot", Array(lhs, rhs), Array(out))
    out
  }

  def maximum(lhs: NDArray, rhs: NDArray): NDArray =
    binary("_maximum", lhs, rhs)
  def minimum(lhs: NDArray, rhs: NDArray): NDArray =
    binary("_minimum", lhs, rhs)
  def power(lhs: NDArray, rhs: NDArray): NDArray =
    binary("_power", lhs, rhs)

  /** Elementwise clip (reference clip(src, a_min, a_max)). */
  def clip(src: NDArray, aMin: Float, aMax: Float): NDArray = {
    val out = empty(src.shape, src.context)
    invoke("clip", Array(src), Array(out), Array(aMin, aMax))
    out
  }

  /** One-hot rows from an index vector (reference onehotEncode). */
  def onehotEncode(indices: NDArray, out: NDArray): NDArray = {
    invoke("onehot_encode", Array(indices), Array(out))
    out
  }

  /** Row-wise argmax (reference argmaxChannel). */
  def argmaxChannel(src: NDArray): NDArray = {
    val out = empty(Shape(src.shape(0)), src.context)
    invoke("argmax_channel", Array(src), Array(out))
    out
  }

  /** Stack along dim 0 via slice-assignment (reference concatenate). */
  def concatenate(arrays: Seq[NDArray]): NDArray = {
    require(arrays.nonEmpty, "nothing to concatenate")
    val tail = arrays.head.shape.drop(1)
    val rows = arrays.map(_.shape(0)).sum
    require(arrays.forall(_.shape.drop(1) == tail),
            "concatenate needs matching trailing dims")
    val out = empty(Shape(rows +: tail.toVector), arrays.head.context)
    var at = 0
    for (a <- arrays) {
      a.copyTo(out.slice(at, at + a.shape(0)))
      at += a.shape(0)
    }
    out
  }

  /** Inverse of NDArray.serialize(). */
  def deserialize(bytes: Array[Byte]): NDArray = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxNDArrayLoadFromRawBytes(bytes, out))
    new NDArray(out(0))
  }

  def empty(shape: Shape, ctx: Context = Context.defaultCtx): NDArray = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxNDArrayCreateEx(shape.toArray, ctx.deviceTypeid,
                                     ctx.deviceId, 0, 0, out))
    new NDArray(out(0))
  }

  def zeros(shape: Shape, ctx: Context = Context.defaultCtx): NDArray =
    empty(shape, ctx).set(0f)

  def ones(shape: Shape, ctx: Context = Context.defaultCtx): NDArray =
    empty(shape, ctx).set(1f)

  def array(values: Array[Float], shape: Shape,
            ctx: Context = Context.defaultCtx): NDArray =
    empty(shape, ctx).set(values)

  def waitall(): Unit = checkCall(_LIB.mxNDArrayWaitAll())

  def save(fname: String, arrays: Map[String, NDArray]): Unit = {
    val (names, handles) = arrays.toSeq.unzip
    checkCall(_LIB.mxNDArraySave(fname, handles.map(_.handle).toArray,
                                 names.toArray))
  }

  def load(fname: String): Map[String, NDArray] = {
    val out2 = new Array[AnyRef](2)
    checkCall(_LIB.mxNDArrayLoad(fname, out2))
    val handles = out2(0).asInstanceOf[Array[Long]]
    val names = out2(1).asInstanceOf[Array[String]]
    // a list-style save carries no names: key positionally rather than
    // silently dropping every array (zip would truncate to the shorter)
    val keys = if (names.length == handles.length) names
               else handles.indices.map(_.toString).toArray
    keys.zip(handles.map(new NDArray(_))).toMap
  }
}
