# Weight initializers (reference R-package/R/initializer.R): shared
# name-pattern rules (bias/beta/moving_mean zero, gamma/moving_var one)
# with the scheme deciding weight draws.  An initializer is a closure
# (name, shape) -> array.

.mx.init.weight <- function(init, name, shape) {
  if (grepl("bias$|beta$|moving_mean$", name)) {
    array(0, dim = shape)
  } else if (grepl("gamma$|moving_var$", name)) {
    array(1, dim = shape)
  } else {
    init(name, shape)
  }
}

mx.init.uniform <- function(scale = 0.07) {
  function(name, shape) {
    .mx.init.weight(function(n, s)
      array(runif(prod(s), -scale, scale), dim = s), name, shape)
  }
}

mx.init.normal <- function(sd = 0.01) {
  function(name, shape) {
    .mx.init.weight(function(n, s)
      array(rnorm(prod(s), sd = sd), dim = s), name, shape)
  }
}

mx.init.Xavier <- function(rnd_type = "uniform", factor_type = "avg",
                           magnitude = 3) {
  function(name, shape) {
    .mx.init.weight(function(n, s) {
      # R shapes are column-major reversed: fan.out is the LAST dim
      fan.out <- s[[length(s)]]
      fan.in <- prod(s) / fan.out
      factor <- switch(factor_type,
                       avg = (fan.in + fan.out) / 2,
                       `in` = fan.in,
                       out = fan.out,
                       stop("bad factor_type: ", factor_type))
      scale <- sqrt(magnitude / factor)
      if (rnd_type == "uniform") {
        array(runif(prod(s), -scale, scale), dim = s)
      } else {
        array(rnorm(prod(s), sd = scale), dim = s)
      }
    }, name, shape)
  }
}

# Initialize every non-input argument of a symbol from inferred shapes.
mx.init.create <- function(initializer, symbol, input.shapes) {
  inferred <- do.call(mx.symbol.infer.shape,
                      c(list(symbol), input.shapes))
  params <- list()
  for (n in arguments.MXSymbol(symbol)) {
    if (n %in% names(input.shapes)) next
    params[[n]] <- initializer(n, inferred$arg.shapes[[n]])
  }
  params
}
