"""Benchmark: ResNet-50 training throughput, images/sec on one TPU chip.

North star (BASELINE.json): match MXNet-CUDA per-chip ResNet-class training
throughput. In-repo baseline: ImageNet Inception-BN b512 on 4x TitanX =
2,495 s/epoch => ~128 img/s/GPU (BASELINE.md, derived).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import sys
import time

import numpy as np

BASELINE_IMG_S_PER_CHIP = 128.0  # MXNet-CUDA TitanX img/s/GPU (BASELINE.md)


def build_step(batch, compute_dtype="bfloat16"):
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import make_mesh, DPTrainStep
    from __graft_entry__ import _resnet_prog

    net, prog, params, aux, data, label = _resnet_prog(
        [3, 4, 6, 3], [64, 256, 512, 1024, 2048], 1000, (3, 224, 224), batch)
    mesh = make_mesh([("dp", 1)], devices=jax.devices()[:1])
    cdt = jnp.bfloat16 if compute_dtype == "bfloat16" else None
    step = DPTrainStep(net, mesh, learning_rate=0.1, momentum=0.9,
                       weight_decay=1e-4, rescale_grad=1.0 / batch,
                       compute_dtype=cdt)
    state = step.init(params, aux)
    sharded = step.shard_batch({"data": data, "softmax_label": label})
    return step, state, sharded


def run(batch, warmup=5, iters=50):
    import jax
    step, state, batch_data = build_step(batch)
    for _ in range(warmup):
        state, outs = step(state, batch_data)
    jax.block_until_ready((state, outs))
    t0 = time.perf_counter()
    for _ in range(iters):
        state, outs = step(state, batch_data)
    jax.block_until_ready((state, outs))
    dt = time.perf_counter() - t0
    return batch * iters / dt


def main():
    import jax
    value = None
    for batch in (512, 256, 128, 64, 32):
        try:
            value = run(batch)
            break
        except Exception as e:  # OOM etc: halve the batch
            sys.stderr.write("bench: batch %d failed (%s)\n" % (batch, e))
    if value is None:
        print(json.dumps({"metric": "resnet50_train_throughput_per_chip",
                          "value": 0.0, "unit": "images/sec",
                          "vs_baseline": 0.0}))
        return
    print(json.dumps({
        "metric": "resnet50_train_throughput_per_chip",
        "value": round(value, 2),
        "unit": "images/sec",
        "vs_baseline": round(value / BASELINE_IMG_S_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    main()
