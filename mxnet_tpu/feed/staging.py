"""Host->device staging: double-buffer the next batch's H2D transfer
under the current train step.

:class:`DevicePrefetchIter` wraps any DataIter and keeps ``depth``
batches in flight: each ``next()`` first tops the window up by pulling
host batches and issuing ``jax.device_put`` for them (async — the call
returns before the DMA completes), then hands out the OLDEST in-flight
batch, whose transfer has had a full step's worth of time to finish.
When the wrapped module runs the fused train step, batches are staged
directly into its batch sharding, so ``FusedTrainStep.make_batch``
recognizes the resident arrays and passes them through without a second
transfer (donation-friendly: the program reads the input buffers in the
layout it compiled for).  On CPU backends ``device_put`` is a cheap copy
and the wrapper degrades to plain lookahead overlap.

``Module.fit(..., prefetch_to_device=True)`` wires this in automatically
(base_module.py); :func:`device_feed` is the manual entry point.
"""
from __future__ import annotations

import time
from collections import deque
from typing import Optional

from .stats import PipelineStats

__all__ = ["DevicePrefetchIter", "device_feed"]


class DevicePrefetchIter:
    """DataIter wrapper: async-stage ``depth`` batches ahead on device.

    Instrumented like a pipeline stage: the ``h2d`` stats row counts
    staged images and the time spent issuing transfers; ``stall_in``
    accumulates time blocked waiting on the wrapped (host) iterator —
    i.e. how long the chip-side consumer was starved by the host
    pipeline.
    """

    def __init__(self, data_iter, sharding=None, module=None, depth: int = 2,
                 name: str = "device_feed"):
        assert depth >= 1
        self._iter = data_iter
        self._module = module
        self._sharding = sharding
        self._depth = depth
        self._pending = deque()
        self._exhausted = False
        self.stats = PipelineStats(name).register()
        self._h2d = self.stats.stage("h2d")
        self.batch_size = getattr(data_iter, "batch_size", 0)

    # -- DataIter surface -------------------------------------------------
    @property
    def provide_data(self):
        return self._iter.provide_data

    @property
    def provide_label(self):
        return self._iter.provide_label

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def reset(self):
        self._pending.clear()
        self._exhausted = False
        self._iter.reset()

    def next(self):
        self._fill()
        if not self._pending:
            raise StopIteration
        return self._pending.popleft()

    def iter_next(self):
        self._fill()
        return bool(self._pending)

    # -- staging ----------------------------------------------------------
    def _resolve_sharding(self):
        if self._sharding is not None:
            return self._sharding
        if self._module is not None:
            fused = getattr(self._module, "_fused", None)
            if fused is not None:
                return fused.batched_sharding()
        return None

    def _fill(self):
        while not self._exhausted and len(self._pending) < self._depth:
            t0 = time.perf_counter()
            try:
                batch = self._iter.next()
            except StopIteration:
                self._exhausted = True
                return
            self._h2d.add_stall_in(time.perf_counter() - t0)
            self._pending.append(self._stage(batch))

    def _stage(self, batch):
        import jax
        from ..io import DataBatch
        from ..ndarray import NDArray
        sh = self._resolve_sharding()
        t0 = time.perf_counter()

        def put(arr):
            a = arr._get() if isinstance(arr, NDArray) else arr
            if sh is not None:
                if getattr(a, "sharding", None) == sh:
                    return arr if isinstance(arr, NDArray) else NDArray(a)
                return NDArray(jax.device_put(a, sh))
            return NDArray(jax.device_put(a))
        data = [put(a) for a in (batch.data or [])]
        label = [put(a) for a in (batch.label or [])]
        n = data[0].shape[0] if data else 0
        self._h2d.add_items(int(n), time.perf_counter() - t0)
        return DataBatch(data=data, label=label, pad=batch.pad,
                         index=batch.index,
                         provide_data=getattr(batch, "provide_data", None),
                         provide_label=getattr(batch, "provide_label", None))


def device_feed(data_iter, module=None, sharding=None, depth: int = 2):
    """Wrap ``data_iter`` so batches arrive pre-staged on device.

    ``module``: resolve the sharding lazily from the module's fused train
    step (call AFTER init_optimizer); ``sharding``: explicit NamedSharding
    override; neither: stage to the default device (still overlaps the
    transfer — the CPU/plain path)."""
    return DevicePrefetchIter(data_iter, sharding=sharding, module=module,
                              depth=depth)
