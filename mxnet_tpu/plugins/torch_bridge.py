"""Torch plugin parity: call torch functions/modules on NDArrays.

Reference: plugin/torch (TorchModule/TorchCriterion wrap Lua Torch) +
python/mxnet/torch.py sugar.  Here the bridge targets PyTorch (CPU build
baked into the image): tensors round-trip host-side; inside compiled graphs
use mxnet_tpu.operator custom ops instead.
"""
from __future__ import annotations

from typing import Callable

import numpy as np

from ..base import MXNetError
from ..ndarray import NDArray, array as nd_array

__all__ = ["to_torch", "from_torch", "torch_function", "TorchModule",
           "TorchCriterion"]


def _torch():
    try:
        import torch
        return torch
    except ImportError as e:  # pragma: no cover
        raise MXNetError("pytorch is not available") from e


def to_torch(arr: NDArray):
    """NDArray -> torch.Tensor (host copy)."""
    return _torch().from_numpy(arr.asnumpy())


def from_torch(tensor, ctx=None) -> NDArray:
    """torch.Tensor -> NDArray."""
    return nd_array(tensor.detach().cpu().numpy(), ctx=ctx)


def torch_function(fn: Callable):
    """Wrap a torch function so it maps NDArray -> NDArray
    (reference python/mxnet/torch.py generated wrappers)."""
    def wrapped(*args, **kwargs):
        conv = [to_torch(a) if isinstance(a, NDArray) else a for a in args]
        out = fn(*conv, **kwargs)
        if isinstance(out, (list, tuple)):
            return [from_torch(o) for o in out]
        return from_torch(out)
    wrapped.__name__ = getattr(fn, "__name__", "torch_fn")
    return wrapped


class TorchModule:
    """Run a torch.nn.Module as a forward/backward block on NDArrays
    (reference plugin/torch torch_module-inl.h capability)."""

    def __init__(self, module):
        self.module = module

    def forward(self, *inputs: NDArray):
        torch = _torch()
        tins = [to_torch(x).requires_grad_(True) for x in inputs]
        self._tins = tins
        self._tout = self.module(*tins)
        return from_torch(self._tout)

    def backward(self, out_grad: NDArray):
        self._tout.backward(to_torch(out_grad))
        return [from_torch(t.grad) for t in self._tins]

    def parameters(self):
        return [from_torch(p) for p in self.module.parameters()]


class TorchCriterion(TorchModule):
    """Torch loss wrapper (reference TorchCriterion)."""

    def forward(self, data: NDArray, label: NDArray):
        torch = _torch()
        tin = to_torch(data).requires_grad_(True)
        self._tins = [tin]
        self._tout = self.module(tin, to_torch(label)).reshape(1)
        return from_torch(self._tout)
