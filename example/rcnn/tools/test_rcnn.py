"""Stage tool: evaluate the Fast R-CNN head alone on saved proposals.

Capability parity with reference example/rcnn/tools/test_rcnn.py:1
(there: HAS_RPN=False eval over precomputed/selective-search rois) —
classification + regression quality isolated from proposal quality:
the rcnn stage classifies the SAVED proposal set, so a weak RPN cannot
mask (or be masked by) the head.

  python tools/test_rcnn.py --prefix /tmp/rcnn2 --epoch 8 \
      --proposals /tmp/props_test.npz --map-gate 0.4
"""
from common import base_parser, setup, test_set


def main():
    ap = base_parser("evaluate the Fast R-CNN head on saved proposals")
    ap.add_argument("--prefix", required=True)
    ap.add_argument("--epoch", type=int, required=True)
    ap.add_argument("--proposals", required=True,
                    help="npz over the TEST set (tools/test_rpn.py "
                         "--proposals … --on-test-set)")
    ap.add_argument("--map-gate", type=float, default=0.0)
    args = ap.parse_args()
    mx, cfg, ctx = setup(args)

    import logging

    import numpy as np

    from rcnn.detector import Detector
    from rcnn.tester import (eval_detections, load_proposals,
                             load_rcnn_test)

    _, arg_params, aux_params = mx.model.load_checkpoint(args.prefix,
                                                         args.epoch)
    rcnn = load_rcnn_test(cfg, arg_params, aux_params, ctx=ctx)
    proposals = load_proposals(args.proposals,
                               expect_images=args.test_images,
                               expect_seed=args.test_seed)
    det = Detector(None, rcnn, cfg)

    all_dets, annotations = {}, {}
    for i, (img, gt_boxes, gt_classes) in enumerate(test_set(cfg, args)):
        annotations[i] = (gt_boxes, gt_classes)
        props, mask, _ = proposals[i]
        for cls, rows in det.classify_rois(
                img, np.asarray(props, np.float32), img_id=i,
                mask=np.asarray(mask, np.float32)).items():
            all_dets.setdefault(cls, []).extend(rows)
    aps, mean_ap = eval_detections(all_dets, annotations, cfg.num_classes)
    for cls, ap_v in sorted(aps.items()):
        logging.info("class %d AP = %.4f", cls, ap_v)
    print("mAP=%.4f" % mean_ap)
    if args.map_gate:
        assert mean_ap >= args.map_gate, \
            "mAP gate failed: %.4f < %.2f" % (mean_ap, args.map_gate)
        print("PASSED")


if __name__ == "__main__":
    main()
