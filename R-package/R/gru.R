# GRU builders (reference R-package/R/gru.R): update/reset-gated cell
# unrolled like lstm.R, weights created once and shared across time.

mx.gru.param <- function(param.prefix, layeridx = 0) {
  nm <- function(part) sprintf("%s_l%d_%s", param.prefix, layeridx, part)
  list(i2h.w = mx.symbol.Variable(nm("i2h_weight")),
       i2h.b = mx.symbol.Variable(nm("i2h_bias")),
       h2h.w = mx.symbol.Variable(nm("h2h_weight")),
       h2h.b = mx.symbol.Variable(nm("h2h_bias")),
       i2hc.w = mx.symbol.Variable(nm("i2hc_weight")),
       i2hc.b = mx.symbol.Variable(nm("i2hc_bias")),
       h2hc.w = mx.symbol.Variable(nm("h2hc_weight")),
       h2hc.b = mx.symbol.Variable(nm("h2hc_bias")))
}

mx.gru.cell <- function(num.hidden, indata, prev.h, param, param.prefix,
                        layeridx = 0, seqidx = 0) {
  nm <- function(part) sprintf("%s_l%d_%s_t%d", param.prefix, layeridx,
                               part, seqidx)
  i2h <- mx.symbol.internal.create("FullyConnected", list(
    data = indata, weight = param$i2h.w, bias = param$i2h.b,
    num_hidden = num.hidden * 2, name = nm("i2h")))
  h2h <- mx.symbol.internal.create("FullyConnected", list(
    data = prev.h, weight = param$h2h.w, bias = param$h2h.b,
    num_hidden = num.hidden * 2, name = nm("h2h")))
  gates <- mx.symbol.internal.create("ElementWiseSum", list(
    i2h, h2h, name = nm("gates")))
  sliced <- mx.symbol.internal.create("SliceChannel", list(
    data = gates, num_outputs = 2, axis = 1, name = nm("slice")))
  update.gate <- mx.symbol.internal.create("Activation", list(
    data = .mx.symbol.pick(sliced, 0), act_type = "sigmoid",
    name = nm("z")))
  reset.gate <- mx.symbol.internal.create("Activation", list(
    data = .mx.symbol.pick(sliced, 1), act_type = "sigmoid",
    name = nm("r")))
  # candidate: htrans = tanh(W x + U (r * h))
  i2h.c <- mx.symbol.internal.create("FullyConnected", list(
    data = indata, weight = param$i2hc.w, bias = param$i2hc.b,
    num_hidden = num.hidden, name = nm("i2hc")))
  h2h.c <- mx.symbol.internal.create("FullyConnected", list(
    data = reset.gate * prev.h, weight = param$h2hc.w,
    bias = param$h2hc.b, num_hidden = num.hidden, name = nm("h2hc")))
  h.trans <- mx.symbol.internal.create("Activation", list(
    data = i2h.c + h2h.c, act_type = "tanh", name = nm("cand")))
  prev.h + update.gate * (h.trans - prev.h)
}

mx.gru <- function(seq.len, num.hidden, num.label) {
  param <- mx.gru.param("gru")
  mx.rnn.buildgraph(
    function(xt, h, t) mx.gru.cell(num.hidden, xt, h, param, "gru",
                                   seqidx = t),
    seq.len, num.label, prefix = "gru")
}
