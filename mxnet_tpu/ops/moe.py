"""MoE ops: ``_moe_dispatch`` / ``_moe_expert_ffn`` / ``_moe_combine``.

The symbol-level surface of ``mxnet_tpu.moe`` (ISSUE 19).  The routing
math and the expert-buffer scatter/gather live in ``moe.router`` /
``moe.dispatch`` — these ops only bind them into the graph, the same
split ``_sparse_embedding`` keeps with ``embed.sparse``.  All shapes
are static per routing geometry (tokens, experts, k, capacity), so the
fused train step and the decode engine compile each geometry once.

``_moe_dispatch`` is multi-output: the ``(E, C, D)`` buffer plus the
combine weights/slots, the load-balance aux loss (wrap it in
``MakeLoss`` to train the router — ``moe.layer.with_aux_loss``), and
the per-expert accepted counts (stop-gradient; a metric/stats head).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ..base import MXNetError
from .registry import OpDef, Param, register_op

_ACTS = ["relu", "tanh", "sigmoid", "softrelu", "identity"]


def _act(name):
    import jax
    return {"relu": jax.nn.relu, "tanh": jnp.tanh,
            "sigmoid": jax.nn.sigmoid, "softrelu": jax.nn.softplus,
            "identity": lambda x: x}[name]


@register_op("_moe_dispatch", hint="moe_dispatch")
class MoEDispatchOp(OpDef):
    """Route ``data`` (T, D) by ``logits`` (T, E) into the capacity-
    bucketed expert buffer (E, C, D).  C is static:
    ``moe.router.resolve_capacity(capacity_factor, T, E, k)``;
    ``capacity_factor <= 0`` means no dropping (C = T).  Overflowed
    token-choices fold to the out-of-range sentinel slot ``E*C`` and
    drop on the scatter — an expert's rows are never corrupted
    (``moe.dispatch``, the scatter choke point)."""
    params = [Param("num_experts", int, required=True),
              Param("k", int, default=2),
              Param("capacity_factor", float, default=0.0),
              Param("renormalize", bool, default=False)]

    def list_arguments(self, p):
        return ["data", "logits"]

    def list_outputs(self, p):
        return ["dispatched", "weight", "slot", "aux", "counts", "hits"]

    def _cap(self, p, T):
        from ..moe.router import resolve_capacity
        return resolve_capacity(p.capacity_factor, T, p.num_experts, p.k)

    def infer_shape(self, p, in_shapes):
        d, lg = in_shapes
        if d is None:
            return in_shapes, [None] * 6, []
        if len(d) != 2:
            raise MXNetError("_moe_dispatch: data must be (tokens, dim), "
                             "got %r" % (d,))
        T, D = d
        E, k = p.num_experts, p.k
        if k < 1 or k > E:
            raise MXNetError("_moe_dispatch: k=%d outside [1, %d]" % (k, E))
        if lg is not None and tuple(lg) != (T, E):
            raise MXNetError("_moe_dispatch: logits must be (%d, %d), "
                             "got %r" % (T, E, lg))
        C = self._cap(p, T)
        return [d, (T, E)], \
            [(E, C, D), (T, k), (T, k), (1,), (E,), (T, E)], []

    def infer_type(self, p, in_types):
        t = in_types[0] if in_types[0] is not None else np.dtype(np.float32)
        f32 = np.dtype(np.float32)
        return [t, f32], \
            [t, f32, np.dtype(np.int32), f32, f32, f32], []

    def forward(self, p, inputs, aux, ctx):
        from ..moe.dispatch import dispatch as _dispatch
        from ..moe.router import route as _route
        x, logits = inputs
        T = x.shape[0]
        C = self._cap(p, T)
        plan = _route(logits, p.k, C, renormalize=p.renormalize)
        buf = _dispatch(x, plan.slot, p.num_experts, C)
        return [buf, plan.weight, plan.slot,
                plan.aux.reshape(1), plan.counts, plan.hits]


@register_op("_moe_expert_ffn", hint="moe_experts")
class MoEExpertFFNOp(OpDef):
    """Per-expert 2-layer FFN over the dispatched buffer: for each
    expert ``e``, ``act(x[e] @ w1[e] + b1[e]) @ w2[e] + b2[e]`` as two
    batched einsums — the stacked weights (E, D, H)/(E, H, O) are what
    an ``ep``-axis ``__sharding__`` attr shards row-wise, exactly like
    a row-sharded embedding table."""
    params = [Param("num_hidden", int, required=True),
              Param("output_dim", int, default=0),
              Param("act_type", str, default="relu", enum=_ACTS),
              Param("no_bias", bool, default=False)]

    def list_arguments(self, p):
        # *_weight / *_bias suffixes keep auto-created variables on the
        # initializer's name-pattern dispatch (the RNN op's convention)
        if p.no_bias:
            return ["data", "i2h_weight", "h2o_weight"]
        return ["data", "i2h_weight", "i2h_bias", "h2o_weight", "h2o_bias"]

    def infer_shape(self, p, in_shapes):
        d = in_shapes[0]
        if d is None:
            return in_shapes, [None], []
        if len(d) != 3:
            raise MXNetError("_moe_expert_ffn: data must be (experts, "
                             "capacity, dim), got %r" % (d,))
        E, C, D = d
        H = p.num_hidden
        O = p.output_dim or D
        if p.no_bias:
            shapes = [d, (E, D, H), (E, H, O)]
        else:
            shapes = [d, (E, D, H), (E, H), (E, H, O), (E, O)]
        return shapes, [(E, C, O)], []

    def forward(self, p, inputs, aux, ctx):
        act = _act(p.act_type)
        if p.no_bias:
            x, w1, w2 = inputs
            h = act(jnp.einsum("ecd,edh->ech", x, w1))
            return [jnp.einsum("ech,eho->eco", h, w2)]
        x, w1, b1, w2, b2 = inputs
        h = act(jnp.einsum("ecd,edh->ech", x, w1) + b1[:, None, :])
        return [jnp.einsum("ech,eho->eco", h, w2) + b2[:, None, :]]


@register_op("_moe_combine", hint="moe_combine")
class MoECombineOp(OpDef):
    """Gather expert outputs (E, C, O) back to token order (T, O),
    weighted by the routing plan's combine weights.  The sentinel slot
    reads zero (clip-gather + explicit mask in ``moe.dispatch.combine``)
    so dropped tokens contribute exactly nothing."""
    params = []

    def list_arguments(self, p):
        return ["data", "weight", "slot"]

    def infer_shape(self, p, in_shapes):
        d, w, s = in_shapes
        if d is None or (w is None and s is None):
            return in_shapes, [None], []
        if len(d) != 3:
            raise MXNetError("_moe_combine: data must be (experts, "
                             "capacity, dim), got %r" % (d,))
        tk = w if w is not None else s
        E, C, O = d
        return [d, tuple(tk), tuple(tk)], [(tk[0], O)], []

    def infer_type(self, p, in_types):
        t = in_types[0] if in_types[0] is not None else np.dtype(np.float32)
        return [t, np.dtype(np.float32), np.dtype(np.int32)], [t], []

    def forward(self, p, inputs, aux, ctx):
        from ..moe.dispatch import combine as _combine
        x, weight, slot = inputs
        E, C = x.shape[0], x.shape[1]
        return [_combine(x, slot, weight, E, C)]
