/*
 * Minimal mock of the R C API surface that R-package/src/mxnet_glue.c
 * consumes — just enough to EXECUTE the glue in this image (which has
 * no R installation) against the real libmxtpu_capi.so.  The real
 * build path is `R CMD SHLIB mxnet_glue.c`; this header exists so the
 * test suite can prove the glue's marshalling end-to-end anyway.
 *
 * SEXPs are heap-allocated tagged records; allocations are leaked (the
 * test process is short-lived, like R's GC arena would reclaim them).
 */
#ifndef MXTPU_TESTS_RMOCK_H_
#define MXTPU_TESTS_RMOCK_H_

#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef long R_xlen_t;

#ifndef TRUE
#define TRUE 1
#define FALSE 0
#endif

typedef struct sexp_rec {
  int type; /* 0 nil, 1 int, 2 real, 3 str, 4 vec, 5 charsxp, 6 extptr */
  long len;
  int *ints;
  double *reals;
  struct sexp_rec **elts; /* vec elements or str charsxps */
  char *chars;            /* charsxp payload */
  void *ptr;              /* extptr payload */
  void (*fin)(struct sexp_rec *);
} *SEXP;

#define NILSXP 0
#define INTSXP 1
#define REALSXP 2
#define STRSXP 3
#define VECSXP 4

static struct sexp_rec rmock_nil = {0, 0, NULL, NULL, NULL, NULL, NULL, NULL};
#define R_NilValue (&rmock_nil)

static SEXP rmock_new(int type, long len) {
  SEXP s = (SEXP)calloc(1, sizeof(struct sexp_rec));
  s->type = type;
  s->len = len;
  if (type == INTSXP) s->ints = (int *)calloc(len ? len : 1, sizeof(int));
  if (type == REALSXP)
    s->reals = (double *)calloc(len ? len : 1, sizeof(double));
  if (type == STRSXP || type == VECSXP)
    s->elts = (SEXP *)calloc(len ? len : 1, sizeof(SEXP));
  return s;
}

static SEXP Rf_allocVector(int type, long len) { return rmock_new(type, len); }
static int LENGTH(SEXP s) { return (int)s->len; }
static long XLENGTH(SEXP s) { return s->len; }
static int *INTEGER(SEXP s) { return s->ints; }
static double *REAL(SEXP s) { return s->reals; }
static SEXP VECTOR_ELT(SEXP s, long i) { return s->elts[i]; }
static void SET_VECTOR_ELT(SEXP s, long i, SEXP v) { s->elts[i] = v; }
static SEXP STRING_ELT(SEXP s, long i) { return s->elts[i]; }
static void SET_STRING_ELT(SEXP s, long i, SEXP v) { s->elts[i] = v; }
static const char *CHAR(SEXP s) { return s->chars; }

static SEXP Rf_mkChar(const char *c) {
  SEXP s = rmock_new(5, (long)strlen(c));
  s->chars = (char *)malloc(strlen(c) + 1);
  memcpy(s->chars, c, strlen(c) + 1);
  return s;
}

static SEXP Rf_mkString(const char *c) {
  SEXP s = rmock_new(STRSXP, 1);
  s->elts[0] = Rf_mkChar(c);
  return s;
}

static SEXP Rf_ScalarInteger(int v) {
  SEXP s = rmock_new(INTSXP, 1);
  s->ints[0] = v;
  return s;
}

static SEXP Rf_ScalarReal(double v) {
  SEXP s = rmock_new(REALSXP, 1);
  s->reals[0] = v;
  return s;
}

static double Rf_asReal(SEXP s) {
  if (s->type == REALSXP) return s->reals[0];
  if (s->type == INTSXP) return (double)s->ints[0];
  return 0.0;
}

static int Rf_asInteger(SEXP s) {
  if (s->type == INTSXP) return s->ints[0];
  if (s->type == REALSXP) return (int)s->reals[0];
  fprintf(stderr, "rmock: asInteger on type %d\n", s->type);
  exit(1);
}

static int Rf_isNull(SEXP s) { return s == R_NilValue || s->type == NILSXP; }

static void Rf_error(const char *fmt, ...) {
  va_list ap;
  va_start(ap, fmt);
  fprintf(stderr, "rmock Rf_error: ");
  vfprintf(stderr, fmt, ap);
  fprintf(stderr, "\n");
  va_end(ap);
  exit(1);
}

static char *R_alloc(size_t n, int size) {
  return (char *)calloc(n ? n : 1, (size_t)size);
}

#define PROTECT(x) (x)
#define UNPROTECT(n) ((void)(n))

static SEXP R_MakeExternalPtr(void *p, SEXP tag, SEXP prot) {
  (void)tag;
  (void)prot;
  SEXP s = rmock_new(6, 0);
  s->ptr = p;
  return s;
}
static void *R_ExternalPtrAddr(SEXP s) { return s->ptr; }
static void R_ClearExternalPtr(SEXP s) { s->ptr = NULL; }
static void R_RegisterCFinalizerEx(SEXP s, void (*fin)(SEXP), int onexit) {
  (void)onexit;
  s->fin = fin;
}

/* registration stubs */
typedef void *DL_FUNC;
typedef struct {
  const char *name;
  DL_FUNC fun;
  int numArgs;
} R_CallMethodDef;
typedef struct DllInfo DllInfo;
static void R_registerRoutines(DllInfo *dll, const void *a,
                               const R_CallMethodDef *b, const void *c,
                               const void *d) {
  (void)dll; (void)a; (void)b; (void)c; (void)d;
}
static void R_useDynamicSymbols(DllInfo *dll, int v) { (void)dll; (void)v; }

#endif /* MXTPU_TESTS_RMOCK_H_ */
