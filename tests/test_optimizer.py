"""Optimizer update-rule tests vs numpy references (reference
python/mxnet/optimizer.py formulas; reference had no dedicated optimizer
unit suite — trainings covered it — but the rules are worth pinning)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt


def _step(optimizer, w0, g, steps=1, index=0):
    weight = mx.nd.array(w0.copy())
    state = optimizer.create_state(index, weight)
    for _ in range(steps):
        optimizer.update(index, weight, mx.nd.array(g), state)
    return weight.asnumpy(), state


def test_sgd_plain_and_momentum():
    w0 = np.array([1.0, -2.0, 3.0], np.float32)
    g = np.array([0.5, 0.5, -1.0], np.float32)
    # plain: w -= lr*(g + wd*w)
    got, _ = _step(opt.SGD(learning_rate=0.1, wd=0.01), w0, g)
    assert np.allclose(got, w0 - 0.1 * (g + 0.01 * w0), atol=1e-6)
    # momentum, two steps: mom = m*mom - lr*g - lr*wd*w ; w += mom
    o = opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.0)
    got, _ = _step(o, w0, g, steps=2)
    w, mom = w0.copy(), np.zeros_like(w0)
    for _ in range(2):
        mom = 0.9 * mom - 0.1 * g
        w = w + mom
    assert np.allclose(got, w, atol=1e-6)


def test_sgd_rescale_and_clip():
    w0 = np.array([1.0, 1.0], np.float32)
    g = np.array([10.0, -10.0], np.float32)
    o = opt.SGD(learning_rate=0.1, wd=0.0, rescale_grad=0.5,
                clip_gradient=2.0)
    got, _ = _step(o, w0, g)
    eff = np.clip(g * 0.5, -2.0, 2.0)
    assert np.allclose(got, w0 - 0.1 * eff, atol=1e-6)


def test_nag():
    w0 = np.array([1.0, -1.0], np.float32)
    g = np.array([0.2, 0.4], np.float32)
    o = opt.NAG(learning_rate=0.1, momentum=0.9, wd=0.0)
    got, _ = _step(o, w0, g, steps=2)
    w, mom = w0.copy(), np.zeros_like(w0)
    for _ in range(2):
        mom = 0.9 * mom + g
        w = w - 0.1 * (0.9 * mom + g)
    assert np.allclose(got, w, atol=1e-6)


def test_adam():
    w0 = np.array([1.0, -1.0], np.float32)
    g = np.array([0.3, 0.6], np.float32)
    o = opt.Adam(learning_rate=0.01, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 wd=0.0)
    got, _ = _step(o, w0, g, steps=3)
    w = w0.copy()
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    for t in range(1, 4):
        lr_t = 0.01 * np.sqrt(1 - 0.999 ** t) / (1 - 0.9 ** t)
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        w = w - lr_t * m / (np.sqrt(v) + 1e-8)
    assert np.allclose(got, w, atol=1e-6)


def test_adagrad():
    w0 = np.array([2.0, -2.0], np.float32)
    g = np.array([0.5, 1.0], np.float32)
    o = opt.AdaGrad(learning_rate=0.1, wd=0.0, eps=1e-7)
    got, _ = _step(o, w0, g, steps=2)
    w = w0.copy()
    h = np.zeros_like(w)
    for _ in range(2):
        h += g * g
        w = w - 0.1 * g / np.sqrt(h + 1e-7)
    assert np.allclose(got, w, atol=1e-6)


def test_rmsprop_graves():
    w0 = np.array([1.0, 1.0], np.float32)
    g = np.array([0.4, -0.2], np.float32)
    o = opt.RMSProp(learning_rate=0.05, gamma1=0.95, gamma2=0.9, wd=0.0)
    got, _ = _step(o, w0, g, steps=2)
    w = w0.copy()
    n = np.zeros_like(w); gb = np.zeros_like(w); d = np.zeros_like(w)
    for _ in range(2):
        n = 0.05 * g * g + 0.95 * n
        gb = 0.05 * g + 0.95 * gb
        d = 0.9 * d - 0.05 * g / np.sqrt(n - gb * gb + 1e-4)
        w = w + d
    assert np.allclose(got, w, atol=1e-6)


def test_adadelta():
    w0 = np.array([1.0, -1.0], np.float32)
    g = np.array([0.3, 0.3], np.float32)
    o = opt.AdaDelta(rho=0.9, epsilon=1e-5, wd=0.0)
    got, _ = _step(o, w0, g, steps=2)
    w = w0.copy()
    ag = np.zeros_like(w); ad = np.zeros_like(w)
    for _ in range(2):
        ag = 0.9 * ag + 0.1 * g * g
        cur = np.sqrt(ad + 1e-5) / np.sqrt(ag + 1e-5) * g
        ad = 0.9 * ad + 0.1 * cur * cur
        w = w - cur
    assert np.allclose(got, w, atol=1e-6)


def test_wd_mult_naming_rule():
    """bias/gamma/beta get wd=0 by naming rule (reference optimizer.py)."""
    o = opt.SGD(learning_rate=0.1, wd=0.5)
    o.idx2name = {0: "fc_weight", 1: "fc_bias", 2: "bn_gamma"}
    w0 = np.array([1.0], np.float32)
    g = np.array([0.0], np.float32)
    got_w, _ = _step(o, w0, g, index=0)
    assert np.allclose(got_w, w0 - 0.1 * 0.5 * w0)     # decayed
    got_b, _ = _step(o, w0, g, index=1)
    assert np.allclose(got_b, w0)                       # bias: wd 0
    got_g, _ = _step(o, w0, g, index=2)
    assert np.allclose(got_g, w0)                       # gamma: wd 0


def test_lr_scheduler_in_optimizer():
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    o = opt.SGD(learning_rate=0.1, wd=0.0, lr_scheduler=sched)
    o.lr_scheduler.base_lr = 0.1
    w0 = np.array([1.0], np.float32)
    g = np.array([1.0], np.float32)
    weight = mx.nd.array(w0)
    state = o.create_state(0, weight)
    deltas = []
    prev = w0[0]
    for _ in range(5):
        o.update(0, weight, mx.nd.array(g), state)
        cur = weight.asnumpy()[0]
        deltas.append(prev - cur)
        prev = cur
    # lr halves every 2 updates: 0.1, 0.1, 0.05, 0.05, 0.025
    assert np.allclose(deltas, [0.1, 0.1, 0.05, 0.05, 0.025], atol=1e-6), deltas


def test_create_and_get_updater():
    o = opt.create("sgd", learning_rate=0.2)
    assert isinstance(o, opt.SGD) and abs(o.lr - 0.2) < 1e-9
    upd = opt.get_updater(opt.SGD(learning_rate=0.1, wd=0.0))
    w = mx.nd.array(np.array([1.0], np.float32))
    upd(0, mx.nd.array(np.array([0.5], np.float32)), w)
    assert np.allclose(w.asnumpy(), [0.95])
