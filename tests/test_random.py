"""Random tests. Modeled on reference tests/python/unittest/test_random.py."""
import numpy as np

import mxnet_tpu as mx


def test_uniform_basic():
    mx.random.seed(42)
    a = mx.random.uniform(-1, 1, shape=(1000,))
    v = a.asnumpy()
    assert v.min() >= -1 and v.max() < 1
    assert abs(v.mean()) < 0.1


def test_normal_basic():
    mx.random.seed(42)
    a = mx.random.normal(3, 2, shape=(10000,))
    v = a.asnumpy()
    assert abs(v.mean() - 3) < 0.1
    assert abs(v.std() - 2) < 0.1


def test_seed_determinism():
    mx.random.seed(7)
    a = mx.random.uniform(shape=(10,)).asnumpy()
    mx.random.seed(7)
    b = mx.random.uniform(shape=(10,)).asnumpy()
    assert np.allclose(a, b)
    c = mx.random.uniform(shape=(10,)).asnumpy()
    assert not np.allclose(b, c)


def test_out_param():
    out = mx.nd.zeros((50,))
    mx.random.uniform(10, 11, out=out)
    v = out.asnumpy()
    assert v.min() >= 10 and v.max() < 11


def test_initializers():
    for init, check in [
            (mx.init.Uniform(0.1), lambda v: np.abs(v).max() <= 0.1),
            (mx.init.Normal(0.1), lambda v: abs(v.mean()) < 0.05),
            (mx.init.Xavier(), lambda v: np.isfinite(v).all()),
            (mx.init.Orthogonal(), lambda v: np.isfinite(v).all()),
            (mx.init.MSRAPrelu(), lambda v: np.isfinite(v).all())]:
        arr = mx.nd.zeros((16, 16))
        init("fc_weight", arr)
        assert check(arr.asnumpy()), init
    arr = mx.nd.zeros((16,))
    mx.init.Uniform()("fc_bias", arr)
    assert (arr.asnumpy() == 0).all()
    mx.init.Uniform()("bn_gamma", arr)
    assert (arr.asnumpy() == 1).all()
