# lint: allow-file(raw-env) — DMLC_* rendezvous vars are the
# launcher-owned wire protocol (reference ps-lite semantics:
# set-vs-unset matters, missing required vars must KeyError loudly)
"""Host-side parameter server for ``dist_async`` training.

Reference: src/kvstore/kvstore_dist.h (worker), kvstore_dist_server.h
(server), ps-lite roles (include/mxnet/kvstore.h:157-206 env config:
DMLC_ROLE / DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT / DMLC_NUM_WORKER /
DMLC_NUM_SERVER).

TPU-native stance (SURVEY §2.4): synchronous data-parallel training rides
XLA collectives and has NO server processes — but asynchronous SGD
("dist_async": the server applies each worker's push immediately, workers
read stale weights, kvstore_dist_server.h:194-202) has no ICI analogue; it
is fundamentally a host-side service.  So the async path keeps the
reference's process architecture — scheduler + S servers + W workers —
re-built on stdlib TCP (multiprocessing.connection replaces the ZeroMQ
van), with the same capability surface:

* key -> server placement: small keys by ``(key*9973) % num_servers``,
  big arrays striped contiguously across ALL servers above
  MXNET_KVSTORE_BIGARRAY_BOUND (reference kvstore_dist.h:230-268).
* per-worker push-then-pull ordering per key: both ride one FIFO TCP
  connection per (worker, server), the analogue of the reference's
  merge-buffer Var ordering (kvstore_dist.h:79-137).
* server-side optimizer shipped as a pickled python object via the command
  channel (reference kvstore.py:231-254 + kvstore_dist_server.h controller).
* barrier via the scheduler (reference ps::Postoffice::Barrier).

The TPU itself never appears on the server: servers hold numpy arrays in
host RAM and apply updates with the pure-python optimizer — exactly the
reference's CPU-side server executor.
"""
from __future__ import annotations

import logging
import os
import pickle
import threading
import zlib
from multiprocessing.connection import Client, Listener

import numpy as np

from .base import get_env, make_lock

__all__ = ["Scheduler", "PSServer", "PSWorkerClient", "run_scheduler",
           "run_server", "bigarray_bound", "key_to_server", "stripe_ranges"]

def _authkey() -> bytes:
    """Per-job connection secret. multiprocessing.connection deserializes
    pickles from any authenticated peer, so a source-code constant would be
    remote code execution for anyone who can reach a non-loopback listener.
    tools/launch.py generates DMLC_PS_AUTHKEY and passes it to every role;
    a job started without the launcher gets a loud single-host default."""
    key = os.environ.get("DMLC_PS_AUTHKEY")
    if key:
        return key.encode()
    local = ("127.0.0.1", "localhost")  # "" binds all interfaces: not local
    # servers bind DMLC_NODE_HOST, the scheduler binds DMLC_PS_ROOT_URI —
    # either being non-loopback exposes a listener
    if (os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1") not in local
            or os.environ.get("DMLC_NODE_HOST", "127.0.0.1") not in local):
        logging.getLogger(__name__).warning(
            "DMLC_PS_AUTHKEY is unset on a non-loopback PS job; peers "
            "authenticate with a well-known default key. Use tools/launch.py "
            "or export a per-job secret, and never expose the PS port.")
    return b"mxnet_tpu_ps_insecure_default"


_AUTHKEY = None  # resolved lazily so the env can be set after import


def _get_authkey():
    global _AUTHKEY
    if _AUTHKEY is None:
        _AUTHKEY = _authkey()
    return _AUTHKEY


def _connect_retry(addr, timeout=None):
    """Dial with retries: roles come up in arbitrary order (each process
    pays the jax import before its listener binds), so clients must retry
    until the rendezvous window closes (reference ps-lite van retries)."""
    import time
    if timeout is None:
        timeout = get_env("MXNET_PS_CONNECT_TIMEOUT", 180.0, float)
    addr = tuple(addr) if isinstance(addr, (list, tuple)) else addr
    deadline = time.monotonic() + timeout
    delay = 0.05
    while True:
        try:
            return Client(addr, authkey=_get_authkey())
        except (ConnectionRefusedError, ConnectionResetError, OSError):
            if time.monotonic() >= deadline:
                raise
            time.sleep(delay)
            delay = min(delay * 2, 1.0)


def _root_addr():
    uri = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
    port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9092"))
    return (uri, port)


def bigarray_bound() -> int:
    """Stripe threshold (reference env MXNET_KVSTORE_BIGARRAY_BOUND)."""
    return get_env("MXNET_KVSTORE_BIGARRAY_BOUND", 1000000, int)


def _key_int(key) -> int:
    if isinstance(key, int):
        return key
    try:
        return int(key)
    except (TypeError, ValueError):
        return zlib.crc32(str(key).encode())


def key_to_server(key, num_servers: int) -> int:
    """Deterministic small-key placement (kvstore_dist.h: (key*9973)%n)."""
    return (_key_int(key) * 9973) % num_servers


def stripe_ranges(size: int, num_servers: int):
    """Contiguous near-equal ranges of a flattened big array, one per
    server (reference GetServerKeyRanges striping)."""
    step = size // num_servers
    ranges = []
    for i in range(num_servers):
        lo = i * step
        hi = (i + 1) * step if i + 1 < num_servers else size
        ranges.append((lo, hi))
    return ranges


# ---------------------------------------------------------------------------
# scheduler: rendezvous + barrier (the ps::Postoffice role)
# ---------------------------------------------------------------------------

class Scheduler:
    """Rendezvous point: servers register their listen address, workers
    fetch the server list and ranks; also implements the worker barrier
    and dead-peer detection.  A role that disconnects WITHOUT sending
    "stop" is dead (TCP EOF fires on any process death, incl. kill -9);
    the scheduler then broadcasts ("abort", reason) to every live role so
    the job fails fast with a clear message instead of hanging (the
    reference job simply hung on node death — SURVEY §5.3)."""

    def __init__(self, num_workers: int, num_servers: int, addr=None):
        self.num_workers = num_workers
        self.num_servers = num_servers
        addr = addr or _root_addr()
        self.listener = Listener(addr, authkey=_get_authkey())
        self.server_addrs = [None] * num_servers
        self._lock = make_lock("ps.scheduler_roster")
        self._servers_ready = threading.Event()
        self._barrier_conns = []
        self._worker_ranks = 0
        self._server_ranks = 0
        # conn -> (role, rank, send-lock); abort broadcast needs both the
        # roster and per-conn write serialization (replies race otherwise)
        self._roster = {}
        self._abort_reason = None

    def serve_forever(self):
        threads = []
        # one connection per role-process; scheduler exits once every worker
        # has sent "stop" and every connection closed.
        conns_expected = self.num_workers + self.num_servers
        accepted = 0
        from multiprocessing import AuthenticationError
        while accepted < conns_expected:
            try:
                conn = self.listener.accept()
            except (AuthenticationError, ConnectionResetError,
                    EOFError) as e:
                # a PER-CONNECTION handshake failure (bad authkey, stray
                # probe, peer killed mid-auth) must not consume a
                # rendezvous slot — keep accepting
                logging.getLogger(__name__).warning(
                    "scheduler: dropped a failed connection handshake "
                    "(%s)", e)
                continue
            except OSError:
                # listener-level failure: closed by _abort, fd
                # exhaustion, ... — accepting again cannot succeed
                break
            accepted += 1
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        try:
            self.listener.close()
        except OSError:
            pass
        if self._abort_reason:
            raise RuntimeError("ps job aborted: %s" % self._abort_reason)

    def _send(self, conn, msg):
        entry = self._roster.get(id(conn))
        lock = entry[2] if entry else make_lock("ps.conn_send")
        try:
            with lock:
                conn.send(msg)
        except (BrokenPipeError, OSError):
            pass

    def _abort(self, reason):
        with self._lock:
            if self._abort_reason is not None:
                return
            self._abort_reason = reason
            self._barrier_conns = []   # their conns are in the roster too
            targets = list(self._roster.values())
        logging.getLogger(__name__).error("aborting ps job: %s", reason)
        self._servers_ready.set()   # unpark reg_worker waiters (they
                                    # re-check _abort_reason after the wait)
        for entry in targets:
            self._send(entry[3], ("abort", reason))
        # unblock serve_forever if the rendezvous never completed
        try:
            self.listener.close()
        except OSError:
            pass

    def _handle(self, conn):
        role, rank = "unknown", -1
        clean_exit = False
        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                kind = msg[0]
                if kind == "reg_server":
                    with self._lock:
                        if self._abort_reason is not None:
                            self._send(conn, ("abort", self._abort_reason))
                            continue
                        rank = self._server_ranks
                        self._server_ranks += 1
                        self.server_addrs[rank] = msg[1]
                        role = "server"
                        self._roster[id(conn)] = (role, rank,
                                                  make_lock("ps.conn_send"), conn)
                        if all(a is not None for a in self.server_addrs):
                            self._servers_ready.set()
                    self._send(conn, ("rank", rank))
                elif kind == "reg_worker":
                    self._servers_ready.wait()   # set by _abort too
                    with self._lock:
                        if self._abort_reason is not None:
                            self._send(conn, ("abort", self._abort_reason))
                            continue
                        rank = self._worker_ranks
                        self._worker_ranks += 1
                        role = "worker"
                        self._roster[id(conn)] = (role, rank,
                                                  make_lock("ps.conn_send"), conn)
                    self._send(conn, ("servers", list(self.server_addrs),
                                      rank))
                elif kind == "barrier":
                    release = []
                    with self._lock:
                        if self._abort_reason is not None:
                            reason = self._abort_reason
                        else:
                            reason = None
                            self._barrier_conns.append(conn)
                            if len(self._barrier_conns) == self.num_workers:
                                release = self._barrier_conns
                                self._barrier_conns = []
                    if reason is not None:
                        self._send(conn, ("abort", reason))
                        continue
                    for c in release:
                        self._send(c, ("barrier_ok",))
                elif kind == "stop":
                    clean_exit = True
                    self._send(conn, ("bye",))
                    return
        finally:
            with self._lock:
                self._roster.pop(id(conn), None)
            if not clean_exit and self._abort_reason is None:
                self._abort("%s rank %d disconnected without stop "
                            "(process died?)" % (role, rank))
            conn.close()


# ---------------------------------------------------------------------------
# server: holds weights, applies updates (kvstore_dist_server.h role)
# ---------------------------------------------------------------------------

class _MainThreadExec:
    """Synchronous executor: handler threads submit closures, the server's
    MAIN thread runs them (reference kvstore_dist_server.h:28-85 Executor —
    "dedicated Executor thread so python updater runs on the RunServer
    thread").  Essential here beyond reference parity: the server loop runs
    while ``import mxnet_tpu`` is still on the main thread's stack
    (kvstore_server import hijack), so any python-level work that can
    trigger an import — unpickling the optimizer, building NDArrays —
    would DEADLOCK on the package import lock if run from a handler
    thread; the main thread holds that lock reentrantly."""

    def __init__(self):
        import queue
        self._q = queue.Queue()

    def exec(self, fn):
        """Submit fn and block until the main thread has run it."""
        done = threading.Event()
        box = {}

        def task():
            try:
                box["result"] = fn()
            except BaseException as e:   # marshal errors to the caller
                box["error"] = e
            done.set()

        self._q.put(task)
        done.wait()
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def run_until(self, stop_event):
        while not stop_event.is_set():
            task = self._q.get()
            if task is None:
                continue
            task()

    def wake(self):
        self._q.put(None)


class PSServer:
    """Async parameter server: ``push`` applies the update IMMEDIATELY per
    worker (stale-weight async SGD, kvstore_dist_server.h:194-202); without
    an updater it accumulates (the default merge ``stored += merged`` that
    the nightly arithmetic test relies on).  All mutations run serialized
    on the main thread via _MainThreadExec; handler threads only do socket
    IO and locked reads."""

    def __init__(self, num_workers: int, root=None):
        self.num_workers = num_workers
        self.store = {}
        self.updater = None
        self._lock = make_lock("ps.server_store")
        self._exec = _MainThreadExec()
        # own listen socket on an ephemeral port
        host = os.environ.get("DMLC_NODE_HOST", "127.0.0.1")
        self.listener = Listener((host, 0), authkey=_get_authkey())
        self.addr = self.listener.address
        # register with the scheduler
        sched = _connect_retry(root or _root_addr())
        sched.send(("reg_server", self.addr))
        msg = sched.recv()
        if isinstance(msg, tuple) and msg and msg[0] == "abort":
            # a peer died while we were registering
            raise RuntimeError("ps job aborted by scheduler: %s" % msg[1])
        self.rank = msg[1]
        self._sched = sched

    def serve_forever(self):
        """Run the executor on this (main) thread; accept one connection
        per worker on a helper thread; exit when all workers stopped.  A
        scheduler abort broadcast (dead peer) tears the server down and
        exits with an error instead of waiting on dead workers."""
        stop = threading.Event()
        abort_reason = []

        def acceptor():
            threads = []
            try:
                for _ in range(self.num_workers):
                    conn = self.listener.accept()
                    t = threading.Thread(target=self._handle, args=(conn,),
                                         daemon=True)
                    t.start()
                    threads.append(t)
            except (OSError, EOFError):
                pass   # listener closed by the abort monitor
            for t in threads:
                t.join()
            stop.set()
            self._exec.wake()

        def abort_monitor():
            while not stop.is_set():
                try:
                    if self._sched.poll(0.5):
                        msg = self._sched.recv()
                        if isinstance(msg, tuple) and msg and \
                                msg[0] == "abort":
                            abort_reason.append(msg[1])
                            logging.getLogger(__name__).error(
                                "server rank %d aborting: %s",
                                self.rank, msg[1])
                            stop.set()
                            self._exec.wake()
                            self.listener.close()
                            return
                except (EOFError, OSError):
                    return   # scheduler gone; acceptor/stop path decides

        accept_thread = threading.Thread(target=acceptor, daemon=True)
        accept_thread.start()
        monitor_thread = threading.Thread(target=abort_monitor, daemon=True)
        monitor_thread.start()
        self._exec.run_until(stop)
        if abort_reason:
            raise RuntimeError("ps server rank %d aborted: %s"
                               % (self.rank, abort_reason[0]))
        accept_thread.join()
        monitor_thread.join()
        self.listener.close()
        try:
            self._sched.send(("stop",))
            self._sched.recv()
            self._sched.close()
        except (EOFError, OSError):
            pass

    # the three mutators below always run on the main thread via _exec ------
    def _do_init(self, key, value):
        with self._lock:
            # rank-0 value wins: first init wins, later ignored
            if key not in self.store:
                self.store[key] = np.array(value, copy=True)

    def _apply_push(self, key, value):
        with self._lock:
            stored = self.store.get(key)
            if stored is None:
                # first push before init: treat as init (reference servers
                # lazily create entries on first push)
                self.store[key] = np.array(value, copy=True)
                return
            if self.updater is not None:
                self.updater(key, value, stored)   # in-place on stored
            else:
                stored += value

    def _command(self, head, body):
        """Command channel (reference kvstore_dist_server.h:91-135):
        head 0 carries the pickled optimizer -> become the updater."""
        if head == 0:
            from . import optimizer as opt_mod
            optimizer = pickle.loads(body)
            updater = opt_mod.get_updater(optimizer)

            def np_updater(key, grad, stored):
                from .ndarray import array as nd_array
                w = nd_array(stored)
                updater(_key_int(key), nd_array(grad), w)
                stored[...] = w.asnumpy()

            with self._lock:
                self.updater = np_updater

    def _handle(self, conn):
        try:
            while True:
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    return
                kind = msg[0]
                if kind == "init":
                    _, key, value = msg
                    self._exec.exec(lambda: self._do_init(key, value))
                    conn.send(("init_ok",))
                elif kind == "push":
                    # blocking exec keeps this worker's FIFO ordering while
                    # the worker itself never waits (fire-and-forget send)
                    key, value = msg[1], msg[2]
                    self._exec.exec(lambda: self._apply_push(key, value))
                elif kind == "pull":
                    with self._lock:
                        val = np.array(self.store[msg[1]], copy=True)
                    conn.send(("val", val))
                elif kind == "cmd":
                    head, body = msg[1], msg[2]
                    self._exec.exec(lambda: self._command(head, body))
                    conn.send(("cmd_ok",))
                elif kind == "stop":
                    conn.send(("bye",))
                    return
        finally:
            conn.close()


# ---------------------------------------------------------------------------
# worker-side client
# ---------------------------------------------------------------------------

class PSWorkerClient:
    """One per worker process: connections to the scheduler and to every
    server.  Push is fire-and-forget (no reply) — the python thread never
    blocks on the update, mirroring the reference's async ZPush; ordering
    per (worker, server) is the TCP FIFO."""

    def __init__(self, root=None):
        root = root or _root_addr()
        self._sched = _connect_retry(root)
        self._sched.send(("reg_worker",))
        msg = self._recv(self._sched, "scheduler registration")
        self.server_addrs = msg[1]
        self.rank = int(os.environ.get("DMLC_WORKER_ID", msg[2]))
        self.num_servers = len(self.server_addrs)
        self._conns = [_connect_retry(a) for a in self.server_addrs]
        self._locks = [make_lock("ps.worker_conn") for _ in self._conns]
        self._sched_lock = make_lock("ps.worker_sched")
        self._closed = False
        self._fatal = False
        # the stop handshake distinguishes a clean exit from a death (the
        # scheduler aborts the job on EOF-without-stop).  Most training
        # scripts never call kv.close() themselves (reference parity), so
        # make interpreter exit clean automatically.  atexit also runs
        # after an UNHANDLED EXCEPTION though — that is a crash, and must
        # reach the scheduler as one, so the excepthook marks the process
        # fatal and the handler then skips the handshake (raw EOF ->
        # dead-peer abort).  os._exit / signals skip atexit entirely and
        # are likewise detected as deaths.
        import atexit
        import sys as _sys
        prev_hook = _sys.excepthook

        def _mark_fatal(tp, val, tb):
            self._fatal = True
            prev_hook(tp, val, tb)

        _sys.excepthook = _mark_fatal
        atexit.register(self._atexit_close)

    def _atexit_close(self):
        if self._fatal:
            return   # crashed: let the EOF trigger the scheduler abort
        self.close()

    @staticmethod
    def _recv(conn, what):
        """Bounded recv: a dead server/scheduler turns into a clear error
        instead of an indefinite hang (the reference job simply hung on
        node death, SURVEY §5.3 — we can do better than that).  A
        scheduler-broadcast ("abort", reason) surfaces as RuntimeError."""
        timeout = get_env("MXNET_PS_RECV_TIMEOUT", 600.0, float)
        if not conn.poll(timeout):
            raise RuntimeError(
                "parameter-server RPC timed out after %.0fs waiting for %s "
                "(server process dead? raise MXNET_PS_RECV_TIMEOUT if not)"
                % (timeout, what))
        try:
            msg = conn.recv()
        except (EOFError, OSError) as e:
            raise RuntimeError(
                "parameter-server connection lost while waiting for %s: %s"
                % (what, e))
        if isinstance(msg, tuple) and msg and msg[0] == "abort":
            raise RuntimeError("ps job aborted by scheduler: %s" % msg[1])
        return msg

    def check_abort(self):
        """Poll the scheduler connection for a pending abort broadcast;
        raises RuntimeError if the job is being torn down.  Called from
        the data plane so a worker that never reaches another barrier
        still fails fast when a peer dies."""
        with self._sched_lock:
            if self._sched.poll(0):
                msg = self._sched.recv()
                if isinstance(msg, tuple) and msg and msg[0] == "abort":
                    raise RuntimeError(
                        "ps job aborted by scheduler: %s" % msg[1])

    @staticmethod
    def _send(conn, msg, what):
        """Clean error instead of a raw socket exception when the peer
        is gone (server torn down by a scheduler abort)."""
        try:
            conn.send(msg)
        except (BrokenPipeError, ConnectionResetError, OSError) as e:
            raise RuntimeError(
                "parameter-server connection lost while sending %s: %s"
                % (what, e))

    # -- placement ----------------------------------------------------------
    def _plan(self, key, size):
        """Return [(server, lo, hi)] covering the flattened value."""
        if size >= bigarray_bound() and self.num_servers > 1:
            return [(s, lo, hi) for s, (lo, hi)
                    in enumerate(stripe_ranges(size, self.num_servers))]
        return [(key_to_server(key, self.num_servers), 0, size)]

    # -- data plane ---------------------------------------------------------
    def init(self, key, value: np.ndarray):
        flat = np.ascontiguousarray(value).reshape(-1)
        for s, lo, hi in self._plan(key, flat.size):
            with self._locks[s]:
                self._send(self._conns[s], ("init", key, flat[lo:hi]),
                           "init")
                self._recv(self._conns[s], "init ack")

    def push(self, key, value: np.ndarray):
        self.check_abort()
        flat = np.ascontiguousarray(value).reshape(-1)
        for s, lo, hi in self._plan(key, flat.size):
            with self._locks[s]:
                self._send(self._conns[s], ("push", key, flat[lo:hi]),
                           "push")

    def pull(self, key, shape, dtype) -> np.ndarray:
        size = int(np.prod(shape)) if shape else 1
        out = np.empty(size, dtype)
        for s, lo, hi in self._plan(key, size):
            with self._locks[s]:
                self._send(self._conns[s], ("pull", key), "pull request")
                out[lo:hi] = self._recv(self._conns[s], "pull reply")[1]
        return out.reshape(shape)

    # -- control plane ------------------------------------------------------
    def send_command_to_servers(self, head, body):
        for s in range(self.num_servers):
            with self._locks[s]:
                self._send(self._conns[s], ("cmd", head, body), "command")
                self._recv(self._conns[s], "command ack")

    def barrier(self):
        with self._sched_lock:
            self._send(self._sched, ("barrier",), "barrier request")
            self._recv(self._sched, "barrier release")

    def close(self):
        if self._closed:
            return
        self._closed = True
        for s in range(self.num_servers):
            try:
                with self._locks[s]:
                    self._conns[s].send(("stop",))
                    self._conns[s].recv()
                    self._conns[s].close()
            except (EOFError, OSError):
                pass
        try:
            with self._sched_lock:
                self._sched.send(("stop",))
                self._sched.recv()
                self._sched.close()
        except (EOFError, OSError):
            pass


# ---------------------------------------------------------------------------
# role entry points (invoked from kvstore_server on import, launch.py)
# ---------------------------------------------------------------------------

def _require_env(*names):
    missing = [n for n in names if not os.environ.get(n)]
    if missing:
        raise RuntimeError(
            "parameter-server role needs %s in the environment (set by "
            "tools/launch.py -s N; see docs/multi_node.md)"
            % ", ".join(missing))


def run_scheduler():
    _require_env("DMLC_NUM_WORKER", "DMLC_NUM_SERVER")
    num_workers = int(os.environ["DMLC_NUM_WORKER"])
    num_servers = int(os.environ["DMLC_NUM_SERVER"])
    logging.info("ps scheduler: %d workers, %d servers", num_workers,
                 num_servers)
    Scheduler(num_workers, num_servers).serve_forever()


def run_server():
    _require_env("DMLC_NUM_WORKER")
    num_workers = int(os.environ["DMLC_NUM_WORKER"])
    server = PSServer(num_workers)
    logging.info("ps server rank %d listening on %s", server.rank,
                 server.addr)
    server.serve_forever()
