"""Paired-stream reader for regression tasks (feature-mapping /
denoising AMs whose targets are another feature stream, not labels).

Capability parity with reference
example/speech-demo/io_func/regr_feat_io.py:1: two label-less
DataReadStreams advanced in lockstep — one over the input list, one
over the output list — yielding (input_feats, target_feats) per
utterance, with the same checkpoint get/set_state surface as the
underlying streams.
"""
from .feat_io import DataReadStream


class RegrDataReadStream:
    def __init__(self, input_lst_file, output_lst_file, **stream_kwargs):
        stream_kwargs["has_labels"] = False
        seed = stream_kwargs.setdefault("seed", 0)
        # both streams must shuffle identically to stay paired
        stream_kwargs["seed"] = seed
        self.input = DataReadStream(input_lst_file, **stream_kwargs)
        self.output = DataReadStream(output_lst_file, **stream_kwargs)

    @classmethod
    def from_dataset_args(cls, dataset_args, n_ins=None):
        """Reference-shaped constructor: a dict with input_lst_file /
        output_lst_file keys (reference regr_feat_io.py:14)."""
        args = dict(dataset_args)
        ins = args.pop("input_lst_file")
        outs = args.pop("output_lst_file")
        args.pop("has_labels", None)
        return cls(ins, outs, **args)

    def reset(self):
        self.input.reset()
        self.output.reset()

    def get_state(self):
        return (self.input.get_state(), self.output.get_state())

    def set_state(self, state):
        self.input.set_state(state[0])
        self.output.set_state(state[1])

    def __iter__(self):
        for (in_feats, _), (out_feats, _) in zip(self.input, self.output):
            assert len(in_feats) == len(out_feats), \
                "paired lists out of sync (%d vs %d frames)" % (
                    len(in_feats), len(out_feats))
            yield in_feats, out_feats
