"""Structural passes: constant folding, CSE, dead-node elimination, and
the uint8 wire prologue.

All of them are built on one primitive — ``rebuild(sym, transform)`` — a
single topo walk that clones the reachable graph while a hook substitutes
per-node rewrites.  Every clone copies ``node.attrs`` verbatim, which is
what makes the pipeline's attr-preservation check (``__sharding__`` must
survive) hold by construction.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import _AttrDict
from ..ops import get_op
from ..symbol import Symbol, _Node, _topo
from .pipeline import Pass, PassError, _as_np

__all__ = ["rebuild", "tensor_name", "FoldConstantsPass", "CSEPass",
           "DeadNodeEliminationPass", "U8WirePass"]


def tensor_name(node: _Node, idx: int) -> str:
    """The name of one node output — EXACTLY the formula
    ``Symbol.list_outputs`` uses, so calibration tables (keyed by
    ``get_internals().list_outputs()``) and the quantize pass agree."""
    if node.is_variable:
        return node.name
    names = node.op.list_outputs(node.params)
    return "%s_%s" % (node.name, names[idx])


def rebuild(sym: Symbol,
            transform: Callable[[_Node, List[Tuple[_Node, int]]],
                                Optional[List[Tuple[_Node, int]]]]) -> Symbol:
    """Clone the reachable graph.  ``transform(old_node, new_inputs)``
    returns a replacement ``[(node, out_idx), ...]`` (one entry per old
    output) or None for a plain clone.  The input graph is untouched."""
    out_map: Dict[Tuple[int, int], Tuple[_Node, int]] = {}
    for node in _topo(sym._heads):
        new_inputs = [out_map[(id(i), x)] for (i, x) in node.inputs]
        res = transform(node, new_inputs)
        if res is None:
            new = _Node(node.op, node.name, _AttrDict(node.params),
                        dict(node.attrs), new_inputs, node.is_aux)
            res = [(new, i) for i in range(node.num_outputs())]
        for i, t in enumerate(res):
            out_map[(id(node), i)] = t
    heads = [out_map[(id(n), i)] for (n, i) in sym._heads]
    return Symbol(heads, graph_attrs=sym._graph_attrs)


def _make_node(op_name: str, name: str, params: Dict[str, Any],
               inputs, attrs=None) -> _Node:
    op = get_op(op_name)
    return _Node(op, name, op.parse_params(params), dict(attrs or {}),
                 list(inputs))


# -- constant folding --------------------------------------------------------

# scalar peepholes: (outer, inner) -> combined scalar, same outer op
_SCALAR_CHAINS = {
    ("_mul_scalar", "_mul_scalar"): lambda a, b: a * b,
    ("_div_scalar", "_div_scalar"): lambda a, b: a * b,   # /a/b == /(a*b)
    ("_plus_scalar", "_plus_scalar"): lambda a, b: a + b,
    ("_minus_scalar", "_minus_scalar"): lambda a, b: a + b,
}
# identities: op applied with this scalar is a no-op
_SCALAR_IDENTITY = {"_mul_scalar": 1.0, "_div_scalar": 1.0,
                    "_plus_scalar": 0.0, "_minus_scalar": 0.0}


class FoldConstantsPass(Pass):
    """Constant folding, two legs:

    * **scalar chains** — back-to-back scalar arithmetic collapses
      (``x*a*b`` -> ``x*(a*b)``) and identity scalars (``*1``, ``+0``)
      disappear.  Normalization prologues (mean/scale) reliably produce
      these.
    * **param subgraphs** — with ``params`` available (the deployment
      path always has them), any node whose inputs are ALL parameter
      variables is evaluated host-side ONCE and replaced by a new baked
      parameter (``<node>_folded``).  The reference analogue of Relay's
      FoldConstant: the serve program never recomputes weight-only math
      per request.  RNG ops and aux-carrying ops (BatchNorm) are never
      folded; variables that receive gradients do not exist here (the
      pipeline is inference-side).

    ``transform_params`` re-folds from fresh weights on hot reload.
    """

    name = "fold_constants"

    def __init__(self, fold_params: bool = True, fold_scalars: bool = True):
        super().__init__()
        self.fold_params = fold_params
        self.fold_scalars = fold_scalars
        # [(folded var name, [input var names], node clone)] — replayed
        # against fresh params on reload
        self._folds: List[Tuple[str, List[str], _Node]] = []

    def config(self) -> str:
        return "fold_params=%s;fold_scalars=%s" % (self.fold_params,
                                                   self.fold_scalars)

    def _eval_node(self, node: _Node, params: Dict) -> np.ndarray:
        from ..ops.registry import OpContext
        import jax.numpy as jnp
        ins = [jnp.asarray(_as_np(params[i.name])) for (i, _) in node.inputs]
        outs = node.op.forward(node.params, ins, [], OpContext(is_train=False))
        if isinstance(outs, tuple):
            outs = outs[0]
        return np.asarray(outs[0])

    def apply(self, sym, params):
        folded = scalars = 0
        self._folds = []
        new_params = dict(params) if params is not None else None
        param_names = set(new_params or ())
        consumers: Dict[int, int] = {}
        for n in _topo(sym._heads):
            for (i, _x) in n.inputs:
                consumers[id(i)] = consumers.get(id(i), 0) + 1
        head_ids = {id(n) for (n, _i) in sym._heads}
        folded_names: List[str] = []

        def transform(node, new_inputs):
            nonlocal folded, scalars
            if node.is_variable:
                return None
            opn = node.op.name
            # scalar identity: drop the node entirely
            if self.fold_scalars and opn in _SCALAR_IDENTITY and \
                    float(node.params.get("scalar")) == _SCALAR_IDENTITY[opn]:
                scalars += 1
                return [new_inputs[0]]
            # scalar chain: this node's (already rewritten) input is the
            # same-family scalar op — merge into one
            if self.fold_scalars and new_inputs and not node.is_variable:
                src, src_idx = new_inputs[0]
                key = (opn, None if src.is_variable else src.op.name)
                comb = _SCALAR_CHAINS.get((opn, key[1]))
                if comb is not None and src_idx == 0:
                    a = float(node.params.get("scalar"))
                    b = float(src.params.get("scalar"))
                    scalars += 1
                    merged = _make_node(opn, node.name,
                                        {"scalar": comb(b, a)},
                                        src.inputs, node.attrs)
                    return [(merged, 0)]
            # param-subgraph folding
            if (self.fold_params and new_params is not None
                    and node.inputs
                    and not node.op.needs_rng
                    and not node.op.list_auxiliary_states(node.params)
                    and id(node) not in head_ids
                    and all(i.is_variable and i.name in param_names
                            for (i, _x) in node.inputs)
                    and node.num_outputs() == 1):
                clone = _Node(node.op, node.name, _AttrDict(node.params),
                              dict(node.attrs),
                              [(i, x) for (i, x) in node.inputs])
                try:
                    value = self._eval_node(clone, new_params)
                except Exception:
                    return None       # not host-evaluable: leave in graph
                vname = "%s_folded" % node.name
                new_params[vname] = value
                self._folds.append(
                    (vname, [i.name for (i, _x) in node.inputs], clone))
                folded += 1
                folded_names.append(node.name)
                var = _Node(None, vname, attrs=dict(node.attrs))
                return [(var, 0)]
            return None

        out = rebuild(sym, transform)
        self.summary = {"rewrites": folded + scalars,
                        "params_folded": folded, "scalar_folds": scalars,
                        "folded_nodes": folded_names}
        return out, new_params

    def transform_params(self, params):
        out = dict(params)
        for vname, in_names, node in self._folds:
            if all(n in out for n in in_names):
                out[vname] = self._eval_node(node, out)
        return out


# -- common-subexpression elimination ---------------------------------------

class CSEPass(Pass):
    """Hash-cons the graph bottom-up: two nodes with the same op, params,
    attrs and (already-canonicalized) inputs are one node.  Variables
    unify by name.  The quantize pass leans on this indirectly: duplicate
    ``_contrib_quantize`` nodes for one tensor+scale merge here when the
    pipeline runs CSE after quantization (the default serving pipeline
    dedupes them at insertion anyway)."""

    name = "cse"

    def apply(self, sym, params):
        seen: Dict[Tuple, _Node] = {}
        merged = 0
        merged_names: List[str] = []

        def transform(node, new_inputs):
            nonlocal merged
            if node.is_variable:
                key = ("var", node.name, node.is_aux,
                       tuple(sorted(node.attrs.items())))
            else:
                key = (node.op.name,
                       tuple(sorted((k, repr(v))
                                    for k, v in node.params.items())),
                       tuple(sorted(node.attrs.items())),
                       tuple((id(n), i) for (n, i) in new_inputs))
            rep = seen.get(key)
            if rep is not None:
                merged += 1
                merged_names.append(node.name)
                return [(rep, i) for i in range(node.num_outputs())]
            if node.is_variable:
                new = _Node(None, node.name, attrs=dict(node.attrs),
                            is_aux=node.is_aux)
            else:
                new = _Node(node.op, node.name, _AttrDict(node.params),
                            dict(node.attrs), new_inputs, node.is_aux)
            seen[key] = new
            return [(new, i) for i in range(node.num_outputs())]

        out = rebuild(sym, transform)
        self.summary = {"rewrites": merged, "merged_nodes": merged_names}
        return out, params


# -- dead-node elimination ---------------------------------------------------

# ops that are the identity at inference time: bypassing them changes
# nothing the serve program computes (Dropout's eval path IS the
# identity; BlockGrad only matters to autodiff)
_INFERENCE_IDENTITY = ("Dropout", "BlockGrad")


class DeadNodeEliminationPass(Pass):
    """Remove nodes that contribute nothing to the heads.

    Unreachable nodes never survive a ``rebuild`` walk by construction;
    the measurable work here is bypassing single-input single-output ops
    that are the identity for the compiled program: inference-mode
    ``Dropout`` / ``BlockGrad`` (``for_inference=True`` — the serving
    pipeline's default) — after which anything they alone kept alive is
    unreachable and falls off.  Multi-output nodes and heads are never
    touched."""

    name = "dce"

    def __init__(self, for_inference: bool = True):
        super().__init__()
        self.for_inference = for_inference

    def config(self) -> str:
        return "for_inference=%s" % self.for_inference

    def apply(self, sym, params):
        removed = 0
        removed_names: List[str] = []
        head_ids = {id(n) for (n, _i) in sym._heads}

        def transform(node, new_inputs):
            nonlocal removed
            if (self.for_inference and not node.is_variable
                    and node.op.name in _INFERENCE_IDENTITY
                    and node.num_outputs() == 1
                    and len(node.inputs) == 1
                    and id(node) not in head_ids):
                removed += 1
                removed_names.append(node.name)
                return [new_inputs[0]]
            return None

        out = rebuild(sym, transform)
        self.summary = {"rewrites": removed, "removed_nodes": removed_names}
        return out, params


# -- uint8 wire prologue -----------------------------------------------------

class U8WirePass(Pass):
    """Move the cast/normalize prologue INTO the graph so the wire stays
    uint8 — the serving mirror of PR 6's training-side H2D win.

    The data variable is retyped to uint8 (``__dtype__`` attr, honored
    by the Predictor's type_dict) and, for image inputs, re-laid-out to
    HWC — exactly the envelope ``io.decode_to_hwc_u8`` produces — then
    the graph itself casts to f32, subtracts ``mean``, multiplies
    ``scale`` and transposes to NCHW before the first real op.  A
    request therefore ships H*W*C bytes instead of 4x that, and the
    normalize math runs inside the compiled program.

    ``hwc=True`` inserts the HWC->NCHW transpose (callers feed
    ``(N,H,W,C)`` input shapes); ``hwc=False`` keeps the layout (MLP
    inputs).  ``mean``/``scale`` are scalars folded into scalar ops.
    """

    name = "u8_wire"

    def __init__(self, data_name: str = "data", mean: float = 0.0,
                 scale: float = 1.0, hwc: bool = True):
        super().__init__()
        self.data_name = data_name
        self.mean = float(mean)
        self.scale = float(scale)
        self.hwc = hwc

    def config(self) -> str:
        return "data=%s;mean=%r;scale=%r;hwc=%s" % (
            self.data_name, self.mean, self.scale, self.hwc)

    def apply(self, sym, params):
        if self.data_name not in sym.list_arguments():
            raise PassError("u8_wire: input %r is not an argument of the "
                            "graph (has %s)"
                            % (self.data_name, sym.list_arguments()))
        built: Dict[str, Tuple[_Node, int]] = {}

        def prologue(var: _Node) -> Tuple[_Node, int]:
            # one prologue per data var NODE; CSE merges same-name twins
            if var.name in built:
                return built[var.name]
            attrs = dict(var.attrs)
            attrs["__dtype__"] = "uint8"
            u8var = _Node(None, var.name, attrs=attrs)
            cur: Tuple[_Node, int] = (
                _make_node("Cast", "%s_u8cast" % var.name,
                           {"dtype": "float32"}, [(u8var, 0)]), 0)
            if self.mean != 0.0:
                cur = (_make_node("_minus_scalar", "%s_u8mean" % var.name,
                                  {"scalar": self.mean}, [cur]), 0)
            if self.scale != 1.0:
                cur = (_make_node("_mul_scalar", "%s_u8scale" % var.name,
                                  {"scalar": self.scale}, [cur]), 0)
            if self.hwc:
                cur = (_make_node("transpose", "%s_u8nchw" % var.name,
                                  {"axes": (0, 3, 1, 2)}, [cur]), 0)
            built[var.name] = cur
            return cur

        def transform(node, new_inputs):
            if node.is_variable:
                return None
            rewired = [prologue(i) if i.is_variable
                       and i.name == self.data_name else (i_new)
                       for (i, _x), i_new in zip(node.inputs, new_inputs)]
            if rewired == new_inputs:
                return None
            new = _Node(node.op, node.name, _AttrDict(node.params),
                        dict(node.attrs), rewired, node.is_aux)
            return [(new, i) for i in range(node.num_outputs())]

        out = rebuild(sym, transform)
        self.summary = {"rewrites": len(built),
                        "type_overrides": {self.data_name: "uint8"},
                        "prologue_inputs": sorted(built)}
        return out, params
