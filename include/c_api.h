/*!
 * C ABI of the TPU-native framework.
 *
 * Mirrors the reference surface (include/mxnet/c_api.h, ~110 MX* functions):
 * every handle is opaque, every function returns 0 on success / -1 on error
 * with the message retrievable via MXGetLastError() (thread-local, like
 * src/c_api/c_api_error.cc).  Underneath, calls are forwarded into the
 * embedded CPython interpreter hosting the JAX/XLA runtime — the TPU-native
 * equivalent of the reference forwarding into its C++ core.
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#ifdef __cplusplus
#define MXTPU_EXTERN_C extern "C"
#else
#define MXTPU_EXTERN_C
#endif

#include <stdint.h>
#include <stddef.h>

#define MXTPU_DLL MXTPU_EXTERN_C __attribute__((visibility("default")))

typedef uint32_t mx_uint;
typedef float mx_float;

typedef void *NDArrayHandle;
typedef const void *FunctionHandle;
typedef const void *AtomicSymbolCreator;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *DataIterHandle;
typedef const void *DataIterCreator;
typedef void *KVStoreHandle;
typedef void *RecordIOHandle;
typedef void *RtcHandle;
typedef void *OptimizerHandle;
typedef const void *OptimizerCreator;

/*! \brief user-defined updater for the kvstore (reference c_api.h:66-74) */
typedef void (MXKVStoreUpdater)(int key, NDArrayHandle recv,
                                NDArrayHandle local, void *handle);

/*! \brief per-op monitor callback (reference c_api.h:60-62) */
typedef void (*ExecutorMonitorCallback)(const char *op_name,
                                        NDArrayHandle output, void *handle);

/*! \brief ABI custom-op callback tables (reference c_api.h:96-135).
 * Tag protocol in forward/backward ptr arrays: 0=in_data 1=out_data
 * 2=in_grad 3=out_grad 4=aux; req codes 0=null 1=write 2=inplace 3=add. */
#ifdef __cplusplus
extern "C" {
#endif
struct CustomOpInfo {
  int (*forward)(int /*size*/, void ** /*ptrs*/, int * /*tags*/,
                 const int * /*reqs*/, const int /*is_train*/,
                 void * /*state*/);
  int (*backward)(int /*size*/, void ** /*ptrs*/, int * /*tags*/,
                  const int * /*reqs*/, const int /*is_train*/,
                  void * /*state*/);
  int (*del_)(void * /*state*/);
  void *p_forward;
  void *p_backward;
  void *p_del;
};

struct CustomOpPropInfo {
  int (*list_arguments)(char *** /*args*/, void * /*state*/);
  int (*list_outputs)(char *** /*outputs*/, void * /*state*/);
  int (*infer_shape)(int /*num_input*/, int * /*ndims*/,
                     unsigned ** /*shapes*/, void * /*state*/);
  int (*declare_backward_dependency)(const int * /*out_grad*/,
                                     const int * /*in_data*/,
                                     const int * /*out_data*/,
                                     int * /*num_deps*/, int ** /*rdeps*/,
                                     void * /*state*/);
  int (*create_operator)(const char * /*ctx*/, int /*num_inputs*/,
                         unsigned ** /*shapes*/, int * /*ndims*/,
                         int * /*dtypes*/, struct CustomOpInfo * /*ret*/,
                         void * /*state*/);
  int (*list_auxiliary_states)(char *** /*aux*/, void * /*state*/);
  int (*del_)(void * /*state*/);
  void *p_list_arguments;
  void *p_list_outputs;
  void *p_infer_shape;
  void *p_declare_backward_dependency;
  void *p_create_operator;
  void *p_list_auxiliary_states;
  void *p_del;
};

typedef int (*CustomOpPropCreator)(const char * /*op_type*/,
                                   const int /*num_kwargs*/,
                                   const char ** /*keys*/,
                                   const char ** /*values*/,
                                   struct CustomOpPropInfo * /*ret*/);
#ifdef __cplusplus
}
#endif

/* -------------------- error handling + global -------------------- */
MXTPU_DLL const char *MXGetLastError();
MXTPU_DLL int MXRandomSeed(int seed);
MXTPU_DLL int MXNotifyShutdown();

/* -------------------- NDArray -------------------- */
MXTPU_DLL int MXNDArrayCreateNone(NDArrayHandle *out);
MXTPU_DLL int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              NDArrayHandle *out);
MXTPU_DLL int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim,
                                int dev_type, int dev_id, int delay_alloc,
                                int dtype, NDArrayHandle *out);
/* `size` is the ELEMENT count (reference c_api.h convention, same as
 * MXPredSetInput/MXPredGetOutput); a mismatch with the array size fails. */
MXTPU_DLL int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void *data,
                                       size_t size);
MXTPU_DLL int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     size_t size);
MXTPU_DLL int MXNDArrayWaitToRead(NDArrayHandle handle);
MXTPU_DLL int MXNDArrayWaitToWrite(NDArrayHandle handle);
MXTPU_DLL int MXNDArrayWaitAll();
MXTPU_DLL int MXNDArrayFree(NDArrayHandle handle);
MXTPU_DLL int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                             mx_uint slice_end, NDArrayHandle *out);
MXTPU_DLL int MXNDArrayAt(NDArrayHandle handle, mx_uint idx,
                          NDArrayHandle *out);
MXTPU_DLL int MXNDArrayReshape(NDArrayHandle handle, int ndim, int *dims,
                               NDArrayHandle *out);
MXTPU_DLL int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                                const mx_uint **out_pdata);
MXTPU_DLL int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata);
MXTPU_DLL int MXNDArrayGetDType(NDArrayHandle handle, int *out_dtype);
MXTPU_DLL int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                                  int *out_dev_id);
/* Raw-byte serialization (reference c_api.h:218-230): self-describing
 * little-endian frame (magic, dtype, shape, payload) used by kvstore /
 * cross-process sends.  The returned buffer stays valid until the next
 * pointer-returning MX* call on this thread. */
MXTPU_DLL int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                                    const char **out_buf);
MXTPU_DLL int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                                        NDArrayHandle *out);
MXTPU_DLL int MXNDArraySave(const char *fname, mx_uint num_args,
                            NDArrayHandle *args, const char **keys);
MXTPU_DLL int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                            NDArrayHandle **out_arr, mx_uint *out_name_size,
                            const char ***out_names);

/* -------------------- NDArray function registry -------------------- */
MXTPU_DLL int MXListFunctions(mx_uint *out_size, FunctionHandle **out_array);
MXTPU_DLL int MXGetFunction(const char *name, FunctionHandle *out);
MXTPU_DLL int MXFuncGetInfo(FunctionHandle fun, const char **name,
                            const char **description, mx_uint *num_args,
                            const char ***arg_names, const char ***arg_type_infos,
                            const char ***arg_descriptions);
MXTPU_DLL int MXFuncDescribe(FunctionHandle fun, mx_uint *num_use_vars,
                             mx_uint *num_scalars, mx_uint *num_mutate_vars,
                             int *type_mask);
MXTPU_DLL int MXFuncInvoke(FunctionHandle fun, NDArrayHandle *use_vars,
                           mx_float *scalar_args, NDArrayHandle *mutate_vars);
/* MXFuncInvoke + string keyword params (reference c_api.h:464-470) */
MXTPU_DLL int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle *use_vars,
                             mx_float *scalar_args,
                             NDArrayHandle *mutate_vars, int num_params,
                             char **param_keys, char **param_vals);

/* -------------------- Symbol -------------------- */
MXTPU_DLL int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                               AtomicSymbolCreator **out_array);
MXTPU_DLL int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                          const char **name,
                                          const char **description,
                                          mx_uint *num_args,
                                          const char ***arg_names,
                                          const char ***arg_type_infos,
                                          const char ***arg_descriptions,
                                          const char **key_var_num_args);
/* The creator handle IS the interned op name (reference c_api.h:488). */
MXTPU_DLL int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                          const char **name);
MXTPU_DLL int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator,
                                         mx_uint num_param, const char **keys,
                                         const char **vals, SymbolHandle *out);
MXTPU_DLL int MXSymbolCreateVariable(const char *name, SymbolHandle *out);
MXTPU_DLL int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle *symbols,
                                  SymbolHandle *out);
MXTPU_DLL int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out);
MXTPU_DLL int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out);
MXTPU_DLL int MXSymbolSaveToJSON(SymbolHandle symbol, const char **out_json);
MXTPU_DLL int MXSymbolSaveToFile(SymbolHandle symbol, const char *fname);
MXTPU_DLL int MXSymbolFree(SymbolHandle symbol);
MXTPU_DLL int MXSymbolCopy(SymbolHandle symbol, SymbolHandle *out);
MXTPU_DLL int MXSymbolPrint(SymbolHandle symbol, const char **out_str);
/* Name of a single-output symbol; success=0 for unnamed groups
 * (reference c_api.h:602-604). */
MXTPU_DLL int MXSymbolGetName(SymbolHandle symbol, const char **out,
                              int *success);
MXTPU_DLL int MXSymbolGetAttr(SymbolHandle symbol, const char *key,
                              const char **out, int *success);
MXTPU_DLL int MXSymbolSetAttr(SymbolHandle symbol, const char *key,
                              const char *value);
/* Recursive attribute listing over the whole graph ("node$key" keys) —
 * reference c_api.h:638-646; out holds 2*out_size key/value strings. */
MXTPU_DLL int MXSymbolListAttr(SymbolHandle symbol, mx_uint *out_size,
                               const char ***out);
/* Attributes of this node only (reference c_api.h:653-655). */
MXTPU_DLL int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint *out_size,
                                      const char ***out);
MXTPU_DLL int MXSymbolListArguments(SymbolHandle symbol, mx_uint *out_size,
                                    const char ***out_str_array);
MXTPU_DLL int MXSymbolListOutputs(SymbolHandle symbol, mx_uint *out_size,
                                  const char ***out_str_array);
MXTPU_DLL int MXSymbolListAuxiliaryStates(SymbolHandle symbol,
                                          mx_uint *out_size,
                                          const char ***out_str_array);
MXTPU_DLL int MXSymbolGetInternals(SymbolHandle symbol, SymbolHandle *out);
MXTPU_DLL int MXSymbolGetOutput(SymbolHandle symbol, mx_uint index,
                                SymbolHandle *out);
MXTPU_DLL int MXSymbolCompose(SymbolHandle sym, const char *name,
                              mx_uint num_args, const char **keys,
                              SymbolHandle *args);
MXTPU_DLL int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt,
                           const char **wrt, SymbolHandle *out);
MXTPU_DLL int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                                 const char **keys,
                                 const mx_uint *arg_ind_ptr,
                                 const mx_uint *arg_shape_data,
                                 mx_uint *in_shape_size,
                                 const mx_uint **in_shape_ndim,
                                 const mx_uint ***in_shape_data,
                                 mx_uint *out_shape_size,
                                 const mx_uint **out_shape_ndim,
                                 const mx_uint ***out_shape_data,
                                 mx_uint *aux_shape_size,
                                 const mx_uint **aux_shape_ndim,
                                 const mx_uint ***aux_shape_data,
                                 int *complete);
MXTPU_DLL int MXSymbolInferShapePartial(SymbolHandle sym, mx_uint num_args,
                                        const char **keys,
                                        const mx_uint *arg_ind_ptr,
                                        const mx_uint *arg_shape_data,
                                        mx_uint *in_shape_size,
                                        const mx_uint **in_shape_ndim,
                                        const mx_uint ***in_shape_data,
                                        mx_uint *out_shape_size,
                                        const mx_uint **out_shape_ndim,
                                        const mx_uint ***out_shape_data,
                                        mx_uint *aux_shape_size,
                                        const mx_uint **aux_shape_ndim,
                                        const mx_uint ***aux_shape_data,
                                        int *complete);
MXTPU_DLL int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                                const char **keys, const int *arg_type_data,
                                mx_uint *in_type_size,
                                const int **in_type_data,
                                mx_uint *out_type_size,
                                const int **out_type_data,
                                mx_uint *aux_type_size,
                                const int **aux_type_data, int *complete);

/* -------------------- Executor -------------------- */
MXTPU_DLL int MXExecutorFree(ExecutorHandle handle);
MXTPU_DLL int MXExecutorPrint(ExecutorHandle handle, const char **out_str);
MXTPU_DLL int MXExecutorForward(ExecutorHandle handle, int is_train);
MXTPU_DLL int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                                 NDArrayHandle *head_grads);
MXTPU_DLL int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                                NDArrayHandle **out);
MXTPU_DLL int MXExecutorBind(SymbolHandle symbol_handle, int dev_type,
                             int dev_id, mx_uint len,
                             NDArrayHandle *in_args,
                             NDArrayHandle *arg_grad_store,
                             mx_uint *grad_req_type, mx_uint aux_states_len,
                             NDArrayHandle *aux_states, ExecutorHandle *out);
MXTPU_DLL int MXExecutorBindX(SymbolHandle symbol_handle, int dev_type,
                              int dev_id, mx_uint num_map_keys,
                              const char **map_keys, const int *map_dev_types,
                              const int *map_dev_ids, mx_uint len,
                              NDArrayHandle *in_args,
                              NDArrayHandle *arg_grad_store,
                              mx_uint *grad_req_type, mx_uint aux_states_len,
                              NDArrayHandle *aux_states, ExecutorHandle *out);
/* Per-op output monitor from any frontend (reference c_api.h:991-993);
 * switches the executor to node-level (eager) execution. */
MXTPU_DLL int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                           ExecutorMonitorCallback callback,
                                           void *callback_handle);
MXTPU_DLL int MXExecutorBindEX(SymbolHandle symbol_handle, int dev_type,
                               int dev_id, mx_uint num_map_keys,
                               const char **map_keys, const int *map_dev_types,
                               const int *map_dev_ids, mx_uint len,
                               NDArrayHandle *in_args,
                               NDArrayHandle *arg_grad_store,
                               mx_uint *grad_req_type, mx_uint aux_states_len,
                               NDArrayHandle *aux_states,
                               ExecutorHandle shared_exec,
                               ExecutorHandle *out);

/* -------------------- Data iterators -------------------- */
MXTPU_DLL int MXListDataIters(mx_uint *out_size, DataIterCreator **out_array);
MXTPU_DLL int MXDataIterCreateIter(DataIterCreator handle, mx_uint num_param,
                                   const char **keys, const char **vals,
                                   DataIterHandle *out);
MXTPU_DLL int MXDataIterGetIterInfo(DataIterCreator creator, const char **name,
                                    const char **description,
                                    mx_uint *num_args,
                                    const char ***arg_names,
                                    const char ***arg_type_infos,
                                    const char ***arg_descriptions);
MXTPU_DLL int MXDataIterFree(DataIterHandle handle);
MXTPU_DLL int MXDataIterNext(DataIterHandle handle, int *out);
MXTPU_DLL int MXDataIterBeforeFirst(DataIterHandle handle);
MXTPU_DLL int MXDataIterGetData(DataIterHandle handle, NDArrayHandle *out);
MXTPU_DLL int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle *out);
MXTPU_DLL int MXDataIterGetIndex(DataIterHandle handle, uint64_t **out_index,
                                 uint64_t *out_size);
MXTPU_DLL int MXDataIterGetPadNum(DataIterHandle handle, int *pad);

/* -------------------- KVStore -------------------- */
MXTPU_DLL int MXKVStoreCreate(const char *type, KVStoreHandle *out);
MXTPU_DLL int MXKVStoreFree(KVStoreHandle handle);
MXTPU_DLL int MXKVStoreInit(KVStoreHandle handle, mx_uint num,
                            const int *keys, NDArrayHandle *vals);
MXTPU_DLL int MXKVStorePush(KVStoreHandle handle, mx_uint num,
                            const int *keys, NDArrayHandle *vals,
                            int priority);
MXTPU_DLL int MXKVStorePull(KVStoreHandle handle, mx_uint num,
                            const int *keys, NDArrayHandle *vals,
                            int priority);
MXTPU_DLL int MXKVStoreSetUpdater(KVStoreHandle handle,
                                  MXKVStoreUpdater updater,
                                  void *updater_handle);
MXTPU_DLL int MXKVStoreGetType(KVStoreHandle handle, const char **type);
MXTPU_DLL int MXKVStoreGetRank(KVStoreHandle handle, int *ret);
MXTPU_DLL int MXKVStoreGetGroupSize(KVStoreHandle handle, int *ret);
MXTPU_DLL int MXKVStoreBarrier(KVStoreHandle handle);
MXTPU_DLL int MXKVStoreRunServer(KVStoreHandle handle);
/* (typo'd name kept for ABI parity with the reference, c_api.h) */
MXTPU_DLL int MXKVStoreSendCommmandToServers(KVStoreHandle handle,
                                             int cmd_id, const char *cmd_body);
MXTPU_DLL int MXInitPSEnv(mx_uint num_vars, const char **keys,
                          const char **vals);
/* Process role queries (reference c_api.h:1218-1238): driven by DMLC_ROLE,
 * matching the launcher contract (tools/launch.py / kvstore_server.py). */
MXTPU_DLL int MXKVStoreIsWorkerNode(int *ret);
MXTPU_DLL int MXKVStoreIsServerNode(int *ret);
MXTPU_DLL int MXKVStoreIsSchedulerNode(int *ret);

/* -------------------- RecordIO -------------------- */
MXTPU_DLL int MXRecordIOWriterCreate(const char *uri, RecordIOHandle *out);
MXTPU_DLL int MXRecordIOWriterFree(RecordIOHandle handle);
MXTPU_DLL int MXRecordIOWriterWriteRecord(RecordIOHandle handle,
                                          const char *buf, size_t size);
MXTPU_DLL int MXRecordIOReaderCreate(const char *uri, RecordIOHandle *out);
MXTPU_DLL int MXRecordIOReaderFree(RecordIOHandle handle);
MXTPU_DLL int MXRecordIOReaderReadRecord(RecordIOHandle handle,
                                         char const **buf, size_t *size);

/* -------------------- Rtc (Pallas-backed runtime kernels) -------------------- */
MXTPU_DLL int MXRtcCreate(char *name, mx_uint num_input, mx_uint num_output,
                          char **input_names, char **output_names,
                          NDArrayHandle *inputs, NDArrayHandle *outputs,
                          char *kernel, RtcHandle *out);
MXTPU_DLL int MXRtcPush(RtcHandle handle, mx_uint num_input,
                        mx_uint num_output, NDArrayHandle *inputs,
                        NDArrayHandle *outputs, mx_uint gridDimX,
                        mx_uint gridDimY, mx_uint gridDimZ, mx_uint blockDimX,
                        mx_uint blockDimY, mx_uint blockDimZ);
MXTPU_DLL int MXRtcFree(RtcHandle handle);

/* -------------------- Optimizer -------------------- */
MXTPU_DLL int MXOptimizerFindCreator(const char *key, OptimizerCreator *out);
MXTPU_DLL int MXOptimizerCreateOptimizer(OptimizerCreator creator,
                                         mx_uint num_param, const char **keys,
                                         const char **vals,
                                         OptimizerHandle *out);
MXTPU_DLL int MXOptimizerFree(OptimizerHandle handle);
MXTPU_DLL int MXOptimizerUpdate(OptimizerHandle handle, int index,
                                NDArrayHandle weight, NDArrayHandle grad,
                                mx_float lr, mx_float wd);

/* -------------------- Custom operators -------------------- */
/* Register a frontend-defined operator usable as sym.Custom(op_type=...)
 * (reference c_api.h:1375).  The creator is called once per symbol
 * instantiation; the frontend owns the lifetime of every callback it
 * installs in the returned tables. */
MXTPU_DLL int MXCustomOpRegister(const char *op_type,
                                 CustomOpPropCreator creator);

#endif  /* MXTPU_C_API_H_ */
