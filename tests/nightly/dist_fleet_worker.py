"""One rank of the fleet chaos run (driven by dist.FleetSupervisor).

Trains a deterministic MLP over a dp=2 mesh spanning 2 processes, with
per-step checkpointing and ``resume=True`` — so a fleet that gets one
rank SIGKILL'd (the ``dist.host`` fault point, targeted per-rank via
``MXNET_FAULTS=points=dist.host@rank1,kinds=crash,...``) restarts from
the latest COMMIT and must land on a final global state BITWISE equal
to a fault-free run.  Rank identity, coordinator, and fault attempt all
arrive via env (the supervisor's rendezvous).

Prints ``FLEET_FINAL rank<r> <sha256 of params>`` + ``PASSED``.
"""
import hashlib
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]

import numpy as np

BS = 8          # per-process batch
EPOCHS = 2
N = 64          # rows per process-epoch -> 8 steps/epoch, 16 total


def main():
    ckpt_dir = sys.argv[sys.argv.index("--ckpt") + 1]
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    rank = jax.process_index()

    mx.random.seed(11)
    rng = np.random.RandomState(3)      # same rows everywhere; each
    X = rng.randn(N, 12).astype(np.float32)   # rank feeds its slice by
    y = (X.sum(axis=1) > 0).astype(np.float32)  # construction of the iter
    half = N // 2
    Xl = X[rank * half:(rank + 1) * half] if jax.process_count() > 1 \
        else X
    yl = y[rank * half:(rank + 1) * half] if jax.process_count() > 1 \
        else y
    it = mx.io.NDArrayIter(Xl, yl, batch_size=BS, shuffle=False)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=EPOCHS, kvstore=None,
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            mesh=parallel.make_mesh([("dp", 2)]),
            checkpoint=ckpt_dir, checkpoint_every=1, resume=True)

    arg_params, aux_params = mod.get_params()
    h = hashlib.sha256()
    for n in sorted(arg_params):
        h.update(n.encode())
        h.update(np.ascontiguousarray(arg_params[n].asnumpy()).tobytes())
    for n in sorted(aux_params):
        h.update(n.encode())
        h.update(np.ascontiguousarray(aux_params[n].asnumpy()).tobytes())
    print("FLEET_FINAL rank%d %s" % (rank, h.hexdigest()), flush=True)
    print("dist_fleet_worker rank %d: PASSED" % rank, flush=True)
    if jax.process_count() > 1:
        # exit barrier: a rank tearing down its sockets while the peer
        # is still inside a trailing collective reads as a fleet death
        from jax.experimental import multihost_utils as mhu
        mhu.sync_global_devices("dist_fleet_worker_done")


if __name__ == "__main__":
    main()
