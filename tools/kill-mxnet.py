#!/usr/bin/env python
"""Kill stray training processes on a host list
(reference tools/kill-mxnet.py capability)."""
import argparse
import subprocess
import sys


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("hostfile", help="one host per line; '-' = local only")
    parser.add_argument("--pattern", default="train_", help="pkill -f pattern")
    args = parser.parse_args()
    if args.hostfile == "-":
        subprocess.call(["pkill", "-f", args.pattern])
        return
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()]
    for host in hosts:
        print("killing %s on %s" % (args.pattern, host))
        subprocess.call(["ssh", "-o", "StrictHostKeyChecking=no", host,
                         "pkill -f %s || true" % args.pattern])


if __name__ == "__main__":
    main()
