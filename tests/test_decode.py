"""mxnet_tpu.serve.DecodeEngine: continuous batching for stateful decode
(tier-1, CPU).

Covers the slot engine's contracts: greedy decode parity against a pure
numpy reference (prompt teacher-forcing included), continuous admission
into freed slots (occupancy, all streams complete), eos stop, admission
overload/validation/deadline semantics, client cancel, the drain-barrier
hot reload (no stream ever mixes weight versions — ISSUE 13 satellite),
zero XLA compiles in the steady decode loop, drain vs no-drain shutdown,
and the profiler serve_report decode row.
"""
import os
import sys
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu.serve import (DecodeEngine, ServeClosedError,
                             ServeDeadlineError, ServeError,
                             ServeOverloadError, ServeRequestError)

VOCAB, EMB, HID = 17, 12, 16


def _decode_net():
    """One recurrent decode step: tok -> embed; h' = tanh(W_ih e + W_hh h);
    outputs [logits, h']."""
    tok = mx.sym.Variable("data")
    h = mx.sym.Variable("h")
    emb = mx.sym.Embedding(tok, input_dim=VOCAB, output_dim=EMB,
                           name="emb")
    emb = mx.sym.Flatten(emb)
    z = mx.sym.FullyConnected(emb, num_hidden=HID, name="ih") + \
        mx.sym.FullyConnected(h, num_hidden=HID, name="hh")
    h_next = mx.sym.Activation(z, act_type="tanh")
    logits = mx.sym.FullyConnected(h_next, num_hidden=VOCAB, name="out")
    return mx.sym.Group([logits, h_next])


def _params(seed=0):
    rng = np.random.RandomState(seed)

    def g(*s):
        return (rng.randn(*s) * 0.5).astype(np.float32)

    return {"emb_weight": g(VOCAB, EMB),
            "ih_weight": g(HID, EMB), "ih_bias": np.zeros(HID, np.float32),
            "hh_weight": g(HID, HID), "hh_bias": np.zeros(HID, np.float32),
            "out_weight": g(VOCAB, HID),
            "out_bias": np.zeros(VOCAB, np.float32)}


def _ref_decode(params, prompt, max_new, eos_id=None):
    """Pure numpy greedy decode — the ground truth the engine must hit
    token-for-token."""
    h = np.zeros(HID, np.float32)
    out = []
    toks = [int(t) for t in prompt]
    i = 0
    tok = toks[0]
    while True:
        e = params["emb_weight"][tok]
        h = np.tanh(params["ih_weight"] @ e + params["ih_bias"]
                    + params["hh_weight"] @ h + params["hh_bias"])
        logits = params["out_weight"] @ h + params["out_bias"]
        if i + 1 < len(toks):
            i += 1
            tok = toks[i]
            continue
        tok = int(np.argmax(logits))
        out.append(tok)
        if len(out) >= max_new or (eos_id is not None and tok == eos_id):
            return np.asarray(out, np.int32)


def _engine(params=None, **kw):
    kw.setdefault("num_slots", 4)
    kw.setdefault("name", "test-decode")
    kw.setdefault("state_shapes", {"h": (HID,)})
    return DecodeEngine(_decode_net(),
                        dict(params if params is not None else _params()),
                        **kw)


def _prompts(n, seed=3):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, VOCAB, 1 + rng.randint(0, 3)) for _ in range(n)]


@pytest.fixture(scope="module")
def model():
    params = _params()
    prompts = _prompts(12)
    refs = [_ref_decode(params, p, 8) for p in prompts]
    return params, prompts, refs


def test_decode_parity_and_continuous_admission(model):
    """12 streams through 4 slots: every stream matches the serial numpy
    reference token-for-token (prompts of mixed length teacher-force
    correctly), streams join freed slots (occupancy), all complete."""
    params, prompts, refs = model
    eng = _engine(params)
    try:
        futs = [eng.submit(p, max_new_tokens=8) for p in prompts]
        for i, f in enumerate(futs):
            got = f.result(timeout=60)
            assert np.array_equal(got, refs[i]), \
                "stream %d: %s != %s" % (i, got, refs[i])
        rep = eng.stats.report()
        assert rep["kind"] == "decode" and rep["num_slots"] == 4
        assert rep["completed"] == len(prompts)
        assert rep["failed"] == 0 and rep["expired"] == 0
        # 12 streams x 8+ steps through 4 slots: the loop must have
        # been batching, not serializing
        assert rep["slot_occupancy"] > 0.5, rep
        assert rep["tokens_out"] >= 8 * len(prompts)
        assert rep["queue_depth"] == 0
    finally:
        eng.close()


def test_eos_stops_stream_early(model):
    params, prompts, _ = model
    full = _ref_decode(params, prompts[0], 8)
    eos = int(full[3])      # stop at the 4th generated token
    want = _ref_decode(params, prompts[0], 8, eos_id=eos)
    assert len(want) <= 4
    eng = _engine(params)
    try:
        got = eng.generate(prompts[0], timeout=60, max_new_tokens=8,
                           eos_id=eos)
        assert np.array_equal(got, want)
    finally:
        eng.close()


def test_admission_validation_and_overload(model):
    params = model[0]
    eng = _engine(params, num_slots=1, queue_depth=2,
                  max_new_tokens=64)
    try:
        with pytest.raises(ServeRequestError):
            eng.submit([])                          # empty prompt
        with pytest.raises(ServeRequestError):
            eng.submit(np.zeros((2, 3), np.int32))  # not 1-D
        with pytest.raises(ServeRequestError):
            eng.submit([0.5])                       # non-integral
        with pytest.raises(ServeRequestError):
            eng.submit([1], max_new_tokens=0)
        # one long stream occupies the slot; wait for its admission so
        # the queue state is deterministic, then fill the queue bound —
        # further submits reject fast instead of hanging
        futs = [eng.submit([1], max_new_tokens=64)]
        t0 = time.perf_counter()
        while eng.pending_requests() > 0:
            assert time.perf_counter() - t0 < 10, "stream never admitted"
            time.sleep(0.005)
        futs += [eng.submit([1], max_new_tokens=64) for _ in range(2)]
        t0 = time.perf_counter()
        with pytest.raises(ServeOverloadError):
            for _ in range(8):
                futs.append(eng.submit([2], max_new_tokens=64))
        assert time.perf_counter() - t0 < 1.0, "overload was not fast"
        assert eng.stats.report()["overloaded"] >= 1
        for f in futs:
            f.result(timeout=120)
    finally:
        eng.close()


def test_queue_deadline_expires(model):
    params = model[0]
    eng = _engine(params, num_slots=1)
    try:
        slow = eng.submit([1], max_new_tokens=200)      # hogs the slot
        doomed = eng.submit([2], max_new_tokens=4, deadline_ms=5.0)
        with pytest.raises(ServeDeadlineError):
            doomed.result(timeout=60)
        assert eng.stats.report()["expired"] == 1
        slow.result(timeout=120)
    finally:
        eng.close()


def test_client_cancel_queued_stream(model):
    params, prompts, refs = model
    eng = _engine(params, num_slots=1)
    try:
        hog = eng.submit(prompts[0], max_new_tokens=100)
        queued = [eng.submit(prompts[i], max_new_tokens=4)
                  for i in range(1, 4)]
        cancelled = [f for f in queued if f.cancel()]
        assert cancelled, "no queued stream was cancellable"
        hog.result(timeout=120)
        for f in queued:
            if not f.cancelled():
                f.result(timeout=60)
        # engine not wedged: a fresh stream still serves
        got = eng.generate(prompts[0], timeout=60, max_new_tokens=8)
        assert np.array_equal(got, refs[0])
        assert eng.stats.report()["cancelled"] == len(cancelled)
    finally:
        eng.close()


def test_hot_reload_drain_barrier_no_mixed_weights(model):
    """ISSUE 13 satellite: a slot's token stream must never mix weights
    across a reload.  Under a closed-loop flood a mid-flight reload
    drains the in-flight streams under v1, swaps, and resumes — every
    completed stream matches exactly one weights version end-to-end."""
    params, prompts, _ = model
    params2 = _params(seed=99)
    refs1 = [_ref_decode(params, p, 6) for p in prompts]
    refs2 = [_ref_decode(params2, p, 6) for p in prompts]
    # the two versions must genuinely disagree or the test proves nothing
    assert any(not np.array_equal(a, b) for a, b in zip(refs1, refs2))
    eng = _engine(params)
    results = {}
    errors = []

    def client(t):
        try:
            for j in range(6):
                i = (t * 6 + j) % len(prompts)
                results[(t, j)] = (i, eng.generate(
                    prompts[i], timeout=120, max_new_tokens=6))
        except Exception as e:          # pragma: no cover - fail loud below
            errors.append(e)

    try:
        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        version = eng.reload(dict(params2), timeout=120)    # mid-flood
        for t in threads:
            t.join()
        assert not errors, errors
        assert version == 1 and eng.weights_version == 1
        n_old = n_new = 0
        for i, got in results.values():
            old = np.array_equal(got, refs1[i])
            new = np.array_equal(got, refs2[i])
            assert old or new, \
                "stream %d matches NEITHER version (mixed weights?)" % i
            n_old += old
            n_new += new
        # steady state after the swap serves v2 only
        got = eng.generate(prompts[0], timeout=60, max_new_tokens=6)
        assert np.array_equal(got, refs2[0])
        assert eng.stats.report()["reloads"] == 1
    finally:
        eng.close()


def test_reload_when_idle_applies_immediately(model):
    params, prompts, _ = model
    params2 = _params(seed=5)
    eng = _engine(params)
    try:
        assert eng.reload(dict(params2), timeout=60) == 1
        want = _ref_decode(params2, prompts[0], 5)
        assert np.array_equal(
            eng.generate(prompts[0], timeout=60, max_new_tokens=5), want)
    finally:
        eng.close()


def test_no_compiles_in_steady_decode_loop(model):
    """Warmup compiles the decode step, the slot-join reset and the
    argmax sampler; the serving loop itself — admissions, steps, state
    write-back, finishes — must never enter the XLA compiler."""
    from compile_guard import assert_no_compiles
    params, prompts, refs = model
    eng = _engine(params)
    try:
        # one full wave through every path (join/step/finish) pre-guard
        eng.generate(prompts[0], timeout=60, max_new_tokens=4)
        with assert_no_compiles("decode loop"):
            futs = [eng.submit(p, max_new_tokens=8) for p in prompts]
            for i, f in enumerate(futs):
                assert np.array_equal(f.result(timeout=120), refs[i])
    finally:
        eng.close()


def test_close_drain_and_no_drain(model):
    params, prompts, refs = model
    eng = _engine(params)
    futs = [eng.submit(p, max_new_tokens=6) for p in prompts[:6]]
    eng.close()                         # drain=True: all streams finish
    for i, f in enumerate(futs):
        assert np.array_equal(f.result(timeout=60),
                              _ref_decode(params, prompts[i], 6))
    with pytest.raises(ServeClosedError):
        eng.submit([1])
    eng.close()                         # idempotent

    eng2 = _engine(params, num_slots=1)
    hog = eng2.submit([1], max_new_tokens=500)
    queued = [eng2.submit([2], max_new_tokens=4) for _ in range(3)]
    eng2.close(drain=False)
    failed = 0
    for f in [hog] + queued:
        try:
            f.result(timeout=60)
        except ServeClosedError:
            failed += 1
    assert failed >= 1, "no stream was failed by close(drain=False)"
    with pytest.raises(ServeError):
        eng2.reload(dict(params))       # reload on a closed engine


def test_decode_symbol_contract_validation(model):
    params = model[0]
    with pytest.raises(ServeError, match="state"):
        _engine(params, state_shapes={"nope": (HID,)},
                state_outputs={"nope": 1})
    with pytest.raises(ServeError, match="out of range"):
        _engine(params, state_outputs={"h": 7})
    with pytest.raises(ServeError, match="distinct"):
        _engine(params, state_outputs={"h": 0})


def test_decode_report_row_and_weak_registry(model):
    params, prompts, _ = model
    eng = _engine(params, name="report-decode")
    try:
        for f in [eng.submit(p, max_new_tokens=4) for p in prompts[:4]]:
            f.result(timeout=60)
        rep = mx.profiler.serve_report()
        keys = [k for k in rep if k.startswith("report-decode#")]
        assert keys, "decode engine not registered with mx.profiler"
        r = rep[keys[-1]]
        assert r["kind"] == "decode" and r["num_slots"] == 4
        assert r["completed"] == 4 and r["tokens_out"] >= 16
        assert r["latency_p99_ms"] >= r["latency_p50_ms"] > 0
        s = mx.profiler.serve_report_str()
        assert "report-decode" in s and "slot occupancy" in s
    finally:
        eng.close()
    del eng
    import gc
    gc.collect()
    assert not any(k.startswith("report-decode#")
                   for k in mx.profiler.serve_report()), \
        "dead decode engine should drop out of the weak registry"


def test_env_knobs(model, monkeypatch):
    params = model[0]
    monkeypatch.setenv("MXNET_SERVE_SLOTS", "2")
    monkeypatch.setenv("MXNET_SERVE_DECODE_QUEUE", "5")
    monkeypatch.setenv("MXNET_SERVE_MAX_TOKENS", "3")
    eng = DecodeEngine(_decode_net(), dict(params),
                       state_shapes={"h": (HID,)}, name="env-decode")
    try:
        assert eng.num_slots == 2
        assert eng.queue_depth == 5
        assert eng.max_new_tokens == 3
        got = eng.generate([1], timeout=60)
        assert len(got) == 3            # default cap from the env
    finally:
        eng.close()


def test_eos_exactly_at_max_new_tokens(model):
    """EOS landing on the final allowed token must not double-count the
    terminal outcome or truncate: the stream completes once, the eos
    token is included, and the length is exactly max_new."""
    params, prompts, _ = model
    full = [int(t) for t in _ref_decode(params, prompts[0], 8)]
    # pick the eos token whose FIRST occurrence is deepest in the
    # stream, and cap max_new exactly there: eos fires ON the cap
    k = max(i for i, t in enumerate(full) if t not in full[:i])
    assert k >= 1, "degenerate stream, test proves nothing"
    eos, max_new = full[k], k + 1
    eng = _engine(params)
    try:
        got = eng.generate(prompts[0], timeout=60,
                           max_new_tokens=max_new, eos_id=eos)
        assert np.array_equal(got, np.asarray(full[:k + 1], np.int32))
        assert len(got) == max_new and int(got[-1]) == eos
        rep = eng.stats.report()
        assert rep["completed"] == 1 and rep["failed"] == 0
        assert rep["outstanding"] == 0
    finally:
        eng.close()


def test_stream_joins_slot_freed_same_step(model):
    """A queued request must be able to join a slot in the same loop
    pass that freed it: with ONE slot and a deep backlog of 1-token
    streams, every stream completes and matches the reference — no
    admission stall between a finish and the next join."""
    params, prompts, _ = model
    eng = _engine(params, num_slots=1, queue_depth=32)
    try:
        futs = [eng.submit(prompts[i % len(prompts)], max_new_tokens=1)
                for i in range(16)]
        for i, f in enumerate(futs):
            want = _ref_decode(params, prompts[i % len(prompts)], 1)
            assert np.array_equal(f.result(timeout=120), want), i
        rep = eng.stats.report()
        assert rep["completed"] == 16 and rep["queue_depth"] == 0
    finally:
        eng.close()


def test_closed_engine_beats_full_queue(model):
    """Submit on a closed engine raises ServeClosedError even when the
    queue is also full: the closed check must run FIRST, so clients see
    'gone', not 'retry with backoff' against an engine that will never
    drain (retrying a dead replica is the router's wedge case)."""
    params, prompts, _ = model
    eng = _engine(params, num_slots=1, queue_depth=2)
    hog = eng.submit([1], max_new_tokens=200)
    t0 = time.perf_counter()
    while eng.pending_requests() > 0:       # wait for the hog to admit
        assert time.perf_counter() - t0 < 10, "hog never admitted"
        time.sleep(0.005)
    queued = [eng.submit([2], max_new_tokens=4) for _ in range(2)]
    assert eng.pending_requests() >= 2      # queue genuinely full
    eng.close(drain=False)
    t0 = time.perf_counter()
    with pytest.raises(ServeClosedError):
        eng.submit(prompts[0], max_new_tokens=4)
    assert time.perf_counter() - t0 < 1.0, "closed fast-fail was slow"
    for f in [hog] + queued:
        with pytest.raises(ServeClosedError):
            f.result(timeout=60)


def test_injected_step_fault_kills_loop_but_not_liveness(model):
    """ISSUE 15 review: an injected decode.step error kills the decode
    loop — a dead engine must flip closed so later submits fast-fail
    with ServeClosedError instead of enqueueing futures that can never
    resolve (a wedged replica the router can then health-count)."""
    from mxnet_tpu import faults
    params, prompts, _ = model
    eng = _engine(params, name="fault-decode")
    try:
        fut = eng.submit(prompts[0], max_new_tokens=4)
        fut.result(timeout=60)                    # healthy first
        faults.install(faults.Rule(points="decode.step", kinds="error",
                                   max_faults=1))
        doomed = eng.submit(prompts[1], max_new_tokens=4)
        with pytest.raises(ServeError):
            doomed.result(timeout=60)             # loop died, stream failed
        faults.clear()
        deadline = time.perf_counter() + 10
        while time.perf_counter() < deadline:     # loop exit is async
            try:
                eng.submit(prompts[2], max_new_tokens=2)
            except ServeClosedError:
                break
            time.sleep(0.02)
        else:
            pytest.fail("dead decode engine still accepting submits")
    finally:
        faults.clear()
        eng.close(drain=False)
