/*
 * Execute the Scala binding's JNI glue
 * (scala-package/native/src/main/native/mxnet_tpu_jni.cc) against the
 * real libmxtpu_capi.so, with the JNI API mocked (jniheaders/jni.h) —
 * the JVM-less analogue of tests/cpp/test_r_glue.c.  Proves the JNI
 * marshalling end-to-end at the binding's acceptance bar: an
 * MNIST-style MLP (synthetic class blobs, zero-egress image) trains to
 * >= 0.95 test accuracy purely through the JNI entry points — ndarray
 * copies, symbol composition, shape inference, executor fwd/bwd, the
 * native optimizer — plus the model-parallel (ctx_group) bind path
 * (reference scala-package core ModelParallelSuite analogue), symbol
 * JSON and param save/load round trips, and kvstore push/pull.
 *
 * Usage: test_jni_glue <path-to-libmxtpu_capi.so> <tmpdir>
 */
#include <jni.h>

#include "../../scala-package/native/src/main/native/mxnet_tpu_jni.cc"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>

#include <string>
#include <vector>

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "CHECK failed at %d: %s\nlast error: %s\n",       \
              __LINE__, #cond, last_error(&env));                       \
      exit(1);                                                          \
    }                                                                   \
  } while (0)

static JNIEnv env;

static const char *last_error(JNIEnv *e) {
  jstring s = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxGetLastError(e, nullptr);
  return s ? s->str.c_str() : "(none)";
}

/* ---- mock-JVM array builders (what the Scala layer would allocate) --- */
static jintArray mkints(const std::vector<jint> &v) {
  jintArray a = env.NewIntArray((jsize)v.size());
  if (!v.empty()) env.SetIntArrayRegion(a, 0, (jsize)v.size(), v.data());
  return a;
}

static jlongArray mklongs(const std::vector<jlong> &v) {
  jlongArray a = env.NewLongArray((jsize)v.size());
  if (!v.empty()) env.SetLongArrayRegion(a, 0, (jsize)v.size(), v.data());
  return a;
}

static jfloatArray mkfloats(const std::vector<jfloat> &v) {
  jfloatArray a = env.NewFloatArray((jsize)v.size());
  if (!v.empty()) env.SetFloatArrayRegion(a, 0, (jsize)v.size(), v.data());
  return a;
}

static jobjectArray mkstrs(const std::vector<std::string> &v) {
  jobjectArray a = env.NewObjectArray((jsize)v.size(), nullptr, nullptr);
  for (size_t i = 0; i < v.size(); ++i)
    env.SetObjectArrayElement(a, (jsize)i, env.NewStringUTF(v[i].c_str()));
  return a;
}

static jlong out_handle(jlongArray ref) { return ref->longs[0]; }

/* ---- thin call wrappers over the JNI natives ------------------------- */
static jlong nd_create(const std::vector<jint> &shape) {
  jlongArray ref = env.NewLongArray(1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayCreateEx(
            &env, nullptr, mkints(shape), 1 /*cpu*/, 0, 0, 0 /*f32*/, ref)
        == 0);
  return out_handle(ref);
}

static void nd_set(jlong h, const std::vector<jfloat> &v) {
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySyncCopyFromCPU(
            &env, nullptr, h, mkfloats(v), (jint)v.size()) == 0);
}

static std::vector<jfloat> nd_get(jlong h, size_t n) {
  jfloatArray buf = env.NewFloatArray((jsize)n);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySyncCopyToCPU(
            &env, nullptr, h, buf, (jint)n) == 0);
  return buf->floats;
}

static jlong find_creator(const char *want) {
  jlongArray cs = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAtomicSymbolCreators(
      &env, nullptr);
  CHECK(cs != nullptr);
  for (jlong c : cs->longs) {
    jstring nm = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetAtomicSymbolName(
        &env, nullptr, c);
    if (nm && nm->str == want) return c;
  }
  fprintf(stderr, "creator %s not found\n", want);
  exit(1);
}

static jlong atomic(jlong creator, const std::vector<std::string> &keys,
                    const std::vector<std::string> &vals) {
  jlongArray ref = env.NewLongArray(1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateAtomicSymbol(
            &env, nullptr, creator, mkstrs(keys), mkstrs(vals), ref) == 0);
  return out_handle(ref);
}

static void compose1(jlong sym, const char *name, jlong arg) {
  std::vector<jlong> args = {arg};
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCompose(
            &env, nullptr, sym, env.NewStringUTF(name),
            mkstrs({"data"}), mklongs(args)) == 0);
}

static std::vector<std::string> list_args(jlong sym) {
  jobjectArray a = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListArguments(
      &env, nullptr, sym);
  CHECK(a != nullptr);
  std::vector<std::string> out;
  for (MockJObject *o : a->objs) out.push_back(o->str);
  return out;
}

/* 4-class blobs, the R gate's synthetic MNIST stand-in */
struct Blobs {
  std::vector<jfloat> X;
  std::vector<jint> y;
};

static unsigned long lcg_state = 12345;
static double lcg_unit() {   /* uniform [0,1) */
  lcg_state = lcg_state * 6364136223846793005UL + 1442695040888963407UL;
  return (double)((lcg_state >> 11) & 0xFFFFFFFFFFFFFUL) / (double)(1UL << 52);
}
static double lcg_gauss() {  /* Box-Muller */
  double u1 = lcg_unit() + 1e-12, u2 = lcg_unit();
  return sqrt(-2.0 * log(u1)) * cos(2.0 * M_PI * u2);
}

static Blobs make_blobs(int n, int dim, int classes, unsigned long seed) {
  static std::vector<double> centers;  /* shared across train/test */
  if (centers.empty()) {
    unsigned long save = lcg_state;
    lcg_state = 999;
    for (int i = 0; i < 4 * 64; ++i) centers.push_back(lcg_gauss() * 3.0);
    lcg_state = save;
  }
  lcg_state = seed;
  Blobs b;
  for (int i = 0; i < n; ++i) {
    int c = (int)(lcg_unit() * classes);
    if (c == classes) c = classes - 1;
    b.y.push_back(c);
    for (int d = 0; d < dim; ++d)
      b.X.push_back((jfloat)(centers[c * dim + d] + lcg_gauss() * 0.8));
  }
  return b;
}

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s libmxtpu_capi.so tmpdir\n", argv[0]);
    return 2;
  }
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_nativeLibInit(
            &env, nullptr, env.NewStringUTF(argv[1])) == 0);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxRandomSeed(&env, nullptr, 7) == 0);

  /* ---- ndarray round trip ---- */
  jlong a = nd_create({2, 3});
  nd_set(a, {1, 2, 3, 4, 5, 6});
  std::vector<jfloat> got = nd_get(a, 6);
  for (int i = 0; i < 6; ++i) CHECK(got[i] == i + 1);
  jintArray shp = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayGetShape(
      &env, nullptr, a);
  CHECK(shp && shp->ints.size() == 2 && shp->ints[0] == 2 && shp->ints[1] == 3);

  /* registry invoke through JNI: out = a + a */
  jlongArray fns = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxListFunctions(&env,
                                                                   nullptr);
  CHECK(fns != nullptr);
  jlong plus = 0;
  for (jlong f : fns->longs) {
    jstring nm = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncGetName(&env, nullptr,
                                                               f);
    if (nm && nm->str == "_plus") plus = f;
  }
  CHECK(plus != 0);
  jintArray d4 = env.NewIntArray(4);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncDescribe(&env, nullptr, plus,
                                                       d4) == 0);
  CHECK(d4->ints[0] == 2 && d4->ints[2] == 1);
  jlong sum = nd_create({2, 3});
  std::vector<jlong> use = {a, a}, mut = {sum};
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncInvoke(
            &env, nullptr, plus, mklongs(use), mkfloats({}), mklongs(mut))
        == 0);
  got = nd_get(sum, 6);
  for (int i = 0; i < 6; ++i) CHECK(got[i] == 2.0f * (i + 1));

  /* ---- MLP symbol through JNI ---- */
  jlong FC = find_creator("FullyConnected");
  jlong ACT = find_creator("Activation");
  jlong SM = find_creator("SoftmaxOutput");

  jlongArray ref = env.NewLongArray(1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateVariable(
            &env, nullptr, env.NewStringUTF("data"), ref) == 0);
  jlong data = out_handle(ref);
  jlong fc1 = atomic(FC, {"num_hidden"}, {"32"});
  compose1(fc1, "fc1", data);
  jlong relu1 = atomic(ACT, {"act_type"}, {"relu"});
  compose1(relu1, "relu1", fc1);
  jlong fc2 = atomic(FC, {"num_hidden"}, {"4"});
  compose1(fc2, "fc2", relu1);
  jlong net = atomic(SM, {}, {});
  compose1(net, "softmax", fc2);

  std::vector<std::string> args = list_args(net);
  CHECK(args.size() == 6);  /* data, fc1_w, fc1_b, fc2_w, fc2_b, label */

  /* JSON round trip */
  jstring json = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolSaveToJSON(
      &env, nullptr, net);
  CHECK(json != nullptr);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolCreateFromJSON(
            &env, nullptr, json, ref) == 0);
  CHECK(list_args(out_handle(ref)).size() == 6);

  /* ---- infer shapes for batch 40 x 64 ---- */
  const int kBatch = 40, kDim = 64, kClasses = 4;
  jobjectArray out3 = env.NewObjectArray(3, nullptr, nullptr);
  jintArray complete = env.NewIntArray(1);
  jobjectArray shapes_in = env.NewObjectArray(1, nullptr, nullptr);
  env.SetObjectArrayElement(shapes_in, 0, mkints({kBatch, kDim}));
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolInferShape(
            &env, nullptr, net, mkstrs({"data"}), shapes_in, out3, complete)
        == 0);
  CHECK(complete->ints[0] == 1);
  jobjectArray arg_shapes = (jobjectArray)env.GetObjectArrayElement(out3, 0);
  CHECK(env.GetArrayLength(arg_shapes) == 6);

  /* ---- create args + grads, bind ---- */
  lcg_state = 42;
  std::vector<jlong> in_args(6), grads(6);
  std::vector<jint> reqs(6);
  int data_idx = -1, label_idx = -1;
  for (int i = 0; i < 6; ++i) {
    jintArray s = (jintArray)env.GetObjectArrayElement(arg_shapes, i);
    std::vector<jint> sv = s->ints;
    in_args[i] = nd_create(sv);
    long total = 1;
    for (jint d : sv) total *= d;
    bool is_io = args[i] == "data" || args[i] == "softmax_label";
    if (args[i] == "data") data_idx = i;
    if (args[i] == "softmax_label") label_idx = i;
    std::vector<jfloat> init((size_t)total);
    if (!is_io) {
      double scale = sv.size() > 1 ? sqrt(2.0 / sv[1]) : 0.0;
      for (long j = 0; j < total; ++j)
        init[j] = (jfloat)(lcg_gauss() * scale);
    }
    nd_set(in_args[i], init);
    if (is_io) {
      grads[i] = 0;
      reqs[i] = 0;  /* null grad */
    } else {
      grads[i] = nd_create(sv);
      reqs[i] = 1;  /* write */
    }
  }
  CHECK(data_idx >= 0 && label_idx >= 0);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorBindX(
            &env, nullptr, net, 1, 0, mkstrs({}), mkints({}), mkints({}),
            mklongs(in_args), mklongs(grads), mkints(reqs), mklongs({}),
            ref) == 0);
  jlong ex = out_handle(ref);

  /* ---- native optimizer ---- */
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerFindCreator(
            &env, nullptr, env.NewStringUTF("sgd"), ref) == 0);
  jlong sgd_creator = out_handle(ref);
  /* rescale_grad = 1/batch: SoftmaxOutput grads are batch-summed, the
   * same normalization FeedForward applies before its updater */
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerCreateOptimizer(
            &env, nullptr, sgd_creator, mkstrs({"momentum", "rescale_grad"}),
            mkstrs({"0.9", "0.025"}), ref) == 0);
  jlong opt = out_handle(ref);

  /* ---- train: the binding's acceptance bar ---- */
  Blobs train = make_blobs(800, kDim, kClasses, 1);
  Blobs test = make_blobs(200, kDim, kClasses, 2);
  const int kEpochs = 10, kBatches = 800 / kBatch;
  for (int ep = 0; ep < kEpochs; ++ep) {
    for (int b = 0; b < kBatches; ++b) {
      std::vector<jfloat> xb(train.X.begin() + (size_t)b * kBatch * kDim,
                             train.X.begin() + (size_t)(b + 1) * kBatch * kDim);
      std::vector<jfloat> yb(kBatch);
      for (int i = 0; i < kBatch; ++i) yb[i] = (jfloat)train.y[b * kBatch + i];
      nd_set(in_args[data_idx], xb);
      nd_set(in_args[label_idx], yb);
      CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorForward(&env, nullptr,
                                                              ex, 1) == 0);
      CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorBackward(
                &env, nullptr, ex, mklongs({})) == 0);
      for (int i = 0; i < 6; ++i) {
        if (grads[i] == 0) continue;
        CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxOptimizerUpdate(
                  &env, nullptr, opt, i, in_args[i], grads[i], 0.2f, 0.0f)
              == 0);
      }
    }
  }

  /* ---- evaluate ---- */
  int correct = 0, total_eval = 0;
  for (int b = 0; b < 200 / kBatch; ++b) {
    std::vector<jfloat> xb(test.X.begin() + (size_t)b * kBatch * kDim,
                           test.X.begin() + (size_t)(b + 1) * kBatch * kDim);
    nd_set(in_args[data_idx], xb);
    nd_set(in_args[label_idx], std::vector<jfloat>(kBatch, 0.0f));
    CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorForward(&env, nullptr, ex,
                                                            0) == 0);
    jlongArray outs = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorOutputs(
        &env, nullptr, ex);
    CHECK(outs && outs->longs.size() == 1);
    std::vector<jfloat> probs = nd_get(outs->longs[0],
                                       (size_t)kBatch * kClasses);
    for (int i = 0; i < kBatch; ++i) {
      int arg = 0;
      for (int c = 1; c < kClasses; ++c)
        if (probs[i * kClasses + c] > probs[i * kClasses + arg]) arg = c;
      correct += (arg == test.y[b * kBatch + i]);
      ++total_eval;
    }
  }
  double acc = (double)correct / total_eval;
  printf("jni glue MLP test accuracy: %.4f\n", acc);
  CHECK(acc >= 0.95);

  /* ---- param save/load round trip ---- */
  char fname[512];
  snprintf(fname, sizeof(fname), "%s/jni_mlp.params", argv[2]);
  std::vector<jlong> save_h;
  std::vector<std::string> save_k;
  for (int i = 0; i < 6; ++i) {
    if (i == data_idx || i == label_idx) continue;
    save_h.push_back(in_args[i]);
    save_k.push_back("arg:" + args[i]);
  }
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySave(
            &env, nullptr, env.NewStringUTF(fname), mklongs(save_h),
            mkstrs(save_k)) == 0);
  jobjectArray loaded = env.NewObjectArray(2, nullptr, nullptr);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayLoad(
            &env, nullptr, env.NewStringUTF(fname), loaded) == 0);
  jlongArray lh = (jlongArray)env.GetObjectArrayElement(loaded, 0);
  jobjectArray ln = (jobjectArray)env.GetObjectArrayElement(loaded, 1);
  CHECK(env.GetArrayLength(lh) == 4 && env.GetArrayLength(ln) == 4);
  /* loaded weights equal the trained ones */
  std::vector<jfloat> w0 = nd_get(save_h[0], 32 * kDim);
  std::vector<jfloat> w0l = nd_get(lh->longs[0], 32 * kDim);
  for (int i = 0; i < 32 * kDim; ++i) CHECK(w0[i] == w0l[i]);

  /* ---- model parallel bind (ModelParallelSuite analogue) ---- */
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolSetAttr(
            &env, nullptr, fc1, env.NewStringUTF("ctx_group"),
            env.NewStringUTF("stage1")) == 0);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolSetAttr(
            &env, nullptr, fc2, env.NewStringUTF("ctx_group"),
            env.NewStringUTF("stage2")) == 0);
  jstring got_attr = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetAttr(
      &env, nullptr, fc1, env.NewStringUTF("ctx_group"));
  CHECK(got_attr && got_attr->str == "stage1");
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorBindX(
            &env, nullptr, net, 1, 0, mkstrs({"stage1", "stage2"}),
            mkints({1, 1}), mkints({1, 2}), mklongs(in_args), mklongs(grads),
            mkints(reqs), mklongs({}), ref) == 0);
  jlong ex_mp = out_handle(ref);
  std::vector<jfloat> xb(test.X.begin(), test.X.begin() + kBatch * kDim);
  nd_set(in_args[data_idx], xb);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorForward(&env, nullptr,
                                                          ex_mp, 0) == 0);
  jlongArray mp_outs = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorOutputs(
      &env, nullptr, ex_mp);
  CHECK(mp_outs && mp_outs->longs.size() == 1);
  std::vector<jfloat> mp_probs = nd_get(mp_outs->longs[0],
                                        (size_t)kBatch * kClasses);
  /* cross-device execution must agree with the single-device executor */
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorForward(&env, nullptr, ex,
                                                          0) == 0);
  jlongArray sd_outs = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorOutputs(
      &env, nullptr, ex);
  std::vector<jfloat> sd_probs = nd_get(sd_outs->longs[0],
                                        (size_t)kBatch * kClasses);
  for (int i = 0; i < kBatch * kClasses; ++i)
    CHECK(fabs(mp_probs[i] - sd_probs[i]) < 1e-4);

  /* ---- kvstore through JNI ---- */
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreCreate(
            &env, nullptr, env.NewStringUTF("local"), ref) == 0);
  jlong kv = out_handle(ref);
  jstring kvt = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreGetType(&env,
                                                                 nullptr, kv);
  CHECK(kvt && kvt->str == "local");
  jlong kw = nd_create({4});
  nd_set(kw, {0, 0, 0, 0});
  jlong kg = nd_create({4});
  nd_set(kg, {1, 1, 1, 1});
  std::vector<jlong> kws = {kw}, kgs = {kg};
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreInit(
            &env, nullptr, kv, mkints({3}), mklongs(kws)) == 0);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStorePush(
            &env, nullptr, kv, mkints({3}), mklongs(kgs), 0) == 0);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStorePull(
            &env, nullptr, kv, mkints({3}), mklongs(kws), 0) == 0);
  got = nd_get(kw, 4);
  CHECK(got[0] == 1.0f && got[3] == 1.0f);
  jintArray rank1 = env.NewIntArray(1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreGetRank(&env, nullptr, kv,
                                                         rank1) == 0);
  CHECK(rank1->ints[0] == 0);

  /* ---- round-5 surface: raw bytes, names/attrs, InvokeEx, roles,
   *      executor print, ABI data iterators (Scala io.IO path) ---- */

  /* raw-byte serialization round trip (Scala Serializer path) */
  jlong raw_src = nd_create({2, 2});
  nd_set(raw_src, {9, 8, 7, 6});
  jbyteArray raw = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArraySaveRawBytes(
      &env, nullptr, raw_src);
  CHECK(raw != nullptr && raw->bytes.size() > 16);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayLoadFromRawBytes(
            &env, nullptr, raw, ref) == 0);
  jlong raw_back = out_handle(ref);
  got = nd_get(raw_back, 4);
  CHECK(got[0] == 9.0f && got[3] == 6.0f);
  jintArray dt1 = env.NewIntArray(1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayGetDType(
            &env, nullptr, raw_back, dt1) == 0);
  CHECK(dt1->ints[0] == 0);  /* float32 */

  /* symbol name + shallow/recursive attrs (Scala Symbol.name/listAttr) */
  jstring symname = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolGetName(
      &env, nullptr, fc1);
  CHECK(symname != nullptr && symname->str == "fc1");
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolSetAttr(
            &env, nullptr, fc1, env.NewStringUTF("lr_mult"),
            env.NewStringUTF("2.0")) == 0);
  jobjectArray attrs = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAttrShallow(
      &env, nullptr, fc1);
  CHECK(attrs != nullptr);
  bool saw_lr = false;
  for (size_t i = 0; i + 1 < attrs->objs.size(); i += 2)
    if (attrs->objs[i]->str == "lr_mult" && attrs->objs[i + 1]->str == "2.0")
      saw_lr = true;
  CHECK(saw_lr);
  jobjectArray rattrs = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxSymbolListAttr(
      &env, nullptr, fc1);
  CHECK(rattrs != nullptr);
  bool saw_deep = false;
  for (size_t i = 0; i + 1 < rattrs->objs.size(); i += 2)
    if (rattrs->objs[i]->str.find("$lr_mult") != std::string::npos)
      saw_deep = true;
  CHECK(saw_deep);

  /* MXFuncInvokeEx: transpose with a string kwarg (Scala kwargs channel) */
  jlong t_in = nd_create({2, 3});
  nd_set(t_in, {1, 2, 3, 4, 5, 6});
  jlong t_out = nd_create({3, 2});
  jlong transpose_fn = 0;
  {
    jlongArray fns = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxListFunctions(
        &env, nullptr);
    CHECK(fns != nullptr);
    for (jlong h : fns->longs) {
      jstring nm = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncGetName(&env,
                                                                 nullptr, h);
      if (nm && nm->str == "transpose") transpose_fn = h;
    }
  }
  CHECK(transpose_fn != 0);
  std::vector<jlong> tu = {t_in}, tm = {t_out};
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxFuncInvokeEx(
            &env, nullptr, transpose_fn, mklongs(tu), mkfloats({}),
            mklongs(tm), mkstrs({"axes"}), mkstrs({"(1,0)"})) == 0);
  got = nd_get(t_out, 6);
  CHECK(got[0] == 1.0f && got[1] == 4.0f && got[2] == 2.0f);

  /* role queries (Scala KVStore.isWorkerNode etc.) */
  jintArray role1 = env.NewIntArray(1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreIsWorkerNode(
            &env, nullptr, role1) == 0);
  CHECK(role1->ints[0] == 1);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreIsServerNode(
            &env, nullptr, role1) == 0);
  CHECK(role1->ints[0] == 0);
  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxKVStoreIsSchedulerNode(
            &env, nullptr, role1) == 0);
  CHECK(role1->ints[0] == 0);

  /* executor debug dump (Scala Executor.debugStr) */
  jstring dbg = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxExecutorPrint(&env, nullptr,
                                                                ex);
  CHECK(dbg != nullptr && dbg->str.size() > 0);

  /* ABI data iterators: CSVIter end-to-end (Scala io.IO.createIterator) */
  {
    std::string csv = std::string(argv[2]) + "/jni_data.csv";
    FILE *f = fopen(csv.c_str(), "w");
    CHECK(f != nullptr);
    for (int i = 0; i < 8; ++i)
      fprintf(f, "%d,%d,%d\n", i, i + 1, i + 2);
    fclose(f);
    jlong csv_creator = 0;
    jlongArray iters = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxListDataIters(
        &env, nullptr);
    CHECK(iters != nullptr && iters->longs.size() >= 3);
    for (jlong h : iters->longs) {
      jstring nm = Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterGetName(
          &env, nullptr, h);
      if (nm && nm->str == "CSVIter") csv_creator = h;
    }
    CHECK(csv_creator != 0);
    CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterCreateIter(
              &env, nullptr, csv_creator,
              mkstrs({"data_csv", "data_shape", "batch_size"}),
              mkstrs({csv, "(3)", "4"}), ref) == 0);
    jlong it = out_handle(ref);
    jintArray has = env.NewIntArray(1);
    int batches = 0;
    float first_val = -1;
    while (true) {
      CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterNext(&env, nullptr, it,
                                                           has) == 0);
      if (!has->ints[0]) break;
      CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterGetData(
                &env, nullptr, it, ref) == 0);
      jlong data_h = out_handle(ref);
      std::vector<jfloat> rows = nd_get(data_h, 12);
      if (batches == 0) first_val = rows[0];
      ++batches;
    }
    CHECK(batches == 2);
    CHECK(first_val == 0.0f);
    /* rewind works */
    CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterBeforeFirst(
              &env, nullptr, it) == 0);
    CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterNext(&env, nullptr, it,
                                                         has) == 0);
    CHECK(has->ints[0] == 1);
    CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxDataIterFree(&env, nullptr, it)
          == 0);
  }

  CHECK(Java_ml_dmlc_mxnet_1tpu_LibInfo_mxNDArrayWaitAll(&env, nullptr) == 0);
  printf("JNI GLUE TESTS PASSED\n");
  return 0;
}
