package ml.dmlc.mxnet_tpu

/** Training callbacks (reference Callback.scala). */
object Callback {

  trait BatchEndCallback {
    def invoke(epoch: Int, nBatch: Int, evalMetric: EvalMetric): Unit
  }

  trait EpochEndCallback {
    def invoke(epoch: Int, symbol: Symbol,
               argParams: Map[String, NDArray],
               auxParams: Map[String, NDArray]): Unit
  }

  class Speedometer(batchSize: Int, frequent: Int = 50)
      extends BatchEndCallback {
    private var init = false
    private var tic = 0L
    private var lastCount = 0

    override def invoke(epoch: Int, count: Int,
                        metric: EvalMetric): Unit = {
      if (lastCount > count) init = false
      lastCount = count
      if (init) {
        if (count % frequent == 0) {
          val speed = frequent.toDouble * batchSize /
            ((System.currentTimeMillis() - tic) / 1000.0)
          val (name, value) = metric.get
          printf("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s=%f\n",
                 epoch, count, speed, name, value)
          tic = System.currentTimeMillis()
        }
      } else {
        init = true
        tic = System.currentTimeMillis()
      }
    }
  }
}
