"""mxnet_tpu.serve: dynamic-batching inference serving (tier-1, CPU).

Covers the subsystem's contracts: concurrent submitters see serial-
identical outputs; flush on max_batch vs max_delay; deadline expiry;
overload fast-fail from a bounded queue; admission-time malformed-
request isolation; hot weight reload with zero dropped or mixed-weights
requests; drain-on-shutdown; and the profiler.serve_report counters.
"""
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu.predictor import Predictor, create_predictor
from mxnet_tpu.serve import (ServeClosedError, ServeDeadlineError,
                             ServeEngine, ServeError, ServeOverloadError,
                             ServeRequestError, default_buckets)

IN_DIM = 6
CLASSES = 3


def _net():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _save_model(tmp_path, epoch=0, seed=0, name="model"):
    """Init (no training needed) + save a legacy pair; returns prefix."""
    net = _net()
    mx.random.seed(seed)
    it = mx.io.NDArrayIter(np.zeros((8, IN_DIM), np.float32),
                           np.zeros(8, np.float32), batch_size=8)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0),
                    force_init=True)
    arg, aux = mod.get_params()
    prefix = str(tmp_path / name)
    mx.model.save_checkpoint(prefix, epoch, net, arg, aux)
    return prefix


def _serial(prefix, epoch, X):
    """Reference outputs: batch-1 Predictor.predict per row."""
    pred = create_predictor(prefix, epoch, {"data": (1, IN_DIM),
                                            "softmax_label": (1,)})
    return np.stack([pred.predict(X[i:i + 1])[0] for i in range(len(X))])


def _engine(prefix, epoch=0, **kw):
    kw.setdefault("batch_buckets", (1, 2, 4, 8))
    kw.setdefault("max_delay_ms", 5.0)
    kw.setdefault("name", "test")
    return ServeEngine.from_checkpoint(
        prefix, epoch, {"data": (1, IN_DIM), "softmax_label": (1,)}, **kw)


@pytest.fixture(scope="module")
def model(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("serve_model")
    prefix = _save_model(tmp, epoch=0, seed=0)
    X = np.random.RandomState(7).randn(96, IN_DIM).astype(np.float32)
    return prefix, X, _serial(prefix, 0, X)


def test_concurrent_submitters_match_serial(model):
    prefix, X, serial = model
    eng = _engine(prefix)
    try:
        results = [None] * len(X)

        def client(lo, hi):
            for i in range(lo, hi):
                results[i] = eng.predict(X[i], timeout=30)

        n_threads = 8
        per = len(X) // n_threads
        with ThreadPoolExecutor(n_threads) as pool:
            list(pool.map(lambda t: client(t * per, (t + 1) * per),
                          range(n_threads)))
        for i in range(n_threads * per):
            assert np.allclose(results[i], serial[i], atol=1e-5), i
        rep = eng.stats.report()
        assert rep["completed"] == n_threads * per
        assert rep["failed"] == 0 and rep["expired"] == 0
        assert rep["batches"] >= 1
    finally:
        eng.close()


def test_flush_on_max_batch_beats_delay(model):
    """A full bucket dispatches immediately — the 1s delay window never
    runs out."""
    prefix, X, serial = model
    eng = _engine(prefix, max_delay_ms=1000.0)
    try:
        t0 = time.perf_counter()
        futs = [eng.submit(X[i]) for i in range(8)]
        rows = [f.result(timeout=30) for f in futs]
        elapsed = time.perf_counter() - t0
        assert elapsed < 0.5, "full batch waited out the delay window"
        for i in range(8):
            assert np.allclose(rows[i], serial[i], atol=1e-5)
        assert eng.stats.report()["bucket_hits"].get(8, 0) >= 1
    finally:
        eng.close()


def test_flush_on_max_delay_with_padding(model):
    """3 requests < max_batch flush at the delay deadline, padded into
    the 4-bucket."""
    prefix, X, serial = model
    eng = _engine(prefix, max_delay_ms=30.0)
    try:
        futs = eng.submit_many([X[0], X[1], X[2]])
        rows = [f.result(timeout=30) for f in futs]
        for i in range(3):
            assert np.allclose(rows[i], serial[i], atol=1e-5)
        rep = eng.stats.report()
        assert rep["bucket_hits"].get(4, 0) >= 1
        assert rep["pad_waste_frac"] > 0.0
        assert rep["batch_occupancy"] < 1.0
    finally:
        eng.close()


def test_deadline_expiry(model):
    """A request whose deadline lapses in the queue fails with
    ServeDeadlineError — promptly, not after the full delay window."""
    prefix, X, _ = model
    eng = _engine(prefix, max_delay_ms=500.0, deadline_ms=10.0)
    try:
        t0 = time.perf_counter()
        fut = eng.submit(X[0])      # alone: can only flush at deadline
        with pytest.raises(ServeDeadlineError):
            fut.result(timeout=30)
        assert time.perf_counter() - t0 < 0.4, \
            "expiry waited out the 500ms delay window"
        assert eng.stats.report()["expired"] == 1
    finally:
        eng.close()


def test_per_request_deadline_override(model):
    prefix, X, serial = model
    eng = _engine(prefix, max_delay_ms=5.0, deadline_ms=5000.0)
    try:
        ok = eng.submit(X[0])
        doomed = eng.submit(X[1], deadline_ms=0.001)
        assert np.allclose(ok.result(timeout=30), serial[0], atol=1e-5)
        with pytest.raises(ServeDeadlineError):
            doomed.result(timeout=30)
    finally:
        eng.close()


def test_overload_fast_fail(model):
    """Bounded queue: once the in-flight batch and the queue are full,
    submit raises ServeOverloadError immediately instead of hanging —
    and every ADMITTED request still completes."""
    prefix, X, serial = model
    eng = _engine(prefix, batch_buckets=(1, 2), max_delay_ms=2.0,
                  queue_depth=2, deadline_ms=0)
    try:
        admitted = []
        with eng.pause():       # dispatcher blocks between batches
            t0 = time.perf_counter()
            with pytest.raises(ServeOverloadError):
                for i in range(32):
                    admitted.append(eng.submit(X[i % len(X)]))
            reject_elapsed = time.perf_counter() - t0
        assert reject_elapsed < 1.0, "overload rejection was not fast"
        # max_batch(2) in flight + queue_depth(2) is the admission cap
        assert len(admitted) <= 4
        assert eng.stats.report()["overloaded"] >= 1
        for i, f in enumerate(admitted):
            assert np.allclose(f.result(timeout=30),
                               serial[i % len(X)], atol=1e-5)
    finally:
        eng.close()


def test_client_cancel_does_not_wedge_engine(model):
    """fut.cancel() on a queued request wins and is dropped at dispatch;
    it must never kill a worker thread (InvalidStateError) — the engine
    keeps serving and close() still returns."""
    prefix, X, serial = model
    eng = _engine(prefix, batch_buckets=(1, 2), max_delay_ms=2.0,
                  queue_depth=8)
    try:
        with eng.pause():       # hold dispatch so requests stay queued
            futs = eng.submit_many([X[i] for i in range(6)])
            time.sleep(0.1)     # dispatcher absorbs <= max_batch in flight
            cancelled = [f for f in futs if f.cancel()]
            assert cancelled, "no queued future was cancellable"
        for f in futs:
            if not f.cancelled():
                f.result(timeout=30)    # survivors still complete
        # the engine is not wedged: later requests serve normally
        assert np.allclose(eng.predict(X[0], timeout=30), serial[0],
                           atol=1e-5)
        rep = eng.stats.report()
        assert rep["cancelled"] == len(cancelled)
        assert rep["failed"] == 0
    finally:
        eng.close()     # must not hang on dead/wedged worker threads


def test_result_count_mismatch_fails_batch(model):
    """If the engine returns fewer results than requests (contract bug),
    the whole batch fails with ServeError instead of leaving the surplus
    futures unresolved forever."""
    prefix, X, _ = model
    eng = _engine(prefix)
    orig = eng._batcher._finish
    try:
        eng._batcher._finish = lambda handoff: orig(handoff)[:-1]
        futs = eng.submit_many([X[i] for i in range(4)])
        for f in futs:
            with pytest.raises(ServeError):
                f.result(timeout=30)
        assert eng.stats.report()["failed"] >= 4
    finally:
        eng._batcher._finish = orig
        eng.close()


def test_tight_deadline_behind_deadline_less_head(model):
    """The flush window is capped by the TIGHTEST deadline in the
    partial batch: a doomed request queued behind a deadline-less head
    fails at its own deadline, not after the full 500ms delay window."""
    prefix, X, serial = model
    eng = _engine(prefix, max_delay_ms=500.0, deadline_ms=0)
    try:
        t0 = time.perf_counter()
        head = eng.submit(X[0])                       # no deadline
        doomed = eng.submit(X[1], deadline_ms=10.0)   # queued behind it
        with pytest.raises(ServeDeadlineError):
            doomed.result(timeout=30)
        assert np.allclose(head.result(timeout=30), serial[0], atol=1e-5)
        assert time.perf_counter() - t0 < 0.4, \
            "doomed request waited out the 500ms delay window"
    finally:
        eng.close()


def test_concurrent_close(model):
    """close() from several threads at once: all return, none before
    shutdown completed, and the engine ends up closed exactly once."""
    prefix, X, serial = model
    eng = _engine(prefix)
    futs = eng.submit_many([X[i] for i in range(4)])
    closers = [threading.Thread(target=eng.close) for _ in range(4)]
    for t in closers:
        t.start()
    for t in closers:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in closers)
    for i, f in enumerate(futs):    # drained, not dropped
        assert np.allclose(f.result(timeout=30), serial[i], atol=1e-5)
    with pytest.raises(ServeClosedError):
        eng.submit(X[0])


def test_close_from_done_callback_does_not_deadlock(model):
    """A future done-callback (run inline on the completion thread) may
    close the engine — 'shut down after the last response' — while an
    outer closer holds the close lock joining that very thread: the
    reentrant close must degrade to a non-joining shutdown request, not
    deadlock."""
    prefix, X, serial = model
    eng = _engine(prefix, batch_buckets=(1, 2), max_delay_ms=2.0,
                  queue_depth=16)
    cb_ran = []
    with eng.pause():
        futs = eng.submit_many([X[i] for i in range(6)])
        for f in futs:
            f.add_done_callback(lambda f: (eng.close(), cb_ran.append(1)))
        closer = threading.Thread(target=eng.close)
        closer.start()      # joins the workers once the pause exits
        time.sleep(0.05)
    closer.join(timeout=30)
    assert not closer.is_alive(), "close deadlocked on a callback close"
    assert len(cb_ran) == len(futs), "a done-callback close hung"
    for i, f in enumerate(futs):    # drained, every request served
        assert np.allclose(f.result(timeout=30), serial[i], atol=1e-5)
    with pytest.raises(ServeClosedError):
        eng.submit(X[0])


def test_close_drain_false_callback_reentrancy(model):
    """close(drain=False) fails dropped futures whose done-callbacks run
    inline on the CLOSER's own thread; a callback that closes again must
    re-enter and return, not self-deadlock on the close lock."""
    prefix, X, _ = model
    eng = _engine(prefix, batch_buckets=(1, 2), max_delay_ms=500.0,
                  queue_depth=16)
    reentered = []
    with eng.pause():
        futs = eng.submit_many([X[i] for i in range(6)])
        time.sleep(0.1)     # dispatcher absorbs <= max_batch in flight
        for f in futs:
            f.add_done_callback(lambda f: (eng.close(drain=False),
                                           reentered.append(1)))
        closer = threading.Thread(target=lambda: eng.close(drain=False))
        closer.start()
        time.sleep(0.2)     # drop path runs callbacks on the closer thread
    closer.join(timeout=30)
    assert not closer.is_alive(), "close self-deadlocked on a callback"
    assert len(reentered) == len(futs), "a reentrant close hung"
    outcomes = {"served": 0, "dropped": 0}
    for f in futs:
        try:
            f.result(timeout=30)
            outcomes["served"] += 1
        except ServeClosedError:
            outcomes["dropped"] += 1
    assert outcomes["dropped"] >= 1 and sum(outcomes.values()) == len(futs)


def test_malformed_request_isolation(model):
    """Bad shape/dtype is rejected at admission, in the caller's thread;
    concurrent good requests are untouched (failed counter stays 0)."""
    prefix, X, serial = model
    eng = _engine(prefix)
    try:
        good = eng.submit_many([X[i] for i in range(8)])
        with pytest.raises(ServeRequestError):
            eng.submit(np.zeros((IN_DIM + 1,), np.float32))   # wrong shape
        with pytest.raises(ServeRequestError):
            eng.submit(np.zeros((2, IN_DIM), np.float32))     # batch dim
        with pytest.raises(ServeRequestError):
            eng.submit(np.array(["a"] * IN_DIM))              # non-numeric
        for i, f in enumerate(good):
            assert np.allclose(f.result(timeout=30), serial[i], atol=1e-5)
        rep = eng.stats.report()
        assert rep["failed"] == 0
        assert rep["completed"] >= 8
    finally:
        eng.close()


def test_hot_reload_parity_and_no_mixed_weights(model, tmp_path):
    """reload() swaps weights between batches: before the swap every
    output matches the old weights, after it the new ones — and under a
    concurrent flood, EVERY row matches exactly one version (a mixed-
    weights forward would match neither)."""
    prefix, X, serial_v1 = model
    prefix2 = _save_model(tmp_path, epoch=1, seed=99, name="model2")
    serial_v2 = _serial(prefix2, 1, X)
    # the two versions genuinely disagree, else the test proves nothing
    assert not np.allclose(serial_v1, serial_v2, atol=1e-3)
    eng = _engine(prefix)
    try:
        assert np.allclose(eng.predict(X[0], timeout=30), serial_v1[0],
                           atol=1e-5)
        results = [None] * len(X)
        errors = []

        def client(lo, hi):
            try:
                for i in range(lo, hi):
                    results[i] = eng.predict(X[i], timeout=30)
            except Exception as e:      # pragma: no cover - fail loud below
                errors.append(e)

        threads = [threading.Thread(target=client,
                                    args=(t * 12, (t + 1) * 12))
                   for t in range(8)]
        for t in threads:
            t.start()
        version = eng.reload_from_checkpoint(prefix2, 1)   # mid-flood
        for t in threads:
            t.join()
        assert not errors, errors
        assert version == 1 and eng.weights_version == 1
        for i in range(96):
            old = np.allclose(results[i], serial_v1[i], atol=1e-5)
            new = np.allclose(results[i], serial_v2[i], atol=1e-5)
            assert old or new, \
                "request %d matches NEITHER weights version (mixed?)" % i
        # steady state after the swap: new weights only
        assert np.allclose(eng.predict(X[1], timeout=30), serial_v2[1],
                           atol=1e-5)
        assert eng.stats.report()["reloads"] == 1
    finally:
        eng.close()


def test_reload_from_checkpoint_dir(model, tmp_path):
    """Hot reload straight from a mxnet_tpu.checkpoint store (and
    from_checkpoint_dir construction) matches the module that saved it."""
    prefix, X, _ = model
    from mxnet_tpu.checkpoint import CheckpointManager, save_module
    net = _net()
    mx.random.seed(5)
    it = mx.io.NDArrayIter(np.zeros((8, IN_DIM), np.float32),
                           np.zeros(8, np.float32), batch_size=8)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier(rnd_type="gaussian", magnitude=2.0),
                    force_init=True)
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    store = str(tmp_path / "ckpt_store")
    with CheckpointManager(store, async_save=False, name="serve-test") as m:
        save_module(m, mod, step=7)
    arg, aux = mod.get_params()
    ref_prefix = str(tmp_path / "ref")
    mx.model.save_checkpoint(ref_prefix, 0, net, arg, aux)
    ref = _serial(ref_prefix, 0, X[:8])

    eng = _engine(prefix)
    try:
        eng.reload_from_checkpoint_dir(store)
        for i in range(8):
            assert np.allclose(eng.predict(X[i], timeout=30), ref[i],
                               atol=1e-5), i
    finally:
        eng.close()
    eng2 = ServeEngine.from_checkpoint_dir(
        store, _net(), {"data": (1, IN_DIM), "softmax_label": (1,)},
        batch_buckets=(1, 2), max_delay_ms=5.0, name="from-dir")
    try:
        assert np.allclose(eng2.predict(X[0], timeout=30), ref[0],
                           atol=1e-5)
    finally:
        eng2.close()


def test_drain_on_shutdown(model):
    """close(drain=True) completes every queued request; later submits
    fail with ServeClosedError."""
    prefix, X, serial = model
    eng = _engine(prefix, max_delay_ms=200.0)
    try:
        futs = eng.submit_many([X[i] for i in range(6)])
        eng.close()     # drains: partial batch flushes now, not at 200ms
        for i, f in enumerate(futs):
            assert np.allclose(f.result(timeout=30), serial[i], atol=1e-5)
        with pytest.raises(ServeClosedError):
            eng.submit(X[0])
    finally:
        eng.close()


def test_close_without_drain_fails_pending(model):
    prefix, X, _ = model
    eng = _engine(prefix, batch_buckets=(1, 2), max_delay_ms=500.0,
                  queue_depth=64)
    # close() joins the worker threads, and the dispatcher needs the
    # pause (swap) lock to finish its in-flight batch — so close from a
    # helper thread and release the pause while it drains
    closer = threading.Thread(target=lambda: eng.close(drain=False))
    with eng.pause():
        futs = eng.submit_many([X[i] for i in range(6)])
        time.sleep(0.1)         # dispatcher absorbs <= max_batch in flight
        closer.start()
        time.sleep(0.1)         # close clears the queue under the lock
    closer.join(timeout=30)
    assert not closer.is_alive()
    failed = 0
    for f in futs:
        try:
            f.result(timeout=30)
        except ServeClosedError:
            failed += 1
    # requests still in the bounded queue (not yet absorbed into the
    # in-flight batch) must be failed, not leaked
    assert failed >= 1


def test_serve_report_counters(model):
    prefix, X, _ = model
    eng = _engine(prefix, name="report-engine")
    try:
        for f in eng.submit_many([X[i] for i in range(8)]):
            f.result(timeout=30)
        rep = mx.profiler.serve_report()
        keys = [k for k in rep if k.startswith("report-engine#")]
        assert keys, "engine not registered with mx.profiler"
        r = rep[keys[-1]]
        assert r["submitted"] == 8 and r["completed"] == 8
        assert r["latency_p99_ms"] >= r["latency_p50_ms"] > 0
        assert 0.0 < r["batch_occupancy"] <= 1.0
        assert sum(b * n for b, n in r["bucket_hits"].items()) >= 8
        s = mx.profiler.serve_report_str()
        assert "report-engine" in s and "p99" in s
    finally:
        eng.close()
    del eng     # the engine (and its batcher cycle) owns the stats ref
    import gc
    gc.collect()
    assert not any(k.startswith("report-engine#")
                   for k in mx.profiler.serve_report()), \
        "dead engine should drop out of the weak registry"


def test_queue_depth_gauge_resets_on_drain(model):
    """ISSUE 13 satellite regression: the queue-depth gauge must track
    every queue transition — after a drain (close with or without
    drain) the report reads 0, not the depth of the last submit frozen
    forever."""
    prefix, X, _ = model
    # drain=True path: dispatcher empties the queue, gauge ends at 0
    eng = _engine(prefix, max_delay_ms=200.0)
    futs = eng.submit_many([X[i] for i in range(6)])
    assert eng.stats.report()["queue_depth_max"] >= 1
    eng.close()
    for f in futs:
        f.result(timeout=30)
    assert eng.stats.report()["queue_depth"] == 0

    # drain=False path: the queue is CLEARED without a dispatch — the
    # gauge must still drop to 0 (this was the stale-forever case)
    eng2 = _engine(prefix, batch_buckets=(1, 2), max_delay_ms=500.0,
                   queue_depth=64)
    closer = threading.Thread(target=lambda: eng2.close(drain=False))
    with eng2.pause():
        eng2.submit_many([X[i] for i in range(6)])
        time.sleep(0.1)
        assert eng2.stats.report()["queue_depth"] >= 1
        closer.start()
        time.sleep(0.1)
    closer.join(timeout=30)
    assert not closer.is_alive()
    assert eng2.stats.report()["queue_depth"] == 0


def test_report_row_is_multiplex_aware(model):
    """Each engine's report row carries its own kind/max_batch_size and
    an outstanding balance (serve_report is per-model, never one global
    batch size per process)."""
    prefix, X, _ = model
    eng = _engine(prefix, batch_buckets=(1, 2, 4, 8))
    try:
        for f in eng.submit_many([X[i] for i in range(4)]):
            f.result(timeout=30)
        r = eng.stats.report()
        assert r["kind"] == "engine"
        assert r["max_batch_size"] == 8
        assert r["outstanding"] == 0
        assert eng.outstanding() == 0
        assert eng.device_bytes() > 0
    finally:
        eng.close()


def test_default_buckets_and_env_knobs(model, monkeypatch):
    assert default_buckets(8) == (1, 2, 4, 8)
    assert default_buckets(6) == (1, 2, 4, 6)
    assert default_buckets(1) == (1,)
    prefix, X, serial = model
    monkeypatch.setenv("MXNET_SERVE_MAX_BATCH", "4")
    monkeypatch.setenv("MXNET_SERVE_MAX_DELAY_MS", "7.5")
    monkeypatch.setenv("MXNET_SERVE_QUEUE_DEPTH", "9")
    monkeypatch.setenv("MXNET_SERVE_DEADLINE_MS", "1234")
    eng = ServeEngine.from_checkpoint(
        prefix, 0, {"data": (1, IN_DIM), "softmax_label": (1,)},
        name="env-knobs")
    try:
        assert eng.buckets == (1, 2, 4)
        assert eng.max_batch_size == 4
        assert eng.max_delay_ms == 7.5
        assert eng.queue_depth == 9
        assert eng.deadline_ms == 1234.0
        assert np.allclose(eng.predict(X[0], timeout=30), serial[0],
                           atol=1e-5)
    finally:
        eng.close()


def test_close_inside_pause_raises_not_deadlocks(model):
    """close() joins the dispatcher, which needs the paused lock for its
    in-flight batch — calling it inside pause() must raise, not hang;
    reload() inside pause() nests fine (RLock)."""
    prefix, X, serial = model
    eng = _engine(prefix)
    try:
        with eng.pause():
            eng.reload_from_checkpoint(prefix, 0)   # nested acquire: ok
            with pytest.raises(ServeError, match="deadlock"):
                eng.close()
        assert eng.pending_requests() == 0
        # the refused close must not have half-closed anything
        assert np.allclose(eng.predict(X[0], timeout=30), serial[0],
                           atol=1e-5)
    finally:
        eng.close()


def test_no_compiles_in_serving_loop(model):
    """Every bucket executable is compiled at construction: the predictor
    executor cache is fully populated before the first submit, and the
    serving loop itself never enters the XLA compiler (shared
    steady-state guard, tests/common/compile_guard.py)."""
    from compile_guard import assert_no_compiles
    prefix, X, _ = model
    eng = _engine(prefix, batch_buckets=(1, 2, 4))
    try:
        assert len(eng._predictor._exec_cache) == 3
        execs_before = set(id(e) for e in eng._predictor._exec_cache.values())
        with assert_no_compiles("serving loop"):
            for f in eng.submit_many([X[i] for i in range(9)]):
                f.result(timeout=30)
        execs_after = set(id(e) for e in eng._predictor._exec_cache.values())
        assert execs_before == execs_after, "serving rebound an executor"
    finally:
        eng.close()
