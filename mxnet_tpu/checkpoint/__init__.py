"""mxnet_tpu.checkpoint: async, sharded, crash-safe checkpointing.

The fault-tolerance layer of the production story (ROADMAP north star):
a training job on preemptible TPUs must survive ``kill -9`` at any
instant and resume bitwise-identically — params, optimizer slots, LR
schedule, RNG, and the exact next batch.

Capabilities (see the submodule docstrings for the mechanics):

* **async snapshot** (snapshot.py) — a save costs ~one step of stall:
  on-device copies + async D2H on the train thread, serialization and
  commit on a background writer;
* **sharded saves/restores** (sharded.py) — each process writes only the
  shards it owns, one file per shard plus a merged index; restore
  device_puts each shard straight to its target devices, no gather;
* **atomic commit** (layout.py) — ``step-N.tmp`` -> fsync -> rename ->
  ``COMMIT`` marker; :func:`latest_step` (the discovery API) can never
  observe a torn save;
* **full train-state capture** (module_state.py) — params, optimizer
  slots, lr_scheduler, RNG, epoch + batch cursor (the feed pipeline's
  ``state()``/``restore()``);
* **policy + preemption** (manager.py) — keep-last-N / keep-every-K
  retention, ``Module.fit(checkpoint=...)`` wiring, SIGTERM
  snapshot-then-exit;
* **observability** — ``mx.profiler.checkpoint_report()`` alongside
  ``feed_report()``.

Quick start::

    mgr = mx.checkpoint.CheckpointManager("/ckpt/run7", keep_last_n=3,
                                          save_every_steps=100)
    mod.fit(train_iter, num_epoch=50, checkpoint=mgr, resume=True)

or standalone over any pytree of arrays::

    mgr.save(step, {"params": params, "opt": slots}, {"epoch": 3})
    tree, meta = mgr.restore()           # newest committed step
"""
from __future__ import annotations

from .layout import (all_steps, latest_step, step_dir_name,
                     COMMIT_MARKER, INDEX_FILE, META_FILE)
from .manager import CheckpointManager, CheckpointStats
from .module_state import (capture_train_state, restore_train_state,
                           save_module, restore_module)
from .snapshot import snapshot_tree

__all__ = ["CheckpointManager", "CheckpointStats", "latest_step",
           "all_steps", "step_dir_name", "snapshot_tree",
           "capture_train_state", "restore_train_state", "save_module",
           "restore_module", "COMMIT_MARKER", "INDEX_FILE", "META_FILE"]
