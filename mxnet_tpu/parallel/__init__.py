"""Parallelism: meshes, sharded training steps, collectives.

TPU-native replacement for the reference's kvstore/ps-lite distribution stack
(SURVEY §2.4, §5.8): data parallel = GSPMD batch sharding + XLA all-reduce
over ICI; model parallel = param PartitionSpecs (ctx_group analogue);
multi-host = the same mesh spanning processes over ICI+DCN.
"""
from .mesh import (make_mesh, parse_mesh_spec, mesh_from_env,
                   normalize_spec, spec_axes, validate_spec,
                   sharding_attrs, dp_sharding, replicated,
                   Mesh, NamedSharding, PartitionSpec)
from .data_parallel import DPTrainStep
from .pipeline import GPipeTrainStep, pipeline_apply

__all__ = ["make_mesh", "parse_mesh_spec", "mesh_from_env",
           "normalize_spec", "spec_axes", "validate_spec",
           "sharding_attrs", "dp_sharding", "replicated",
           "Mesh", "NamedSharding", "PartitionSpec", "DPTrainStep",
           "GPipeTrainStep", "pipeline_apply"]
