#!/usr/bin/env python
"""Print the before/after graph per optimization pass (mxnet_tpu.passes).

The pass-regression debugging loop: when a pipeline produces a wrong or
slow graph, this shows exactly which pass did what — node counts, the
nodes each pass folded/merged/removed, the q/dq pairs quantization
inserted, and the per-pass wall time::

    python tools/dump_passes.py model-symbol.json
    python tools/dump_passes.py model-symbol.json --params model-0001.params
    python tools/dump_passes.py model-symbol.json --params model-0001.params \
        --quantize int8 --calib-npy sample.npy --data-shape 8,3,224,224
    python tools/dump_passes.py model-symbol.json --u8-wire --diff
    python tools/dump_passes.py model-symbol.json --out-prefix /tmp/stage

``--diff`` prints a per-pass op-census delta (which op counts changed);
``--out-prefix`` writes ``<prefix>.<NN>.<pass>.json`` after every stage
so two pipeline versions can be diffed offline with plain ``diff``.

Without ``--params`` the structural passes still run (param-subgraph
folding and quantization need the blob and are skipped loudly).
"""
from __future__ import annotations

import argparse
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def op_census(sym) -> "collections.Counter":
    doc = json.loads(sym.tojson())
    return collections.Counter(n["op"] for n in doc["nodes"])


def census_delta(before, after) -> str:
    parts = []
    for op in sorted(set(before) | set(after)):
        d = after.get(op, 0) - before.get(op, 0)
        if d:
            parts.append("%s%+d %s" % ("", d, op))
    return ", ".join(parts) or "(no op-census change)"


def summarize(summary: dict) -> str:
    """One line per interesting summary key, lists truncated."""
    lines = []
    for k in sorted(summary):
        if k == "type_overrides":
            continue
        v = summary[k]
        if isinstance(v, list):
            shown = ", ".join(map(str, v[:8]))
            if len(v) > 8:
                shown += ", ... +%d more" % (len(v) - 8)
            lines.append("    %s (%d): %s" % (k, len(v), shown or "-"))
        else:
            lines.append("    %s: %s" % (k, v))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("symbol", help="symbol json file (tojson/save output)")
    ap.add_argument("--params", help="param blob (save_checkpoint .params); "
                                     "enables param folding + quantization")
    ap.add_argument("--quantize", default=None,
                    help="int8|float16|bfloat16 (int8 needs --calib-npy)")
    ap.add_argument("--calib-npy",
                    help=".npy of calibration items (wire format, "
                         "item-stacked; batched per --data-shape)")
    ap.add_argument("--data-shape", default=None,
                    help="comma shape WITH batch dim for calibration "
                         "binding, e.g. 8,3,224,224")
    ap.add_argument("--data-name", default="data")
    ap.add_argument("--u8-wire", action="store_true",
                    help="insert the uint8 cast/normalize wire prologue")
    ap.add_argument("--no-verify", action="store_true",
                    help="skip per-pass round-trip/attr verification")
    ap.add_argument("--diff", action="store_true",
                    help="print the per-pass op-census delta")
    ap.add_argument("--out-prefix", default=None,
                    help="write <prefix>.<NN>.<pass>.json after each pass")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from mxnet_tpu import passes
    from mxnet_tpu.predictor import load_ndarray_file
    from mxnet_tpu.symbol import load_json

    with open(args.symbol) as f:
        sym = load_json(f.read())
    params = None
    if args.params:
        params = {k: v.asnumpy()
                  for k, v in load_ndarray_file(args.params).items()}
    elif args.quantize:
        print("dump_passes: --quantize needs --params (weights are "
              "pre-quantized host-side)", file=sys.stderr)
        return 2

    q_pass = None
    if args.quantize:
        kw = {"dtype": args.quantize, "data_name": args.data_name}
        if args.calib_npy:
            import numpy as np
            kw["calib_data"] = np.load(args.calib_npy)
            if not args.data_shape:
                print("dump_passes: --calib-npy needs --data-shape",
                      file=sys.stderr)
                return 2
            kw["calib_shapes"] = {args.data_name: tuple(
                int(x) for x in args.data_shape.split(","))}
        q_pass = kw
    pipe = passes.build_serving_pipeline(
        quantize=q_pass, data_name=args.data_name,
        u8_wire=args.u8_wire or None, name="dump")
    pipe.verify = not args.no_verify

    census = op_census(sym)
    print("input graph: %d nodes — %s"
          % (sum(census.values()),
             ", ".join("%dx %s" % (c, op)
                       for op, c in census.most_common())))

    # run pass-by-pass so each stage can be censused/dumped individually
    out_sym, out_params = sym, params
    for i, p in enumerate(pipe.passes):
        stage = passes.PassPipeline([p], name="dump:%s" % p.name,
                                    verify=pipe.verify)
        before = op_census(out_sym)
        try:
            out_sym, out_params = stage.run(out_sym, out_params)
        except passes.PassError as e:
            print("\n[%d] %-16s FAILED: %s" % (i, p.name, e))
            return 1
        rep = stage.last_report[0]
        after = op_census(out_sym)
        print("\n[%d] %-16s %d -> %d nodes, %s rewrites, %.1f ms"
              % (i, p.name, rep["nodes_in"], rep["nodes_out"],
                 rep["summary"].get("rewrites", 0), rep["wall_s"] * 1e3))
        detail = summarize(rep["summary"])
        if detail:
            print(detail)
        if args.diff:
            print("    op census: %s" % census_delta(before, after))
        if args.out_prefix:
            path = "%s.%02d.%s.json" % (args.out_prefix, i, p.name)
            with open(path, "w") as f:
                f.write(out_sym.tojson())
            print("    wrote %s" % path)

    print("\npipeline fingerprint: %s" % pipe.fingerprint())
    roundtrip = passes.verify_roundtrip(out_sym, label="final graph")
    problems = passes.diff_attrs(sym, roundtrip)
    if problems:
        print("ATTR REGRESSIONS vs input graph:")
        for p in problems[:20]:
            print("  " + p)
        return 1
    print("final graph round-trips; node attrs preserved")
    return 0


if __name__ == "__main__":
    sys.exit(main())
