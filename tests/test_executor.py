"""Executor tests. Modeled on reference tests/python/unittest/test_executor.py."""
import numpy as np

import mxnet_tpu as mx


def reldiff(a, b):
    diff = np.sum(np.abs(a - b))
    norm = np.sum(np.abs(a)) + 1e-12
    return diff / norm


def check_bind_with_uniform(uf, gf, dim):
    """check function consistency with uniform random numbers
    (reference test_executor.py check_bind_with_uniform)."""
    shape = tuple(np.random.randint(1, 8, size=dim))
    lhs = mx.sym.Variable("lhs")
    rhs = mx.sym.Variable("rhs")
    ret = uf(lhs, rhs)
    assert ret.list_arguments() == ["lhs", "rhs"]
    lhs_arr = mx.nd.array(np.random.uniform(-1, 1, shape))
    rhs_arr = mx.nd.array(np.random.uniform(-1, 1, shape))
    lhs_grad = mx.nd.empty(shape)
    rhs_grad = mx.nd.empty(shape)

    executor = ret.bind(mx.current_context(), args=[lhs_arr, rhs_arr],
                        args_grad=[lhs_grad, rhs_grad])
    exec3 = ret.bind(mx.current_context(), args=[lhs_arr, rhs_arr])
    exec4 = ret.bind(mx.current_context(), args={"rhs": rhs_arr, "lhs": lhs_arr},
                     args_grad={"lhs": lhs_grad, "rhs": rhs_grad})
    executor.forward()
    exec3.forward()
    exec4.forward()
    out1 = executor.outputs[0].asnumpy()
    out2 = uf(lhs_arr.asnumpy(), rhs_arr.asnumpy())
    out3 = exec3.outputs[0].asnumpy()
    out4 = exec4.outputs[0].asnumpy()
    assert reldiff(out1, out2) < 1e-5
    assert reldiff(out1, out3) < 1e-5
    assert reldiff(out1, out4) < 1e-5
    # test gradient
    out_grad = mx.nd.array(np.ones(out2.shape))
    lhs_grad2, rhs_grad2 = gf(out_grad.asnumpy(),
                              lhs_arr.asnumpy(), rhs_arr.asnumpy())
    executor.forward(is_train=True)
    executor.backward([out_grad])
    assert reldiff(lhs_grad.asnumpy(), lhs_grad2) < 1e-5
    assert reldiff(rhs_grad.asnumpy(), rhs_grad2) < 1e-5


def test_bind():
    np.random.seed(0)
    nrepeat = 3
    maxdim = 3
    for _ in range(nrepeat):
        for dim in range(1, maxdim):
            check_bind_with_uniform(lambda x, y: x + y,
                                    lambda g, x, y: (g, g), dim)
            check_bind_with_uniform(lambda x, y: x - y,
                                    lambda g, x, y: (g, -g), dim)
            check_bind_with_uniform(lambda x, y: x * y,
                                    lambda g, x, y: (y * g, x * g), dim)
            check_bind_with_uniform(lambda x, y: x / y,
                                    lambda g, x, y: (g / y, -x * g / (y ** 2)),
                                    dim)


def test_reshape_executor():
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=4, name="fc")
    exe = y.simple_bind(mx.current_context(), x=(5, 4), grad_req="null")
    exe.arg_dict["x"][:] = 1
    exe.arg_dict["fc_weight"][:] = np.eye(4)
    exe.arg_dict["fc_bias"][:] = 0
    new_exe = exe.reshape(x=(3, 4))
    new_exe.arg_dict["x"][:] = 1
    new_exe.forward(is_train=False)
    # weights are shared with the original executor
    assert new_exe.arg_dict["fc_weight"] is exe.arg_dict["fc_weight"]
    assert np.allclose(new_exe.outputs[0].asnumpy(), np.ones((3, 4)))


def test_grad_req_add():
    x = mx.sym.Variable("x")
    y = 2.0 * x
    xv = mx.nd.array(np.ones((2, 2)))
    g = mx.nd.zeros((2, 2))
    exe = y.bind(mx.current_context(), args={"x": xv}, args_grad={"x": g}, grad_req="add")
    exe.forward(is_train=True)
    exe.backward()
    exe.forward(is_train=True)
    exe.backward()
    assert np.allclose(g.asnumpy(), 4 * np.ones((2, 2)))


def test_output_dict_and_copy_params():
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    exe = y.simple_bind(mx.current_context(), x=(3, 2))
    exe.copy_params_from({"fc_weight": mx.nd.ones((2, 2)),
                          "fc_bias": mx.nd.zeros((2,))})
    exe.arg_dict["x"][:] = 2
    exe.forward()
    assert list(exe.output_dict.keys()) == ["fc_output"]
    assert np.allclose(exe.outputs[0].asnumpy(), 4 * np.ones((3, 2)))


def test_monitor_callback():
    stats = []
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    z = mx.sym.Activation(y, act_type="relu", name="act")
    exe = z.simple_bind(mx.current_context(), x=(2, 2))
    exe.set_monitor_callback(lambda name, arr: stats.append(name))
    exe.arg_dict["x"][:] = 1
    exe.forward()
    assert "fc_output" in stats
    assert "act_output" in stats


def test_debug_str():
    x = mx.sym.Variable("x")
    y = mx.sym.FullyConnected(x, num_hidden=2, name="fc")
    exe = y.simple_bind(mx.current_context(), x=(2, 2))
    s = exe.debug_str()
    assert "fc" in s and "MB allocated" in s


def test_forward_kwargs_update_args():
    x = mx.sym.Variable("x")
    y = x * 3.0
    exe = y.simple_bind(mx.current_context(), x=(2, 2))
    out = exe.forward(x=np.ones((2, 2), dtype=np.float32))
    assert np.allclose(out[0].asnumpy(), 3 * np.ones((2, 2)))


def test_head_gradient():
    x = mx.sym.Variable("x")
    y = x * x
    xv = mx.nd.array(np.array([[1.0, 2.0]], dtype=np.float32))
    g = mx.nd.zeros((1, 2))
    exe = y.bind(mx.current_context(), args={"x": xv}, args_grad={"x": g})
    exe.forward(is_train=True)
    exe.backward(mx.nd.array(np.array([[10.0, 100.0]], dtype=np.float32)))
    assert np.allclose(g.asnumpy(), np.array([[20.0, 400.0]]))


def test_backward_mirror_grad_equivalence(monkeypatch):
    """MXNET_BACKWARD_DO_MIRROR (memonger -> jax.checkpoint) must not
    change gradients, only the memory/compute trade
    (reference static_graph.cc:404-437)."""
    rng = np.random.RandomState(0)
    x = rng.rand(4, 6).astype(np.float32)
    lab = rng.randint(0, 3, (4,)).astype(np.float32)

    def grads(mirror):
        if mirror:
            monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
        else:
            monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)
        data = mx.sym.Variable("data")
        net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
        net = mx.sym.Activation(net, act_type="tanh")
        net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        ex = net.simple_bind(mx.current_context(), grad_req="write", data=x.shape,
                             softmax_label=lab.shape)
        rng2 = np.random.RandomState(1)
        for k, v in ex.arg_dict.items():
            if k == "data":
                v[:] = x
            elif k == "softmax_label":
                v[:] = lab
            else:
                v[:] = rng2.rand(*v.shape).astype(np.float32) * 0.1
        ex.forward(is_train=True)
        ex.backward()
        return {k: g.asnumpy() for k, g in ex.grad_dict.items()
                if g is not None}

    g_plain = grads(False)
    g_mirror = grads(True)
    assert set(g_plain) == set(g_mirror)
    for k in g_plain:
        assert np.allclose(g_plain[k], g_mirror[k], atol=1e-6), k


def test_backward_head_grad_omission_rules():
    """Omitting a head grad is allowed only when it cannot reach any
    argument (reference ref_count==0 rule): loss heads and (wrapped)
    BlockGrad tails qualify; plain outputs do not."""
    import numpy as np
    import pytest
    x = mx.sym.Variable("x")
    loss = mx.sym.LinearRegressionOutput(
        data=x * 2.0, label=mx.sym.Variable("y"), name="loss")
    # Reshape AROUND BlockGrad: the wrapper itself is not grad-optional,
    # but every backward path dies in BlockGrad — omission must pass
    tail = mx.sym.Reshape(mx.sym.BlockGrad(x * 3.0), shape=(4, 1))
    grouped = mx.sym.Group([loss, tail])
    xv = mx.nd.array(np.arange(4, dtype=np.float32))
    yv = mx.nd.array(np.zeros(4, dtype=np.float32))
    gx = mx.nd.zeros((4,))
    exe = grouped.bind(mx.cpu(), {"x": xv, "y": yv}, args_grad={"x": gx},
                       grad_req={"x": "write", "y": "null"})
    exe.forward(is_train=True)
    exe.backward([mx.nd.ones((4,))])   # only the loss head's grad
    # d(loss)/dx = (2x - y) * 2 regardless of supplied head grad
    assert np.allclose(gx.asnumpy(), 4.0 * xv.asnumpy())

    # a REQUIRED head grad omitted -> loud error, not silent zeros
    plain = mx.sym.Group([loss, x * 5.0])
    exe2 = plain.bind(mx.cpu(), {"x": xv, "y": yv}, args_grad={"x": gx},
                      grad_req={"x": "write", "y": "null"})
    exe2.forward(is_train=True)
    with pytest.raises(mx.base.MXNetError, match="requires a head gradient"):
        exe2.backward([mx.nd.ones((4,))])
