"""Asynchronous distributed training convergence test.

Reference capability: dist_async training (docs/how_to/multi_node.md,
kvstore_dist_server.h:194-202) — each worker pushes gradients that the
parameter server applies immediately; workers train on stale weights.
Launched by tools/launch.py -n 2 -s 2; gate: async SGD still converges on
the synthetic-blob task (same oracle as dist_mlp.py for sync).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path[:] = [p for p in sys.path if ".axon_site" not in p]

import numpy as np
import mxnet_tpu as mx


def make_blobs(n, dim=10, classes=4, seed=0):
    centers = np.random.RandomState(1234).randn(classes, dim) * 3
    rng = np.random.RandomState(seed)
    ys = rng.randint(classes, size=n)
    X = centers[ys] + rng.randn(n, dim) * 0.5
    return X.astype(np.float32), ys.astype(np.float32)


def main():
    kv = mx.kv.create("dist_async")
    rank, nworker = kv.rank, kv.num_workers
    X, y = make_blobs(800)
    shard = len(X) // nworker
    Xs = X[rank * shard:(rank + 1) * shard]
    ys = y[rank * shard:(rank + 1) * shard]
    it = mx.io.NDArrayIter(Xs, ys, batch_size=50, shuffle=True)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=6, kvstore=kv,
            optimizer_params={"learning_rate": 0.3})
    Xv, yv = make_blobs(400, seed=99)
    val = mx.io.NDArrayIter(Xv, yv, batch_size=50)
    acc = mod.score(val, "acc")[0][1]
    print("dist_async_mlp rank %d/%d final accuracy=%.4f"
          % (rank, nworker, acc))
    assert acc >= 0.90, "accuracy gate failed: %f" % acc
    kv.barrier()
    kv.close()
    print("dist_async_mlp rank %d: PASSED" % rank)


if __name__ == "__main__":
    main()
