"""mxnet_tpu.online: the continuous-training loop (ISSUE 17, tier-1).

Covers each leg and then the whole loop:

* **capture** — exact deterministic sampling, SEALED two-step publish,
  torn-shard quarantine (an injected torn fault leaves an unsealed
  tail that replay refuses), resume-vs-fresh index semantics, the
  router seam (``ServeRouter(capture=...)``) with the sampled rate
  verifiable from the serve/router reports;
* **replay** — sealed shards -> FeedDataIter batches, the unsealed
  runtime assertion backing the ``unsealed-replay`` lint rule, and
  cursor-exact ``state()``/``restore()`` resume;
* **trainer** — cumulative fine-tune rounds against one checkpoint
  store, idempotent re-entry of a finished round;
* **gate / promote** — drift + quality decisions with reasons,
  quarantine records, embed-table freshness carry-forward, and
  promotion parity under concurrent DecodeEngine traffic (in-flight
  streams finish on old weights, post-promotion streams token-exact
  vs a fresh engine on the new weights);
* **THE acceptance scenario** — a live ServeRouter flood feeds capture,
  OnlineTrainer fine-tunes under the Supervisor, a gated promotion
  lands via rolling_restart with zero dropped requests, all under a
  chaos schedule (torn capture shard, SIGKILL mid-commit, crash
  mid-promotion) — and the promoted weights are bitwise equal to a
  fault-free run of the same loop.
"""
import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu import faults, online, serve
from mxnet_tpu.base import MXNetError
from mxnet_tpu.faults import Backoff, FaultPlan, InjectedFault, Rule
from mxnet_tpu.online import (CaptureWriter, OnlineTrainer, PromotionGate,
                              UnsealedShardError, freshen_embed)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clear_plan():
    yield
    faults.clear()


def _mlp():
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    return mx.sym.SoftmaxOutput(fc, name="softmax")


def _init_params(seed=7):
    rng = np.random.RandomState(seed)
    return {"fc_weight": mx.nd.array(
        rng.uniform(-0.05, 0.05, (3, 6)).astype(np.float32)),
        "fc_bias": mx.nd.zeros((3,))}


def _fill(writer, n=32, seed=0, dim=6, classes=3):
    rng = np.random.RandomState(seed)
    for i in range(n):
        writer.offer(rng.uniform(size=(dim,)).astype(np.float32),
                     np.float32(i % classes))
    writer.flush()


# -- capture -----------------------------------------------------------------

def test_capture_sampling_is_exact_and_deterministic(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=0.25, shard_items=4,
                      fresh=True)
    kept = [w.offer(np.float32(i), np.float32(0)) for i in range(40)]
    w.flush()
    assert sum(kept) == 10                      # exactly rate * offered
    # every-Nth accumulator, not a coin flip: the pattern is periodic
    assert kept[:8] == [False, False, False, True] * 2
    r = w.report()
    assert r["offered"] == 40 and r["kept"] == 10
    assert r["kept_frac"] == 0.25
    assert r["items_sealed"] + r["pending"] == 10


def test_capture_seal_two_step_publish(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=8,
                      fresh=True)
    _fill(w, n=20)
    sealed = online.sealed_shards(str(tmp_path))
    assert [os.path.basename(p) for p in sealed] == [
        "shard-00000000.npz", "shard-00000001.npz", "shard-00000002.npz"]
    for p in sealed:
        assert online.is_sealed(p)
        meta = json.load(open(online.seal_path(p)))
        assert meta["items"] in (8, 4)
    # no tmp wreckage after clean publishes
    assert not [f for f in os.listdir(str(tmp_path)) if ".tmp-" in f]


def test_capture_torn_shard_stays_unsealed_and_writer_dies_loud(tmp_path):
    faults.install(FaultPlan([
        Rule(points="online.capture@seal", kinds="torn", after=1,
             max_faults=1)], seed=3))
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4,
                      fresh=True)
    rng = np.random.RandomState(0)
    with pytest.raises(InjectedFault):
        for i in range(12):
            w.offer(rng.uniform(size=(6,)).astype(np.float32),
                    np.float32(i % 3))
    # shard 0 sealed, shard 1 published-but-torn (no marker)
    sealed = online.sealed_shards(str(tmp_path))
    assert [os.path.basename(p) for p in sealed] == ["shard-00000000.npz"]
    torn = online.shard_path(str(tmp_path), 1)
    assert os.path.exists(torn) and not online.is_sealed(torn)
    # the writer remembers: no further capture, flush re-raises
    with pytest.raises(InjectedFault):
        w.offer(np.zeros(6, np.float32), np.float32(0))
    with pytest.raises(InjectedFault):
        w.flush()
    assert w.report()["errored"]


def test_capture_fresh_vs_resume_indexing(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4,
                      fresh=True)
    _fill(w, n=8)
    # default: continue past the highest existing index
    w2 = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4)
    _fill(w2, n=4)
    names = [os.path.basename(p)
             for p in online.sealed_shards(str(tmp_path))]
    assert names == ["shard-00000000.npz", "shard-00000001.npz",
                     "shard-00000002.npz"]
    # fresh=True wipes
    w3 = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4,
                       fresh=True)
    assert online.sealed_shards(str(tmp_path)) == []
    _fill(w3, n=4)
    assert [os.path.basename(p) for p in
            online.sealed_shards(str(tmp_path))] == ["shard-00000000.npz"]


def test_capture_transform_shapes_the_label(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4,
                      fresh=True,
                      transform=lambda d, o: (d, np.argmax(o)))
    for i in range(4):
        scores = np.eye(3, dtype=np.float32)[i % 3]
        w.offer(np.zeros(6, np.float32), scores)
    w.flush()
    _data, label = online.load_shard(
        online.sealed_shards(str(tmp_path))[0])
    assert label.tolist() == [0, 1, 2, 0]


# -- replay ------------------------------------------------------------------

def test_replay_refuses_unsealed_shard(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4,
                      fresh=True)
    _fill(w, n=8)
    victim = online.sealed_shards(str(tmp_path))[1]
    os.unlink(online.seal_path(victim))         # simulate a torn tail
    with pytest.raises(UnsealedShardError):
        online.load_shard(victim)
    # the listing never offers it, so the pipeline trains on shard 0 only
    it = online.replay_pipeline(str(tmp_path), batch_size=4)
    batches = 0
    try:
        while True:
            it.next()
            batches += 1
    except StopIteration:
        pass
    it.close()
    assert batches == 1


def test_replay_restore_is_cursor_exact(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=8,
                      fresh=True)
    _fill(w, n=24)
    it = online.replay_pipeline(str(tmp_path), batch_size=4)
    first = [it.next() for _ in range(3)]
    st = it.state()
    expect = it.next()
    it.close()
    it2 = online.replay_pipeline(str(tmp_path), batch_size=4)
    it2.restore(st)
    got = it2.next()
    it2.close()
    assert np.array_equal(expect.data[0].asnumpy(),
                          got.data[0].asnumpy())
    assert np.array_equal(expect.label[0].asnumpy(),
                          got.label[0].asnumpy())
    assert first[0].data[0].shape == (4, 6)


def test_replay_snapshot_is_pinned_at_construction(tmp_path):
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=4,
                      fresh=True)
    _fill(w, n=8)
    factory, n_items = online.replay_source(str(tmp_path))
    assert n_items == 8
    # shards sealed AFTER the snapshot belong to the next round
    _fill(CaptureWriter(str(tmp_path), sample=1.0, shard_items=4), n=4)
    assert sum(1 for _ in factory()) == 8
    assert len(online.sealed_shards(str(tmp_path))) == 3


# -- router capture seam -----------------------------------------------------

def test_router_capture_rate_verifiable_from_reports(tmp_path):
    net, init = _mlp(), _init_params()
    w = CaptureWriter(str(tmp_path), sample=0.5, shard_items=8,
                      fresh=True,
                      transform=lambda d, o: (d, np.argmax(o)))

    def factory(i):
        return serve.ServeEngine(net, dict(init), {"data": (4, 6)},
                                 name="cap-rep%d" % i, warmup=False)
    router = serve.ServeRouter(factory, replicas=2, capture=w,
                               name="cap-router")
    try:
        rng = np.random.RandomState(1)
        for i in range(40):   # closed loop: completion order = offer order
            router.submit(
                rng.uniform(size=(6,)).astype(np.float32)).result(
                timeout=30)
        router.capture_sync(timeout=30)
        rep = router.stats.report()
        assert rep["completed"] == 40
        assert rep["captured"] == 20 and rep["capture_errors"] == 0
        assert rep["capture_rate"] == pytest.approx(0.5)
        # mirrored onto the engines: sum of per-replica captured
        eng_captured = sum(row["engine"]["captured"]
                           for row in rep["per_replica"].values())
        assert eng_captured == 20
    finally:
        router.close()
    w.flush()
    assert w.report()["kept"] == 20
    assert sum(json.load(open(online.seal_path(p)))["items"]
               for p in online.sealed_shards(str(tmp_path))) == 20


def test_router_capture_failure_never_reaches_clients(tmp_path):
    net, init = _mlp(), _init_params()
    faults.install(FaultPlan([
        Rule(points="online.capture@seal", kinds="torn",
             max_faults=1)], seed=5))
    w = CaptureWriter(str(tmp_path), sample=1.0, shard_items=2,
                      fresh=True)

    def factory(i):
        return serve.ServeEngine(net, dict(init), {"data": (4, 6)},
                                 name="swallow-rep%d" % i, warmup=False)
    router = serve.ServeRouter(factory, replicas=1, capture=w,
                               name="swallow-router")
    try:
        rng = np.random.RandomState(2)
        for _ in range(8):    # every request succeeds for the client
            router.submit(
                rng.uniform(size=(6,)).astype(np.float32)).result(
                timeout=30)
        router.capture_sync(timeout=30)
        rep = router.stats.report()
        assert rep["completed"] == 8
        assert rep["capture_errors"] >= 1
    finally:
        router.close()
    with pytest.raises(InjectedFault):   # ...but the loop dies loud
        w.flush()


# -- trainer -----------------------------------------------------------------

def test_trainer_rounds_resume_and_reenter_idempotently(tmp_path):
    cap, ck = str(tmp_path / "cap"), str(tmp_path / "ck")
    w = CaptureWriter(cap, sample=1.0, shard_items=8, fresh=True)
    _fill(w, n=32)
    tr = OnlineTrainer(_mlp(), cap, ck, batch_size=8,
                       optimizer_params=(("learning_rate", 0.05),),
                       arg_params=_init_params())
    r1 = tr.round(num_epoch=2)
    assert r1["step"] == 8                      # 4 batches * 2 epochs
    # re-entering a finished round is a no-op (crash-restart shape)
    assert tr.round(num_epoch=2)["step"] == 8
    assert tr.round(num_epoch=3)["step"] == 12
    rep = tr.report()
    assert rep["rounds"] == 3 and rep["last_step"] == 12


def test_trainer_empty_capture_fails_loud(tmp_path):
    cap, ck = str(tmp_path / "cap"), str(tmp_path / "ck")
    os.makedirs(cap)
    tr = OnlineTrainer(_mlp(), cap, ck, batch_size=8,
                       arg_params=_init_params())
    with pytest.raises(MXNetError, match="no sealed capture shards"):
        tr.round(num_epoch=1)


# -- gate / promote ----------------------------------------------------------

def test_gate_decides_with_reasons():
    y = np.array([0, 1, 2, 0, 1, 2, 0, 1])
    right = np.eye(3, dtype=np.float32)[y]          # 100% correct
    wrong = np.eye(3, dtype=np.float32)[(y + 1) % 3]
    gate = PromotionGate(min_improve=0.0, max_drift=1.0)
    up = gate.decide(wrong, right, y)
    assert up["promote"] and up["improvement"] == 1.0
    down = gate.decide(right, wrong, y)
    assert not down["promote"]
    assert any("PROMOTE_MIN" in r for r in down["reasons"])
    drifty = PromotionGate(min_improve=-1.0, max_drift=0.5)
    d = drifty.decide(right, wrong, y)
    assert not d["promote"] and any("MAX_DRIFT" in r for r in d["reasons"])
    assert d["drift"] == 1.0
    rep = gate.report()
    assert rep["decisions"] == 2
    assert rep["promoted"] == 1 and rep["quarantined"] == 1


def test_gate_env_knobs(monkeypatch):
    monkeypatch.setenv("MXNET_ONLINE_PROMOTE_MIN", "0.25")
    monkeypatch.setenv("MXNET_ONLINE_MAX_DRIFT", "0.75")
    gate = PromotionGate()
    assert gate.min_improve == 0.25 and gate.max_drift == 0.75


def test_quarantine_writes_reasoned_record(tmp_path):
    dec = {"promote": False, "reasons": ["improvement -0.2 < 0.0"],
           "improvement": -0.2, "drift": 0.1}
    online.quarantine(str(tmp_path), dec)
    rec = online.read_record(str(tmp_path), online.QUARANTINED_RECORD)
    assert rec["action"] == "quarantine"
    assert rec["decision"]["reasons"] == dec["reasons"]


def test_freshen_embed_carries_live_tail_rows():
    cand = {"embed_weight": np.ones((4, 3), np.float32),
            "fc_weight": np.zeros((2, 2), np.float32)}
    live = {"embed_weight": np.concatenate(
        [np.full((4, 3), 2.0, np.float32),
         np.full((2, 3), 7.0, np.float32)]),
        "fc_weight": np.full((2, 2), 9.0, np.float32)}
    out = freshen_embed(cand, live)
    assert out["embed_weight"].shape == (6, 3)
    # candidate's trained rows win; live's NEW rows carry forward
    assert (out["embed_weight"][:4] == 1.0).all()
    assert (out["embed_weight"][4:] == 7.0).all()
    assert (out["fc_weight"] == 0.0).all()      # same shape: untouched
    with pytest.raises(MXNetError, match="missing"):
        freshen_embed(cand, live, keys=["nope"])


def test_gate_journal_context_rides_the_decision(tmp_path, monkeypatch):
    from mxnet_tpu.trace import journal
    path = str(tmp_path / "run.jsonl")
    monkeypatch.setenv("MXNET_TRACE_JOURNAL", path)
    journal.write_journal_line(path, 100)
    journal.write_journal_line(path, 150)
    gate = PromotionGate(min_improve=-1.0, max_drift=1.0)
    y = np.array([0, 1])
    dec = gate.decide(np.eye(3)[y], np.eye(3)[y], y)
    assert dec["journal"]["last_step"] == 150
    assert dec["journal"]["step_delta"] == 50


# -- promotion parity under concurrent DecodeEngine traffic ------------------

_VOCAB, _EMB, _HID = 11, 6, 8


def _decode_symbol():
    """One recurrent decode step (test_decode.py idiom): tok -> embed;
    h' = tanh(W_ih e + W_hh h); outputs [logits, h']."""
    tok = mx.sym.Variable("data")
    h = mx.sym.Variable("h")
    emb = mx.sym.Embedding(tok, input_dim=_VOCAB, output_dim=_EMB,
                           name="emb")
    emb = mx.sym.Flatten(emb)
    z = mx.sym.FullyConnected(emb, num_hidden=_HID, name="ih") + \
        mx.sym.FullyConnected(h, num_hidden=_HID, name="hh")
    h_next = mx.sym.Activation(z, act_type="tanh")
    logits = mx.sym.FullyConnected(h_next, num_hidden=_VOCAB, name="out")
    return mx.sym.Group([logits, h_next])


def _decode_params(seed):
    rng = np.random.RandomState(seed)

    def g(*s):
        return (rng.randn(*s) * 0.5).astype(np.float32)

    return {"emb_weight": g(_VOCAB, _EMB),
            "ih_weight": g(_HID, _EMB),
            "ih_bias": np.zeros(_HID, np.float32),
            "hh_weight": g(_HID, _HID),
            "hh_bias": np.zeros(_HID, np.float32),
            "out_weight": g(_VOCAB, _HID),
            "out_bias": np.zeros(_VOCAB, np.float32)}


def _tokens(engine, prompt, n=6):
    return [int(t) for t in
            engine.submit(np.asarray(prompt, np.int32),
                          max_new_tokens=n).result(timeout=60)]


@pytest.mark.filterwarnings("ignore::DeprecationWarning")
def test_rolling_restart_promotion_parity_under_decode_traffic():
    """Satellite: in-flight streams finish on the weights they started
    with; post-promotion streams are token-exact vs a fresh engine on
    the new weights — across a ROUTER promotion, with traffic running
    throughout."""
    sym = _decode_symbol()
    params_a, params_b = _decode_params(1), _decode_params(2)
    kw = dict(state_shapes={"h": (_HID,)}, num_slots=4,
              max_new_tokens=8, warmup=False)

    ref_a = serve.DecodeEngine(sym, params_a, name="ref-a", **kw)
    ref_b = serve.DecodeEngine(sym, params_b, name="ref-b", **kw)
    prompts = [[1, 2], [3], [2, 4, 1], [0, 3]]
    try:
        want_a = [_tokens(ref_a, p) for p in prompts]
        want_b = [_tokens(ref_b, p) for p in prompts]
        assert want_a != want_b      # the promotion is observable
    finally:
        ref_a.close()
        ref_b.close()

    router = serve.ServeRouter(
        lambda i: serve.DecodeEngine(sym, dict(params_a),
                                     name="par-rep%d" % i, **kw),
        replicas=2, name="parity-router")
    stop = threading.Event()
    background = {"done": 0, "failed": 0}

    def traffic():
        k = 0
        while not stop.is_set():
            try:
                router.submit(np.asarray(prompts[k % 4], np.int32),
                              max_new_tokens=4).result(timeout=60)
                background["done"] += 1
            except Exception:
                background["failed"] += 1
            k += 1
    t = threading.Thread(target=traffic, name="parity-traffic")
    t.start()
    try:
        # in-flight across the swap: submitted before, read after
        inflight = [router.submit(np.asarray(p, np.int32),
                                  max_new_tokens=6)
                    for p in prompts]
        router.rolling_restart(reload=params_b, timeout=120)
        got_inflight = [[int(x) for x in f.result(timeout=60)]
                        for f in inflight]
        # streams admitted before the drain finished under SOME single
        # weights version — old or new, never a mix
        for got, a, b in zip(got_inflight, want_a, want_b):
            assert got == a or got == b
        got_after = [_tokens(router, p) for p in prompts]
        assert got_after == want_b
    finally:
        stop.set()
        t.join(timeout=60)
        router.close()
    assert background["failed"] == 0 and background["done"] > 0


# -- THE acceptance: the whole loop, chaos-tested, bitwise -------------------

_CHAOS_LOOP = """
import json, os, sys, threading
sys.path.insert(0, %(root)r)
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import faults, online, serve
from mxnet_tpu.base import atomic_local_write

cap_dir, ck_dir, markers, out_path = sys.argv[1:5]
chaos = len(sys.argv) > 5 and sys.argv[5] == "chaos"

def once(name):
    try:
        os.close(os.open(os.path.join(markers, name),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY))
        return True
    except FileExistsError:
        return False

if chaos:
    faults.install(faults.FaultPlan([
        # attempt 0: tear the second shard between publish and SEALED —
        # the flood finishes (clients never fail) but flush dies loud
        faults.Rule(points="online.capture@seal", kinds="torn",
                    attempts=[0], after=1, max_faults=1),
        # attempt 1: SIGKILL the training worker mid-commit-protocol
        faults.Rule(points="checkpoint.commit@after_rename",
                    kinds="crash", attempts=[1], max_faults=1),
        # attempt 2: crash mid-promotion (candidate loaded, restart
        # not yet begun) — the re-run re-gates and re-lands
        faults.Rule(points="online.promote@restart", kinds="crash",
                    attempts=[2], max_faults=1),
    ], seed=11))

mx.random.seed(123)
net = mx.sym.SoftmaxOutput(
    mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                          name="fc"), name="softmax")
init = {"fc_weight": mx.nd.array(
    np.random.RandomState(7).uniform(-0.05, 0.05, (3, 6))
    .astype(np.float32)), "fc_bias": mx.nd.zeros((3,))}

def factory(i):
    return serve.ServeEngine(net, dict(init), {"data": (4, 6)},
                             name="loop-rep%%d" %% i, warmup=False)

# -- phase 1: live router flood feeds capture (exactly once on disk) --------
if not os.path.exists(os.path.join(markers, "capture_done")):
    writer = online.CaptureWriter(
        cap_dir, sample=0.5, shard_items=8, fresh=True,
        transform=lambda d, o: (d, np.argmax(o)))
    router = serve.ServeRouter(factory, replicas=2, capture=writer,
                               name="loop-capture")
    flood = np.random.RandomState(5).uniform(
        size=(64, 6)).astype(np.float32)
    try:
        # closed loop: completion (= capture) order is submission order,
        # so a re-capture after a torn attempt reproduces the shards
        for i in range(64):
            router.submit(flood[i]).result(timeout=60)
    finally:
        router.close()
    writer.flush()          # raises if a shard tore -> restart, re-capture
    once("capture_done")

# -- phase 2: supervised fine-tune (cumulative target: idempotent) ----------
shards = online.sealed_shards(cap_dir)
assert len(shards) == 4, shards
trainer = online.OnlineTrainer(
    net, cap_dir, ck_dir, batch_size=8, optimizer="sgd",
    optimizer_params=(("learning_rate", 0.05), ("momentum", 0.9)),
    arg_params=init, checkpoint_every=3)
cand = trainer.round(num_epoch=2, shards=shards)

# -- phase 3: gated promotion under live traffic, zero drops ----------------
hold = np.random.RandomState(9).uniform(size=(16, 6)).astype(np.float32)
hold_y = np.random.RandomState(10).randint(0, 3, 16)
router = serve.ServeRouter(factory, replicas=2, name="loop-promote")
try:
    live_scores = np.stack([
        np.asarray(router.submit(hold[i]).result(timeout=60))
        for i in range(16)])
    cand_engine = serve.ServeEngine.from_checkpoint_dir(
        ck_dir, net, {"data": (4, 6)}, warmup=False, name="loop-cand")
    try:
        cand_scores = np.stack([
            np.asarray(cand_engine.submit(hold[i]).result(timeout=60))
            for i in range(16)])
    finally:
        cand_engine.close()
    gate = online.PromotionGate(min_improve=-1.0, max_drift=1.0)
    decision = gate.decide(live_scores, cand_scores, hold_y)
    assert decision["promote"], decision

    stop = threading.Event()
    drops = {"n": 0, "done": 0}
    def traffic():
        k = 0
        while not stop.is_set():
            try:
                router.submit(hold[k %% 16]).result(timeout=60)
                drops["done"] += 1
            except Exception:
                drops["n"] += 1
            k += 1
    t = threading.Thread(target=traffic, name="promote-traffic")
    t.start()
    try:
        record = gate.apply(decision, router, ck_dir, timeout=120)
    finally:
        stop.set()
        t.join(timeout=60)
    post = np.stack([
        np.asarray(router.submit(hold[i]).result(timeout=60))
        for i in range(16)])
finally:
    router.close()
assert np.allclose(post, cand_scores, atol=1e-5)

with atomic_local_write(out_path, "w") as f:
    json.dump({"dropped": drops["n"], "served": drops["done"],
               "step": record["step"], "decision": decision,
               "shards": [os.path.basename(s) for s in shards]}, f)
sys.exit(0)
"""


def test_chaos_online_loop_is_bitwise(tmp_path):
    """The ISSUE 17 acceptance scenario: serve -> capture -> fine-tune
    -> gated promotion, supervised, under a schedule that tears a
    capture shard (attempt 0), SIGKILLs the trainer mid-commit
    (attempt 1) and crashes mid-promotion (attempt 2) — zero dropped
    requests, and the promoted checkpoint bitwise equal to the
    fault-free run."""
    from mxnet_tpu import checkpoint as ck
    from test_faults import _tree_equal
    script = tmp_path / "loop_child.py"
    script.write_text(_CHAOS_LOOP % {"root": ROOT})
    env = dict(os.environ)
    env.pop("MXNET_FAULTS", None)
    env["JAX_PLATFORMS"] = "cpu"

    # fault-free reference (same seeds, fresh process)
    ref = {k: str(tmp_path / ("ref_" + k)) for k in ("cap", "ck", "mk")}
    for d in ref.values():
        os.makedirs(d)
    ref_out = str(tmp_path / "ref.json")
    res = subprocess.run(
        [sys.executable, str(script), ref["cap"], ref["ck"], ref["mk"],
         ref_out], env=env, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr

    # chaos run under the supervisor
    cha = {k: str(tmp_path / ("cha_" + k)) for k in ("cap", "ck", "mk")}
    for d in cha.values():
        os.makedirs(d)
    cha_out = str(tmp_path / "cha.json")
    sup = faults.Supervisor(
        [sys.executable, str(script), cha["cap"], cha["ck"], cha["mk"],
         cha_out, "chaos"],
        max_restarts=4, backoff=Backoff(base_s=0.05, jitter=0.0),
        timeout_s=240.0, checkpoint_dir=cha["ck"],
        env={"JAX_PLATFORMS": "cpu"}, name="chaos-online")
    assert sup.run() == 0
    r = sup.stats.report()
    # torn capture, SIGKILL mid-commit, crash mid-promotion, then clean
    assert r["restarts"] == 3, r

    ref_doc = json.load(open(ref_out))
    cha_doc = json.load(open(cha_out))
    assert ref_doc["dropped"] == 0 and cha_doc["dropped"] == 0
    assert cha_doc["served"] >= 0 and ref_doc["step"] == cha_doc["step"]
    assert ref_doc["shards"] == cha_doc["shards"]

    # identical capture shards (torn attempt recaptured cleanly) ...
    for name in ref_doc["shards"]:
        a = open(os.path.join(ref["cap"], name), "rb").read()
        b = open(os.path.join(cha["cap"], name), "rb").read()
        assert a == b, "capture shard %s diverged" % name

    # ... and a bitwise-identical promoted train state
    ref_mgr = ck.CheckpointManager(ref["ck"], keep_last_n=None)
    cha_mgr = ck.CheckpointManager(cha["ck"], keep_last_n=None)
    try:
        assert ref_mgr.latest_step() == cha_mgr.latest_step() == \
            ref_doc["step"]
        ref_tree, ref_meta = ref_mgr.restore()
        cha_tree, cha_meta = cha_mgr.restore()
        _tree_equal(ref_tree, cha_tree)
        for k in ("global_step", "epoch", "nbatch"):
            assert ref_meta.get(k) == cha_meta.get(k), k
    finally:
        ref_mgr.close()
        cha_mgr.close()
    for d in (ref["ck"], cha["ck"]):
        rec = online.read_record(d, online.PROMOTED_RECORD)
        assert rec["action"] == "promote"
        assert rec["step"] == ref_doc["step"]
