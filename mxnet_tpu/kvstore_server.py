# lint: allow-file(raw-env) — DMLC protocol vars: reference
# kvstore_server semantics distinguish set-vs-unset and must KeyError
# loudly on a broken launcher rendezvous, not fold into typed defaults
"""Server-role entry for distributed training.

Reference: python/mxnet/kvstore_server.py (68 LoC): on import, non-worker
DMLC_ROLE processes create a dist kvstore, register a controller that
un-pickles the optimizer shipped by workers, block in RunServer, and exit.

TPU-native: `dist_sync_tpu` has NO server role — aggregation is an XLA
collective over the mesh (SURVEY §5.8 north star) and jobs launch with
-s 0.  ``dist_async`` keeps the reference process model: when a process is
launched with DMLC_ROLE=server/scheduler AND the PS rendezvous env
(DMLC_PS_ROOT_URI, set by tools/launch.py -s N), importing mxnet_tpu runs
the parameter-server loop (mxnet_tpu.ps) and exits — exactly the
reference's import-time hijack (kvstore_server.py:58-68: ``import mxnet``
on a server role never returns to user code).
"""
from __future__ import annotations

import logging
import os
import sys

__all__ = ["KVStoreServer", "_init_kvstore_server_module"]


class KVStoreServer:
    """Compatibility shim for the server loop (reference kvstore_server.py:9)."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handle = None
        self.init_logging = False

    def run(self):
        import os
        if not (os.environ.get("DMLC_PS_ROOT_URI")
                and os.environ.get("DMLC_NUM_WORKER")):
            logging.info("no parameter-server environment (DMLC_PS_ROOT_URI/"
                         "DMLC_NUM_WORKER); nothing to serve — returning")
            return
        from . import ps
        ps.run_server()


def _init_kvstore_server_module():
    role = os.environ.get("DMLC_ROLE", "worker")
    if role not in ("server", "scheduler"):
        return
    if os.environ.get("DMLC_PS_ROOT_URI"):
        from . import ps
        if role == "scheduler":
            ps.run_scheduler()
        else:
            ps.run_server()
        sys.exit(0)
    logging.warning(
        "DMLC_ROLE=%s without DMLC_PS_ROOT_URI: synchronous TPU kvstore "
        "uses XLA collectives over the device mesh and needs no server "
        "processes (launch with -s 0; dist_async needs launch.py -s N). "
        "Exiting cleanly.", role)
    sys.exit(0)


_init_kvstore_server_module()
