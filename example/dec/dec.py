"""Deep Embedded Clustering (reference example/dec/dec.py capability).

Pretrains a stacked autoencoder, initializes cluster centroids with a small
built-in k-means (no sklearn dependency), then refines encoder + centroids
by minimizing KL(P || Q) of the Student-t soft assignments — the DEC
objective — as a MakeLoss graph, all in one fused XLA program per step.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx


def encoder_symbol(dims):
    net = mx.sym.Variable("data")
    for i, d in enumerate(dims[1:]):
        net = mx.sym.FullyConnected(net, num_hidden=d, name="enc_%d" % i)
        if i < len(dims) - 2:
            net = mx.sym.Activation(net, act_type="relu")
    return net


def ae_symbol(dims):
    net = encoder_symbol(dims)
    for i, d in enumerate(reversed(dims[:-1])):
        net = mx.sym.FullyConnected(net, num_hidden=d, name="dec_%d" % i)
        if i < len(dims) - 2:
            net = mx.sym.Activation(net, act_type="relu")
    return mx.sym.LinearRegressionOutput(
        net, label=mx.sym.Variable("rec_label"), name="rec")


def kmeans(z, k, iters=20, restarts=4, seed=0):
    """Lloyd's with k-means++ seeding, best of `restarts` by inertia."""
    rng = np.random.RandomState(seed)
    best = None
    for _ in range(restarts):
        centers = [z[rng.randint(len(z))]]
        for _ in range(k - 1):
            d2 = np.min(((z[:, None, :] - np.asarray(centers)[None]) ** 2
                         ).sum(-1), axis=1)
            centers.append(z[rng.choice(len(z), p=d2 / d2.sum())])
        centers = np.asarray(centers)
        for _ in range(iters):
            assign = ((z[:, None, :] - centers[None]) ** 2).sum(-1).argmin(1)
            for j in range(k):
                pts = z[assign == j]
                if len(pts):
                    centers[j] = pts.mean(axis=0)
        inertia = ((z - centers[assign]) ** 2).sum()
        if best is None or inertia < best[0]:
            best = (inertia, centers, assign)
    return best[1], best[2]


def dec_symbol(dims, num_cluster, alpha=1.0):
    """Student-t soft assignment + KL(P||Q) self-training loss."""
    z = encoder_symbol(dims)                       # (batch, latent)
    mu = mx.sym.Variable("centroids")              # (k, latent)
    p = mx.sym.Variable("target_p")                # (batch, k) fixed target
    # q_ij ~ (1 + |z_i - mu_j|^2 / alpha)^-(alpha+1)/2, row-normalized
    zz = mx.sym.Reshape(z, shape=(-1, 1, dims[-1]))
    mu3 = mx.sym.Reshape(mu, shape=(1, num_cluster, dims[-1]))
    diff = mx.sym.broadcast_minus(zz, mu3)
    dist = mx.sym.sum_axis(diff * diff, axis=2)    # (batch, k)
    qu = (1.0 + dist * (1.0 / alpha)) ** (-(alpha + 1.0) / 2.0)
    q = mx.sym.broadcast_div(qu, mx.sym.sum_axis(qu, axis=1, keepdims=True))
    kl = mx.sym.sum(p * (mx.sym.log(p + 1e-10) - mx.sym.log(q + 1e-10)))
    group = mx.sym.Group([mx.sym.MakeLoss(kl), mx.sym.BlockGrad(q)])
    return group


def target_distribution(q):
    w = (q ** 2) / q.sum(axis=0, keepdims=True)
    return w / w.sum(axis=1, keepdims=True)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--num-cluster", type=int, default=4)
    parser.add_argument("--batch-size", type=int, default=256)
    parser.add_argument("--pretrain-epochs", type=int, default=6)
    parser.add_argument("--dec-iters", type=int, default=60)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    # well-separated gaussian clusters in 64-d, projected to 784-d
    rng = np.random.RandomState(0)
    k = args.num_cluster
    proj = rng.randn(64, 784).astype(np.float32) / 8.0
    means = rng.randn(k, 64).astype(np.float32) * 4.0
    truth = rng.randint(0, k, size=4096)
    data = ((means[truth] + rng.randn(4096, 64).astype(np.float32)) @ proj)

    dims = [784, 256, 10]
    ae = mx.mod.Module(ae_symbol(dims), context=[mx.cpu()],
                       label_names=("rec_label",))
    it = mx.io.NDArrayIter(data, data, batch_size=args.batch_size,
                           shuffle=True, label_name="rec_label")
    ae.fit(it, num_epoch=args.pretrain_epochs, optimizer="adam",
           optimizer_params={"learning_rate": 1e-3}, eval_metric="mse")
    ae_args, _ = ae.get_params()

    # embed all data, init centroids by k-means
    enc = encoder_symbol(dims)
    enc_exe = enc.simple_bind(ctx=mx.cpu(), grad_req="null",
                              data=(len(data), 784))
    for nm, arr in ae_args.items():
        if nm in enc_exe.arg_dict:
            enc_exe.arg_dict[nm][:] = arr.asnumpy()
    enc_exe.arg_dict["data"][:] = data
    enc_exe.forward(is_train=False)
    z = enc_exe.outputs[0].asnumpy()
    centers, assign = kmeans(z, k)

    # DEC refinement
    dec = dec_symbol(dims, k)
    exe = dec.simple_bind(ctx=mx.cpu(), grad_req="write",
                          data=(args.batch_size, 784),
                          centroids=(k, dims[-1]),
                          target_p=(args.batch_size, k))
    for nm, arr in ae_args.items():
        if nm in exe.arg_dict:
            exe.arg_dict[nm][:] = arr.asnumpy()
    exe.arg_dict["centroids"][:] = centers
    opt = mx.optimizer.SGD(learning_rate=0.01, momentum=0.9,
                           rescale_grad=1.0 / args.batch_size)
    states = {nm: opt.create_state(i, exe.arg_dict[nm])
              for i, nm in enumerate(exe.grad_dict)}
    for it_i in range(args.dec_iters):
        idx = rng.randint(0, len(data), size=args.batch_size)
        exe.arg_dict["data"][:] = data[idx]
        exe.forward(is_train=True)
        q = exe.outputs[1].asnumpy()
        exe.arg_dict["target_p"][:] = target_distribution(q)
        exe.forward(is_train=True)
        exe.backward()
        for i, nm in enumerate(exe.grad_dict):
            if nm in ("data", "target_p"):
                continue
            opt.update(i, exe.arg_dict[nm], exe.grad_dict[nm], states[nm])

    # final cluster accuracy (best label permutation via greedy matching)
    enc_exe2 = enc.simple_bind(ctx=mx.cpu(), grad_req="null",
                               data=(len(data), 784))
    for nm in enc_exe2.arg_dict:
        if nm != "data":
            enc_exe2.arg_dict[nm][:] = exe.arg_dict[nm].asnumpy()
    enc_exe2.arg_dict["data"][:] = data
    enc_exe2.forward(is_train=False)
    z2 = enc_exe2.outputs[0].asnumpy()
    dist = ((z2[:, None, :] - exe.arg_dict["centroids"].asnumpy()[None]) ** 2
            ).sum(-1)
    pred = dist.argmin(1)
    # greedy cluster->class matching
    acc = 0
    used = set()
    for c in range(k):
        best, best_n = -1, -1
        for t in range(k):
            if t in used:
                continue
            n = int(((pred == c) & (truth == t)).sum())
            if n > best_n:
                best, best_n = t, n
        used.add(best)
        acc += best_n
    print("cluster accuracy: %.3f" % (acc / len(data)))


if __name__ == "__main__":
    main()
