package ml.dmlc.mxnet_tpu

import ml.dmlc.mxnet_tpu.Base._

/**
 * Symbolic graph node (reference Symbol.scala).  Operators come from the
 * live creator registry (MXSymbolListAtomicSymbolCreators) rather than
 * generated stubs: `Symbol.create("Convolution", ...)` works for every
 * registered op, and the common layers get named helpers.
 */
class Symbol private[mxnet_tpu](private[mxnet_tpu] val handle: SymbolHandle)
    extends Serializable {

  def listArguments(): IndexedSeq[String] = {
    val a = _LIB.mxSymbolListArguments(handle)
    require(a != null, _LIB.mxGetLastError())
    a.toIndexedSeq
  }

  def listOutputs(): IndexedSeq[String] = {
    val a = _LIB.mxSymbolListOutputs(handle)
    require(a != null, _LIB.mxGetLastError())
    a.toIndexedSeq
  }

  def listAuxiliaryStates(): IndexedSeq[String] = {
    val a = _LIB.mxSymbolListAuxiliaryStates(handle)
    require(a != null, _LIB.mxGetLastError())
    a.toIndexedSeq
  }

  def attr(key: String): Option[String] =
    Option(_LIB.mxSymbolGetAttr(handle, key))

  def setAttr(key: String, value: String): Unit =
    checkCall(_LIB.mxSymbolSetAttr(handle, key, value))

  /** Name of a single-output symbol; None for unnamed groups
   * (MXSymbolGetName). */
  def name: Option[String] = Option(_LIB.mxSymbolGetName(handle))

  /** This node's attributes only (MXSymbolListAttrShallow). */
  def listAttr(): Map[String, String] = {
    val flat = _LIB.mxSymbolListAttrShallow(handle)
    require(flat != null, _LIB.mxGetLastError())
    flat.grouped(2).map(kv => kv(0) -> kv(1)).toMap
  }

  /** Whole-graph attributes as "node$key" -> value (MXSymbolListAttr). */
  def attrMap(): Map[String, String] = {
    val flat = _LIB.mxSymbolListAttr(handle)
    require(flat != null, _LIB.mxGetLastError())
    flat.grouped(2).map(kv => kv(0) -> kv(1)).toMap
  }

  /** Graph-composition arithmetic (reference Symbol.scala operators):
   * each builds the corresponding registered elementwise op node. */
  def +(other: Symbol): Symbol = Symbol.binop("_plus", this, other)
  def -(other: Symbol): Symbol = Symbol.binop("_minus", this, other)
  def *(other: Symbol): Symbol = Symbol.binop("_mul", this, other)
  def /(other: Symbol): Symbol = Symbol.binop("_div", this, other)
  def +(s: Float): Symbol = Symbol.scalarOp("_plus_scalar", this, s)
  def -(s: Float): Symbol = Symbol.scalarOp("_minus_scalar", this, s)
  def *(s: Float): Symbol = Symbol.scalarOp("_mul_scalar", this, s)
  def /(s: Float): Symbol = Symbol.scalarOp("_div_scalar", this, s)

  def save(fname: String): Unit = {
    val out = new java.io.PrintWriter(fname)
    try out.write(toJson) finally out.close()
  }

  def copy(): Symbol = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxSymbolCopy(handle, out))
    new Symbol(out(0))
  }

  def getInternals(): Symbol = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxSymbolGetInternals(handle, out))
    new Symbol(out(0))
  }

  def get(index: Int): Symbol = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxSymbolGetOutput(handle, index, out))
    new Symbol(out(0))
  }

  def toJson: String = {
    val s = _LIB.mxSymbolSaveToJSON(handle)
    require(s != null, _LIB.mxGetLastError())
    s
  }

  /** (argShapes, outShapes, auxShapes); empty seqs when incomplete. */
  def inferShape(known: Map[String, Shape])
      : (IndexedSeq[Shape], IndexedSeq[Shape], IndexedSeq[Shape]) = {
    val (keys, shapes) = known.toSeq.unzip
    val out3 = new Array[AnyRef](3)
    val complete = new Array[Int](1)
    checkCall(_LIB.mxSymbolInferShape(
      handle, keys.toArray,
      shapes.map(_.toArray.asInstanceOf[AnyRef]).toArray, out3, complete))
    if (complete(0) == 0) {
      (IndexedSeq.empty, IndexedSeq.empty, IndexedSeq.empty)
    } else {
      def grp(i: Int): IndexedSeq[Shape] =
        out3(i).asInstanceOf[Array[AnyRef]]
          .map(s => Shape(s.asInstanceOf[Array[Int]].toSeq)).toIndexedSeq
      (grp(0), grp(1), grp(2))
    }
  }

  /** Bind with explicit arrays (reference Symbol.bind). */
  def bind(ctx: Context, args: IndexedSeq[NDArray],
           argsGrad: IndexedSeq[NDArray], gradReqs: IndexedSeq[Int],
           auxStates: IndexedSeq[NDArray] = IndexedSeq.empty,
           group2ctx: Map[String, Context] = Map.empty): Executor = {
    val (mapKeys, mapCtx) = group2ctx.toSeq.unzip
    val out = new Array[Long](1)
    checkCall(_LIB.mxExecutorBindX(
      handle, ctx.deviceTypeid, ctx.deviceId, mapKeys.toArray,
      mapCtx.map(_.deviceTypeid).toArray, mapCtx.map(_.deviceId).toArray,
      args.map(_.handle).toArray,
      argsGrad.map(g => if (g == null) 0L else g.handle).toArray,
      gradReqs.toArray, auxStates.map(_.handle).toArray, out))
    new Executor(out(0), this, args, argsGrad, auxStates)
  }

  /** Allocate arg/grad arrays from inferred shapes and bind
   * (reference Symbol.simpleBind). */
  def simpleBind(ctx: Context, gradReq: String = "write",
                 shapes: Map[String, Shape] = Map.empty,
                 group2ctx: Map[String, Context] = Map.empty): Executor = {
    val (argShapes, _, auxShapes) = inferShape(shapes)
    require(argShapes.nonEmpty, "incomplete shapes for simpleBind")
    val argNames = listArguments()
    val req = Executor.gradReqCode(gradReq)
    val args = argShapes.map(NDArray.zeros(_, ctx))
    val grads = argNames.zip(argShapes).map { case (name, s) =>
      if (req == 0 || shapes.contains(name)) null.asInstanceOf[NDArray]
      else NDArray.zeros(s, ctx)
    }
    val reqs = argNames.map(n => if (shapes.contains(n)) 0 else req)
    val aux = auxShapes.map(NDArray.zeros(_, ctx))
    bind(ctx, args, grads, reqs, aux, group2ctx)
  }

  def dispose(): Unit = checkCall(_LIB.mxSymbolFree(handle))
}

object Symbol {
  private lazy val creators: Map[String, Long] = {
    val handles = _LIB.mxSymbolListAtomicSymbolCreators()
    require(handles != null, _LIB.mxGetLastError())
    handles.map(h => _LIB.mxSymbolGetAtomicSymbolName(h) -> h).toMap
  }

  def Variable(name: String): Symbol = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxSymbolCreateVariable(name, out))
    new Symbol(out(0))
  }

  def Group(symbols: Symbol*): Symbol = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxSymbolCreateGroup(symbols.map(_.handle).toArray, out))
    new Symbol(out(0))
  }

  def loadJson(json: String): Symbol = {
    val out = new Array[Long](1)
    checkCall(_LIB.mxSymbolCreateFromJSON(json, out))
    new Symbol(out(0))
  }

  def load(fname: String): Symbol = {
    val src = scala.io.Source.fromFile(fname, "UTF-8")
    try loadJson(src.mkString) finally src.close()
  }

  private[mxnet_tpu] def binop(op: String, lhs: Symbol,
                               rhs: Symbol): Symbol =
    create(op, "", Map("lhs" -> lhs, "rhs" -> rhs))

  private[mxnet_tpu] def scalarOp(op: String, src: Symbol,
                                  s: Float): Symbol =
    create(op, "", Map("data" -> src), Map("scalar" -> s.toString))

  /** Create any registered operator by name with keyword inputs +
   * string-typed params — the whole op inventory, no generated stubs.
   * An empty `name` is auto-generated by the current NameManager, and
   * the current AttrScope's attributes merge under `params` (the same
   * scope rules the python binding applies). */
  def create(op: String, rawName: String, inputs: Map[String, Symbol],
             params: Map[String, String] = Map.empty): Symbol = {
    val creator = creators.getOrElse(op,
      throw new MXNetError(s"unknown operator $op"))
    val name =
      if (rawName == null || rawName.isEmpty)
        NameManager.current.get(None, op.toLowerCase)
      else rawName
    val out = new Array[Long](1)
    val (pk, pv) = params.toSeq.unzip
    checkCall(_LIB.mxSymbolCreateAtomicSymbol(creator, pk.toArray,
                                              pv.toArray, out))
    val sym = new Symbol(out(0))
    // scope attributes (ctx_group, lr_mult, ...) are symbol ATTRS, not
    // op params — apply them through the attr API so the op's param
    // parser never sees them; explicit per-call params win on clashes
    for ((k, v) <- AttrScope.current.get(None)) {
      if (!params.contains(k)) {
        checkCall(_LIB.mxSymbolSetAttr(out(0), k, v))
      }
    }
    val (ik, iv) = inputs.toSeq.unzip
    checkCall(_LIB.mxSymbolCompose(sym.handle, name, ik.toArray,
                                   iv.map(_.handle).toArray))
    sym
  }

  def listOperators(): IndexedSeq[String] = creators.keys.toIndexedSeq.sorted

  // named helpers for the common layers
  def FullyConnected(data: Symbol, numHidden: Int, name: String): Symbol =
    create("FullyConnected", name, Map("data" -> data),
           Map("num_hidden" -> numHidden.toString))

  def Activation(data: Symbol, actType: String, name: String): Symbol =
    create("Activation", name, Map("data" -> data),
           Map("act_type" -> actType))

  def Convolution(data: Symbol, kernel: Shape, numFilter: Int,
                  name: String, params: Map[String, String] = Map.empty)
      : Symbol =
    create("Convolution", name, Map("data" -> data),
           params + ("kernel" -> kernel.toString,
                     "num_filter" -> numFilter.toString))

  def Pooling(data: Symbol, kernel: Shape, poolType: String, name: String,
              params: Map[String, String] = Map.empty): Symbol =
    create("Pooling", name, Map("data" -> data),
           params + ("kernel" -> kernel.toString, "pool_type" -> poolType))

  def Flatten(data: Symbol, name: String): Symbol =
    create("Flatten", name, Map("data" -> data))

  def SoftmaxOutput(data: Symbol, name: String): Symbol =
    create("SoftmaxOutput", name, Map("data" -> data))

  def BatchNorm(data: Symbol, name: String): Symbol =
    create("BatchNorm", name, Map("data" -> data))
}
