"""Network visualization. Reference: python/mxnet/visualization.py (152 LoC)."""
from __future__ import annotations

import json
from typing import Dict, Optional

from .base import MXNetError
from .symbol import Symbol

__all__ = ["plot_network", "print_summary"]


def print_summary(symbol: Symbol, shape: Optional[Dict] = None):
    """Print layer summary table with output shapes and parameter counts
    (reference visualization.py print_summary)."""
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    out_shape_by_name = {}
    arg_shape_by_name = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, out_shapes, _ = internals.infer_shape(**shape)
        for name, s in zip(internals.list_outputs(), out_shapes):
            out_shape_by_name[name] = tuple(s)
        arg_shapes, _, _ = symbol.infer_shape(**shape)
        for name, s in zip(symbol.list_arguments(), arg_shapes):
            arg_shape_by_name[name] = tuple(s)
    import numpy as _np
    print("%-28s %-18s %-20s %-10s" % ("Layer (type)", "Op", "Output Shape",
                                       "Params"))
    print("=" * 80)
    total = 0
    data_names = set(shape.keys()) if shape else {"data"}
    for node in nodes:
        if node["op"] == "null":
            continue
        # parameters = this op's null inputs that aren't data/labels
        n_params = 0
        for (j, _) in node["inputs"]:
            src = nodes[j]
            if src["op"] == "null" and src["name"] not in data_names:
                s = arg_shape_by_name.get(src["name"])
                if s:
                    n_params += int(_np.prod(s))
        total += n_params
        out_s = (out_shape_by_name.get(node["name"] + "_output")
                 or out_shape_by_name.get(node["name"] + "_out") or "")
        print("%-28s %-18s %-20s %-10d" % (node["name"], node["op"],
                                           str(out_s), n_params))
    print("=" * 80)
    print("Total params: %d" % total)


def plot_network(symbol: Symbol, title="plot", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz plot (reference visualization.py plot_network)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise MXNetError("plot_network requires graphviz; "
                         "use print_summary for a text view")
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    dot = Digraph(name=title)
    for i, node in enumerate(nodes):
        name = node["name"]
        if node["op"] == "null":
            if hide_weights and (name.endswith("weight") or name.endswith("bias")
                                 or name.endswith("gamma") or name.endswith("beta")):
                continue
            dot.node(name=name, label=name, shape="oval")
        else:
            dot.node(name=name, label="%s\n%s" % (name, node["op"]), shape="box")
    for node in nodes:
        if node["op"] == "null":
            continue
        for (j, _) in node["inputs"]:
            src = nodes[j]["name"]
            dot.edge(tail_name=src, head_name=node["name"])
    return dot
