# Convenience MLP interface (reference R-package/R/mlp.R mx.mlp): build
# the stacked FullyConnected/Activation/SoftmaxOutput symbol and train
# it through FeedForward in one call.

mx.mlp.symbol <- function(hidden_node = c(), out_node,
                          activation = "tanh",
                          out_activation = "softmax") {
  net <- mx.symbol.Variable("data")
  acts <- if (length(hidden_node) == 0) character(0)
          else rep(activation, length.out = length(hidden_node))
  for (i in seq_along(hidden_node)) {
    net <- mx.symbol.internal.create("FullyConnected", list(
      data = net, num_hidden = hidden_node[[i]],
      name = sprintf("fc%d", i)))
    net <- mx.symbol.internal.create("Activation", list(
      data = net, act_type = acts[[i]],
      name = sprintf("act%d", i)))
  }
  net <- mx.symbol.internal.create("FullyConnected", list(
    data = net, num_hidden = out_node,
    name = sprintf("fc%d", length(hidden_node) + 1)))
  if (out_activation == "softmax") {
    mx.symbol.internal.create("SoftmaxOutput", list(data = net,
                                                    name = "softmax"))
  } else if (out_activation == "logistic") {
    mx.symbol.internal.create("LogisticRegressionOutput", list(
      data = net, name = "softmax"))
  } else {
    mx.symbol.internal.create("LinearRegressionOutput", list(
      data = net, name = "softmax"))
  }
}

mx.mlp <- function(data, label, hidden_node = c(), out_node,
                   activation = "tanh", out_activation = "softmax",
                   ctx = mx.cpu(), num.round = 10, learning.rate = 0.1,
                   momentum = 0.9, array.batch.size = 32,
                   eval.metric = mx.metric.accuracy, verbose = TRUE) {
  net <- mx.mlp.symbol(hidden_node, out_node, activation, out_activation)
  mx.model.FeedForward.create(net, data, label, ctx = ctx,
                              num.round = num.round,
                              learning.rate = learning.rate,
                              momentum = momentum,
                              array.batch.size = array.batch.size,
                              eval.metric = eval.metric,
                              verbose = verbose)
}
