"""WarpCTC plugin parity: CTC loss layer.

Reference: plugin/warpctc/warpctc-inl.h — inputs [data, label], params
label_length (padded label width, blank=0-padded) and input_length (T);
data is the (T*batch, alphabet) concat of per-step activations; forward
outputs softmax, backward injects the CTC gradient (head grad ignored).

TPU-native: the CTC alpha-beta recursion comes from optax.ctc_loss (pure
lax.scan — compiles to one fused XLA loop); the layer gradient is
jax.grad of that loss wrt the activations, wrapped in custom_vjp to
reproduce the reference loss-layer semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import OpDef, Param, register_op


@register_op("WarpCTC", hint="warpctc")
class WarpCTCOp(OpDef):
    params = [Param("label_length", int, required=True),
              Param("input_length", int, required=True)]

    def list_arguments(self, p):
        return ["data", "label"]

    def infer_shape(self, p, in_shapes):
        d = in_shapes[0]
        if d is None:
            return in_shapes, [None], []
        batch = d[0] // p.input_length
        return [d, (batch, p.label_length)], [d], []

    def forward(self, p, inputs, aux, ctx):
        import optax
        data, label = inputs
        T = p.input_length
        A = data.shape[1]
        B = data.shape[0] // T

        def ctc_grad(data, label):
            logits = data.reshape(T, B, A).transpose(1, 0, 2)  # (B, T, A)
            logprobs = jax.nn.log_softmax(logits, axis=-1)
            labels = label.astype(jnp.int32)
            # blank=0; zero-padding marks unused label slots (reference
            # labelLengths counts to the first blank)
            label_pad = (labels == 0).astype(jnp.float32)
            logit_pad = jnp.zeros((B, T), jnp.float32)
            loss = optax.ctc_loss(logprobs, logit_pad, labels, label_pad,
                                  blank_id=0)
            return jnp.sum(loss)

        @jax.custom_vjp
        def f(data, label):
            return jax.nn.softmax(data, axis=-1)

        def f_fwd(data, label):
            return jax.nn.softmax(data, axis=-1), (data, label)

        def f_bwd(res, g):
            data, label = res
            del g  # loss layer: head gradient ignored (reference behavior)
            grad = jax.grad(ctc_grad)(data, label)
            return grad, jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(data, label)]
