"""Predictor (c_predict_api parity) + engine semantics tests."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.predictor import Predictor, create_predictor


def _train_tiny(tmp_path):
    np.random.seed(0)
    mx.random.seed(0)
    X = np.random.randn(64, 6).astype(np.float32)
    y = (X.sum(axis=1) > 0).astype(np.float32)
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(it, num_epoch=3, optimizer_params={"learning_rate": 0.5})
    arg, aux = mod.get_params()
    prefix = str(tmp_path / "model")
    mx.model.save_checkpoint(prefix, 3, net, arg, aux)
    return prefix, X, mod, it


def test_predictor_matches_module(tmp_path):
    prefix, X, mod, it = _train_tiny(tmp_path)
    pred = create_predictor(prefix, 3, {"data": (16, 6),
                                        "softmax_label": (16,)})
    out = pred.predict(X[:16])
    module_out = mod.predict(it, num_batch=1).asnumpy()
    assert np.allclose(out, module_out, atol=1e-5)


def test_predictor_reshape(tmp_path):
    prefix, X, _, _ = _train_tiny(tmp_path)
    pred = create_predictor(prefix, 3, {"data": (16, 6),
                                        "softmax_label": (16,)})
    out16 = pred.predict(X[:16])
    pred.reshape({"data": (4, 6), "softmax_label": (4,)})
    out4 = pred.predict(X[:4])
    assert np.allclose(out16[:4], out4, atol=1e-5)


@pytest.mark.parametrize("dtype", [np.float16, np.float32, np.int32,
                                   np.uint8])
def test_set_input_respects_bound_dtype(dtype):
    """set_input casts to the EXECUTOR's input dtype, not a hardcoded
    float32 (regression: predictor.py once forced np.float32)."""
    sym = mx.sym.Flatten(mx.sym.Variable("data"))
    pred = Predictor(sym.tojson(), {}, {"data": (2, 3)},
                     type_dict={"data": dtype})
    assert pred._exec.arg_dict["data"].dtype == np.dtype(dtype)
    src = np.arange(6, dtype=np.float64).reshape(2, 3)
    pred.set_input("data", src)     # float64 in: must cast, not crash
    pred.forward()
    out = pred.get_output(0)
    assert out.dtype == np.dtype(dtype)
    assert np.array_equal(out, src.astype(dtype))


def test_fp16_params_bind_fp16_program(tmp_path):
    """An fp16 checkpoint serves an fp16 executor end-to-end: params keep
    their stored dtype and the data input defaults to the params' common
    float dtype."""
    prefix, X, _, _ = _train_tiny(tmp_path)
    pred32 = create_predictor(prefix, 3, {"data": (4, 6),
                                          "softmax_label": (4,)})
    ref = pred32.predict(X[:4])
    params16 = {k: v.astype(np.float16)
                for k, v in pred32._arg_params.items()}
    pred16 = Predictor(open("%s-symbol.json" % prefix).read(), params16,
                       {"data": (4, 6), "softmax_label": (4,)})
    assert pred16._exec.arg_dict["data"].dtype == np.float16
    out = pred16.predict(X[:4])
    assert out.dtype == np.float16
    assert np.allclose(out.astype(np.float32), ref, atol=2e-2)


def test_predictor_reshape_reuses_cached_executor(tmp_path):
    """reshape() back to a seen shape set reuses the compiled executor
    (BucketingModule-style per-shape cache) and all cached executors see
    a set_params weight swap."""
    prefix, X, _, _ = _train_tiny(tmp_path)
    pred = create_predictor(prefix, 3, {"data": (16, 6),
                                        "softmax_label": (16,)})
    first = pred._exec
    out16 = pred.predict(X[:16])
    pred.reshape({"data": (4, 6), "softmax_label": (4,)})
    second = pred._exec
    assert second is not first
    pred.reshape({"data": (16, 6), "softmax_label": (16,)})
    assert pred._exec is first, "seen shape must hit the executor cache"
    assert len(pred._exec_cache) == 2
    assert np.allclose(pred.predict(X[:16]), out16, atol=1e-6)
    # weight hot-swap reaches every cached executor
    zeros = {k: mx.nd.zeros(v.shape, dtype=v.dtype)
             for k, v in pred._arg_params.items()}
    pred.set_params(zeros)
    flat16 = pred.predict(X[:16])
    pred.reshape({"data": (4, 6), "softmax_label": (4,)})
    flat4 = pred.predict(X[:4])
    # all-zero weights => uniform softmax from BOTH executors
    assert np.allclose(flat16, flat16[0], atol=1e-6)
    assert np.allclose(flat4, flat16[:4], atol=1e-6)


def test_create_predictor_missing_files(tmp_path):
    prefix, _, _, _ = _train_tiny(tmp_path)
    with pytest.raises(MXNetError, match="symbol file missing"):
        create_predictor(str(tmp_path / "nope"), 3, {"data": (4, 6)})
    # wrong epoch: params missing, existing candidates listed
    with pytest.raises(MXNetError, match="params file missing.*0003"):
        create_predictor(prefix, 99, {"data": (4, 6)})


def test_create_predictor_corrupt_files(tmp_path):
    prefix, _, _, _ = _train_tiny(tmp_path)
    bad = str(tmp_path / "bad")
    with open(bad + "-symbol.json", "w") as f:
        f.write('{"nodes": [truncated')
    with open(bad + "-0003.params", "wb") as f:
        f.write(b"garbage")
    with pytest.raises(MXNetError, match="symbol file corrupt"):
        create_predictor(bad, 3, {"data": (4, 6)})
    import shutil
    shutil.copy("%s-symbol.json" % prefix, bad + "-symbol.json")
    with pytest.raises(MXNetError, match="params file corrupt"):
        create_predictor(bad, 3, {"data": (4, 6)})


def test_engine_naive_mode():
    """NaiveEngine-equivalent sync mode (reference MXNET_ENGINE_TYPE)."""
    from mxnet_tpu import engine
    with engine.naive_mode():
        assert engine.engine().is_naive
        a = mx.nd.ones((4, 4)) * 3
        assert (a.asnumpy() == 3).all()
    assert not engine.engine().is_naive


def test_engine_waitall_and_ordering():
    """Writes to a chunk serialize; wait_for_all drains pending work
    (reference threaded_engine_test.cc semantics)."""
    a = mx.nd.zeros((100, 100))
    for i in range(10):
        a += 1  # each write depends on the previous buffer
    mx.nd.waitall()
    assert (a.asnumpy() == 10).all()
    # read-after-write through a view
    v = a[5:10]
    a *= 2
    assert (v.asnumpy() == 20).all()


def test_profiler_trace(tmp_path):
    """mx.profiler: start/stop produces a trace dir; scope annotates."""
    out = str(tmp_path / "trace")
    mx.profiler.profiler_set_config(filename=out)
    mx.profiler.profiler_set_state("run")
    with mx.profiler.scope("work"):
        (mx.nd.ones((64, 64)) * 2).asnumpy()
    mx.profiler.profiler_set_state("stop")
    assert mx.profiler.state() == "stop"
    import os as _os
    found = []
    for root, _, files in _os.walk(out):
        found += files
    assert found, "no trace files written"
