"""Repo lint: style gate + project-specific static analysis.

Two stages (reference tests/travis/run_test.sh ran pylint + cpplint;
this image ships no linters, so both stages are vendored):

* **style** — python syntax, tabs, trailing whitespace, long lines over
  the whole repo; C++ trailing whitespace / tabs-in-indent.
* **analysis** — the AST rules in ``mxnet_tpu/analysis/linter.py``
  (donated-aliasing, raw-jit, raw-env, raw-time, unseeded-fork-rng,
  raw-future-settle, raw-pallas-call, ... — each distilled from a
  CHANGES.md incident, see docs/analysis.md) over ``mxnet_tpu/``.

Usage::

    python tools/lint.py                    # style (repo) + analysis
    python tools/lint.py mxnet_tpu/serve    # both stages, these paths
    python tools/lint.py --diff HEAD~1      # only files changed since
                                            # rev (fast pre-commit path)
    python tools/lint.py --write-baseline   # grandfather current hits

Known findings live in ``tools/lint_baseline.json`` (override with
``MXNET_LINT_BASELINE`` or ``--baseline``); only NEW findings fail.
Exit 0 clean, 1 with findings listed.

The analysis module is loaded by file path — not ``import mxnet_tpu``
— so the linter runs in milliseconds without initializing jax.
"""
import argparse
import ast
import importlib.util
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_LEN = 100
SKIP_DIRS = {".git", "__pycache__", ".claude", "node_modules",
             ".venv", "venv", "build", "dist", ".eggs"}
DEFAULT_BASELINE = os.path.join(ROOT, "tools", "lint_baseline.json")


def _load_linter():
    path = os.path.join(ROOT, "mxnet_tpu", "analysis", "linter.py")
    spec = importlib.util.spec_from_file_location("_mxtpu_linter", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def py_files(paths):
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
            continue
        for base, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(base, f)


def cc_files(paths):
    exts = (".cc", ".h", ".hpp", ".c")
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(exts):
                yield p
            continue
        for base, dirs, files in os.walk(p):
            dirs[:] = [d for d in dirs if d not in SKIP_DIRS]
            for f in sorted(files):
                if f.endswith(exts):
                    yield os.path.join(base, f)


def style_problems(py_paths, cc_paths):
    problems = []
    for path in py_files(py_paths):
        rel = os.path.relpath(path, ROOT)
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                ast.parse(f.read(), filename=rel)
        except SyntaxError as e:
            problems.append("%s:%s: syntax error: %s"
                            % (rel, e.lineno, e.msg))
            continue
        with open(path, encoding="utf-8", errors="replace") as f:
            for i, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if "\t" in line:
                    problems.append("%s:%d: tab character" % (rel, i))
                if line != line.rstrip():
                    problems.append("%s:%d: trailing whitespace" % (rel, i))
                if len(line) > MAX_LEN:
                    problems.append("%s:%d: line length %d > %d"
                                    % (rel, i, len(line), MAX_LEN))
    for path in cc_files(cc_paths):
        rel = os.path.relpath(path, ROOT)
        with open(path, encoding="utf-8", errors="replace") as f:
            for i, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if line != line.rstrip():
                    problems.append("%s:%d: trailing whitespace" % (rel, i))
                indent = line[:len(line) - len(line.lstrip())]
                if "\t" in indent:
                    problems.append("%s:%d: tab in indentation" % (rel, i))
    return problems


def _default_cc_paths():
    return [os.path.join(ROOT, s)
            for s in ("src", "include", "tests/cpp", "amalgamation",
                      "cpp-package", "example/cpp")
            if os.path.isdir(os.path.join(ROOT, s))]


def _diff_paths(rev):
    """Changed files vs ``rev`` (committed + staged + worktree + new
    untracked files — a brand-new module is exactly what a pre-commit
    lint must see), repo paths that still exist."""
    out = subprocess.run(
        ["git", "diff", "--name-only", rev, "--"],
        cwd=ROOT, capture_output=True, text=True)
    if out.returncode != 0:
        raise SystemExit("lint: git diff %s failed: %s"
                         % (rev, out.stderr.strip()))
    names = out.stdout.splitlines()
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=ROOT, capture_output=True, text=True)
    if untracked.returncode == 0:
        names += untracked.stdout.splitlines()
    paths = []
    for line in sorted(set(names)):
        p = os.path.join(ROOT, line.strip())
        if line.strip() and os.path.exists(p):
            paths.append(p)
    return paths


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: whole repo style "
                    "+ mxnet_tpu/ analysis)")
    ap.add_argument("--diff", metavar="REV",
                    help="lint only files changed since REV")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default tools/lint_baseline.json "
                    "or $MXNET_LINT_BASELINE)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="record current analysis findings as the "
                    "baseline and exit")
    ap.add_argument("--no-style", action="store_true",
                    help="skip the style stage (analysis only)")
    ap.add_argument("--no-analysis", action="store_true",
                    help="skip the analysis stage (style only)")
    args = ap.parse_args(argv)

    if args.diff:
        if args.paths:
            ap.error("--diff and explicit paths are mutually exclusive")
        changed = _diff_paths(args.diff)
        style_paths = changed
        analysis_paths = [p for p in changed
                          if os.path.relpath(p, ROOT)
                          .startswith("mxnet_tpu" + os.sep)
                          and p.endswith(".py")]
        cc_extra = []
    elif args.paths:
        style_paths = [os.path.abspath(p) for p in args.paths]
        analysis_paths = style_paths
        cc_extra = []
    else:
        style_paths = [ROOT]
        analysis_paths = [os.path.join(ROOT, "mxnet_tpu")]
        cc_extra = _default_cc_paths()

    problems = []
    if not args.no_style:
        # the default run keeps the historical shape: python over the
        # whole tree, C++ over the reference source dirs only
        problems += style_problems(style_paths, style_paths + cc_extra
                                   if (args.paths or args.diff)
                                   else cc_extra)

    findings = []
    if not args.no_analysis:
        linter = _load_linter()
        findings = linter.lint_paths(analysis_paths, ROOT)
        baseline_path = (args.baseline
                         or os.environ.get("MXNET_LINT_BASELINE")
                         or DEFAULT_BASELINE)
        if args.write_baseline:
            linter.Baseline(set()).save(baseline_path, findings)
            print("lint: baseline written to %s (%d finding(s) "
                  "grandfathered)" % (os.path.relpath(baseline_path, ROOT),
                                      len(findings)))
            return 0
        baseline = linter.load_baseline(baseline_path)
        findings = baseline.new_findings(findings)

    for p in problems:
        print(p)
    for f in findings:
        print(f)
    total = len(problems) + len(findings)
    print("lint: %d finding(s) (%d style, %d analysis)"
          % (total, len(problems), len(findings)))
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
