"""SVMOutput head instead of softmax (reference example/svm_mnist/
svm_mnist.py capability): hinge-loss (L2-SVM) classifier on MLP features.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=100)
    parser.add_argument("--num-epochs", type=int, default=8)
    parser.add_argument("--use-linear", action="store_true",
                        help="L1-SVM hinge instead of squared hinge")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    net = mx.sym.SVMOutput(net, name="svm", margin=1.0,
                           regularization_coefficient=1.0,
                           use_linear=args.use_linear)

    rng = np.random.RandomState(0)
    w = rng.randn(50, 10).astype(np.float32)
    x = rng.randn(4000, 50).astype(np.float32)
    y = (x @ w).argmax(axis=1).astype(np.float32)
    train = mx.io.NDArrayIter(x, y, batch_size=args.batch_size, shuffle=True,
                              label_name="svm_label")

    mod = mx.mod.Module(net, context=[mx.cpu()], label_names=("svm_label",))
    mod.fit(train, num_epoch=args.num_epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.01, "momentum": 0.9})

    train.reset()
    acc = mx.metric.Accuracy()
    mod.score(train, acc)
    print("svm accuracy: %.3f" % acc.get()[1])
    assert acc.get()[1] > 0.8


if __name__ == "__main__":
    main()
