"""Multichip scaling benchmark leg: Module.fit(mesh=...) + tp-sharded serve.

Measures what ISSUE 7 shipped — the first-class mesh path — as scaling
efficiency against the 1-device fused step, plus the tp-sharded
ServeEngine's closed-loop throughput:

  multichip_scaling_eff_dp8      img/s(dp=8) / (8 x img/s(1 dev)),
                                 weak scaling: per-device batch fixed
  multichip_scaling_eff_dp4tp2   same for the dp=4 x tp=2 mesh with the
                                 conv head tensor-parallel over tp
  multichip_serve_tp_qps         closed-loop QPS of a tp=2-sharded
                                 ServeEngine (8 client threads)
  multichip_backend              'native' when the parent process sees
                                 >= 8 real devices, else 'host_cpu'
                                 (XLA_FLAGS forced 8 host devices — the
                                 tier-1 topology; efficiencies on a
                                 shared-core host measure the GSPMD
                                 path's overhead, not chip scaling)

ISSUE 18 (mxnet_tpu.dist) adds the multi-PROCESS legs — always on the
host-CPU backend (two local processes cannot share one TPU, and the
gloo process-boundary overhead is what the leg measures):

  dist_scaling_eff_2proc         img/s(2 processes x 1 dev, dp=2 mesh
                                 across the process boundary) / img/s
                                 (1 process x 2 forced host devices,
                                 same dp=2 mesh) — the cost of crossing
                                 from XLA-internal collectives to gloo
  dist_host_recovery_s           FleetSupervisor under the dist.host
                                 chaos spec: SIGKILL'd rank ->
                                 checkpoint-commit recovery seconds
  shardsearch_vs_hand_frac       worst-case (CNN, LSTM) ratio of the
                                 sharding="auto" winner's steady step
                                 time over the hand-written PR 7 specs
                                 on a dp=4 x tp=2 mesh; <= 1.05 is the
                                 acceptance bar ("within 5% of hand")

Each datapoint runs in a FRESH subprocess (same pattern as
bench_compile.py): the mesh is a process-level property of the backend,
and forcing the host platform must not poison the parent's real device.
The 2-process leg goes through ``tools/launch.py --launcher local`` —
the exact rendezvous a real fleet uses.
"""
import json
import os
import re
import subprocess
import sys
import time

import numpy as np

PER_DEVICE_BATCH = 16
IMG_SHAPE = (3, 16, 16)
CLASSES = 10
FILTERS = 32
TRAIN_ITERS = 16
TRAIN_WINDOWS = 3
SERVE_THREADS = 8
SERVE_SECONDS = 4.0
SERVE_HIDDEN = 64
DIST_PORT = 9343
FLEET_CHAOS = "points=dist.host@rank1,kinds=crash,after=5,max=1,attempts=0"
SHARD_WINDOWS = 5
SHARD_ITERS = 8


def _cnn():
    import mxnet_tpu as mx
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                             num_filter=FILTERS, name="conv0")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=SERVE_HIDDEN, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _train_child(mesh_spec):
    """One steady-state throughput measurement; prints a json line."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from jax.sharding import PartitionSpec as P

    mesh = None
    sharding = None
    dp = 1
    if mesh_spec:
        from mxnet_tpu.parallel import make_mesh, parse_mesh_spec
        axes = parse_mesh_spec(mesh_spec)
        mesh = make_mesh(axes)
        dp = int(dict(axes)["dp"])
        if "tp" in dict(mesh.shape):
            # tensor-parallel head: fc1 column-parallel over tp
            sharding = {"fc1_weight": P("tp", None), "fc1_bias": P("tp")}
    batch = PER_DEVICE_BATCH * dp

    rng = np.random.RandomState(0)
    X = rng.rand(batch, *IMG_SHAPE).astype(np.float32)
    y = rng.randint(0, CLASSES, batch).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=batch)
    # every leg must run on the SAME backend the mesh legs use: on an
    # accelerator host the 1-device baseline trains on chip 0, not on
    # the host CPU (a CPU baseline would make the efficiency ratio
    # compare TPU against CPU throughput)
    ctx = mx.cpu(0) if jax.default_backend() == "cpu" else mx.tpu(0)
    mod = mx.mod.Module(_cnn(), context=ctx)
    mod.bind(it.provide_data, it.provide_label, mesh=mesh,
             sharding=sharding)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    # pre-stage the batch in the step's input layout (device throughput,
    # not input-pipeline throughput — same convention as bench.py)
    if mod._fused is not None:
        mod._fused_ensure_state()
        sh = mod._fused.batched_sharding()
        staged = mx.io.DataBatch(
            data=[mx.nd.NDArray(jax.device_put(jnp.asarray(X), sh))],
            label=[mx.nd.NDArray(jax.device_put(jnp.asarray(y), sh))])
    else:
        staged = next(iter(it))
    for _ in range(4):
        mod.forward(staged, is_train=True)
        mod.backward()
        mod.update()
    jax.block_until_ready(next(iter(mod._fused_state["params"].values()))
                          if mod._fused_state is not None else 0)
    rates = []
    for _ in range(TRAIN_WINDOWS):
        t0 = time.perf_counter()
        for _ in range(TRAIN_ITERS):
            mod.forward(staged, is_train=True)
            mod.backward()
            mod.update()
        if mod._fused_state is not None:
            jax.block_until_ready(
                next(iter(mod._fused_state["params"].values())))
        rates.append(batch * TRAIN_ITERS / (time.perf_counter() - t0))
    img_s = sorted(rates)[len(rates) // 2]
    print("BENCH_MULTICHIP_CHILD " + json.dumps(
        {"img_s": img_s, "devices": jax.device_count(), "batch": batch}),
        flush=True)


def _serve_child():
    """tp=2-sharded ServeEngine closed-loop QPS; prints a json line."""
    import tempfile
    import threading
    import jax
    import mxnet_tpu as mx
    from jax.sharding import PartitionSpec as P

    net = _cnn()
    rng = np.random.RandomState(0)
    it = mx.io.NDArrayIter(np.zeros((8,) + IMG_SHAPE, np.float32),
                           np.zeros(8, np.float32), batch_size=8)
    mod = mx.mod.Module(net, context=mx.cpu(0))
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Xavier())
    arg, aux = mod.get_params()
    tmp = tempfile.mkdtemp(prefix="bench_mc_")
    prefix = os.path.join(tmp, "model")
    mx.model.save_checkpoint(prefix, 0, net, arg, aux)

    eng = mx.serve.ServeEngine.from_checkpoint(
        prefix, 0,
        input_shapes={"data": (1,) + IMG_SHAPE, "softmax_label": (1,)},
        batch_buckets=(1, 2, 4, 8), mesh="tp=2",
        param_specs={"fc1_weight": P("tp", None), "fc1_bias": P("tp")},
        name="bench_serve_tp")
    xs = rng.rand(64, *IMG_SHAPE).astype(np.float32)
    done = [0]
    stop = threading.Event()
    lock = threading.Lock()

    def client(i):
        j = i
        while not stop.is_set():
            eng.predict(xs[j % len(xs)], timeout=30)
            j += SERVE_THREADS
            with lock:
                done[0] += 1

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(SERVE_THREADS)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(SERVE_SECONDS)
    stop.set()
    for t in threads:
        t.join(timeout=30)
    dt = time.perf_counter() - t0
    eng.close()
    print("BENCH_MULTICHIP_CHILD " + json.dumps(
        {"qps": done[0] / dt, "requests": done[0],
         "devices": jax.device_count()}), flush=True)


def _dist_train_child(ref):
    """One side of the 2-process scaling leg: the SAME dp=2 CNN step,
    either across two launch.py workers (1 host device each, dist_sync
    rendezvous — the gloo path) or in one process over 2 forced host
    devices (the XLA-internal-collectives baseline).  Prints a json
    line with the GLOBAL img/s."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import make_mesh

    global_bs = PER_DEVICE_BATCH * 2
    if ref:
        assert jax.device_count() == 2, \
            "--ref needs XLA_FLAGS=--xla_force_host_platform_device_count=2"
        kv, rank, bs = None, 0, global_bs
    else:
        kv = mx.kv.create("dist_sync")
        rank, bs = kv.rank, PER_DEVICE_BATCH

    rng = np.random.RandomState(0)
    X = rng.rand(bs, *IMG_SHAPE).astype(np.float32)
    y = rng.randint(0, CLASSES, bs).astype(np.float32)
    mod = mx.mod.Module(_cnn(), context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (bs,) + IMG_SHAPE)],
             label_shapes=[("softmax_label", (bs,))])
    mod.init_params(mx.init.Xavier())
    mod.set_mesh(make_mesh([("dp", 2)]))
    mod.init_optimizer(kvstore=kv, optimizer_params={
        "learning_rate": 0.05, "momentum": 0.9})
    assert mod._fused is not None, "fused mesh path did not engage"
    # both sides feed host arrays through the normal DataBatch path:
    # the ratio must charge the input transfer to BOTH legs equally
    batch = mx.io.DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(y)])
    for _ in range(4):
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    jax.block_until_ready(next(iter(mod._fused_state["params"].values())))
    if not ref:
        from jax.experimental import multihost_utils as mhu
        mhu.sync_global_devices("bench_dist_warm")
    rates = []
    for _ in range(TRAIN_WINDOWS):
        t0 = time.perf_counter()
        for _ in range(TRAIN_ITERS):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        jax.block_until_ready(
            next(iter(mod._fused_state["params"].values())))
        rates.append(global_bs * TRAIN_ITERS / (time.perf_counter() - t0))
    img_s = sorted(rates)[len(rates) // 2]
    print("BENCH_MULTICHIP_CHILD " + json.dumps(
        {"img_s": img_s, "rank": rank, "nproc": 1 if ref else 2}),
        flush=True)
    if not ref:
        from jax.experimental import multihost_utils as mhu
        mhu.sync_global_devices("bench_dist_done")


def _fleet_child():
    """FleetSupervisor recovery leg: 2 fleet workers, the dist.host
    chaos spec SIGKILLs rank1 mid-run, and the supervisor's
    commit-watch clocks death-to-recommit seconds."""
    import tempfile
    from mxnet_tpu.dist import FleetSupervisor

    root = os.path.dirname(os.path.abspath(__file__))
    worker = os.path.join(root, "tests", "nightly", "dist_fleet_worker.py")
    ckpt = tempfile.mkdtemp(prefix="bench_fleet_")
    sup = FleetSupervisor(
        [sys.executable, worker, "--ckpt", ckpt],
        nworkers=2, on_loss="rejoin", checkpoint_dir=ckpt,
        timeout_s=240, env={"MXNET_FAULTS": FLEET_CHAOS})
    rc = sup.run()
    doc = sup.stats.report()
    doc["rc"] = rc
    print("BENCH_MULTICHIP_CHILD " + json.dumps(doc), flush=True)


def _shard_cnn(mesh, sharding):
    import mxnet_tpu as mx
    bs = PER_DEVICE_BATCH * 4
    mod = mx.mod.Module(_cnn(), context=mx.cpu(0))
    mod.bind(data_shapes=[("data", (bs,) + IMG_SHAPE)],
             label_shapes=[("softmax_label", (bs,))])
    mod.init_params(mx.init.Xavier())
    mod.set_mesh(mesh, sharding=sharding)
    mod.init_optimizer(optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.rand(bs, *IMG_SHAPE).astype(np.float32))],
        label=[mx.nd.array(rng.randint(0, CLASSES, bs)
                           .astype(np.float32))])
    return mod, batch


def _shard_lstm(mesh, sharding):
    import mxnet_tpu as mx
    from mxnet_tpu.models.lstm import lstm_unroll
    bs, seq, vocab, hidden = 32, 8, 256, 64
    net = lstm_unroll(1, seq, vocab, hidden, hidden, vocab, dropout=0.0)
    data_names = ["data", "l0_init_c", "l0_init_h"]
    data_shapes = [("data", (bs, seq)), ("l0_init_c", (bs, hidden)),
                   ("l0_init_h", (bs, hidden))]
    mod = mx.mod.Module(net, data_names=data_names,
                        label_names=["softmax_label"], context=mx.cpu(0))
    mod.bind(data_shapes, [("softmax_label", (bs, seq))])
    mod.init_params(mx.init.Xavier())
    mod.set_mesh(mesh, sharding=sharding)
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(0)
    batch = mx.io.DataBatch(
        data=[mx.nd.array(rng.randint(0, vocab, (bs, seq))
                          .astype(np.float32)),
              mx.nd.array(np.zeros((bs, hidden), np.float32)),
              mx.nd.array(np.zeros((bs, hidden), np.float32))],
        label=[mx.nd.array(rng.randint(0, vocab, (bs, seq))
                           .astype(np.float32))])
    return mod, batch


def _shard_child(model, mode):
    """Steady step time of MODEL on a dp=4 x tp=2 mesh under either the
    hand-written PR 7 specs or the persisted sharding="auto" winner
    (the search runs before timing starts; only the chosen program is
    measured)."""
    import jax
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh([("dp", 4), ("tp", 2)])
    if mode == "auto":
        sharding = "auto"
    elif model == "cnn":
        sharding = {"fc1_weight": P("tp", None), "fc1_bias": P("tp")}
    else:
        # Megatron-style vocab parallelism: the embedding table and the
        # classifier head split their vocab rows over tp
        sharding = {"embed_weight": P("tp", None),
                    "cls_weight": P("tp", None)}
    mod, batch = (_shard_cnn if model == "cnn" else _shard_lstm)(
        mesh, sharding)
    for _ in range(4):
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    jax.block_until_ready(next(iter(mod._fused_state["params"].values())))
    times = []
    for _ in range(SHARD_WINDOWS):
        t0 = time.perf_counter()
        for _ in range(SHARD_ITERS):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        jax.block_until_ready(
            next(iter(mod._fused_state["params"].values())))
        times.append((time.perf_counter() - t0) / SHARD_ITERS)
    step_ms = sorted(times)[len(times) // 2] * 1e3
    print("BENCH_MULTICHIP_CHILD " + json.dumps(
        {"step_ms": step_ms, "model": model, "mode": mode}), flush=True)


def _child_env(force_host):
    env = dict(os.environ)
    if force_host:
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    return env


def _dist_env(ndev=None):
    """Env for the multi-process legs: always host CPU (two local
    processes cannot share one TPU; the process boundary is the thing
    measured), with an EXACT forced device count when asked — the
    parent's own XLA_FLAGS never leak into a worker that must see 1."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    if ndev:
        env["XLA_FLAGS"] = ("--xla_force_host_platform_device_count=%d"
                            % ndev)
    return env


def _run_child(args, force_host, timeout_s=600, env=None):
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"] + args,
        env=env if env is not None else _child_env(force_host),
        capture_output=True, text=True, timeout=timeout_s)
    if res.returncode != 0:
        raise RuntimeError("bench_multichip child %s failed: %s"
                           % (args, res.stderr[-1200:]))
    for ln in res.stdout.splitlines():
        if ln.startswith("BENCH_MULTICHIP_CHILD "):
            return json.loads(ln.split(" ", 1)[1])
    raise RuntimeError("bench_multichip child %s printed no result: %s"
                       % (args, res.stdout[-800:]))


def _dist_leg():
    """2-process vs 1-process dp=2: the gloo process-boundary tax."""
    root = os.path.dirname(os.path.abspath(__file__))
    args = [sys.executable, os.path.join(root, "tools", "launch.py"),
            "-n", "2", "--launcher", "local", "--port", str(DIST_PORT),
            "%s %s --child dist_train"
            % (sys.executable, os.path.abspath(__file__))]
    res = subprocess.run(args, capture_output=True, text=True,
                         timeout=600, env=_dist_env(), cwd=root)
    if res.returncode != 0:
        raise RuntimeError("dist_train workers failed: %s"
                           % (res.stderr[-1200:] or res.stdout[-1200:]))
    # two ranks share one pipe — match by pattern, not by line
    docs = [json.loads(m) for m in
            re.findall(r"BENCH_MULTICHIP_CHILD (\{[^{}\n]*\})",
                       res.stdout)]
    two = next(d for d in docs if d.get("rank") == 0)
    ref = _run_child(["dist_ref"], True, env=_dist_env(2))
    eff = two["img_s"] / ref["img_s"] if ref["img_s"] else None
    return {"dist_img_s_2proc": round(two["img_s"], 1),
            "dist_img_s_1proc_2dev": round(ref["img_s"], 1),
            "dist_scaling_eff_2proc": round(eff, 4) if eff else None}


def _fleet_leg():
    doc = _run_child(["fleet"], True, env=_dist_env())
    if doc.get("rc") not in (0, None) or not doc.get("restarts"):
        raise RuntimeError("fleet leg did not recover: %r" % doc)
    return {"dist_host_recovery_s": round(float(doc["last_recovery_s"]),
                                          2)}


def _shard_leg(feed):
    """auto-vs-hand specs on the dp=4 x tp=2 mesh; the published frac
    is the WORST model's ratio (<= 1.05 = within 5% of hand)."""
    import tempfile
    store = tempfile.mkdtemp(prefix="bench_shard_store_")
    env8 = _dist_env(8)
    out = {}
    fracs = []
    for model in ("cnn", "lstm"):
        feed("shardsearch-" + model)
        hand = _run_child(["shard", model, "hand"], True, env=dict(env8))
        auto = _run_child(["shard", model, "auto"], True,
                          env=dict(env8, MXNET_AUTOTUNE_DIR=store))
        out["shardsearch_%s_hand_step_ms" % model] = \
            round(hand["step_ms"], 2)
        out["shardsearch_%s_auto_step_ms" % model] = \
            round(auto["step_ms"], 2)
        fracs.append(auto["step_ms"] / hand["step_ms"])
    out["shardsearch_vs_hand_frac"] = round(max(fracs), 4)
    return out


def run(feed=lambda *_: None):
    """Returns the multichip_* metrics dict.  ``feed`` is the watchdog
    heartbeat."""
    import jax
    force_host = jax.device_count() < 8
    backend = "host_cpu" if force_host else "native"

    feed("multichip-1dev")
    try:
        one = _run_child(["train", ""], force_host)
    except Exception as e:
        if force_host:
            raise
        # a backend that admits ONE process (local libtpu exclusivity —
        # the parent bench already holds the chips) kills every child at
        # init; fall back to the forced-host topology rather than
        # silently emitting no multichip metrics at all
        sys.stderr.write("bench_multichip: native children failed (%s); "
                         "falling back to 8 forced host-CPU devices\n"
                         % str(e)[-300:])
        force_host = True
        backend = "host_cpu_fallback"
        one = _run_child(["train", ""], force_host)
    feed("multichip-dp8")
    dp8 = _run_child(["train", "dp=8"], force_host)
    feed("multichip-dp4tp2")
    dp4tp2 = _run_child(["train", "dp=4,tp=2"], force_host)
    feed("multichip-serve-tp")
    serve = _run_child(["serve"], force_host)

    base = one["img_s"]
    out = {
        "multichip_backend": backend,
        "multichip_img_s_1dev": round(base, 1),
        "multichip_img_s_dp8": round(dp8["img_s"], 1),
        "multichip_img_s_dp4tp2": round(dp4tp2["img_s"], 1),
        "multichip_scaling_eff_dp8": round(dp8["img_s"] / (8 * base), 4)
        if base else None,
        "multichip_scaling_eff_dp4tp2": round(
            dp4tp2["img_s"] / (8 * base), 4) if base else None,
        "multichip_serve_tp_qps": round(serve["qps"], 1),
        # the acceptance key names it serve_tp_qps; publish both
        "serve_tp_qps": round(serve["qps"], 1),
    }
    # ISSUE 18 multi-process legs — guarded individually: a flaky
    # rendezvous must not take the in-process metrics down with it (the
    # gate's MISSING row still flags the lost leg)
    for name, leg in (("dist-2proc", _dist_leg),
                      ("dist-fleet", _fleet_leg),
                      ("shardsearch", lambda: _shard_leg(feed))):
        feed(name)
        try:
            out.update(leg())
        except Exception as e:
            sys.stderr.write("bench_multichip: %s leg failed (%s)\n"
                             % (name, str(e)[-400:]))
    return out


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        if sys.argv[2] == "train":
            _train_child(sys.argv[3] if len(sys.argv) > 3 else "")
        elif sys.argv[2] == "dist_train":
            _dist_train_child(ref=False)
        elif sys.argv[2] == "dist_ref":
            _dist_train_child(ref=True)
        elif sys.argv[2] == "fleet":
            _fleet_child()
        elif sys.argv[2] == "shard":
            _shard_child(sys.argv[3], sys.argv[4])
        else:
            _serve_child()
        return
    print(json.dumps(run()), flush=True)


if __name__ == "__main__":
    main()
