"""Quantize/dequantize insertion: rewrite matmul/conv subgraphs to int8
(fp16 where int8 is unsupported) with calibration-baked scales.

The rewrite, per eligible ``FullyConnected``/``Convolution`` node::

    x (f32) ──> _contrib_quantize(scale=s_x) ──> int8 ─┐
    W (f32 param)  ── pre-quantized host-side ── int8 ─┤──> _quantized_*  ──> f32
    W_wscale (new f32 param, per-out-channel)  ────────┤     (int32 MXU
    bias (f32 param, untouched) ───────────────────────┘      accumulate,
                                                              fused dequant)

* Activation scales come from a :class:`CalibrationTable` (recorded on
  the f32 graph by ``passes.calibrate``); weight scales are computed
  here, per output channel, and baked into the param blob as a small
  f32 vector — the json stays graph-shaped, hot reload re-quantizes.
* One ``_contrib_quantize`` node is inserted per (tensor, scale): two
  consumers of one activation share the q node.
* Nodes whose op is not int8-eligible on this backend fall back to
  fp16 (``Cast`` sandwich + fp16 params) when a fallback dtype is
  configured; otherwise they stay f32.  On CPU hosts the measured
  reality is inverted — XLA's int8 GEMM wins 2-7x but int8 conv and
  fp16-anything LOSE badly (docs/quantize.md) — so the defaults are
  platform-aware: CPU quantizes the matmul family only and leaves the
  fallback off.
* The OUTPUT layer (a matmul with no matmul downstream) is skipped by
  default: quantization noise on logits flips top-1 answers; hidden
  layers are where the weight bytes live anyway.

Env knobs (all overridable per-pass):

* ``MXNET_QUANTIZE_OPS``       comma list of int8-eligible op names
  (default: FullyConnected,Convolution on TPU; FullyConnected on CPU)
* ``MXNET_QUANTIZE_FALLBACK``  dtype for non-int8-eligible targets:
  ``float16``/``bfloat16``/``float32``=leave (default: float16 on TPU,
  float32 on CPU)
* ``MXNET_QUANTIZE_CALIB_MODE``/``MXNET_QUANTIZE_PERCENTILE``/
  ``MXNET_QUANTIZE_CALIB_BATCHES``  calibration defaults
* ``MXNET_QUANTIZE_SKIP``      comma list of node-name substrings to
  never rewrite
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError, get_env, _AttrDict
from ..ops import get_op
from ..ops.quantized import quantize_array
from ..symbol import Symbol, _Node, _topo
from .calibrate import CalibrationTable, calibrate_arrays
from .graph_passes import (CSEPass, DeadNodeEliminationPass,
                           FoldConstantsPass, U8WirePass, _make_node,
                           rebuild, tensor_name)
from .pipeline import Pass, PassError, PassPipeline, _as_np

__all__ = ["QuantizePass", "default_inference_pipeline",
           "build_serving_pipeline", "quantize_model",
           "default_quantize_ops", "default_fallback_dtype"]

# ops the rewrite understands at all (the matmul/conv family)
_TARGET_OPS = ("FullyConnected", "Convolution")
# Convolution params the quantized op does not carry
_DROP_CONV_PARAMS = ("workspace", "cudnn_tune", "cudnn_off")


def _platform() -> str:
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def default_quantize_ops() -> Tuple[str, ...]:
    """int8-eligible ops for this backend.  The MXU takes int8 convs;
    XLA:CPU lowers int8 conv to a scalar loop that measures ~30x slower
    than f32 (docs/quantize.md), so CPU defaults to the GEMM family."""
    env = get_env("MXNET_QUANTIZE_OPS", "", str)
    if env:
        return tuple(x for x in env.split(",") if x)
    if _platform() == "cpu":
        return ("FullyConnected",)
    return ("FullyConnected", "Convolution")


def default_fallback_dtype() -> Optional[str]:
    """Precision for targets int8 cannot take: float16 on accelerators;
    None on CPU, where fp16 is emulated (measured 4-80x SLOWER) and the
    honest fallback is staying f32."""
    env = get_env("MXNET_QUANTIZE_FALLBACK", "", str)
    if env:
        return None if env in ("float32", "off", "none") else env
    return None if _platform() == "cpu" else "float16"


class QuantizePass(Pass):
    """The q/dq insertion pass (see module docstring).

    Parameters
    ----------
    calib : CalibrationTable, optional
        Activation ranges.  When absent, ``calib_data`` must be given
        and the pass self-calibrates on the graph it is applied to
        (so upstream passes — u8 wire, folds — are already in effect).
    calib_data : ndarray or list of feed dicts, optional
        Feed sample in wire format: an array of items batched into
        ``calib_shapes``'s data shape, or explicit feed dicts.
    calib_shapes : dict name -> shape, optional
        Bind shapes for self-calibration (batch dim included).
    ops / fallback_dtype / skip / per_channel / skip_output_layer :
        See module docstring; defaults are platform/env-aware.
    """

    name = "quantize"

    def __init__(self, calib: Optional[CalibrationTable] = None, *,
                 calib_data=None, calib_shapes=None,
                 data_name: str = "data",
                 num_batches: Optional[int] = None,
                 mode: Optional[str] = None,
                 percentile: Optional[float] = None,
                 ops: Optional[Sequence[str]] = None,
                 fallback_dtype: Optional[str] = "auto",
                 skip: Sequence[str] = (),
                 skip_output_layer: bool = True,
                 per_channel: bool = True,
                 ctx=None):
        super().__init__()
        self.calib = calib
        self.calib_data = calib_data
        self.calib_shapes = dict(calib_shapes or {})
        self.data_name = data_name
        self.num_batches = num_batches if num_batches is not None else \
            get_env("MXNET_QUANTIZE_CALIB_BATCHES", 10, int)
        self.mode = mode or get_env("MXNET_QUANTIZE_CALIB_MODE",
                                    "percentile", str)
        self.percentile = percentile if percentile is not None else \
            get_env("MXNET_QUANTIZE_PERCENTILE", 99.99, float)
        self.ops = tuple(ops) if ops is not None else default_quantize_ops()
        self.fallback_dtype = default_fallback_dtype() \
            if fallback_dtype == "auto" else fallback_dtype
        env_skip = get_env("MXNET_QUANTIZE_SKIP", "", str)
        self.skip = tuple(skip) + tuple(x for x in env_skip.split(",") if x)
        self.skip_output_layer = skip_output_layer
        self.per_channel = per_channel
        self.ctx = ctx
        # weight-transform records for hot reload:
        # [(wname, wscale_name, axis)] int8; [(pname, dtype)] casts
        self._w_quant: List[Tuple[str, str, Optional[int]]] = []
        self._p_cast: List[Tuple[str, str]] = []

    def config(self) -> str:
        return ";".join([
            "calib=%s" % (self.calib.digest() if self.calib else "-"),
            "ops=%s" % ",".join(self.ops),
            "fallback=%s" % (self.fallback_dtype or "-"),
            "skip=%s" % ",".join(self.skip),
            "skip_output=%s" % self.skip_output_layer,
            "per_channel=%s" % self.per_channel,
            "mode=%s;pct=%r;batches=%d" % (self.mode, self.percentile,
                                           self.num_batches),
        ])

    # -- calibration --------------------------------------------------------
    def _feeds(self) -> List[Dict[str, np.ndarray]]:
        data = self.calib_data
        if isinstance(data, (list, tuple)) and data and \
                isinstance(data[0], dict):
            return list(data)
        arr = _as_np(data)
        shape = self.calib_shapes.get(self.data_name)
        if shape is None:
            raise PassError("quantize: calib_shapes must name %r when "
                            "calib_data is an array" % self.data_name)
        b = int(shape[0])
        n = (arr.shape[0] // b) * b
        if n == 0:
            raise PassError(
                "quantize: calib_data has %d items, need >= one batch of "
                "%d" % (arr.shape[0], b))
        feeds = []
        for i in range(0, min(n, b * self.num_batches), b):
            feeds.append({self.data_name:
                          arr[i:i + b].reshape((b,) + tuple(shape[1:]))})
        return feeds

    def _ensure_calib(self, sym: Symbol, params: Dict) -> None:
        if self.calib is not None or self.calib_data is None:
            return
        # params is the MERGED arg+aux blob (the Predictor/ServeEngine
        # contract); pass it as both — copy_params_from filters by name,
        # and dropping aux here would calibrate BatchNorm models on
        # default moving stats instead of the trained ones
        self.calib = calibrate_arrays(
            sym, self._feeds(), arg_params=params, aux_params=params,
            mode=self.mode, percentile=self.percentile, ctx=self.ctx,
            default_shapes=self.calib_shapes)

    # -- eligibility --------------------------------------------------------
    def _skippable(self, name: str) -> bool:
        return any(s and s in name for s in self.skip)

    @staticmethod
    def _output_layers(sym: Symbol) -> set:
        """ids of target nodes with NO target node downstream — the
        logits layer(s), skipped by default (argmax fidelity)."""
        downstream_has_target: Dict[int, bool] = {}
        consumers: Dict[int, List[_Node]] = {}
        topo = _topo(sym._heads)
        for n in topo:
            for (i, _x) in n.inputs:
                consumers.setdefault(id(i), []).append(n)

        def walk(node) -> bool:
            key = id(node)
            if key in downstream_has_target:
                return downstream_has_target[key]
            downstream_has_target[key] = False      # cycle guard
            found = False
            for c in consumers.get(key, ()):
                if (not c.is_variable and c.op.name in _TARGET_OPS) \
                        or walk(c):
                    found = True
                    break
            downstream_has_target[key] = found
            return found

        return {id(n) for n in topo
                if not n.is_variable and n.op.name in _TARGET_OPS
                and not walk(n)}

    def _int8_eligible(self, node: _Node) -> bool:
        if node.op.name not in self.ops:
            return False
        if node.op.name == "Convolution" and (
                node.params.get("num_group") or 1) != 1:
            return False
        return True

    # -- the rewrite --------------------------------------------------------
    def apply(self, sym, params):
        if params is None:
            raise PassError("quantize needs the parameter blob (weights "
                            "are pre-quantized host-side)")
        self._ensure_calib(sym, params)
        new_params = dict(params)
        self._w_quant, self._p_cast = [], []
        output_layers = self._output_layers(sym) if self.skip_output_layer \
            else set()
        # weight vars consumed by >1 node cannot be retyped safely
        var_consumers: Dict[str, int] = {}
        for n in _topo(sym._heads):
            for (i, _x) in n.inputs:
                if i.is_variable:
                    var_consumers[i.name] = var_consumers.get(i.name, 0) + 1
        q_cache: Dict[Tuple[int, int, float], Tuple[_Node, int]] = {}
        quantized: List[str] = []
        fp16ed: List[str] = []
        q_nodes = 0

        def q_insert(src: Tuple[_Node, int], scale: float, label: str):
            nonlocal q_nodes
            key = (id(src[0]), src[1], scale)
            hit = q_cache.get(key)
            if hit is not None:
                return hit
            node = _make_node("_contrib_quantize", "%s_quantize" % label,
                              {"scale": scale}, [src])
            q_cache[key] = (node, 0)
            q_nodes += 1
            return (node, 0)

        def try_int8(node, new_inputs):
            src_node, src_idx = node.inputs[0]
            in_name = tensor_name(src_node, src_idx)
            s_in = self.calib.scale(in_name) if self.calib else None
            if s_in is None:
                return None
            wvar = node.inputs[1][0]
            wname = wvar.name
            w = _as_np(new_params[wname])
            if w.dtype != np.float32 and w.dtype != np.float64:
                return None                       # already transformed?
            axis = 0 if self.per_channel else None
            wq, wscale = quantize_array(w, axis=axis)
            wscale_vec = np.broadcast_to(
                np.asarray(wscale, np.float32).reshape(-1),
                (w.shape[0],)).copy()
            new_params[wname] = wq
            wsname = "%s_wscale" % wname
            new_params[wsname] = wscale_vec
            self._w_quant.append((wname, wsname, axis))
            p = {k: v for k, v in node.op.serialize_params(node.params)
                 .items() if k not in _DROP_CONV_PARAMS}
            p["scale_data"] = s_in
            qdata = q_insert(new_inputs[0], s_in, in_name)
            wsvar = _Node(None, wsname, attrs={})
            new_wvar = _Node(None, wname, attrs=dict(wvar.attrs))
            inputs = [qdata, (new_wvar, 0), (wsvar, 0)]
            if not node.params.get("no_bias"):
                inputs.append(new_inputs[2])
            qnode = _make_node("_quantized_%s" % node.op.name, node.name,
                               p, inputs, node.attrs)
            quantized.append(node.name)
            return [(qnode, 0)]

        def try_fp16(node, new_inputs):
            dt = self.fallback_dtype
            cast_in = _make_node("Cast", "%s_%scast" % (node.name, dt[:3]),
                                 {"dtype": dt}, [new_inputs[0]])
            inputs = [(cast_in, 0)] + list(new_inputs[1:])
            for (pv, _x) in node.inputs[1:]:
                if not (pv.is_variable and pv.name in new_params):
                    return None
            for (pv, _x) in node.inputs[1:]:
                arr = _as_np(new_params[pv.name])
                if arr.dtype.kind == "f" and str(arr.dtype) != dt:
                    import jax.numpy as jnp
                    new_params[pv.name] = np.asarray(
                        jnp.asarray(arr).astype(dt))
                    self._p_cast.append((pv.name, dt))
            body = _Node(node.op, node.name, _AttrDict(node.params),
                         dict(node.attrs), inputs, node.is_aux)
            out = _make_node("Cast", "%s_f32cast" % node.name,
                             {"dtype": "float32"}, [(body, 0)])
            fp16ed.append(node.name)
            return [(out, 0)]

        def transform(node, new_inputs):
            if node.is_variable or node.op.name not in _TARGET_OPS:
                return None
            if self._skippable(node.name) or id(node) in output_layers:
                return None
            wvar = node.inputs[1][0]
            if not (wvar.is_variable and wvar.name in new_params
                    and var_consumers.get(wvar.name, 0) == 1):
                return None                  # shared/missing weight: leave
            if self._int8_eligible(node):
                res = try_int8(node, new_inputs)
                if res is not None:
                    return res
            if self.fallback_dtype:
                return try_fp16(node, new_inputs)
            return None

        out = rebuild(sym, transform)
        self.summary = {
            "rewrites": len(quantized) + len(fp16ed),
            "int8_nodes": quantized, "fp16_nodes": fp16ed,
            "q_nodes_inserted": q_nodes,
            "calib_tensors": len(self.calib) if self.calib else 0,
            "calib_digest": self.calib.digest() if self.calib else None,
        }
        return out, new_params

    def transform_params(self, params):
        """Hot reload: re-quantize fresh f32 weights into the already-
        rewritten graph's int8 + wscale convention, re-cast fp16 params.
        Weights already at their target dtype pass through."""
        out = dict(params)
        for wname, wsname, axis in self._w_quant:
            if wname not in out:
                continue
            w = _as_np(out[wname])
            if w.dtype == np.int8:
                continue
            wq, wscale = quantize_array(w, axis=axis)
            out[wname] = wq
            out[wsname] = np.broadcast_to(
                np.asarray(wscale, np.float32).reshape(-1),
                (w.shape[0],)).copy()
        for pname, dt in self._p_cast:
            if pname in out:
                arr = _as_np(out[pname])
                if arr.dtype.kind == "f" and str(arr.dtype) != dt:
                    import jax.numpy as jnp
                    out[pname] = np.asarray(jnp.asarray(arr).astype(dt))
        return out


# -- pipeline builders -------------------------------------------------------

def default_inference_pipeline(quantize: Optional[QuantizePass] = None,
                               u8_wire: Optional[U8WirePass] = None,
                               fuse=None,
                               name: str = "inference",
                               verify: bool = True,
                               embed_dedup=None,
                               moe_exact=None) -> PassPipeline:
    """The serving pipeline: [u8 wire] -> fold -> cse -> dce ->
    [quantize] -> [fuse].  Order matters: the u8 prologue must exist
    before calibration sees the graph; folds/CSE/DCE shrink what
    calibration and quantization must visit; fusion runs LAST so the
    int8 epilogues exist to fuse (the pipeline enforces this ordering
    — see ``passes.fuse``).  ``fuse``: falsy = off (the default here;
    ``build_serving_pipeline`` defaults it on via ``MXNET_FUSE``), True
    or a dict of FuseEpiloguePass kwargs + ``elemwise``."""
    from .fuse import fusion_passes
    passes: List[Pass] = []
    if u8_wire is not None:
        passes.append(u8_wire)
    passes += [FoldConstantsPass(), CSEPass(), DeadNodeEliminationPass()]
    if quantize is not None:
        passes.append(quantize)
    if moe_exact is None:
        from .moe import default_moe_exact
        moe_exact = default_moe_exact()
    if moe_exact:
        # no-op on MoE-free graphs; on routed graphs, pin serve-time
        # capacity to no-drop so responses don't depend on batch
        # composition (see passes.moe).  Before fusion: it only edits
        # _moe_dispatch attrs, and fusion must stay last.
        from .moe import MoEServeParityPass
        passes.append(MoEServeParityPass())
    passes += fusion_passes(fuse)
    if embed_dedup:
        from .embed import SparseEmbedPass
        passes.append(SparseEmbedPass(
            None if embed_dedup is True
            else int(embed_dedup)))
    return PassPipeline(passes, name=name, verify=verify)


def build_serving_pipeline(quantize=None, calib_data=None, calib_shapes=None,
                           data_name: str = "data", u8_wire=None,
                           fuse=None, name: str = "serve",
                           ctx=None, embed_dedup=None) -> PassPipeline:
    """ServeEngine's pipeline factory.

    ``quantize``: falsy = off; ``"int8"``/``"float16"``/``"bfloat16"``;
    or a dict of QuantizePass kwargs (plus optional ``"dtype"``).  int8
    needs ``calib_data`` (a sample of requests in WIRE format — u8 HWC
    items when ``u8_wire`` is on) or an explicit ``calib=`` table in the
    dict.  ``u8_wire``: falsy = off; True or a dict with
    ``mean``/``scale``/``hwc``.  ``fuse``: None = the ``MXNET_FUSE``
    default (on); False = off; True/dict = fusion passes appended after
    quantization (see ``passes.fuse``).  ``embed_dedup``: None = the
    ``MXNET_EMBED_DEDUP`` default (off); True/int = rewrite Embedding
    lookups to the deduped ``_sparse_embedding`` op (an int sets the
    traced unique cap — see ``passes.embed``).
    """
    from .embed import default_embed_dedup
    from .fuse import default_fuse
    if fuse is None:
        fuse = default_fuse()
    if embed_dedup is None:
        embed_dedup = default_embed_dedup()
    u8_pass = None
    if u8_wire:
        kw = dict(u8_wire) if isinstance(u8_wire, dict) else {}
        u8_pass = U8WirePass(data_name=data_name, **kw)
    q_pass = None
    if quantize:
        kw = dict(quantize) if isinstance(quantize, dict) else {}
        dtype = kw.pop("dtype", quantize if isinstance(quantize, str)
                       else "int8")
        if dtype in ("float16", "bfloat16"):
            # pure precision rewrite: every target op goes to the
            # fallback dtype, no calibration involved — a calib_data
            # passed alongside is NOT forwarded (self-calibration would
            # burn bind+forward time on a table no node consults and
            # perturb the pipeline fingerprint for nothing)
            kw.setdefault("ops", ())
            kw.setdefault("fallback_dtype", dtype)
        elif dtype != "int8":
            raise MXNetError("quantize dtype must be int8|float16|bfloat16, "
                             "got %r" % (dtype,))
        kw.setdefault("data_name", data_name)
        if dtype == "int8":
            if calib_data is not None:
                kw.setdefault("calib_data", calib_data)
            if calib_shapes is not None:
                kw.setdefault("calib_shapes", calib_shapes)
            if kw.get("calib") is None and kw.get("calib_data") is None:
                raise MXNetError(
                    "quantize='int8' needs calibration: pass calib_data= "
                    "(a sample of requests) or quantize={'calib': table}")
        q_pass = QuantizePass(**kw)
        q_pass.ctx = ctx if q_pass.ctx is None else q_pass.ctx
    return default_inference_pipeline(quantize=q_pass, u8_wire=u8_pass,
                                      fuse=fuse, name=name,
                                      embed_dedup=embed_dedup)


def quantize_model(sym: Symbol, arg_params: Dict, aux_params: Dict,
                   calib_data=None, calib_shapes=None, **kwargs):
    """One-call offline flow (the upstream ``quantize_model`` shape):
    -> (qsym, qarg_params, qaux_params, pipeline).  ``kwargs`` go to
    QuantizePass."""
    pipe = default_inference_pipeline(
        quantize=QuantizePass(calib_data=calib_data,
                              calib_shapes=calib_shapes, **kwargs),
        name="quantize_model")
    params = dict(arg_params)
    params.update(aux_params or {})
    qsym, qparams = pipe.run(sym, params)
    aux_names = set(qsym.list_auxiliary_states())
    qarg = {k: v for k, v in qparams.items() if k not in aux_names}
    qaux = {k: v for k, v in qparams.items() if k in aux_names}
    return qsym, qarg, qaux, pipe
