"""DCGAN generator/discriminator (reference example/gan capability;
Radford et al. 2015).  Fresh implementation on the symbol API."""
from .. import symbol as sym


def make_generator(ngf=64, nc=3, code_dim=100, fix_gamma=True, eps=1e-5 + 1e-12):
    """z (N, code_dim, 1, 1) -> image (N, nc, 64, 64)."""
    rand = sym.Variable("rand")
    g1 = sym.Deconvolution(rand, name="g1", kernel=(4, 4), num_filter=ngf * 8,
                           no_bias=True)
    gbn1 = sym.BatchNorm(g1, name="gbn1", fix_gamma=fix_gamma, eps=eps)
    gact1 = sym.Activation(gbn1, name="gact1", act_type="relu")
    g2 = sym.Deconvolution(gact1, name="g2", kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ngf * 4, no_bias=True)
    gbn2 = sym.BatchNorm(g2, name="gbn2", fix_gamma=fix_gamma, eps=eps)
    gact2 = sym.Activation(gbn2, name="gact2", act_type="relu")
    g3 = sym.Deconvolution(gact2, name="g3", kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ngf * 2, no_bias=True)
    gbn3 = sym.BatchNorm(g3, name="gbn3", fix_gamma=fix_gamma, eps=eps)
    gact3 = sym.Activation(gbn3, name="gact3", act_type="relu")
    g4 = sym.Deconvolution(gact3, name="g4", kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=ngf, no_bias=True)
    gbn4 = sym.BatchNorm(g4, name="gbn4", fix_gamma=fix_gamma, eps=eps)
    gact4 = sym.Activation(gbn4, name="gact4", act_type="relu")
    g5 = sym.Deconvolution(gact4, name="g5", kernel=(4, 4), stride=(2, 2),
                           pad=(1, 1), num_filter=nc, no_bias=True)
    return sym.Activation(g5, name="gact5", act_type="tanh")


def make_discriminator(ndf=64, fix_gamma=True, eps=1e-5 + 1e-12):
    """image (N, nc, 64, 64) -> logistic real/fake."""
    data = sym.Variable("data")
    label = sym.Variable("label")
    d1 = sym.Convolution(data, name="d1", kernel=(4, 4), stride=(2, 2),
                         pad=(1, 1), num_filter=ndf, no_bias=True)
    dact1 = sym.LeakyReLU(d1, name="dact1", act_type="leaky", slope=0.2)
    d2 = sym.Convolution(dact1, name="d2", kernel=(4, 4), stride=(2, 2),
                         pad=(1, 1), num_filter=ndf * 2, no_bias=True)
    dbn2 = sym.BatchNorm(d2, name="dbn2", fix_gamma=fix_gamma, eps=eps)
    dact2 = sym.LeakyReLU(dbn2, name="dact2", act_type="leaky", slope=0.2)
    d3 = sym.Convolution(dact2, name="d3", kernel=(4, 4), stride=(2, 2),
                         pad=(1, 1), num_filter=ndf * 4, no_bias=True)
    dbn3 = sym.BatchNorm(d3, name="dbn3", fix_gamma=fix_gamma, eps=eps)
    dact3 = sym.LeakyReLU(dbn3, name="dact3", act_type="leaky", slope=0.2)
    d4 = sym.Convolution(dact3, name="d4", kernel=(4, 4), stride=(2, 2),
                         pad=(1, 1), num_filter=ndf * 8, no_bias=True)
    dbn4 = sym.BatchNorm(d4, name="dbn4", fix_gamma=fix_gamma, eps=eps)
    dact4 = sym.LeakyReLU(dbn4, name="dact4", act_type="leaky", slope=0.2)
    d5 = sym.Convolution(dact4, name="d5", kernel=(4, 4), num_filter=1,
                         no_bias=True)
    d5 = sym.Flatten(d5)
    return sym.LogisticRegressionOutput(data=d5, label=label, name="dloss")
