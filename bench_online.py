"""Online-loop benchmark leg (ISSUE 17): how fresh can the model be?

The continuous-training promise is a latency promise: traffic served
NOW shapes the weights serving soon.  Four numbers, gated by
tools/bench_gate.py:

  online_freshness_s            wall seconds from the last captured
                                request to the retrained weights
                                serving live — capture flush, fine-tune
                                round, gate decision and the zero-drop
                                rolling promotion, end to end
  online_freshness_chaos_s      the same loop re-measured with an
                                absorbable fault plan armed (errored
                                dispatches the router's retry budget
                                eats) — the freshness cost of riding
                                through faults
  online_promote_dropped        requests lost by a closed-loop flood
                                running THROUGH the promotion
                                (ZERO_FLOOR: rolling_restart drains,
                                nothing may drop)
  online_capture_overhead_frac  fractional cost of the capture seam on
                                router flood throughput, sampling
                                enabled vs no capture at all
                                (ABS_CEILING 0.02: capture must stay
                                invisible to serving)
"""
import json
import os
import shutil
import statistics
import sys
import tempfile
import threading
import time

import numpy as np

ROOT = os.path.dirname(os.path.abspath(__file__))

_IN, _CLASSES = 16, 4


def _net():
    import mxnet_tpu as mx
    return mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"),
                              num_hidden=_CLASSES, name="fc"),
        name="softmax")


def _params(seed=3):
    rng = np.random.RandomState(seed)
    return {"fc_weight": rng.randn(_CLASSES, _IN).astype(np.float32) * 0.1,
            "fc_bias": np.zeros(_CLASSES, np.float32)}


def _factory(net, params, name):
    from mxnet_tpu.serve import ServeEngine

    def factory(i):
        return ServeEngine(net, dict(params), {"data": (8, _IN)},
                           max_delay_ms=1.0, name="%s-rep%d" % (name, i),
                           warmup=False)
    return factory


def _flood(router, X, requests, window=16):
    """Closed-loop windowed flood; -> (elapsed_s, dropped)."""
    dropped = 0
    inflight = []
    t0 = time.perf_counter()
    for i in range(requests):
        inflight.append(router.submit(X[i % len(X)]))
        if len(inflight) >= window:
            try:
                inflight.pop(0).result(timeout=120)
            except Exception:
                dropped += 1
    for f in inflight:
        try:
            f.result(timeout=120)
        except Exception:
            dropped += 1
    return time.perf_counter() - t0, dropped


def capture_overhead_leg(requests=300, repeats=9, feed=lambda *_: None):
    """online_capture_overhead_frac: the serve-path price of sampling.

    Same windowed flood, capture off vs capture on (sample 0.25, large
    shards so the spill cost amortizes the way production capture
    does).  The two routers live side by side and the trials
    INTERLEAVE (off, on, off, on, ...) so machine drift lands on both
    sides equally, and the metric is the MEDIAN of the per-pair
    fractions ``(on_i - off_i) / off_i`` — pairing cancels the drift
    each adjacent trial shares, and the median throws away the
    scheduler-outlier pairs a mean (or a min-of-N) would gate on.
    What survives is the systematic cost, which is what the ceiling
    is about."""
    from mxnet_tpu import online, serve
    out = {}
    net, params = _net(), _params()
    rng = np.random.RandomState(0)
    X = rng.randn(64, _IN).astype(np.float32)
    tmp = tempfile.mkdtemp(prefix="bench-online-cap-")
    feed("online-capture-overhead")
    try:
        writer = online.CaptureWriter(
            os.path.join(tmp, "cap"), sample=0.25, shard_items=4096,
            fresh=True, transform=lambda d, o: (d, np.argmax(o)))
        plain = serve.ServeRouter(_factory(net, params, "cap-off"),
                                  replicas=2, name="bench-cap-off")
        capped = serve.ServeRouter(_factory(net, params, "cap-on"),
                                   replicas=2, capture=writer,
                                   name="bench-cap-on")
        try:
            _flood(plain, X, requests)                 # warm both
            _flood(capped, X, requests)
            t_off, t_on = [], []
            for _ in range(repeats):
                t_off.append(_flood(plain, X, requests)[0])
                t_on.append(_flood(capped, X, requests)[0])
            capped.capture_sync(timeout=60)
            rep = capped.stats.report()
            assert rep["capture_errors"] == 0, rep
        finally:
            plain.close()
            capped.close()
        writer.flush()
        fracs = [(on - off) / off for on, off in zip(t_on, t_off)]
        out["online_capture_overhead_frac"] = round(
            max(0.0, statistics.median(fracs)), 4)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return out


def _freshness_once(chaos, feed):
    """One full loop: flood+capture -> fine-tune -> gate -> promote
    with traffic running through the swap.  -> (freshness_s, dropped)."""
    import mxnet_tpu as mx
    from mxnet_tpu import faults, online, serve
    net, params = _net(), _params()
    rng = np.random.RandomState(1)
    X = rng.randn(128, _IN).astype(np.float32)
    y = rng.randint(0, _CLASSES, 128)
    tmp = tempfile.mkdtemp(prefix="bench-online-fresh-")
    try:
        cap_dir = os.path.join(tmp, "cap")
        ck_dir = os.path.join(tmp, "ck")
        writer = online.CaptureWriter(
            cap_dir, sample=0.5, shard_items=32, fresh=True,
            transform=lambda d, o: (d, np.argmax(o)))
        # 3 replicas + a deep retry budget + fast probes: during a
        # rolling restart one replica is draining, and the chaos plan
        # must not be able to trip the breaker on BOTH others at once
        router = serve.ServeRouter(_factory(net, params, "fresh"),
                                   replicas=3, capture=writer,
                                   unhealthy_after=8, retries=8,
                                   probe_after_s=0.02,
                                   name="bench-fresh")
        if chaos:
            # absorbable: errored dispatches the retry budget eats —
            # the loop must stay zero-drop, only slower
            faults.install(
                "seed=29,rate=0.03,kinds=error,points=serve.dispatch")
        try:
            _t, dropped_flood = _flood(router, X, 192)
            t0 = time.perf_counter()            # last request served
            router.capture_sync(timeout=120)
            writer.flush()
            trainer = online.OnlineTrainer(
                net, cap_dir, ck_dir, batch_size=16,
                optimizer_params=(("learning_rate", 0.05),),
                arg_params={k: mx.nd.array(v) for k, v in params.items()},
                checkpoint_every=2, name="bench-online-trainer")
            cand = trainer.round(num_epoch=1)
            live = np.stack([router.predict(X[i], timeout=60)
                             for i in range(32)])
            # candidate scoring is offline (no router, no retry budget
            # to absorb injected dispatch faults) — the chaos plan
            # covers the serving plane, so it steps aside here
            if chaos:
                faults.clear()
            eng = serve.ServeEngine.from_checkpoint_dir(
                ck_dir, net, {"data": (8, _IN)}, warmup=False,
                name="bench-fresh-cand")
            try:
                cand_scores = np.stack([eng.predict(X[i], timeout=60)
                                        for i in range(32)])
            finally:
                eng.close()
            gate = online.PromotionGate(min_improve=-1.0, max_drift=1.0)
            decision = gate.decide(live, cand_scores, y[:32])
            assert decision["promote"], decision
            if chaos:
                faults.install(
                    "seed=31,rate=0.03,kinds=error,points=serve.dispatch")

            stop = threading.Event()
            drops = {"n": 0}

            def traffic():
                k = 0
                while not stop.is_set():
                    try:
                        router.submit(X[k % len(X)]).result(timeout=120)
                    except Exception:
                        drops["n"] += 1
                    k += 1
            t = threading.Thread(target=traffic, name="bench-promote")
            t.start()
            try:
                gate.apply(decision, router, ck_dir, timeout=120)
            finally:
                stop.set()
                t.join(timeout=120)
            router.predict(X[0], timeout=60)    # new weights serving
            freshness = time.perf_counter() - t0
            assert cand["step"] is not None
            return freshness, drops["n"] + dropped_flood
        finally:
            faults.clear()
            router.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def freshness_leg(feed=lambda *_: None):
    """online_freshness_s / online_promote_dropped, then the chaos
    re-measure (online_freshness_chaos_s)."""
    out = {}
    feed("online-freshness")
    fresh_s, dropped = _freshness_once(chaos=False, feed=feed)
    out["online_freshness_s"] = round(fresh_s, 3)
    out["online_promote_dropped"] = dropped
    feed("online-freshness-chaos")
    chaos_s, chaos_dropped = _freshness_once(chaos=True, feed=feed)
    out["online_freshness_chaos_s"] = round(chaos_s, 3)
    # chaos drops fold into the same zero-floor gate: absorbable means
    # absorbed
    out["online_promote_dropped"] += chaos_dropped
    return out


def run(feed=lambda *_: None):
    """Returns the online-loop bench metrics; each sub-leg degrades
    independently (a failed optional leg must not sink the others)."""
    out = {}
    for leg in (capture_overhead_leg, freshness_leg):
        try:
            out.update(leg(feed=feed))
        except Exception as e:                    # pragma: no cover
            sys.stderr.write("bench_online: %s failed (%s)\n"
                             % (leg.__name__, e))
    return out


if __name__ == "__main__":
    print(json.dumps(run(), indent=1))
