# Learning-rate schedules (reference R-package/R/lr_scheduler.R):
# closures (num.update, base.lr) -> lr, consumed by mx.opt.get.updater.

mx.lr_scheduler.FactorScheduler <- function(step, factor,
                                            stop_factor_lr = 1e-8) {
  stopifnot(step >= 1, factor < 1)
  function(num.update, base.lr) {
    lr <- base.lr * factor ^ (num.update %/% step)
    max(lr, stop_factor_lr)
  }
}

mx.lr_scheduler.MultiFactorScheduler <- function(step, factor,
                                                 stop_factor_lr = 1e-8) {
  stopifnot(all(diff(step) > 0), factor < 1)
  function(num.update, base.lr) {
    lr <- base.lr * factor ^ sum(num.update > step)
    max(lr, stop_factor_lr)
  }
}
