"""Data pipeline for the bi-LSTM sorting task.

Capability parity with reference example/bi-lstm-sort/sort_io.py:1:
vocab building, frequency-driven bucket generation, SimpleBatch,
DummyIter (fixed-batch speed testing), and a bucketed iterator whose
labels are the per-row *sorted* input sequence.  A corpus generator is
included since this image cannot download the reference's data files.
"""
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx


def gen_sort_data(path, n_lines=10000, min_len=3, max_len=8, vocab_size=100,
                  seed=0):
    """Write lines of space-separated random integers — the sort task's
    training text."""
    rng = np.random.RandomState(seed)
    with open(path, "w") as f:
        for _ in range(n_lines):
            ln = rng.randint(min_len, max_len + 1)
            f.write(" ".join(str(v) for v in
                             rng.randint(0, vocab_size, size=ln)) + "\n")


def default_read_content(path):
    with open(path) as f:
        return f.read().replace("\n", " <eos> ").replace(". ", " <eos> ")


def default_build_vocab(path):
    words = sorted(set(w for w in default_read_content(path).split(" ") if w))
    vocab = {" ": 0}                       # 0 is the padding id
    for i, w in enumerate(words):
        vocab[w] = i + 1
    return vocab


def default_text2id(sentence, the_vocab):
    return [the_vocab[w] for w in sentence.split(" ") if w and w in the_vocab]


def default_gen_buckets(sentences, batch_size, the_vocab):
    """Greedy frequency sweep: cut a bucket whenever the accumulated
    sentence count since the last cut reaches a batch (reference
    sort_io.py:46)."""
    counts = {}
    for s in sentences:
        n = len(default_text2id(s, the_vocab))
        if n:
            counts[n] = counts.get(n, 0) + 1
    buckets, pending = [], 0
    for length in sorted(counts):
        pending += counts[length]
        if pending >= batch_size:
            buckets.append(length)
            pending = 0
    if pending > 0:
        buckets.append(max(counts))
    return buckets


class SimpleBatch:
    """Minimal bucketed batch carrier (reference sort_io.py:76)."""

    def __init__(self, data_names, data, label_names, label, bucket_key):
        self.data, self.label = data, label
        self.data_names, self.label_names = data_names, label_names
        self.bucket_key = bucket_key
        self.pad, self.index = 0, None

    @property
    def provide_data(self):
        return [(n, x.shape) for n, x in zip(self.data_names, self.data)]

    @property
    def provide_label(self):
        return [(n, x.shape) for n, x in zip(self.label_names, self.label)]


class DummyIter(mx.io.DataIter):
    """Replays one real batch forever — isolates compute speed from IO
    (reference sort_io.py:95)."""

    def __init__(self, real_iter):
        super().__init__()
        self.real_iter = real_iter
        self.provide_data = real_iter.provide_data
        self.provide_label = real_iter.provide_label
        self.batch_size = real_iter.batch_size
        self.the_batch = next(iter(real_iter))

    def __iter__(self):
        return self

    def __next__(self):
        return self.the_batch

    next = __next__


class BucketSentenceIter(mx.io.DataIter):
    """Buckets integer sequences by length; each batch's label is the
    row-wise sorted copy of its data (reference sort_io.py:113)."""

    def __init__(self, path, vocab, buckets, batch_size, init_states,
                 data_name="data", label_name="label",
                 seperate_char=" <eos> ", text2id=None, read_content=None):
        super().__init__()
        self.text2id = text2id or default_text2id
        self.read_content = read_content or default_read_content
        sentences = self.read_content(path).split(seperate_char)
        if not buckets:
            buckets = default_gen_buckets(sentences, batch_size, vocab)
        self.vocab_size = len(vocab)
        self.data_name, self.label_name = data_name, label_name
        self.buckets = sorted(buckets)
        self.default_bucket_key = max(self.buckets)

        per_bucket = [[] for _ in self.buckets]
        for s in sentences:
            ids = self.text2id(s, vocab)
            if not ids:
                continue
            for i, cap in enumerate(self.buckets):
                if cap >= len(ids):
                    per_bucket[i].append(ids)
                    break
        self.data = []
        for i, rows in enumerate(per_bucket):
            arr = np.zeros((len(rows), self.buckets[i]))
            for j, ids in enumerate(rows):
                arr[j, :len(ids)] = ids
            self.data.append(arr)

        print("Summary of dataset ==================")
        for cap, arr in zip(self.buckets, self.data):
            print("bucket of len %3d : %d samples" % (cap, len(arr)))

        self.batch_size = batch_size
        self.init_states = init_states
        self.init_state_arrays = [mx.nd.zeros(x[1]) for x in init_states]
        self.provide_data = [("data", (batch_size,
                                       self.default_bucket_key))] + \
            list(init_states)
        self.provide_label = [("softmax_label",
                               (batch_size, self.default_bucket_key))]
        self.make_data_iter_plan()

    def make_data_iter_plan(self):
        n_batches = [len(x) // self.batch_size for x in self.data]
        self.data = [x[:n * self.batch_size]
                     for x, n in zip(self.data, n_batches)]
        plan = np.hstack([np.full(n, i, int)
                          for i, n in enumerate(n_batches)]) \
            if any(n_batches) else np.zeros((0,), int)
        np.random.shuffle(plan)
        self.bucket_plan = plan
        self.bucket_idx_all = [np.random.permutation(len(x))
                               for x in self.data]
        self.bucket_curr_idx = [0] * len(self.data)

    def __iter__(self):
        state_names = [x[0] for x in self.init_states]
        for i_bucket in self.bucket_plan:
            pos = self.bucket_curr_idx[i_bucket]
            rows = self.bucket_idx_all[i_bucket][pos:pos + self.batch_size]
            self.bucket_curr_idx[i_bucket] += self.batch_size
            data = self.data[i_bucket][rows]
            label = np.sort(data, axis=1)      # the task: emit sorted input
            yield SimpleBatch(
                ["data"] + state_names,
                [mx.nd.array(data)] + self.init_state_arrays,
                ["softmax_label"], [mx.nd.array(label)],
                self.buckets[i_bucket])

    def reset(self):
        self.bucket_curr_idx = [0] * len(self.data)
