"""Bidirectional LSTM that learns to sort short digit sequences (reference
example/bi-lstm-sort/{lstm_sort.py,sort_io.py} capability).

A forward and a backward LSTM scan the input sequence; their per-step hidden
states are concatenated and classified per position.  Both directions unroll
into the same fused XLA program.
"""
import argparse
import logging
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxnet_tpu as mx
from mxnet_tpu.models.lstm import lstm_cell, LSTMState, LSTMParam


def bi_lstm_unroll(seq_len, input_dim, num_hidden, num_label):
    embed_weight = mx.sym.Variable("embed_weight")
    cls_weight = mx.sym.Variable("cls_weight")
    cls_bias = mx.sym.Variable("cls_bias")

    def make_param(tag):
        return LSTMParam(
            i2h_weight=mx.sym.Variable("%s_i2h_weight" % tag),
            i2h_bias=mx.sym.Variable("%s_i2h_bias" % tag),
            h2h_weight=mx.sym.Variable("%s_h2h_weight" % tag),
            h2h_bias=mx.sym.Variable("%s_h2h_bias" % tag))

    def make_state(tag):
        return LSTMState(c=mx.sym.Variable("%s_init_c" % tag),
                         h=mx.sym.Variable("%s_init_h" % tag))

    fwd_param, bwd_param = make_param("fwd"), make_param("bwd")

    data = mx.sym.Variable("data")            # (batch, seq_len) token ids
    embed = mx.sym.Embedding(data, input_dim=input_dim, output_dim=num_hidden,
                             weight=embed_weight, name="embed")
    steps = mx.sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                squeeze_axis=True)

    fwd_hidden = []
    state = make_state("fwd")
    for t in range(seq_len):
        state = lstm_cell(num_hidden, indata=steps[t], prev_state=state,
                          param=fwd_param, seqidx=t, layeridx=0)
        fwd_hidden.append(state.h)

    bwd_hidden = [None] * seq_len
    state = make_state("bwd")
    for t in reversed(range(seq_len)):
        state = lstm_cell(num_hidden, indata=steps[t], prev_state=state,
                          param=bwd_param, seqidx=t, layeridx=1)
        bwd_hidden[t] = state.h

    outs = []
    for t in range(seq_len):
        h = mx.sym.Concat(fwd_hidden[t], bwd_hidden[t], dim=1)
        fc = mx.sym.FullyConnected(h, weight=cls_weight, bias=cls_bias,
                                   num_hidden=num_label,
                                   name="t%d_cls" % t)
        outs.append(mx.sym.SoftmaxOutput(
            fc, label=mx.sym.Variable("t%d_label" % t),
            name="t%d_sm" % t))
    return mx.sym.Group(outs)


def make_data(n, seq_len, vocab, seed=0):
    rng = np.random.RandomState(seed)
    seqs = rng.randint(0, vocab, size=(n, seq_len))
    sorted_seqs = np.sort(seqs, axis=1)
    return seqs.astype(np.float32), sorted_seqs.astype(np.float32)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch-size", type=int, default=50)
    parser.add_argument("--num-epochs", type=int, default=10)
    parser.add_argument("--seq-len", type=int, default=5)
    parser.add_argument("--vocab", type=int, default=10)
    parser.add_argument("--num-hidden", type=int, default=64)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)

    seqs, sorted_seqs = make_data(4000, args.seq_len, args.vocab)
    label_names = ["t%d_label" % t for t in range(args.seq_len)]
    state_shapes = {"%s_init_%s" % (tag, s): (args.batch_size,
                                              args.num_hidden)
                    for tag in ("fwd", "bwd") for s in ("c", "h")}
    # init states ride along as zero "data" inputs (truncated-BPTT style)
    iter_data = {"data": seqs}
    for k, shape in state_shapes.items():
        iter_data[k] = np.zeros((len(seqs), shape[1]), np.float32)
    labels = {label_names[t]: sorted_seqs[:, t] for t in range(args.seq_len)}
    train = mx.io.NDArrayIter(iter_data, labels,
                              batch_size=args.batch_size, shuffle=True)

    net = bi_lstm_unroll(args.seq_len, args.vocab, args.num_hidden,
                         args.vocab)
    mod = mx.mod.Module(net, context=[mx.cpu()],
                        data_names=tuple(["data"] + sorted(state_shapes)),
                        label_names=tuple(label_names))
    mod.fit(train, num_epoch=args.num_epochs, optimizer="adam",
            optimizer_params={"learning_rate": 5e-3},
            eval_metric=mx.metric.CustomMetric(
                lambda l, p: float((np.asarray(p).argmax(1) ==
                                    np.asarray(l).astype(int)).mean()),
                name="pos-acc"))

    # measure whole-sequence sort accuracy
    train.reset()
    correct = total = 0
    for batch in train:
        mod.forward(batch, is_train=False)
        outs = [o.asnumpy().argmax(axis=1) for o in mod.get_outputs()]
        pred = np.stack(outs, axis=1)
        truth = np.stack([l.asnumpy() for l in batch.label], axis=1)
        correct += (pred == truth).all(axis=1).sum()
        total += pred.shape[0]
    print("exact-sort accuracy: %.3f" % (correct / total))


if __name__ == "__main__":
    main()
