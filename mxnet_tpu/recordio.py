"""RecordIO: sequential binary record container + packed image records.

Reference: python/mxnet/recordio.py (189 LoC), dmlc-core recordio format,
tools/im2rec.  Byte-compatible framing: magic 0xced7230a, length word with
continuation flag, 4-byte alignment — so .rec files pack/unpack the same way.
"""
from __future__ import annotations

import os
import struct
from collections import namedtuple
from typing import List, Optional

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img", "stream_records", "count_records"]

_MAGIC = 0xced7230a


def _iter_frames(uri: str, want, chunk_bytes: int):
    """Walk a .rec file's framing via chunked ``os.pread``, yielding
    ``(index, payload_or_None)`` for every record — payload bytes are
    assembled only when ``want(index)`` is true, so skipping a record
    costs header arithmetic, not a copy, and the whole file is never
    resident (at most ~``chunk_bytes`` of it is)."""
    fd = os.open(uri, os.O_RDONLY)
    try:
        size = os.fstat(fd).st_size
        buf = b""
        base = 0          # file offset of buf[0]
        pos = 0           # absolute parse position
        idx = 0
        while pos + 8 <= size:
            if pos + 8 > base + len(buf):
                buf = os.pread(fd, chunk_bytes, pos)
                base = pos
            magic, length = struct.unpack_from("<II", buf, pos - base)
            if magic != _MAGIC:
                raise MXNetError("invalid record magic at offset %d in %s"
                                 % (pos, uri))
            length &= (1 << 29) - 1
            pad = (4 - length % 4) % 4
            if pos + 8 + length > size:
                raise MXNetError("truncated record %d at offset %d in %s"
                                 % (idx, pos, uri))
            if want is None or want(idx):
                end = pos + 8 + length
                if end > base + len(buf):
                    # record spans past the buffered chunk: one pread
                    # sized to the record (large records never force a
                    # whole-file read)
                    buf = os.pread(fd, max(chunk_bytes, 8 + length), pos)
                    base = pos
                off = pos - base
                yield idx, bytes(buf[off + 8:off + 8 + length])
            else:
                yield idx, None
            pos += 8 + length + pad
            idx += 1
    finally:
        os.close(fd)


def stream_records(uri: str, want=None, chunk_bytes: int = 1 << 20):
    """Stream ``(index, payload)`` out of a RecordIO file without ever
    materializing it: records are parsed out of a sliding pread window
    (``chunk_bytes`` at a time).  ``want(index) -> bool`` selects which
    records get their payload copied out — the sharded-reader workers
    pass ``lambda i: i % nshards == shard`` so each process pays copy
    cost only for its own shard while the page cache amortizes the
    sequential walk across processes."""
    for idx, payload in _iter_frames(uri, want, chunk_bytes):
        if payload is not None:
            yield idx, payload


def count_records(uri: str, chunk_bytes: int = 1 << 20) -> int:
    """Number of records in a .rec file via a payload-free framing walk
    (headers only are decoded; nothing is copied)."""
    n = 0
    for idx, _ in _iter_frames(uri, lambda _i: False, chunk_bytes):
        n = idx + 1
    return n


class MXRecordIO:
    """Sequential RecordIO reader/writer (reference recordio.py:10)."""

    def __init__(self, uri: str, flag: str):
        self.uri = uri
        self.flag = flag
        if flag == "w":
            self._f = open(uri, "wb")
        elif flag == "r":
            self._f = open(uri, "rb")
        else:
            raise ValueError("Invalid flag %s" % flag)
        self.is_open = True

    def close(self):
        if self.is_open:
            self._f.close()
            self.is_open = False

    def __del__(self):
        self.close()

    def tell(self) -> int:
        return self._f.tell()

    def seek(self, pos: int):
        self._f.seek(pos)

    def write(self, buf: bytes):
        assert self.flag == "w"
        self._f.write(struct.pack("<II", _MAGIC, len(buf)))
        self._f.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self._f.write(b"\x00" * pad)

    def read(self) -> Optional[bytes]:
        assert self.flag == "r"
        header = self._f.read(8)
        if len(header) < 8:
            return None
        magic, length = struct.unpack("<II", header)
        if magic != _MAGIC:
            raise MXNetError("invalid record magic in %s" % self.uri)
        length &= (1 << 29) - 1  # mask continuation flag bits
        buf = self._f.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self._f.read(pad)
        return buf

    def reset(self):
        self._f.seek(0)


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with .idx sidecar (reference recordio.py:65)."""

    def __init__(self, idx_path: str, uri: str, flag: str, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys: List = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if flag == "r" and os.path.exists(idx_path):
            with open(idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    key = key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.flag == "w" and self.is_open:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write("%s\t%d\n" % (str(key), self.idx[key]))
        super().close()

    def seek_idx(self, idx):
        self.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek_idx(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


# packed image record header (reference recordio.py IRHeader)
IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack an image record (reference recordio.py pack)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        out = struct.pack(_IR_FORMAT, header.flag, float(header.label),
                          header.id, header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        out = struct.pack(_IR_FORMAT, len(label), 0.0, header.id, header.id2)
        out += label.tobytes()
    return out + s


def unpack(s: bytes):
    """Unpack an image record -> (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    s = s[_IR_SIZE:]
    if flag > 0:
        label = np.frombuffer(s[:flag * 4], dtype=np.float32)
        s = s[flag * 4:]
    header = IRHeader(flag, label, id_, id2)
    return header, s


def pack_img(header: IRHeader, img, quality=95, img_fmt=".jpg") -> bytes:
    """Pack a numpy image (HWC uint8) into a record; JPEG via PIL if present."""
    try:
        from PIL import Image
        import io as _io
        buf = _io.BytesIO()
        fmt = "JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG"
        Image.fromarray(np.asarray(img, dtype=np.uint8)).save(
            buf, format=fmt, quality=quality)
        return pack(header, buf.getvalue())
    except ImportError:
        # raw fallback: store CHW bytes
        arr = np.asarray(img, dtype=np.uint8)
        if arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        return pack(header, arr.tobytes())


def unpack_img(s: bytes, iscolor=-1):
    header, img_bytes = unpack(s)
    try:
        from PIL import Image
        import io as _io
        img = np.asarray(Image.open(_io.BytesIO(img_bytes)))
    except ImportError:
        img = np.frombuffer(img_bytes, dtype=np.uint8)
    return header, img
