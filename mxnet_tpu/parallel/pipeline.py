"""Explicit pipeline parallelism: GPipe-style microbatching over a ``pp``
mesh axis.

Beyond reference parity (SURVEY §2.4: the reference's model-parallel LSTM
overlapped timesteps only implicitly through the engine's async
scheduling; no explicit schedule existed).  The TPU-native formulation:
stage parameters are stacked along a leading axis and sharded over
``pp``, every device runs the SAME stage function under ``shard_map``,
and activations hop stage-to-stage with ``lax.ppermute`` inside a
``lax.scan`` over pipeline ticks — the canonical compiler-friendly
pipeline (static shapes, no data-dependent control flow, collectives on
ICI).  JAX differentiates through scan + ppermute, so the backward
pipeline (reverse hops) comes from autodiff rather than a hand schedule.

Scope: homogeneous stages (each stage applies the same ``stage_fn`` with
its own parameter slice — e.g. a stack of identical residual/MLP blocks),
GPipe fill-drain schedule (bubble fraction (S-1)/(M+S-1) for S stages and
M microbatches; raise M to amortize).  Heterogeneous first/last layers
(embedding, classifier head) run outside the pipelined stack, which is
how the stacked-stage pattern is used in practice.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import shard_map_norep

__all__ = ["pipeline_apply", "GPipeTrainStep"]


def pipeline_apply(stage_fn: Callable, mesh: Mesh, stacked_params, micros,
                   axis: str = "pp"):
    """Run microbatches through the stage pipeline; returns stacked
    outputs (M, ...) with the same sharding as the inputs.

    stage_fn(params_slice, x) -> y where y.shape == x.shape (homogeneous
    stages); stacked_params pytree leaves have leading dim = S (sharded
    over `axis`); micros has leading dim M (replicated).
    """
    S = mesh.shape[axis]
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != S:
            raise ValueError(
                "stacked param leading dim %d != pipeline stages %d "
                "(each leaf must stack one slice per pp-axis device)"
                % (leaf.shape[0], S))

    def run(params, micros_in):
        # params leaves: (1, ...) — this device's stage slice
        local = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = lax.axis_index(axis)
        M = micros_in.shape[0]
        T = M + S - 1
        perm = [(i, (i + 1) % S) for i in range(S)]

        def tick(carry, t):
            buf, outs = carry
            # stage 0 injects microbatch t while t < M (beyond that the
            # injected value is garbage that never reaches a recorded out)
            inject = micros_in[jnp.minimum(t, M - 1)]
            x = jnp.where(stage == 0, inject, buf)
            y = stage_fn(local, x)
            # the last stage records micro m = t - (S-1)
            m = t - (S - 1)
            record = (stage == S - 1) & (m >= 0)
            outs = lax.cond(
                record,
                lambda o: o.at[jnp.maximum(m, 0)].set(y),
                lambda o: o, outs)
            buf_next = lax.ppermute(y, axis, perm)
            return (buf_next, outs), None

        buf0 = jnp.zeros_like(micros_in[0])
        outs0 = jnp.zeros_like(micros_in)
        (_, outs), _ = lax.scan(tick, (buf0, outs0), jnp.arange(T))
        # only the last stage holds real outputs; make the value
        # replicated so out_specs=P() is sound
        outs = lax.psum(jnp.where(stage == S - 1, outs,
                                  jnp.zeros_like(outs)), axis)
        return outs

    sharded = shard_map_norep(run, mesh, in_specs=(P(axis), P()),
                              out_specs=P())
    return sharded(stacked_params, micros)


class GPipeTrainStep:
    """Microbatched pipeline training step over a ``pp`` mesh axis.

    model: S x stage_fn(stage_params_i, h) -> h  (pipelined stack)
           loss_fn(tail_params, h, label) -> scalar loss (replicated
           tail; put any non-pipelined encoder/embedding inside stage 0's
           parameters or precompute it into the input batch)

    Gradients flow back through the pipeline via autodiff (reverse
    ppermute hops); the optimizer update (SGD) runs replicated — the
    same update-on-every-stage model the fused data-parallel step uses.
    """

    def __init__(self, stage_fn, loss_fn, mesh: Mesh, num_micro: int,
                 learning_rate: float = 0.1, axis: str = "pp"):
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.mesh = mesh
        self.num_micro = num_micro
        self.lr = learning_rate
        self.axis = axis
        self._step = None

    def init(self, stacked_params, tail_params):
        # jnp.copy: the state is donated every step and device_put may
        # zero-copy alias the caller's host buffers (see
        # DPTrainStep.init)
        spec = NamedSharding(self.mesh, P(self.axis))
        rep = NamedSharding(self.mesh, P())
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.copy(jax.device_put(jnp.asarray(a), spec)),
            stacked_params)
        tail = jax.tree_util.tree_map(
            lambda a: jnp.copy(jax.device_put(jnp.asarray(a), rep)),
            tail_params)
        return {"stages": stacked, "tail": tail}

    def _build(self):
        mesh, axis, M = self.mesh, self.axis, self.num_micro
        stage_fn, loss_fn, lr = self.stage_fn, self.loss_fn, self.lr

        def loss_of(params, data, labels):
            # data: (B, ...) -> microbatches (M, B/M, ...)
            micros = data.reshape((M, data.shape[0] // M) + data.shape[1:])
            outs = pipeline_apply(stage_fn, mesh, params["stages"], micros,
                                  axis)
            h = outs.reshape(data.shape[0], *outs.shape[2:])
            return loss_fn(params["tail"], h, labels)

        def step(params, data, labels):
            loss, grads = jax.value_and_grad(loss_of)(params, data, labels)
            new = jax.tree_util.tree_map(lambda w, g: w - lr * g,
                                         params, grads)
            return new, loss

        from ..compile_cache import cached_jit
        return cached_jit(step, name="parallel:pipeline_step",
                          donate_argnums=(0,))

    def __call__(self, params, data, labels):
        if len(data) % self.num_micro:
            raise ValueError(
                "batch size %d must be divisible by num_micro=%d"
                % (len(data), self.num_micro))
        if self._step is None:
            self._step = self._build()
        return self._step(params, jnp.asarray(data), jnp.asarray(labels))
