package ml.dmlc.mxnet_tpu

import org.scalatest.{BeforeAndAfterAll, FunSuite}

/**
 * Reference OperatorSuite.scala analogue: symbolic operators driven
 * through simpleBind executors with numeric forward checks and a
 * finite-difference-free backward sanity check (gradients populated
 * and shaped).  Everything crosses the flat-array JNI layer.
 */
class OperatorSuite extends FunSuite with BeforeAndAfterAll {

  private def bindUnary(op: String, params: Map[String, String],
                        in: Array[Float], shape: Shape)
      : (Executor, Symbol) = {
    val data = Symbol.Variable("data")
    val sym = Symbol.create(op, s"${op.toLowerCase}_t",
                            Map("data" -> data), params)
    val exe = sym.simpleBind(Context.cpu(),
                             shapes = Map("data" -> shape))
    exe.argDict("data").set(in)
    (exe, sym)
  }

  test("Activation relu forward clamps negatives") {
    val (exe, _) = bindUnary("Activation", Map("act_type" -> "relu"),
                             Array(-2f, -1f, 0f, 3f), Shape(2, 2))
    exe.forward()
    assert(exe.outputs(0).toArray.toSeq == Seq(0f, 0f, 0f, 3f))
  }

  test("FullyConnected forward matches hand matmul") {
    val data = Symbol.Variable("data")
    val fc = Symbol.FullyConnected(data, numHidden = 2, name = "fc")
    val exe = fc.simpleBind(Context.cpu(),
                            shapes = Map("data" -> Shape(1, 3)))
    exe.argDict("data").set(Array(1f, 2f, 3f))
    exe.argDict("fc_weight").set(Array(1f, 0f, 0f, 0f, 1f, 0f))
    exe.argDict("fc_bias").set(Array(0.5f, -0.5f))
    exe.forward()
    assert(exe.outputs(0).toArray.toSeq == Seq(1.5f, 1.5f))
  }

  test("SoftmaxOutput forward normalizes and backward fills grads") {
    val data = Symbol.Variable("data")
    val sm = Symbol.SoftmaxOutput(
      Symbol.FullyConnected(data, numHidden = 3, name = "fc"),
      name = "softmax")
    val exe = sm.simpleBind(Context.cpu(),
                            shapes = Map("data" -> Shape(2, 4),
                                         "softmax_label" -> Shape(2)))
    exe.argDict("data").set(Array.fill(8)(0.3f))
    exe.argDict("softmax_label").set(Array(0f, 2f))
    exe.forward(isTrain = true)
    val probs = exe.outputs(0).toArray
    val rowSum = probs.take(3).sum
    assert(math.abs(rowSum - 1f) < 1e-4)
    exe.backward()
    val g = exe.gradDict("fc_weight").toArray
    assert(g.length == 12 && g.exists(_ != 0f))
  }

  test("elementwise symbol composition (a+b)*c") {
    val a = Symbol.Variable("a")
    val b = Symbol.Variable("b")
    val sum = Symbol.create("_plus", "plus_t",
                            Map("lhs" -> a, "rhs" -> b))
    val exe = sum.simpleBind(Context.cpu(),
                             shapes = Map("a" -> Shape(2),
                                          "b" -> Shape(2)))
    exe.argDict("a").set(Array(1f, 2f))
    exe.argDict("b").set(Array(10f, 20f))
    exe.forward()
    assert(exe.outputs(0).toArray.toSeq == Seq(11f, 22f))
  }
}
