package ml.dmlc.mxnet_tpu

/** Learning-rate schedules keyed on the update count
 * (reference LRScheduler.scala). */
abstract class LRScheduler(var baseLR: Float = 0.01f) {
  def apply(numUpdate: Int): Float
}

class FactorScheduler(step: Int, factor: Float) extends LRScheduler {
  require(step >= 1, "step must be at least 1")
  require(factor < 1f, "factor must decay")
  private var count = 0
  private var decay = 1f   // baseLR is owned by the optimizer and may be
                           // assigned after construction: never snapshot it

  def apply(numUpdate: Int): Float = {
    if (numUpdate > count + step) {
      count += step
      decay *= factor
    }
    baseLR * decay
  }
}
