"""Compilation observability: where cold-start time goes, per program.

One process-global ``CompileStats`` (compilation is process-global: the
jit caches, the disk cache, and the XLA compiler are all shared), fed by
every ``cached_jit`` wrapper and surfaced through
``mx.profiler.compile_report()/_str()``.

Per program name: trace+lower seconds, backend-compile seconds,
deserialize seconds, cache hits/misses/bypasses (with the bypass
reason), and a ``steady_retraces`` counter — the number of times a
program object that had ALREADY compiled once compiled again for a new
input signature.  A nonzero steady retrace count is the silent-10x
regression (a shape/dtype wobble re-entering XLA every step) that the
tier-1 recompile guard turns into a test failure.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from ..base import make_lock


class _ProgramStats:
    __slots__ = ("trace_lower_s", "compile_s", "deserialize_s", "hits",
                 "misses", "bypasses", "compiles", "retraces",
                 "bypass_reasons")

    def __init__(self):
        self.trace_lower_s = 0.0
        self.compile_s = 0.0
        self.deserialize_s = 0.0
        self.hits = 0
        self.misses = 0
        self.bypasses = 0
        self.compiles = 0
        self.retraces = 0
        self.bypass_reasons: Dict[str, int] = {}

    def report(self) -> dict:
        out = {"trace_lower_s": self.trace_lower_s,
               "compile_s": self.compile_s,
               "deserialize_s": self.deserialize_s,
               "hits": self.hits, "misses": self.misses,
               "bypasses": self.bypasses, "compiles": self.compiles,
               "steady_retraces": self.retraces}
        if self.bypass_reasons:
            out["bypass_reasons"] = dict(self.bypass_reasons)
        return out


class CompileStats:
    """Aggregated per-name compile counters (thread-safe: warmup pools
    compile many programs concurrently)."""

    def __init__(self, name: str = "compile"):
        self.name = name
        self._lock = make_lock("compile_cache.stats")
        self._programs: Dict[str, _ProgramStats] = {}
        self.bytes_written = 0
        self.entries_written = 0

    def _prog(self, name: str) -> _ProgramStats:
        ps = self._programs.get(name)
        if ps is None:
            ps = self._programs.setdefault(name, _ProgramStats())
        return ps

    # -- recording ---------------------------------------------------------
    def note_trace_lower(self, name: str, seconds: float) -> None:
        with self._lock:
            self._prog(name).trace_lower_s += seconds

    def note_compile(self, name: str, seconds: float,
                     retrace: bool = False) -> None:
        with self._lock:
            ps = self._prog(name)
            ps.compile_s += seconds
            ps.compiles += 1
            if retrace:
                ps.retraces += 1

    def note_hit(self, name: str, seconds: float) -> None:
        with self._lock:
            ps = self._prog(name)
            ps.deserialize_s += seconds
            ps.hits += 1

    def note_miss(self, name: str) -> None:
        with self._lock:
            self._prog(name).misses += 1

    def note_bypass(self, name: str, reason: str) -> None:
        with self._lock:
            ps = self._prog(name)
            ps.bypasses += 1
            ps.bypass_reasons[reason] = ps.bypass_reasons.get(reason, 0) + 1

    def note_store(self, nbytes: int) -> None:
        with self._lock:
            if nbytes > 0:
                self.bytes_written += nbytes
                self.entries_written += 1

    # -- reporting ---------------------------------------------------------
    def totals(self) -> dict:
        with self._lock:
            progs = {n: p.report() for n, p in self._programs.items()}
        tot = {"programs": len(progs),
               "trace_lower_s": sum(p["trace_lower_s"] for p in progs.values()),
               "compile_s": sum(p["compile_s"] for p in progs.values()),
               "deserialize_s": sum(p["deserialize_s"] for p in progs.values()),
               "hits": sum(p["hits"] for p in progs.values()),
               "misses": sum(p["misses"] for p in progs.values()),
               "bypasses": sum(p["bypasses"] for p in progs.values()),
               "compiles": sum(p["compiles"] for p in progs.values()),
               "steady_retraces": sum(p["steady_retraces"]
                                      for p in progs.values()),
               "bytes_written": self.bytes_written,
               "entries_written": self.entries_written}
        lookups = tot["hits"] + tot["misses"]
        tot["hit_rate"] = (tot["hits"] / lookups) if lookups else None
        return tot

    def report(self, cache=None) -> dict:
        """Full report; ``cache`` (a CompileCache) contributes the disk
        view (dir, entries, bytes, mode)."""
        with self._lock:
            progs = {n: p.report() for n, p in sorted(self._programs.items())}
        out = {"totals": self.totals(), "per_program": progs}
        if cache is not None:
            out["cache"] = cache.describe()
        return out

    def report_str(self, cache=None) -> str:
        r = self.report(cache=cache)
        t = r["totals"]
        lines = ["%s: %d programs, %d compiles (%.2fs), %d hits (%.2fs "
                 "deserialize), %d misses, %d bypasses, %d steady retraces"
                 % (self.name, t["programs"], t["compiles"], t["compile_s"],
                    t["hits"], t["deserialize_s"], t["misses"],
                    t["bypasses"], t["steady_retraces"])]
        if t["hit_rate"] is not None:
            lines.append("  hit_rate %.2f" % t["hit_rate"])
        c = r.get("cache")
        if c:
            lines.append("  cache %s: mode=%s, %d entries, %.1f MB on disk"
                         % (c["directory"], c["mode"], c["entries"],
                            c["disk_bytes"] / 2 ** 20))
        for name, p in r["per_program"].items():
            lines.append(
                "  %-40s lower %6.2fs  compile %6.2fs  hit/miss/byp "
                "%d/%d/%d" % (name[:40], p["trace_lower_s"],
                              p["compile_s"], p["hits"], p["misses"],
                              p["bypasses"]))
        return "\n".join(lines)


_global_stats: Optional[CompileStats] = None
_stats_lock = make_lock("compile_cache.stats_registry")


def get_stats() -> CompileStats:
    global _global_stats
    with _stats_lock:
        if _global_stats is None:
            _global_stats = CompileStats()
        return _global_stats


def _reset_stats() -> None:   # test hook
    global _global_stats
    with _stats_lock:
        _global_stats = None
