"""Subprocess body for test_router.py::
test_draining_restart_under_flood_subprocess.

Closed-loop flood (6 client threads) against a 3-replica ServeRouter
while replica 1 does a full draining restart mid-flood.  Prints ONE
JSON line: expected/completed/dropped/errors/restarts/parity_failures.
Exit 0 only if the flood itself ran; the parent asserts the counters.
"""
import json
import os
import sys
import threading

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.serve import ServeEngine, ServeRouter

IN_DIM, HID, CLASSES = 6, 8, 3
SHAPES = {"data": (1, IN_DIM), "softmax_label": (1,)}
THREADS, REQS = 4, 20


def _net():
    data = mx.sym.Variable("data")
    n = mx.sym.FullyConnected(data, num_hidden=HID, name="fc1")
    n = mx.sym.Activation(n, act_type="relu")
    n = mx.sym.FullyConnected(n, num_hidden=CLASSES, name="fc2")
    return mx.sym.SoftmaxOutput(n, name="softmax")


def _params():
    rng = np.random.RandomState(0)
    return {"fc1_weight": rng.randn(HID, IN_DIM).astype(np.float32),
            "fc1_bias": np.zeros(HID, np.float32),
            "fc2_weight": rng.randn(CLASSES, HID).astype(np.float32),
            "fc2_bias": np.zeros(CLASSES, np.float32)}


def factory(i):
    return ServeEngine(_net(), _params(), SHAPES, batch_buckets=(1, 2, 4),
                       max_delay_ms=2.0, deadline_ms=60000.0,
                       name="flood-rep%d" % i)


def main():
    X = np.random.RandomState(7).randn(THREADS * REQS,
                                       IN_DIM).astype(np.float32)
    router = ServeRouter(factory, replicas=3, name="flood-router")
    ref = router.predict(X[0], timeout=60)
    results = [None] * len(X)
    errors = []
    started = threading.Event()

    def client(t):
        try:
            for j in range(REQS):
                i = t * REQS + j
                results[i] = router.predict(X[i], timeout=120)
                if j == 2:
                    started.set()       # flood demonstrably in flight
        except Exception as e:
            errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(t,))
               for t in range(THREADS)]
    for t in threads:
        t.start()
    started.wait(60)
    router.restart(1, timeout=120)      # draining full rebuild mid-flood
    for t in threads:
        t.join()
    rep = router.stats.report()
    # parity: every row must match the (single-model) reference —
    # a dropped/garbled request would either error or mismatch
    parity_failures = sum(
        1 for i, y in enumerate(results)
        if y is None or not np.allclose(
            y, mxref(ref, X, i), atol=1e-4))
    doc = {
        "expected": len(X),
        "completed": sum(1 for y in results if y is not None),
        "dropped": sum(1 for y in results if y is None),
        "errors": len(errors),
        "error_samples": errors[:3],
        "restarts": sum(r["restarts"]
                        for r in rep["per_replica"].values()),
        "parity_failures": parity_failures,
        "rejected": rep["rejected"],
        "retried": rep["retried"],
    }
    router.close()
    print(json.dumps(doc), flush=True)


def mxref(ref0, X, i):
    """All replicas serve identical weights; compute the expected row
    once per call via a shared batch-1 predictor."""
    global _PRED
    try:
        _PRED
    except NameError:
        from mxnet_tpu.predictor import Predictor
        _PRED = Predictor(_net().tojson(), _params(),
                          {"data": (1, IN_DIM), "softmax_label": (1,)})
    return _PRED.predict(X[i:i + 1])[0]


if __name__ == "__main__":
    main()
