function callmxnet(func, varargin)
%CALLMXNET call a predict-ABI entry point, checking the return code.
%
% MATLAB-only (Octave does not implement loadlibrary/calllib).
% Loads libmxtpu_predict.so on first use.  Set the environment variable
% MXNET_TPU_HOME to the repository root (the library lives in
% mxnet_tpu/), and start MATLAB with PYTHONPATH containing that
% root — the library embeds the CPython interpreter hosting the JAX
% runtime, like every other binding of this framework.

if ~libisloaded('libmxtpu_predict')
  root = getenv('MXNET_TPU_HOME');
  assert(~isempty(root), 'set MXNET_TPU_HOME to the repository root');
  lib = fullfile(root, 'mxnet_tpu', 'libmxtpu_predict.so');
  % attribute-free mirror of include/c_predict_api.h: loadlibrary's
  % parser cannot digest the GCC visibility attribute in the real header
  hdr = fullfile(root, 'matlab', '+mxnet', 'private', ...
                 'mxtpu_predict_matlab.h');
  assert(exist(lib, 'file') == 2, 'build the native core first: make');
  loadlibrary(lib, hdr, 'alias', 'libmxtpu_predict');
end

assert(ischar(func), 'func must be a string');
ret = calllib('libmxtpu_predict', func, varargin{:});
assert(ret == 0, ['call to ', func, ' failed']);
end
