# Build configuration knobs (reference make/config.mk shape).
# Copy to the repo root as config.mk or pass on the command line:
#   make CXX=clang++ ADD_CFLAGS=-march=native
#
# The native surface here is deliberately small: XLA/PJRT (via jaxlib)
# does the accelerator work the reference built CUDA/cuDNN/BLAS flags
# for, so most reference knobs have no TPU-build counterpart and are
# listed at the bottom for porters.

# toolchain
export CXX ?= g++
export ADD_CFLAGS ?=
export ADD_LDFLAGS ?=

# optimization level for the native core (engine/storage/IO/ABI)
export OPT_FLAGS ?= -O3

# whether `make test` runs the whole suite or the fast unit tier
export TEST_TIER ?= all

# ---------------------------------------------------------------------------
# Reference knobs with no equivalent here (documented, not honored):
#   USE_CUDA / USE_CUDNN / USE_CUDA_PATH  -> XLA:TPU via jaxlib
#   USE_BLAS / USE_MKL / ATLAS            -> MXU matmuls via XLA
#   USE_OPENCV                            -> libjpeg decode in src/, PIL tail
#   USE_DIST_KVSTORE / USE_HDFS / USE_S3  -> always on (collectives + fsspec)
#   USE_NVRTC                             -> Pallas kernels (mxnet_tpu/rtc.py)
