package ml.dmlc.mxnet_tpu

/** Weight initializers (reference Initializer.scala): name-pattern rules
 * shared by every binding — bias/beta/moving_mean zero, gamma/moving_var
 * one, weights by the concrete scheme. */
abstract class Initializer {
  def apply(name: String, arr: NDArray): Unit = {
    if (name.endsWith("bias") || name.endsWith("beta") ||
        name.endsWith("moving_mean")) {
      arr.set(0f)
    } else if (name.endsWith("gamma") || name.endsWith("moving_var")) {
      arr.set(1f)
    } else {
      initWeight(name, arr)
    }
  }

  protected def initWeight(name: String, arr: NDArray): Unit
}

class Uniform(scale: Float = 0.07f) extends Initializer {
  protected def initWeight(name: String, arr: NDArray): Unit = {
    val rnd = new scala.util.Random(name.hashCode)
    arr.set(Array.fill(arr.size)((rnd.nextFloat() * 2 - 1) * scale))
  }
}

class Normal(sigma: Float = 0.01f) extends Initializer {
  protected def initWeight(name: String, arr: NDArray): Unit = {
    val rnd = new scala.util.Random(name.hashCode)
    arr.set(Array.fill(arr.size)(rnd.nextGaussian().toFloat * sigma))
  }
}

/** Xavier/Glorot: scale by fan-in/fan-out (reference Initializer.scala). */
class Xavier(rndType: String = "uniform", factorType: String = "avg",
             magnitude: Float = 3f) extends Initializer {
  protected def initWeight(name: String, arr: NDArray): Unit = {
    val shape = arr.shape
    val fanOut = shape(0).toFloat
    val fanIn = shape.drop(1).product.toFloat
    val factor = factorType match {
      case "avg" => (fanIn + fanOut) / 2f
      case "in" => fanIn
      case "out" => fanOut
      case other => throw new Base.MXNetError(s"bad factor_type $other")
    }
    val scale = math.sqrt(magnitude / factor).toFloat
    val rnd = new scala.util.Random(name.hashCode)
    rndType match {
      case "uniform" =>
        arr.set(Array.fill(arr.size)((rnd.nextFloat() * 2 - 1) * scale))
      case "gaussian" =>
        arr.set(Array.fill(arr.size)(rnd.nextGaussian().toFloat * scale))
      case other => throw new Base.MXNetError(s"bad rnd_type $other")
    }
  }
}
