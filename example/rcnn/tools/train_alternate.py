"""Stage tool: the full 4-step alternate schedule as ONE command, each
stage exactly what the individual tools run (reference
tools/train_alternate.py).  For the stage-by-stage path:

  python tools/train_rpn.py   --prefix P/rpn1 --epochs 8
  python tools/test_rpn.py    --prefix P/rpn1 --epoch 8 --proposals P/p1.npz
  python tools/train_rcnn.py  --prefix P/rcnn1 --proposals P/p1.npz
  python tools/train_rpn.py   --prefix P/rpn2 --init-prefix P/rcnn1 \
                              --init-epoch 8 --freeze-trunk
  python tools/test_rpn.py    --prefix P/rpn2 --epoch 8 --proposals P/p2.npz
  python tools/train_rcnn.py  --prefix P/rcnn2 --proposals P/p2.npz \
                              --init-prefix P/rcnn1 --init-epoch 8 \
                              --freeze-trunk
  python tools/test_net.py    --rpn-prefix P/rpn2 --rpn-epoch 8 \
                              --rcnn-prefix P/rcnn2 --rcnn-epoch 8
"""
import os
import sys

from common import base_parser

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = base_parser("4-step alternate Faster R-CNN training")
    ap.add_argument("--epochs", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--map-gate", type=float, default=0.0)
    ap.add_argument("--model-prefix", type=str)
    args = ap.parse_args()
    # one implementation: the repo-root driver already runs the 4 stages
    # in-process through rcnn.solver/rcnn.tester
    sys.argv = [sys.argv[0], "--epochs", str(args.epochs),
                "--lr", str(args.lr),
                "--train-images", str(args.train_images),
                "--test-images", str(args.test_images),
                "--data-seed", str(args.data_seed),
                "--test-seed", str(args.test_seed)]
    if args.map_gate:
        sys.argv += ["--map-gate", str(args.map_gate)]
    if args.model_prefix:
        sys.argv += ["--model-prefix", args.model_prefix]
    if args.tpus:
        sys.argv += ["--tpus", args.tpus]
    import importlib
    mod = importlib.import_module("train_alternate")
    mod.main()


if __name__ == "__main__":
    main()
