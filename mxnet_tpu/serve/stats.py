"""Serving instrumentation: one stats object per serving component.

The report answers the capacity questions a serving operator actually
asks, in one place (``mx.profiler.serve_report()``, next to the feed /
checkpoint / superstep report family).  ``serve_report()`` is
multiplex-aware: every registered component contributes its own row —
one :class:`ServeStats` per batching engine, one :class:`DecodeStats`
per continuous-batching decode engine, plus the multiplexer's and
router's own counters (mux.py / router.py) — each row tagged with a
``kind`` and carrying its OWN ``max_batch_size`` / ``num_slots``, so a
process multiplexing N models never pretends there is one global batch
size.

Per :class:`ServeStats` row:

* **latency** — p50/p95/p99 over a sliding window of completed
  requests (queue wait + inference + D2H, i.e. what the client saw);
* **batch occupancy** — mean fraction of ``max_batch_size`` each
  dispatched batch actually filled (low occupancy at high qps means
  ``max_delay_ms`` is flushing too early);
* **pad waste** — fraction of dispatched rows that were padding (high
  waste means the bucket grid is too coarse for the arrival pattern);
* **per-bucket hit counts** — which compiled programs serve the
  traffic;
* **queue depth** (live + high-water) and the reject/expiry/cancel/
  failure counters that tell overload apart from client impatience.

Per :class:`DecodeStats` row: slot occupancy (mean fraction of decode
slots active per step), steps/tokens emitted, admission counters, and
the same latency window measured submit → stream resolve.

Per :class:`PagedStats` row (serve.paged): everything DecodeStats
tracks, plus prefill-token throughput, speculative-decode
proposed/accepted counters (acceptance rate is the headline spec-decode
health metric), KV-block-pool gauges (used / reserved / total), an
**inter-token latency** window (the p99 the chunked-prefill scheduler
exists to bound), and ``dropped_streams`` — 0 by design under exact
block reservation, reported so the bench gate can hold it at 0.
"""
from __future__ import annotations

import collections
import math
import threading
from typing import Dict, List, Optional

from ..base import make_lock

__all__ = ["ServeStats", "DecodeStats", "PagedStats"]

# sliding latency window: big enough for stable p99, small enough that a
# report reflects the recent regime rather than the whole process life
LATENCY_WINDOW = 4096


def _percentile(sorted_ms: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted list (0 when empty)."""
    if not sorted_ms:
        return 0.0
    idx = max(0, min(len(sorted_ms) - 1,
                     int(math.ceil(q / 100.0 * len(sorted_ms))) - 1))
    return sorted_ms[idx]


class ServeStats:
    """Counters for one ServeEngine; written from the submit/dispatch/
    completion threads under a lock, snapshotted atomically by
    ``report()``."""

    def __init__(self, name: str, max_batch_size: int):
        self.name = name
        self.max_batch_size = int(max_batch_size)
        self._lock = make_lock("serve.stats")
        self._submitted = 0
        self._completed = 0
        self._overloaded = 0
        self._expired = 0
        self._cancelled = 0
        self._failed = 0
        self._reloads = 0
        self._captured = 0
        self._batches = 0
        self._batch_items = 0
        self._pad_items = 0
        self._bucket_hits: Dict[int, int] = {}
        self._queue_depth = 0
        self._queue_depth_max = 0
        self._lat_ms = collections.deque(maxlen=LATENCY_WINDOW)

    # -- recording ---------------------------------------------------------
    def on_submit(self, queue_depth: int) -> None:
        with self._lock:
            self._submitted += 1
            self._queue_depth = queue_depth
            if queue_depth > self._queue_depth_max:
                self._queue_depth_max = queue_depth

    def on_overload(self) -> None:
        with self._lock:
            self._overloaded += 1

    def on_expired(self, n: int) -> None:
        with self._lock:
            self._expired += n

    def on_cancelled(self, n: int) -> None:
        with self._lock:
            self._cancelled += n

    def on_failed(self, n: int) -> None:
        with self._lock:
            self._failed += n

    def on_batch(self, items: int, bucket: int) -> None:
        with self._lock:
            self._batches += 1
            self._batch_items += items
            self._pad_items += bucket - items
            self._bucket_hits[bucket] = self._bucket_hits.get(bucket, 0) + 1

    def on_complete(self, latencies_ms) -> None:
        with self._lock:
            self._completed += len(latencies_ms)
            self._lat_ms.extend(latencies_ms)

    def on_reload(self) -> None:
        with self._lock:
            self._reloads += 1

    def on_captured(self) -> None:
        """A completed request was sampled into the online-training
        capture (mxnet_tpu.online) — NOT a terminal outcome (the
        request already completed), so it stays out of the outstanding
        balance; it exists so the sampled rate is verifiable as
        captured / completed straight from serve_report()."""
        with self._lock:
            self._captured += 1

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def _outstanding_locked(self) -> int:
        """Terminal-outcome balance — EVERY new terminal counter must be
        subtracted here and only here (lock held by the caller)."""
        return max(0, self._submitted - self._completed - self._failed
                   - self._expired - self._cancelled)

    def outstanding(self) -> int:
        """Admitted requests not yet terminally resolved (queued or in
        flight).  Overloaded submits never entered the queue, so they
        are not part of the balance."""
        with self._lock:
            return self._outstanding_locked()

    # -- reading -----------------------------------------------------------
    def report(self) -> Dict:
        with self._lock:
            lat = sorted(self._lat_ms)
            dispatched = self._batch_items + self._pad_items
            out = {
                "kind": "engine",
                "max_batch_size": self.max_batch_size,
                "outstanding": self._outstanding_locked(),
                "submitted": self._submitted,
                "completed": self._completed,
                "overloaded": self._overloaded,
                "expired": self._expired,
                "cancelled": self._cancelled,
                "failed": self._failed,
                "reloads": self._reloads,
                "captured": self._captured,
                "capture_rate": round(self._captured / self._completed, 4)
                if self._completed else 0.0,
                "batches": self._batches,
                "batch_occupancy": round(
                    self._batch_items
                    / (self._batches * self.max_batch_size), 4)
                if self._batches else 0.0,
                "pad_waste_frac": round(self._pad_items / dispatched, 4)
                if dispatched else 0.0,
                "bucket_hits": dict(sorted(self._bucket_hits.items())),
                "queue_depth": self._queue_depth,
                "queue_depth_max": self._queue_depth_max,
            }
        out["latency_p50_ms"] = round(_percentile(lat, 50), 3)
        out["latency_p95_ms"] = round(_percentile(lat, 95), 3)
        out["latency_p99_ms"] = round(_percentile(lat, 99), 3)
        return out

    def report_str(self) -> str:
        r = self.report()
        buckets = ", ".join("%d:%d" % (b, n)
                            for b, n in r["bucket_hits"].items()) or "-"
        return ("serve engine %r\n"
                "  requests: %d submitted / %d completed "
                "(%d overloaded, %d expired, %d cancelled, %d failed), "
                "%d reloads\n"
                "  latency ms: p50 %.2f  p95 %.2f  p99 %.2f\n"
                "  batches: %d, occupancy %.2f of max %d, "
                "pad waste %.1f%%\n"
                "  bucket hits: %s\n"
                "  queue depth: %d now / %d high-water" % (
                    self.name, r["submitted"], r["completed"],
                    r["overloaded"], r["expired"], r["cancelled"],
                    r["failed"], r["reloads"],
                    r["latency_p50_ms"], r["latency_p95_ms"],
                    r["latency_p99_ms"], r["batches"], r["batch_occupancy"],
                    self.max_batch_size, 100.0 * r["pad_waste_frac"],
                    buckets, r["queue_depth"], r["queue_depth_max"]))


class DecodeStats:
    """Counters for one DecodeEngine (continuous batching): written from
    the submitter threads and the decode-loop thread under a lock,
    snapshotted atomically by ``report()``.

    The capacity question here is **slot occupancy**: the mean fraction
    of decode slots holding an active stream per step.  Low occupancy
    at high load means requests are not arriving fast enough to refill
    freed slots (or the queue bound is too tight); tokens/step is
    occupancy x num_slots."""

    def __init__(self, name: str, num_slots: int):
        self.name = name
        self.num_slots = int(num_slots)
        self._lock = make_lock("serve.stats")
        self._submitted = 0
        self._admitted = 0
        self._completed = 0
        self._failed = 0
        self._expired = 0
        self._cancelled = 0
        self._overloaded = 0
        self._reloads = 0
        self._captured = 0
        self._steps = 0
        self._slot_steps = 0
        self._tokens_out = 0
        self._queue_depth = 0
        self._queue_depth_max = 0
        self._lat_ms = collections.deque(maxlen=LATENCY_WINDOW)

    # -- recording ---------------------------------------------------------
    def on_submit(self, queue_depth: int) -> None:
        with self._lock:
            self._submitted += 1
            self._queue_depth = queue_depth
            if queue_depth > self._queue_depth_max:
                self._queue_depth_max = queue_depth

    def on_overload(self) -> None:
        with self._lock:
            self._overloaded += 1

    def on_admitted(self, n: int = 1) -> None:
        with self._lock:
            self._admitted += n

    def on_expired(self, n: int = 1) -> None:
        with self._lock:
            self._expired += n

    def on_cancelled(self, n: int = 1) -> None:
        with self._lock:
            self._cancelled += n

    def on_failed(self, n: int = 1) -> None:
        with self._lock:
            self._failed += n

    def on_step(self, active: int, emitted: int) -> None:
        with self._lock:
            self._steps += 1
            self._slot_steps += active
            self._tokens_out += emitted

    def on_complete(self, latencies_ms) -> None:
        with self._lock:
            self._completed += len(latencies_ms)
            self._lat_ms.extend(latencies_ms)

    def on_reload(self) -> None:
        with self._lock:
            self._reloads += 1

    def on_captured(self) -> None:
        """Stream sampled into the online-training capture — not a
        terminal outcome (see ServeStats.on_captured)."""
        with self._lock:
            self._captured += 1

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self._queue_depth = depth

    def _outstanding_locked(self) -> int:
        """Terminal-outcome balance — EVERY new terminal counter must be
        subtracted here and only here (lock held by the caller)."""
        return max(0, self._submitted - self._completed - self._failed
                   - self._expired - self._cancelled)

    def outstanding(self) -> int:
        with self._lock:
            return self._outstanding_locked()

    # -- reading -----------------------------------------------------------
    def report(self) -> Dict:
        with self._lock:
            lat = sorted(self._lat_ms)
            out = {
                "kind": "decode",
                "num_slots": self.num_slots,
                "outstanding": self._outstanding_locked(),
                "submitted": self._submitted,
                "admitted": self._admitted,
                "completed": self._completed,
                "overloaded": self._overloaded,
                "expired": self._expired,
                "cancelled": self._cancelled,
                "failed": self._failed,
                "reloads": self._reloads,
                "captured": self._captured,
                "capture_rate": round(self._captured / self._completed, 4)
                if self._completed else 0.0,
                "steps": self._steps,
                "tokens_out": self._tokens_out,
                "slot_occupancy": round(
                    self._slot_steps / (self._steps * self.num_slots), 4)
                if self._steps else 0.0,
                "queue_depth": self._queue_depth,
                "queue_depth_max": self._queue_depth_max,
            }
        out["latency_p50_ms"] = round(_percentile(lat, 50), 3)
        out["latency_p95_ms"] = round(_percentile(lat, 95), 3)
        out["latency_p99_ms"] = round(_percentile(lat, 99), 3)
        return out

    def report_str(self) -> str:
        r = self.report()
        return ("decode engine %r\n"
                "  streams: %d submitted / %d admitted / %d completed "
                "(%d overloaded, %d expired, %d cancelled, %d failed), "
                "%d reloads\n"
                "  latency ms: p50 %.2f  p95 %.2f  p99 %.2f\n"
                "  steps: %d, %d tokens out, slot occupancy %.2f of %d "
                "slots\n"
                "  queue depth: %d now / %d high-water" % (
                    self.name, r["submitted"], r["admitted"],
                    r["completed"], r["overloaded"], r["expired"],
                    r["cancelled"], r["failed"], r["reloads"],
                    r["latency_p50_ms"], r["latency_p95_ms"],
                    r["latency_p99_ms"], r["steps"], r["tokens_out"],
                    r["slot_occupancy"], self.num_slots,
                    r["queue_depth"], r["queue_depth_max"]))


class PagedStats(DecodeStats):
    """DecodeStats plus the paged-serving axes (see module docstring).
    Written from the submitter threads and the ONE paged-decode thread;
    the terminal-outcome balance is inherited — dropped_streams is NOT
    a terminal counter (a dropped stream also counts failed), it is the
    zero-floor health gauge."""

    def __init__(self, name: str, num_slots: int, pool_blocks: int):
        super().__init__(name, num_slots)
        self.pool_blocks = int(pool_blocks)
        self._prefill_tokens = 0
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._dropped_streams = 0
        self._blocks_used = 0
        self._blocks_reserved = 0
        self._blocks_used_peak = 0
        self._it_ms = collections.deque(maxlen=LATENCY_WINDOW)

    # -- recording ---------------------------------------------------------
    def on_prefill(self, tokens: int) -> None:
        with self._lock:
            self._prefill_tokens += tokens

    def on_spec_round(self, proposed: int, accepted: int) -> None:
        with self._lock:
            self._spec_rounds += 1
            self._spec_proposed += proposed
            self._spec_accepted += accepted

    def on_dropped(self, n: int = 1) -> None:
        with self._lock:
            self._dropped_streams += n

    def on_inter_token(self, gaps_ms) -> None:
        with self._lock:
            self._it_ms.extend(gaps_ms)

    def set_pool(self, used: int, reserved: int) -> None:
        with self._lock:
            self._blocks_used = used
            self._blocks_reserved = reserved
            if used > self._blocks_used_peak:
                self._blocks_used_peak = used

    # -- reading -----------------------------------------------------------
    def report(self) -> Dict:
        out = super().report()
        with self._lock:
            it = sorted(self._it_ms)
            out.update({
                "kind": "paged",
                "prefill_tokens": self._prefill_tokens,
                "spec_rounds": self._spec_rounds,
                "spec_proposed": self._spec_proposed,
                "spec_accepted": self._spec_accepted,
                "spec_accept_rate": round(
                    self._spec_accepted / self._spec_proposed, 4)
                if self._spec_proposed else 0.0,
                "dropped_streams": self._dropped_streams,
                "kv_blocks": self.pool_blocks,
                "kv_blocks_used": self._blocks_used,
                "kv_blocks_reserved": self._blocks_reserved,
                "kv_utilization": round(
                    self._blocks_used / self.pool_blocks, 4)
                if self.pool_blocks else 0.0,
                # peak survives stream completion: "how full did the
                # pool get" outlives "is anything live right now"
                "kv_utilization_peak": round(
                    self._blocks_used_peak / self.pool_blocks, 4)
                if self.pool_blocks else 0.0,
            })
        out["inter_token_p50_ms"] = round(_percentile(it, 50), 3)
        out["inter_token_p99_ms"] = round(_percentile(it, 99), 3)
        return out

    def report_str(self) -> str:
        r = self.report()
        return ("paged decode engine %r\n"
                "  streams: %d submitted / %d admitted / %d completed "
                "(%d overloaded, %d expired, %d cancelled, %d failed, "
                "%d dropped)\n"
                "  latency ms: p50 %.2f  p99 %.2f; inter-token p50 %.2f "
                "p99 %.2f\n"
                "  steps: %d, %d tokens out, %d prefill tokens, slot "
                "occupancy %.2f of %d\n"
                "  spec decode: %d rounds, %d proposed, %d accepted "
                "(rate %.2f)\n"
                "  kv pool: %d used / %d reserved / %d blocks "
                "(util %.2f)\n"
                "  queue depth: %d now / %d high-water" % (
                    self.name, r["submitted"], r["admitted"],
                    r["completed"], r["overloaded"], r["expired"],
                    r["cancelled"], r["failed"], r["dropped_streams"],
                    r["latency_p50_ms"], r["latency_p99_ms"],
                    r["inter_token_p50_ms"], r["inter_token_p99_ms"],
                    r["steps"], r["tokens_out"], r["prefill_tokens"],
                    r["slot_occupancy"], self.num_slots,
                    r["spec_rounds"], r["spec_proposed"],
                    r["spec_accepted"], r["spec_accept_rate"],
                    r["kv_blocks_used"], r["kv_blocks_reserved"],
                    r["kv_blocks"], r["kv_utilization"],
                    r["queue_depth"], r["queue_depth_max"]))
