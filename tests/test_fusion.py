"""mxnet_tpu.passes.fuse + ops.fused: operator fusion (tier-1, CPU).

ISSUE 11 contracts: golden-graph structure + numerical parity for every
fusion rewrite (f32 BITWISE — fusion reorders no math; int8 within the
calibrated tolerance the unfused quantized graph already meets);
single-consumer / non-head safety rules; ``__sharding__`` attr survival;
the pass-ordering footgun raising a loud PassError with the corrected
order; fused-vs-unfused compile-cache key disjointness; zero XLA
compiles in the steady fused serve loop; the Pallas epilogue kernel's
interpret-mode parity; and tools/dump_passes.py rendering the
``_fused_*`` census with ``--diff`` shrinkage and stage dumps.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))

import mxnet_tpu as mx
from mxnet_tpu import passes
from mxnet_tpu.passes import (ElementwiseFusePass, FuseEpiloguePass,
                              PassError, PassPipeline, QuantizePass,
                              build_serving_pipeline, calibrate_arrays,
                              default_inference_pipeline)

IN_DIM = 16
HIDDEN = 32
CLASSES = 4


def _node_ops(sym):
    return [n["op"] for n in json.loads(sym.tojson())["nodes"]]


def _mlp():
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=HIDDEN, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=HIDDEN, name="fc2")
    net = mx.sym.Activation(net, act_type="tanh", name="tanh2")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _params(seed=0, scale=0.3):
    rng = np.random.RandomState(seed)
    return {
        "fc1_weight": (rng.randn(HIDDEN, IN_DIM) * scale).astype(np.float32),
        "fc1_bias": (rng.randn(HIDDEN) * 0.1).astype(np.float32),
        "fc2_weight": (rng.randn(HIDDEN, HIDDEN) * scale).astype(np.float32),
        "fc2_bias": (rng.randn(HIDDEN) * 0.1).astype(np.float32),
        "fc3_weight": (rng.randn(CLASSES, HIDDEN) * scale).astype(np.float32),
        "fc3_bias": np.zeros(CLASSES, np.float32),
    }


def _forward(sym, params, X, extra_shapes=None):
    shapes = {"data": tuple(X.shape)}
    shapes.update({"softmax_label": (X.shape[0],)}
                  if extra_shapes is None else extra_shapes)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", **shapes)
    exe.copy_params_from(params, {}, allow_extra_params=True)
    exe.arg_dict["data"][:] = np.asarray(X, exe.arg_dict["data"].dtype)
    return np.asarray(exe.forward(is_train=False)[0]._get())


def _calib_feeds(n=4, batch=8, seed=1):
    rng = np.random.RandomState(seed)
    return [{"data": rng.rand(batch, IN_DIM).astype(np.float32)}
            for _ in range(n)]


# ---------------------------------------------------------------------------
# epilogue fusion: golden graphs + parity


def test_fc_act_fusion_golden_and_bitwise():
    sym = _mlp()
    params = _params()
    p = FuseEpiloguePass()
    pipe = PassPipeline([p], name="t-fuse")
    out, _ = pipe.run(sym, params)
    ops = _node_ops(out)
    # fc1+relu1 and fc2+tanh2 fuse; fc3 (no activation) stays
    assert ops.count("_fused_FullyConnected") == 2
    assert ops.count("FullyConnected") == 1
    assert ops.count("Activation") == 0
    assert p.summary["rewrites"] == 2
    assert set(p.summary["act_fused"]) == {"relu1", "tanh2"}
    # fusion reorders no math: f32 parity is BITWISE
    X = np.random.RandomState(2).rand(8, IN_DIM).astype(np.float32)
    np.testing.assert_array_equal(_forward(sym, params, X),
                                  _forward(out, params, X))
    # the fused node carries the epilogue's name: outputs unchanged
    assert out.list_outputs() == sym.list_outputs()


def test_conv_act_fusion_golden_and_bitwise():
    net = mx.sym.Variable("data")
    net = mx.sym.Convolution(net, kernel=(3, 3), num_filter=4, pad=(1, 1),
                             name="c1")
    net = mx.sym.Activation(net, act_type="relu", name="cr1")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    rng = np.random.RandomState(0)
    params = {"c1_weight": (rng.randn(4, 3, 3, 3) * 0.2).astype(np.float32),
              "c1_bias": (rng.randn(4) * 0.1).astype(np.float32),
              "fc_weight": (rng.randn(CLASSES, 4 * 8 * 8) * 0.1
                            ).astype(np.float32),
              "fc_bias": np.zeros(CLASSES, np.float32)}
    out, _ = PassPipeline([FuseEpiloguePass()], name="t-conv").run(net,
                                                                   params)
    ops = _node_ops(out)
    assert ops.count("_fused_Convolution") == 1
    assert ops.count("Convolution") == 0
    X = rng.rand(2, 3, 8, 8).astype(np.float32)
    np.testing.assert_array_equal(_forward(net, params, X),
                                  _forward(out, params, X))


def test_shared_producer_not_fused():
    """An FC whose output feeds the activation AND something else must
    not fuse: fusing would duplicate the GEMM (or change semantics)."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=HIDDEN, name="fc_s")
    act = mx.sym.Activation(fc, act_type="relu", name="r_s")
    y = act + fc                     # second consumer of fc
    p = FuseEpiloguePass()
    out, _ = PassPipeline([p], name="t-shared").run(y, None)
    ops = _node_ops(out)
    assert ops.count("_fused_FullyConnected") == 0
    assert ops.count("FullyConnected") == 1
    assert p.summary["rewrites"] == 0


def test_head_producer_not_fused():
    """An FC that is itself a graph output must survive fusion — its
    output is part of the external contract."""
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, num_hidden=HIDDEN, name="fc_h")
    act = mx.sym.Activation(fc, act_type="relu", name="r_h")
    grouped = mx.sym.Group([fc, act])
    out, _ = PassPipeline([FuseEpiloguePass()], name="t-head").run(grouped,
                                                                   None)
    ops = _node_ops(out)
    assert ops.count("FullyConnected") == 1
    assert ops.count("_fused_FullyConnected") == 0
    assert out.list_outputs() == grouped.list_outputs()


def test_quantized_epilogue_fusion_golden_and_tolerance():
    """After QuantizePass the hidden layers are _quantized_FC -> Act ->
    _contrib_quantize chains; fusion collapses each into ONE
    _fused_quantized_FullyConnected whose out_scale absorbs the q node
    (int8 out), bitwise-identical to the unfused quantized graph and
    within the calibrated tolerance of f32."""
    sym = _mlp()
    params = _params()
    calib = calibrate_arrays(sym, _calib_feeds(), arg_params=params)
    plain = default_inference_pipeline(
        quantize=QuantizePass(calib=calib), name="t-q-plain")
    fused = default_inference_pipeline(
        quantize=QuantizePass(calib=calib), fuse=True, name="t-q-fuse")
    qsym, qparams = plain.run(sym, params)
    fsym, fparams = fused.run(sym, params)
    qops, fops = _node_ops(qsym), _node_ops(fsym)
    assert qops.count("_quantized_FullyConnected") == 2
    assert fops.count("_fused_quantized_FullyConnected") == 2
    assert fops.count("_quantized_FullyConnected") == 0
    assert fops.count("Activation") == 0
    # the q node feeding fc2's data was absorbed into fc1's epilogue
    assert fops.count("_contrib_quantize") \
        == qops.count("_contrib_quantize") - 1
    # the absorbed epilogue carries the SAME scale the q node had
    fdoc = json.loads(fsym.tojson())
    out_scales = [float(n["param"]["out_scale"])
                  for n in fdoc["nodes"]
                  if n["op"] == "_fused_quantized_FullyConnected"
                  and "out_scale" in n.get("param", {})]
    assert len(out_scales) == 1 and out_scales[0] > 0
    X = np.random.RandomState(7).rand(8, IN_DIM).astype(np.float32)
    yq = _forward(qsym, qparams, X)
    yf = _forward(fsym, fparams, X)
    np.testing.assert_array_equal(yq, yf)          # same math, same order
    np.testing.assert_allclose(_forward(sym, params, X), yf, atol=0.02)


# ---------------------------------------------------------------------------
# elementwise chains


def test_elemwise_chain_fused_golden_and_bitwise():
    data = mx.sym.Variable("data")
    y = (data * 2.0) + 3.0
    y = mx.sym.exp(y, name="e1")
    y = mx.sym.FullyConnected(y, num_hidden=CLASSES, name="fc")
    p = ElementwiseFusePass()
    out, _ = PassPipeline([p], name="t-chain").run(y, None)
    ops = _node_ops(out)
    assert ops.count("_fused_elemwise") == 1
    assert not any(o.endswith("_scalar") for o in ops)
    assert "exp" not in ops
    assert p.summary["steps_fused"] == 3
    params = {"fc_weight": _params()["fc3_weight"][:, :IN_DIM],
              "fc_bias": np.zeros(CLASSES, np.float32)}
    X = np.random.RandomState(3).rand(8, IN_DIM).astype(np.float32)
    np.testing.assert_array_equal(
        _forward(y, params, X, extra_shapes={}),
        _forward(out, params, X, extra_shapes={}))


def test_elemwise_chain_stops_at_multi_consumer():
    """An interior node with a second consumer breaks the chain — its
    value is needed elsewhere, so it must stay materialized."""
    data = mx.sym.Variable("data")
    a = data * 2.0                     # 2 consumers: chain must not eat it
    b = mx.sym.exp(a + 1.0, name="e")
    y = b + a
    p = ElementwiseFusePass()
    out, _ = PassPipeline([p], name="t-multi").run(y, None)
    ops = _node_ops(out)
    assert ops.count("_mul_scalar") == 1           # survives un-fused
    assert ops.count("_fused_elemwise") == 1       # (+1.0, exp) chain
    X = np.random.RandomState(4).rand(4, IN_DIM).astype(np.float32)
    np.testing.assert_array_equal(
        _forward(y, {}, X, extra_shapes={}),
        _forward(out, {}, X, extra_shapes={}))


def test_u8_wire_prologue_chain_fuses_and_stays_bitwise():
    """The u8 wire's cast -> -mean -> *scale prologue: the scalar pair
    fuses into one _fused_elemwise and the served math is unchanged."""
    net = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    params = {"fc_weight": _params()["fc3_weight"][:, :IN_DIM],
              "fc_bias": np.zeros(CLASSES, np.float32)}
    mk = lambda fuse: build_serving_pipeline(
        u8_wire={"mean": 128.0, "scale": 1 / 128.0, "hwc": False},
        fuse=fuse, name="t-u8f%s" % fuse)
    plain_sym, _ = mk(False).run(net, dict(params))
    fused_sym, _ = mk(True).run(net, dict(params))
    assert "_fused_elemwise" in _node_ops(fused_sym)
    X = np.random.RandomState(5).randint(
        0, 256, (4, IN_DIM)).astype(np.uint8)
    np.testing.assert_array_equal(_forward(plain_sym, params, X),
                                  _forward(fused_sym, params, X))


# ---------------------------------------------------------------------------
# safety: attrs, ordering, env knob


def test_sharding_attr_survives_fusion():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("fcs_weight", attr={"__sharding__": "tp,None"})
    net = mx.sym.FullyConnected(data, weight=w, num_hidden=HIDDEN,
                                name="fcs", attr={"__sharding__": "x"})
    net = mx.sym.Activation(net, act_type="relu", name="rs")
    net = mx.sym.FullyConnected(net, num_hidden=CLASSES, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    out, _ = PassPipeline([FuseEpiloguePass(), ElementwiseFusePass()],
                          name="t-attr").run(net, None)
    attrs = out.attr_dict()
    assert attrs.get("fcs_weight", {}).get("__sharding__") == "tp,None"
    # the fused node (named after the epilogue) inherits the producer's
    # attrs — the cross-layer contract rides along
    assert attrs.get("rs", {}).get("__sharding__") == "x"


def test_pass_ordering_footgun_raises_with_corrected_order():
    """Fusion before quantization silently defeats int8 epilogue fusion
    (quantize skips _fused_* nodes) — the pipeline refuses it LOUDLY and
    names the corrected order."""
    sym = _mlp()
    params = _params()
    calib = calibrate_arrays(sym, _calib_feeds(), arg_params=params)
    with pytest.raises(PassError) as ei:
        PassPipeline([FuseEpiloguePass(), QuantizePass(calib=calib)],
                     name="t-bad")
    msg = str(ei.value)
    assert "fuse_epilogue" in msg and "quantize" in msg
    assert "Corrected order" in msg
    assert msg.index("'quantize'", msg.index("Corrected order")) \
        < msg.index("'fuse_epilogue'", msg.index("Corrected order"))
    # elemwise_fuse before fuse_epilogue is the same class of bug
    with pytest.raises(PassError):
        PassPipeline([ElementwiseFusePass(), FuseEpiloguePass()],
                     name="t-bad2")
    # the canonical order is what default_inference_pipeline builds
    good = default_inference_pipeline(
        quantize=QuantizePass(calib=calib), fuse=True, name="t-good")
    assert [p.name for p in good.canonical_order()] \
        == [p.name for p in good.passes]


def test_fuse_env_knob(monkeypatch):
    monkeypatch.setenv("MXNET_FUSE", "0")
    off = build_serving_pipeline(name="t-envoff")
    assert "fuse_epilogue" not in [p.name for p in off.passes]
    monkeypatch.delenv("MXNET_FUSE")
    on = build_serving_pipeline(name="t-envon")
    assert [p.name for p in on.passes][-2:] == ["fuse_epilogue",
                                                "elemwise_fuse"]
    # fingerprints must differ: fused programs can never alias unfused
    assert off.fingerprint() != on.fingerprint()


# ---------------------------------------------------------------------------
# compile-cache keys + steady serve loop


def test_fused_and_unfused_cache_keys_disjoint(tmp_path):
    """The aliasing contract has two halves.  (1) FAST keys are
    disjoint: the fused graph's ``__passes__`` fingerprint joins
    ``Executor._program_desc``, so the trace-free fast path can never
    hand a graph the other variant's program without checking.  (2)
    f32 fusion is EXACT — same jnp calls, same order — so both variants
    lower to byte-identical StableHLO and the content-addressed ground-
    truth layer dedups the executable: warming the fused grid after the
    unfused one costs ZERO new XLA compiles.  (Quantized fused programs
    lower differently and stay fully disjoint — the quantize-vs-f32
    test in test_passes.py covers that axis.)"""
    from mxnet_tpu import compile_cache as cc
    from mxnet_tpu.compile_cache.stats import _reset_stats, get_stats
    from mxnet_tpu.predictor import Predictor

    sym = _mlp()
    params = _params()
    shapes = [{"data": (b, IN_DIM), "softmax_label": (b,)} for b in (1, 2)]

    def predictor(fuse):
        return Predictor(sym.tojson(), dict(params), shapes[0],
                         pipeline=build_serving_pipeline(
                             fuse=fuse, name="t-cc%s" % fuse))

    def totals():
        t = get_stats().totals()
        return t["hits"], t["misses"]

    # (1) the fast keys can never alias
    pu, pf = predictor(False), predictor(True)
    assert pu.symbol._graph_attrs["__passes__"] \
        != pf.symbol._graph_attrs["__passes__"]
    assert pu._exec._program_desc() != pf._exec._program_desc()

    _reset_stats()
    cc.configure(str(tmp_path / "cc"), 64)
    try:
        predictor(False).precompile(shapes, threads=1)   # all misses
        h, m = totals()
        assert h == 0 and m == len(shapes)
        # (2) fused grid: identical lowered programs -> ground-truth
        # HITS (shared executable), zero new compiles
        predictor(True).precompile(shapes, threads=1)
        h, m = totals()
        assert h == len(shapes) and m == len(shapes)
        predictor(True).precompile(shapes, threads=1)    # warm again
        h, m = totals()
        assert h == 2 * len(shapes) and m == len(shapes)
    finally:
        cc.reset()
        _reset_stats()


def test_fused_serve_steady_loop_zero_compiles():
    from compile_guard import assert_no_compiles
    from mxnet_tpu.serve import ServeEngine
    eng = ServeEngine(_mlp(), _params(),
                      {"data": (1, IN_DIM), "softmax_label": (1,)},
                      batch_buckets=(1, 2, 4), name="t-fuse-serve",
                      fuse=True)
    try:
        assert "fuse_epilogue" in [p.name for p in eng.pipeline.passes]
        X = np.random.RandomState(14).rand(16, IN_DIM).astype(np.float32)
        for x in X[:4]:                      # touch the grid once
            eng.predict(x, timeout=60)
        for fut in eng.submit_many(X[:4]):
            fut.result(timeout=60)
        with assert_no_compiles("steady fused serve loop"):
            for x in X[4:10]:
                eng.predict(x, timeout=60)
            for fut in eng.submit_many(X[10:]):
                fut.result(timeout=60)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# Pallas epilogue kernel


def test_pallas_fc_epilogue_interpret_parity():
    from mxnet_tpu.ops.pallas_kernels import HAS_PALLAS, fused_fc_epilogue
    if not HAS_PALLAS:
        pytest.skip("pallas unavailable")
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(8, 128).astype(np.float32))
    w = jnp.asarray(rng.randn(256, 128).astype(np.float32))
    b = jnp.asarray(rng.randn(256).astype(np.float32))
    ref = np.asarray(x) @ np.asarray(w).T + np.asarray(b)
    out = fused_fc_epilogue(x, w, b, "relu", interpret=True)
    assert np.allclose(np.asarray(out), np.maximum(ref, 0), atol=2e-5)
    scale = 0.05
    outq = fused_fc_epilogue(x, w, b, "relu", out_scale=scale,
                             interpret=True)
    refq = np.clip(np.round(np.maximum(ref, 0) / scale), -127, 127)
    assert outq.dtype == jnp.int8
    # interpret-mode matmul rounds differently at the last ulp; only
    # boundary values may flip by one quantization step
    assert np.abs(np.asarray(outq).astype(np.int32)
                  - refq.astype(np.int32)).max() <= 1


def test_pallas_fc_epilogue_cpu_falls_back():
    """Off-TPU without interpret the hook must return None so the op's
    jnp body runs — CPU tier-1 numerics stay the unfused graph's."""
    from mxnet_tpu.ops.pallas_kernels import fused_fc_epilogue
    import jax
    import jax.numpy as jnp
    if jax.default_backend() == "tpu":
        pytest.skip("TPU host: the kernel path is live here")
    x = jnp.zeros((8, 128), jnp.float32)
    w = jnp.zeros((128, 128), jnp.float32)
    assert fused_fc_epilogue(x, w, None, "relu") is None


# ---------------------------------------------------------------------------
# tools/dump_passes.py renders the fused census + stage dumps


def test_dump_passes_shows_fusion_and_stage_dumps(tmp_path):
    sym_path = str(tmp_path / "m-symbol.json")
    _mlp().save(sym_path)
    prefix = str(tmp_path / "stage")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "dump_passes.py"),
         sym_path, "--diff", "--out-prefix", prefix],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert res.returncode == 0, res.stdout + res.stderr
    assert "fuse_epilogue" in res.stdout
    assert "+2 _fused_FullyConnected" in res.stdout     # census delta
    assert "-2 Activation" in res.stdout                # shrinkage
    stage_files = sorted(os.listdir(str(tmp_path)))
    assert any("fuse_epilogue" in f for f in stage_files)
    # every stage dump is a loadable symbol
    from mxnet_tpu.symbol import load_json
    for f in stage_files:
        if f.startswith("stage."):
            with open(str(tmp_path / f)) as fh:
                load_json(fh.read())
