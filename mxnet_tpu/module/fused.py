"""Fused train step for the Module/FeedForward reference API.

The reference's hot loop (model.py:119-310, module/module.py:377-394) has
python push gradients per-parameter through kvstore and run the optimizer
per-parameter on the host. On TPU that python round-trip dominates: the
fwd+bwd pair is one XLA program, but ~2N more dispatches follow it every
batch. This module collapses the whole batch body — forward, backward,
cross-device gradient reduction, and the optimizer — into ONE donated,
jit-compiled XLA program over the device mesh:

* batch slicing across contexts  -> batch-axis NamedSharding over "dp"
* kvstore local/device reduce    -> psum inserted by GSPMD (rides ICI)
* per-param python updater       -> optimizer's fused_update_fn traced in
* buffer reuse                   -> donation of the whole train state

Engaged automatically by ``Module.init_optimizer`` when semantics allow
(see Module._fusable); anything it can't express (monitor, ctx_group,
grad_req!='write', optimizers without a functional form, shared/bucketing
executors, dist_async kvstores) falls back to the reference path
unchanged.  dist_sync kvstores fuse too (``global_dp``): the mesh spans
every process's devices, each worker feeds its batch as its slice of the
global array, and the cross-process gradient reduction is a GSPMD
collective instead of kvstore round trips.  Disable with
MXNET_FUSED_TRAIN=0.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError, get_env
from ..executor import _GraphProgram
from ..ndarray import NDArray
from .. import trace as _trace

__all__ = ["FusedTrainStep"]


def _hparams_undeclared(cls):
    """True when the class providing this optimizer's fused_update_fn did
    not also declare (or inherit from a more-derived class declaring)
    ``fused_hparams`` — i.e. the baked-scalar snapshot could be blind to
    state the closures capture."""
    def definer(name):
        for c in cls.__mro__:
            if name in c.__dict__:
                return c
        return None
    fu, fh = definer("fused_update_fn"), definer("fused_hparams")
    return fh is None or not issubclass(fh, fu)


class FusedTrainStep:
    """One donated XLA program per (shapes, dtypes): fwd+bwd+reduce+update.

    State layout (a single donated pytree)::

        {"params": {name: w}, "opt": {name: state}, "aux": {name: a},
         "fixed": {name: w}}

    ``step(state, batch, lr, t)`` advances it one batch and returns the
    graph outputs; ``forward_only(state, batch)`` evaluates without
    touching state (used for eval/predict on the live training params).
    """

    def __init__(self, symbol, contexts, data_names: Sequence[str],
                 label_names: Sequence[str], param_names: Sequence[str],
                 fixed_param_names: Sequence[str], optimizer,
                 label_shapes=None, remat: bool = False,
                 compute_dtype=None, global_dp: bool = False,
                 mesh=None, sharding=None):
        self.global_dp = global_dp
        self.named_mesh = mesh is not None
        if mesh is not None:
            # first-class multichip: a user-provided named mesh (e.g.
            # parallel.make_mesh([("dp", 4), ("tp", 2)])).  The batch
            # axis shards over "dp"; per-param GSPMD constraints over
            # the remaining axes come from ``sharding`` below.
            mdevs = list(mesh.devices.ravel())
            if global_dp:
                # dist_sync + named mesh: the mesh axes span the WHOLE
                # process group (mxnet_tpu.dist).  A mesh covering only
                # a subset would leave the other workers' devices out
                # of the collectives — every SPMD program would hang at
                # the first cross-process barrier, so refuse up front
                # with the shapes.
                if set(mdevs) != set(jax.devices()):
                    raise MXNetError(
                        "dist_sync needs the named mesh to span every "
                        "process's devices (%d in mesh, %d global over "
                        "%d processes); build it from jax.devices() — "
                        "parallel.make_mesh does by default"
                        % (len(mdevs), len(jax.devices()),
                           jax.process_count()))
            if len(set(mdevs)) != len(mdevs):
                raise MXNetError("fused step needs distinct devices")
            if "dp" not in mesh.axis_names:
                raise MXNetError(
                    "mesh %s has no 'dp' axis; the batch shards over "
                    "'dp' — use dp=1 for pure tensor parallelism"
                    % (dict(mesh.shape),))
            self.mesh = mesh
        else:
            devices = [c.jax_device() for c in contexts]
            if len(set(devices)) != len(devices):
                raise MXNetError("fused step needs distinct devices")
            if global_dp:
                # multi-host dist_sync: ONE mesh over every process's
                # devices; GSPMD turns the dp gradient mean into
                # cross-process collectives (ICI within a slice, DCN
                # across) — no kvstore round trips in the hot loop
                # (reference kvstore_dist.h:65-98 semantics at "python
                # pushes one pointer" cost)
                if set(devices) != set(jax.local_devices()):
                    raise MXNetError(
                        "dist_sync fused step needs the module bound on "
                        "every local device (%d bound, %d local)"
                        % (len(devices), jax.local_device_count()))
                self.mesh = Mesh(np.array(jax.devices()), ("dp",))
            else:
                self.mesh = Mesh(np.array(devices), ("dp",))
        self.dp_size = int(self.mesh.shape["dp"])
        # how many PROCESSES the mesh spans: >1 engages the multi-host
        # contract everywhere (per-process batch slices, broadcast init,
        # host-local output gathers, collective-safe checkpointing) —
        # for dist_sync's implicit dp mesh AND for a named mesh whose
        # axes cross process boundaries (mxnet_tpu.dist)
        self._mesh_procs = len({d.process_index
                                for d in self.mesh.devices.ravel()})
        self.data_names = tuple(data_names)
        self.label_names = tuple(label_names)
        self.label_shapes = dict(label_shapes or [])
        fixed = set(fixed_param_names or ())
        self.train_names = [n for n in param_names if n not in fixed]
        self.fixed_names = [n for n in param_names if n in fixed]
        self.aux_names = symbol.list_auxiliary_states()
        # per-param GSPMD sharding constraints: the ``sharding=`` map
        # merged over ``__sharding__`` symbol attributes (explicit map
        # wins).  Resolved to NamedShardings and applied with
        # lax.with_sharding_constraint inside the step trace, so the
        # partitioner inserts the tensor-parallel collectives.
        from ..parallel.mesh import (normalize_spec, sharding_attrs,
                                     validate_spec)
        specs = sharding_attrs(symbol)
        specs.update(sharding or {})
        known = set(param_names) | set(self.aux_names)
        unknown = sorted(set(specs) - known)
        if unknown:
            raise MXNetError(
                "sharding specs name no bound parameter: %s (params: %s)"
                % (unknown, sorted(known)))
        self.param_specs = {}
        for n, sp in specs.items():
            sp = normalize_spec(sp)
            validate_spec(n, sp, self.mesh)
            self.param_specs[n] = sp
        self.optimizer = optimizer
        fused = optimizer.fused_update_fn()
        if fused is None:
            raise MXNetError("optimizer has no fused form")
        if _hparams_undeclared(type(optimizer)):
            # a fused form whose baked scalars we cannot snapshot could be
            # mutated mid-training without us noticing; refuse to fuse
            raise MXNetError(
                "optimizer %s overrides fused_update_fn without declaring "
                "fused_hparams at the same (or a more derived) class; "
                "falling back to the per-param update path"
                % type(optimizer).__name__)
        self._opt_init, self._opt_update = fused
        # deduped sparse embedding updates (mxnet_tpu.embed): Embedding
        # layers whose ids input is a data variable and whose table is
        # consumed nowhere else train through the sparse path — the step
        # dedups the batch's ids, gathers each unique row ONCE, takes
        # grads w.r.t. those rows only (the take-VJP then scatters into
        # a cap-row buffer, not the full table), and applies the
        # optimizer lazily to the touched rows.  One donated dispatch
        # still covers dense + sparse params.  MXNET_EMBED_SPARSE=0
        # restores the dense take-VJP everywhere (the bench baseline).
        from ..embed.detect import find_sparse_embeds
        from ..embed.sparse import slot_leaves_row_shaped
        self.sparse_embeds = {}
        for n, sp in find_sparse_embeds(symbol, self.data_names,
                                        self.train_names).items():
            # lazy per-row updates need row-shaped optimizer state
            # (SGD/NAG/Adagrad/Adam); anything else keeps the dense path
            # for that table
            if slot_leaves_row_shaped(self._opt_init, sp.vocab, sp.dim,
                                      jnp.float32):
                self.sparse_embeds[n] = sp
        self.embed_stats = None
        if self.sparse_embeds:
            from ..embed.stats import EmbedStats
            from .. import profiler as _prof
            self.embed_stats = EmbedStats("fused")
            _prof.register_embed_stats(self.embed_stats)
        self._embed_stats_every = max(
            1, get_env("MXNET_EMBED_STATS_EVERY", 1, int))
        self._embed_stats_n = 0
        # routed-MoE blocks: graph-side detection registers the stats
        # consumer (per-expert traffic lands here from bench/serve
        # samplers — routing is data-dependent, so there is nothing to
        # sample host-side per step) and stamps each block's routing
        # geometry into the program descriptor
        from ..moe.detect import find_moe_blocks
        self.moe_blocks = find_moe_blocks(symbol)
        self.moe_stats = None
        if self.moe_blocks:
            from ..moe.stats import MoeStats
            from .. import profiler as _prof
            self.moe_stats = MoeStats("fused")
            _prof.register_moe_stats(self.moe_stats)
        # static per-param schedule factors (reference lr_mult/wd_mult and
        # the bias/gamma/beta wd rule, resolved by NAME not index)
        self._lr_mult = {n: optimizer._name_lr_mult(n) for n in self.train_names}
        self._wd = {n: optimizer._name_wd(n) for n in self.train_names}
        # remat: checkpoint the WHOLE loss (see _build_step) instead of
        # per-node jax.checkpoint — wrapping single primitives saves
        # nothing (their inputs stay live) and measured 3x LARGER HLO
        # temp at b1024 by blocking XLA's buffer reuse
        self._remat = remat
        self._prog = _GraphProgram(symbol, {}, None, do_mirror=False)
        # mixed precision the TPU way (fp16-era capability, SURVEY §7):
        # master weights and optimizer state stay f32, the fwd/bwd compute
        # runs in bf16 on the MXU, grads are cast back before the update
        self.compute_dtype = compute_dtype
        from ..symbol import id_valued_inputs
        self._no_cast = set(self.label_names) | id_valued_inputs(symbol)
        # MXNET_SHARD_WEIGHT_UPDATE=1: cross-replica sharded weight
        # update (Xu et al. 2020, arxiv 2004.13336 — the ZeRO-1 recipe
        # the TPU way): gradients reduce-scatter over dp, each replica
        # updates only its shard of every parameter and keeps only its
        # shard of the optimizer state, updated params all-gather back.
        # Same math, optimizer memory and update flops divided by the
        # dp degree; expressed purely through sharding constraints, the
        # partitioner forms the collectives.  Generalized to arbitrary
        # named meshes: the update shards over the mesh's "dp" AXIS
        # (not the whole device set), composing with per-param tensor-
        # parallel specs — a dp=4 x tp=2 mesh shards each tp shard's
        # update 4 ways.
        self.shard_update = (
            get_env("MXNET_SHARD_WEIGHT_UPDATE", False, bool)
            and self.dp_size > 1)
        # on-device augmentation prologue (feed.AugmentSpec): when set,
        # uint8 HWC data batches are cast/cropped/flipped/normalized
        # INSIDE the compiled step (feed.augment), so the feed ships
        # ~4x fewer H2D bytes and the per-image python augment loop
        # disappears from the hot path
        self.device_augment = None
        self._step = None
        self._fwd = None
        self._lr_cache = None
        # multichip observability: per-step dispatch vs (sampled) device
        # time, plus XLA cost analysis + collective counts once an AOT
        # compile ran — surfaced via mx.profiler.multichip_report()
        self.multichip_stats = None
        if len(self.mesh.devices.ravel()) > 1:
            from .. import profiler as _prof
            from ..parallel.mesh import mesh_axes as _mesh_axes
            from ..parallel.mesh import spec_axes as _spec_axes
            self.multichip_stats = _prof.MultichipStats(
                "fused", axes=_mesh_axes(self.mesh),
                spec_axes=sorted({a for sp in self.param_specs.values()
                                  for a in _spec_axes(sp)}))
            _prof.register_multichip_stats(self.multichip_stats)

    def _cast_compute(self, args):
        from ..symbol import cast_compute
        return cast_compute(args, self.compute_dtype, self._no_cast)

    # -- on-device augmentation ---------------------------------------------
    def set_device_augment(self, spec) -> None:
        """Install (or clear) the traced augmentation prologue.  Already-
        built programs are dropped on a real change — the prologue is
        part of the trace and of the compile-cache key; a no-op set
        (same spec, or None over None) keeps the warm programs."""
        if spec is None and self.device_augment is None:
            return
        if getattr(self.device_augment, "signature", None) is not None \
                and spec is not None \
                and self.device_augment.signature() == spec.signature():
            return
        self.device_augment = spec
        self._step = None
        self._fwd = None

    def _maybe_augment(self, batch, rng, train: bool):
        """Trace-time dispatch of the prologue: applies ONLY when the
        first data input arrives as a 4-D uint8 array (the compact HWC
        wire format) — an f32 batch from a host-augmented eval iterator
        or a warmup zero-batch passes through untouched, so one compiled
        family serves both wire formats without runtime branching."""
        spec = self.device_augment
        if spec is None or not self.data_names:
            return batch
        name = self.data_names[0]
        x = batch.get(name)
        if x is None or x.dtype != jnp.uint8 or x.ndim != 4:
            return batch
        from ..feed.augment import AUG_FOLD, augment_batch
        out = dict(batch)
        # a dedicated fold keeps augmentation draws out of the model's
        # own RNG stream; both derive from the per-step key, so resume
        # replays identical crops/flips
        out[name] = augment_batch(x, jax.random.fold_in(rng, AUG_FOLD),
                                  spec, train)
        return out

    # -- placement ----------------------------------------------------------
    def _replicated(self):
        return NamedSharding(self.mesh, P())

    def _batched(self):
        return NamedSharding(self.mesh, P("dp"))

    def batched_sharding(self):
        """Public handle for input pipelines (feed.device_feed /
        feed.DevicePutStage): batches staged with this sharding are
        recognized by make_batch and passed through without a second
        transfer — the H2D lands once, async, in the exact layout the
        donated step program compiled for."""
        return self._batched()

    def megabatched_sharding(self):
        """Sharding for a K-step megabatch: leading K axis unsharded
        (the scan iterates it), batch axis sharded over dp — the layout
        the superstep program compiles for.  feed.DevicePrefetchIter's
        megabatch mode stages with this so make_megabatch passes the
        resident arrays through without a second transfer."""
        return NamedSharding(self.mesh, P(None, "dp"))

    def _multiprocess(self):
        return self._mesh_procs > 1

    def _param_sharding(self, name):
        """At-rest sharding for one named param/aux: its declared GSPMD
        spec, replicated when none."""
        return NamedSharding(self.mesh, self.param_specs.get(name, P()))

    def _update_spec(self, x, name=None):
        """Sharding for one update-path leaf (gradient / optimizer
        slot): the param's declared spec, with the leading dim
        additionally sharded over the dp axis when MXNET_SHARD_WEIGHT_
        UPDATE is on and it divides evenly (replicated otherwise — tiny
        params).  Composes: a tp-sharded weight's momentum stays
        tp-sharded AND dp-sharded at rest."""
        from ..parallel.mesh import spec_axes
        nd = getattr(x, "ndim", 0)
        base = tuple(self.param_specs.get(name, P())) if name else ()
        spec = list(base[:nd]) + [None] * (nd - len(base[:nd]))
        if self.shard_update and nd >= 1 and spec and spec[0] is None \
                and "dp" not in spec_axes(spec) \
                and x.shape[0] % self.dp_size == 0:
            # a declared spec may already spend "dp" on another dim
            # (P(None, "dp")) — a second use would be an invalid
            # duplicate-axis PartitionSpec, so the update rides the
            # declared layout alone
            spec[0] = "dp"
        if not any(e is not None for e in spec):
            return self._replicated()
        return NamedSharding(self.mesh, P(*spec))

    def _check_divisible(self, name, shape):
        """A declared spec whose axis does not divide its dim would shard
        unevenly — checkpoint shard indexes and the donated layout both
        want the even case; refuse with the numbers."""
        spec = self.param_specs.get(name)
        if spec is None:
            return
        from ..parallel.mesh import validate_spec
        validate_spec(name, spec, self.mesh, shape=shape)

    def init_state(self, arg_params: Dict[str, NDArray],
                   aux_params: Dict[str, NDArray]):
        """Build the device-resident train state from host param dicts.
        Each leaf lands directly in its declared sharding (tensor-
        parallel params never materialize replicated on the mesh)."""
        rep = self._replicated()

        def host(v):
            a = v._get() if isinstance(v, NDArray) else v
            return np.asarray(a)
        tree = {
            "params": {n: host(arg_params[n]) for n in self.train_names},
            "fixed": {n: host(arg_params[n]) for n in self.fixed_names},
            "aux": {n: host(aux_params[n]) for n in self.aux_names},
        }
        for group in tree.values():
            for n, a in group.items():
                self._check_divisible(n, a.shape)
        if self._multiprocess():
            # dist init semantics: rank 0's value wins everywhere
            # (reference kvstore_dist init); a global device_put needs
            # identical host values on every process anyway.  ONE pytree
            # collective, not one per tensor.
            from jax.experimental import multihost_utils as mhu
            tree = mhu.broadcast_one_to_all(tree)

        def put(a, sh=rep):
            # device_put may alias the caller's buffer when it already
            # lives here; the state is donated every step, so it must own
            # fresh storage or the source NDArrays get deleted under it
            return jnp.copy(jax.device_put(a, sh))
        params = {n: put(a, self._param_sharding(n))
                  for n, a in tree["params"].items()}
        fixed = {n: put(a, self._param_sharding(n))
                 for n, a in tree["fixed"].items()}
        aux = {n: put(a, self._param_sharding(n))
               for n, a in tree["aux"].items()}
        if self.shard_update or self.param_specs:
            # optimizer state lives SHARDED at rest: each replica holds
            # only its slice (the paper's memory saving) and the donated
            # state keeps one stable layout across steps.  Allocate each
            # leaf DIRECTLY into its shard (out_shardings) — a
            # replicate-then-reshard would spike peak HBM by exactly the
            # amount this mode exists to save.
            opt = {}
            init_cache = {}   # one compile per (shape, dtype, spec)
            for n, w in params.items():
                key = (tuple(w.shape), str(w.dtype),
                       repr(self.param_specs.get(n)))
                if key not in init_cache:
                    struct = jax.eval_shape(self._opt_init, w)
                    shardings = jax.tree_util.tree_map(
                        lambda x, _n=n: self._update_spec(x, _n), struct)
                    # lint: allow(raw-jit) — one-shot init compile
                    # per (shape, dtype, spec); out_shardings are LIVE
                    # mesh objects, not serializable cache-key material
                    init_cache[key] = jax.jit(self._opt_init,
                                              out_shardings=shardings)
                opt[n] = init_cache[key](w)
        else:
            opt = {n: self._opt_init(w) for n, w in params.items()}
        # the step counter lives on device and increments in-program: a
        # host-built scalar would cost one transfer per step
        t = jax.device_put(jnp.zeros((), jnp.int32), rep)
        return {"params": params, "opt": opt, "aux": aux, "fixed": fixed,
                "t": t}

    def hparam_signature(self):
        """Snapshot of the optimizer hyperparameters baked into the
        compiled step (everything except lr, which rides in as a runtime
        scalar).  Module.update compares this per batch: a mutation
        (set_lr_mult, wd change, momentum/beta change, ...) drops back to
        the classic path, which resolves them per update like the
        reference."""
        opt = self.optimizer
        # each optimizer class declares which of its scalars the
        # fused_update_fn closures capture (optimizer.fused_hparams);
        # FusedTrainStep.__init__ refused any fused form without the
        # declaration, so nothing baked can escape this snapshot
        baked = tuple((k, getattr(opt, k, None))
                      for k in sorted(opt.fused_hparams))
        return (tuple(sorted(opt.lr_mult.items())),
                tuple(sorted(opt.wd_mult.items())),
                opt.wd, opt.rescale_grad, opt.clip_gradient, baked)

    def make_batch(self, data_batch) -> Dict[str, jnp.ndarray]:
        """Shard one DataBatch over the dp axis of the mesh.  In
        multi-process (dist_sync) mode each process contributes its OWN
        batch as its slice of the global array — the reference's
        data-partitioned-by-rank contract, with the global batch being
        num_workers x the bound batch size."""
        sh = self._batched()
        mp = self._multiprocess()
        if self.embed_stats is not None:
            # dedup-ratio instrumentation on the HOST ids (microseconds
            # on an int batch vs a multi-ms step), sampled every
            # MXNET_EMBED_STATS_EVERY batches — the number
            # mx.profiler.embed_report() and bench_embed's
            # embed_dedup_ratio leg surface
            self._embed_stats_n += 1
            if self._embed_stats_n % self._embed_stats_every == 0:
                by_name = dict(zip(self.data_names, data_batch.data))
                from ..embed.sparse import resolve_cap
                for n, sp in self.sparse_embeds.items():
                    ids = by_name.get(sp.ids_name)
                    if ids is not None:
                        self.embed_stats.note_ids(n, ids.asnumpy())
                        self.embed_stats.note_update(
                            n, resolve_cap(sp.cap, ids.size, sp.vocab))

        def put(arr):
            a = arr._get()
            # already resident with the right sharding (a device-prefetched
            # pipeline): hand it through untouched
            if getattr(a, "sharding", None) == sh:
                return a
            if mp:
                return jax.make_array_from_process_local_data(
                    sh, np.asarray(a))
            return jax.device_put(a, sh)
        out = {}
        for name, arr in zip(self.data_names, data_batch.data):
            out[name] = put(arr)
        labels = data_batch.label or []
        for i, name in enumerate(self.label_names):
            if i < len(labels) and labels[i] is not None:
                out[name] = put(labels[i])
            else:
                # label-free forward (predict): loss layers ignore the
                # label in their forward pass
                shape = self.label_shapes.get(name)
                if shape is None:
                    raise MXNetError("missing label %r" % name)
                if mp:
                    out[name] = jax.make_array_from_process_local_data(
                        sh, np.zeros(shape, np.float32))
                else:
                    out[name] = jax.device_put(
                        jnp.zeros(shape, jnp.float32), sh)
        return out

    def make_megabatch(self, batches):
        """Assemble a K-step megabatch: ``{name: (K, B, ...) array}`` in
        the megabatched sharding.  ``batches`` is either a pre-staged
        object with a ``megabatch`` attribute and stacked ``data``/
        ``label`` lists (feed.MegaBatch — resident arrays already in the
        right sharding pass through untouched) or a list of K DataBatch,
        stacked on host and shipped in ONE device_put per input.
        Returns ``(k, megabatch_dict)``."""
        if self._multiprocess():
            raise MXNetError("superstep megabatches are single-process "
                             "only (dist training keeps per-step dispatch)")
        sh = self.megabatched_sharding()

        def put(arr):
            a = arr._get() if isinstance(arr, NDArray) else arr
            if getattr(a, "sharding", None) == sh:
                return a
            return jax.device_put(np.asarray(a), sh)

        if hasattr(batches, "megabatch"):
            k = int(batches.megabatch)
            out = {}
            for name, arr in zip(self.data_names, batches.data):
                out[name] = put(arr)
            labels = batches.label or []
            for i, name in enumerate(self.label_names):
                if i >= len(labels) or labels[i] is None:
                    raise MXNetError("superstep training needs label %r"
                                     % name)
                out[name] = put(labels[i])
            return k, out

        k = len(batches)
        from ..feed.staging import stack_batch_arrays

        def stack(arrs):
            return stack_batch_arrays(arrs, sh)

        out = {}
        for i, name in enumerate(self.data_names):
            out[name] = stack([b.data[i] for b in batches])
        for i, name in enumerate(self.label_names):
            col = []
            for b in batches:
                lab = b.label[i] if b.label and i < len(b.label) else None
                if lab is None:
                    raise MXNetError("superstep training needs label %r"
                                     % name)
                col.append(lab)
            out[name] = stack(col)
        return k, out

    def host_outputs(self, outs, batch) -> List[NDArray]:
        """Wrap program outputs for host-side consumers (update_metric,
        get_outputs).  Single-process arrays wrap as-is; multi-process
        global arrays come back as THIS worker's rows (batch-major
        outputs) or the full replicated value, matching the reference's
        per-worker metric semantics.  ``batch`` is the program input dict
        the outputs came from — its leading dim is the global row count
        (a stale module-level row count would mis-slice after an
        interleaved eval of a different batch size)."""
        if not self._multiprocess():
            return [NDArray(o) for o in outs]
        from jax.experimental import multihost_utils as mhu
        rows = batch[self.data_names[0]].shape[0] if self.data_names else None
        res = []
        for o in outs:
            local = mhu.global_array_to_host_local_array(
                o, self.mesh, self._host_spec(o, rows))
            res.append(NDArray(np.asarray(local)))
        return res

    @staticmethod
    def _host_spec(o, rows):
        """Batch-major (slice this worker's rows) vs replicated (keep
        whole), decided from the output's ACTUAL sharding: a replicated
        output whose leading dim merely coincides with the global batch
        must not be sliced.  Falls back to the row-count heuristic only
        when the sharding exposes no named spec."""
        spec = getattr(getattr(o, "sharding", None), "spec", None)
        if spec is not None:
            lead = spec[0] if len(spec) else None
            names = lead if isinstance(lead, tuple) else (lead,)
            return P("dp") if "dp" in names else P()
        return P("dp") if (o.ndim >= 1 and o.shape[0] == rows) else P()

    # -- compiled programs ---------------------------------------------------
    def _make_step_fn(self):
        """The ONE batch-body trace: fwd+bwd+reduce+update as a pure
        function of (state, batch, lr, base_key).  _build_step jits it
        directly; build_superstep runs it K times under jax.lax.scan —
        sharing the trace is what makes superstep K bitwise-identical to
        K sequential fused steps."""
        prog = self._prog
        rescale = self.optimizer.rescale_grad
        clip = self.optimizer.clip_gradient
        lr_mult, wd, opt_update = self._lr_mult, self._wd, self._opt_update
        sparse = self.sparse_embeds
        # which params ride GSPMD constraints through the update: every
        # specced (tensor-parallel) param always; every param when the
        # cross-replica sharded weight update is on
        constrained = self.shard_update or bool(self.param_specs)

        def wsc_param(n, w):
            if n in self.param_specs:
                return jax.lax.with_sharding_constraint(
                    w, self._param_sharding(n))
            return w

        def step(state, batch, lr, base_key):
            params, fixed, aux = state["params"], state["fixed"], state["aux"]
            if self.param_specs:
                # pin the declared layouts at the trace root so GSPMD
                # propagates them through the matmuls (inserting the
                # tensor-parallel collectives) instead of re-deriving a
                # layout from scratch
                params = {n: wsc_param(n, w) for n, w in params.items()}
                fixed = {n: wsc_param(n, w) for n, w in fixed.items()}
            t = state["t"] + 1
            # per-step randomness derived in-program from one resident key:
            # creating a fresh host key every batch would cost a transfer
            rng = jax.random.fold_in(base_key, t)
            batch = self._maybe_augment(batch, rng, train=True)

            # sparse embed prologue: dedup each table's id batch, gather
            # the unique rows ONCE (zero-masked for out-of-range / padded
            # ids), and substitute (rows, inverse indices) for (table,
            # ids) — the Embedding op computes take(rows, inv), which is
            # bit-identical to take(table, ids), but its VJP now scatters
            # into a cap-row buffer instead of the full (vocab, dim)
            # table.  full_tables keeps the real tables for the update.
            full_tables = {}
            sparse_ctx = {}
            if sparse:
                from ..embed.sparse import (_mask_oov_rows, dedup_ids,
                                            resolve_cap)
                batch = dict(batch)
                params = dict(params)
                for n, sp in sparse.items():
                    ids = batch[sp.ids_name]
                    flat = ids.reshape(-1).astype(jnp.int32)
                    cap = resolve_cap(sp.cap, flat.shape[0], sp.vocab)
                    uniq, inv = dedup_ids(flat, cap, sentinel=sp.vocab)
                    full_tables[n] = params[n]
                    raw = jnp.take(params[n], uniq, axis=0, mode="clip")
                    params[n] = _mask_oov_rows(raw, uniq, sp.vocab)
                    batch[sp.ids_name] = inv.reshape(ids.shape)
                    sparse_ctx[n] = (uniq, cap)

            def loss_fn(train_params):
                args = dict(train_params)
                args.update(fixed)
                args.update(batch)
                args = self._cast_compute(args)
                outs, new_aux = prog.eval(args, aux, rng, True)
                # aux (BN moving stats) must keep its dtype or the donated
                # state changes signature between steps
                new_aux = {k: v.astype(aux[k].dtype) if k in aux else v
                           for k, v in new_aux.items()}
                return outs, new_aux

            if self._remat:
                # MXNET_BACKWARD_DO_MIRROR=1: rematerialize the forward
                # in the backward pass — activations are not stored, the
                # bwd recomputes them (~1/3 extra FLOPs for ~activation-
                # free HBM), the sublinear-memory trade the reference's
                # mirroring implemented graph-side
                loss_fn = jax.checkpoint(loss_fn)
            outs, vjp_fn, new_aux = jax.vjp(loss_fn, params, has_aux=True)
            grads = vjp_fn([jnp.ones_like(o) for o in outs])[0]

            if sparse:
                from ..embed.sparse import sparse_apply_rows
            new_params, new_opt = {}, {}
            for n, sp in sparse.items():
                # grads[n] is ALREADY per-unique-row: the take-over-inv
                # VJP segment-summed the per-occurrence grads into the
                # cap-row buffer.  Lazy per-row optimizer on the touched
                # rows only; sentinel rows drop on the scatter.
                uniq, cap = sparse_ctx[n]
                w = full_tables[n]
                g = grads[n].astype(w.dtype) * rescale
                if clip is not None:
                    g = jnp.clip(g, -clip, clip)
                new_params[n], new_opt[n] = sparse_apply_rows(
                    w, state["opt"][n], uniq, g, opt_update,
                    lr * lr_mult[n], wd[n], t)
                if constrained:
                    new_params[n] = jax.lax.with_sharding_constraint(
                        new_params[n], self._param_sharding(n))
                    new_opt[n] = jax.tree_util.tree_map(
                        lambda x, _n=n: jax.lax.with_sharding_constraint(
                            x, self._update_spec(x, _n)), new_opt[n])
            for n, w in params.items():
                if n in sparse:
                    continue
                g = grads[n].astype(w.dtype) * rescale
                if clip is not None:
                    g = jnp.clip(g, -clip, clip)
                if constrained:
                    # grads arrive sharded (reduce-scatter over dp,
                    # tensor-parallel shards stay put), the update runs
                    # on the shard, params leave in their at-rest spec
                    # (all-gather over dp when replicated there) and
                    # optimizer state stays sharded
                    g = jax.lax.with_sharding_constraint(
                        g, self._update_spec(g, n))
                new_params[n], new_opt[n] = opt_update(
                    w, g, state["opt"][n], lr * lr_mult[n], wd[n], t)
                if constrained:
                    new_params[n] = jax.lax.with_sharding_constraint(
                        new_params[n], self._param_sharding(n))
                    new_opt[n] = jax.tree_util.tree_map(
                        lambda x, _n=n: jax.lax.with_sharding_constraint(
                            x, self._update_spec(x, _n)), new_opt[n])
            merged_aux = dict(aux)
            merged_aux.update(new_aux)
            return ({"params": new_params, "opt": new_opt,
                     "aux": merged_aux, "fixed": fixed, "t": t}, outs)

        return step

    def _program_desc(self, tag: str) -> str:
        """Trace-free fast-key description for this step's programs:
        the symbol graph plus every closed-over ingredient of the trace
        — optimizer class + baked hparams + per-name schedule factors,
        remat, compute dtype, sharded-update mode, mesh layout, and the
        train/fixed/label name split.  Op and optimizer IMPLEMENTATIONS
        are covered by the cache's code_fingerprint."""
        import hashlib
        from ..parallel.mesh import mesh_axes as _mesh_axes
        h = hashlib.sha256()
        h.update(self._prog.symbol.tojson().encode())
        for part in (tag, type(self.optimizer).__name__,
                     repr(self.hparam_signature()),
                     repr(sorted(self._lr_mult.items())),
                     repr(sorted(self._wd.items())),
                     str(self.compute_dtype), str(self._remat),
                     repr(self.device_augment.signature()
                          if self.device_augment is not None else None),
                     str(self.shard_update), str(self.global_dp),
                     # mesh AXES, not just devices: dp=8 and dp=4 x tp=2
                     # over the same chips partition differently but list
                     # identical device ids — without the axis shape the
                     # fast key would alias the two programs
                     repr(_mesh_axes(self.mesh)),
                     repr(sorted((n, tuple(s))
                                 for n, s in self.param_specs.items())),
                     # sparse-embed geometry: a cap change or a table
                     # entering/leaving the sparse path is a different
                     # program
                     repr(sorted((n, sp.describe())
                                 for n, sp in self.sparse_embeds.items())),
                     # MoE routing geometry: belt-and-braces with the
                     # symbol json, same as the embed specs
                     repr(sorted((n, sp.describe())
                                 for n, sp in self.moe_blocks.items())),
                     repr([int(d.id) for d in self.mesh.devices.ravel()]),
                     repr(self.train_names), repr(self.fixed_names),
                     repr(sorted(self.label_shapes.items()))):
            h.update(str(part).encode())
            h.update(b"\x00")
        return "fused|%s" % h.hexdigest()

    def _build_step(self):
        from ..compile_cache import cached_jit
        self._step = cached_jit(self._make_step_fn(), name="fused:step",
                                donate_argnums=(0,),
                                fast_key=self._program_desc("step"))
        return self._step

    def _build_fwd(self):
        # one cached program per mode (is_train closed over rather than
        # a static argnum: the compile cache keys concrete programs)
        from ..compile_cache import cached_jit
        prog = self._prog

        def make(is_train):
            def fwd(state, batch, rng):
                batch = self._maybe_augment(batch, rng, train=is_train)
                args = dict(state["params"])
                args.update(state["fixed"])
                args.update(batch)
                args = self._cast_compute(args)
                outs, _ = prog.eval(args, state["aux"], rng, is_train)
                return outs
            mode = "train" if is_train else "eval"
            return cached_jit(fwd, name="fused:fwd_%s" % mode,
                              fast_key=self._program_desc("fwd_%s" % mode))

        self._fwd = {True: make(True), False: make(False)}
        return self._fwd

    def build_superstep(self, k, metric_update=None, unroll=1):
        """ONE donated XLA program executing K fused steps: the step body
        from _make_step_fn traced under ``jax.lax.scan`` over the
        megabatch's leading K axis, with zero host involvement between
        steps.  ``metric_update(acc, labels, preds)`` (a traced reducer
        from EvalMetric.device_reducer) rides in the scan carry, so the
        caller drains one tiny scalar pytree every K steps instead of
        full output arrays every step.  Per-step learning rates arrive
        as a K-vector (the host resolves the scheduler at each step
        position, exactly as K sequential update() calls would).

        Returns ``superstep(state, megabatch, lrs, base_key, acc) ->
        (new_state, acc)``, jitted with the state donated.  Because the
        scan body IS the sequential step's trace (same in-program step
        counter, same per-step RNG fold), superstep K is bitwise-
        identical to K sequential fused steps — and ``unroll`` (the
        ``lax.scan`` unroll factor, an autotune="joint" knob) only
        restructures control flow, so it preserves that bit-identity."""
        step_fn = self._make_step_fn()
        label_names = self.label_names
        unroll = max(1, min(int(unroll), int(k)))

        def superstep(state, megabatch, lrs, base_key, acc):
            def body(carry, xs):
                st, a = carry
                batch, lr = xs
                st, outs = step_fn(st, batch, lr, base_key)
                if metric_update is not None:
                    labels = [batch[n] for n in label_names]
                    a = metric_update(a, labels, list(outs))
                return (st, a), None

            (state, acc), _ = jax.lax.scan(body, (state, acc),
                                           (megabatch, lrs), length=k,
                                           unroll=unroll)
            return state, acc

        from ..compile_cache import cached_jit
        # the traced metric reducer is part of the program; identify it
        # by owner class + qualname — process-stable, unlike a repr with
        # an object address (implementation changes ride code_fingerprint)
        if metric_update is None:
            mtag = "none"
        else:
            owner = getattr(metric_update, "__self__", None)
            mtag = "%s:%s" % (
                type(owner).__name__ if owner is not None else "",
                getattr(metric_update, "__qualname__",
                        type(metric_update).__name__))
        return cached_jit(superstep, name="fused:superstep:k%d" % k,
                          donate_argnums=(0,),
                          fast_key=self._program_desc(
                              "superstep:k%d:u%d:%s" % (k, unroll, mtag)))

    def step(self, state, batch, base_key):
        """Advance one batch; returns (new_state, outputs)."""
        if self._step is None:
            self._build_step()
        lr = self.optimizer.base_lr()
        if self._multiprocess():
            # a host scalar is replicated implicitly; an uncommitted
            # device scalar cannot join a multi-process computation
            return self._dispatch(state, batch, np.float32(lr), base_key)
        if self._lr_cache is None or self._lr_cache[0] != lr:
            # lr changes only when the scheduler fires; keep the device
            # scalar resident between changes
            self._lr_cache = (lr, jnp.asarray(lr, jnp.float32))
        return self._dispatch(state, batch, self._lr_cache[1], base_key)

    def _dispatch(self, state, batch, lr, base_key):
        """Run the step program, feeding the multichip counters and the
        span recorder: host dispatch time every step, full device step
        wall on a sampled subset (one sync every sample_every steps —
        the async pipeline stays intact between samples)."""
        stats = self.multichip_stats
        import time as _time
        if stats is None:
            if not _trace.enabled():
                return self._step(state, batch, lr, base_key)
            t0 = _time.perf_counter()
            out = self._step(state, batch, lr, base_key)
            _trace.complete("fused:dispatch", t0,
                            _time.perf_counter() - t0, cat="train")
            return out
        first = stats.steps == 0
        sample = not first and stats.should_sample()
        if sample:
            # drain the async backlog BEFORE timing, or the sampled
            # wait charges up to sample_every queued steps' device time
            # to this one step (the input state is the previous step's
            # output — ready means the queue is empty)
            jax.block_until_ready(
                next(iter(state["params"].values()), state["t"]))
        t0 = _time.perf_counter()
        out = self._step(state, batch, lr, base_key)
        dt = _time.perf_counter() - t0
        if first:
            # blocks through trace+compile on a cold cache: its own
            # counter, not the steady dispatch average
            stats.note_first(dt)
            _trace.complete("fused:first_step(compile)", t0, dt,
                            cat="train")
        else:
            stats.add_step(dt)
            _trace.complete("fused:dispatch", t0, dt, cat="train")
        if sample:
            t1 = _time.perf_counter()
            leaf = next(iter(out[0]["params"].values()), out[0]["t"])
            jax.block_until_ready(leaf)
            wait = _time.perf_counter() - t1
            stats.add_wait(wait)
            # the sampled device-wall: the one span that shows real
            # device compute in a timeline otherwise full of async
            # dispatches
            _trace.complete("fused:device_wait(sampled)", t1, wait,
                            cat="train")
        return out

    def gather_update_leaf(self, x):
        """One sharded-at-rest optimizer-state leaf -> replicated (and,
        multi-process, host-materializable).  The classic-updater
        fallback consumes replicated per-param state; handing it raw
        dp shards would crash (non-addressable) or silently feed it a
        layout it cannot use."""
        if x is None:
            return None
        # lint: allow(raw-jit) — trivial all-gather reshard with live
        # out_shardings, built on the rare classic-fallback path; never a
        # steady-state dispatch worth a disk entry
        gathered = jax.jit(lambda a: a,
                           out_shardings=self._replicated())(x)
        # materialize through host: the classic path mixes this with
        # per-device arrays, and a mesh-committed array would poison
        # every eager op it meets with a device mismatch
        return jnp.asarray(np.asarray(gathered.addressable_data(0)))

    def warm_step(self, state, batch, base_key) -> str:
        """Compile (or cache-load) the step program for these avals
        WITHOUT executing it: nothing is donated, no optimizer update
        runs, no state copy is needed.  The warmup entry point for
        Module.prepare / BucketingModule.precompile; safe from a warmup
        thread pool."""
        if self._step is None:
            self._build_step()
        # the lr operand must match step()'s form exactly: a host scalar
        # in multi-process mode (an uncommitted device scalar cannot
        # join a multi-process computation), a device scalar otherwise
        if self._multiprocess():
            lr = np.float32(self.optimizer.base_lr())
        else:
            lr = jnp.asarray(self.optimizer.base_lr(), jnp.float32)
        if hasattr(self._step, "warm"):
            return self._step.warm(state, batch, lr, base_key)
        return "present"     # already an installed AOT executable

    def aot_compile(self, state, batch, base_key):
        """Ahead-of-time compile the step for exactly these avals,
        install the executable as the step program, and return its
        executed-FLOP count from XLA cost analysis (0.0 when the backend
        cannot report one).  Keeps the (state, batch, lr, key) calling
        contract in one place; bench.py uses this so its utilization
        numerator is the very program its loop runs.  Routed through the
        compile cache: a warm process start installs the deserialized
        executable without compiling."""
        if self._step is None:
            self._build_step()
        lr = jnp.asarray(self.optimizer.base_lr(), jnp.float32)
        if hasattr(self._step, "compile_for"):
            compiled = self._step.compile_for(state, batch, lr, base_key)
        else:
            compiled = self._step.lower(state, batch, lr, base_key).compile()
        flops = 0.0
        bytes_accessed = 0.0
        try:
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, list) else ca
            if ca:
                flops = float(ca.get("flops", 0.0))
                bytes_accessed = float(ca.get("bytes accessed", 0.0))
        except Exception:
            pass
        census = None
        if self.multichip_stats is not None:
            # the optimized (post-SPMD-partitioner) HLO names the REAL
            # collectives; parse counts + payload bytes for the
            # collective-vs-compute split in multichip_report()
            txt = None
            try:
                if hasattr(compiled, "as_text"):
                    txt = compiled.as_text()
                elif hasattr(compiled, "_loaded"):
                    txt = compiled._loaded.hlo_modules()[0].to_string()
            except Exception:
                pass
            from .. import profiler as _prof
            census = _prof.parse_hlo_collectives(txt) if txt else None
            self.multichip_stats.set_cost(
                flops=flops, bytes_accessed=bytes_accessed,
                collectives=census)
        # the cost-model featurizer reads this regardless of topology
        # (multichip_stats only exists past one device)
        self.cost_summary = {"flops": flops,
                             "bytes_accessed": bytes_accessed,
                             "collectives": census}
        self._step = compiled
        self._lr_cache = None
        return flops

    def forward_only(self, state, batch, rng, is_train=False):
        if self._fwd is None:
            self._build_fwd()
        return self._fwd[bool(is_train)](state, batch, rng)

    # -- host sync -----------------------------------------------------------
    def read_params(self, state, arg_params: Dict[str, NDArray],
                    aux_params: Dict[str, NDArray]):
        """Pull the live state back into host-side NDArray dicts. Copies:
        the state buffers are donated to the next step, which would delete
        the arrays under any NDArray handed out here."""
        # Materialize through host in BOTH cases (the docstring's
        # contract): a jnp.copy would stay committed to the fused mesh,
        # and a mesh-committed weight leaking into the classic per-device
        # path (kvstore re-seed on fallback, exec-group updates) poisons
        # every eager op it meets with a device mismatch.  A tensor-
        # parallel (specced) param is SHARDED at rest — addressable_data(0)
        # would hand back one shard as if it were the whole weight, so
        # non-replicated leaves gather first.
        def host(x):
            sh = getattr(x, "sharding", None)
            if sh is not None and not x.is_fully_replicated:
                if x.is_fully_addressable:
                    return NDArray(jnp.asarray(np.asarray(x)))
                return NDArray(self.gather_update_leaf(x))
            return NDArray(jnp.asarray(np.asarray(x.addressable_data(0))))
        for n in self.train_names:
            arg_params[n] = host(state["params"][n])
        for n in self.fixed_names:
            arg_params[n] = host(state["fixed"][n])
        for n in self.aux_names:
            aux_params[n] = host(state["aux"][n])
