"""Input-pipeline benchmark leg: RecordIO -> native decode -> device.

Measures what bench.py's device-only number deliberately excludes: the
host-side cost of feeding the chip.  Two legs over synthetic .rec files
built at bench time (self-contained, no dataset on disk):

  jpeg: training-resolution JPEG records (what im2rec --resize 256
        produces for ImageNet) through the native loader's libjpeg worker
        threads + crop/mirror/normalize, ending in jax.device_put — the
        reference's ImageRecordIter+prefetcher path
        (src/io/iter_image_recordio.cc:139-291).
  raw:  raw-CHW-packed records (decode-free), isolating the framing +
        normalize + H2D cost.

Throughput scales with host cores (each worker owns a full decode chain);
`io_host_cores` is reported so a 1-core tunnel host reading 500 img/s and
a 32-core production host reading 12k img/s are both interpretable.
"""
import os
import tempfile
import time

import numpy as np


def _build_jpeg_rec(path, n=192, edge=256, quality=90, seed=0):
    """Pack n pseudo-photo JPEGs (shorter edge = `edge`) into a .rec."""
    import io as _io
    from PIL import Image
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        h, wd = edge, edge + int(rng.randint(0, 96))
        if rng.rand() < 0.5:
            h, wd = wd, h
        # low-frequency content compresses like a photo, unlike pure noise
        base = rng.randint(0, 255, (8, 8, 3)).astype(np.uint8)
        img = Image.fromarray(base).resize((wd, h), Image.BILINEAR)
        buf = _io.BytesIO()
        img.save(buf, format="JPEG", quality=quality)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 1000), i, 0),
                              buf.getvalue()))
    w.close()


def _build_raw_rec(path, n=192, shape=(3, 224, 224), seed=0):
    from mxnet_tpu import recordio
    rng = np.random.RandomState(seed)
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        arr = rng.randint(0, 255, shape).astype(np.uint8)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 1000), i, 0),
                              arr.tobytes()))
    w.close()


def _pump(loader, seconds=4.0):
    """Drain epochs for ~seconds; returns host-pipeline img/s (decoded
    float32 batches staged in host RAM, ready for H2D)."""
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        out = loader.next()
        if out is None:
            loader.reset()
            continue
        n += out[0].shape[0]
    return n / (time.perf_counter() - t0)


def _h2d_probe(batch=128, iters=8):
    """Host->device bandwidth for one training batch (MB/s).  Reported
    separately from the pipeline rate: on a production TPU host this is a
    local DMA that overlaps compute (PJRT async dispatch); through the
    bench tunnel it is a network hop and would dominate any combined
    number, which is why the device-side bench pre-stages batches."""
    import jax
    import jax.numpy as jnp
    x = np.random.rand(batch, 3, 224, 224).astype(np.float32)
    jax.block_until_ready(jax.device_put(x))  # warm path
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(jax.device_put(x))
    dt = time.perf_counter() - t0
    return x.nbytes * iters / dt / 1e6


def run(batch=128, threads=None, seconds=4.0, feed=lambda *_: None):
    """Returns dict of io_* metrics.  `feed` is the watchdog heartbeat."""
    from mxnet_tpu.native_io import NativeBatchLoader, lib_available
    if not lib_available():
        raise RuntimeError("libmxtpu.so not built")
    cores = os.cpu_count() or 1
    threads = threads or cores
    out = {"io_host_cores": cores, "io_threads": threads}
    with tempfile.TemporaryDirectory() as tmp:
        feed("io-build")
        jpeg_rec = os.path.join(tmp, "bench_jpeg.rec")
        raw_rec = os.path.join(tmp, "bench_raw.rec")
        _build_jpeg_rec(jpeg_rec)
        _build_raw_rec(raw_rec)
        feed("io-jpeg")
        ld = NativeBatchLoader(jpeg_rec, batch, (3, 224, 224),
                               threads=threads, shuffle=True, rand_crop=True,
                               rand_mirror=True, scale=1.0 / 255)
        out["io_jpeg_img_s"] = round(_pump(ld, seconds=seconds), 1)
        del ld
        feed("io-raw")
        ld = NativeBatchLoader(raw_rec, batch, (3, 224, 224),
                               threads=threads, shuffle=True)
        out["io_raw_img_s"] = round(_pump(ld, seconds=seconds), 1)
        del ld
    feed("io-h2d")
    try:
        out["io_h2d_mb_s"] = round(_h2d_probe(batch), 1)
    except Exception:
        pass
    return out


if __name__ == "__main__":
    import json
    print(json.dumps(run()))
